"""Benchmark driver contract: prints ONE JSON line.

Headline metric: the centralized assignment pipeline (align + cdist + LAP) —
the only hard number the reference publishes: "for n = 15, takes 5-10 ms"
on the base-station CPU (`aclswarm/nodes/operator.py:241`, BASELINE.md).
We time the identical pipeline (2D Umeyama alignment, pairwise distances,
exact LAP via the device auction kernel) fully jitted on one TPU chip and
report throughput in assignments/second; ``vs_baseline`` is the speedup over
the reference's midpoint (7.5 ms => 133.3 Hz).
"""
import json
import time

import numpy as np

BASELINE_HZ = 1000.0 / 7.5  # operator.py:241 midpoint


def main():
    import jax
    import jax.numpy as jnp

    from aclswarm_tpu.assignment import auction
    from aclswarm_tpu.core import geometry
    from aclswarm_tpu.core import perm as permutil

    n = 15
    rng = np.random.default_rng(0)
    points = rng.normal(size=(n, 3)) * 3.0
    q = rng.normal(size=(n, 3)) * 3.0
    v2f = jnp.asarray(rng.permutation(n).astype(np.int32))

    @jax.jit
    def assign(q, points, v2f):
        q_form = permutil.veh_to_formation_order(q, v2f)
        paligned = geometry.align(points, q_form, d=2)
        res = auction.auction_lap(-geometry.cdist(q, paligned))
        return res.row_to_col

    qd = jnp.asarray(q, jnp.float32)
    pd = jnp.asarray(points, jnp.float32)
    out = assign(qd, pd, v2f)
    jax.block_until_ready(out)  # compile + warm

    # block every call: the baseline is a *latency* figure, so measure
    # latency, not pipelined dispatch throughput
    iters = 200
    t0 = time.perf_counter()
    for _ in range(iters):
        jax.block_until_ready(assign(qd, pd, v2f))
    dt = (time.perf_counter() - t0) / iters
    hz = 1.0 / dt

    print(json.dumps({
        "metric": "central_assignment_n15_hz",
        "value": round(hz, 1),
        "unit": "Hz",
        "vs_baseline": round(hz / BASELINE_HZ, 2),
    }))


if __name__ == "__main__":
    main()
