"""Benchmark driver contract: prints ONE JSON line.

Headline metric (the north star, BASELINE.md): n=1000 swarm assignment on
one TPU chip, reported as sustained assignment throughput. The reference's
centralized path does align + cdist + Hungarian for n=15 in 5-10 ms on a
base-station CPU (`aclswarm/nodes/operator.py:241`); its decentralized path
needs 2n sequential bid rounds. The target here is >= 100 Hz at n=1000
(`vs_baseline` = value / 100 Hz).

Methodology (pinned after round-1 variance, see VERDICT r1 weak #9):
- Work is chained inside a single jit: `lax.scan` over K=400 *distinct*
  problem instances, so the device cannot dedupe repeated dispatches and
  each scan step is a true dependent computation. Reported value =
  wall-clock / K, median of 5 repeats (median kills one-off host jitter).
- This is sustained throughput, not single-shot dispatch latency: this
  environment adds a fixed ~108 ms per-executable-launch overhead through
  the remote-TPU tunnel (measured: a K=400 scan of trivial bodies costs
  the same ~108 ms as one launch), which would swamp a single ~1.5 ms
  assignment. K=400 bounds the floor's contribution to ~0.27 ms per
  instance; the steady-state device time is what a pipelined consumer
  would see.
- Completion is detected by a host readback of a scalar digest, NOT
  `block_until_ready` (unreliable through the tunnel — see
  benchmarks/scale.py `_sync`).
- Quality is guarded, not assumed: the same kernel config is checked
  against the exact host LAP (`assignment.lapjv`) and the line includes the
  measured suboptimality ratio (target <= 2%).
"""
import json
import os
import sys
from pathlib import Path

from aclswarm_tpu.utils.retry import Watchdog, subprocess_probe

BASELINE_HZ = 100.0  # north-star target at n=1000 (BASELINE.md)
N = 1000
K = 400

# hard ceiling on the whole run: the remote-TPU tunnel can wedge in a
# way where even jax.devices() blocks forever (observed once this
# round); a hung bench burns the driver's whole budget, so a watchdog
# emits a diagnostic line — keeping the one-JSON-line contract — and
# hard-exits. Normal runs finish in ~3-4 min incl. first compile.
WATCHDOG_S = 900.0
# a wedged tunnel blocks jax.devices() itself, so before arming the main
# measurement the backend is probed in a THROWAWAY subprocess with a
# short budget: a wedge costs PROBE_TIMEOUT_S, not the full 900 s
PROBE_TIMEOUT_S = 120.0
_PROBE_CODE = "import jax; jax.devices(); print('ok')"


def _error_line(msg: str) -> None:
    print(json.dumps({
        "metric": f"sinkhorn_assign_n{N}_hz",
        "value": 0.0,
        "unit": "Hz",
        "vs_baseline": 0.0,
        "error": msg,
    }), flush=True)


def _on_watchdog_fire() -> None:
    _error_line(f"bench did not complete within {WATCHDOG_S:.0f} s — "
                "device backend unreachable (tunnel wedge?); see "
                "benchmarks/results/scale_tpu.json for the committed "
                "measurement")
    os._exit(2)


# the finish-vs-fire boundary race (a measurement completing exactly at
# the timeout must never allow a second output line) lives in the
# unified retry layer now: `utils.retry.Watchdog` makes the claim atomic
_wd = Watchdog(on_fire=_on_watchdog_fire)
_done = _wd.done          # tests poke these exact names
_watchdog = _wd.fire


def _probe_device(timeout_s: float | None = None) -> bool:
    """True iff a subprocess can enumerate jax devices within the budget.
    Run as a separate process because a wedged device tunnel hangs the
    *calling* process inside jax.devices() uncancellably
    (`utils.retry.subprocess_probe` — the probe is sacrificial)."""
    return subprocess_probe(
        _PROBE_CODE,
        PROBE_TIMEOUT_S if timeout_s is None else timeout_s,
        cwd=str(Path(__file__).resolve().parent))


def main():
    if not _probe_device():
        _error_line(f"device backend probe did not answer within "
                    f"{PROBE_TIMEOUT_S:.0f} s (tunnel wedge?) — skipping "
                    "the measurement instead of burning the "
                    f"{WATCHDOG_S:.0f} s budget; see "
                    "benchmarks/results/scale_tpu.json for the committed "
                    "measurement")
        return 2
    _wd.arm(WATCHDOG_S)
    # single source of truth for the measurement lives in benchmarks/scale.py
    sys.path.insert(0, str(Path(__file__).resolve().parent / "benchmarks"))
    from scale import sinkhorn_throughput

    sk = sinkhorn_throughput(N, K, reps=5)
    _wd.finish()            # measurement done: from here the watchdog
    #                         can no longer claim the output line
    print(json.dumps({
        "metric": f"sinkhorn_assign_n{N}_hz",
        "value": round(sk["hz"], 1),
        "unit": "Hz",
        "vs_baseline": round(sk["hz"] / BASELINE_HZ, 2),
        "subopt_vs_lap": round(sk["subopt"], 4),
        # min/max Hz over the 5 timing reps (round-2 next-step #9: spread
        # makes regressions visible beyond the single median)
        "hz_spread": sk["hz_spread"],
        # roofline position (round-3 weak #6): achieved FLOP/s + HBM GB/s
        # vs v5e peaks (197 TF bf16 / 819 GB/s). Pallas bodies are opaque
        # to XLA's flops estimate, so Pallas-routed rows merge the
        # kernels' analytic counts and carry
        # flops_model="xla+analytic" (benchmarks/scale.py
        # _roofline; round-4 review Weak #1)
        "roofline": sk["roofline"],
        # single-shot latency split into the environment's fixed
        # per-dispatch floor vs on-device time (round-4 review Weak #4)
        "latency_ms": round(sk["latency_ms"], 2),
        "latency_decomposition": sk["latency_decomposition"],
    }))
    return 0


if __name__ == "__main__":
    sys.exit(main())
