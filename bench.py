"""Benchmark driver contract: prints ONE JSON line, exits 0.

Headline metric (the north star, BASELINE.md): n=1000 swarm assignment on
one TPU chip, reported as sustained assignment throughput. The reference's
centralized path does align + cdist + Hungarian for n=15 in 5-10 ms on a
base-station CPU (`aclswarm/nodes/operator.py:241`); its decentralized path
needs 2n sequential bid rounds. The target here is >= 100 Hz at n=1000
(`vs_baseline` = value / 100 Hz).

Methodology (pinned after round-1 variance, see VERDICT r1 weak #9):
- Work is chained inside a single jit: `lax.scan` over K=400 *distinct*
  problem instances, so the device cannot dedupe repeated dispatches and
  each scan step is a true dependent computation. Reported value =
  wall-clock / K, median of 5 repeats (median kills one-off host jitter).
- This is sustained throughput, not single-shot dispatch latency: this
  environment adds a fixed ~108 ms per-executable-launch overhead through
  the remote-TPU tunnel (measured: a K=400 scan of trivial bodies costs
  the same ~108 ms as one launch), which would swamp a single ~1.5 ms
  assignment. K=400 bounds the floor's contribution to ~0.27 ms per
  instance; the steady-state device time is what a pipelined consumer
  would see.
- Completion is detected by a host readback of a scalar digest, NOT
  `block_until_ready` (unreliable through the tunnel — see
  benchmarks/scale.py `_sync`).
- Quality is guarded, not assumed: the same kernel config is checked
  against the exact host LAP (`assignment.lapjv`) and the line includes the
  measured suboptimality ratio (target <= 2%).

Execution path (round-6, docs/SERVICE.md): the measurement runs as a
swarmserve CLIENT — subprocess device probe under the unified
RetryPolicy, then one deadline-bounded request through `SwarmService`
with the retry/degrade executor underneath. EVERY outcome is a
structured row with rc=0: a wedged tunnel (the BENCH_r05 failure mode),
a non-TPU fallback backend, and a deadline miss all produce a
``degraded: true`` row carrying the structured reason — the committed
device measurement in benchmarks/results/scale_tpu.json remains the
reference. rc != 0 now means the DRIVER is broken, never the device.
"""
import json
import os
import sys
from pathlib import Path

from aclswarm_tpu.utils.retry import Watchdog

BASELINE_HZ = 100.0  # north-star target at n=1000 (BASELINE.md)
N = 1000
K = 400
REPS = 5
# non-TPU fallback sizing: the full K=400 x 5-rep chain is a multi-
# minute CPU burn that measures nothing the committed artifact doesn't;
# the degraded row keeps the same methodology at evidence-smoke scale
K_DEGRADED = 24
REPS_DEGRADED = 3

# hard ceiling on the whole run: the remote-TPU tunnel can wedge in a
# way where even jax.devices() blocks forever (observed round 5); a hung
# bench burns the driver's whole budget, so a watchdog emits a
# structured DEGRADED row — keeping the one-JSON-line, rc=0 contract —
# and hard-exits. Normal runs finish in ~3-4 min incl. first compile.
WATCHDOG_S = 900.0
# a wedged tunnel blocks jax.devices() itself, so before arming the main
# measurement the backend is probed in a THROWAWAY subprocess with a
# short budget: a wedge costs ~2 probe attempts, not the full 900 s.
# (_PROBE_CODE stays a module attribute — tests monkeypatch it.)
PROBE_TIMEOUT_S = 120.0
from aclswarm_tpu.serve.client import PROBE_CODE as _PROBE_CODE  # noqa: E402


def _degraded_line(msg: str, serve_fields: dict | None = None,
                   telemetry: dict | None = None) -> None:
    from aclswarm_tpu.serve.stats import ServeStats
    row = {
        "metric": f"sinkhorn_assign_n{N}_hz",
        "value": 0.0,
        "unit": "Hz",
        "vs_baseline": 0.0,
        "degraded": True,
        "error": msg,
        # compact swarmscope snapshot (docs/OBSERVABILITY.md): EVERY
        # outcome carries the same telemetry block — a zeroed one when
        # no service ever started (probe failure, watchdog fire)
        "telemetry": telemetry or ServeStats.empty_compact(),
    }
    if serve_fields:
        row.update(serve_fields)
    print(json.dumps(row), flush=True)


def _on_watchdog_fire() -> None:
    _degraded_line(
        f"bench did not complete within {WATCHDOG_S:.0f} s — device "
        "backend unreachable or wedged mid-measurement; see "
        "benchmarks/results/scale_tpu.json for the committed "
        "measurement")
    os._exit(0)          # structured degraded row delivered: rc=0


# the finish-vs-fire boundary race (a measurement completing exactly at
# the timeout must never allow a second output line) lives in the
# unified retry layer: `utils.retry.Watchdog` makes the claim atomic
_wd = Watchdog(on_fire=_on_watchdog_fire)
_done = _wd.done          # tests poke these exact names
_watchdog = _wd.fire


def _probe_device(timeout_s: float | None = None) -> str | None:
    """Backend name iff a subprocess can initialize jax within the
    budget (2 attempts under the unified RetryPolicy), else None. Run
    as a separate process because a wedged device tunnel hangs the
    *calling* process inside jax.devices() uncancellably
    (`serve.client.probe_backend` — the probe is sacrificial)."""
    from aclswarm_tpu.serve.client import probe_backend
    return probe_backend(
        PROBE_TIMEOUT_S if timeout_s is None else timeout_s,
        code=_PROBE_CODE, cwd=str(Path(__file__).resolve().parent))


def main(argv=None):
    import argparse
    ap = argparse.ArgumentParser(
        description="n=1000 assignment throughput bench (one JSON row, "
                    "rc=0)")
    ap.add_argument("--profile-dir", default=None,
                    help="opt-in swarmscope jax.profiler capture: write "
                    "one trace of the measurement into this directory "
                    "(TensorBoard/Perfetto; docs/OBSERVABILITY.md)")
    args = ap.parse_args(argv)
    backend = _probe_device()
    if backend is None:
        _degraded_line(
            f"device backend probe did not answer within 2 x "
            f"{PROBE_TIMEOUT_S:.0f} s (tunnel wedge?) — skipping the "
            f"measurement instead of burning the {WATCHDOG_S:.0f} s "
            "budget; see benchmarks/results/scale_tpu.json for the "
            "committed measurement")
        return 0
    _wd.arm(WATCHDOG_S)
    # single source of truth for the measurement lives in
    # benchmarks/scale.py; the serving layer owns retry/degrade/deadline
    sys.path.insert(0, str(Path(__file__).resolve().parent / "benchmarks"))
    from scale import sinkhorn_throughput

    from aclswarm_tpu.serve import ServiceConfig, SwarmService

    on_device = backend == "tpu"
    k, reps = (K, REPS) if on_device else (K_DEGRADED, REPS_DEGRADED)

    svc = SwarmService(ServiceConfig())
    svc.register(
        "bench_sinkhorn",
        lambda p: sinkhorn_throughput(p["n"], p["K"], reps=p["reps"]))
    import contextlib
    if args.profile_dir:
        # jax.profiler is process-global: a trace opened here captures
        # the device work the service worker thread dispatches
        from aclswarm_tpu.utils import timing as timinglib
        prof = timinglib.trace(args.profile_dir)
    else:
        prof = contextlib.nullcontext()
    with prof:
        ticket = svc.submit("bench_sinkhorn",
                            {"n": N, "K": k, "reps": reps},
                            tenant="bench",
                            deadline_s=WATCHDOG_S - 120.0)
        res = ticket.result(timeout=WATCHDOG_S)
    # claim the output line the instant the measurement lands (ADVICE
    # r5: a timer firing between completion and post-processing must
    # not discard a finished measurement) — post-processing follows
    if not _wd.finish():     # watchdog already claimed the output line
        return 0             # pragma: no cover — fire() hard-exits
    svc.close()
    serve_fields = svc.row_fields()
    telemetry = svc.serve_stats().compact()
    if not res.ok:
        _degraded_line(
            f"measurement request terminated {res.status}: "
            f"{res.error.code}: {res.error.message}",
            serve_fields, telemetry=telemetry)
        return 0
    sk = res.value
    row = {
        "metric": f"sinkhorn_assign_n{N}_hz",
        "value": round(sk["hz"], 1),
        "unit": "Hz",
        "vs_baseline": round(sk["hz"] / BASELINE_HZ, 2),
        "subopt_vs_lap": round(sk["subopt"], 4),
        # min/max Hz over the timing reps (round-2 next-step #9: spread
        # makes regressions visible beyond the single median)
        "hz_spread": sk["hz_spread"],
        # roofline position (round-3 weak #6): achieved FLOP/s + HBM GB/s
        # vs v5e peaks (197 TF bf16 / 819 GB/s). Pallas bodies are opaque
        # to XLA's flops estimate, so Pallas-routed rows merge the
        # kernels' analytic counts and carry
        # flops_model="xla+analytic" (benchmarks/scale.py
        # _roofline; round-4 review Weak #1)
        "roofline": sk["roofline"],
        # single-shot latency split into the environment's fixed
        # per-dispatch floor vs on-device time (round-4 review Weak #4)
        "latency_ms": round(sk["latency_ms"], 2),
        "latency_decomposition": sk["latency_decomposition"],
        # serving-layer provenance: the request's measured latency plus
        # any retry/degrade markers the executor recorded
        "serve": dict(serve_fields.get("serve", {}),
                      request_latency_s=round(res.latency_s, 2)),
        # compact swarmscope snapshot (occupancy, queue depth,
        # preemptions, and the fleet provenance: worker count +
        # failover events — docs/OBSERVABILITY.md); present on degraded
        # rows too, so row consumers never branch on key presence
        "telemetry": telemetry,
    }
    if not on_device:
        # a fallback backend is a DEGRADED capture by definition: same
        # methodology, wrong silicon — never comparable to the baseline
        row["degraded"] = True
        row["degraded_reason"] = (
            f"backend={backend!r} (not the bench TPU); K={k}, "
            f"reps={reps} evidence-smoke sizing — the committed device "
            "measurement is benchmarks/results/scale_tpu.json")
    for key in ("retries", "degraded", "execution_failures"):
        if key in serve_fields:
            row.setdefault(key, serve_fields[key])
    print(json.dumps(row), flush=True)
    return 0


if __name__ == "__main__":
    sys.exit(main())
