"""Vehicle registry tests (O4: named vehicles <-> batch indices,
`utils.h:43-72` loadVehicleInfo semantics + `param/vehicles.yaml`)."""
import pytest

from aclswarm_tpu.core.registry import (DEFAULT_REGISTRY, VehicleRegistry,
                                        load_registry, make_registry)


class TestRegistry:
    def test_mixed_fleet_names(self):
        r = make_registry(["SQ01s", "HX04", "SQ03s"])
        assert r.n == 3
        assert r.index("HX04") == 1          # index = list position
        assert r.name(2) == "SQ03s"
        assert list(r) == ["SQ01s", "HX04", "SQ03s"]

    def test_unknown_name_is_error(self):
        # the reference errors out, never defaults (`utils.h:60-64`)
        r = make_registry(["SQ01s"])
        with pytest.raises(KeyError):
            r.index("HX99")

    def test_duplicate_names_rejected(self):
        with pytest.raises(ValueError):
            make_registry(["SQ01s", "SQ01s"])

    def test_int_builds_sil_convention(self):
        # trial.sh:64-78 builds /vehs as SQ01s..SQnns
        r = make_registry(3)
        assert list(r) == ["SQ01s", "SQ02s", "SQ03s"]

    def test_shipped_registry_loads(self):
        r = load_registry()
        assert DEFAULT_REGISTRY.exists()
        assert r.n >= 1 and r.index(r.name(0)) == 0

    def test_ros_adapter_uses_registry(self):
        from aclswarm_tpu.interop import ros_bridge as rb
        from aclswarm_tpu.interop.ros_fakes import FakeMsgs, FakeRospy
        ros = FakeRospy(params={"/vehs": ["SQ01s", "HX04"]})
        node = rb.run(ros, FakeMsgs)
        assert isinstance(node.registry, VehicleRegistry)
        assert node.registry.index("HX04") == 1
        # per-vehicle topics follow the registered names
        assert "/HX04/distcmd" in ros.pubs
