"""Control-layer tests.

Per SURVEY.md §4's implications, every batched kernel is validated against an
independent *sequential* reference implementation written the way the C++
does it (per-vehicle loops, linearized-angle sector union), on random inputs:

- formation control law vs a literal per-vehicle translation of
  `DistCntrl::compute` (`aclswarm/src/distcntrl.cpp:46-102`);
- collision avoidance vs an edge-sort/parenthesis-count implementation of
  `Safety::collisionAvoidance` (`aclswarm/src/safety.cpp:412-541`);
- safety shaping invariants (`safety.cpp:172-197,330-408`).
"""
import math

import pytest

import jax
import jax.numpy as jnp
import numpy as np

from aclswarm_tpu import control
from aclswarm_tpu.core import perm
from aclswarm_tpu.core.types import (ControlGains, SafetyParams, SwarmState,
                                     make_formation)


def wrap(a):
    while a > math.pi:
        a -= 2 * math.pi
    while a < -math.pi:
        a += 2 * math.pi
    return a


def distcntrl_sequential(q_veh, vel, qdes, adj, gains_flat, v2f, g):
    """Per-vehicle loop mirror of `DistCntrl::compute` (distcntrl.cpp:46-102)."""
    n = q_veh.shape[0]
    dstar_xy = np.linalg.norm(qdes[:, None, :2] - qdes[None, :, :2], axis=-1)
    dstar_z = np.abs(qdes[:, None, 2] - qdes[None, :, 2])
    # P_ * q_veh: row v lands at row v2f[v]
    q = np.zeros_like(q_veh)
    for v in range(n):
        q[v2f[v]] = q_veh[v]
    u_all = np.zeros((n, 3))
    for v in range(n):
        i = v2f[v]
        u = np.zeros(3)
        for j in range(n):
            if adj[i, j]:
                Aij = gains_flat[3 * i:3 * i + 3, 3 * j:3 * j + 3]
                qij = q[j] - q[i]
                e_xy = np.linalg.norm(qij[:2]) - dstar_xy[i, j]
                F_xy = g.K1_xy * math.atan(g.K2_xy * e_xy)
                e_z = abs(qij[2]) - dstar_z[i, j]
                F_z = g.K1_z * math.atan(g.K2_z * e_z)
                F = np.zeros(3)
                if abs(e_xy) > g.e_xy_thr:
                    F[0] = F[1] = F_xy
                if abs(e_z) > g.e_z_thr:
                    F[2] = F_z
                up = Aij @ qij + F * qij
                u += g.kp * up + g.kd * (-vel[v])
        u_all[v] = u
    return u_all


def colavoid_sequential(q, vel, vehid, d_thresh, r_keep):
    """Linearized-angle mirror of `Safety::collisionAvoidance`
    (safety.cpp:412-541): sector edges, sort, parenthesis-count union."""
    did_wrap = False
    edges = []
    for j in range(q.shape[0]):
        if j == vehid:
            continue
        qij = q[j] - q[vehid]
        d = np.linalg.norm(qij[:2])
        if d > d_thresh:
            continue
        theta = math.atan2(qij[1], qij[0])
        alpha = abs(math.asin(min(1.0, r_keep / d))) if d > 0 else math.pi / 2
        beg, end = wrap(theta - alpha), wrap(theta + alpha)
        edges.append((beg, +1))
        edges.append((end, -1))
        if beg > end:
            did_wrap = True
            edges.append((-math.pi, +1))
            edges.append((math.pi, -1))
    v = vel.copy()
    if not edges:
        return v, False
    edges.sort()
    count, start, zones = 0, 0.0, []
    for a, s in edges:
        if count == 0:
            start = a
        count += s
        if count == 0:
            zones.append((start, a))
    psi = math.atan2(v[1], v[0])
    if not any(z[0] < psi < z[1] for z in zones):
        return v, False
    zedges = []
    for z in zones:
        if not did_wrap or abs(z[0]) != math.pi:
            zedges.append(z[0])
        if not did_wrap or abs(z[1]) != math.pi:
            zedges.append(z[1])
    if not zedges:
        return np.zeros(3), True
    zedges.sort()
    # utils::closest tie rule: strict `<` on the prev comparison means exact
    # ties resolve to the larger edge (utils.h:309-325)
    best = min(zedges, key=lambda e: (abs(e - psi), -e))
    if abs(wrap(best - psi)) <= math.pi / 2:
        umag = np.linalg.norm(v[:2])
        return np.array([umag * math.cos(best), umag * math.sin(best),
                         v[2]]), True
    return np.zeros(3), True


class TestDistCntrl:
    def _random_problem(self, seed, n=6, permute=True):
        rng = np.random.default_rng(seed)
        qdes = rng.normal(size=(n, 3)) * 2.0
        q = rng.normal(size=(n, 3)) * 2.0
        vel = rng.normal(size=(n, 3)) * 0.3
        adj = (rng.random((n, n)) < 0.6).astype(float)
        adj = np.triu(adj, 1)
        adj = adj + adj.T
        gains_flat = rng.normal(size=(3 * n, 3 * n)) * 0.2
        if permute:
            v2f = rng.permutation(n).astype(np.int32)
        else:
            v2f = np.arange(n, dtype=np.int32)
        return q, vel, qdes, adj, gains_flat, v2f

    def test_matches_sequential_reference(self):
        g = ControlGains()
        for seed in range(5):
            q, vel, qdes, adj, gains_flat, v2f = self._random_problem(seed)
            ref = distcntrl_sequential(q, vel, qdes, adj, gains_flat, v2f, g)
            f = make_formation(qdes, adj, gains_flat)
            out = control.compute(
                SwarmState(q=jnp.asarray(q), vel=jnp.asarray(vel)), f,
                jnp.asarray(v2f), g)
            np.testing.assert_allclose(np.asarray(out), ref, atol=1e-9)

    def test_converged_swarm_zero_command(self):
        # at the exact formation with zero velocity, u must vanish when gains
        # have the kernel property A @ (formation offsets) = 0; use a simple
        # consensus-style gain A_ij = I to check only relative-error terms
        n = 5
        rng = np.random.default_rng(11)
        qdes = rng.normal(size=(n, 3))
        adj = np.ones((n, n)) - np.eye(n)
        # zero gains: linear term off; swarm exactly at formation => scale
        # errors are zero => u = 0
        f = make_formation(qdes, adj)
        out = control.compute(
            SwarmState(q=jnp.asarray(qdes), vel=jnp.zeros((n, 3))), f,
            perm.identity(n), ControlGains())
        np.testing.assert_allclose(np.asarray(out), 0.0, atol=1e-12)

    def test_jit(self):
        q, vel, qdes, adj, gains_flat, v2f = self._random_problem(42)
        f = make_formation(qdes, adj, gains_flat)
        fn = jax.jit(control.compute)
        out = fn(SwarmState(q=jnp.asarray(q), vel=jnp.asarray(vel)), f,
                 jnp.asarray(v2f), ControlGains())
        assert out.shape == q.shape


class TestColAvoid:
    def _params(self):
        return SafetyParams(d_avoid_thresh=1.5, r_keep_out=0.6)

    def test_matches_sequential_reference(self):
        p = self._params()
        matched_modified = 0
        for seed in range(30):
            rng = np.random.default_rng(100 + seed)
            n = 6
            q = rng.normal(size=(n, 3)) * 1.2
            vel = rng.normal(size=(n, 3)) * 0.5
            out, mod = control.collision_avoidance(
                jnp.asarray(q), jnp.asarray(vel), p)
            for i in range(n):
                vref, mref = colavoid_sequential(
                    q, vel[i], i, p.d_avoid_thresh, p.r_keep_out)
                assert bool(mod[i]) == mref, (seed, i)
                np.testing.assert_allclose(np.asarray(out[i]), vref,
                                           atol=1e-7, err_msg=f"{seed},{i}")
                matched_modified += int(mref)
        # make sure the sweep actually exercised avoidance
        assert matched_modified > 10

    def test_far_apart_untouched(self):
        p = self._params()
        q = np.array([[0.0, 0, 1], [10.0, 0, 1], [0, 10.0, 1]])
        vel = np.array([[0.3, 0, 0], [0, 0.3, 0], [0.1, 0.1, 0]])
        out, mod = control.collision_avoidance(jnp.asarray(q),
                                               jnp.asarray(vel), p)
        np.testing.assert_allclose(np.asarray(out), vel)
        assert not np.any(np.asarray(mod))

    def test_head_on_deflects(self):
        # two vehicles approaching head-on: both goals must be modified and
        # rotated away from the collision bearing
        p = self._params()
        q = np.array([[0.0, 0, 1], [1.0, 0, 1]])
        vel = np.array([[0.5, 0, 0], [-0.5, 0, 0]])
        out, mod = control.collision_avoidance(jnp.asarray(q),
                                               jnp.asarray(vel), p)
        assert np.all(np.asarray(mod))
        # speed preserved (rotated, not scaled) since an escape edge exists
        np.testing.assert_allclose(
            np.linalg.norm(np.asarray(out)[:, :2], axis=1), 0.5, atol=1e-9)
        # heading moved off the direct bearing
        assert abs(math.atan2(float(out[0, 1]), float(out[0, 0]))) > 0.1

    @pytest.mark.slow
    def test_keepout_repulse_escapes_pair_trap(self):
        """Two vehicles locked INSIDE each other's keep-out cylinders:
        with the reference semantics (repulse off) the degenerate
        half-plane sectors hold them; the opt-in escape pushes them
        radially apart until the keep-out clears (SCALE_TUNING par.6's
        failure mode)."""
        p = self._params()            # r_keep_out = 0.6
        q = np.array([[0.0, 0, 1], [0.4, 0, 1]])   # 0.4 m < r_keep_out
        vel = np.array([[0.5, 0, 0], [-0.5, 0, 0]])  # pushing together
        # reference semantics: both flagged, neither commanded apart
        out, mod = control.collision_avoidance(jnp.asarray(q),
                                               jnp.asarray(vel), p)
        assert np.all(np.asarray(mod))
        assert float(out[1, 0] - out[0, 0]) <= 1e-9   # no separation cmd
        # opt-in repulse: radial separation at the configured speed
        pr = p.replace(keepout_repulse_vel=0.4)
        out, mod = control.collision_avoidance(jnp.asarray(q),
                                               jnp.asarray(vel), pr)
        assert np.all(np.asarray(mod))
        np.testing.assert_allclose(np.asarray(out)[0, :2], [-0.4, 0.0],
                                   atol=1e-7)
        np.testing.assert_allclose(np.asarray(out)[1, :2], [0.4, 0.0],
                                   atol=1e-7)
        # closed loop: the pair separates past the keep-out and repulse
        # disengages (normal VO resumes outside r_keep_out)
        qq = q.copy()
        for _ in range(400):
            out, mod = control.collision_avoidance(jnp.asarray(qq),
                                                   jnp.asarray(vel), pr)
            qq = qq + np.asarray(out) * 0.01
        assert np.linalg.norm(qq[0, :2] - qq[1, :2]) > 0.6
        # and far-apart pairs are untouched by the knob
        qfar = np.array([[0.0, 0, 1], [10.0, 0, 1]])
        out, mod = control.collision_avoidance(jnp.asarray(qfar),
                                               jnp.asarray(vel), pr)
        np.testing.assert_allclose(np.asarray(out), vel)
        assert not np.any(np.asarray(mod))

    def test_dz_ignore_unblocks_vertically_clear_neighbors(self):
        """Opt-in z-aware avoidance (`SafetyParams.colavoid_dz_ignore`):
        the reference's planar VO blocks regardless of vertical
        separation (the non-degenerate half of the SCALE_TUNING §6/§7
        traps); the knob turns the infinite keep-out column into a
        cylinder — vertically clear neighbors cast no sector, near-level
        ones keep full reference semantics."""
        p = self._params()
        # neighbor dead ahead but 2 m below the commanded vehicle
        q = np.array([[0.0, 0, 3.0], [0.8, 0, 1.0]])
        vel = np.array([[0.5, 0, 0], [0.0, 0, 0]])
        # reference semantics: planar distance 0.8 < threshold => blocked
        out, mod = control.collision_avoidance(jnp.asarray(q),
                                               jnp.asarray(vel), p)
        assert bool(mod[0])
        # knob on, |dz|=2 > 1.5: no sector, command passes through
        pz = p.replace(colavoid_dz_ignore=1.5)
        out, mod = control.collision_avoidance(jnp.asarray(q),
                                               jnp.asarray(vel), pz)
        np.testing.assert_allclose(np.asarray(out), vel)
        assert not np.any(np.asarray(mod))
        # knob on but |dz|=1.0 <= 1.5: still reference-blocked
        qnear = np.array([[0.0, 0, 2.0], [0.8, 0, 1.0]])
        out, mod = control.collision_avoidance(jnp.asarray(qnear),
                                               jnp.asarray(vel), pz)
        assert bool(mod[0])
        # the keep-out repulse honors the same cylinder: a z-separated
        # planar "violation" no longer triggers radial separation
        pzr = pz.replace(keepout_repulse_vel=0.4)
        qviol = np.array([[0.0, 0, 3.0], [0.4, 0, 1.0]])
        out, mod = control.collision_avoidance(jnp.asarray(qviol),
                                               jnp.asarray(vel), pzr)
        np.testing.assert_allclose(np.asarray(out), vel)
        assert not np.any(np.asarray(mod))

    def test_dz_ignore_pruned_path_keeps_level_obstacles(self):
        """Top-k pruning must rank only ACTIVE neighbors: with the dz
        knob on, a crowd of vertically-clear (inactive) vehicles that
        are planar-closer than a level obstacle must not consume the
        top-k slots and drop its sector (review r5: selection keyed on
        raw planar distance was only sound while activation was a
        monotone function of it)."""
        p = self._params().replace(colavoid_dz_ignore=1.0)
        # agent 0 at origin commanding +x; agents 1-4 vertically clear
        # (|dz|=2) and planar-close (0.3 m); agent 5 LEVEL, dead ahead
        # inside the threshold
        q = np.array([[0.0, 0.0, 3.0],
                      [0.3, 0.0, 1.0], [-0.3, 0.0, 1.0],
                      [0.0, 0.3, 1.0], [0.0, -0.3, 1.0],
                      [0.8, 0.0, 3.0]])
        vel = np.zeros((6, 3)); vel[0, 0] = 0.5
        out, mod = control.collision_avoidance(
            jnp.asarray(q), jnp.asarray(vel), p, max_neighbors=4)
        assert bool(mod[0]), "level obstacle dropped by dz-excluded crowd"
        # and identical to the dense (exact) result
        out_d, mod_d = control.collision_avoidance(
            jnp.asarray(q), jnp.asarray(vel), p)
        np.testing.assert_allclose(np.asarray(out), np.asarray(out_d))
        np.testing.assert_array_equal(np.asarray(mod), np.asarray(mod_d))

    def test_heading_exactly_pi_still_avoided(self):
        # INTENTIONAL divergence from the reference: its linearized strict
        # zone test can never flag psi == ±pi (safety.cpp:487-493), letting a
        # vehicle commanded exactly along -x fly unmodified at an obstacle
        # dead ahead. The circular formulation must flag and deflect it.
        p = self._params()
        q = np.array([[0.0, 0, 1], [-1.0, 0, 1]])   # obstacle at bearing pi
        vel = np.array([[-0.5, 0.0, 0.0], [0.0, 0.0, 0.0]])  # psi == pi
        out, mod = control.collision_avoidance(jnp.asarray(q),
                                               jnp.asarray(vel), p)
        assert bool(mod[0])
        # deflected but speed-preserving (an escape edge exists within 90°)
        np.testing.assert_allclose(
            np.linalg.norm(np.asarray(out)[0, :2]), 0.5, atol=1e-9)
        assert abs(float(out[0, 1])) > 0.1  # rotated off the -x axis

    @pytest.mark.slow
    def test_topk_pruning_exact_when_sparse(self):
        # with <= k vehicles inside the threshold per agent, the pruned
        # O(n*k^2) path must match the dense O(n^3) path exactly
        p = self._params()
        for seed in range(10):
            rng = np.random.default_rng(300 + seed)
            n = 12
            q = rng.uniform(-4, 4, size=(n, 3))
            vel = rng.normal(size=(n, 3)) * 0.5
            dense_v, dense_m = control.collision_avoidance(
                jnp.asarray(q), jnp.asarray(vel), p)
            # count in-range neighbors to pick a sufficient k
            d = np.linalg.norm(q[:, None, :2] - q[None, :, :2], axis=-1)
            within = (d <= p.d_avoid_thresh).sum(1) - 1
            k = int(within.max()) + 1
            prun_v, prun_m = control.collision_avoidance(
                jnp.asarray(q), jnp.asarray(vel), p, max_neighbors=k)
            np.testing.assert_array_equal(np.asarray(dense_m),
                                          np.asarray(prun_m))
            np.testing.assert_allclose(np.asarray(dense_v),
                                       np.asarray(prun_v), atol=1e-12)

    def test_surrounded_stops(self):
        # agent ringed by close obstacles on all sides => full stop
        p = SafetyParams(d_avoid_thresh=3.0, r_keep_out=1.2)
        angles = np.linspace(0, 2 * math.pi, 8, endpoint=False)
        ring = np.stack([1.4 * np.cos(angles), 1.4 * np.sin(angles),
                         np.ones(8)], axis=1)
        q = np.concatenate([[[0.0, 0, 1]], ring])
        vel = np.zeros((9, 3))
        vel[0] = [0.5, 0.0, 0.2]
        out, mod = control.collision_avoidance(jnp.asarray(q),
                                               jnp.asarray(vel), p)
        assert bool(mod[0])
        np.testing.assert_allclose(np.asarray(out[0]), 0.0, atol=1e-12)


class TestSafetyShaping:
    def test_saturate_velocity(self):
        p = SafetyParams(max_vel_xy=0.5, max_vel_z=0.3)
        v = jnp.asarray(np.array([[3.0, 4.0, -1.0], [0.1, 0.0, 0.1]]))
        out = np.asarray(control.saturate_velocity(v, p))
        np.testing.assert_allclose(np.linalg.norm(out[0, :2]), 0.5, atol=1e-9)
        # direction preserved
        np.testing.assert_allclose(out[0, :2] / 0.5,
                                   np.array([3.0, 4.0]) / 5.0, atol=1e-9)
        assert out[0, 2] == -0.3
        np.testing.assert_allclose(out[1], [0.1, 0.0, 0.1])

    def test_make_safe_traj_integrates_and_bounds(self):
        p = SafetyParams(
            bounds_min=jnp.asarray([0.0, 0.0, 0.0]),
            bounds_max=jnp.asarray([5.0, 5.0, 3.0]),
            max_accel_xy=100.0, max_accel_z=100.0)
        goal = control.TrajGoal.hover_at(jnp.asarray([[4.99, 2.0, 1.0]]))
        vel = jnp.asarray([[1.0, 0.0, 0.0]])
        dt = 0.01
        g2 = goal
        for _ in range(10):
            g2 = control.make_safe_traj(dt, vel, jnp.zeros((1,)), g2, p)
        # clamped at the x wall, velocity zeroed there
        assert float(g2.pos[0, 0]) <= 5.0 + 1e-12
        assert float(g2.vel[0, 0]) == 0.0

    def test_make_safe_traj_rate_limits(self):
        p = SafetyParams(max_accel_xy=0.5, max_accel_z=0.8,
                         bounds_min=jnp.asarray([-100.0, -100.0, -100.0]),
                         bounds_max=jnp.asarray([100.0, 100.0, 100.0]))
        goal = control.TrajGoal.hover_at(jnp.zeros((1, 3)))
        vel = jnp.asarray([[10.0, 0.0, 0.0]])
        g2 = control.make_safe_traj(0.01, vel, jnp.zeros((1,)), goal, p)
        # one tick from rest: |dv| <= a*dt
        assert abs(float(g2.vel[0, 0])) <= 0.5 * 0.01 + 1e-12
