"""Wire front end for swarmserve (`aclswarm_tpu.serve.wire`;
docs/SERVICE.md §wire protocol).

External-process semantics over the shm rings, tested in-process with
real rings: submit/accept/event/result round trips match the direct
API bit-for-bit, a CRC-failing frame is rejected loudly without
touching service state, admission rejection crosses the wire with its
retry-after hint, and a client that stops talking has its QUEUED
entries cancelled with a structured error while resident work finishes
its batch (loud disconnect, never a batch cancellation).

Requires the native transport (``make -C native``) — skipped loudly
otherwise, like the rest of the shm tests.
"""
from __future__ import annotations

import time
import uuid

import numpy as np
import pytest

from aclswarm_tpu.interop import native as nat
from aclswarm_tpu.serve import FAILED, ServiceConfig, SwarmService

pytestmark = [pytest.mark.serve,
              pytest.mark.skipif(not nat.build(),
                                 reason="native transport not built "
                                        "(make -C native)")]

ROLL = {"n": 5, "ticks": 60, "chunk_ticks": 20, "seed": 5}


def _base() -> str:
    return "asw-wiretest-" + uuid.uuid4().hex[:6]


@pytest.fixture
def stack():
    """(service, server, client) on a unique ring namespace."""
    from aclswarm_tpu.serve.wire import WireClient, WireServer

    svc = SwarmService(ServiceConfig(max_batch=2))
    base = _base()
    srv = WireServer(svc, base, client_lease_s=30.0)
    cli = WireClient(base, tenant="ext")
    yield svc, srv, cli
    cli.close()
    srv.close()
    svc.close()


class TestWireRoundTrip:
    def test_submit_stream_result_matches_direct_api(self, stack):
        svc, srv, cli = stack
        want = svc.submit("rollout", ROLL, tenant="direct").result(240)
        t = cli.submit("rollout", ROLL)
        res = t.result(timeout=240)
        assert res.ok and res.chunks == 3
        # the wire result is the SAME value the in-process API returns
        assert int(res.value["digest"]) == int(want.value["digest"])
        assert np.array_equal(np.asarray(res.value["q"]),
                              np.asarray(want.value["q"]))
        events = list(t.stream(timeout=1))
        assert [e.payload["chunk"] for e in events] == [0, 1, 2]
        assert events[-1].payload["digest"] == res.value["digest"]

    def test_single_shot_kinds_and_malformed_refusal(self, stack):
        svc, srv, cli = stack
        ra = cli.submit("assign", {"n": 10, "seed": 1}).result(120)
        assert ra.ok
        assert sorted(np.asarray(ra.value["perm"])) == list(range(10))
        # a malformed request is refused with a structured wire error,
        # not accepted-and-failed (admission-time validation holds
        # across the wire)
        rbad = cli.submit("rollout", {"n": 5, "ticks": 50,
                                      "chunk_ticks": 20}).result(60)
        assert rbad.status == FAILED
        assert rbad.error.code == "wire_error"
        assert "chunks run whole" in rbad.error.message
        assert svc.stats["accepted"] == 1   # only the assign

    def test_wire_trace_postmortem_and_stats_scrape(self, tmp_path):
        """swarmtrace across the wire: the CLIENT mints the trace_id,
        the service adopts it (journal acceptance frame + every
        lifecycle event + the result frame), and the postmortem
        reconstructs the whole story from the journal alone. Plus the
        `stats` kind: an off-process client scrapes prometheus text
        over the same rings — no package import needed on the scraper
        side (ISSUE 9 satellites)."""
        from aclswarm_tpu.serve.wire import WireClient, WireServer
        from aclswarm_tpu.telemetry import postmortem

        svc = SwarmService(ServiceConfig(max_batch=2,
                                         journal_dir=str(tmp_path)))
        base = _base()
        srv = WireServer(svc, base, client_lease_s=30.0)
        cli = WireClient(base, tenant="ext")
        t = cli.submit("rollout", ROLL, request_id="w-traced",
                       trace_id="beefbeefbeefbeef")
        res = t.result(timeout=240)
        assert res.ok and res.chunks == 3
        # the client-minted id came back on the wire result frame
        assert res.trace_id == "beefbeefbeefbeef"
        # an auto-minted wire trace also round-trips
        r2 = cli.submit("assign", {"n": 6, "seed": 1}).result(120)
        assert r2.ok and len(r2.trace_id) == 16
        # off-process scrape over the wire: prometheus text, no import
        rs = cli.submit("stats", {"format": "prometheus"}).result(120)
        assert rs.ok and "serve_accepted_total" in rs.value["text"]
        cli.close()
        srv.close()
        svc.close()
        # postmortem from the journal alone: the wire-submitted request
        # reconstructs complete + gap-free under the CLIENT's trace_id
        rep = postmortem.reconstruct(tmp_path)
        wt = rep["requests"]["w-traced"]
        assert wt["complete"] and wt["gap_free"], wt["problems"]
        assert wt["trace_id"] == "beefbeefbeefbeef"
        assert wt["chunks"] == 3 and wt["status"] == "completed"
        assert rep["complete"] == rep["reconstructed"]

    def test_crc_rejection_is_loud_and_isolated(self, stack):
        svc, srv, cli = stack
        cli._c2s.send_bytes(b"\x00garbage that is not a frame")
        deadline = time.monotonic() + 10
        reject = svc.telemetry.counter("wire_crc_rejected_total")
        while reject.value < 1 and time.monotonic() < deadline:
            time.sleep(0.02)
        assert reject.value == 1
        # the connection survives: the next valid frame is served
        assert cli.submit("assign", {"n": 6}).result(120).ok

    def test_concurrent_client_connections_serialize_on_ctl(self):
        """The shm ring is single-producer, but every client HELLOs on
        the one shared control ring: the cross-process writer lock must
        serialize them (regression: two concurrent connects interleaved
        their head updates and misframed the ctl ring for everyone)."""
        import threading

        from aclswarm_tpu.serve.wire import WireClient, WireServer

        svc = SwarmService(ServiceConfig(max_batch=4))
        base = _base()
        srv = WireServer(svc, base)
        oks, errs = [], []

        def connect(i):
            try:
                c = WireClient(base, tenant=f"c{i}")
                oks.append(c.submit("assign",
                                    {"n": 6, "seed": i}).result(120).ok)
                c.close()
            except Exception as e:      # noqa: BLE001 — recorded
                errs.append(repr(e))

        threads = [threading.Thread(target=connect, args=(i,))
                   for i in range(5)]
        for t in threads:
            t.start()
        for t in threads:
            t.join(180)
        assert not errs and oks == [True] * 5, (oks, errs)
        srv.close()
        svc.close()

    def test_queue_full_rejection_crosses_the_wire(self):
        from aclswarm_tpu.serve.wire import WireClient, WireServer

        svc = SwarmService(ServiceConfig(max_queue_per_tenant=1),
                           start=False)
        base = _base()
        srv = WireServer(svc, base)
        cli = WireClient(base, tenant="ext")
        t1 = cli.submit("assign", {"n": 6}, request_id="w-keep")
        r2 = cli.submit("assign", {"n": 6},
                        request_id="w-bounce").result(30)
        assert r2.status == FAILED and r2.error.code == "queue_full"
        assert r2.error.detail["retry_after_s"] > 0
        assert not t1.done                  # accepted, still owed
        cli.close()
        srv.close()
        svc.close(drain=False)

    def test_connection_default_deadline_applies(self):
        """Regression: the client frame always carries a ``deadline_s``
        key (None when unset), so the server must apply its
        per-connection default on a None VALUE, not on key absence —
        otherwise `default_deadline_s` is dead code and a slow client
        parks unbounded work."""
        from aclswarm_tpu.serve.wire import WireClient, WireServer

        svc = SwarmService(ServiceConfig())
        base = _base()
        srv = WireServer(svc, base, default_deadline_s=0.0)
        cli = WireClient(base, tenant="ext")
        r = cli.submit("rollout", ROLL).result(timeout=60)
        assert r.status == "timed_out"
        assert r.error.code == "deadline_exceeded"
        # an explicit per-request deadline overrides the default
        r2 = cli.submit("assign", {"n": 6},
                        deadline_s=60.0).result(timeout=60)
        assert r2.ok
        cli.close()
        srv.close()
        svc.close()

    def test_dead_client_cancels_entries_at_boundaries(self):
        """Loud disconnect semantics: the client vanishes (no BYE, no
        pings) with two long rollouts in flight. Every entry terminates
        with a structured ``cancelled`` error — queued entries
        immediately, the RESIDENT one only at its next chunk boundary
        (``Result.chunks >= 1``: the running batch is never cancelled
        mid-kernel), and the disconnect is counted + logged."""
        from aclswarm_tpu.serve.wire import WireClient, WireServer

        svc = SwarmService(ServiceConfig(max_batch=1, quantum_chunks=99))
        base = _base()
        srv = WireServer(svc, base, client_lease_s=1.0)
        cli = WireClient(base, tenant="ext", ping_s=0.2)
        cli.submit("rollout", dict(ROLL, ticks=10_000),
                   request_id="w-a")
        cli.submit("rollout", dict(ROLL, ticks=10_000, seed=9),
                   request_id="w-b")
        # wait until at least one is resident and producing chunks
        deadline = time.monotonic() + 120
        while svc.stats["chunks"] < 1 and time.monotonic() < deadline:
            time.sleep(0.02)
        assert svc.stats["chunks"] >= 1
        # the client DIES: reader+pinger stop, rings stay (no BYE)
        cli._stop.set()
        cli._thread.join(5)
        deadline = time.monotonic() + 60
        while svc.stats["cancelled"] < 2 and time.monotonic() < deadline:
            time.sleep(0.05)
        assert svc.stats["cancelled"] == 2
        disc = svc.telemetry.counter("wire_client_disconnects_total")
        assert disc.value == 1
        results = {rid: svc._done_prior.get(rid)
                   for rid in ("w-a", "w-b")}
        assert all(r is not None and r.status == FAILED
                   and r.error.code == "cancelled"
                   for r in results.values()), results
        # the resident request reached a boundary before terminating —
        # it was never killed mid-batch
        assert max(r.chunks for r in results.values()) >= 1
        assert all(r.chunks < 500 for r in results.values())
        srv.close()
        svc.close(drain=False)
