"""Wire front end for swarmserve (`aclswarm_tpu.serve.wire`;
docs/SERVICE.md §wire protocol + §off-host serving).

External-process semantics over BOTH transports, tested in-process
with real rings and real sockets: submit/accept/event/result round
trips match the direct API bit-for-bit, a CRC-failing frame is
rejected loudly without touching service state, admission rejection
crosses the wire with its retry-after hint, and a client that stops
talking has its QUEUED entries cancelled with a structured error while
resident work finishes its batch (loud disconnect, never a batch
cancellation). The TCP classes add the off-host hardening surface:
slow-loris read/write bounds, handshake deadlines, accept-rate
bounding, reconnect attach, and seeded wire-frame fuzzing
(truncation / bit-flip / oversize / mid-frame disconnect) over both
transports.

The shm classes require the native transport (``make -C native``) —
skipped loudly otherwise, like the rest of the shm tests. The TCP
classes are pure stdlib and always run.
"""
from __future__ import annotations

import socket
import time
import uuid

import numpy as np
import pytest

from aclswarm_tpu.interop import native as nat
from aclswarm_tpu.serve import FAILED, ServiceConfig, SwarmService

pytestmark = [pytest.mark.serve]

needs_native = pytest.mark.skipif(not nat.build(),
                                  reason="native transport not built "
                                         "(make -C native)")

ROLL = {"n": 5, "ticks": 60, "chunk_ticks": 20, "seed": 5}


def _base() -> str:
    return "asw-wiretest-" + uuid.uuid4().hex[:6]


def _tcp_stack(svc, **kw):
    """(server, (host, port)) bound on an ephemeral port."""
    from aclswarm_tpu.serve.wire import WireServer

    srv = WireServer(svc, base=None, tcp=("127.0.0.1", 0),
                     client_lease_s=kw.pop("client_lease_s", 30.0), **kw)
    return srv, srv.tcp_address


@pytest.fixture
def stack():
    """(service, server, client) on a unique ring namespace."""
    from aclswarm_tpu.serve.wire import WireClient, WireServer

    svc = SwarmService(ServiceConfig(max_batch=2))
    base = _base()
    srv = WireServer(svc, base, client_lease_s=30.0)
    cli = WireClient(base, tenant="ext")
    yield svc, srv, cli
    cli.close()
    srv.close()
    svc.close()


@needs_native
class TestWireRoundTrip:
    def test_submit_stream_result_matches_direct_api(self, stack):
        svc, srv, cli = stack
        want = svc.submit("rollout", ROLL, tenant="direct").result(240)
        t = cli.submit("rollout", ROLL)
        res = t.result(timeout=240)
        assert res.ok and res.chunks == 3
        # the wire result is the SAME value the in-process API returns
        assert int(res.value["digest"]) == int(want.value["digest"])
        assert np.array_equal(np.asarray(res.value["q"]),
                              np.asarray(want.value["q"]))
        events = list(t.stream(timeout=1))
        assert [e.payload["chunk"] for e in events] == [0, 1, 2]
        assert events[-1].payload["digest"] == res.value["digest"]

    def test_single_shot_kinds_and_malformed_refusal(self, stack):
        svc, srv, cli = stack
        ra = cli.submit("assign", {"n": 10, "seed": 1}).result(120)
        assert ra.ok
        assert sorted(np.asarray(ra.value["perm"])) == list(range(10))
        # a malformed request is refused with a structured wire error,
        # not accepted-and-failed (admission-time validation holds
        # across the wire)
        rbad = cli.submit("rollout", {"n": 5, "ticks": 50,
                                      "chunk_ticks": 20}).result(60)
        assert rbad.status == FAILED
        assert rbad.error.code == "wire_error"
        assert "chunks run whole" in rbad.error.message
        assert svc.stats["accepted"] == 1   # only the assign

    def test_wire_trace_postmortem_and_stats_scrape(self, tmp_path):
        """swarmtrace across the wire: the CLIENT mints the trace_id,
        the service adopts it (journal acceptance frame + every
        lifecycle event + the result frame), and the postmortem
        reconstructs the whole story from the journal alone. Plus the
        `stats` kind: an off-process client scrapes prometheus text
        over the same rings — no package import needed on the scraper
        side (ISSUE 9 satellites)."""
        from aclswarm_tpu.serve.wire import WireClient, WireServer
        from aclswarm_tpu.telemetry import postmortem

        svc = SwarmService(ServiceConfig(max_batch=2,
                                         journal_dir=str(tmp_path)))
        base = _base()
        srv = WireServer(svc, base, client_lease_s=30.0)
        cli = WireClient(base, tenant="ext")
        t = cli.submit("rollout", ROLL, request_id="w-traced",
                       trace_id="beefbeefbeefbeef")
        res = t.result(timeout=240)
        assert res.ok and res.chunks == 3
        # the client-minted id came back on the wire result frame
        assert res.trace_id == "beefbeefbeefbeef"
        # an auto-minted wire trace also round-trips
        r2 = cli.submit("assign", {"n": 6, "seed": 1}).result(120)
        assert r2.ok and len(r2.trace_id) == 16
        # off-process scrape over the wire: prometheus text, no import
        rs = cli.submit("stats", {"format": "prometheus"}).result(120)
        assert rs.ok and "serve_accepted_total" in rs.value["text"]
        cli.close()
        srv.close()
        svc.close()
        # postmortem from the journal alone: the wire-submitted request
        # reconstructs complete + gap-free under the CLIENT's trace_id
        rep = postmortem.reconstruct(tmp_path)
        wt = rep["requests"]["w-traced"]
        assert wt["complete"] and wt["gap_free"], wt["problems"]
        assert wt["trace_id"] == "beefbeefbeefbeef"
        assert wt["chunks"] == 3 and wt["status"] == "completed"
        assert rep["complete"] == rep["reconstructed"]

    def test_crc_rejection_is_loud_and_isolated(self, stack):
        svc, srv, cli = stack
        cli._c2s.send_bytes(b"\x00garbage that is not a frame")
        deadline = time.monotonic() + 10
        reject = svc.telemetry.counter("wire_crc_rejected_total")
        while reject.value < 1 and time.monotonic() < deadline:
            time.sleep(0.02)
        assert reject.value == 1
        # the connection survives: the next valid frame is served
        assert cli.submit("assign", {"n": 6}).result(120).ok

    @pytest.mark.slow
    def test_concurrent_client_connections_serialize_on_ctl(self):
        """The shm ring is single-producer, but every client HELLOs on
        the one shared control ring: the cross-process writer lock must
        serialize them (regression: two concurrent connects interleaved
        their head updates and misframed the ctl ring for everyone)."""
        import threading

        from aclswarm_tpu.serve.wire import WireClient, WireServer

        svc = SwarmService(ServiceConfig(max_batch=4))
        base = _base()
        srv = WireServer(svc, base)
        oks, errs = [], []

        def connect(i):
            try:
                c = WireClient(base, tenant=f"c{i}")
                oks.append(c.submit("assign",
                                    {"n": 6, "seed": i}).result(120).ok)
                c.close()
            except Exception as e:      # noqa: BLE001 — recorded
                errs.append(repr(e))

        threads = [threading.Thread(target=connect, args=(i,))
                   for i in range(5)]
        for t in threads:
            t.start()
        for t in threads:
            t.join(180)
        assert not errs and oks == [True] * 5, (oks, errs)
        srv.close()
        svc.close()

    def test_queue_full_rejection_crosses_the_wire(self):
        from aclswarm_tpu.serve.wire import WireClient, WireServer

        svc = SwarmService(ServiceConfig(max_queue_per_tenant=1),
                           start=False)
        base = _base()
        srv = WireServer(svc, base)
        cli = WireClient(base, tenant="ext")
        t1 = cli.submit("assign", {"n": 6}, request_id="w-keep")
        r2 = cli.submit("assign", {"n": 6},
                        request_id="w-bounce").result(30)
        assert r2.status == FAILED and r2.error.code == "queue_full"
        assert r2.error.detail["retry_after_s"] > 0
        assert not t1.done                  # accepted, still owed
        cli.close()
        srv.close()
        svc.close(drain=False)

    def test_connection_default_deadline_applies(self):
        """Regression: the client frame always carries a ``deadline_s``
        key (None when unset), so the server must apply its
        per-connection default on a None VALUE, not on key absence —
        otherwise `default_deadline_s` is dead code and a slow client
        parks unbounded work."""
        from aclswarm_tpu.serve.wire import WireClient, WireServer

        svc = SwarmService(ServiceConfig())
        base = _base()
        srv = WireServer(svc, base, default_deadline_s=0.0)
        cli = WireClient(base, tenant="ext")
        r = cli.submit("rollout", ROLL).result(timeout=60)
        assert r.status == "timed_out"
        assert r.error.code == "deadline_exceeded"
        # an explicit per-request deadline overrides the default
        r2 = cli.submit("assign", {"n": 6},
                        deadline_s=60.0).result(timeout=60)
        assert r2.ok
        cli.close()
        srv.close()
        svc.close()

    def test_dead_client_cancels_entries_at_boundaries(self):
        """Loud disconnect semantics: the client vanishes (no BYE, no
        pings) with two long rollouts in flight. Every entry terminates
        with a structured ``cancelled`` error — queued entries
        immediately, the RESIDENT one only at its next chunk boundary
        (``Result.chunks >= 1``: the running batch is never cancelled
        mid-kernel), and the disconnect is counted + logged."""
        from aclswarm_tpu.serve.wire import WireClient, WireServer

        svc = SwarmService(ServiceConfig(max_batch=1, quantum_chunks=99))
        base = _base()
        srv = WireServer(svc, base, client_lease_s=1.0)
        cli = WireClient(base, tenant="ext", ping_s=0.2)
        cli.submit("rollout", dict(ROLL, ticks=10_000),
                   request_id="w-a")
        cli.submit("rollout", dict(ROLL, ticks=10_000, seed=9),
                   request_id="w-b")
        # wait until at least one is resident and producing chunks
        deadline = time.monotonic() + 120
        while svc.stats["chunks"] < 1 and time.monotonic() < deadline:
            time.sleep(0.02)
        assert svc.stats["chunks"] >= 1
        # the client DIES: reader+pinger stop, rings stay (no BYE)
        cli._stop.set()
        cli._thread.join(5)
        deadline = time.monotonic() + 60
        while svc.stats["cancelled"] < 2 and time.monotonic() < deadline:
            time.sleep(0.05)
        assert svc.stats["cancelled"] == 2
        disc = svc.telemetry.counter("wire_client_disconnects_total")
        assert disc.value == 1
        results = {rid: svc._done_prior.get(rid)
                   for rid in ("w-a", "w-b")}
        assert all(r is not None and r.status == FAILED
                   and r.error.code == "cancelled"
                   for r in results.values()), results
        # the resident request reached a boundary before terminating —
        # it was never killed mid-batch
        assert max(r.chunks for r in results.values()) >= 1
        assert all(r.chunks < 500 for r in results.values())
        srv.close()
        svc.close(drain=False)


# ---------------------------------------------------------------- TCP


class TestTcpWire:
    def test_round_trip_matches_direct_api(self):
        from aclswarm_tpu.serve.wire import WireClient

        svc = SwarmService(ServiceConfig(max_batch=2))
        srv, (host, port) = _tcp_stack(svc)
        cli = WireClient(tcp=(host, port), tenant="ext")
        want = svc.submit("rollout", ROLL, tenant="direct").result(240)
        t = cli.submit("rollout", ROLL)
        res = t.result(timeout=240)
        assert res.ok and res.chunks == 3
        assert int(res.value["digest"]) == int(want.value["digest"])
        assert np.array_equal(np.asarray(res.value["q"]),
                              np.asarray(want.value["q"]))
        events = list(t.stream(timeout=1))
        assert [e.payload["chunk"] for e in events] == [0, 1, 2]
        # the scrape surface works off-host too
        rs = cli.submit("stats", {"format": "prometheus"}).result(120)
        assert rs.ok and "serve_accepted_total" in rs.value["text"]
        cli.close()
        srv.close()
        svc.close()

    def test_submit_and_wait_honors_retry_after(self):
        """The ISSUE-13 satellite: a queue_full rejection is retried
        after the server's hint (deterministic jitter), not surfaced
        raw — the caller sees the eventual result, and the reject
        ledger shows the backpressure actually engaged."""
        import threading

        from aclswarm_tpu.serve.wire import WireClient

        svc = SwarmService(ServiceConfig(max_queue_per_tenant=1,
                                         max_batch=1, idle_poll_s=0.01),
                           start=False)
        srv, (host, port) = _tcp_stack(svc)
        cli = WireClient(tcp=(host, port), tenant="ext")
        # the workers are NOT started: the occupier pins the one
        # tenant-cap slot, so the next submit is deterministically
        # rejected. The worker fleet starts shortly after — the
        # honored retry then lands once the occupier is picked.
        cli.submit("rollout", ROLL, request_id="w-occupy")
        starter = threading.Timer(0.8, svc.start)
        starter.start()
        r = cli.submit_and_wait("assign", {"n": 6}, timeout=240,
                                reject_retries=16)
        starter.join()
        assert r.ok, r.error
        assert svc.telemetry.counter("serve_rejected_total").value >= 1
        # with retries disabled the raw queue_full surfaces
        svc2 = SwarmService(ServiceConfig(max_queue_per_tenant=1),
                            start=False)
        srv2, (h2, p2) = _tcp_stack(svc2)
        cli2 = WireClient(tcp=(h2, p2), tenant="ext")
        cli2.submit("assign", {"n": 6}, request_id="w-keep")
        r2 = cli2.submit_and_wait("assign", {"n": 6}, timeout=30,
                                  reject_retries=0)
        assert r2.status == FAILED and r2.error.code == "queue_full"
        assert r2.error.detail["retry_after_s"] > 0
        cli2.close()
        srv2.close()
        svc2.close(drain=False)
        cli.close()
        srv.close()
        svc.close()

    def test_slowloris_read_bound_drops_only_the_loris(self):
        """A client trickling a frame byte-by-byte is declared gone at
        the read deadline (counted), its queued work cancelled with the
        structured error — while an honest client on the same server
        keeps being served and the dispatcher never stalls."""
        from aclswarm_tpu.serve.wire import (K_HELLO, K_SUBMIT,
                                             WireClient, _frame)

        svc = SwarmService(ServiceConfig(max_batch=2))
        srv, (host, port) = _tcp_stack(svc, read_deadline_s=0.5,
                                       handshake_s=2.0)
        s = socket.create_connection((host, port))
        hello = _frame(K_HELLO, {"client": "loris"})
        s.sendall(len(hello).to_bytes(4, "little") + hello)
        sub = _frame(K_SUBMIT, {
            "request_id": "l-1", "kind": "rollout",
            "params": dict(ROLL, ticks=10_000), "tenant": "loris",
            "deadline_s": None, "trace_id": "f" * 16})
        framed = len(sub).to_bytes(4, "little") + sub
        s.sendall(framed[:6])          # header + 2 bytes, then stall
        loris = svc.telemetry.counter("wire_slowloris_dropped_total")
        deadline = time.monotonic() + 15
        while loris.value < 1 and time.monotonic() < deadline:
            time.sleep(0.02)
        assert loris.value == 1
        # the honest client was never impacted
        cli = WireClient(tcp=(host, port), tenant="honest")
        assert cli.submit("assign", {"n": 6}).result(120).ok
        cli.close()
        s.close()
        srv.close()
        svc.close()

    def test_write_stall_bounded_buffer_drops_client(self):
        """The write half of slow-loris: a client that submits work
        and never drains responses fills its BOUNDED outbound buffer
        and is declared gone — the dispatcher keeps serving instead of
        blocking on the send."""
        from aclswarm_tpu.serve.wire import (K_HELLO, K_SUBMIT,
                                             WireClient, _frame)

        svc = SwarmService(ServiceConfig(max_batch=2))
        # a tiny server-side user buffer so undrained responses
        # overflow it once the kernel buffers are pinched below
        srv, (host, port) = _tcp_stack(svc, sock_buffer=4096,
                                       read_deadline_s=30.0)
        # the client: minimal receive window, and it NEVER reads
        s = socket.socket(socket.AF_INET, socket.SOCK_STREAM)
        s.setsockopt(socket.SOL_SOCKET, socket.SO_RCVBUF, 1)
        s.connect((host, port))
        hello = _frame(K_HELLO, {"client": "sink"})
        s.sendall(len(hello).to_bytes(4, "little") + hello)
        # pinch the server's kernel send buffer too, once the
        # connection is promoted
        deadline = time.monotonic() + 10
        while "sink" not in srv._conns and time.monotonic() < deadline:
            time.sleep(0.02)
        srv._conns["sink"].s2c._sock.setsockopt(
            socket.SOL_SOCKET, socket.SO_SNDBUF, 1)
        # ask for work whose responses are BIG (the prometheus scrape
        # is several KB) and never drain any of it
        for k in range(24):
            sub = _frame(K_SUBMIT, {
                "request_id": f"sink-{k}", "kind": "stats",
                "params": {"format": "prometheus"}, "tenant": "sink",
                "deadline_s": None, "trace_id": "a" * 16})
            s.sendall(len(sub).to_bytes(4, "little") + sub)
        loris = svc.telemetry.counter("wire_slowloris_dropped_total")
        deadline = time.monotonic() + 25
        while loris.value < 1 and time.monotonic() < deadline:
            time.sleep(0.05)
        assert loris.value >= 1
        # server healthy for others
        cli = WireClient(tcp=(host, port), tenant="honest")
        assert cli.submit("assign", {"n": 6}).result(120).ok
        cli.close()
        s.close()
        srv.close()
        svc.close(drain=False)

    def test_reconnect_attaches_pending_and_replays_idempotently(self):
        """Reconnect-storm hardening: an abrupt socket death (no BYE)
        followed by a reconnect under the SAME client id transfers the
        pending tickets; re-submitting the same request_id attaches to
        the existing job via the atomic id reservation — exactly one
        execution, the result delivered to the new connection."""
        from aclswarm_tpu.serve.wire import WireClient

        svc = SwarmService(ServiceConfig(max_batch=1,
                                         quantum_chunks=99))
        srv, (host, port) = _tcp_stack(svc)
        cli = WireClient(tcp=(host, port), tenant="ext",
                         client_id="stormy")
        roll = dict(ROLL, ticks=4000)
        cli.submit("rollout", roll, request_id="w-keep")
        deadline = time.monotonic() + 120
        while svc.stats["chunks"] < 1 and time.monotonic() < deadline:
            time.sleep(0.02)
        # the client WEDGES: reader stopped, no BYE, socket left open
        # (closing it races the reconnect against the server's
        # reset-detection — a client that dies visibly first gets the
        # documented `cancelled` outcome instead; the attach path under
        # test is reconnect-before-the-server-notices)
        cli._stop.set()
        cli._thread.join(5)
        cli2 = WireClient(tcp=(host, port), tenant="ext",
                          client_id="stormy")
        r = cli2.submit("rollout", roll,
                        request_id="w-keep").result(timeout=240)
        assert r.ok
        assert svc.stats["accepted"] == 1          # ONE execution
        assert svc.telemetry.counter(
            "wire_reconnects_total").value == 1
        cli._c2s.close()           # the wedged client's leaked fd
        cli2.close()
        srv.close()
        svc.close()

    def test_handshake_deadline_and_garbage_hello(self):
        from aclswarm_tpu.serve.wire import WireServer

        svc = SwarmService(ServiceConfig(), start=False)
        srv = WireServer(svc, base=None, tcp=("127.0.0.1", 0),
                         handshake_s=0.3)
        host, port = srv.tcp_address
        expired = svc.telemetry.counter("wire_handshake_expired_total")
        rejected = svc.telemetry.counter("wire_handshake_rejected_total")
        # a socket that never completes a HELLO is closed at the bound
        s1 = socket.create_connection((host, port))
        # a socket whose first frame is garbage is closed immediately —
        # counted SEPARATELY (a misbehaving client, not a slow
        # handshake)
        s2 = socket.create_connection((host, port))
        s2.sendall((16).to_bytes(4, "little") + b"x" * 16)
        deadline = time.monotonic() + 10
        while (expired.value < 1 or rejected.value < 1) \
                and time.monotonic() < deadline:
            time.sleep(0.02)
        assert expired.value == 1 and rejected.value == 1
        s1.close()
        s2.close()
        srv.close()
        svc.close(drain=False)

    @pytest.mark.slow
    def test_accept_rate_bounding_defers_not_denies(self):
        """The token bucket defers accepts past the rate (counted) but
        every well-behaved client still connects — the storm waits in
        the backlog instead of monopolizing the dispatcher."""
        from aclswarm_tpu.serve.wire import WireClient, WireServer

        svc = SwarmService(ServiceConfig(max_batch=4))
        srv = WireServer(svc, base=None, tcp=("127.0.0.1", 0),
                         accept_rate=5.0)
        srv._listener._burst = 2        # tiny burst for the test
        srv._listener._tokens = 2.0
        host, port = srv.tcp_address
        clis = [WireClient(tcp=(host, port), tenant=f"c{i}",
                           hello_timeout_s=30.0) for i in range(6)]
        oks = [c.submit("assign", {"n": 6, "seed": i}).result(120).ok
               for i, c in enumerate(clis)]
        assert oks == [True] * 6
        assert srv._listener.throttled >= 1
        for c in clis:
            c.close()
        srv.close()
        svc.close()


# ------------------------------------------------------ wire fuzzing


def _fuzz_stack(transport_kind: str):
    """(svc, srv, cli, raw_send, teardown) — raw_send injects BYTES
    onto the client->server channel of an ESTABLISHED connection, on
    either transport."""
    from aclswarm_tpu.serve.wire import WireClient

    svc = SwarmService(ServiceConfig(max_batch=2))
    if transport_kind == "tcp":
        srv, (host, port) = _tcp_stack(svc, read_deadline_s=30.0)
        cli = WireClient(tcp=(host, port), tenant="fuzz")
        raw = cli._c2s._sock.sendall
    else:
        from aclswarm_tpu.serve.wire import WireServer

        base = _base()
        srv = WireServer(svc, base, client_lease_s=30.0)
        cli = WireClient(base, tenant="fuzz")

        def raw(b):
            assert cli._c2s.send_bytes(b)

    def teardown():
        cli.close()
        srv.close()
        svc.close(drain=False)

    return svc, srv, cli, raw, teardown


def _tcp_framed(record: bytes) -> bytes:
    return len(record).to_bytes(4, "little") + record


@pytest.mark.parametrize("transport_kind", [
    "tcp", pytest.param("shm", marks=needs_native)])
class TestWireFuzz:
    """Seeded wire-frame fuzzing over both transports: the dispatcher
    survives every class of damage, exactly the afflicted connection
    is declared gone (when the damage is structural to the STREAM),
    and the rejection counters increment — never a partial
    application, never a wedged server."""

    def test_bitflip_records_all_rejected(self, transport_kind):
        from aclswarm_tpu.serve.wire import K_SUBMIT, _frame

        svc, srv, cli, raw, teardown = _fuzz_stack(transport_kind)
        try:
            rng = np.random.default_rng(5)
            reject = svc.telemetry.counter("wire_crc_rejected_total")
            sent = 12
            for k in range(sent):
                rec = bytearray(_frame(K_SUBMIT, {
                    "request_id": f"fz-{k}", "kind": "assign",
                    "params": {"n": 6, "seed": k}, "tenant": "fuzz",
                    "deadline_s": None, "trace_id": "b" * 16}))
                pos = int(rng.integers(0, len(rec)))
                rec[pos] ^= 1 << int(rng.integers(0, 8))
                raw(_tcp_framed(bytes(rec)) if transport_kind == "tcp"
                    else bytes(rec))
            deadline = time.monotonic() + 30
            while reject.value < sent and time.monotonic() < deadline:
                time.sleep(0.02)
            # EVERY flipped record rejected; nothing applied
            assert reject.value == sent
            assert svc.stats["accepted"] == 0
            # the connection survives record-level damage: a valid
            # submit on the same connection is served
            assert cli.submit("assign", {"n": 6}).result(120).ok
        finally:
            teardown()

    def test_truncated_record_rejected(self, transport_kind):
        from aclswarm_tpu.serve.wire import K_SUBMIT, _frame

        svc, srv, cli, raw, teardown = _fuzz_stack(transport_kind)
        try:
            rec = _frame(K_SUBMIT, {
                "request_id": "tr-1", "kind": "assign",
                "params": {"n": 6}, "tenant": "fuzz",
                "deadline_s": None, "trace_id": "c" * 16})
            cut = rec[:len(rec) // 2]
            # a truncated RECORD inside a well-formed transport frame:
            # the codec CRC rejects it, the connection survives
            raw(_tcp_framed(cut) if transport_kind == "tcp" else cut)
            reject = svc.telemetry.counter("wire_crc_rejected_total")
            deadline = time.monotonic() + 15
            while reject.value < 1 and time.monotonic() < deadline:
                time.sleep(0.02)
            assert reject.value == 1 and svc.stats["accepted"] == 0
            assert cli.submit("assign", {"n": 6}).result(120).ok
        finally:
            teardown()

    def test_oversize_frame_kills_only_that_connection(
            self, transport_kind):
        from aclswarm_tpu.serve.wire import WireClient

        svc, srv, cli, raw, teardown = _fuzz_stack(transport_kind)
        try:
            if transport_kind == "tcp":
                # a length prefix past max_frame is stream corruption:
                # THIS connection is declared gone...
                raw((1 << 30).to_bytes(4, "little") + b"x" * 64)
                gone = svc.telemetry.counter(
                    "wire_client_disconnects_total")
                deadline = time.monotonic() + 15
                while gone.value < 1 and time.monotonic() < deadline:
                    time.sleep(0.02)
                assert gone.value == 1
            else:
                # the shm ring bounds frames at the SENDING side: an
                # oversized frame is refused with ValueError before it
                # can ever misframe the ring (the receiving-side n<0
                # contract is covered by the native ring tests)
                with pytest.raises(ValueError):
                    cli._c2s.send_bytes(b"x" * (2 << 20))
            # ...and the SERVER keeps serving new connections
            if transport_kind == "tcp":
                c2 = WireClient(tcp=srv.tcp_address, tenant="ok")
            else:
                c2 = WireClient(srv.base, tenant="ok")
            assert c2.submit("assign", {"n": 6}).result(120).ok
            c2.close()
        finally:
            teardown()

    def test_midframe_disconnect_declares_client_gone(
            self, transport_kind):
        from aclswarm_tpu.serve.wire import K_SUBMIT, _frame

        if transport_kind == "shm":
            pytest.skip("mid-frame disconnect is a stream property; "
                        "the shm ring writes frames atomically")
        svc, srv, cli, raw, teardown = _fuzz_stack(transport_kind)
        try:
            rec = _frame(K_SUBMIT, {
                "request_id": "md-1", "kind": "assign",
                "params": {"n": 6}, "tenant": "fuzz",
                "deadline_s": None, "trace_id": "d" * 16})
            framed = _tcp_framed(rec)
            raw(framed[:len(framed) // 2])
            # the socket dies mid-frame (no BYE): reader stops first so
            # the close is abrupt from the server's point of view
            cli._stop.set()
            cli._thread.join(5)
            cli._c2s._sock.close()
            gone = svc.telemetry.counter(
                "wire_client_disconnects_total")
            deadline = time.monotonic() + 15
            while gone.value < 1 and time.monotonic() < deadline:
                time.sleep(0.02)
            assert gone.value == 1
            # the half-frame was never applied
            assert svc.stats["accepted"] == 0
        finally:
            srv.close()
            svc.close(drain=False)

    def test_codec_single_bit_flips_all_detected(self, transport_kind):
        """The exhaustive ground truth under the per-connection CRC
        story: EVERY single-bit flip of a wire record — header bytes
        included — fails `checkpoint.loads`. (The reserved-byte and
        meta-length header gaps this found are regression-pinned
        here.)"""
        if transport_kind == "shm":
            pytest.skip("transport-independent — run once under tcp")
        from aclswarm_tpu.resilience import checkpoint as ck
        from aclswarm_tpu.serve.wire import K_SUBMIT, _frame

        rec = _frame(K_SUBMIT, {
            "request_id": "bf", "kind": "assign",
            "params": {"n": 6, "seed": 1}, "tenant": "t",
            "deadline_s": None, "trace_id": "e" * 16})
        undetected = []
        for pos in range(len(rec)):
            for bit in range(8):
                bad = bytearray(rec)
                bad[pos] ^= 1 << bit
                try:
                    ck.loads(bytes(bad), "<fuzz>")
                    undetected.append((pos, bit))
                except ck.CheckpointError:
                    pass
        assert not undetected, undetected


# --------------------------------------------------- socket transport


class TestSocketTransport:
    def test_burst_framing_and_observables(self):
        from aclswarm_tpu.interop import transport as T

        with T.SocketListener() as lst:
            host, port = lst.address
            cli = T.connect_when_ready(host, port, grace_s=5)
            srv = None
            deadline = time.monotonic() + 5
            while srv is None and time.monotonic() < deadline:
                srv = lst.accept()
                time.sleep(0.005)
            frames = [bytes([i]) * (50 + i) for i in range(20)]
            for f in frames:
                assert cli.send_bytes(f)
            got = []
            deadline = time.monotonic() + 5
            while len(got) < 20 and time.monotonic() < deadline:
                f = srv.recv_bytes()
                if f is None:
                    time.sleep(0.001)
                    continue
                got.append(f)
            assert got == frames
            # slow-loris observable: a partial frame ages
            cli._sock.sendall((500).to_bytes(4, "little") + b"zz")
            time.sleep(0.06)
            assert srv.recv_bytes() is None
            assert srv.stalled_recv_s > 0.0
            # peer close raises (the corrupt-ring contract)
            cli.close()
            with pytest.raises(OSError):
                deadline = time.monotonic() + 5
                while time.monotonic() < deadline:
                    srv.recv_bytes()
                    time.sleep(0.005)
            srv.close()

    def test_bounded_buffer_backpressure_and_oversize(self):
        from aclswarm_tpu.interop import transport as T

        with T.SocketListener() as lst:
            host, port = lst.address
            cli = T.connect_when_ready(host, port, grace_s=5)
            srv = None
            deadline = time.monotonic() + 5
            while srv is None and time.monotonic() < deadline:
                srv = lst.accept()
                time.sleep(0.005)
            # a frame that can NEVER fit raises (ring parity)
            cli._max_frame = 1024
            with pytest.raises(ValueError):
                cli.send_bytes(b"x" * 4096)
            # an undrained peer turns into False (explicit
            # backpressure), never a blocked writer
            cli._max_frame = T.MAX_FRAME
            cli._max_buffer = 8192
            cli._sock.setsockopt(socket.SOL_SOCKET,
                                 socket.SO_SNDBUF, 2048)
            sent = 0
            saw_false = False
            for _ in range(2000):
                if cli.send_bytes(b"y" * 1024):
                    sent += 1
                else:
                    saw_false = True
                    break
            assert saw_false and sent >= 1
            assert cli.queued_bytes > 0
            cli.close()
            srv.close()

    def test_connect_when_ready_error_distinction(self):
        from aclswarm_tpu.interop import transport as T

        with pytest.raises(OSError, match="refused every connection"):
            T.connect_when_ready("127.0.0.1", 1, grace_s=0.3)

    @needs_native
    def test_open_when_ready_error_distinction(self, tmp_path):
        from aclswarm_tpu.interop import transport as T

        # never appeared: the message must blame the missing peer, not
        # the handshake
        with pytest.raises(OSError, match="never appeared"):
            T.open_when_ready("asw-nonexistent-" + uuid.uuid4().hex[:6],
                              grace_s=0.2)

    @needs_native
    def test_ring_roundtrip_via_memoryview_paths(self):
        """The satellite rewrite of the ring copy paths (zero-copy
        send cast + persistent-view recv): byte-exact round trip,
        including embedded NULs and large frames."""
        from aclswarm_tpu.interop import transport as T

        name = "asw-mv-" + uuid.uuid4().hex[:6]
        with T.Channel(name, create=True, capacity=1 << 16) as ch:
            for frame in (b"", b"\x00" * 7, bytes(range(256)) * 100):
                if not frame:
                    continue        # empty frames are not a ring case
                assert ch.send_bytes(frame)
                assert ch.recv_bytes() == frame


class TestTcpWireOwnership:
    def test_foreign_client_cannot_steal_a_result(self):
        """Review regression: the service-level idempotent attach has
        no tenancy, so the WIRE door owns rid->client-id — a different
        client replaying a known id (live OR already retired) is
        refused, never handed the owner's result."""
        from aclswarm_tpu.serve.wire import WireClient

        svc = SwarmService(ServiceConfig(max_batch=2))
        srv, (host, port) = _tcp_stack(svc)
        owner = WireClient(tcp=(host, port), tenant="owner",
                           client_id="owner")
        r = owner.submit("assign", {"n": 6}, request_id="mine").result(120)
        assert r.ok                      # completed + retired
        thief = WireClient(tcp=(host, port), tenant="thief",
                           client_id="thief")
        rs = thief.submit("assign", {"n": 6},
                          request_id="mine").result(timeout=60)
        assert rs.status == FAILED and rs.error.code == "wire_error"
        assert "owned by another client" in rs.error.message
        assert svc.telemetry.counter("wire_rid_refused_total").value == 1
        # the owner can still replay its own id (idempotent attach)
        r2 = owner.submit("assign", {"n": 6},
                          request_id="mine").result(120)
        assert r2.ok
        owner.close()
        thief.close()
        srv.close()
        svc.close()


class TestSocketTransportBounds:
    def test_completed_frames_reset_the_stall_clock(self):
        """Review regression: stalled_recv_s means 'oldest INCOMPLETE
        frame', not 'oldest busy stretch' — a fast client whose buffer
        always ends mid-frame must never age into the loris bound."""
        from aclswarm_tpu.interop import transport as T

        with T.SocketListener() as lst:
            host, port = lst.address
            cli = T.connect_when_ready(host, port, grace_s=5)
            srv = None
            deadline = time.monotonic() + 5
            while srv is None and time.monotonic() < deadline:
                srv = lst.accept()
                time.sleep(0.005)
            frame = b"z" * 64
            framed = (len(frame)).to_bytes(4, "little") + frame
            # keep the rx buffer ALWAYS mid-frame: full frame + half
            # the next, completing the half on the following beat
            cli._sock.sendall(framed + framed[:30])
            t_end = time.monotonic() + 0.5
            while time.monotonic() < t_end:
                got = srv.recv_bytes()
                if got is not None:
                    cli._sock.sendall(framed[30:] + framed[:30])
                # the stall clock must track only the CURRENT partial
                assert srv.stalled_recv_s < 0.4
                time.sleep(0.01)
            cli.close()
            srv.close()

    def test_inbound_buffer_bounded_under_frame_flood(self):
        """Review regression: recv_bytes reads from the kernel only
        until a frame is ready — a pre-sent flood of small frames
        cannot balloon the server-side buffer (TCP flow control takes
        over once we stop reading)."""
        from aclswarm_tpu.interop import transport as T

        with T.SocketListener() as lst:
            host, port = lst.address
            cli = T.connect_when_ready(host, port, grace_s=5)
            srv = None
            deadline = time.monotonic() + 5
            while srv is None and time.monotonic() < deadline:
                srv = lst.accept()
                time.sleep(0.005)
            frame = b"f" * 100
            framed = (len(frame)).to_bytes(4, "little") + frame
            blob = framed * 3000        # ~300 KB of tiny frames
            cli._sock.setblocking(True)
            sent = 0
            cli._sock.settimeout(2.0)
            try:
                while sent < len(blob):
                    sent += cli._sock.send(blob[sent:])
            except socket.timeout:
                pass                    # flow control engaged: good
            got = 0
            deadline = time.monotonic() + 10
            while got < 100 and time.monotonic() < deadline:
                if srv.recv_bytes() is not None:
                    got += 1
                # the inbound buffer stays ~one read chunk, never the
                # whole flood
                assert len(srv._rx) <= (1 << 16) + len(framed)
            assert got == 100
            cli.close()
            srv.close()


class TestClientHandleLocking:
    """Regression: `WireClient._handle` used to read `_tickets`
    without the client lock, racing `submit`'s insert from the caller
    thread (a ticket registered between the reader thread's lookup and
    the dict resize could be missed or corrupt the dict)."""

    def test_ticket_lookup_holds_client_lock(self):
        import threading

        from aclswarm_tpu.serve import wire
        from aclswarm_tpu.utils import get_logger
        from aclswarm_tpu.utils.locks import OrderedLock

        # a bare client: exactly the attributes _handle touches, no
        # transport — the lock discipline is what's under test
        client = wire.WireClient.__new__(wire.WireClient)
        client.log = get_logger("test.wire.client")
        client.server_info = {}
        client._connected = threading.Event()
        client._lock = OrderedLock("serve.wire")
        depths = []

        class _Guarded(dict):
            def get(_self, key, default=None):
                depths.append(client._lock._depth)
                return dict.get(_self, key, default)

        client._tickets = _Guarded()
        client._handle({"request_id": "ghost", "seq": 0,
                        "payload": {}}, wire.K_EVENT)
        assert depths == [1], \
            "ticket lookup must run under the client lock"
