"""Gain-design tests: golden parity + algebraic invariants + closed loop.

Mirrors the reference's own ADMM test suite (`aclswarm/test/test_admm.cpp`):
exact golden matrices for n=4 (tol 1e-8), zero-block and structure checks for
n=9, trace invariants for n=20 — plus the eigenstructure validation the
Python gain designer applies (`aclswarm/src/aclswarm/control.py:221-261`) and
an end-to-end check that self-designed gains fly the closed-loop sim.
"""
import jax.numpy as jnp
import numpy as np
import pytest

from aclswarm_tpu import gains as gainslib
from aclswarm_tpu.gains import admm, reference

GOLD_SQUARE_PTS = np.array([[0.0, 0.0, 2.5], [2.0, 0.0, 3.5],
                            [2.0, 2.0, 4.5], [0.0, 2.0, 1.5]])

# `test_admm.cpp:26-37`: MATLAB golden gains, 4-agent square, complete graph
GOLD_FC = np.array([
    [-0.50, 0, 0, 0.25, 0.25, 0, 0, 0, 0, 0.25, -0.25, 0],
    [0, -0.50, 0, -0.25, 0.25, 0, 0, 0, 0, 0.25, 0.25, 0],
    [0, 0, -0.70, 0, 0, 0.20, 0, 0, 0.10, 0, 0, 0.40],
    [0.25, -0.25, 0, -0.50, 0, 0, 0.25, 0.25, 0, 0, 0, 0],
    [0.25, 0.25, 0, 0, -0.50, 0, -0.25, 0.25, 0, 0, 0, 0],
    [0, 0, 0.20, 0, 0, -0.70, 0, 0, 0.40, 0, 0, 0.10],
    [0, 0, 0, 0.25, -0.25, 0, -0.50, 0, 0, 0.25, 0.25, 0],
    [0, 0, 0, 0.25, 0.25, 0, 0, -0.50, 0, -0.25, 0.25, 0],
    [0, 0, 0.10, 0, 0, 0.40, 0, 0, -0.30, 0, 0, -0.20],
    [0.25, 0.25, 0, 0, 0, 0, 0.25, -0.25, 0, -0.50, 0, 0],
    [-0.25, 0.25, 0, 0, 0, 0, 0.25, 0.25, 0, 0, -0.50, 0],
    [0, 0, 0.40, 0, 0, 0.10, 0, 0, -0.20, 0, 0, -0.30]])

# `test_admm.cpp:64-75`: golden gains, same square, edges (0,2),(1,3) removed
GOLD_NC = np.array([
    [-0.500, 0, 0, 0.250, 0.250, 0, 0, 0, 0, 0.250, -0.250, 0],
    [0, -0.500, 0, -0.250, 0.250, 0, 0, 0, 0, 0.250, 0.250, 0],
    [0, 0, -0.750, 0, 0, 0.375, 0, 0, 0, 0, 0, 0.375],
    [0.250, -0.250, 0, -0.500, 0, 0, 0.250, 0.250, 0, 0, 0, 0],
    [0.250, 0.250, 0, 0, -0.500, 0, -0.250, 0.250, 0, 0, 0, 0],
    [0, 0, 0.375, 0, 0, -0.750, 0, 0, 0.375, 0, 0, 0],
    [0, 0, 0, 0.250, -0.250, 0, -0.500, 0, 0, 0.250, 0.250, 0],
    [0, 0, 0, 0.250, 0.250, 0, 0, -0.500, 0, -0.250, 0.250, 0],
    [0, 0, 0, 0, 0, 0.375, 0, 0, -0.250, 0, 0, -0.125],
    [0.250, 0.250, 0, 0, 0, 0, 0.250, -0.250, 0, -0.500, 0, 0],
    [-0.250, 0.250, 0, 0, 0, 0, 0.250, 0.250, 0, 0, -0.500, 0],
    [0, 0, 0.375, 0, 0, 0, 0, 0, -0.125, 0, 0, -0.250]])


def fc_adj(n):
    return np.ones((n, n)) - np.eye(n)


def nine_agent_case():
    """`test_admm.cpp:84-152`: 9 agents, 5 removed edges, fixed points."""
    adj = fc_adj(9)
    for i, j in [(0, 6), (2, 4), (5, 7), (5, 8), (6, 7)]:
        adj[i, j] = adj[j, i] = 0
    p = np.array([
        [-1.7484733199059646, 1.7306756147165174, 0.2977622220453062],
        [6.8174866001631180, -6.2778267151168700, 1.7416024649609380],
        [-3.8137004331127518, -2.3232057308608365, 0.4655014204423282],
        [2.7536551200474015, -5.5700708736518450, 1.7252000594155040],
        [-3.5935365621834463, 4.8028457222331170, 1.2981050175550286],
        [-2.5820075847777666, 7.4136205487374910, 1.5131454738258028],
        [0.8900655441583734, 3.2902893860285527, 1.5581930129432586],
        [0.4370445360276376, -5.7714142992744755, 0.2531727259898202],
        [-6.1065377928157310, -5.7852241311701940, 1.7663507973073431]])
    return p, adj


class TestOracleGoldenParity:
    """NumPy mirror vs the committed MATLAB goldens (tol 1e-8)."""

    def test_four_agent_fc(self):
        A = reference.solve_gains(GOLD_SQUARE_PTS, fc_adj(4))
        assert np.linalg.norm(A - GOLD_FC) < 1e-8

    def test_four_agent_noncomplete(self):
        adj = fc_adj(4)
        adj[0, 2] = adj[2, 0] = 0
        adj[1, 3] = adj[3, 1] = 0
        A = reference.solve_gains(GOLD_SQUARE_PTS, adj)
        assert np.linalg.norm(A - GOLD_NC) < 1e-8


class TestDeviceSolverGoldenParity:
    """Projection-form device solver vs the same goldens and the oracle."""

    def test_four_agent_fc(self):
        A = np.asarray(gainslib.solve_gains(GOLD_SQUARE_PTS, fc_adj(4)))
        assert np.linalg.norm(A - GOLD_FC) < 1e-8

    def test_four_agent_noncomplete(self):
        adj = fc_adj(4)
        adj[0, 2] = adj[2, 0] = 0
        adj[1, 3] = adj[3, 1] = 0
        A = np.asarray(gainslib.solve_gains(GOLD_SQUARE_PTS, adj))
        assert np.linalg.norm(A - GOLD_NC) < 1e-8

    def test_matches_oracle_nine_agents(self):
        p, adj = nine_agent_case()
        A_dev = np.asarray(gainslib.solve_gains(p, adj))
        A_ref = reference.solve_gains(p, adj)
        np.testing.assert_allclose(A_dev, A_ref, atol=1e-9)

    @pytest.mark.slow
    def test_matches_oracle_random_sparse(self):
        rng = np.random.default_rng(7)
        n = 12
        adj = fc_adj(n)
        # knock out a handful of edges, keep graph dense enough for rigidity
        for _ in range(6):
            i, j = rng.integers(0, n, 2)
            if i != j:
                adj[i, j] = adj[j, i] = 0
        p = rng.normal(size=(n, 3)) * 4
        A_dev = np.asarray(gainslib.solve_gains(p, adj))
        A_ref = reference.solve_gains(p, adj)
        np.testing.assert_allclose(A_dev, A_ref, atol=1e-9)


class TestInvariants:
    """`test_admm.cpp:84-227` structural/trace checks on the device solver."""

    def test_zero_blocks(self):
        p, adj = nine_agent_case()
        A = np.asarray(gainslib.solve_gains(p, adj))
        for i in range(9):
            for j in range(9):
                if i != j and adj[i, j] == 0:
                    blk = A[3 * i:3 * i + 3, 3 * j:3 * j + 3]
                    np.testing.assert_allclose(blk, 0.0, atol=1e-8)

    def test_block_structure(self):
        p, adj = nine_agent_case()
        A = np.asarray(gainslib.solve_gains(p, adj))
        for i in range(9):
            for j in range(9):
                blk = A[3 * i:3 * i + 3, 3 * j:3 * j + 3]
                # [a b 0; -b a 0; 0 0 c]
                assert abs(blk[0, 0] - blk[1, 1]) < 1e-8
                assert abs(blk[1, 0] + blk[0, 1]) < 1e-8
                for r, c in [(0, 2), (2, 0), (1, 2), (2, 1)]:
                    assert abs(blk[r, c]) < 1e-8

    @pytest.mark.parametrize("sparse", [False, True])
    def test_fixed_trace_n20(self, sparse):
        rng = np.random.default_rng(20 + sparse)
        n = 20
        adj = fc_adj(n)
        if sparse:
            adj[0, 5] = adj[5, 0] = 0
            adj[3, 15] = adj[15, 3] = 0
        p = rng.uniform(-5, 5, size=(n, 3))
        A = np.asarray(gainslib.solve_gains(p, adj))
        assert abs(np.trace(A) - (-3 * (n - 2))) < 1e-8

    def test_eigenstructure(self):
        # non-flat formation: nullity 6, no positive eigs, rest negative
        p, adj = nine_agent_case()
        A = np.asarray(gainslib.solve_gains(p, adj))
        v = gainslib.validate_gains(A, p)
        assert v["no_positive"], v["eigenvalues"]
        assert v["kernel_ok"], v["eigenvalues"]
        assert v["strictly_negative_rest"], v["eigenvalues"]

    def test_planar_formation_nullity5(self):
        rng = np.random.default_rng(3)
        n = 6
        p = np.column_stack([rng.normal(size=(n, 2)) * 3, np.full(n, 1.5)])
        A = np.asarray(gainslib.solve_gains(p, fc_adj(n)))
        v = gainslib.validate_gains(A, p)
        assert v["nullity"] == 5
        assert v["no_positive"] and v["kernel_ok"]

    def test_desired_formation_in_kernel(self):
        # A @ vec-stacked formation coordinates must vanish: the formation
        # (and its rigid motions) are equilibria of the linear term
        p, adj = nine_agent_case()
        A = np.asarray(gainslib.solve_gains(p, adj))
        qvec = p.reshape(-1)  # [x0 y0 z0 x1 ...] matches 3x3 block layout
        np.testing.assert_allclose(A @ qvec, 0.0, atol=1e-7)


class TestClosedLoopWithDesignedGains:
    @pytest.mark.slow
    def test_swarm6_pyramid_flies(self):
        """End of the gain-design story: our own gains fly the demo."""
        import jax
        from aclswarm_tpu import harness, sim
        from aclswarm_tpu.core.types import ControlGains
        from aclswarm_tpu.harness import supervisor
        from tests.test_sim import room_params, spread_start, shape_error

        spec = harness.load_formation("Pentagonal Pyramid",
                                      group="swarm6_3d")
        f = spec.to_device(gains=np.asarray(
            gainslib.solve_gains(spec.points, spec.adjmat)))
        st = sim.init_state(spread_start(6, 11))
        cfg = sim.SimConfig(assignment="auction")
        # 90 s: the library's sparse per-formation graph (8 edges, spectral
        # gap 0.27 vs the complete graph's) settles about 2x slower than
        # the fc demo did — shape error 0.37 at 45 s, 0.22 at 90 s
        final, m = sim.rollout(st, f, ControlGains(), room_params(), cfg,
                               9000)
        res = supervisor.evaluate(
            np.asarray(m.distcmd_norm), np.asarray(m.ca_active),
            np.asarray(m.q), np.asarray(m.reassigned),
            np.asarray(m.assign_valid), cfg.control_dt)
        assert res.converged, res
        err = shape_error(final.swarm.q, spec.points, final.v2f)
        assert err < 0.35, err


class TestSparseGraphsAtScale:
    """The matrix-free constraint treatment (`gains/admm.py
    _constraint_system`): sparse non-complete graphs at simform100 scale,
    one compiled program per padded bucket."""

    def test_simform100_graph_invariants(self):
        """Random rigidity-preserving sparse graph at n=100 (the simform100
        shape): all reference invariants hold (`test_admm.cpp:84-227`)."""
        from aclswarm_tpu.harness import formgen

        n = 100
        rng = np.random.default_rng(3)
        adj = formgen.random_adjmat(np.random.default_rng(17), n, fc=False)
        assert adj.sum() < n * (n - 1)  # actually non-complete
        pts = rng.normal(size=(n, 3)) * 10
        A = np.asarray(gainslib.solve_gains(pts, adj))
        blocks = A.reshape(n, 3, n, 3)
        # zero blocks exactly at non-edges
        for i in range(n):
            for j in range(n):
                if i != j and adj[i, j] == 0:
                    assert np.all(blocks[i, :, j, :] == 0.0), (i, j)
        # trace = -d (n - 2)
        np.testing.assert_allclose(np.trace(A), -3 * (n - 2), atol=1e-6)
        v = gainslib.validate_gains(A, pts)
        assert v["no_positive"] and v["kernel_ok"] \
            and v["strictly_negative_rest"]

    def test_bucketed_graphs_share_one_compile(self):
        """Different adjacency patterns in the same max_nonedges bucket hit
        one compiled executable (no per-graph recompile — Monte-Carlo
        random-graph trials stay compile-free)."""
        from aclswarm_tpu.harness import formgen

        n = 16
        rng = np.random.default_rng(0)
        pts = rng.normal(size=(n, 3)) * 5
        before = admm._solve_jit._cache_size()
        results = []
        for s in range(4):
            adj = formgen.random_adjmat(np.random.default_rng(s), n,
                                        fc=False)
            results.append(np.asarray(
                gainslib.solve_gains(pts, adj, max_nonedges=n - 4)))
        assert admm._solve_jit._cache_size() - before == 1
        # and the padding is inert: bucketed == exact-size solve
        adj = formgen.random_adjmat(np.random.default_rng(2), n, fc=False)
        exact = np.asarray(gainslib.solve_gains(pts, adj))
        bucketed = np.asarray(gainslib.solve_gains(pts, adj,
                                                max_nonedges=n - 4))
        np.testing.assert_allclose(bucketed, exact, atol=1e-9)

    def test_newton_psd_matches_eigh_at_f64(self):
        """The Newton-Schulz PSD step (the f32 device fast path) agrees
        with the exact eigendecomposition to ~1e-6 at f64 — isolating the
        method error from precision error."""
        from aclswarm_tpu.harness import formgen

        n = 24
        rng = np.random.default_rng(5)
        pts = rng.normal(size=(n, 3)) * 8
        adj = formgen.random_adjmat(np.random.default_rng(5), n, fc=False)
        Ae = np.asarray(gainslib.solve_gains(
            pts, adj, reference.AdmmParams(psd_method="eigh")))
        An = np.asarray(gainslib.solve_gains(
            pts, adj, reference.AdmmParams(psd_method="newton")))
        assert np.abs(An - Ae).max() < 1e-5
        v = gainslib.validate_gains(An, pts)
        assert v["no_positive"] and v["kernel_ok"] \
            and v["strictly_negative_rest"]


class TestWarmStart:
    """The dispatch-loop carry (ROADMAP item 1): `solve_gains(carry=...)`
    re-seeds the next formation's ADMM from the previous fixed point.
    The contract: seeding with the COLD carry (`init_carry`) is
    bit-identical to the carry-free path (warm start off is free), a
    carried fixed point re-converges in a fraction of the cold
    iterations, and both land on the same answer to the solver's own
    stopping tolerance."""

    def _pair(self, n=12, seeds=(11, 12)):
        rng_a = np.random.default_rng(seeds[0])
        rng_b = np.random.default_rng(seeds[1])
        pts_a = rng_a.normal(size=(n, 3)) * 5
        pts_b = pts_a + rng_b.normal(size=(n, 3)) * 0.5
        return pts_a, pts_b, fc_adj(n)

    def test_cold_carry_is_bitwise_cold(self):
        pts_a, _, adj = self._pair()
        cold = np.asarray(gainslib.solve_gains(pts_a, adj))
        carry0 = gainslib.init_carry(len(pts_a),
                                     gainslib.planar_of(pts_a))
        warm, new_carry = gainslib.solve_gains(pts_a, adj, carry=carry0)
        assert np.array_equal(np.asarray(warm), cold)
        assert isinstance(new_carry, gainslib.AdmmCarry)

    def test_warm_reconverges_faster_same_fixed_point(self):
        pts_a, pts_b, adj = self._pair()
        cold_b, st_cold = gainslib.solve_gains(pts_b, adj, telemetry=True)
        carry0 = gainslib.init_carry(len(pts_a),
                                     gainslib.planar_of(pts_a))
        _, carry_a = gainslib.solve_gains(pts_a, adj, carry=carry0)
        warm_b, _, st_warm = gainslib.solve_gains(pts_b, adj,
                                                  carry=carry_a,
                                                  telemetry=True)
        assert int(st_warm.iters) < int(st_cold.iters)
        np.testing.assert_allclose(np.asarray(warm_b),
                                   np.asarray(cold_b), atol=5e-3)

    def test_batch_bit_parity_with_serial(self):
        n, B = 10, 3
        rng = np.random.default_rng(4)
        pts = rng.normal(size=(B, n, 3)) * 4
        adjs = np.stack([fc_adj(n)] * B)
        adjs[1, 0, 3] = adjs[1, 3, 0] = 0     # distinct graphs, one bucket
        batched = np.asarray(gainslib.solve_gains_batch(
            pts, adjs, max_nonedges=2))
        for b in range(B):
            serial = np.asarray(gainslib.solve_gains(
                pts[b], adjs[b], max_nonedges=2))
            assert np.array_equal(batched[b], serial), b

    def test_f32_gate_validates_or_falls_back(self):
        pts, adj = nine_agent_case()
        g, report = gainslib.solve_gains_f32(pts, adj)
        assert isinstance(report["f32_ok"], bool)
        v = gainslib.validate_gains(np.asarray(g), pts, tol=1e-4)
        assert v["no_positive"] and v["kernel_ok"] \
            and v["strictly_negative_rest"]
