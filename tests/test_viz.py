"""Smoke tests for the offline visualization (`viz_commands.py` analogue)."""
import numpy as np
import pytest

matplotlib = pytest.importorskip("matplotlib")

from aclswarm_tpu import sim
from aclswarm_tpu.core.types import ControlGains, SafetyParams, make_formation
from aclswarm_tpu.harness import viz


@pytest.fixture(scope="module")
def short_rollout():
    n = 4
    pts = np.array([[0., 0, 1], [2, 0, 1], [2, 2, 1], [0, 2, 1]])
    adj = np.ones((n, n)) - np.eye(n)
    from aclswarm_tpu import gains as gainslib
    G = np.asarray(gainslib.solve_gains(pts, adj))
    formation = make_formation(pts, adj, G)
    rng = np.random.default_rng(0)
    q0 = rng.normal(size=(n, 3)); q0[:, 2] = 1.0
    state = sim.init_state(q0)
    cfg = sim.SimConfig(dynamics="firstorder")
    _, metrics = sim.rollout(state, formation, ControlGains(),
                             SafetyParams(), cfg, 600)
    return metrics, formation


def test_plot_rollout(short_rollout, tmp_path):
    metrics, formation = short_rollout
    out = viz.plot_rollout(metrics, formation, str(tmp_path / "r.png"))
    assert (tmp_path / "r.png").stat().st_size > 10_000


def test_plot_timeseries(short_rollout, tmp_path):
    metrics, formation = short_rollout
    viz.plot_timeseries(metrics, str(tmp_path / "t.png"))
    assert (tmp_path / "t.png").stat().st_size > 10_000


def test_aligned_formation_properties(short_rollout):
    metrics, formation = short_rollout
    q = np.asarray(metrics.q[-1])
    v2f = np.asarray(metrics.v2f[-1])
    pts = np.asarray(formation.points)
    goal = viz.aligned_formation(q, pts, v2f)
    # rigid alignment: the displayed goal preserves the formation's shape
    # (pairwise distances) in vehicle order
    want = np.linalg.norm(pts[:, None] - pts[None, :], axis=-1)
    have = np.linalg.norm(goal[:, None] - goal[None, :], axis=-1)
    np.testing.assert_allclose(have, want[np.ix_(v2f, v2f)], atol=1e-8)
    # d=2 alignment matches the swarm's xy centroid
    np.testing.assert_allclose(goal[:, :2].mean(0), q[:, :2].mean(0),
                               atol=1e-8)


class TestLivePlot:
    def _feed(self, lp, n=4, ticks=120):
        import numpy as np

        from aclswarm_tpu.interop import messages as m
        rng = np.random.default_rng(0)
        q = rng.normal(size=(n, 3))
        for k in range(ticks):
            t = k * 0.01
            lp.ingest(m.DistCmd(header=m.Header(seq=k, stamp=t),
                                vel=rng.normal(size=(n, 3))))
            lp.ingest(m.SafetyStatusArray(
                header=m.Header(seq=k, stamp=t),
                active=(rng.random(n) < 0.2).astype(np.uint8)))
            lp.ingest(m.VehicleEstimates(
                header=m.Header(seq=k, stamp=t), positions=q + 0.01 * k,
                stamps=np.full(n, t)))

    def test_ingest_and_render(self, tmp_path):
        """The rqt_multiplot-equivalent consumer: wire messages in, a
        multiplot frame out (`cfg/multiplot_xyvel.xml` analogue)."""
        from aclswarm_tpu.harness.liveplot import LivePlot
        lp = LivePlot(n=4, window_s=0.5)
        self._feed(lp)
        out = tmp_path / "live.png"
        lp.render(str(out))
        assert out.exists() and out.stat().st_size > 5000
        # rolling window: only the trailing 0.5 s stays buffered
        ts, vel = lp._window(lp._cmd)
        assert ts[0] >= ts[-1] - 0.5 and vel.shape[1:] == (4, 3)

    def test_observe_over_wire(self, tmp_path):
        """End-to-end over injected channels (the shm deployment shape is
        the same recv loop)."""
        import numpy as np

        from aclswarm_tpu.harness import liveplot
        from aclswarm_tpu.interop import messages as m

        class FakeChannel:
            def __init__(self, msgs):
                self.msgs = list(msgs)

            def recv(self):
                return self.msgs.pop(0) if self.msgs else None

        n = 3
        rng = np.random.default_rng(1)
        cmds = [m.DistCmd(header=m.Header(seq=k, stamp=k * 0.01),
                          vel=rng.normal(size=(n, 3))) for k in range(50)]
        safety = [m.SafetyStatusArray(header=m.Header(seq=k, stamp=k * 0.01),
                                      active=np.zeros(n, np.uint8))
                  for k in range(50)]
        out = tmp_path / "obs.png"
        frames = liveplot.observe(
            "/unused", n, str(out), interval_s=0.1, duration_s=0.4,
            channels={"distcmd": FakeChannel(cmds),
                      "safety": FakeChannel(safety),
                      "estimates": FakeChannel([])})
        assert frames >= 2 and out.exists()
