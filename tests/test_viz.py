"""Smoke tests for the offline visualization (`viz_commands.py` analogue)."""
import numpy as np
import pytest

matplotlib = pytest.importorskip("matplotlib")

from aclswarm_tpu import sim
from aclswarm_tpu.core.types import ControlGains, SafetyParams, make_formation
from aclswarm_tpu.harness import viz


@pytest.fixture(scope="module")
def short_rollout():
    n = 4
    pts = np.array([[0., 0, 1], [2, 0, 1], [2, 2, 1], [0, 2, 1]])
    adj = np.ones((n, n)) - np.eye(n)
    from aclswarm_tpu import gains as gainslib
    G = np.asarray(gainslib.solve_gains(pts, adj))
    formation = make_formation(pts, adj, G)
    rng = np.random.default_rng(0)
    q0 = rng.normal(size=(n, 3)); q0[:, 2] = 1.0
    state = sim.init_state(q0)
    cfg = sim.SimConfig(dynamics="firstorder")
    _, metrics = sim.rollout(state, formation, ControlGains(),
                             SafetyParams(), cfg, 600)
    return metrics, formation


def test_plot_rollout(short_rollout, tmp_path):
    metrics, formation = short_rollout
    out = viz.plot_rollout(metrics, formation, str(tmp_path / "r.png"))
    assert (tmp_path / "r.png").stat().st_size > 10_000


def test_plot_timeseries(short_rollout, tmp_path):
    metrics, formation = short_rollout
    viz.plot_timeseries(metrics, str(tmp_path / "t.png"))
    assert (tmp_path / "t.png").stat().st_size > 10_000


def test_aligned_formation_properties(short_rollout):
    metrics, formation = short_rollout
    q = np.asarray(metrics.q[-1])
    v2f = np.asarray(metrics.v2f[-1])
    pts = np.asarray(formation.points)
    goal = viz.aligned_formation(q, pts, v2f)
    # rigid alignment: the displayed goal preserves the formation's shape
    # (pairwise distances) in vehicle order
    want = np.linalg.norm(pts[:, None] - pts[None, :], axis=-1)
    have = np.linalg.norm(goal[:, None] - goal[None, :], axis=-1)
    np.testing.assert_allclose(have, want[np.ix_(v2f, v2f)], atol=1e-8)
    # d=2 alignment matches the swarm's xy centroid
    np.testing.assert_allclose(goal[:, :2].mean(0), q[:, :2].mean(0),
                               atol=1e-8)
