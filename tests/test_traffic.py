"""swarmstress traffic fleet (`aclswarm_tpu.serve.traffic`;
docs/SERVICE.md §off-host serving).

The replayability contract (a schedule is a pure function of its
config), the heavy-tailed/mixed shape of what it generates, and one
small end-to-end fleet run over the TCP front end whose client ledger
must reconcile to the last arrival — the in-tier miniature of the
committed `benchmarks/results/serve_overload.json` proof.
"""
from __future__ import annotations

import numpy as np
import pytest

from aclswarm_tpu.serve import ServiceConfig, SwarmService
from aclswarm_tpu.serve.traffic import (Arrival, TrafficConfig,
                                        TrafficFleet, build_schedule)

pytestmark = [pytest.mark.serve]


class TestSchedule:
    def test_replayable_and_seed_sensitive(self):
        cfg = TrafficConfig(seed=11, duration_s=4.0, offered_hz=60.0)
        a, b = build_schedule(cfg), build_schedule(cfg)
        assert a == b and len(a) > 50
        c = build_schedule(TrafficConfig(seed=12, duration_s=4.0,
                                         offered_hz=60.0))
        assert c != a

    def test_mixes_deadlines_and_heavy_tail(self):
        cfg = TrafficConfig(seed=3, duration_s=30.0, offered_hz=40.0,
                            deadline_frac=0.3)
        sched = build_schedule(cfg)
        assert all(isinstance(s, Arrival) for s in sched)
        # every configured tenant and kind appears
        assert {s.tenant for s in sched} == set(cfg.tenants)
        kinds = {s.kind for s in sched}
        assert kinds == {"rollout", "assign", "scenario"}
        # scenario draws come from the registry's serve-compatible
        # (truth-localization) families only
        from aclswarm_tpu.scenarios.registry import FAMILIES
        fams = {s.params["family"] for s in sched
                if s.kind == "scenario"}
        assert fams and all(
            FAMILIES[f].localization == "truth" for f in fams)
        # deadlines: roughly the configured fraction, inside the range
        dl = [s.deadline_s for s in sched if s.deadline_s is not None]
        assert 0.1 < len(dl) / len(sched) < 0.6
        lo, hi = cfg.deadline_range_s
        assert all(lo <= d <= hi for d in dl)
        # heavy tail: the mean gap honors the offered rate while the
        # max gap dwarfs the median (a metronome would fail this)
        t = np.asarray([s.t for s in sched])
        gaps = np.diff(t)
        assert abs(len(sched) / cfg.duration_s
                   - cfg.offered_hz) / cfg.offered_hz < 0.35
        assert gaps.max() > 4 * np.median(gaps)

    def test_request_ids_unique_and_seeded(self):
        cfg = TrafficConfig(seed=5, duration_s=3.0, offered_hz=50.0)
        sched = build_schedule(cfg)
        rids = [s.request_id for s in sched]
        assert len(set(rids)) == len(rids)
        assert all(r.startswith("s5-") for r in rids)


class TestFleetEndToEnd:
    @pytest.mark.slow
    def test_small_fleet_ledger_reconciles(self):
        """A polite mini-fleet over TCP: every arrival reaches a
        terminal outcome (nothing unresolved), accepted == completed,
        and the report's ledger adds up to the offered count — the
        tier-1 miniature of the overload artifact's reconcile."""
        from aclswarm_tpu.serve.wire import WireServer

        svc = SwarmService(ServiceConfig(max_batch=4, quantum_chunks=4,
                                         max_queue_per_tenant=16,
                                         max_queue_total=48,
                                         idle_poll_s=0.01))
        srv = WireServer(svc, base=None, tcp=("127.0.0.1", 0),
                         client_lease_s=15.0)
        host, port = srv.tcp_address
        cfg = TrafficConfig(seed=9, duration_s=1.5, offered_hz=8.0,
                            slowloris_clients=0, corrupt_clients=0,
                            reconnect_storms=0, deadline_frac=0.0,
                            drain_timeout_s=240.0)
        rep = TrafficFleet(cfg, host, port).run()
        srv.close()
        svc.close()
        assert rep["unresolved"] == 0 and rep["wire_lost"] == 0
        total = (rep["completed"] + rep["timed_out"] + rep["cancelled"]
                 + rep["rejected_final"] + rep["failed_other"])
        assert total == rep["offered"] == rep["submitted"]
        assert rep["completed"] >= 1
        assert svc.stats["completed"] == rep["completed"]

    @pytest.mark.slow
    def test_adversaries_do_not_break_honest_traffic(self):
        """Slow-loris + corrupt-frame clients riding along: the honest
        arrivals still all terminate, the corrupt frames are all
        CRC-rejected (none applied), and the loris is dropped at the
        read deadline."""
        from aclswarm_tpu.serve.wire import WireServer

        svc = SwarmService(ServiceConfig(max_batch=4, quantum_chunks=4,
                                         max_queue_per_tenant=16,
                                         max_queue_total=48,
                                         idle_poll_s=0.01))
        srv = WireServer(svc, base=None, tcp=("127.0.0.1", 0),
                         client_lease_s=15.0, read_deadline_s=0.5)
        host, port = srv.tcp_address
        cfg = TrafficConfig(seed=10, duration_s=1.5, offered_hz=6.0,
                            slowloris_clients=1, corrupt_clients=1,
                            corrupt_hz=10.0, reconnect_storms=0,
                            deadline_frac=0.0, drain_timeout_s=240.0)
        rep = TrafficFleet(cfg, host, port).run()
        srv.close()
        svc.close(drain=False)
        assert rep["unresolved"] == 0
        assert rep["completed"] + rep["rejected_final"] \
            + rep["cancelled"] + rep["timed_out"] == rep["offered"]
        # every corrupt frame the server read was rejected, none
        # accepted (the fleet tenant names would show up in stats)
        crc = svc.telemetry.counter("wire_crc_rejected_total").value
        assert crc >= 1
        assert svc.telemetry.counter(
            "wire_slowloris_dropped_total").value >= 1
        # the schedule's arrivals are the only accepted work
        assert svc.stats["accepted"] \
            == rep["completed"] + rep["timed_out"] + rep["cancelled"]
