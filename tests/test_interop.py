"""Interop boundary tests: wire codec, native parity, shm transport,
message-driven planner.

The 'done' criterion from the round-1 review: a test drives the planner
purely through the message-shaped API (no framework internals), so the
final ROS plugin is a transport swap.
"""
import os
import subprocess
import sys
import uuid

import numpy as np
import pytest

from aclswarm_tpu.interop import codec, messages as m
from aclswarm_tpu.interop import native as nat

RNG = np.random.default_rng(0)


def _load_factor() -> float:
    """Deadline multiplier for the cross-process tests: under parallel
    suite load (two pytest halves + a bridge child per test) wall-clock
    deadlines tuned for an idle box flake (round-2 weak #5). Scales with
    the 1-min load average, capped so a pathological box still fails."""
    import os
    try:
        return min(4.0, max(1.0, os.getloadavg()[0] / os.cpu_count()))
    except OSError:
        return 1.0


def _formation_msg(n=6, gains=True, name="ring6"):
    g = None
    if gains:
        g = RNG.normal(size=(3 * n, 3 * n)).astype(np.float32)
    adj = (RNG.random((n, n)) > 0.4).astype(np.uint8)
    adj = np.triu(adj, 1)
    adj = adj + adj.T
    return m.Formation(header=m.Header(seq=7, stamp=12.5, frame_id="world"),
                       name=name, points=RNG.normal(size=(n, 3)),
                       adjmat=adj, gains=g)


def _cbaa_msg(n=6):
    return m.CBAA(header=m.Header(seq=3, stamp=0.25, frame_id="SQ01s"),
                  auction_id=42, iter=5,
                  price=RNG.random(n).astype(np.float32),
                  who=RNG.integers(-1, n, n).astype(np.int32))


def _est_msg(n=6):
    return m.VehicleEstimates(header=m.Header(seq=9, stamp=3.0),
                              positions=RNG.normal(size=(n, 3)),
                              stamps=RNG.random(n))


def _status_msg(active=True):
    return m.SafetyStatus(header=m.Header(seq=1, stamp=0.01,
                                          frame_id="SQ02s"),
                          collision_avoidance_active=active)


class TestCodec:
    @pytest.mark.parametrize("msg_fn", [
        lambda: _formation_msg(gains=True),
        lambda: _formation_msg(gains=False),
        lambda: _formation_msg(n=1, gains=False, name=""),
        _cbaa_msg, _est_msg,
        lambda: _status_msg(True), lambda: _status_msg(False)])
    def test_roundtrip(self, msg_fn):
        msg = msg_fn()
        out = codec.decode(codec.encode(msg))
        assert type(out) is type(msg)
        assert out.header.seq == msg.header.seq
        assert out.header.stamp == msg.header.stamp
        assert out.header.frame_id == msg.header.frame_id
        for f in msg.__dataclass_fields__:
            a, b = getattr(msg, f), getattr(out, f)
            if isinstance(a, np.ndarray):
                np.testing.assert_array_equal(a, b)
            elif f != "header":
                assert a == b, f

    def test_corruption_detected(self):
        buf = bytearray(codec.encode(_cbaa_msg()))
        buf[20] ^= 0xFF  # flip a payload byte
        with pytest.raises(ValueError, match="crc"):
            codec.decode(bytes(buf))

    def test_bad_magic(self):
        buf = bytearray(codec.encode(_status_msg()))
        buf[0] ^= 0xFF
        with pytest.raises(ValueError, match="magic"):
            codec.decode(bytes(buf))

    def test_truncation_detected(self):
        buf = codec.encode(_est_msg())
        with pytest.raises(ValueError):
            codec.decode(buf[:len(buf) - 3])

    def test_overlong_string_length_raises(self):
        """A CRC-consistent frame whose declared string length overruns
        the payload must raise (native Reader::str bounds-checks the same
        way) — silent truncation would misparse every later field."""
        import struct as _s
        import zlib as _z
        msg = _formation_msg(gains=False)
        msg.header.frame_id = ""
        buf = bytearray(codec.encode(msg))
        hdr = codec._HDR.size
        # name length lives right after the 14-byte header (seq+stamp+len0)
        name_off = hdr + 14
        _s.pack_into("<H", buf, name_off, 0xFFFF)
        payload = bytes(buf[hdr:])
        _s.pack_into("<I", buf, 12, _z.crc32(payload) & 0xFFFFFFFF)
        with pytest.raises(ValueError, match="string length"):
            codec.decode(bytes(buf))


needs_native = pytest.mark.skipif(not nat.build(),
                                  reason="native library not buildable")


@needs_native
class TestNativeParity:
    """The C++ codec must produce byte-identical frames to the Python
    reference implementation, and decode Python-encoded frames."""

    def test_crc32_matches_zlib(self):
        import ctypes as C
        import zlib
        lib = nat.load()
        for size in (0, 1, 7, 1024):
            data = bytes(RNG.integers(0, 256, size, dtype=np.uint8))
            arr = (C.c_uint8 * size).from_buffer_copy(data) if size \
                else (C.c_uint8 * 1)()
            assert lib.asw_crc32(arr, size) == (zlib.crc32(data)
                                                & 0xFFFFFFFF)

    def _np_ptr(self, a, ctype):
        import ctypes as C
        return a.ctypes.data_as(C.POINTER(ctype))

    def test_formation_bytes_identical(self):
        import ctypes as C
        lib = nat.load()
        for gains in (True, False):
            msg = _formation_msg(gains=gains)
            py = codec.encode(msg)
            out = (C.c_uint8 * (len(py) + 64))()
            gp = (self._np_ptr(msg.gains, C.c_float) if gains
                  else C.POINTER(C.c_float)())
            nbytes = lib.asw_encode_formation(
                msg.header.seq, msg.header.stamp,
                msg.header.frame_id.encode(), msg.name.encode(), msg.n,
                self._np_ptr(msg.points, C.c_double),
                self._np_ptr(msg.adjmat, C.c_uint8), gp, out, len(out))
            assert nbytes == len(py)
            assert bytes(out[:nbytes]) == py

    def test_cbaa_bytes_identical_and_decode(self):
        import ctypes as C
        lib = nat.load()
        msg = _cbaa_msg()
        py = codec.encode(msg)
        out = (C.c_uint8 * (len(py) + 64))()
        nb = lib.asw_encode_cbaa(
            msg.header.seq, msg.header.stamp, msg.header.frame_id.encode(),
            msg.auction_id, msg.iter, len(msg.price),
            self._np_ptr(msg.price, C.c_float),
            self._np_ptr(msg.who, C.c_int32), out, len(out))
        assert nb == len(py) and bytes(out[:nb]) == py
        # C++ decodes the Python-encoded frame
        buf = (C.c_uint8 * len(py)).from_buffer_copy(py)
        n = C.c_uint32()
        assert lib.asw_cbaa_n(buf, len(py), C.byref(n)) == 0
        assert n.value == len(msg.price)
        price = np.zeros(n.value, np.float32)
        who = np.zeros(n.value, np.int32)
        seq, stamp = C.c_uint32(), C.c_double()
        aid, it = C.c_uint32(), C.c_uint32()
        assert lib.asw_decode_cbaa(
            buf, len(py), C.byref(seq), C.byref(stamp), C.byref(aid),
            C.byref(it), self._np_ptr(price, C.c_float),
            self._np_ptr(who, C.c_int32)) == 0
        assert (seq.value, aid.value, it.value) == (3, 42, 5)
        np.testing.assert_array_equal(price, msg.price)
        np.testing.assert_array_equal(who, msg.who)

    def test_estimates_and_status_bytes_identical(self):
        import ctypes as C
        lib = nat.load()
        est = _est_msg()
        py = codec.encode(est)
        out = (C.c_uint8 * (len(py) + 64))()
        nb = lib.asw_encode_estimates(
            est.header.seq, est.header.stamp, est.header.frame_id.encode(),
            len(est.stamps), self._np_ptr(est.stamps, C.c_double),
            self._np_ptr(est.positions, C.c_double), out, len(out))
        assert nb == len(py) and bytes(out[:nb]) == py
        st = _status_msg(True)
        py = codec.encode(st)
        nb = lib.asw_encode_status(st.header.seq, st.header.stamp,
                                   st.header.frame_id.encode(), 1, out,
                                   len(out))
        assert nb == len(py) and bytes(out[:nb]) == py

    def test_cpp_rejects_corruption(self):
        import ctypes as C
        lib = nat.load()
        py = bytearray(codec.encode(_cbaa_msg()))
        py[25] ^= 0x01
        buf = (C.c_uint8 * len(py)).from_buffer_copy(bytes(py))
        assert lib.asw_parse_frame(buf, len(py), None, None) == -5  # crc


@needs_native
class TestShmRing:
    def _channel(self, **kw):
        from aclswarm_tpu.interop.transport import Channel
        return Channel(f"aswtest-{uuid.uuid4().hex[:12]}", create=True, **kw)

    def test_send_recv_messages(self):
        with self._channel() as ch:
            msgs = [_formation_msg(), _cbaa_msg(), _est_msg(),
                    _status_msg()]
            for msg in msgs:
                assert ch.send(msg)
            for msg in msgs:
                out = ch.recv()
                assert type(out) is type(msg)
            assert ch.recv() is None

    def test_wraparound_many_messages(self):
        """Thousands of sends through a small ring exercise the pad-marker
        wrap path; FIFO order and payload integrity must hold."""
        with self._channel(capacity=4096) as ch:
            sent = 0
            for i in range(5000):
                msg = m.CBAA(header=m.Header(seq=i), auction_id=i, iter=0,
                             price=np.full(7, i, np.float32),
                             who=np.arange(7, dtype=np.int32))
                if not ch.send(msg):  # full: drain one and retry
                    got = ch.recv()
                    assert got.header.seq == sent
                    sent += 1
                    assert ch.send(msg)
            while (got := ch.recv()) is not None:
                assert got.header.seq == sent
                assert got.auction_id == sent
                sent += 1
            assert sent == 5000

    def test_stale_shm_reclaimed_on_create(self):
        """A ring left behind by a crashed owner must not block restarts:
        create-over-stale unlinks and recreates instead of raising until
        /dev/shm is cleaned by hand."""
        from aclswarm_tpu.interop.transport import Channel
        name = f"aswtest-{uuid.uuid4().hex[:12]}"
        ch1 = Channel(name, create=True)
        ch1.send(_cbaa_msg())
        # simulate a crash: drop the mapping without shm_unlink
        ch1.close(unlink=False)
        with Channel(name, create=True) as ch2:
            # fresh ring: the stale message is gone, and traffic flows
            assert ch2.recv() is None
            assert ch2.send(_status_msg())
            assert isinstance(ch2.recv(), m.SafetyStatus)

    def test_live_ring_not_hijacked_by_second_creator(self):
        """Reclaim must only fire for crashed owners: while the first
        creator is alive (its flock held), a second create fails loudly
        instead of unlinking the live ring out from under it."""
        from aclswarm_tpu.interop.transport import Channel
        name = f"aswtest-{uuid.uuid4().hex[:12]}"
        with Channel(name, create=True) as ch1:
            with pytest.raises(OSError):
                Channel(name, create=True)
            assert ch1.send(_status_msg())   # ring untouched
            assert isinstance(ch1.recv(), m.SafetyStatus)

    def test_backpressure_not_silent_drop(self):
        with self._channel(capacity=256) as ch:
            msg = _cbaa_msg(20)
            writes = 0
            while ch.send(msg):
                writes += 1
            assert writes >= 1
            assert not ch.send(msg)   # full reports False
            assert ch.recv() is not None
            assert ch.send(msg)       # space reclaimed after a read

    def test_cross_process(self):
        """A child process opens the ring by name, receives a CBAA bid and
        echoes it back with iter+1 — the reference's bid exchange shape
        over the native transport."""
        import pathlib
        import time

        from aclswarm_tpu.interop.transport import Channel
        name = f"aswtest-{uuid.uuid4().hex[:12]}"
        repo = str(pathlib.Path(__file__).resolve().parents[1])
        child_src = f"""
import sys, time
sys.path.insert(0, {repo!r})
from aclswarm_tpu.interop.transport import Channel
req = Channel("{name}-req")
rep = Channel("{name}-rep")
deadline = time.time() + 20
while time.time() < deadline:
    msg = req.recv()
    if msg is not None:
        msg.iter += 1
        assert rep.send(msg)
        break
    time.sleep(0.005)
"""
        with Channel(name + "-req", create=True) as req, \
                Channel(name + "-rep", create=True) as rep:
            child = subprocess.Popen([sys.executable, "-c", child_src])
            try:
                bid = _cbaa_msg()
                assert req.send(bid)
                reply = None
                deadline = time.time() + 20
                while time.time() < deadline and reply is None:
                    reply = rep.recv()
                    time.sleep(0.005)
                assert reply is not None, "child never replied"
                assert reply.iter == bid.iter + 1
                np.testing.assert_array_equal(reply.price, bid.price)
            finally:
                child.wait(timeout=20)


class TestPlanner:
    """Drive the planner purely through the message-shaped API."""

    def _spec(self, n=6):
        ang = np.linspace(0, 2 * np.pi, n, endpoint=False)
        pts = np.stack([3 * np.cos(ang), 3 * np.sin(ang),
                        np.full(n, 1.5)], 1)
        adj = np.ones((n, n), np.uint8) - np.eye(n, dtype=np.uint8)
        return pts, adj

    def test_formation_then_ticks(self):
        from aclswarm_tpu.interop import TpuPlanner
        n = 6
        pts, adj = self._spec(n)
        planner = TpuPlanner(n, assign_every=50)

        # before any formation: zero command (commit-gap semantics)
        out = planner.tick(np.zeros((n, 3)))
        assert np.all(out.distcmd == 0) and out.assignment is None

        # dispatch a Formation with no gains -> on-device ADMM solve
        fmsg = m.Formation(header=m.Header(seq=1, stamp=0.0),
                           name="ring6", points=pts, adjmat=adj)
        planner.handle_formation(fmsg)

        rng = np.random.default_rng(5)
        q = rng.normal(size=(n, 3)) * 2.0
        q[:, 2] = 1.5
        est = m.VehicleEstimates(header=m.Header(seq=1, stamp=0.0),
                                 positions=q, stamps=np.zeros(n))
        out = planner.tick(est)
        # first tick auctions: a valid permutation assignment is published
        assert out.assignment is not None
        assert sorted(out.assignment.tolist()) == list(range(n))
        assert out.auction_valid
        assert np.linalg.norm(out.distcmd) > 0

        # closed loop through the message API only: first-order vehicle
        dt, tau = 0.01, 0.15
        vel = np.zeros((n, 3))
        for k in range(2, 1500):
            est = m.VehicleEstimates(header=m.Header(seq=k, stamp=k * dt),
                                     positions=q, stamps=np.full(n, k * dt))
            out = planner.tick(est, vel=vel)
            vel += (dt / tau) * (out.distcmd - vel)
            q = q + vel * dt
        # converged: command magnitude small
        assert np.linalg.norm(out.distcmd, axis=1).mean() < 0.3

    def test_formation_with_gains_skips_solve(self):
        from aclswarm_tpu.interop import TpuPlanner
        n = 4
        pts = np.array([[0., 0, 1], [2, 0, 1], [2, 2, 1], [0, 2, 1]])
        adj = np.ones((n, n), np.uint8) - np.eye(n, dtype=np.uint8)
        from aclswarm_tpu import gains as gainslib
        G = np.asarray(gainslib.solve_gains(pts, adj), np.float32)
        fmsg = m.Formation(header=m.Header(), name="sq", points=pts,
                           adjmat=adj, gains=G)
        # byte round-trip first: the planner consumes a decoded wire msg
        fmsg = codec.decode(codec.encode(fmsg))
        planner = TpuPlanner(n)
        planner.handle_formation(fmsg)
        out = planner.tick(pts + 0.1)
        assert out.assignment is not None

    def test_wrong_size_rejected(self):
        from aclswarm_tpu.interop import TpuPlanner
        pts, adj = self._spec(6)
        planner = TpuPlanner(5)
        with pytest.raises(ValueError):
            planner.handle_formation(
                m.Formation(header=m.Header(), name="x", points=pts,
                            adjmat=adj))

    @pytest.mark.slow
    def test_large_swarm_assignment_is_exact_int32(self):
        """n > 255 must publish an int32 permutation — a uint8 payload
        would silently wrap indices >= 256 into a corrupt non-permutation
        (the wire Assignment message is int32 for exactly this reason)."""
        from aclswarm_tpu.interop import TpuPlanner
        n = 300
        rng = np.random.default_rng(0)
        pts = rng.normal(size=(n, 3)) * 20.0
        adj = np.ones((n, n), np.uint8) - np.eye(n, dtype=np.uint8)
        # zero gains: skips the (expensive) ADMM solve; the auction and
        # the publish path — what this test pins — don't depend on them
        G = np.zeros((3 * n, 3 * n), np.float32)
        planner = TpuPlanner(n)
        planner.handle_formation(
            m.Formation(header=m.Header(), name="big", points=pts,
                        adjmat=adj, gains=G))
        out = planner.tick(rng.normal(size=(n, 3)) * 20.0)
        assert out.assignment is not None
        assert out.assignment.dtype == np.int32
        assert sorted(out.assignment.tolist()) == list(range(n))


class TestPlannerFirstAcceptSemantics:
    def test_unchanged_assignment_after_commit_is_published(self):
        """The first valid auction after a commit publishes even when the
        assignment is unchanged and earlier auctions were skipped
        (`auctioneer.cpp:310-316` formation_just_received; regression for
        the invalid-first-auction case)."""
        from aclswarm_tpu.interop import TpuPlanner
        n = 4
        pts = np.array([[0., 0, 1], [2, 0, 1], [2, 2, 1], [0, 2, 1]])
        adj = np.ones((n, n), np.uint8) - np.eye(n, dtype=np.uint8)
        planner = TpuPlanner(n, assign_every=10)
        planner.handle_formation(
            m.Formation(header=m.Header(), name="sq", points=pts,
                        adjmat=adj))
        # vehicles already on their points: the LAP returns identity (an
        # unchanged assignment) -> must still be published once
        out = planner.tick(pts)
        assert out.assignment is not None
        # subsequent unchanged auctions are NOT re-published
        for k in range(10):
            out = planner.tick(pts)
        assert out.assignment is None


class TestCentralAssignment:
    """Comparison-mode backdoor: an operator-computed assignment is pushed
    into the flying planner at runtime and used as if the auctioneer had
    decided it (`coordination_ros.cpp:272-280,330-343`,
    `operator.py:221-246`)."""

    def _planner(self, n=6, assign_every=10):
        from aclswarm_tpu.interop import TpuPlanner
        ang = np.linspace(0, 2 * np.pi, n, endpoint=False)
        pts = np.stack([3 * np.cos(ang), 3 * np.sin(ang),
                        np.full(n, 1.5)], 1)
        adj = np.ones((n, n), np.uint8) - np.eye(n, dtype=np.uint8)
        G = np.zeros((3 * n, 3 * n), np.float32)   # skip the ADMM solve
        pl = TpuPlanner(n, assign_every=assign_every,
                        central_assignment=True)
        pl.handle_formation(m.Formation(header=m.Header(), name="ring",
                                        points=pts, adjmat=adj, gains=G))
        return pl, pts

    def test_pushed_assignment_overrides_auction(self):
        """A deliberately suboptimal central permutation wins over what
        the device auction would have computed — proof no auction ran."""
        pl, pts = self._planner()
        n = 6
        rng = np.random.default_rng(1)
        q = pts[rng.permutation(n)]
        pushed = np.roll(np.arange(n), 1).astype(np.int32)
        assert pl.handle_central_assignment(
            m.Assignment(header=m.Header(), perm=pushed))
        out = pl.tick(q)
        np.testing.assert_array_equal(out.assignment, pushed)
        np.testing.assert_array_equal(np.asarray(pl.v2f), pushed)
        assert out.auction_valid

    def test_cadence_and_change_gating(self):
        """Adoption happens only at the auction cadence; an unchanged push
        after the first is ignored (`centralAssignmentCb`'s
        first_assignment_ || changed gate)."""
        pl, pts = self._planner(assign_every=10)
        n = 6
        ident = np.arange(n, dtype=np.int32)
        pl.handle_central_assignment(ident)
        out = pl.tick(pts)
        # first assignment after the commit publishes even though it is
        # the identity the planner already held
        assert out.assignment is not None
        pl.handle_central_assignment(ident)          # unchanged -> ignored
        for _ in range(10):
            out = pl.tick(pts)
            assert out.assignment is None
        newp = np.roll(ident, 2).astype(np.int32)
        pl.handle_central_assignment(newp)
        emitted = [(k, out.assignment) for k in range(10)
                   if (out := pl.tick(pts)).assignment is not None]
        assert len(emitted) == 1                     # once, on the cadence
        np.testing.assert_array_equal(emitted[0][1], newp)

    def test_new_formation_discards_pending_push(self):
        """A permutation pushed for formation A is not adopted after a
        commit of formation B (documented divergence: the reference
        leaves the latch set but its operator re-pushes faster than the
        cadence)."""
        pl, pts = self._planner(assign_every=10)
        stale = np.roll(np.arange(6), 3).astype(np.int32)
        pl.handle_central_assignment(stale)
        # commit a new formation before any adoption cadence elapses
        n = 6
        adj = np.ones((n, n), np.uint8) - np.eye(n, dtype=np.uint8)
        pl.handle_formation(m.Formation(
            header=m.Header(), name="ring2", points=pts * 1.5, adjmat=adj,
            gains=np.zeros((3 * n, 3 * n), np.float32)))
        for _ in range(12):
            assert pl.tick(pts).assignment is None
        np.testing.assert_array_equal(np.asarray(pl.v2f), np.arange(6))

    def test_malformed_push_rejected(self):
        pl, pts = self._planner()
        bad_dup = np.array([0, 0, 1, 2, 3, 4], np.int32)
        assert not pl.handle_central_assignment(bad_dup)
        assert not pl.handle_central_assignment(
            np.arange(5, dtype=np.int32))
        out = pl.tick(pts)
        assert out.assignment is None
        np.testing.assert_array_equal(np.asarray(pl.v2f), np.arange(6))

    def test_no_auction_without_push(self):
        """Central mode with no operator push: the planner holds identity
        forever (the reference never starts the auctioneer in this mode)."""
        pl, pts = self._planner()
        rng = np.random.default_rng(2)
        q = pts[rng.permutation(6)]
        for _ in range(25):
            assert pl.tick(q).assignment is None
        np.testing.assert_array_equal(np.asarray(pl.v2f), np.arange(6))

    def test_operator_central_matches_lap_oracle(self):
        from aclswarm_tpu.assignment.cbaa_ref import arun_np
        from aclswarm_tpu.assignment.lapjv import solve_assignment_host
        from aclswarm_tpu.interop.operator import Operator
        op = Operator("swarm4")
        # before any dispatch: formidx == -1 guard (`operator.py:231`)
        assert op.central_assignment(np.zeros((4, 3))) is None
        fmsg = op.next_formation()
        p = np.asarray(fmsg.points, np.float64)
        rng = np.random.default_rng(3)
        q = p[rng.permutation(4)] + rng.normal(scale=0.05, size=(4, 3)) \
            + [5.0, 0.0, 0.0]
        msg = op.central_assignment(q)
        assert sorted(msg.perm.tolist()) == list(range(4))
        # parity with align+LAP done by hand (last=identity -> qq == q)
        R, t = arun_np(p, q, d=2)
        np.testing.assert_array_equal(
            msg.perm, solve_assignment_host(q, p @ R.T + t))


@needs_native
class TestOversizeFrame:
    def test_never_fitting_frame_raises(self):
        from aclswarm_tpu.interop.transport import Channel
        with Channel(f"aswtest-{uuid.uuid4().hex[:12]}", create=True,
                     capacity=256) as ch:
            big = m.CBAA(header=m.Header(), auction_id=0, iter=0,
                         price=np.zeros(500, np.float32),
                         who=np.zeros(500, np.int32))
            with pytest.raises(ValueError, match="never fit"):
                ch.send(big)

    def test_opener_reads_true_capacity(self):
        from aclswarm_tpu.interop.transport import Channel
        name = f"aswtest-{uuid.uuid4().hex[:12]}"
        with Channel(name, create=True, capacity=4096) as creator:
            opener = Channel(name)   # default capacity arg ignored
            try:
                assert opener._capacity == creator._capacity == 4096
            finally:
                opener.close()


def _distcmd_msg(n=5):
    return m.DistCmd(header=m.Header(seq=4, stamp=1.5, frame_id="w"),
                     vel=RNG.normal(size=(n, 3)))


def _assignment_msg(n=5):
    return m.Assignment(header=m.Header(seq=6, stamp=2.5),
                        perm=RNG.permutation(n).astype(np.int32))


def _flightmode_msg(mode=m.MODE_KILL):
    return m.FlightMode(header=m.Header(seq=7, stamp=3.5), mode=mode)


def _safety_array_msg(n=5):
    return m.SafetyStatusArray(header=m.Header(seq=8, stamp=4.5),
                               active=RNG.integers(0, 2, n, dtype=np.uint8))


class TestOutputMessages:
    @pytest.mark.parametrize("msg_fn", [_distcmd_msg, _assignment_msg,
                                        _flightmode_msg, _safety_array_msg])
    def test_roundtrip(self, msg_fn):
        msg = msg_fn()
        out = codec.decode(codec.encode(msg))
        assert type(out) is type(msg)
        for f in msg.__dataclass_fields__:
            a, b = getattr(msg, f), getattr(out, f)
            if isinstance(a, np.ndarray):
                np.testing.assert_array_equal(a, b)

    @needs_native
    def test_native_parity_and_decode(self):
        import ctypes as C
        lib = nat.load()
        cmd = _distcmd_msg()
        py = codec.encode(cmd)
        out = (C.c_uint8 * (len(py) + 64))()
        nb = lib.asw_encode_distcmd(
            cmd.header.seq, cmd.header.stamp, cmd.header.frame_id.encode(),
            cmd.vel.shape[0], cmd.vel.ctypes.data_as(C.POINTER(C.c_double)),
            out, len(out))
        assert nb == len(py) and bytes(out[:nb]) == py
        asn = _assignment_msg()
        py = codec.encode(asn)
        nb = lib.asw_encode_assignment(
            asn.header.seq, asn.header.stamp, asn.header.frame_id.encode(),
            len(asn.perm), asn.perm.ctypes.data_as(C.POINTER(C.c_int32)),
            out, len(out))
        assert nb == len(py) and bytes(out[:nb]) == py
        # C++ decode of the Python-encoded assignment
        buf = (C.c_uint8 * len(py)).from_buffer_copy(py)
        nn = C.c_uint32()
        assert lib.asw_assignment_n(buf, len(py), C.byref(nn)) == 0
        perm = np.zeros(nn.value, np.int32)
        assert lib.asw_decode_assignment(
            buf, len(py), None, None,
            perm.ctypes.data_as(C.POINTER(C.c_int32))) == 0
        np.testing.assert_array_equal(perm, asn.perm)

    @needs_native
    def test_flightmode_safety_native_parity(self):
        import ctypes as C
        lib = nat.load()
        fm = _flightmode_msg(m.MODE_LAND)
        py = codec.encode(fm)
        out = (C.c_uint8 * (len(py) + 64))()
        nb = lib.asw_encode_flightmode(
            fm.header.seq, fm.header.stamp, fm.header.frame_id.encode(),
            fm.mode, out, len(out))
        assert nb == len(py) and bytes(out[:nb]) == py
        buf = (C.c_uint8 * len(py)).from_buffer_copy(py)
        mode = C.c_int()
        assert lib.asw_decode_flightmode(buf, len(py), None, None,
                                         C.byref(mode)) == 0
        assert mode.value == m.MODE_LAND

        sa = _safety_array_msg()
        py = codec.encode(sa)
        nb = lib.asw_encode_safety_array(
            sa.header.seq, sa.header.stamp, sa.header.frame_id.encode(),
            len(sa.active),
            sa.active.ctypes.data_as(C.POINTER(C.c_uint8)), out, len(out))
        assert nb == len(py) and bytes(out[:nb]) == py
        buf = (C.c_uint8 * len(py)).from_buffer_copy(py)
        nn = C.c_uint32()
        assert lib.asw_safety_array_n(buf, len(py), C.byref(nn)) == 0
        active = np.zeros(nn.value, np.uint8)
        assert lib.asw_decode_safety_array(
            buf, len(py), None, None,
            active.ctypes.data_as(C.POINTER(C.c_uint8))) == 0
        np.testing.assert_array_equal(active, sa.active)


class TestOperator:
    def test_cycles_group_like_reference(self):
        """START-while-flying cycles formations (`operator.py:128-134`)."""
        from aclswarm_tpu.interop.operator import Operator
        op = Operator("swarm4")
        sent = []
        for _ in range(4):
            op.dispatch(sent.append)
        names = [s.name for s in sent]
        assert names[0] != names[1]           # cycles
        assert names[0] == names[2]           # wraps
        assert all(s.gains is not None for s in sent)   # library gains ship
        op2 = Operator("swarm4", send_gains=False)
        msg = op2.next_formation()
        assert msg.gains is None


@needs_native
class TestBridgeLifecycle:
    @pytest.mark.slow
    def test_takeoff_fly_land_kill_over_wire(self):
        """The whole flight lifecycle wire-only: an operator broadcasts
        GO/LAND/KILL `FlightMode` messages and dispatches a `Formation`;
        a bridge process owns the planner; this process plays the
        vehicles' L2/L1 stack (flight FSM + safe-traj + tracking) fed
        exclusively by decoded wire traffic. Verifies the round-2 gaps:
        the flight-mode channel exists, `SafetyStatusArray` streams per
        tick, and KILL cuts distcmd to zero on the very next tick
        (`safety.cpp:116-120`, `operator.py:117-135`)."""
        import pathlib
        import time

        import jax.numpy as jnp

        from aclswarm_tpu.control import safety as safetylib
        from aclswarm_tpu.core.types import SafetyParams
        from aclswarm_tpu.interop.operator import Operator
        from aclswarm_tpu.interop.transport import Channel
        from aclswarm_tpu.sim import vehicle as veh

        ns = f"/aswtest-{uuid.uuid4().hex[:8]}"
        repo = str(pathlib.Path(__file__).resolve().parents[1])
        n = 4
        dt = 0.01
        lf = _load_factor()
        child = subprocess.Popen(
            [sys.executable, "-m", "aclswarm_tpu.interop.bridge",
             "--n", str(n), "--ns", ns, "--assign-every", "50",
             "--idle-timeout", str(180 * lf)], cwd=repo)
        chans = {}
        try:
            deadline = time.time() + 60 * lf
            for name in ("formation", "flightmode", "estimates", "distcmd",
                         "assignment", "safety"):
                while True:
                    try:
                        chans[name] = Channel(f"{ns}-{name}")
                        break
                    except OSError:
                        if time.time() > deadline:
                            raise
                        time.sleep(0.05)

            # vehicle-side broadcast ring (operator -> vehicles), the
            # /globalflightmode edge the fleet consumes
            veh_mode = Channel(f"{ns}-flightmode-veh", create=True)
            chans["flightmode-veh"] = veh_mode

            # fast ramps so the lifecycle fits a test budget
            sparams = SafetyParams(
                bounds_min=jnp.asarray([-50.0, -50.0, 0.0]),
                bounds_max=jnp.asarray([50.0, 50.0, 30.0]),
                spinup_time=0.1, takeoff_inc=0.02,
                landing_fast_dec=0.02, landing_slow_dec=0.01)
            rng = np.random.default_rng(7)
            q = np.zeros((n, 3))
            q[:, :2] = rng.normal(size=(n, 2)) * 2.0
            q = jnp.asarray(q)
            fs = veh.init_flight(n, q.dtype, flying=False)
            goal = safetylib.TrajGoal.hover_at(q)
            tick = 0

            def vehicle_tick():
                """One wire-fed vehicle tick; returns (distcmd, safety)."""
                nonlocal q, fs, goal, tick
                cmd = veh.CMD_NONE
                while isinstance(fm := veh_mode.recv(), m.FlightMode):
                    cmd = int(fm.mode)   # MODE_* == CMD_* by construction
                fs = veh.apply_command(fs, jnp.asarray(cmd, jnp.int32))
                assert chans["estimates"].send(m.VehicleEstimates(
                    header=m.Header(seq=tick, stamp=tick * dt),
                    positions=np.asarray(q), stamps=np.full(n, tick * dt)))
                cmdmsg = None
                t0 = time.time()
                while cmdmsg is None and time.time() - t0 < 60 * lf:
                    cmdmsg = chans["distcmd"].recv()
                    if cmdmsg is None:
                        time.sleep(0.0005)
                assert cmdmsg is not None, f"no distcmd at tick {tick}"
                safe = chans["safety"].recv()
                u = jnp.where((fs.mode == veh.FLYING)[:, None],
                              jnp.asarray(cmdmsg.vel), 0.0)
                u = safetylib.saturate_velocity(u, sparams)
                sg = safetylib.make_safe_traj(dt, u, jnp.zeros((n,)), goal,
                                              sparams)
                fs, goal = veh.flight_step(fs, goal, sg, q, sparams, dt)
                q = goal.pos
                tick += 1
                return cmdmsg, safe

            op = Operator("swarm4")

            # -- phase 1: START on the ground => GO broadcast, takeoff --
            assert op.start(veh_mode.send) is None and op.flying
            # bridge hears the same broadcast on its own ring
            assert chans["flightmode"].send(
                m.FlightMode(header=m.Header(), mode=m.MODE_GO))
            for _ in range(1500):
                cmdmsg, _ = vehicle_tick()
                assert np.all(cmdmsg.vel == 0)   # no formation committed
                if bool(jnp.all(fs.mode == veh.FLYING)):
                    break
            assert bool(jnp.all(fs.mode == veh.FLYING)), np.asarray(fs.mode)
            np.testing.assert_allclose(np.asarray(q)[:, 2], 1.0, atol=0.11)

            # -- phase 2: START in flight => formation dispatch, fly --
            fmsg = op.start(veh_mode.send, chans["formation"].send)
            assert isinstance(fmsg, m.Formation)
            got_asn = got_safety = False
            moved = 0.0
            for _ in range(300):
                cmdmsg, safe = vehicle_tick()
                if chans["assignment"].recv() is not None:
                    got_asn = True
                if safe is not None:
                    got_safety = True
                    assert safe.active.shape == (n,)
                moved = max(moved, float(np.abs(cmdmsg.vel).max()))
            assert got_asn and got_safety and moved > 0

            # -- phase 3: END => LAND broadcast, descend to ground --
            op.end(veh_mode.send)
            assert not op.flying
            for _ in range(2000):
                vehicle_tick()
                if bool(jnp.all(fs.mode == veh.NOT_FLYING)):
                    break
            assert bool(jnp.all(fs.mode == veh.NOT_FLYING))
            assert float(jnp.max(q[:, 2])) < 0.05

            # -- phase 4: GO again, then KILL mid-flight --
            assert op.start(veh_mode.send) is None
            chans["flightmode"].send(
                m.FlightMode(header=m.Header(), mode=m.MODE_GO))
            for _ in range(1500):
                vehicle_tick()
                if bool(jnp.all(fs.mode == veh.FLYING)):
                    break
            # formation is still committed: commands flow again
            cmdmsg, _ = vehicle_tick()
            op.kill(veh_mode.send)
            chans["flightmode"].send(
                m.FlightMode(header=m.Header(), mode=m.MODE_KILL))
            # the bridge drains flight modes before the tick: the very
            # next distcmd frame must be all-zero (e-stop semantics)
            cmdmsg, _ = vehicle_tick()
            assert np.all(cmdmsg.vel == 0.0), cmdmsg.vel
            assert bool(jnp.all(fs.mode == veh.NOT_FLYING))

            # shut the bridge down cleanly over the wire
            pts = np.asarray(fmsg.points)
            chans["formation"].send(m.Formation(
                header=m.Header(), name="__shutdown__", points=pts,
                adjmat=np.asarray(fmsg.adjmat)))
        finally:
            child.terminate()
            try:
                child.wait(timeout=30)
            except subprocess.TimeoutExpired:
                child.kill()
                child.wait(timeout=30)
            for ch in chans.values():
                ch.close()


class TestCentralAssignmentWire:
    @pytest.mark.slow
    def test_operator_pushed_assignment_over_wire(self):
        """Centralized-vs-decentralized comparison end-to-end over the
        wire: a bridge in --central-assignment mode adopts the operator's
        Hungarian permutation from the <ns>-central-assignment channel
        instead of auctioning (`coordination_ros.cpp:330-343`), and a
        later push interrupts the flying swarm's assignment at the next
        cadence."""
        import pathlib
        import time

        from aclswarm_tpu.interop.operator import Operator
        from aclswarm_tpu.interop.transport import Channel

        ns = f"/aswtest-{uuid.uuid4().hex[:8]}"
        repo = str(pathlib.Path(__file__).resolve().parents[1])
        n = 4
        lf = _load_factor()
        child = subprocess.Popen(
            [sys.executable, "-m", "aclswarm_tpu.interop.bridge",
             "--n", str(n), "--ns", ns, "--assign-every", "5",
             "--central-assignment",
             "--idle-timeout", str(180 * lf)], cwd=repo)
        chans = {}
        try:
            deadline = time.time() + 60 * lf
            for name in ("formation", "estimates", "central-assignment",
                         "distcmd", "assignment"):
                while True:
                    try:
                        chans[name] = Channel(f"{ns}-{name}")
                        break
                    except OSError:
                        if time.time() > deadline:
                            raise
                        time.sleep(0.05)

            op = Operator("swarm4")
            fmsg = op.next_formation()
            # zero gains: skip the on-commit ADMM solve (not under test)
            fmsg.gains = np.zeros((3 * n, 3 * n), np.float32)
            assert chans["formation"].send(fmsg)

            rng = np.random.default_rng(11)
            q = np.asarray(fmsg.points)[rng.permutation(n)] \
                + rng.normal(scale=0.05, size=(n, 3))

            def tick(k, q):
                assert chans["estimates"].send(m.VehicleEstimates(
                    header=m.Header(seq=k, stamp=k * 0.01),
                    positions=q, stamps=np.full(n, k * 0.01)))
                t0 = time.time()
                while time.time() - t0 < 60 * lf:
                    if (cmd := chans["distcmd"].recv()) is not None:
                        return cmd
                    time.sleep(0.0005)
                raise AssertionError(f"no distcmd at tick {k}")

            # phase 1: no push yet -> no assignment ever published
            for k in range(6):
                tick(k, q)
            assert chans["assignment"].recv() is None

            # phase 2: operator pushes its Hungarian -> adopted at the
            # next cadence and published on <ns>-assignment
            push1 = op.central_assignment(q, stamp=0.06)
            assert chans["central-assignment"].send(push1)
            got = None
            for k in range(6, 20):
                tick(k, q)
                if (msg := chans["assignment"].recv()) is not None:
                    got = msg
                    break
            assert got is not None, "central assignment never adopted"
            np.testing.assert_array_equal(got.perm, push1.perm)

            # phase 3: a *different* push mid-flight interrupts the held
            # assignment (the runtime-injection semantics)
            push2 = m.Assignment(header=m.Header(seq=99),
                                 perm=np.roll(push1.perm, 1).astype(
                                     np.int32))
            assert chans["central-assignment"].send(push2)
            got = None
            for k in range(20, 40):
                tick(k, q)
                if (msg := chans["assignment"].recv()) is not None:
                    got = msg
                    break
            assert got is not None
            np.testing.assert_array_equal(got.perm, push2.perm)

            pts = np.asarray(fmsg.points)
            chans["formation"].send(m.Formation(
                header=m.Header(), name="__shutdown__", points=pts,
                adjmat=np.asarray(fmsg.adjmat)))
        finally:
            child.terminate()
            try:
                child.wait(timeout=30)
            except subprocess.TimeoutExpired:
                child.kill()
                child.wait(timeout=30)
            for ch in chans.values():
                ch.close()


@needs_native
class TestBridgeEndToEnd:
    def test_operator_bridge_vehicle_loop(self):
        """Full cross-process SIL shape over the native transport: an
        operator dispatches a Formation, a bridge process owns the
        planner, and this process plays the vehicles — estimates in,
        distcmd out, first-order integration — until the swarm converges.
        The north star's 'SIL trials unchanged at the aclswarm_msgs
        boundary', with the shm ring standing in for TCPROS."""
        import pathlib
        import time

        from aclswarm_tpu.interop.operator import Operator
        from aclswarm_tpu.interop.transport import Channel
        ns = f"/aswtest-{uuid.uuid4().hex[:8]}"
        repo = str(pathlib.Path(__file__).resolve().parents[1])
        n, ticks = 4, 600
        lf = _load_factor()
        child = subprocess.Popen(
            [sys.executable, "-m", "aclswarm_tpu.interop.bridge",
             "--n", str(n), "--ns", ns, "--ticks", str(ticks),
             "--assign-every", "50", "--idle-timeout", str(120 * lf)],
            cwd=repo)
        try:
            # the bridge creates the rings; wait for them
            deadline = time.time() + 60 * lf
            chans = {}
            for name in ("formation", "estimates", "distcmd", "assignment"):
                while True:
                    try:
                        chans[name] = Channel(f"{ns}-{name}")
                        break
                    except OSError:
                        if time.time() > deadline:
                            raise
                        time.sleep(0.05)

            op = Operator("swarm4")
            fmsg = op.dispatch(chans["formation"].send)
            pts = np.asarray(fmsg.points)

            rng = np.random.default_rng(11)
            q = rng.normal(size=(n, 3)) * 2.0
            q[:, 2] = 1.0
            vel = np.zeros((n, 3))
            dt, tau = 0.01, 0.15
            got_assignment = False
            last_cmd = None
            for k in range(ticks):
                assert chans["estimates"].send(m.VehicleEstimates(
                    header=m.Header(seq=k, stamp=k * dt), positions=q,
                    stamps=np.full(n, k * dt)))
                cmd = None
                t0 = time.time()
                while cmd is None and time.time() - t0 < 60 * lf:
                    cmd = chans["distcmd"].recv()
                    if cmd is None:
                        time.sleep(0.001)
                assert cmd is not None, f"no distcmd at tick {k}"
                asn = chans["assignment"].recv()
                if asn is not None:
                    got_assignment = True
                    assert sorted(asn.perm.tolist()) == list(range(n))
                vel += (dt / tau) * (cmd.vel - vel)
                q = q + vel * dt
                last_cmd = cmd
            assert got_assignment
            assert np.linalg.norm(last_cmd.vel, axis=1).mean() < 0.5
        finally:
            # kill the bridge before waiting: its idle-timeout matches the
            # wait timeout, so a mid-loop assertion would otherwise be
            # masked by TimeoutExpired (or leave a zombie holding the shm)
            child.terminate()
            try:
                child.wait(timeout=30)
            except subprocess.TimeoutExpired:
                child.kill()
                child.wait(timeout=30)
            for ch in chans.values():
                ch.close()


class TestConnectFdHygiene:
    def test_refused_connect_storm_leaks_no_fds(self):
        """`connect_when_ready` against a port nothing listens on: the
        per-attempt socket close is structural (try/finally), so 50+
        refused attempts leave the process fd table exactly where it
        started. A leak here is one fd per retry until the rlimit —
        the router's respawn loop reconnects exactly this way."""
        import socket as _socket

        from aclswarm_tpu.interop.transport import connect_when_ready

        # grab a port the OS just proved free, then close the listener
        # so every connect is refused
        probe = _socket.socket(_socket.AF_INET, _socket.SOCK_STREAM)
        probe.bind(("127.0.0.1", 0))
        port = probe.getsockname()[1]
        probe.close()

        fd_dir = "/proc/self/fd"
        if not os.path.isdir(fd_dir):
            pytest.skip("no /proc fd table on this platform")

        def _fds():
            return len(os.listdir(fd_dir))

        # one throwaway round first: lazy imports inside the helper
        # (utils.retry) may open fds of their own on first use
        with pytest.raises(OSError):
            connect_when_ready("127.0.0.1", port, grace_s=0.05,
                               poll_s=0.01)
        before = _fds()
        attempts = 0
        while attempts < 50:
            with pytest.raises(OSError, match="refused|never"):
                connect_when_ready("127.0.0.1", port, grace_s=0.05,
                                   poll_s=0.01)
            attempts += 5   # >= 5 internal attempts per 0.05 s grace
        assert _fds() == before, \
            f"fd table grew {before} -> {_fds()} over refused connects"
