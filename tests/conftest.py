"""Test configuration: 8-device virtual CPU mesh + float64.

Multi-chip hardware is not available in CI; per the framework's test strategy
(SURVEY.md §4 implications), sharding is validated on a virtual 8-device CPU
mesh. float64 is enabled so golden-value tests can match the reference's
double-precision C++/MATLAB outputs (`aclswarm/test/test_admm.cpp` uses 1e-8
tolerances).

The f32 device tier (`pytest -m f32`, tests/test_f32.py) toggles x64 off per
test; run it on the real chip with ACLSWARM_TEST_TPU=1 (which skips the
CPU forcing below — the axon plugin then provides the default TPU backend).
"""
import os

import pytest

ON_TPU = os.environ.get("ACLSWARM_TEST_TPU", "") == "1"

flags = os.environ.get("XLA_FLAGS", "")
if not ON_TPU and "xla_force_host_platform_device_count" not in flags:
    os.environ["XLA_FLAGS"] = (
        flags + " --xla_force_host_platform_device_count=8").strip()
if not ON_TPU:
    os.environ["JAX_PLATFORMS"] = "cpu"

import jax  # noqa: E402

if not ON_TPU:
    jax.config.update("jax_platforms", "cpu")
jax.config.update("jax_enable_x64", True)


def pytest_configure(config):
    config.addinivalue_line(
        "markers",
        "f32: device-precision tier — runs the core kernels at float32 "
        "(on TPU when ACLSWARM_TEST_TPU=1) with justified tolerances")
    config.addinivalue_line(
        "markers",
        "slow: > ~30 s (full trials, cross-process bridge loops). Quick "
        "tier: pytest -m 'not slow' (< ~2 min); run the full suite "
        "before committing substantial changes")
    config.addinivalue_line(
        "markers",
        "faults: fault-injection / elastic-swarm subsystem "
        "(aclswarm_tpu.faults; docs/FAULTS.md). Batch-scale sweeps "
        "(B >= 8) additionally carry `slow` so tier-1 stays on budget")
    config.addinivalue_line(
        "markers",
        "analysis: jaxcheck static analysis — AST lint (JC001-JC006) + "
        "trace-time compile/transfer audit of the jitted entry points "
        "(aclswarm_tpu.analysis; docs/STATIC_ANALYSIS.md). The heavy "
        "n=16/B=4 audit grid additionally carries `slow`")
    config.addinivalue_line(
        "markers",
        "resilience: resilient execution layer — chunk-boundary "
        "checkpoint/resume (bit-identical, proven vs uninterrupted "
        "runs), unified retry/backoff, crash injection "
        "(aclswarm_tpu.resilience; docs/RESILIENCE.md)")
    config.addinivalue_line(
        "markers",
        "serve: swarmserve always-on serving layer — admission control "
        "and backpressure, per-tenant fair batching, deadline "
        "enforcement, checkpoint-backed preemption, journal recovery "
        "(aclswarm_tpu.serve; docs/SERVICE.md). Soak-sized runs "
        "additionally carry `slow` to respect the tier-1 duration guard")
    config.addinivalue_line(
        "markers",
        "telemetry: swarmscope unified telemetry layer — host metrics "
        "registry (counters/gauges/histograms, span flight recorder, "
        "JSONL + Prometheus exports), device-resident ChunkTelemetry "
        "chunk counters (zero-cost off via the shared HLO baseline), "
        "ServeStats, and the log/timing unification "
        "(aclswarm_tpu.telemetry; docs/OBSERVABILITY.md)")
    config.addinivalue_line(
        "markers",
        "scenario: swarmscenario composable scenario compiler — "
        "timelines-as-pytrees (obstacles, wind/noise, formation "
        "sequences, byzantine bidders, goal drift), no_scenario "
        "bit-parity, family registry, invariant-oracle fuzzer, and "
        "scenarios as a serve request kind (aclswarm_tpu.scenarios; "
        "docs/SCENARIOS.md). The full >= 50-composition fuzz sweep "
        "additionally carries `slow`; tier-1 runs a quick-seed subset")
    config.addinivalue_line(
        "markers",
        "locks: swarmguard host-side concurrency tier — OrderedLock/"
        "OrderedRLock rank enforcement, two-thread inversion/cycle "
        "detection under ACLSWARM_LOCK_DEBUG=1, and the lock hold/wait "
        "histogram contract (aclswarm_tpu.utils.locks + "
        "aclswarm_tpu.analysis.concurrency; docs/STATIC_ANALYSIS.md "
        "§host-side concurrency)")
    config.addinivalue_line(
        "markers",
        "invariants: swarmcheck runtime sanitizer — compiled-in "
        "invariant contracts (aclswarm_tpu.analysis.invariants; "
        "docs/STATIC_ANALYSIS.md runtime tier): clean-system positives, "
        "seeded-corruption mutation tests with trial/tick/contract "
        "attribution, zero-cost-off. The n>=16 full contract grid "
        "additionally carries `slow`")


@pytest.fixture
def f32_mode():
    """Run a test at f32 (x64 off), restoring the suite's f64 default."""
    jax.config.update("jax_enable_x64", False)
    try:
        yield
    finally:
        jax.config.update("jax_enable_x64", True)
