"""Test configuration: 8-device virtual CPU mesh + float64.

Multi-chip hardware is not available in CI; per the framework's test strategy
(SURVEY.md §4 implications), sharding is validated on a virtual 8-device CPU
mesh. float64 is enabled so golden-value tests can match the reference's
double-precision C++/MATLAB outputs (`aclswarm/test/test_admm.cpp` uses 1e-8
tolerances).
"""
import os

flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in flags:
    os.environ["XLA_FLAGS"] = (
        flags + " --xla_force_host_platform_device_count=8").strip()
os.environ["JAX_PLATFORMS"] = "cpu"

import jax  # noqa: E402

jax.config.update("jax_platforms", "cpu")
jax.config.update("jax_enable_x64", True)
