"""Fault-injection & elastic-swarm subsystem tests (`aclswarm_tpu.faults`).

Pins the subsystem's three contracts:

1. **No-fault parity**: a rollout carrying `no_faults(n)` is BIT-IDENTICAL
   to one carrying ``faults=None`` — serial and batched, every assignment
   mode, both information models (every fault mask is a `where` whose
   all-true case is the pass-through operand).
2. **Masked-assignment degenerates**: all-dead, single-survivor, and
   dropout-then-rejoin round trips keep `v2f` a valid permutation with
   dead vehicles pinned to their current points, for auction, CBAA, and
   Sinkhorn.
3. **Fault semantics**: dead vehicles freeze and cast no avoidance
   sector, lossy links go hold-last-value stale in the flood, and the
   on-device recovery clock (`sim.summary`) matches host recomputation.
"""
import numpy as np
import pytest

import jax
import jax.numpy as jnp

from aclswarm_tpu import faults, sim
from aclswarm_tpu.core import perm as permutil
from aclswarm_tpu.core.types import ControlGains, SafetyParams, make_formation
from aclswarm_tpu.sim import summary as sumlib

pytestmark = pytest.mark.faults

METRIC_FIELDS = ("distcmd_norm", "ca_active", "assign_valid", "reassigned",
                 "auctioned", "q", "mode", "v2f")


def _problem(B, n, seed=0, localization=False, scheds=None):
    """B (formation, state) pairs + stacked batch, as in test_batched."""
    rng = np.random.default_rng(seed)
    adj = np.ones((n, n)) - np.eye(n)
    forms, states = [], []
    for b in range(B):
        pts = rng.normal(size=(n, 3)) * 5
        gains = rng.normal(size=(n, n, 3, 3)) * 0.01
        forms.append(make_formation(jnp.asarray(pts), jnp.asarray(adj),
                                    jnp.asarray(gains)))
        states.append(sim.init_state(
            rng.normal(size=(n, 3)) * 5 + np.array([0, 0, 2.0]),
            localization=localization,
            faults=None if scheds is None else scheds[b]))
    sp = SafetyParams(bounds_min=jnp.asarray([-50.0, -50.0, 0.0]),
                      bounds_max=jnp.asarray([50.0, 50.0, 20.0]))
    bstate = jax.tree.map(lambda *xs: jnp.stack(xs), *states)
    bform = jax.tree.map(lambda *xs: jnp.stack(xs), *forms)
    return states, forms, bstate, bform, sp


def _assert_rollouts_equal(m1, m2, f1, f2):
    for name in METRIC_FIELDS:
        np.testing.assert_array_equal(np.asarray(getattr(m1, name)),
                                      np.asarray(getattr(m2, name)), name)
    np.testing.assert_array_equal(np.asarray(f1.swarm.q),
                                  np.asarray(f2.swarm.q))
    np.testing.assert_array_equal(np.asarray(f1.swarm.vel),
                                  np.asarray(f2.swarm.vel))
    np.testing.assert_array_equal(np.asarray(f1.v2f), np.asarray(f2.v2f))


def _assert_valid_perms(v2f):
    """(T, n) or (T, B, n): every tick's assignment is a permutation."""
    n = v2f.shape[-1]
    flat = np.asarray(v2f).reshape(-1, n)
    for row in flat:
        assert sorted(row) == list(range(n))


# --------------------------------------------------------------------------
# 1. no-fault schedule == today's faultless engine, bit for bit
# --------------------------------------------------------------------------

@pytest.mark.parametrize("assignment", ["auction", "sinkhorn", "cbaa"])
def test_no_fault_schedule_bit_parity_serial(assignment):
    n, T = 6, 130
    states, forms, _, _, sp = _problem(1, n, seed=1)
    cfg = sim.SimConfig(assignment=assignment, assign_every=60,
                        flight_fsm=True)
    nf = faults.no_faults(n, states[0].swarm.q.dtype)
    f1, m1 = sim.rollout(states[0], forms[0], ControlGains(), sp, cfg, T)
    f2, m2 = sim.rollout(states[0].replace(faults=nf), forms[0],
                         ControlGains(), sp, cfg, T)
    _assert_rollouts_equal(m1, m2, f1, f2)
    # the fault observables exist and are trivial
    assert np.asarray(m2.alive).all()
    assert not np.asarray(m2.fault_event).any()
    assert m1.alive is None


def test_no_fault_schedule_bit_parity_flooded():
    """Flooded information model: the link mask must not perturb the
    timestamped flood (estimate tables bit-identical too)."""
    n, T = 6, 130
    states, forms, _, _, sp = _problem(1, n, seed=2, localization=True)
    cfg = sim.SimConfig(assignment="cbaa", assign_every=60,
                        localization="flooded", flight_fsm=True)
    nf = faults.no_faults(n, states[0].swarm.q.dtype)
    f1, m1 = sim.rollout(states[0], forms[0], ControlGains(), sp, cfg, T)
    f2, m2 = sim.rollout(states[0].replace(faults=nf), forms[0],
                         ControlGains(), sp, cfg, T)
    _assert_rollouts_equal(m1, m2, f1, f2)
    np.testing.assert_array_equal(np.asarray(f1.loc.est),
                                  np.asarray(f2.loc.est))
    np.testing.assert_array_equal(np.asarray(f1.loc.age),
                                  np.asarray(f2.loc.age))


def test_no_fault_schedule_bit_parity_batched():
    """Batched: a batch of no-fault schedules == the schedule-less batched
    rollout, bit for bit (and == serial, transitively via test_batched)."""
    B, n, T = 3, 6, 130
    states, forms, bstate, bform, sp = _problem(B, n, seed=3)
    cfg = sim.SimConfig(assignment="auction", assign_every=60)
    nf = [faults.no_faults(n, bstate.swarm.q.dtype) for _ in range(B)]
    # deep-copy: batched_rollout donates its carry, and the two batches
    # would otherwise share (and invalidate) the same buffers
    bstate_nf = jax.tree.map(jnp.copy, bstate).replace(
        faults=jax.tree.map(lambda *xs: jnp.stack(xs), *nf))
    bf1, bm1 = sim.batched_rollout(bstate, bform, ControlGains(), sp, cfg, T)
    bf2, bm2 = sim.batched_rollout(bstate_nf, bform, ControlGains(), sp,
                                   cfg, T)
    _assert_rollouts_equal(bm1, bm2, bf1, bf2)


# --------------------------------------------------------------------------
# 2. batched rollout with heterogeneous fault scripts == serial, bit for bit
# --------------------------------------------------------------------------

def test_heterogeneous_schedules_batched_matches_serial():
    """The tentpole acceptance claim: trials carrying DIFFERENT fault
    scripts run in one compiled vmapped scan, bit-identical per trial to
    serial rollouts with the same scripts (shared-tick decimation holds)."""
    B, n, T = 3, 6, 130
    scheds = [
        faults.no_faults(n, jnp.float64),
        faults.sample_schedule(11, n, dropout_frac=0.34, drop_tick=30,
                               rejoin_tick=90, dtype=jnp.float64),
        faults.sample_schedule(12, n, dropout_frac=0.5, drop_tick=61,
                               link_loss=0.4, dtype=jnp.float64),
    ]
    states, forms, bstate, bform, sp = _problem(B, n, seed=4,
                                                localization=True,
                                                scheds=scheds)
    cfg = sim.SimConfig(assignment="cbaa", assign_every=60,
                        localization="flooded", flight_fsm=True)
    bf, bm = sim.batched_rollout(bstate, bform, ControlGains(), sp, cfg, T)
    for b in range(B):
        fs, ms = sim.rollout(states[b], forms[b], ControlGains(), sp, cfg, T)
        for name in METRIC_FIELDS + ("alive", "fault_event"):
            np.testing.assert_array_equal(
                np.asarray(getattr(ms, name)),
                np.asarray(getattr(bm, name))[:, b], (b, name))
        np.testing.assert_array_equal(np.asarray(fs.loc.age),
                                      np.asarray(bf.loc.age)[b])
    _assert_valid_perms(bm.v2f)


# --------------------------------------------------------------------------
# 3. masked-assignment degenerate cases
# --------------------------------------------------------------------------

def _degenerate_schedule(n, kind, drop=30, rejoin=90, dtype=jnp.float64):
    """all_dead / single_survivor / partial round-trip scripts."""
    drops = np.full((n,), faults.NEVER, np.int32)
    rejoins = np.full((n,), faults.NEVER, np.int32)
    if kind == "all_dead":
        drops[:] = drop
    elif kind == "single_survivor":
        drops[1:] = drop
    elif kind == "round_trip":
        drops[: n // 2] = drop
        rejoins[: n // 2] = rejoin
    else:
        raise ValueError(kind)
    return faults.FaultSchedule(drop_tick=jnp.asarray(drops),
                                rejoin_tick=jnp.asarray(rejoins),
                                link_loss=jnp.zeros((n, n), dtype),
                                key=jnp.zeros((2,), jnp.uint32))


@pytest.mark.parametrize("assignment", ["auction", "sinkhorn", "cbaa"])
@pytest.mark.parametrize("kind",
                         ["all_dead", "single_survivor", "round_trip"])
def test_masked_assignment_degenerates(assignment, kind):
    """All-dead, single-survivor, and dropout-then-rejoin round trips keep
    the assignment a valid permutation with dead vehicles pinned to their
    current points, in every solver mode."""
    n, T, drop, rejoin = 6, 190, 30, 90
    states, forms, _, _, sp = _problem(1, n, seed=5)
    sched = _degenerate_schedule(n, kind, drop, rejoin)
    cfg = sim.SimConfig(assignment=assignment, assign_every=60)
    st = states[0].replace(faults=sched)
    final, m = sim.rollout(st, forms[0], ControlGains(), sp, cfg, T)
    _assert_valid_perms(m.v2f)

    v2f = np.asarray(m.v2f)
    alive = np.asarray(m.alive)
    # dead vehicles never change assignment while dead: compare each
    # dead tick's v2f entry to the pre-drop assignment
    pre = v2f[drop - 1]
    for t in range(drop, T):
        dead = ~alive[t]
        np.testing.assert_array_equal(v2f[t][dead], pre[dead],
                                      f"dead row reassigned at tick {t}")
    if kind == "round_trip":
        # after rejoin the fleet keeps auctioning validly (auctions at
        # t=120, 180 with everyone alive again)
        assert alive[rejoin:].all()
        auct = np.asarray(m.auctioned) & np.asarray(m.assign_valid)
        assert auct[rejoin:].any()


def test_dead_vehicles_freeze_and_cast_no_sector():
    """A dead vehicle's pose/velocity hold exactly; survivors' collision
    avoidance ignores it (no CA activity from a frozen obstacle parked
    outside their paths); its ca/distcmd observables read inactive."""
    n, T, drop, rejoin = 6, 120, 20, 80
    states, forms, _, _, sp = _problem(1, n, seed=6)
    sched = _degenerate_schedule(n, "round_trip", drop, rejoin)
    cfg = sim.SimConfig(assignment="auction", assign_every=60)
    st = states[0].replace(faults=sched)
    _, m = sim.rollout(st, forms[0], ControlGains(), sp, cfg, T)
    q = np.asarray(m.q)
    vel_dead = np.asarray(m.distcmd_norm)
    alive = np.asarray(m.alive)
    dead = ~alive[drop]
    assert dead.any()
    # frozen: every dead tick's pose equals the pose at the drop tick
    for t in range(drop, rejoin):
        np.testing.assert_array_equal(q[t][dead], q[drop][dead])
    # moves again after rejoin (the control law pulls it toward its point)
    assert not np.array_equal(q[rejoin + 30][dead], q[drop][dead])
    # dead observables: no distcmd, no CA activity
    assert (vel_dead[drop:rejoin][:, dead] == 0.0).all()
    assert not np.asarray(m.ca_active)[drop:rejoin][:, dead].any()


def test_lossy_links_hold_last_value():
    """link_loss=1 between all pairs: the flood delivers nothing, so every
    off-diagonal estimate stays the startup census (hold-last-value) and
    its age grows monotonically; loss=0 floods normally."""
    n, T = 5, 40
    states, forms, _, _, sp = _problem(1, n, seed=7, localization=True)
    cfg = sim.SimConfig(assignment="none", localization="flooded")
    loss = jnp.ones((n, n)) - jnp.eye(n)
    sched = faults.FaultSchedule(
        drop_tick=jnp.full((n,), faults.NEVER, jnp.int32),
        rejoin_tick=jnp.full((n,), faults.NEVER, jnp.int32),
        link_loss=loss.astype(states[0].swarm.q.dtype),
        key=jnp.zeros((2,), jnp.uint32))
    st = states[0].replace(faults=sched)
    final, _ = sim.rollout(st, forms[0], ControlGains(), sp, cfg, T)
    age = np.asarray(final.loc.age)
    off = ~np.eye(n, dtype=bool)
    census = np.asarray(states[0].loc.est)
    # nothing ever delivered: ages reach T everywhere off-diagonal and the
    # estimates are still the startup census
    assert (age[off] == T).all()
    np.testing.assert_array_equal(np.asarray(final.loc.est)[off],
                                  census[off])
    # control: loss=0 actually floods (ages bounded by the flood period)
    nf = faults.no_faults(n, states[0].swarm.q.dtype)
    final0, _ = sim.rollout(states[0].replace(faults=nf), forms[0],
                            ControlGains(), sp, cfg, T)
    assert (np.asarray(final0.loc.age)[off] < T).all()


def test_link_draws_reproducible_and_seeded():
    p = 0.5
    n = 8
    s1 = faults.sample_schedule(1, n, link_loss=p)
    s2 = faults.sample_schedule(1, n, link_loss=p)
    s3 = faults.sample_schedule(2, n, link_loss=p)
    a = np.asarray(faults.link_up_at(s1, 17))
    assert np.array_equal(a, np.asarray(faults.link_up_at(s2, 17)))
    assert not np.array_equal(a, np.asarray(faults.link_up_at(s1, 18)))
    assert not np.array_equal(a, np.asarray(faults.link_up_at(s3, 17)))
    # diagonal never lossy in sampled specs
    assert np.asarray(faults.link_up_at(s1, 17))[np.eye(n, dtype=bool)].all()


# --------------------------------------------------------------------------
# 4. recovery observability (sim.summary)
# --------------------------------------------------------------------------

def test_recovery_clock_matches_host_recompute():
    """Device recovery clock == host recomputation over the per-tick
    fault_event/conv/reassigned bools, across a chunk boundary."""
    B, n, T, W = 2, 6, 150, 20
    scheds = [faults.sample_schedule(20 + b, n, dropout_frac=0.34,
                                     drop_tick=40, rejoin_tick=100,
                                     dtype=jnp.float64)
              for b in range(B)]
    states, forms, bstate, bform, sp = _problem(B, n, seed=8,
                                                scheds=scheds)
    cfg = sim.SimConfig(assignment="auction", assign_every=50)
    carry = sumlib.init_carry(n, W, dtype=jnp.float64, batch=B)
    chunks = []
    for _ in range(2):
        bstate, carry, summ = sumlib.batched_rollout_summary(
            bstate, carry, bform, ControlGains(), sp, cfg, T // 2,
            None, 0, window=W, takeoff_alt=2.0)
        chunks.append(jax.tree.map(np.asarray, summ))
    cat = lambda name: np.concatenate(
        [getattr(c, name) for c in chunks], axis=1)
    ev, conv, re = cat("fault_event"), cat("conv_all"), cat("reassigned")
    rec, chn = cat("recovery_ticks"), cat("fault_churn")
    for b in range(B):
        pending, since, churn = False, 0, 0
        for t in range(T):
            since = 0 if ev[b, t] else since + 1
            churn = 0 if ev[b, t] else churn + int(re[b, t])
            pending = pending or bool(ev[b, t])
            done = (pending and bool(conv[b, t]) and not bool(ev[b, t])
                    and since >= W)   # full-window gate (`_recovery_clock`)
            assert rec[b, t] == (since if done else -1), (b, t)
            assert chn[b, t] == (churn if done else -1), (b, t)
            if done:
                pending = False
        # two fault events surfaced (drop + rejoin)
        assert ev[b].sum() == 2


def test_summary_without_faults_has_none_fields():
    n, T, W = 6, 60, 20
    states, forms, _, _, sp = _problem(1, n, seed=9)
    cfg = sim.SimConfig(assignment="auction", assign_every=60)
    _, m = sim.rollout(states[0], forms[0], ControlGains(), sp, cfg, T)
    carry = sumlib.init_carry(n, W, dtype=jnp.float64)
    summ, _ = sumlib.summarize_chunk(m, carry, W, 2.0)
    assert summ.recovery_ticks is None and summ.fault_event is None
    assert summ.n_alive is None and summ.fault_churn is None


# --------------------------------------------------------------------------
# 5. guard rails
# --------------------------------------------------------------------------

def test_flooded_with_faults_needs_localization_tables():
    """The satellite check: flooded + FaultSchedule without
    init_state(..., localization=True) raises the fault-specific error."""
    n = 5
    states, forms, _, _, sp = _problem(1, n, seed=10)
    cfg = sim.SimConfig(assignment="none", localization="flooded")
    st = states[0].replace(faults=faults.no_faults(n))
    with pytest.raises(ValueError, match="FaultSchedule"):
        sim.step(st, forms[0], ControlGains(), sp, cfg)
    # and the pre-existing flooded check still fires without faults
    with pytest.raises(ValueError, match="localization=True"):
        sim.step(states[0], forms[0], ControlGains(), sp, cfg)


def test_sample_schedule_validates_rejoin():
    with pytest.raises(ValueError, match="rejoin_tick"):
        faults.sample_schedule(0, 4, dropout_frac=0.5, drop_tick=10,
                               rejoin_tick=10)


# --------------------------------------------------------------------------
# 6. batch-scale sweep (slow tier)
# --------------------------------------------------------------------------

@pytest.mark.slow
def test_fault_sweep_b8_batched_matches_serial():
    """A B=8 wave of distinct dropout/link-loss scripts through the
    batched engine == 8 serial rollouts (the faults_suite sweep shape)."""
    B, n, T = 8, 6, 130
    rng = np.random.default_rng(0)
    scheds = []
    for b in range(B):
        scheds.append(faults.sample_schedule(
            b, n, dropout_frac=float(rng.choice([0.0, 0.17, 0.34])),
            drop_tick=30, rejoin_tick=int(rng.integers(70, 110)),
            link_loss=float(rng.choice([0.0, 0.3])), dtype=jnp.float64))
    states, forms, bstate, bform, sp = _problem(B, n, seed=11,
                                                localization=True,
                                                scheds=scheds)
    cfg = sim.SimConfig(assignment="cbaa", assign_every=60,
                        localization="flooded")
    bf, bm = sim.batched_rollout(bstate, bform, ControlGains(), sp, cfg, T)
    for b in range(B):
        fs, ms = sim.rollout(states[b], forms[b], ControlGains(), sp,
                             cfg, T)
        for name in METRIC_FIELDS + ("alive",):
            np.testing.assert_array_equal(
                np.asarray(getattr(ms, name)),
                np.asarray(getattr(bm, name))[:, b], (b, name))
    _assert_valid_perms(bm.v2f)
