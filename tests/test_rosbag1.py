"""Pure-Python rosbag v2.0 ingestion tests (`readACLBag.m` /
`review_bag.py` parity without ROS): writer/reader round-trips, bz2
chunks, and a synthetic hardware bag replayed end-to-end through the
`harness.review` FSM."""
import bz2
import struct

import numpy as np
import pytest

from aclswarm_tpu.harness import review, rosbag1
from aclswarm_tpu.harness.supervisor import NAMES

VEHS = ["SQ01s", "SQ02s", "SQ03s", "SQ04s"]


class TestRecordLayer:
    def test_serializer_roundtrips(self):
        stamp, pos = rosbag1.des_pose_stamped(
            rosbag1.ser_pose_stamped(1.25, [1.0, -2.0, 3.5],
                                     frame_id="world"))
        assert stamp == 1.25
        np.testing.assert_allclose(pos, [1.0, -2.0, 3.5])

        stamp, vec = rosbag1.des_vector3_stamped(
            rosbag1.ser_vector3_stamped(0.5, [0.1, 0.2, -0.3]))
        np.testing.assert_allclose(vec, [0.1, 0.2, -0.3])

        stamp, ca = rosbag1.des_safety_status(
            rosbag1.ser_safety_status(2.0, True))
        assert stamp == 2.0 and ca is True

        perm = np.array([2, 0, 3, 1], np.uint8)
        np.testing.assert_array_equal(
            rosbag1.des_uint8_multiarray(
                rosbag1.ser_uint8_multiarray(perm)), perm)

    def test_multiarray_decode_with_layout_dims(self):
        """Real publishers may fill layout.dim; the decoder must skip it
        (the reference publishes the assignment with an empty layout but
        other tools do not)."""
        label = b"len"
        body = (struct.pack("<I", 1)                       # one dim
                + struct.pack("<I", len(label)) + label
                + struct.pack("<II", 4, 1)                 # size, stride
                + struct.pack("<I", 0)                     # data_offset
                + struct.pack("<I", 4) + bytes([3, 1, 0, 2]))
        np.testing.assert_array_equal(
            rosbag1.des_uint8_multiarray(body), [3, 1, 0, 2])

    def test_write_read_roundtrip(self, tmp_path):
        path = tmp_path / "mini.bag"
        with rosbag1.BagWriter(path) as bag:
            bag.write("/SQ01s/world", "geometry_msgs/PoseStamped", 0.0,
                      rosbag1.ser_pose_stamped(0.0, [1, 2, 3]))
            bag.write("/SQ01s/assignment", "std_msgs/UInt8MultiArray",
                      0.5, rosbag1.ser_uint8_multiarray([1, 0]))
            bag.write("/SQ01s/world", "geometry_msgs/PoseStamped", 1.0,
                      rosbag1.ser_pose_stamped(1.0, [4, 5, 6]))
        msgs = list(rosbag1.read_bag(path))
        assert [m.topic for m in msgs] == ["/SQ01s/world",
                                           "/SQ01s/assignment",
                                           "/SQ01s/world"]
        assert msgs[0].msgtype == "geometry_msgs/PoseStamped"
        assert msgs[2].time == 1.0
        _, pos = rosbag1.des_pose_stamped(msgs[2].raw)
        np.testing.assert_allclose(pos, [4, 5, 6])

    def test_bz2_chunk(self, tmp_path):
        """Real hardware bags often record with bz2 chunk compression —
        rewrap the writer's uncompressed chunk and re-read."""
        path = tmp_path / "plain.bag"
        with rosbag1.BagWriter(path) as bag:
            bag.write("/SQ01s/world", "geometry_msgs/PoseStamped", 0.25,
                      rosbag1.ser_pose_stamped(0.25, [7, 8, 9]))
        raw = path.read_bytes()
        # locate the chunk record after the padded 4096-byte bag header
        off = len(rosbag1.MAGIC) + 4096
        header, chunk_data, end = rosbag1._read_record(raw, off)
        assert header["compression"] == b"none"
        comp = bz2.compress(chunk_data)
        new_hdr = rosbag1._pack_header({
            "op": bytes([rosbag1.OP_CHUNK]),
            "compression": b"bz2",
            "size": struct.pack("<I", len(chunk_data))})
        rewrapped = (raw[:off]
                     + struct.pack("<I", len(new_hdr)) + new_hdr
                     + struct.pack("<I", len(comp)) + comp
                     + raw[end:])
        path2 = tmp_path / "bz2.bag"
        path2.write_bytes(rewrapped)
        msgs = list(rosbag1.read_bag(path2))
        assert len(msgs) == 1
        _, pos = rosbag1.des_pose_stamped(msgs[0].raw)
        np.testing.assert_allclose(pos, [7, 8, 9])


def _write_trial_bag(path, T=1500, n=4, dt=0.02, takeoff_alt=1.0):
    """A synthetic hardware flight at the reviewer's 50 Hz: ground start,
    takeoff ramp, auctions from 8 s, convergence at 14 s — the
    happy-path signal shape of `test_review.py::_synthetic_metrics`, as
    actual bag topic traffic."""
    t = np.arange(T)
    z = np.clip((t - 50) * 0.01, 0.0, takeoff_alt)
    with rosbag1.BagWriter(path) as bag:
        prev = None
        for k in range(T):
            tk = 100.0 + k * dt          # hardware bags start at wall time
            for i, veh in enumerate(VEHS):
                bag.write(f"/{veh}/world", "geometry_msgs/PoseStamped",
                          tk, rosbag1.ser_pose_stamped(
                              tk, [2.0 * i, 0.0, z[k]]))
                dn = 2.0 if k <= 700 else 0.1
                bag.write(f"/{veh}/distcmd",
                          "geometry_msgs/Vector3Stamped", tk,
                          rosbag1.ser_vector3_stamped(tk, [dn, 0, 0]))
                bag.write(f"/{veh}/safety/status",
                          "aclswarm_msgs/SafetyStatus", tk,
                          rosbag1.ser_safety_status(tk, False))
            if k >= 400 and (k - 400) % 60 == 0:
                perm = [1, 0, 2, 3] if k == 400 else [1, 0, 2, 3]
                bag.write(f"/{VEHS[0]}/assignment",
                          "std_msgs/UInt8MultiArray", tk,
                          rosbag1.ser_uint8_multiarray(perm))
                prev = perm
    return str(path)


class TestBagToRecording:
    def test_streams_resampled(self, tmp_path):
        bag = _write_trial_bag(tmp_path / "trial.bag", T=200)
        rec = rosbag1.bag_to_recording(bag)
        assert rec["q"].shape[1] == 4
        # sample-and-hold poses: z follows the takeoff ramp
        assert rec["q"][0, 0, 2] == 0.0
        assert rec["q"][-1, 0, 2] > 0.9
        assert rec["distcmd_norm"][10, 2] == 2.0
        assert not rec["ca_active"].any()

    def test_hardware_bag_reviews_complete(self, tmp_path):
        """The round-5 done-criterion: a synthetic .bag replayed
        end-to-end through `harness.review`'s FSM — `review.launch` +
        `review_bag.py` parity with zero ROS."""
        bag = _write_trial_bag(tmp_path / "trial.bag")
        fsm = review.review(bag, n_formations=1, takeoff_alt=1.0)
        assert fsm.completed, NAMES[fsm.state]
        assert 0.0 < fsm.times[0] < 20.0

    def test_npz_export_reimport(self, tmp_path):
        """recording npz -> .bag -> recording: the writer is the
        reader's inverse on the signals the FSM consumes."""
        bag = _write_trial_bag(tmp_path / "trial.bag", T=300)
        rec = rosbag1.bag_to_recording(
            bag, out_npz=tmp_path / "trial.npz")
        back_bag = rosbag1.recording_to_bag(tmp_path / "trial.npz",
                                            tmp_path / "back.bag",
                                            vehs=VEHS)
        rec2 = rosbag1.bag_to_recording(back_bag)
        np.testing.assert_allclose(rec2["q"], rec["q"], atol=1e-9)
        np.testing.assert_allclose(rec2["distcmd_norm"],
                                   rec["distcmd_norm"], atol=1e-9)
        np.testing.assert_array_equal(rec2["auctioned"], rec["auctioned"])


class TestReviewFixes:
    def test_index_only_connections(self, tmp_path):
        """Standard bags keep connection records only in the post-chunk
        index section — messages inside chunks must still resolve."""
        path = tmp_path / "idx.bag"
        with rosbag1.BagWriter(path) as bag:
            bag.write("/SQ01s/world", "geometry_msgs/PoseStamped", 0.0,
                      rosbag1.ser_pose_stamped(0.0, [1, 2, 3]))
        raw = bytearray(path.read_bytes())
        # strip the in-chunk connection record, keeping the index copy:
        # re-walk the chunk and rebuild it with only the message record
        off = len(rosbag1.MAGIC) + 4096
        header, chunk, end = rosbag1._read_record(bytes(raw), off)
        h2, _, inner_off = rosbag1._read_record(chunk, 0)
        assert h2["op"][0] == rosbag1.OP_CONNECTION
        new_chunk = chunk[inner_off:]            # message record only
        new_hdr = rosbag1._pack_header({
            "op": bytes([rosbag1.OP_CHUNK]), "compression": b"none",
            "size": struct.pack("<I", len(new_chunk))})
        rebuilt = (bytes(raw[:off])
                   + struct.pack("<I", len(new_hdr)) + new_hdr
                   + struct.pack("<I", len(new_chunk)) + new_chunk
                   + bytes(raw[end:]))           # index section intact
        path2 = tmp_path / "idx2.bag"
        path2.write_bytes(rebuilt)
        msgs = list(rosbag1.read_bag(path2))
        assert len(msgs) == 1 and msgs[0].topic == "/SQ01s/world"

    def test_wide_assignment_export_n300(self, tmp_path):
        """n > 255 recordings export as Int32MultiArray — uint8 would
        silently wrap indices into a non-permutation."""
        n, ticks = 300, 4
        rng = np.random.default_rng(11)
        perm = rng.permutation(n).astype(np.int32)
        rec = {
            "q": np.zeros((ticks, n, 3)),
            "distcmd_norm": np.zeros((ticks, n)),
            "ca_active": np.zeros((ticks, n), bool),
            "reassigned": np.array([False, True, False, False]),
            "auctioned": np.array([False, True, False, False]),
            "assign_valid": np.ones(ticks, bool),
            "mode": np.zeros((ticks, n), np.int32),
            "v2f": np.tile(perm, (ticks, 1)),
            "dt": np.asarray(0.02),
        }
        npz = tmp_path / "n300.npz"
        np.savez_compressed(npz, **rec)
        bag = rosbag1.recording_to_bag(npz, tmp_path / "n300.bag")
        back = rosbag1.bag_to_recording(bag)
        k = np.argmax(back["auctioned"])
        np.testing.assert_array_equal(back["v2f"][k], perm)
        assert int(back["v2f"][k].max()) == n - 1

    def test_uint8_serializer_guards_wrap(self):
        import pytest
        with pytest.raises(ValueError):
            rosbag1.ser_uint8_multiarray(np.arange(300))


def _write_throttled_bag(path, T=200, n=3, dt=0.02, with_tags=False,
                         missing_safety_veh=None):
    """A real-flight-shaped bag: `bag_record.sh` records the throttled
    signal topics (`status_throttle` / `distcmd_throttle`) and the
    anchor-tag poses `/Tag01/world` / `/Tag02/world`."""
    vehs = [f"SQ{i + 1:02d}s" for i in range(n)]
    with rosbag1.BagWriter(path) as bag:
        for k in range(T):
            tk = 50.0 + k * dt
            for i, veh in enumerate(vehs):
                bag.write(f"/{veh}/world", "geometry_msgs/PoseStamped",
                          tk, rosbag1.ser_pose_stamped(tk, [i, 0.0, 1.0]))
                if veh != missing_safety_veh:
                    bag.write(f"/{veh}/safety/status_throttle",
                              "aclswarm_msgs/SafetyStatus", tk,
                              rosbag1.ser_safety_status(tk, i == 0))
                bag.write(f"/{veh}/distcmd_throttle",
                          "geometry_msgs/Vector3Stamped", tk,
                          rosbag1.ser_vector3_stamped(tk, [1.5, 0, 0]))
            if with_tags:
                for tag in ("Tag01", "Tag02"):
                    bag.write(f"/{tag}/world",
                              "geometry_msgs/PoseStamped", tk,
                              rosbag1.ser_pose_stamped(tk, [9.0, 9.0, 0.0]))
            if k % 50 == 0:
                bag.write(f"/{vehs[0]}/assignment",
                          "std_msgs/UInt8MultiArray", tk,
                          rosbag1.ser_uint8_multiarray(
                              np.arange(n, dtype=np.uint8)))
    return str(path)


class TestRealFlightBagFixes:
    """ADVICE r5: the reader must score *real* hardware bags, whose topic
    names and anchor-tag traffic differ from the synthetic fixtures."""

    def test_throttled_topic_names_resolve(self, tmp_path):
        """/safety/status_throttle and /distcmd_throttle (bag_record.sh
        names; review_bag.py:90 subscribes the former) must feed the
        signals instead of silently defaulting to converged-and-blind."""
        bag = _write_throttled_bag(tmp_path / "hw.bag")
        rec = rosbag1.bag_to_recording(bag)
        assert rec["q"].shape[1] == 3
        assert rec["ca_active"][10:, 0].all()        # throttled safety
        assert not rec["ca_active"][:, 1].any()
        assert np.all(rec["distcmd_norm"][10:] > 1.0)  # throttled distcmd

    def test_anchor_tags_do_not_inflate_n(self, tmp_path):
        """/Tag01/world-style anchor topics carry poses only — they must
        not be discovered as vehicles (n would inflate and the
        perm.size == n check would reject every assignment)."""
        bag = _write_throttled_bag(tmp_path / "tags.bag", with_tags=True)
        with pytest.warns(UserWarning, match="Tag01"):
            rec = rosbag1.bag_to_recording(bag)
        assert rec["q"].shape[1] == 3
        # assignments still resolve against the un-inflated n
        assert rec["auctioned"].any()
        k = int(np.argmax(rec["auctioned"]))
        np.testing.assert_array_equal(rec["v2f"][k], np.arange(3))

    def test_missing_stream_warns_not_silent(self, tmp_path):
        """A vehicle with no safety stream gets a UserWarning — defaults
        make the FSM blind to gridlock, which is a wrong verdict."""
        bag = _write_throttled_bag(tmp_path / "gap.bag",
                                   missing_safety_veh="SQ02s")
        with pytest.warns(UserWarning, match="SQ02s has no safety"):
            rec = rosbag1.bag_to_recording(bag)
        assert not rec["ca_active"][:, 1].any()      # default, but loud

    def test_assignment_size_mismatch_warns(self, tmp_path):
        """A real vehicle whose signal streams were ALL lost looks like
        an anchor tag to discovery — the recorded assignment permutation
        length is the cross-check, and the mismatch must be loud."""
        path = tmp_path / "lost.bag"
        vehs = ["SQ01s", "SQ02s", "SQ03s"]
        with rosbag1.BagWriter(path) as bag:
            for k in range(80):
                tk = k * 0.02
                for i, veh in enumerate(vehs):
                    bag.write(f"/{veh}/world", "geometry_msgs/PoseStamped",
                              tk, rosbag1.ser_pose_stamped(tk, [i, 0, 1.0]))
                    if veh != "SQ03s":      # SQ03s lost every signal topic
                        bag.write(f"/{veh}/distcmd",
                                  "geometry_msgs/Vector3Stamped", tk,
                                  rosbag1.ser_vector3_stamped(tk, [1, 0, 0]))
                if k == 40:                 # fleet-size-3 assignment
                    bag.write("/SQ01s/assignment",
                              "std_msgs/UInt8MultiArray", tk,
                              rosbag1.ser_uint8_multiarray([2, 0, 1]))
        with pytest.warns(UserWarning, match="assignment permutations"):
            rec = rosbag1.bag_to_recording(path)
        assert rec["q"].shape[1] == 2       # SQ03s dropped (documented)
        # explicit vehs override recovers the full fleet
        rec = rosbag1.bag_to_recording(path, vehs=vehs)
        assert rec["q"].shape[1] == 3
        k = int(np.argmax(rec["auctioned"]))
        np.testing.assert_array_equal(rec["v2f"][k], [2, 0, 1])

    def test_pose_only_bag_still_reads(self, tmp_path):
        """No vehicle traffic at all (synthetic pose-only fixtures): fall
        back to world-prefix discovery instead of an empty vehicle set."""
        path = tmp_path / "poses.bag"
        with rosbag1.BagWriter(path) as bag:
            for k in range(60):
                tk = k * 0.02
                bag.write("/SQ01s/world", "geometry_msgs/PoseStamped",
                          tk, rosbag1.ser_pose_stamped(tk, [0, 0, 1.0]))
        rec = rosbag1.bag_to_recording(path)
        assert rec["q"].shape[1] == 1

    def test_decimated_export_keeps_assignment_events(self, tmp_path):
        """recording_to_bag(pose_every=4): auctioned events on ticks not
        divisible by 4 must still land in the exported bag."""
        n, ticks = 4, 40
        auction_ticks = [3, 17, 30]                  # none divisible by 4
        rec = {
            "q": np.zeros((ticks, n, 3)),
            "distcmd_norm": np.zeros((ticks, n)),
            "ca_active": np.zeros((ticks, n), bool),
            "reassigned": np.zeros(ticks, bool),
            "auctioned": np.zeros(ticks, bool),
            "assign_valid": np.ones(ticks, bool),
            "mode": np.zeros((ticks, n), np.int32),
            "v2f": np.tile(np.arange(n, dtype=np.int32), (ticks, 1)),
            "dt": np.asarray(0.02),
        }
        for k in auction_ticks:
            rec["auctioned"][k] = True
            rec["reassigned"][k] = True
        npz = tmp_path / "dec.npz"
        np.savez_compressed(npz, **rec)
        bag = rosbag1.recording_to_bag(npz, tmp_path / "dec.bag",
                                       vehs=VEHS, pose_every=4)
        msgs = [m for m in rosbag1.read_bag(bag)
                if m.topic.endswith("/assignment")]
        assert len(msgs) == len(auction_ticks)
        got = sorted(round(m.time / 0.02) for m in msgs)
        assert got == auction_ticks
