"""Assignment solver tests.

Follows the reference's test strategy (SURVEY.md §4): golden/oracle
cross-checks (lapjv vs scipy vs brute force; auction/sinkhorn vs lapjv) and
algorithm-level scenario tests modeled on
`aclswarm/matlab/CBAA/test_CBAA_aclswarm.m` (recover an obvious matching,
adversarial swapped configurations, random permutations).
"""
import itertools

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from aclswarm_tpu.assignment import (assign_min_dist, auction_lap,
                                     cbaa_assign, cbaa_from_state, lapjv,
                                     round_dominant, round_parallel,
                                     round_to_permutation, sinkhorn_assign,
                                     two_opt_refine)
from aclswarm_tpu.assignment import cbaa
from aclswarm_tpu.core import geometry, perm


def brute_force_min(cost):
    n = cost.shape[0]
    best, best_p = np.inf, None
    for p in itertools.permutations(range(n)):
        c = cost[np.arange(n), list(p)].sum()
        if c < best:
            best, best_p = c, np.array(p)
    return best, best_p


class TestLapjv:
    def test_vs_brute_force(self):
        rng = np.random.default_rng(0)
        for _ in range(20):
            n = int(rng.integers(2, 7))
            C = rng.normal(size=(n, n))
            r = lapjv(C)
            best, _ = brute_force_min(C)
            assert C[np.arange(n), r].sum() == pytest.approx(best, abs=1e-9)

    def test_vs_scipy(self):
        scipy_opt = pytest.importorskip("scipy.optimize")
        rng = np.random.default_rng(1)
        for _ in range(50):
            n = int(rng.integers(2, 40))
            C = rng.normal(size=(n, n)) * 10
            r = lapjv(C)
            ri, ci = scipy_opt.linear_sum_assignment(C)
            assert C[np.arange(n), r].sum() == pytest.approx(
                C[ri, ci].sum(), abs=1e-8)


class TestAuction:
    @pytest.mark.slow
    def test_optimal_cost_vs_lapjv(self):
        rng = np.random.default_rng(2)
        for trial in range(10):
            n = int(rng.integers(3, 30))
            C = rng.normal(size=(n, n)) * 5
            res = auction_lap(jnp.asarray(-C), eps_min=1e-6)
            r = np.asarray(res.row_to_col)
            assert perm.is_valid(jnp.asarray(r))
            opt = C[np.arange(n), lapjv(C)].sum()
            got = C[np.arange(n), r].sum()
            # auction guarantee: within n * eps_min of optimal
            assert got <= opt + n * 1e-5

    def test_assign_min_dist_recovers_obvious(self):
        # vehicles sitting exactly on distinct formation points
        rng = np.random.default_rng(3)
        n = 12
        p = rng.normal(size=(n, 3)) * 5
        true = rng.permutation(n)
        q = p[true]
        v2f = assign_min_dist(jnp.asarray(q), jnp.asarray(p))
        np.testing.assert_array_equal(np.asarray(v2f), true)

    def test_jit(self):
        rng = np.random.default_rng(4)
        C = jnp.asarray(rng.normal(size=(8, 8)))
        f = jax.jit(lambda b: auction_lap(b).row_to_col)
        r = f(C)
        assert perm.is_valid(r)


class TestSinkhorn:
    @pytest.mark.slow
    def test_valid_permutation_always(self):
        rng = np.random.default_rng(5)
        for _ in range(5):
            n = int(rng.integers(3, 25))
            q = rng.normal(size=(n, 3))
            p = rng.normal(size=(n, 3))
            res = sinkhorn_assign(jnp.asarray(q), jnp.asarray(p))
            assert bool(perm.is_valid(res.row_to_col))

    def test_near_optimal_on_separated_instances(self):
        # well-separated instances: sinkhorn must match the exact solver
        rng = np.random.default_rng(6)
        n = 15
        p = rng.normal(size=(n, 3)) * 10
        true = rng.permutation(n)
        q = p[true] + rng.normal(size=(n, 3)) * 0.05
        res = sinkhorn_assign(jnp.asarray(q), jnp.asarray(p))
        np.testing.assert_array_equal(np.asarray(res.row_to_col), true)

    def test_cost_gap_vs_exact(self):
        rng = np.random.default_rng(7)
        n = 20
        q = rng.normal(size=(n, 3)) * 3
        p = rng.normal(size=(n, 3)) * 3
        cost = np.linalg.norm(q[:, None] - p[None, :], axis=-1)
        opt = cost[np.arange(n), lapjv(cost)].sum()
        res = sinkhorn_assign(jnp.asarray(q), jnp.asarray(p))
        got = cost[np.arange(n), np.asarray(res.row_to_col)].sum()
        assert got <= opt * 1.10 + 1e-6  # fast path: within 10% of exact


class TestCBAA:
    def test_recovers_obvious_matching_complete_graph(self):
        # swarm standing exactly on formation points, scrambled: CBAA must
        # find the ground-truth matching (test_CBAA_aclswarm.m scenario 1)
        rng = np.random.default_rng(8)
        n = 8
        p = rng.normal(size=(n, 3)) * 5
        true = rng.permutation(n).astype(np.int32)
        q = p[true]  # vehicle v at point true[v]
        adj = jnp.asarray(np.ones((n, n)) - np.eye(n))
        paligned = jnp.broadcast_to(jnp.asarray(p), (n, n, 3))
        res = cbaa_assign(jnp.asarray(q), paligned, adj, perm.identity(n))
        assert bool(res.valid)
        np.testing.assert_array_equal(np.asarray(res.v2f), true)

    def test_agreement_and_validity_random(self):
        rng = np.random.default_rng(9)
        for trial in range(5):
            n = int(rng.integers(4, 12))
            p = rng.normal(size=(n, 3)) * 4
            q = rng.normal(size=(n, 3)) * 4
            adj = jnp.asarray(np.ones((n, n)) - np.eye(n))
            paligned = jnp.broadcast_to(jnp.asarray(p), (n, n, 3))
            res = cbaa_assign(jnp.asarray(q), paligned, adj, perm.identity(n))
            assert bool(res.valid), f"trial {trial}: CBAA did not converge"
            # consensus: every agent's who-table identical
            assert bool(jnp.all(res.who == res.who[0][None, :]))

    def test_price_semantics_match_reference(self):
        # price = 1/(dist + 1e-8): the winning bid for each task must be the
        # price of the vehicle assigned to it (auctioneer.cpp:546-549)
        rng = np.random.default_rng(10)
        n = 6
        p = rng.normal(size=(n, 3))
        q = rng.normal(size=(n, 3))
        adj = jnp.asarray(np.ones((n, n)) - np.eye(n))
        paligned = jnp.broadcast_to(jnp.asarray(p), (n, n, 3))
        res = cbaa_assign(jnp.asarray(q), paligned, adj, perm.identity(n))
        assert bool(res.valid)
        f2v = np.asarray(res.f2v)
        d = np.linalg.norm(np.asarray(q)[f2v] - np.asarray(p), axis=-1)
        np.testing.assert_allclose(np.asarray(res.price[0]),
                                   1.0 / (d + 1e-8), rtol=1e-6)

    def test_full_pipeline_with_local_alignment(self):
        # end-to-end start(): local alignment then auction, on a rotated+
        # translated swarm in formation shape -> recovers correspondence
        rng = np.random.default_rng(11)
        n = 6
        th = np.linspace(0, 2 * np.pi, n, endpoint=False)
        p = np.stack([np.cos(th), np.sin(th), np.ones(n)], 1)
        c, s = np.cos(1.1), np.sin(1.1)
        R = np.array([[c, -s], [s, c]])
        qf = p.copy()
        qf[:, :2] = p[:, :2] @ R.T + [4.0, 2.0]
        true = rng.permutation(n).astype(np.int32)
        q = qf[true]
        adj = jnp.asarray(np.ones((n, n)) - np.eye(n))
        res = cbaa_from_state(jnp.asarray(q), jnp.asarray(p), adj,
                              perm.identity(n))
        assert bool(res.valid)
        # the hexagon is rotationally symmetric and the alignment runs off
        # the stale (identity) assignment, exactly like the reference — so
        # the result is the ground truth composed with a formation symmetry.
        # Require congruence: swarm in formation order matches the formation
        # shape exactly.
        q_fs = np.asarray(perm.veh_to_formation_order(jnp.asarray(q),
                                                      res.v2f))
        np.testing.assert_allclose(
            np.asarray(geometry.pdistmat(jnp.asarray(q_fs))),
            np.asarray(geometry.pdistmat(jnp.asarray(p))), atol=1e-6)

    def test_noncomplete_graph_converges(self):
        # ring + chords graph (diameter 2-ish): still reaches consensus
        rng = np.random.default_rng(12)
        n = 8
        adj = np.zeros((n, n))
        for i in range(n):
            for dj in (1, 2, 3):
                j = (i + dj) % n
                adj[i, j] = adj[j, i] = 1
        p = rng.normal(size=(n, 3)) * 5
        true = rng.permutation(n).astype(np.int32)
        q = p[true]
        paligned = jnp.broadcast_to(jnp.asarray(p), (n, n, 3))
        res = cbaa_assign(jnp.asarray(q), paligned, jnp.asarray(adj),
                          perm.identity(n))
        assert bool(res.valid)
        np.testing.assert_array_equal(np.asarray(res.v2f), true)


class TestParallelRounding:
    """`round_parallel` — the n=1000 fast path replacing sequential greedy."""

    def test_always_valid_permutation(self):
        rng = np.random.default_rng(0)
        for n in (3, 8, 40):
            for seed in range(5):
                plan = jnp.asarray(
                    np.random.default_rng(seed).normal(size=(n, n)))
                out = np.asarray(round_parallel(plan))
                assert sorted(out.tolist()) == list(range(n)), (n, seed)

    def test_matches_greedy_on_sharp_plans(self):
        # with a concentrated plan (one dominant entry per row/col), both
        # roundings recover the underlying permutation exactly
        rng = np.random.default_rng(1)
        n = 30
        true = rng.permutation(n)
        plan = rng.normal(size=(n, n)) * 0.01
        plan[np.arange(n), true] += 10.0
        par = np.asarray(round_parallel(jnp.asarray(plan)))
        seq = np.asarray(round_to_permutation(jnp.asarray(plan)))
        np.testing.assert_array_equal(par, true)
        np.testing.assert_array_equal(seq, true)

    def test_quality_near_lapjv(self):
        # on random smooth costs through the full sinkhorn path, parallel
        # rounding stays within a few percent of the exact optimum
        rng = np.random.default_rng(2)
        n = 60
        q = jnp.asarray(rng.normal(size=(n, 3)) * 5)
        p = jnp.asarray(rng.normal(size=(n, 3)) * 5)
        res = sinkhorn_assign(q, p, rounding="parallel")
        cost = np.linalg.norm(np.asarray(q)[:, None]
                              - np.asarray(p)[None, :], axis=-1)
        opt = cost[np.arange(n), lapjv(cost)].sum()
        got = cost[np.arange(n), np.asarray(res.row_to_col)].sum()
        assert sorted(np.asarray(res.row_to_col).tolist()) == list(range(n))
        assert got <= opt * 1.05, (got, opt)


class TestDominantRoundingAndRefine:
    def test_dominant_equals_sequential_greedy(self):
        # Preis's locally-dominant matching must reproduce the sequential
        # global-greedy matching exactly, for any score matrix
        for seed in range(6):
            rng = np.random.default_rng(400 + seed)
            n = 25
            plan = jnp.asarray(rng.normal(size=(n, n)))
            dom = np.asarray(round_dominant(plan))
            seq = np.asarray(round_to_permutation(plan))
            np.testing.assert_array_equal(dom, seq)

    def test_two_opt_improves_and_stays_valid(self):
        rng = np.random.default_rng(5)
        n = 40
        cost = jnp.asarray(rng.uniform(0, 10, size=(n, n)))
        v0 = jnp.asarray(rng.permutation(n).astype(np.int32))
        v1 = two_opt_refine(cost, v0, sweeps=30)
        v1np = np.asarray(v1)
        assert sorted(v1np.tolist()) == list(range(n))
        c = np.asarray(cost)
        before = c[np.arange(n), np.asarray(v0)].sum()
        after = c[np.arange(n), v1np].sum()
        assert after <= before
        # 2-opt is a *repair* step: from a random start on unstructured
        # costs it only guarantees monotone improvement to a swap-stable
        # point (quality from good starts is covered by
        # test_full_fast_path_quality); just require real progress here
        assert after <= 0.8 * before

    def test_full_fast_path_quality(self):
        # sinkhorn + dominant + 2-opt on a hard random instance
        rng = np.random.default_rng(6)
        n = 80
        q = jnp.asarray(rng.normal(size=(n, 3)) * 5)
        p = jnp.asarray(rng.normal(size=(n, 3)) * 5)
        res = sinkhorn_assign(q, p)   # defaults: dominant + refine
        v = np.asarray(res.row_to_col)
        assert sorted(v.tolist()) == list(range(n))
        cost = np.linalg.norm(np.asarray(q)[:, None]
                              - np.asarray(p)[None, :], axis=-1)
        opt = cost[np.arange(n), lapjv(cost)].sum()
        got = cost[np.arange(n), v].sum()
        assert got <= opt * 1.03, (got, opt)


class TestChunkedConsensus:
    """task_block bounds consensus memory at O(n^2 B); results must be
    bit-identical to the dense (n, n, n) form (round-1 review weak #4 —
    the faithful decentralized mode now scales)."""

    def _case(self, seed, n):
        rng = np.random.default_rng(seed)
        q = jnp.asarray(rng.normal(size=(n, 3)) * 5)
        p = jnp.asarray(rng.normal(size=(n, 3)) * 5)
        adj = np.zeros((n, n))
        for i in range(n):
            adj[i, (i + 1) % n] = adj[(i + 1) % n, i] = 1
            adj[i, (i + 2) % n] = adj[(i + 2) % n, i] = 1
        v2f = jnp.asarray(rng.permutation(n), jnp.int32)
        return q, p, jnp.asarray(adj), v2f

    @pytest.mark.parametrize("seed,n,block", [(0, 9, 4), (1, 12, 5),
                                              (2, 15, 16), (3, 10, 1)])
    def test_chunked_equals_dense(self, seed, n, block):
        q, p, adj, v2f = self._case(seed, n)
        dense = cbaa.cbaa_from_state(q, p, adj, v2f)
        chunk = cbaa.cbaa_from_state(q, p, adj, v2f, task_block=block)
        np.testing.assert_array_equal(np.asarray(dense.v2f),
                                      np.asarray(chunk.v2f))
        np.testing.assert_array_equal(np.asarray(dense.who),
                                      np.asarray(chunk.who))
        np.testing.assert_array_equal(np.asarray(dense.price),
                                      np.asarray(chunk.price))
        assert bool(dense.valid) == bool(chunk.valid)

    def test_large_n_smoke(self):
        """n=300 faithful consensus rounds run without the 216-MB dense
        broadcast (a handful of rounds — full consensus is 2n rounds by
        design, the reference's own sequential latency)."""
        q, p, adj, v2f = self._case(5, 300)
        res = cbaa.cbaa_from_state(q, p, adj, v2f, n_iters=6,
                                   task_block=32)
        assert res.who.shape == (300, 300)


class TestCBAAEarlyExit:
    """Fixed-point early exit must be bit-identical to the full 2n-round
    budget: the round map is a deterministic pure function of the tables,
    so once a round changes nothing, no later round can (the budgeted scan
    just replays the fixed point). Only the bulk-synchronous form can see
    this — each reference vehicle only holds its own table
    (`auctioneer.cpp:441-444` counts iterations instead)."""

    @pytest.mark.parametrize("seed,n", [(0, 6), (1, 9), (2, 14), (3, 20)])
    def test_bit_parity_and_fewer_rounds(self, seed, n):
        rng = np.random.default_rng(seed)
        q = jnp.asarray(rng.normal(size=(n, 3)) * 4)
        p = jnp.asarray(rng.normal(size=(n, 3)) * 4)
        adj = np.zeros((n, n))
        for i in range(n):
            adj[i, (i + 1) % n] = adj[(i + 1) % n, i] = 1
            adj[i, (i + 3) % n] = adj[(i + 3) % n, i] = 1
        adj = jnp.asarray(adj)
        v2f = jnp.asarray(rng.permutation(n), jnp.int32)
        fast = cbaa.cbaa_from_state(q, p, adj, v2f, early_exit=True)
        full = cbaa.cbaa_from_state(q, p, adj, v2f, early_exit=False)
        for field in ("v2f", "f2v", "price", "who"):
            np.testing.assert_array_equal(np.asarray(getattr(fast, field)),
                                          np.asarray(getattr(full, field)))
        assert bool(fast.valid) == bool(full.valid)
        assert int(fast.rounds) < int(full.rounds) == 2 * n

    def test_early_exit_with_task_block(self):
        rng = np.random.default_rng(7)
        n = 12
        q = jnp.asarray(rng.normal(size=(n, 3)) * 4)
        p = jnp.asarray(rng.normal(size=(n, 3)) * 4)
        adj = jnp.asarray(np.ones((n, n)) - np.eye(n))
        v2f = jnp.asarray(np.arange(n), jnp.int32)
        fast = cbaa.cbaa_from_state(q, p, adj, v2f, early_exit=True,
                                    task_block=5)
        full = cbaa.cbaa_from_state(q, p, adj, v2f, early_exit=False)
        np.testing.assert_array_equal(np.asarray(fast.price),
                                      np.asarray(full.price))
        np.testing.assert_array_equal(np.asarray(fast.who),
                                      np.asarray(full.who))


class TestCBAAWarmTables:
    """Warm auction tables + hysteresis (ROADMAP item 1's CBAA warm
    start): seeding with `init_tables` is bit-identical to the cold
    auction (warm off is free), a carried fixed point re-converges —
    validly — in fewer rounds, release-at-seed keeps moved-geometry
    re-auctions convergent, and `assign_eps=0` is bitwise today's
    engine while larger eps never reassigns more."""

    def _setup(self, n=10, seed=13):
        rng = np.random.default_rng(seed)
        p = rng.normal(size=(n, 3)) * 5
        q = p + rng.normal(size=(n, 3)) * 0.3
        adj = jnp.asarray(np.ones((n, n)) - np.eye(n))
        paligned = jnp.broadcast_to(jnp.asarray(p), (n, n, 3))
        return jnp.asarray(q), paligned, adj

    def test_init_tables_seed_is_bitwise_cold(self):
        q, paligned, adj = self._setup()
        n = q.shape[0]
        cold = cbaa_assign(q, paligned, adj, perm.identity(n))
        warm = cbaa_assign(q, paligned, adj, perm.identity(n),
                           warm=cbaa.init_tables(n, dtype=q.dtype))
        for a, b in zip(cold, warm):
            assert np.array_equal(np.asarray(a), np.asarray(b))

    def test_carried_fixed_point_reconverges_fast(self):
        q, paligned, adj = self._setup()
        n = q.shape[0]
        cold = cbaa_assign(q, paligned, adj, perm.identity(n))
        tab = cbaa.CbaaTables(price=cold.price, who=cold.who)
        warm = cbaa_assign(q, paligned, adj, perm.identity(n), warm=tab)
        assert bool(warm.valid)
        np.testing.assert_array_equal(np.asarray(warm.v2f),
                                      np.asarray(cold.v2f))
        assert int(warm.rounds) < int(cold.rounds)

    def test_release_at_seed_survives_moved_geometry(self):
        # the fleet moved enough that carried winners are stale: the
        # re-auction must still converge to a valid permutation (a
        # holder that no longer prefers its carried task releases it at
        # seed time instead of orphaning it under the max-consensus
        # ratchet)
        q, paligned, adj = self._setup()
        n = q.shape[0]
        cold = cbaa_assign(q, paligned, adj, perm.identity(n))
        tab = cbaa.CbaaTables(price=cold.price, who=cold.who)
        rng = np.random.default_rng(14)
        q2 = q + jnp.asarray(rng.normal(size=(n, 3)) * 2.0)
        ref = cbaa_assign(q2, paligned, adj, perm.identity(n))
        warm = cbaa_assign(q2, paligned, adj, perm.identity(n), warm=tab)
        assert bool(warm.valid)
        np.testing.assert_array_equal(np.asarray(warm.v2f),
                                      np.asarray(ref.v2f))

    def test_eps_zero_is_bitwise_default(self):
        q, paligned, adj = self._setup()
        n = q.shape[0]
        base = cbaa_assign(q, paligned, adj, perm.identity(n))
        eps0 = cbaa_assign(q, paligned, adj, perm.identity(n),
                           assign_eps=0.0)
        for a, b in zip(base, eps0):
            assert np.array_equal(np.asarray(a), np.asarray(b))

    def test_hysteresis_monotone_in_eps(self):
        # drift the swarm through a sequence of auctions; a larger veto
        # threshold can only reassign at (weakly) fewer steps
        n = 8
        rng = np.random.default_rng(15)
        p = rng.normal(size=(n, 3)) * 5
        adj = jnp.asarray(np.ones((n, n)) - np.eye(n))
        paligned = jnp.broadcast_to(jnp.asarray(p), (n, n, 3))
        drift = rng.normal(size=(n, 3)) * 0.8
        changes = {}
        for eps in (0.0, 0.3):
            v2f = perm.identity(n)
            moved = 0
            for k in range(5):
                q = jnp.asarray(p + drift * k)
                res = cbaa_assign(q, paligned, adj, v2f,
                                  assign_eps=eps,
                                  first=jnp.asarray(k == 0))
                if bool(res.valid):
                    moved += int(np.any(np.asarray(res.v2f)
                                        != np.asarray(v2f)))
                    v2f = res.v2f
            changes[eps] = moved
        assert changes[0.3] <= changes[0.0]
        assert changes[0.0] >= 1    # the drift actually forced churn
