"""Recorded-rollout reviewer tests (the `review_bag.py` analogue)."""
import numpy as np
import pytest

from aclswarm_tpu.harness import review
from aclswarm_tpu.harness.supervisor import (COMPLETE, NAMES, TrialFSM,
                                             evaluate)


def _synthetic_metrics(T=2200, n=4, dt=0.01, takeoff_alt=1.0):
    """A hand-built 'bag': ground start, takeoff ramp, one auction, quick
    convergence — the happy path of a 1-formation trial."""
    class M:
        pass

    m = M()
    t = np.arange(T)
    z = np.clip((t - 100) * 0.005, 0.0, takeoff_alt)     # ramp from tick 100
    m.q = np.zeros((T, n, 3))
    m.q[:, :, 0] = np.arange(n)[None, :] * 2.0
    m.q[:, :, 2] = z[:, None]
    m.distcmd_norm = np.full((T, n), 2.0)
    m.distcmd_norm[t > 700] = 0.1                        # converges at 7 s
    m.ca_active = np.zeros((T, n), bool)
    m.reassigned = np.zeros(T, bool)
    m.auctioned = np.zeros(T, bool)
    m.assign_valid = np.ones(T, bool)
    # periodic auto-auction once airborne (1.2 s period), so an accepted
    # assignment lands shortly after the supervisor starts waiting for one
    m.auctioned[400::120] = True
    m.reassigned[400] = True
    m.mode = np.full((T, n), 2, np.int32)
    m.v2f = np.tile(np.arange(n, dtype=np.int32), (T, 1))
    return m


class TestRecordReplay:
    def test_roundtrip_fields(self, tmp_path):
        m = _synthetic_metrics()
        path = str(tmp_path / "trial.npz")
        review.record(path, m, dt=0.01, seed=7, formation="swarm4")
        rec = review.Recording(path)
        np.testing.assert_array_equal(rec.q, m.q)
        np.testing.assert_array_equal(rec.distcmd_norm, m.distcmd_norm)
        assert rec.dt == 0.01
        assert int(rec.meta["seed"]) == 7
        assert str(rec.meta["formation"]) == "swarm4"
        assert rec.n == 4 and rec.n_ticks == m.q.shape[0]

    def test_review_completes_happy_path(self, tmp_path):
        m = _synthetic_metrics()
        path = str(tmp_path / "trial.npz")
        review.record(path, m, dt=0.01)
        fsm = review.review(path, n_formations=1, takeoff_alt=1.0)
        assert fsm.completed, NAMES[fsm.state]
        # logging starts at FLYING entry (~7.9 s) and stops at
        # IN_FORMATION exit; the synthetic signals converge ~2-4 s later
        assert 0.0 < fsm.times[0] < 10.0

    def test_review_matches_live_fsm(self, tmp_path):
        """Replaying a recording yields the same outcome as stepping the
        FSM live on the same signals — one oracle, two feeds."""
        m = _synthetic_metrics()
        path = str(tmp_path / "trial.npz")
        review.record(path, m, dt=0.01)
        replay = review.review(path, n_formations=1, takeoff_alt=1.0)

        live = TrialFSM(4, 1, takeoff_alt=1.0, dt=0.01)
        awaiting = False
        for t in range(m.q.shape[0]):
            event = bool(m.reassigned[t])
            if awaiting and m.auctioned[t] and m.assign_valid[t]:
                event, awaiting = True, False
            action = live.step(m.q[t], m.distcmd_norm[t], m.ca_active[t],
                               event)
            if action == "dispatch":
                awaiting = True
            if live.done:
                break
        assert replay.state == live.state
        assert replay.times == live.times
        np.testing.assert_allclose(replay.csv_row(0), live.csv_row(0))

    @pytest.mark.slow
    def test_trial_records_reviewable_bag(self, tmp_path):
        """End-to-end: a trial with record_dir writes a bag whose replay
        reproduces the trial's own outcome (the review.launch workflow)."""
        from aclswarm_tpu.harness import trials
        cfg = trials.TrialConfig(formation="swarm4", trials=1, seed=3,
                                 out=str(tmp_path / "t.csv"), verbose=False,
                                 record_dir=str(tmp_path / "bags"))
        fsm_live = trials.run_trial(cfg, 0)
        bag = tmp_path / "bags" / "trial_0.npz"
        assert bag.exists()
        rec = review.Recording(str(bag))
        assert str(rec.meta["formation"]) == "swarm4"
        fsm_replay = review.review(str(bag), n_formations=fsm_live.n_formations)
        assert fsm_replay.completed == fsm_live.completed
        # convergence times agree to the chunk latency (the live driver
        # applies dispatches at chunk boundaries; replay sees the recorded
        # signal stream, so event timing matches exactly)
        assert np.allclose(fsm_replay.times, fsm_live.times)

    def test_review_flags_no_takeoff(self, tmp_path):
        m = _synthetic_metrics()
        m.q[:, :, 2] = 0.0                   # never leaves the ground
        path = str(tmp_path / "trial.npz")
        review.record(path, m, dt=0.01)
        fsm = review.review(path, n_formations=1, takeoff_alt=1.0)
        assert not fsm.completed


class TestInteractiveGate:
    """Human-in-the-loop `/in_formation` mode (`review_bag.py:29-60`)."""

    def test_human_confirm_replaces_machine_predicate(self, tmp_path):
        # signals NEVER satisfy the machine convergence predicate; only
        # the human call completes the formation
        m = _synthetic_metrics()
        m.distcmd_norm[:] = 2.0
        path = str(tmp_path / "trial.npz")
        review.record(path, m, dt=0.01)
        assert not review.review(path, n_formations=1,
                                 takeoff_alt=1.0).completed
        calls = []

        def gate(t, fsm):
            calls.append(t)
            return t >= 1500        # human calls the service at 15 s

        fsm = review.review(path, n_formations=1, takeoff_alt=1.0,
                            in_formation_gate=gate)
        assert fsm.completed
        assert len(fsm.times) == 1 and fsm.times[0] > 0.0
        assert calls  # gate was polled

    def test_human_call_during_gridlock_aborts(self, tmp_path):
        m = _synthetic_metrics()
        m.distcmd_norm[:] = 2.0
        m.ca_active[900:, :] = True    # hard gridlock from 9 s on
        path = str(tmp_path / "trial.npz")
        review.record(path, m, dt=0.01)
        from aclswarm_tpu.harness.supervisor import TrialState

        def gate(t, fsm):
            # the human calls once the FSM has entered GRIDLOCK
            return fsm.state == TrialState.GRIDLOCK

        fsm = review.review(path, n_formations=1, takeoff_alt=1.0,
                            in_formation_gate=gate)
        assert fsm.state == TrialState.TERMINATE
        # the abort fires on the human call, well before the 90 s
        # gridlock watchdog would have
        assert fsm.tick_count * 0.01 < 30.0
