"""f32 device-precision tier (`pytest -m f32`).

The main suite validates golden parity in f64 on CPU; "works in f64 on CPU"
is not "works on TPU". These tests run the assignment, gain-design, and
closed-loop paths at float32 — the TPU execution precision — with
tolerances justified by measurement:

- alignment: `precision="highest"` contractions keep the planted-transform
  recovery error ~1e-5 at f32 (without it, bf16 matmuls reach 1e-2 — the
  hazard documented in `core/geometry.py`);
- gain design: the f32 solve leaves residue in the kernel eigenmodes —
  measured ~3e-5 per mode on CPU-f32 and up to ~7e-5 on the v5e chip
  (different matmul rounding), against a ~1.0 spectral gap to the
  structural modes — so eigenstructure validates at tol=2e-4; the
  zero-block masking claim (`gains/admm.py`) must hold *exactly* at f32 —
  that is the point of the mask;
- assignment: rounding decisions are discrete, so f32 only moves ties;
  quality stays within the same <=2% LAP-suboptimality budget as f64;
- closed loop: convergence thresholds are physical (m, m/s), far above f32
  noise — the supervisor oracle must reach the same verdict.

Run on the real chip: ACLSWARM_TEST_TPU=1 python -m pytest -m f32 tests/.
"""
import jax
import jax.numpy as jnp
import numpy as np
import pytest

from aclswarm_tpu import gains as gainslib
from aclswarm_tpu import sim
from aclswarm_tpu.assignment import lapjv, sinkhorn
from aclswarm_tpu.core import geometry
from aclswarm_tpu.core.types import ControlGains, SafetyParams, make_formation
from aclswarm_tpu.harness import formgen, supervisor

pytestmark = pytest.mark.f32


def test_alignment_planted_transform(f32_mode):
    """Arun alignment at f32 recovers a planted rotation+translation of a
    scrambled swarm to ~1e-4 (needs precision='highest' contractions)."""
    n = 50
    rng = np.random.default_rng(0)
    pts = rng.normal(size=(n, 3)).astype(np.float32) * 10
    th = 1.2
    R = np.array([[np.cos(th), -np.sin(th), 0],
                  [np.sin(th), np.cos(th), 0],
                  [0, 0, 1]], np.float32)
    # z translation stays 0: the forced-d=2 alignment only recovers the
    # rot-about-z + xy-translation the control law is invariant to
    # (`assignment.py:76-78`)
    q = pts @ R.T + np.float32([3.0, -2.0, 0.0])
    aligned = np.asarray(jax.jit(
        lambda p, q: geometry.align(p, q, d=2))(jnp.asarray(pts),
                                                jnp.asarray(q)))
    assert aligned.dtype == np.float32
    err = np.abs(aligned - q).max()
    assert err < 1e-3, err


def test_assignment_quality_and_validity(f32_mode):
    """f32 Sinkhorn assignment: always a valid permutation, within the
    2% LAP-suboptimality budget at n=200."""
    n = 200
    rng = np.random.default_rng(1)
    p = jnp.asarray(rng.normal(size=(n, 3)).astype(np.float32) * 20)
    subs = []
    for k in range(3):
        q = jnp.asarray(rng.normal(size=(n, 3)).astype(np.float32) * 20)
        v = np.asarray(jax.jit(
            lambda q: sinkhorn.sinkhorn_assign(q, p).row_to_col)(q))
        assert sorted(v.tolist()) == list(range(n))  # valid permutation
        cost = np.asarray(geometry.cdist(q, p), np.float64)
        opt = cost[np.arange(n), lapjv(cost)].sum()
        subs.append(cost[np.arange(n), v].sum() / opt - 1)
    assert max(subs) < 0.02, subs


def test_gain_design_invariants(f32_mode):
    """f32 on-device gain design (Newton-Schulz PSD path) on a sparse
    simformN-shape graph: zero blocks EXACT, trace within f32 accumulation
    error, eigenstructure at the measured f32 tolerance."""
    n = 40
    rng = np.random.default_rng(2)
    pts = (rng.normal(size=(n, 3)) * 10).astype(np.float32)
    adj = formgen.random_adjmat(np.random.default_rng(2), n, fc=False)
    A = np.asarray(jax.jit(
        lambda p: gainslib.solve_gains(p, adj, max_nonedges=n - 4))(
            jnp.asarray(pts)))
    assert A.dtype == np.float32
    blocks = A.reshape(n, 3, n, 3)
    for i in range(n):
        for j in range(n):
            if i != j and adj[i, j] == 0:
                # the masking claim: no f32 residue leaks outside the graph
                assert np.all(blocks[i, :, j, :] == 0.0), (i, j)
    np.testing.assert_allclose(np.trace(A.astype(np.float64)), -3 * (n - 2),
                               atol=0.2)
    v = gainslib.validate_gains(A.astype(np.float64), pts.astype(np.float64),
                                tol=2e-4)
    assert v["no_positive"] and v["kernel_ok"] \
        and v["strictly_negative_rest"], v["eigenvalues"][-8:]


def test_closed_loop_convergence(f32_mode):
    """Short f32 closed-loop rollout with f32-designed gains: the
    supervisor oracle declares convergence, same as the f64 tier."""
    n = 6
    rng = np.random.default_rng(3)
    ang = np.linspace(0, 2 * np.pi, n, endpoint=False)
    pts = np.stack([4 * np.cos(ang), 4 * np.sin(ang),
                    np.zeros(n)], 1).astype(np.float32)
    adj = (np.ones((n, n)) - np.eye(n)).astype(np.float32)
    A = gainslib.solve_gains_blocks(pts, adj)
    formation = make_formation(pts, adj, A.astype(jnp.float32))
    sp = SafetyParams(bounds_min=jnp.asarray([-20.0, -20.0, 0.0]),
                      bounds_max=jnp.asarray([20.0, 20.0, 10.0]))
    cfg = sim.SimConfig(assignment="auction", assign_every=120)
    q0 = (rng.normal(size=(n, 3)) * 3 + [0, 0, 2]).astype(np.float32)
    st = sim.init_state(q0)
    final, m = sim.rollout(st, formation, ControlGains(), sp, cfg, 3000)
    assert np.asarray(m.q).dtype == np.float32
    res = supervisor.evaluate(np.asarray(m.distcmd_norm),
                              np.asarray(m.ca_active), np.asarray(m.q),
                              np.asarray(m.reassigned),
                              np.asarray(m.assign_valid), dt=cfg.control_dt)
    assert res.converged
