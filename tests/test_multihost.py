"""Multi-host launch tests: the `remote_start.sh` analogue, exercised
for real — two OS processes, one `jax.distributed` runtime, the sharded
faithful-stack rollout, equal digests.

Marked slow-ish (two fresh JAX processes + a gRPC handshake on one CI
core, ~1 min); the digest equality is the certificate a real pod
bring-up ends with (`scripts/pod_up.sh`)."""
import json
import os
import socket
import subprocess
import sys
from pathlib import Path

import pytest

REPO = str(Path(__file__).resolve().parents[1])

# Error signatures that mean THIS HOST cannot run a 2-process
# `jax.distributed` computation at all — an environment capability gap,
# not a regression in the launch path. The canonical case: jaxlib builds
# whose CPU backend lacks cross-process collectives ("Multiprocess
# computations aren't implemented on the CPU backend"); also the
# coordination-service handshake failing to come up on constrained CI
# hosts. On a capable host none of these strings can appear.
_HOST_CANNOT = (
    "Multiprocess computations aren't implemented",
    "Failed to initialize distributed",
    "DEADLINE_EXCEEDED",
    "UNAVAILABLE: connection",
    "failed to connect to coordination service",
)


def _free_port() -> int:
    with socket.socket() as s:
        s.bind(("127.0.0.1", 0))
        return s.getsockname()[1]


@pytest.mark.slow
def test_two_process_launch_agrees():
    port = _free_port()
    procs = []
    env = dict(os.environ, JAX_PLATFORMS="cpu")
    env.pop("XLA_FLAGS", None)       # one device per process
    for pid in (1, 0):               # coordinator (0) last: joiner waits
        try:
            procs.append(subprocess.Popen(
                [sys.executable, "-m", "aclswarm_tpu.parallel.launch",
                 "--cpu", "--coordinator", f"127.0.0.1:{port}",
                 "--num-processes", "2", "--process-id", str(pid),
                 "--n", "16", "--ticks", "6"],
                cwd=REPO, env=env, stdout=subprocess.PIPE,
                stderr=subprocess.PIPE, text=True))
        except OSError as e:         # host refuses to spawn the process
            for q in procs:
                q.kill()
            pytest.skip("SKIPPING multihost launch test: this host cannot "
                        f"spawn the second process ({e!r}) — the 2-process "
                        "jax.distributed certificate needs a host that can "
                        "fork a second Python/JAX runtime")
    # decide skip-vs-fail from the COORDINATOR (process-id 0, spawned
    # last): a capability gap shows up in its own output. Inspecting the
    # joiner first would let its secondary symptoms (DEADLINE_EXCEEDED
    # while waiting on a coordinator that died of a REAL bug) convert a
    # genuine regression into a skip.
    coordinator, joiner = procs[1], procs[0]
    reports = []
    for p in (coordinator, joiner):
        is_coord = p is coordinator
        try:
            out, err = p.communicate(timeout=240)
        except subprocess.TimeoutExpired:
            for q in procs:
                q.kill()
            if is_coord:
                pytest.skip("SKIPPING multihost launch test: the "
                            "2-process jax.distributed handshake wedged "
                            "for 240 s on this host (capability gap, "
                            "e.g. a 1-core CI box that cannot schedule "
                            "both runtimes)")
            raise AssertionError(
                "joiner wedged although the coordinator completed — a "
                "real launch-path regression, not a host capability gap")
        if p.returncode != 0 and is_coord:
            blob = out + err
            for sig in _HOST_CANNOT:
                if sig in blob:
                    for q in procs:
                        q.kill()
                    pytest.skip(
                        "SKIPPING multihost launch test: this host cannot "
                        "run 2-process jax.distributed computations "
                        f"(matched capability-gap signature {sig!r} in "
                        "the coordinator's output). Run on a host/jaxlib "
                        "with multiprocess backend support to exercise "
                        "the real certificate. Coordinator said:\n"
                        f"{err[-2000:]}")
        assert p.returncode == 0, f"launch failed:\n{out}\n{err}"
        line = [ln for ln in out.splitlines() if ln.startswith("{")][-1]
        reports.append(json.loads(line))
    assert all(r["multiprocess"] for r in reports)
    assert {r["process"] for r in reports} == {0, 1}
    assert all(r["processes"] == 2 for r in reports)
    assert all(r["global_devices"] == 2 for r in reports)
    # the digest is a pure function of the GLOBAL computation: equality
    # across processes certifies the multi-controller run agreed
    assert reports[0]["digest"] == reports[1]["digest"]
    assert abs(reports[0]["digest"]) > 0.0
