"""Multi-host launch tests: the `remote_start.sh` analogue, exercised
for real — two OS processes, one `jax.distributed` runtime, the sharded
faithful-stack rollout, equal digests.

Marked slow-ish (two fresh JAX processes + a gRPC handshake on one CI
core, ~1 min); the digest equality is the certificate a real pod
bring-up ends with (`scripts/pod_up.sh`)."""
import json
import os
import socket
import subprocess
import sys
from pathlib import Path

REPO = str(Path(__file__).resolve().parents[1])


def _free_port() -> int:
    with socket.socket() as s:
        s.bind(("127.0.0.1", 0))
        return s.getsockname()[1]


def test_two_process_launch_agrees():
    port = _free_port()
    procs = []
    env = dict(os.environ, JAX_PLATFORMS="cpu")
    env.pop("XLA_FLAGS", None)       # one device per process
    for pid in (1, 0):               # coordinator (0) last: joiner waits
        procs.append(subprocess.Popen(
            [sys.executable, "-m", "aclswarm_tpu.parallel.launch",
             "--cpu", "--coordinator", f"127.0.0.1:{port}",
             "--num-processes", "2", "--process-id", str(pid),
             "--n", "16", "--ticks", "6"],
            cwd=REPO, env=env, stdout=subprocess.PIPE,
            stderr=subprocess.PIPE, text=True))
    reports = []
    for p in procs:
        out, err = p.communicate(timeout=240)
        assert p.returncode == 0, f"launch failed:\n{out}\n{err}"
        line = [ln for ln in out.splitlines() if ln.startswith("{")][-1]
        reports.append(json.loads(line))
    assert all(r["multiprocess"] for r in reports)
    assert {r["process"] for r in reports} == {0, 1}
    assert all(r["processes"] == 2 for r in reports)
    assert all(r["global_devices"] == 2 for r in reports)
    # the digest is a pure function of the GLOBAL computation: equality
    # across processes certifies the multi-controller run agreed
    assert reports[0]["digest"] == reports[1]["digest"]
    assert abs(reports[0]["digest"]) > 0.0
