"""bench.py driver contract: ONE structured JSON line, rc=0.

Round 5's wedge (BENCH_r05: 0.0 Hz, rc=2) is the regression under
guard: a wedged device tunnel, a hung measurement, and a fallback
backend must each yield a single STRUCTURED row — ``degraded: true``
plus the reason — with exit 0, so the driver's budget is never burned
and the capture is evidence instead of a dead run. A non-zero rc now
means the driver itself is broken, never the device."""
import json
import subprocess
import sys
from pathlib import Path

REPO = str(Path(__file__).resolve().parents[1])


def _json_lines(stdout: str) -> list[dict]:
    return [json.loads(ln) for ln in stdout.splitlines()
            if ln.startswith("{")]


def test_wedge_emits_single_degraded_line_rc0():
    code = (
        "import bench, threading, time\n"
        "bench.WATCHDOG_S = 0.5\n"
        "t = threading.Timer(bench.WATCHDOG_S, bench._watchdog)\n"
        "t.daemon = True; t.start()\n"
        "time.sleep(10)\n"       # simulate the hung measurement
    )
    r = subprocess.run([sys.executable, "-c", code], cwd=REPO,
                       capture_output=True, text=True, timeout=30)
    assert r.returncode == 0
    lines = _json_lines(r.stdout)
    assert len(lines) == 1
    d = lines[0]
    assert d["metric"] == "sinkhorn_assign_n1000_hz"
    assert d["degraded"] is True and "error" in d and d["value"] == 0.0
    _assert_fleet_telemetry(d)


def _assert_fleet_telemetry(row: dict) -> None:
    """EVERY bench outcome — degraded included — carries the telemetry
    block with the fleet-provenance keys (PR 8): `workers` (serving
    capacity behind the row) and `failovers` (worker deaths survived
    while producing it). Zeroed when no service ever started."""
    tel = row["telemetry"]
    assert isinstance(tel["workers"], int) and tel["workers"] >= 0
    assert isinstance(tel["failovers"], int) and tel["failovers"] >= 0


def test_probe_timeout_emits_degraded_line_fast_rc0():
    """A wedged tunnel (simulated: a probe that sleeps forever) must
    yield the structured degraded line via the cheap PRE-measurement
    probe — rc=0 within the probe budget, not after 900 s."""
    code = (
        "import bench, sys\n"
        "bench.PROBE_TIMEOUT_S = 0.5\n"
        "bench._PROBE_CODE = 'import time; time.sleep(30)'\n"
        "sys.exit(bench.main())\n"
    )
    r = subprocess.run([sys.executable, "-c", code], cwd=REPO,
                       capture_output=True, text=True, timeout=60)
    assert r.returncode == 0
    lines = _json_lines(r.stdout)
    assert len(lines) == 1
    d = lines[0]
    assert d["metric"] == "sinkhorn_assign_n1000_hz"
    assert d["degraded"] is True
    assert "probe" in d["error"] and d["value"] == 0.0
    # no service ever started: the fleet keys are present and zeroed
    _assert_fleet_telemetry(d)
    assert d["telemetry"]["workers"] == 0
    assert d["telemetry"]["failovers"] == 0


def test_probe_reports_backend_name():
    """The probe returns the backend NAME (the degraded-marking input)
    on a working backend."""
    code = (
        "import bench\n"
        "print('PROBE', bench._probe_device(timeout_s=60))\n"
    )
    r = subprocess.run([sys.executable, "-c", code], cwd=REPO,
                       capture_output=True, text=True, timeout=120)
    probe = [ln for ln in r.stdout.splitlines()
             if ln.startswith("PROBE ")]
    assert probe and probe[0].split()[1] in ("cpu", "tpu", "gpu")


def test_boundary_finish_suppresses_watchdog():
    code = (
        "import bench, threading, time, json\n"
        "bench.WATCHDOG_S = 0.2\n"
        "t = threading.Timer(bench.WATCHDOG_S, bench._watchdog)\n"
        "t.daemon = True\n"
        "bench._done.set()\n"    # main finished exactly at the boundary
        "t.start(); time.sleep(1)\n"
        "print(json.dumps({'ok': True}))\n"
    )
    r = subprocess.run([sys.executable, "-c", code], cwd=REPO,
                       capture_output=True, text=True, timeout=30)
    assert r.returncode == 0
    lines = _json_lines(r.stdout)
    assert len(lines) == 1 and lines[0]["ok"]
