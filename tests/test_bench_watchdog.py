"""bench.py watchdog: a wedged device tunnel must yield ONE diagnostic
JSON line and exit 2 (never a silent hang that burns the driver's
budget), and a measurement finishing at the timer boundary must not
race a second line in."""
import json
import subprocess
import sys
from pathlib import Path

REPO = str(Path(__file__).resolve().parents[1])


def test_wedge_emits_single_diagnostic_line():
    code = (
        "import bench, threading, time\n"
        "bench.WATCHDOG_S = 0.5\n"
        "t = threading.Timer(bench.WATCHDOG_S, bench._watchdog)\n"
        "t.daemon = True; t.start()\n"
        "time.sleep(10)\n"       # simulate the hung measurement
    )
    r = subprocess.run([sys.executable, "-c", code], cwd=REPO,
                       capture_output=True, text=True, timeout=30)
    assert r.returncode == 2
    lines = [ln for ln in r.stdout.splitlines() if ln.startswith("{")]
    assert len(lines) == 1
    d = json.loads(lines[0])
    assert d["metric"] == "sinkhorn_assign_n1000_hz"
    assert "error" in d and d["value"] == 0.0


def test_probe_timeout_emits_error_line_fast():
    """A wedged tunnel (simulated: a probe that sleeps forever) must
    yield the structured error line via the cheap PRE-measurement probe
    — exit 2 within the probe budget, not after 900 s."""
    code = (
        "import bench, sys\n"
        "bench.PROBE_TIMEOUT_S = 0.5\n"
        "bench._PROBE_CODE = 'import time; time.sleep(30)'\n"
        "sys.exit(bench.main())\n"
    )
    r = subprocess.run([sys.executable, "-c", code], cwd=REPO,
                       capture_output=True, text=True, timeout=60)
    assert r.returncode == 2
    lines = [ln for ln in r.stdout.splitlines() if ln.startswith("{")]
    assert len(lines) == 1
    d = json.loads(lines[0])
    assert d["metric"] == "sinkhorn_assign_n1000_hz"
    assert "probe" in d["error"] and d["value"] == 0.0


def test_probe_accepts_healthy_backend():
    """The probe itself passes on a working (CPU) backend."""
    code = (
        "import bench\n"
        "bench._PROBE_CODE = \"print('ok')\"\n"
        "print('PROBE', bench._probe_device(timeout_s=30))\n"
    )
    r = subprocess.run([sys.executable, "-c", code], cwd=REPO,
                       capture_output=True, text=True, timeout=60)
    assert "PROBE True" in r.stdout


def test_boundary_finish_suppresses_watchdog():
    code = (
        "import bench, threading, time, json\n"
        "bench.WATCHDOG_S = 0.2\n"
        "t = threading.Timer(bench.WATCHDOG_S, bench._watchdog)\n"
        "t.daemon = True\n"
        "bench._done.set()\n"    # main finished exactly at the boundary
        "t.start(); time.sleep(1)\n"
        "print(json.dumps({'ok': True}))\n"
    )
    r = subprocess.run([sys.executable, "-c", code], cwd=REPO,
                       capture_output=True, text=True, timeout=30)
    assert r.returncode == 0
    lines = [ln for ln in r.stdout.splitlines() if ln.startswith("{")]
    assert len(lines) == 1 and json.loads(lines[0])["ok"]
