"""Pallas kernel tests (`aclswarm_tpu.ops`).

The CPU suite runs the kernels through the Pallas interpreter (same kernel
code, no Mosaic); the f32 tier (`ACLSWARM_TEST_TPU=1 pytest -m f32`)
compiles them for the real chip.
"""
import numpy as np
import pytest

import jax
import jax.numpy as jnp

from aclswarm_tpu.assignment import sinkhorn
from aclswarm_tpu.ops import sinkhorn_log_pallas

ON_TPU = jax.default_backend() == "tpu"


class TestSinkhornPallas:
    @pytest.mark.parametrize("n", [5, 64, 130, 200])
    def test_matches_xla_interpret(self, n):
        rng = np.random.default_rng(n)
        cost = jnp.asarray(rng.random((n, n)).astype(np.float32) * 3)
        ref = sinkhorn.sinkhorn_log(cost, n_iters=40)
        pal = sinkhorn_log_pallas(cost, n_iters=40, interpret=not ON_TPU)
        # identical update order; differences are f32 transcendental noise
        np.testing.assert_allclose(np.asarray(pal), np.asarray(ref),
                                   atol=5e-5)

    def test_padded_entries_carry_no_mass(self):
        """n=130 pads to 256 lanes: the returned slice must equal the
        unpadded computation (padding leaks would shift marginals)."""
        rng = np.random.default_rng(0)
        n = 130
        cost = jnp.asarray(rng.random((n, n)).astype(np.float32))
        pal = sinkhorn_log_pallas(cost, n_iters=60, interpret=not ON_TPU)
        row_mass = np.exp(jax.nn.logsumexp(pal, axis=1))
        col_mass = np.exp(jax.nn.logsumexp(pal, axis=0))
        np.testing.assert_allclose(row_mass, 1.0 / n, atol=1e-4)
        np.testing.assert_allclose(col_mass, 1.0 / n, atol=1e-4)

    def test_assign_impl_routing(self):
        rng = np.random.default_rng(1)
        n = 40
        q = jnp.asarray(rng.normal(size=(n, 3)).astype(np.float32) * 5)
        p = jnp.asarray(rng.normal(size=(n, 3)).astype(np.float32) * 5)
        with pytest.raises(ValueError, match="impl"):
            sinkhorn.sinkhorn_log(jnp.zeros((4, 4)), impl="nope")
        if ON_TPU:
            a = sinkhorn.sinkhorn_assign(q, p, impl="xla")
            b = sinkhorn.sinkhorn_assign(q, p, impl="pallas")
            np.testing.assert_array_equal(np.asarray(a.row_to_col),
                                          np.asarray(b.row_to_col))


class TestRoundingPallas:
    @pytest.mark.parametrize("n", [5, 17, 64, 130])
    def test_bit_identical_to_xla(self, n):
        """The gather-free VMEM rounding kernel reproduces
        `round_dominant` exactly — same first-hit argmax tie rule, same
        commit/strike order, bit-identical permutation."""
        from aclswarm_tpu.ops import round_dominant_pallas
        rng = np.random.default_rng(n)
        plan = jnp.asarray(rng.normal(size=(n, n)).astype(np.float32) * 3)
        ref = np.asarray(sinkhorn.round_dominant(plan))
        out = np.asarray(round_dominant_pallas(plan,
                                               interpret=not ON_TPU))
        np.testing.assert_array_equal(ref, out)
        assert sorted(out.tolist()) == list(range(n))

    def test_duplicate_scores_tie_rule(self):
        """Ties (equal plan entries) must resolve like jnp.argmax's first
        hit in both row and column searches."""
        from aclswarm_tpu.ops import round_dominant_pallas
        plan = jnp.asarray(np.zeros((8, 8), np.float32))
        ref = np.asarray(sinkhorn.round_dominant(plan))
        out = np.asarray(round_dominant_pallas(plan,
                                               interpret=not ON_TPU))
        np.testing.assert_array_equal(ref, out)


class TestFloodMergePallas:
    @pytest.mark.parametrize("n", [7, 64, 130])
    def test_bit_identical_to_dense(self, n):
        """The VMEM flood-merge kernel == the dense masked min (the
        localization scale path routes through it on TPU)."""
        from aclswarm_tpu.ops.flood_pallas import (SENTINEL,
                                                   flood_merge_pallas)
        rng = np.random.default_rng(n)
        packed = jnp.asarray(rng.integers(0, 2**30, (n, n)), jnp.int32)
        comm = jnp.asarray(rng.random((n, n)) < 0.3)
        ref = np.where(np.asarray(comm)[:, :, None],
                       np.asarray(packed)[None, :, :],
                       SENTINEL).min(axis=1)
        out = np.asarray(flood_merge_pallas(packed, comm,
                                            interpret=not ON_TPU))
        np.testing.assert_array_equal(ref, out)

    def test_tile_params_bit_identical_and_guarded(self):
        """Non-default tv/wc tiles produce identical results; non-divisor
        tiles raise instead of silently dropping senders/receivers."""
        from aclswarm_tpu.ops.flood_pallas import flood_merge_pallas
        rng = np.random.default_rng(9)
        n = 130
        packed = jnp.asarray(rng.integers(0, 2**30, (n, n)), jnp.int32)
        comm = jnp.asarray(rng.random((n, n)) < 0.3)
        ref = np.asarray(flood_merge_pallas(packed, comm,
                                            interpret=not ON_TPU))
        out = np.asarray(flood_merge_pallas(packed, comm, tv=16, wc=64,
                                            interpret=not ON_TPU))
        np.testing.assert_array_equal(ref, out)
        with pytest.raises(ValueError, match="divide"):
            flood_merge_pallas(packed, comm, wc=96)
        with pytest.raises(ValueError, match="divide"):
            flood_merge_pallas(packed, comm, tv=48)

    @pytest.mark.parametrize("n,w", [(64, 32), (130, 65), (7, 3)])
    def test_stripe_bit_identical(self, n, w):
        """Non-square (senders x stripe) inputs — the phased-flood mode."""
        from aclswarm_tpu.ops.flood_pallas import (SENTINEL,
                                                   flood_merge_pallas)
        rng = np.random.default_rng(n + w)
        packed = jnp.asarray(rng.integers(0, 2**30, (n, w)), jnp.int32)
        comm = jnp.asarray(rng.random((n, n)) < 0.3)
        ref = np.where(np.asarray(comm)[:, :, None],
                       np.asarray(packed)[None, :, :],
                       SENTINEL).min(axis=1)
        out = np.asarray(flood_merge_pallas(packed, comm,
                                            interpret=not ON_TPU))
        np.testing.assert_array_equal(ref, out)


@pytest.mark.f32
class TestSinkhornPallasDevice:
    def test_compiled_matches_xla(self, f32_mode):
        """On the real chip (ACLSWARM_TEST_TPU=1): Mosaic-compiled kernel
        vs the XLA scan."""
        if not ON_TPU:
            pytest.skip("needs the TPU (interpret path covered above)")
        rng = np.random.default_rng(2)
        n = 300
        cost = jnp.asarray(rng.random((n, n)).astype(np.float32) * 3)
        ref = jax.jit(lambda c: sinkhorn.sinkhorn_log(c, n_iters=50))(cost)
        pal = jax.jit(lambda c: sinkhorn_log_pallas(c, n_iters=50))(cost)
        np.testing.assert_allclose(np.asarray(pal), np.asarray(ref),
                                   atol=5e-5)
