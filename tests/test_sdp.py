"""Second-oracle tests: the original-SDP gain design vs the device ADMM.

The round-1 review flagged that the gain oracle chain was ADMM-vs-ADMM;
this file closes it: `gains.sdp` implements the reference's independent
formulation (`aclswarm/src/aclswarm/control.py:11-104`, Fathian ICRA'18)
with a completely different algorithm (full-space projected supergradient
ascent), and the ADMM solver is cross-validated against it.
"""
import numpy as np
import pytest

from aclswarm_tpu.gains import sdp
from aclswarm_tpu.gains.admm import solve_gains, validate_gains

SQUARE = np.array([[0., 0, 0], [2, 0, 0], [2, 2, 0], [0, 2, 0]])
SQUARE3D = np.array([[0., 0, 0], [2, 0, 1], [2, 2, 0], [0, 2, 1]])
FC4 = np.ones((4, 4)) - np.eye(4)
CYCLE4 = np.array([[0, 1, 0, 1], [1, 0, 1, 0],
                   [0, 1, 0, 1], [1, 0, 1, 0]], float)


def hexagon(z=None):
    ang = np.linspace(0, 2 * np.pi, 6, endpoint=False)
    pts = np.stack([2 * np.cos(ang), 2 * np.sin(ang), np.zeros(6)], 1)
    if z is not None:
        pts[:, 2] = z
    return pts


class TestSdpOracle:
    @pytest.mark.parametrize("pts,adj,nullity", [
        (SQUARE, FC4, 5), (SQUARE3D, FC4, 6), (SQUARE, CYCLE4, 5)])
    def test_feasibility_and_eigenstructure(self, pts, adj, nullity):
        A = sdp.solve_sdp_gains(pts, adj, iters=600)
        N, nl = sdp.kernel_basis(pts)
        assert nl == nullity
        # kernel constraint A N = 0 to machine precision
        assert np.abs(A @ N).max() < 1e-12
        # NSD with exact nullity (the reference's runtime self-check,
        # `control.py:221-261`)
        v = validate_gains(A, pts, tol=1e-4)
        assert v["no_positive"] and v["kernel_ok"] \
            and v["strictly_negative_rest"]

    def test_sparsity_and_block_structure(self):
        A = sdp.solve_sdp_gains(SQUARE, CYCLE4, iters=400)
        B = A.reshape(4, 3, 4, 3).transpose(0, 2, 1, 3)
        # non-edge blocks exactly zero (i != j)
        for i, j in [(0, 2), (2, 0), (1, 3), (3, 1)]:
            assert np.abs(B[i, j]).max() == 0.0
        # edge blocks are [[a, b, 0], [-b, a, 0], [0, 0, c]]
        for i in range(4):
            for j in range(4):
                if CYCLE4[i, j]:
                    blk = B[i, j]
                    assert blk[0, 0] == pytest.approx(blk[1, 1], abs=1e-12)
                    assert blk[0, 1] == pytest.approx(-blk[1, 0], abs=1e-12)
                    assert np.abs(blk[[0, 1, 2, 2], [2, 2, 0, 1]]).max() \
                        < 1e-12

    def test_deterministic(self):
        A1 = sdp.solve_sdp_gains(SQUARE, FC4, iters=100, seed=3)
        A2 = sdp.solve_sdp_gains(SQUARE, FC4, iters=100, seed=3)
        np.testing.assert_array_equal(A1, A2)


class TestCrossValidation:
    """The point of the second oracle: quality cross-checks."""

    @pytest.mark.parametrize("pts,adj", [
        (SQUARE, FC4), (SQUARE3D, FC4), (SQUARE, CYCLE4),
        (hexagon(), np.ones((6, 6)) - np.eye(6))])
    def test_admm_quality_vs_sdp_optimum(self, pts, adj):
        """The SDP maximizes the spectral gap; the ADMM solution (same
        constraints, feasibility-driven) must be close: its gap within
        [0.5, 1.05] of the SDP's. Below 0.5 would mean the fast solver
        produces meaningfully slower formations; above ~1 is impossible
        up to ascent slack (the SDP is the optimum)."""
        _, nullity = sdp.kernel_basis(pts)
        gap_sdp = sdp.spectral_gap(
            sdp.solve_sdp_gains(pts, adj, iters=800), nullity)
        gap_admm = sdp.spectral_gap(np.asarray(solve_gains(pts, adj)),
                                    nullity)
        assert gap_sdp > 0.1
        ratio = gap_admm / gap_sdp
        assert 0.5 <= ratio <= 1.05, ratio

    def test_admm_gains_near_feasible_for_sdp(self):
        """ADMM output satisfies the SDP's constraint subspace: projecting
        it onto V barely changes it (shared constraint set, independently
        implemented).

        Needs a non-flat formation (the two formulations intentionally
        differ in the flat z-kernel: ADMM drops the z-translation vector,
        `solver.cpp:100-119` vs `control.py:36-66`) and a z-feasible graph
        (the 4-cycle on the alternating-z square admits only the zero
        z-gain, so both solvers emit degenerate output there)."""
        adj = FC4.copy()
        adj[0, 2] = adj[2, 0] = 0
        A = np.asarray(solve_gains(SQUARE3D, adj))
        P_V = sdp.feasible_projector(SQUARE3D, adj)
        assert np.abs(P_V(A.copy()) - A).max() < 1e-8
