"""Distribution tests on the virtual 8-device CPU mesh (conftest sets
xla_force_host_platform_device_count=8; SURVEY.md §4 note on testing
multi-"node" behavior without hardware).

The key property: sharding is a *layout*, not a semantics change — a sharded
step must produce bit-comparable results to the single-device step.
"""
import jax
import jax.numpy as jnp
import numpy as np
import pytest

from aclswarm_tpu import parallel, sim
from aclswarm_tpu.core.types import (ControlGains, SafetyParams,
                                     make_formation)

pytestmark = pytest.mark.skipif(
    len(jax.devices()) < 2, reason="needs a multi-device (virtual) mesh")


def ring_problem(n, seed=0):
    rng = np.random.default_rng(seed)
    ang = np.linspace(0, 2 * np.pi, n, endpoint=False)
    points = np.stack([4 * np.cos(ang), 4 * np.sin(ang),
                       1.0 + 0.3 * np.sin(3 * ang)], 1)
    adj = np.ones((n, n)) - np.eye(n)
    gains = rng.normal(size=(n, n, 3, 3)) * 0.05
    formation = make_formation(points, adj, gains)
    sparams = SafetyParams(
        bounds_min=jnp.asarray([-50.0, -50.0, 0.0]),
        bounds_max=jnp.asarray([50.0, 50.0, 10.0]))
    state = sim.init_state(rng.normal(size=(n, 3)) * 5 + [0, 0, 2.0])
    return formation, sparams, state


class TestShardedStep:
    def test_matches_single_device(self):
        n = 16
        formation, sparams, state = ring_problem(n)
        cfg = sim.SimConfig(assignment="auction", assign_every=1)
        gains = ControlGains()

        ref_state, ref_metrics = jax.jit(
            lambda s: sim.step(s, formation, gains, sparams, cfg))(state)

        mesh = parallel.make_mesh()
        state_sh, formation_sh, _, _ = parallel.shard_problem(
            state, formation, mesh)
        step = parallel.sharded_step_fn(mesh, formation_sh, gains, sparams,
                                        cfg)
        out_state, out_metrics = step(state_sh)

        np.testing.assert_allclose(np.asarray(out_state.swarm.q),
                                   np.asarray(ref_state.swarm.q), atol=1e-12)
        np.testing.assert_array_equal(np.asarray(out_state.v2f),
                                      np.asarray(ref_state.v2f))
        np.testing.assert_allclose(np.asarray(out_metrics.distcmd_norm),
                                   np.asarray(ref_metrics.distcmd_norm),
                                   atol=1e-12)

    def test_output_stays_sharded(self):
        n = 16
        formation, sparams, state = ring_problem(n, seed=1)
        cfg = sim.SimConfig(assignment="none")
        mesh = parallel.make_mesh()
        state_sh, formation_sh, st_sh, _ = parallel.shard_problem(
            state, formation, mesh)
        step = parallel.sharded_step_fn(mesh, formation_sh, ControlGains(),
                                        sparams, cfg)
        out_state, _ = step(state_sh)
        # the q rows must still live distributed over the agent axis
        assert out_state.swarm.q.sharding.is_equivalent_to(
            st_sh.swarm.q, out_state.swarm.q.ndim)

    def test_sharded_rollout_converges(self):
        # ring formation with consensus-ish gains: just check the sharded
        # scan runs multi-tick and stays finite & assigned
        n = 16
        formation, sparams, state = ring_problem(n, seed=2)
        cfg = sim.SimConfig(assignment="auction")
        mesh = parallel.make_mesh()
        state_sh, formation_sh, _, _ = parallel.shard_problem(
            state, formation, mesh)
        roll = parallel.sharded_rollout_fn(mesh, formation_sh,
                                           ControlGains(), sparams, cfg, 50)
        final, metrics = roll(state_sh)
        assert bool(jnp.all(jnp.isfinite(final.swarm.q)))
        assert metrics.distcmd_norm.shape == (50, n)

    def test_batched_sharded_rollout_matches_unsharded(self):
        """Both scaling axes composed: trial-vmap (batch replicated)
        outside agent-axis GSPMD sharding — same values as the unsharded
        batched rollout."""
        B, n, T = 2, 16, 40
        probs = [ring_problem(n, seed=10 + b) for b in range(B)]
        formation = jax.tree.map(lambda *xs: jnp.stack(xs),
                                 *[p[0] for p in probs])
        sparams = probs[0][1]
        state = jax.tree.map(lambda *xs: jnp.stack(xs),
                             *[p[2] for p in probs])
        cfg = sim.SimConfig(assignment="auction", assign_every=20)
        gains = ControlGains()
        mesh = parallel.make_mesh()
        st_sh = parallel.batched_sim_state_sharding(mesh)
        f_sh = parallel.batched_formation_sharding(mesh)
        state_sh = jax.device_put(state, st_sh)
        formation_sh = jax.device_put(formation, f_sh)
        # both rollouts donate their state carry, and device_put may alias
        # the replicated leaves — give the reference its own buffers
        ref_final, ref_metrics = sim.batched_rollout(
            jax.tree.map(jnp.copy, state), formation, gains, sparams,
            cfg, T)
        roll = parallel.batched_rollout_fn(mesh, formation_sh, gains,
                                           sparams, cfg, T)
        out_final, out_metrics = roll(state_sh)
        np.testing.assert_allclose(np.asarray(out_final.swarm.q),
                                   np.asarray(ref_final.swarm.q),
                                   atol=1e-12)
        np.testing.assert_array_equal(np.asarray(out_final.v2f),
                                      np.asarray(ref_final.v2f))
        np.testing.assert_allclose(np.asarray(out_metrics.distcmd_norm),
                                   np.asarray(ref_metrics.distcmd_norm),
                                   atol=1e-12)

    def test_uneven_agents_pick_dividing_mesh(self):
        # n = 12 on 8 devices: jit shardings need even division, so the mesh
        # drops to the largest dividing device count (6) — whole agents per
        # device, like the reference's process placement
        n = 12
        formation, sparams, state = ring_problem(n, seed=3)
        cfg = sim.SimConfig(assignment="none")
        gains = ControlGains()
        ref_state, _ = jax.jit(
            lambda s: sim.step(s, formation, gains, sparams, cfg))(state)
        mesh = parallel.make_mesh(n_agents=n)
        assert n % len(mesh.devices.ravel()) == 0
        assert len(mesh.devices.ravel()) > 1
        state_sh, formation_sh, _, _ = parallel.shard_problem(
            state, formation, mesh)
        step = parallel.sharded_step_fn(mesh, formation_sh, gains, sparams,
                                        cfg)
        out_state, _ = step(state_sh)
        np.testing.assert_allclose(np.asarray(out_state.swarm.q),
                                   np.asarray(ref_state.swarm.q), atol=1e-12)


class TestShardedAssignment:
    def test_sinkhorn_assign_sharded_matches_single_device(self):
        """Agent-axis GSPMD sharding of the full Sinkhorn assignment pipeline
        (cost, log-domain iterations, dominant rounding, 2-opt repair) makes
        the same rounding decisions as the single-device program — the
        correctness half of the v5e-8 scale-out story (BASELINE.md)."""
        import jax
        from jax.sharding import NamedSharding, PartitionSpec as P

        from aclswarm_tpu.assignment import sinkhorn
        from aclswarm_tpu.parallel import mesh as meshlib

        n = 64
        rng = np.random.default_rng(11)
        q = jnp.asarray(rng.normal(size=(n, 3)) * 10)
        p = jnp.asarray(rng.normal(size=(n, 3)) * 10)

        ref = np.asarray(jax.jit(
            lambda q: sinkhorn.sinkhorn_assign(q, p).row_to_col)(q))

        mesh = meshlib.make_mesh(n_agents=n)
        assert len(mesh.devices.ravel()) > 1
        assert n % len(mesh.devices.ravel()) == 0
        row = NamedSharding(mesh, P("agents"))
        rep = NamedSharding(mesh, P())
        out = np.asarray(jax.jit(
            lambda q: sinkhorn.sinkhorn_assign(q, p).row_to_col,
            in_shardings=(row,), out_shardings=rep)(
                jax.device_put(q, row)))
        np.testing.assert_array_equal(out, ref)

        # staged shardings (docs/SCALING.md: iterations sharded, the
        # sequential rounding loops replicated) are a pure layout change —
        # identical decisions again
        staged = np.asarray(jax.jit(
            lambda q: sinkhorn.sinkhorn_assign(
                q, p, stage_shardings=(row, rep)).row_to_col,
            in_shardings=(row,), out_shardings=rep)(
                jax.device_put(q, row)))
        np.testing.assert_array_equal(staged, ref)


class TestShardedFloodedLocalization:
    @pytest.mark.slow
    def test_sharded_flooded_matches_single_device(self):
        """The flooded information model under the agent-axis sharding:
        bit-parity with the unsharded rollout (the estimate tables shard
        by owning agent; the flood's merge crosses shards)."""
        import numpy as np

        from aclswarm_tpu import gains as gainslib
        from aclswarm_tpu.core.types import (ControlGains, SafetyParams,
                                             make_formation)
        from aclswarm_tpu.parallel import mesh as meshlib
        from aclswarm_tpu.parallel.rollout import sharded_rollout_fn

        rng = np.random.default_rng(2)
        n = 8
        adj = np.zeros((n, n))
        for i in range(n):
            adj[i, (i + 1) % n] = adj[(i + 1) % n, i] = 1
            adj[i, (i + 2) % n] = adj[(i + 2) % n, i] = 1
        ang = np.linspace(0, 2 * np.pi, n, endpoint=False)
        pts = np.stack([3 * np.cos(ang), 3 * np.sin(ang),
                        np.full(n, 1.5)], 1)
        G = np.asarray(gainslib.solve_gains(pts, adj))
        formation = make_formation(pts, adj, G)
        q0 = rng.normal(size=(n, 3)) * 2.0
        q0[:, 2] = 1.5
        cfg = sim.SimConfig(assignment="cbaa", localization="flooded",
                            dynamics="firstorder")
        state = sim.init_state(jnp.asarray(q0), localization=True)
        ref_state, ref_metrics = sim.rollout(
            state, formation, ControlGains(), SafetyParams(), cfg, 300)

        mesh = meshlib.make_mesh(n_agents=n)
        assert len(mesh.devices.ravel()) > 1
        st_sh, f_sh, _, _ = meshlib.shard_problem(state, formation, mesh)
        roll = sharded_rollout_fn(mesh, f_sh, ControlGains(),
                                  SafetyParams(), cfg, 300)
        sh_state, sh_metrics = roll(st_sh)
        np.testing.assert_allclose(np.asarray(sh_state.swarm.q),
                                   np.asarray(ref_state.swarm.q),
                                   atol=1e-12)
        np.testing.assert_array_equal(np.asarray(sh_state.v2f),
                                      np.asarray(ref_state.v2f))
        np.testing.assert_allclose(np.asarray(sh_state.loc.est),
                                   np.asarray(ref_state.loc.est),
                                   atol=1e-12)

        # the phased flood (flood_phases=2) under the same mesh: the
        # stripe's dynamic_slice/update along the TARGET axis must not
        # disturb the owning-agent sharding — bit parity again
        cfg_p = sim.SimConfig(assignment="cbaa", localization="flooded",
                              dynamics="firstorder", flood_phases=2)
        ref_p, _ = sim.rollout(state, formation, ControlGains(),
                               SafetyParams(), cfg_p, 300)
        roll_p = sharded_rollout_fn(mesh, f_sh, ControlGains(),
                                    SafetyParams(), cfg_p, 300)
        sh_p, _ = roll_p(st_sh)
        np.testing.assert_allclose(np.asarray(sh_p.swarm.q),
                                   np.asarray(ref_p.swarm.q), atol=1e-12)
        np.testing.assert_allclose(np.asarray(sh_p.loc.est),
                                   np.asarray(ref_p.loc.est), atol=1e-12)


class TestMultihost:
    def test_single_process_degenerate(self):
        from aclswarm_tpu.parallel import multihost
        assert multihost.initialize() is False    # no cluster env in CI
        mesh = multihost.global_agent_mesh(n_agents=8)
        assert len(mesh.devices.ravel()) >= 1
