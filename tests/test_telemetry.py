"""swarmscope unified telemetry layer (aclswarm_tpu.telemetry;
docs/OBSERVABILITY.md).

Four tiers under test:

1. the host registry itself — concurrent counter/histogram updates from
   worker + client threads (serve is multithreaded), snapshot
   consistency under fire, flight-recorder ring wraparound, Prometheus
   text escaping, JSONL export;
2. the device `ChunkTelemetry` carry — counter semantics per solver,
   serial vs batched bit-parity, telemetry-off structural absence
   (the zero-cost HLO proof itself lives in
   tests/test_analysis.py::TestZeroCostOff via the shared baseline);
3. the serve surface — `ServeStats` counters/occupancy/latency;
4. the unification satellites — `timing_stats` histogram feed with an
   unchanged return contract, `get_logger` record counters.
"""
import json
import threading
import time

import numpy as np
import pytest

pytestmark = pytest.mark.telemetry

from aclswarm_tpu.telemetry import (FlightRecorder, MetricsRegistry,  # noqa: E402
                                    Span, get_registry, reset_registry)
from aclswarm_tpu.telemetry.registry import _escape_label  # noqa: E402


# ---------------------------------------------------------------- registry

class TestRegistry:
    def test_counter_gauge_histogram_basics(self):
        reg = MetricsRegistry()
        c = reg.counter("events_total")
        c.inc()
        c.inc(4)
        assert c.value == 5
        with pytest.raises(ValueError):
            c.inc(-1)
        g = reg.gauge("depth")
        g.set(3)
        g.add(-1)
        assert g.value == 2.0
        h = reg.histogram("lat_s")
        for v in (1.0, 2.0, 3.0, 4.0):
            h.observe(v)
        row = h.to_row()
        assert row["count"] == 4 and row["sum"] == 10.0
        assert row["min"] == 1.0 and row["max"] == 4.0
        # interpolated order statistics (numpy 'linear'): the even-count
        # median is the midpoint, and p99 sits just under the max
        assert row["p50"] == 2.5
        assert 3.9 < row["p99"] < 4.0

    def test_small_count_tail_quantiles_not_aliased(self):
        """PR-11 satellite: nearest-rank collapsed p95 and p99 onto the
        same order statistic at small counts — the committed latency
        breakdown reported p95_s == p99_s for EVERY stage at count=15.
        Interpolation keeps the tail ordered and distinct whenever the
        top samples differ, and agrees with numpy's default method."""
        import numpy as np

        reg = MetricsRegistry()
        h = reg.histogram("stage_s")
        vals = [float(v) for v in range(1, 16)]      # n=15, distinct
        for v in vals:
            h.observe(v)
        pct = h.percentiles()
        assert pct["p50"] == np.percentile(vals, 50)
        assert pct["p95"] == pytest.approx(np.percentile(vals, 95))
        assert pct["p99"] == pytest.approx(np.percentile(vals, 99))
        # the tail is ordered and NOT aliased
        assert pct["p50"] < pct["p95"] < pct["p99"] <= max(vals)
        # degenerate cases stay sane: one sample, identical samples
        h1 = reg.histogram("one_s")
        h1.observe(7.0)
        assert h1.percentiles() == {"p50": 7.0, "p95": 7.0, "p99": 7.0}
        hsame = reg.histogram("same_s")
        for _ in range(15):
            hsame.observe(3.0)
        assert set(hsame.percentiles().values()) == {3.0}

    def test_get_or_create_is_keyed_by_name_kind_labels(self):
        reg = MetricsRegistry()
        assert reg.counter("x") is reg.counter("x")
        assert reg.counter("x") is not reg.counter("x", {"t": "a"})
        # same name, different kind: distinct metric OBJECTS (keyed by
        # kind internally) — but snapshot()/Prometheus key by name, so
        # export-facing metrics must use distinct names (serve's
        # `_hist` suffix convention)
        g, h = reg.gauge("occ"), reg.histogram("occ")
        g.set(1.0)
        h.observe(0.5)
        assert g.value == 1.0 and h.count == 1

    def test_histogram_reservoir_bounded_and_newest_win(self):
        reg = MetricsRegistry()
        h = reg.histogram("h", reservoir=8)
        for v in range(100):
            h.observe(float(v))
        row = h.to_row()
        assert row["count"] == 100          # exact count survives
        assert row["max"] == 99.0           # exact extrema survive
        # percentiles come from the NEWEST 8 samples (92..99)
        assert row["p50"] >= 92.0

    def test_concurrent_updates_and_snapshot_consistency(self):
        """Worker + client threads hammer one registry while the main
        thread snapshots: final counts are exact (no lost updates) and
        every mid-flight snapshot is well-formed."""
        reg = MetricsRegistry()
        K, T = 2000, 4
        stop = threading.Event()
        snaps = []

        def worker(tid):
            c = reg.counter("hits_total")
            h = reg.histogram("obs_s", labels={"tenant": f"t{tid}"})
            for i in range(K):
                c.inc()
                h.observe(i * 1e-6)

        def snapshotter():
            while not stop.is_set():
                s = reg.snapshot()
                snaps.append(s["metrics"].get("hits_total",
                                              {"value": 0})["value"])

        threads = [threading.Thread(target=worker, args=(t,))
                   for t in range(T)]
        sn = threading.Thread(target=snapshotter)
        sn.start()
        for t in threads:
            t.start()
        for t in threads:
            t.join()
        stop.set()
        sn.join()
        assert reg.counter("hits_total").value == K * T
        for t in range(T):
            assert reg.histogram("obs_s",
                                 labels={"tenant": f"t{t}"}).count == K
        # snapshots taken under fire are monotone non-decreasing counts
        assert snaps == sorted(snaps)

    def test_snapshot_and_jsonl_roundtrip(self):
        reg = MetricsRegistry()
        reg.counter("a_total", {"k": "v"}).inc(2)
        reg.histogram("b_s").observe(0.5)
        with reg.span("phase", step=1):
            pass
        snap = reg.snapshot()
        assert snap["metrics"]["a_total{k=v}"]["value"] == 2
        assert snap["spans_recorded"] == 1
        rows = [json.loads(ln) for ln in reg.to_jsonl().splitlines()]
        kinds = {r.get("kind") for r in rows if "kind" in r}
        assert kinds == {"counter", "histogram"}
        assert any(r.get("span") == "phase" for r in rows)

    def test_dump_writes_jsonl(self, tmp_path):
        reg = MetricsRegistry()
        reg.counter("c_total").inc()
        out = tmp_path / "sub" / "tel.jsonl"
        reg.dump(out)
        assert json.loads(out.read_text().splitlines()[0])["value"] == 1


class TestPrometheusText:
    def test_escaping_of_label_values_and_names(self):
        reg = MetricsRegistry()
        reg.counter("weird total", {"path": 'a"b\\c\nd'}).inc()
        text = reg.prometheus_text()
        # metric name sanitized, label value escaped per the format spec
        assert "weird_total" in text
        assert '\\"b' in text and "\\\\c" in text and "\\nd" in text
        assert "\nd" not in text.replace("\\nd", "")   # no raw newline

    def test_escape_label_exact(self):
        assert _escape_label('a"b') == 'a\\"b'
        assert _escape_label("a\\b") == "a\\\\b"
        assert _escape_label("a\nb") == "a\\nb"

    def test_histogram_exports_summary_series(self):
        reg = MetricsRegistry()
        h = reg.histogram("lat_s", {"tenant": "a"})
        for v in (1.0, 2.0, 3.0):
            h.observe(v)
        text = reg.prometheus_text()
        assert 'lat_s{tenant="a",quantile="0.5"} 2' in text
        assert 'lat_s_count{tenant="a"} 3' in text
        assert 'lat_s_sum{tenant="a"} 6' in text
        assert "# TYPE lat_s summary" in text


class TestFlightRecorder:
    def test_ring_wraparound_keeps_newest_and_counts_drops(self):
        rec = FlightRecorder(capacity=8)
        for i in range(20):
            rec.record(Span(name=f"s{i}", t_wall=0.0, dur_s=0.001))
        spans = rec.spans()
        assert len(spans) == 8
        assert [s.seq for s in spans] == list(range(12, 20))
        assert [s.name for s in spans] == [f"s{i}" for i in range(12, 20)]
        assert rec.recorded == 20 and rec.dropped == 12

    def test_span_ctx_records_duration_and_histogram(self):
        reg = MetricsRegistry()
        with reg.span("work", idx=3):
            time.sleep(0.01)
        (s,) = reg.spans()
        assert s.name == "work" and s.attrs == {"idx": 3}
        assert s.dur_s >= 0.009
        assert reg.histogram("span_work_s").count == 1

    def test_span_ctx_marks_errors_and_reraises(self):
        reg = MetricsRegistry()
        with pytest.raises(RuntimeError):
            with reg.span("boom"):
                raise RuntimeError("x")
        (s,) = reg.spans()
        assert s.attrs.get("error") is True


# ----------------------------------------------------- device chunk counters

def _problem(n=5, dtype=None):
    import jax.numpy as jnp

    from aclswarm_tpu.core.types import (ControlGains, SafetyParams,
                                         make_formation)
    dt = dtype or jnp.result_type(float)
    ang = np.linspace(0, 2 * np.pi, n, endpoint=False)
    pts = np.stack([3 * np.cos(ang), 3 * np.sin(ang), np.full(n, 2.0)], 1)
    form = make_formation(
        jnp.asarray(pts, dt), jnp.asarray(np.ones((n, n)) - np.eye(n), dt),
        jnp.asarray(np.eye(n)[:, :, None, None]
                    * np.eye(3)[None, None] * 0.01, dt))
    sp = SafetyParams(bounds_min=jnp.asarray([-50.0, -50.0, 0.0], dt),
                      bounds_max=jnp.asarray([50.0, 50.0, 10.0], dt))
    rng = np.random.default_rng(0)
    q0 = jnp.asarray(rng.normal(size=(n, 3)) * 2.0 + [0, 0, 2.0], dt)
    return pts, form, ControlGains(), sp, q0


class TestChunkTelemetry:
    @pytest.mark.slow
    def test_counters_per_solver_and_off_absence(self):
        from aclswarm_tpu import sim
        from aclswarm_tpu.telemetry import device as devtel

        _, form, cg, sp, q0 = _problem()
        for solver, rounds_expected in (("auction", True), ("cbaa", True),
                                        ("sinkhorn", False)):
            st = sim.init_state(q0, telemetry=True)
            cfg = sim.SimConfig(assignment=solver, assign_every=5,
                                telemetry="on")
            st2, m = sim.rollout(st, form, cg, sp, cfg, 20)
            th = devtel.to_host(st2.tel)
            assert th["auctions"] == 4, (solver, th)
            assert (th["assign_rounds"] > 0) == rounds_expected
            assert th["reassigns"] <= th["auctions"]
            # StepMetrics carries the per-tick cumulative snapshot
            assert np.asarray(m.tel.auctions).shape == (20,)
            last = devtel.to_host(m.tel, index=-1)
            assert last == th
        # off: structurally absent everywhere
        st = sim.init_state(q0)
        st2, m = sim.rollout(st, form, cg, sp,
                             sim.SimConfig(assignment="auction",
                                           assign_every=5), 10)
        assert st2.tel is None and m.tel is None

    def test_flood_staleness_counts_only_in_flooded_mode(self):
        from aclswarm_tpu import sim
        from aclswarm_tpu.telemetry import device as devtel

        _, form, cg, sp, q0 = _problem()
        st = sim.init_state(q0, telemetry=True, localization=True)
        cfg = sim.SimConfig(assignment="cbaa", assign_every=4,
                            localization="flooded", flood_every=2,
                            telemetry="on")
        st2, _ = sim.rollout(st, form, cg, sp, cfg, 12)
        assert devtel.to_host(st2.tel)["flood_stale_max"] >= 1

    def test_batched_matches_serial_bit_exact(self):
        """The batched carry attributes counters per trial, bit-equal to
        B serial rollouts (the engine's row-independence guarantee
        extends to telemetry)."""
        import jax
        import jax.numpy as jnp

        from aclswarm_tpu import sim
        from aclswarm_tpu.telemetry import device as devtel

        _, form, cg, sp, _ = _problem()
        rng = np.random.default_rng(3)
        dt = form.points.dtype
        states, serial = [], []
        cfg = sim.SimConfig(assignment="auction", assign_every=5,
                            telemetry="on")
        for b in range(2):
            q0 = jnp.asarray(rng.normal(size=(5, 3)) * 2.0 + [0, 0, 2.0],
                             dt)
            states.append(sim.init_state(q0, telemetry=True))
        for st in states:
            fin, _ = sim.rollout(st, form, cg, sp, cfg, 20)
            serial.append(devtel.to_host(fin.tel))
        bstate = jax.tree.map(lambda *xs: jnp.stack(xs), *states)
        bform = jax.tree.map(lambda *xs: jnp.stack(xs), form, form)
        bfin, _ = sim.batched_rollout(bstate, bform, cg, sp, cfg, 20)
        for b in range(2):
            assert devtel.to_host(bfin.tel, index=b) == serial[b]

    def test_telemetry_on_needs_carry(self):
        from aclswarm_tpu import sim

        _, form, cg, sp, q0 = _problem()
        st = sim.init_state(q0)                  # no carry allocated
        cfg = sim.SimConfig(assignment="auction", telemetry="on")
        with pytest.raises(ValueError, match="telemetry=True"):
            sim.rollout(st, form, cg, sp, cfg, 2)
        with pytest.raises(ValueError, match="telemetry mode"):
            sim.rollout(st, form, cg, sp,
                        sim.SimConfig(telemetry="bogus"), 2)

    def test_admm_solve_stats(self):
        from aclswarm_tpu import gains as gainslib

        pts, _, _, _, _ = _problem(6)
        adj = np.ones((6, 6)) - np.eye(6)
        g_plain = np.asarray(gainslib.solve_gains(pts[:6], adj))
        g, st = gainslib.solve_gains(pts[:6], adj, telemetry=True)
        assert isinstance(st, gainslib.AdmmSolveStats)
        assert st.iters > 0 and np.isfinite(st.residual)
        np.testing.assert_array_equal(np.asarray(g), g_plain)


class TestChunkPublisher:
    def test_deltas_monotone_across_chunks_and_trials(self):
        from aclswarm_tpu.telemetry import device as devtel

        reg = MetricsRegistry()
        pub = devtel.ChunkPublisher(reg, prefix="trial")
        base = {"auctions": 0, "assign_rounds": 0, "reassigns": 0,
                "ca_ticks": 0, "flood_stale_max": 0, "admm_iters": 0,
                "admm_residual": 0.0}
        pub.publish(0, dict(base, auctions=2, assign_rounds=20))
        pub.publish(0, dict(base, auctions=5, assign_rounds=55,
                            admm_iters=9, admm_residual=0.01))
        pub.publish(1, dict(base, auctions=3, assign_rounds=30))
        assert reg.counter("trial_auctions_total").value == 8
        assert reg.counter("trial_assign_rounds_total").value == 85
        assert reg.histogram("trial_admm_iters").count == 1
        # a resumed trial replays its cumulative value: no double count
        pub2 = devtel.ChunkPublisher(reg, prefix="trial")
        pub2.publish(0, dict(base, auctions=5, assign_rounds=55))
        assert reg.counter("trial_auctions_total").value == 13


# ------------------------------------------------------------- serve stats

@pytest.mark.serve
class TestServeStats:
    def test_counters_occupancy_latency(self):
        from aclswarm_tpu.serve import ServeStats, ServiceConfig, \
            SwarmService

        svc = SwarmService(ServiceConfig(max_batch=2))
        ts = [svc.submit("rollout",
                         {"n": 5, "ticks": 20, "chunk_ticks": 20,
                          "seed": i}, tenant=f"t{i % 2}")
              for i in range(3)]
        for t in ts:
            assert t.result(timeout=300).ok
        svc.close()
        st = svc.serve_stats()
        assert isinstance(st, ServeStats)
        assert st.counts["accepted"] == 3
        assert st.counts["completed"] == 3
        assert 0.0 < st.occupancy_mean <= 1.0
        assert set(st.latency_s) == {"t0", "t1"}
        assert st.latency_s["t0"]["count"] == 2
        compact = st.compact()
        assert set(compact) == set(ServeStats.empty_compact())
        assert st.spans_recorded >= 1
        # the private registry exports Prometheus text too
        assert "serve_accepted_total 3" in svc.telemetry.prometheus_text()

    def test_deadline_miss_and_reject_counters(self):
        from aclswarm_tpu.serve import (RejectedError, ServiceConfig,
                                        SwarmService)

        svc = SwarmService(ServiceConfig(
            max_batch=1, max_queue_per_tenant=1, max_queue_total=1),
            start=False)
        svc.submit("rollout", {"n": 5, "ticks": 20, "chunk_ticks": 20})
        with pytest.raises(RejectedError):
            svc.submit("rollout", {"n": 5, "ticks": 20, "chunk_ticks": 20})
        st = svc.serve_stats()
        assert st.counts["rejected"] == 1
        assert svc.telemetry.histogram("serve_retry_after_s").count == 1
        svc.close(drain=False, timeout=5)

        svc2 = SwarmService(ServiceConfig(max_batch=1))
        t = svc2.submit("rollout",
                        {"n": 5, "ticks": 20, "chunk_ticks": 20},
                        deadline_s=0.0)
        res = t.result(timeout=60)
        assert res.status == "timed_out"
        svc2.close()
        assert svc2.serve_stats().counts["deadline_miss"] == 1


# ------------------------------------------------- unification satellites

class TestUnifiedEntryPoints:
    def test_timing_stats_feeds_histogram_contract_unchanged(self):
        from aclswarm_tpu.utils import timing

        reg = MetricsRegistry()
        stats = timing.timing_stats(lambda x: x, np.zeros(1), reps=4,
                                    name="unit", registry=reg)
        # the artifact-facing contract is untouched (TestTimingStats)
        assert set(stats) == {"median_s", "min_s", "max_s", "reps"}
        h = reg.histogram("timing_unit_s")
        assert h.count == 4                     # warmup NOT observed
        row = h.to_row()
        assert row["min"] <= stats["median_s"] <= row["max"] + 1e-12

    def test_timing_stats_default_registry(self):
        from aclswarm_tpu.utils import timing

        reg = reset_registry()
        timing.timing_stats(lambda x: x, np.zeros(1), reps=2, name="dflt")
        assert reg.histogram("timing_dflt_s").count == 2
        assert get_registry() is reg
        reset_registry()

    def test_log_records_counted_by_level(self):
        from aclswarm_tpu.utils.log import get_logger

        reg = reset_registry()
        log = get_logger("telemetry_test")
        log.warning("one")
        log.warning("two")
        log.error("boom")
        log.debug("invisible at INFO level")
        warn = reg.counter("log_records_total", {"level": "warning"})
        err = reg.counter("log_records_total", {"level": "error"})
        assert warn.value == 2 and err.value == 1
        reset_registry()
