"""Committed benchmark artifacts stay on schema (benchmarks/check_results).

Tier-1 guard: every committed `benchmarks/results/*.json` row carries a
usable name + value (or a recorded error), and strict new-style artifacts
(fault_recovery.json) carry full ``{name, n, value}`` rows — schema drift
fails loudly here instead of silently corrupting downstream evidence.
"""
import json
import sys
from pathlib import Path

import pytest

sys.path.insert(0, str(Path(__file__).resolve().parents[1] / "benchmarks"))

from check_results import (RESULTS, check_all, check_file,  # noqa: E402
                           check_serve_soak)


def test_committed_artifacts_pass_schema():
    probs = check_all()
    assert not probs, "artifact schema drift:\n" + "\n".join(probs)


def test_strict_artifact_present_and_strictly_checked():
    """fault_recovery.json is committed and held to {name, n, value}."""
    path = RESULTS / "fault_recovery.json"
    assert path.exists(), "benchmarks/results/fault_recovery.json missing"
    rows = [json.loads(ln) for ln in path.read_text().strip().splitlines()]
    assert rows, "fault_recovery.json has no rows"
    for row in rows:
        assert isinstance(row["name"], str) and row["name"]
        assert isinstance(row["n"], int) and row["n"] > 0
        assert isinstance(row["value"], (int, float))
    # both benchmark scales are represented
    assert {r["n"] for r in rows} >= {10, 100}


def test_checker_flags_drift(tmp_path):
    """The guard actually fails on drifted rows (not a rubber stamp)."""
    bad = tmp_path / "fault_recovery.json"
    bad.write_text('{"name": "x", "value": 1.0}\n'     # missing n
                   '{"n": 10, "value": 2.0}\n'         # missing name
                   '{"name": "y", "n": 10}\n')         # missing value
    probs = check_file(bad)
    assert len(probs) == 3, probs

    ok = tmp_path / "whatever.json"
    ok.write_text('{"metric": "legacy_row", "value": 3.0}\n'
                  '{"metric": "recorded_failure", "error": "boom"}\n')
    assert check_file(ok) == []

    drift = tmp_path / "other.json"
    drift.write_text('{"metric": "no_value_no_error"}\n')
    assert len(check_file(drift)) == 1
    empty = tmp_path / "empty.json"
    empty.write_text("")
    assert len(check_file(empty)) == 1


def test_checker_rejects_nonfinite_values(tmp_path):
    """NaN/Inf `value` fields serialize through json (non-standard
    extension) and poison trend comparisons — the checker rejects them
    in both lenient and strict rows; a recorded `error` string is the
    legal way to log a failed measurement."""
    bad = tmp_path / "whatever.json"
    bad.write_text('{"metric": "m", "value": NaN}\n'
                   '{"metric": "m2", "value": Infinity}\n'
                   '{"metric": "m3", "value": -Infinity}\n')
    probs = check_file(bad)
    assert len(probs) == 3, probs
    assert all("non-finite" in p for p in probs)

    strict = tmp_path / "fault_recovery.json"
    strict.write_text('{"name": "m", "n": 10, "value": NaN}\n')
    probs = check_file(strict)
    # non-finite AND (strict) no usable value
    assert any("non-finite" in p for p in probs), probs

    ok = tmp_path / "fine.json"
    ok.write_text('{"metric": "m", "value": 1e308}\n'
                  '{"metric": "failed", "error": "diverged to inf"}\n')
    assert check_file(ok) == []


def test_checker_accepts_summary_objects(tmp_path):
    summ = tmp_path / "trials_summary.json"
    summ.write_text(json.dumps({"backend": "cpu", "configs": {}}, indent=1))
    assert check_file(summ) == []


def test_resilience_metadata_validated(tmp_path):
    """The resume/retries/degraded/execution_failures metadata
    (docs/RESILIENCE.md) is validated when present: booleans are
    booleans, retries a non-negative int, and failure records carry
    exactly the ExecutionFailure schema — unknown keys rejected."""
    ok = tmp_path / "whatever.json"
    ok.write_text(json.dumps(
        {"metric": "m", "value": 1.0, "resume": True, "retries": 2,
         "degraded": True, "execution_failures": [
             {"stage": "chunk3", "error": "UNAVAILABLE", "attempts": 3,
              "elapsed_s": 1.25, "fallback": "cpu"}]}) + "\n")
    assert check_file(ok) == []

    bad = tmp_path / "bad.json"
    bad.write_text("\n".join([
        json.dumps({"metric": "m", "value": 1.0, "resume": "yes"}),
        json.dumps({"metric": "m", "value": 1.0, "retries": -1}),
        json.dumps({"metric": "m", "value": 1.0, "retries": True}),
        json.dumps({"metric": "m", "value": 1.0, "degraded": 1}),
        json.dumps({"metric": "m", "value": 1.0,
                    "execution_failures": [{"stage": "s"}]}),       # no error
        json.dumps({"metric": "m", "value": 1.0,
                    "execution_failures": [
                        {"stage": "s", "error": "e", "extra": 1}]}),  # unknown
    ]) + "\n")
    probs = check_file(bad)
    assert len(probs) == 6, probs
    assert any("unknown keys" in p for p in probs)


def test_strict_rows_accept_recorded_cell_failures(tmp_path):
    """A suite that survives a failing grid cell records it as an error
    row (the continue-the-sweep fix) — legal in strict artifacts, while
    a row with neither value nor error still fails."""
    strict = tmp_path / "fault_recovery.json"
    strict.write_text(
        json.dumps({"name": "fault_sweep_n100", "n": 100,
                    "error": "XlaRuntimeError: RESOURCE_EXHAUSTED",
                    "execution_failures": [
                        {"stage": "fault_sweep_n100", "error": "boom"}],
                    }) + "\n")
    assert check_file(strict) == []
    strict.write_text(json.dumps({"name": "x", "n": 10}) + "\n")
    assert len(check_file(strict)) == 1


def _soak_row(**over):
    row = {
        "name": "serve_soak", "n": 8, "backend": "cpu", "tenants": 3,
        "accepted": 12, "completed": 11, "rejected": 6, "preempted": 26,
        "timed_out": 1, "failed": 0, "silent_losses": 0, "resumed": 6,
        "sigkills": 1, "resume_bit_identical": True,
        "latency_s": {"p50": 12.8, "p95": 15.3, "p99": 15.7},
        "wall_s": 22.5, "quick": False,
    }
    row.update(over)
    return row


def test_serve_soak_schema_accepts_valid_row(tmp_path):
    """The soak artifact (docs/SERVICE.md) is held to an EXACT key set
    with reconciling counters and finite latency percentiles."""
    p = tmp_path / "serve_soak.json"
    p.write_text(json.dumps(_soak_row(), indent=1) + "\n")
    assert check_file(p) == []


def test_serve_soak_schema_flags_drift(tmp_path):
    p = tmp_path / "serve_soak.json"
    cases = [
        # missing counter key
        ({k: v for k, v in _soak_row().items() if k != "preempted"},
         "missing keys"),
        # unknown key (exact key set)
        (_soak_row(extra=1), "unknown keys"),
        # negative count
        (_soak_row(rejected=-1), "non-negative"),
        # ledger does not reconcile: a silent loss hidden in the counts
        (_soak_row(completed=9), "must reconcile"),
        # NaN percentile (json parses it; the checker must not)
        (_soak_row(latency_s={"p50": float("nan"), "p95": 1.0,
                              "p99": 2.0}), "finite"),
        # percentile keys are exactly p50/p95/p99
        (_soak_row(latency_s={"p50": 1.0, "p95": 2.0, "p99": 3.0,
                              "p90": 2.5}), "unknown keys"),
        (_soak_row(latency_s={"p50": 1.0, "p95": 2.0}), "missing"),
        # out-of-order percentiles
        (_soak_row(latency_s={"p50": 5.0, "p95": 2.0, "p99": 3.0}),
         "non-decreasing"),
        # bool-typed count smuggling
        (_soak_row(sigkills=True), "non-negative"),
        (_soak_row(resume_bit_identical="yes"), "bool"),
    ]
    for row, needle in cases:
        p.write_text(json.dumps(row, indent=1) + "\n")
        probs = check_file(p)
        assert probs and any(needle in x for x in probs), (row, probs)


def test_serve_soak_direct_checker_on_non_dict():
    assert check_serve_soak([1, 2], "x") == ["x: not a JSON object"]


def test_serve_soak_artifact_committed():
    """The chaos-soak evidence is committed, on schema, and shows the
    promises held: zero silent losses and bit-identical resume under
    worker SIGKILL (benchmarks/serve_soak.py)."""
    path = RESULTS / "serve_soak.json"
    assert path.exists(), "benchmarks/results/serve_soak.json missing " \
                          "(python benchmarks/serve_soak.py)"
    row = json.loads(path.read_text())
    assert check_serve_soak(row, path.name) == []
    assert row["silent_losses"] == 0
    assert row["resume_bit_identical"] is True
    assert row["sigkills"] >= 1 and row["accepted"] > 0
    assert row["preempted"] > 0 and row["rejected"] > 0


def _mw_row(**over):
    row = {
        "name": "serve_multiworker_soak", "n": 8, "backend": "cpu",
        "workers": 3, "tenants": 3, "accepted": 9, "completed": 8,
        "rejected": 0, "preempted": 16, "timed_out": 0, "failed": 1,
        "poisoned": 1, "silent_losses": 0, "worker_kills": 5,
        "requeued": 6, "migrated_resumes": 3,
        "migrated_bit_identical": True, "fairness_ok": True,
        "latency_s": {"p50": 4.6, "p95": 5.6, "p99": 5.6},
        "wall_s": 11.7, "quick": False,
    }
    row.update(over)
    return row


def test_serve_multiworker_soak_schema_accepts_valid_row(tmp_path):
    p = tmp_path / "serve_multiworker_soak.json"
    p.write_text(json.dumps(_mw_row(), indent=1) + "\n")
    assert check_file(p) == []


def test_serve_multiworker_soak_schema_flags_drift(tmp_path):
    """Exact key set + the acceptance bars AS schema: a committed
    artifact that stops proving N>=3 workers / zero loss / migrated
    bit-identical resume / fairness is rejected, not re-interpreted."""
    p = tmp_path / "serve_multiworker_soak.json"
    cases = [
        ({k: v for k, v in _mw_row().items() if k != "worker_kills"},
         "missing keys"),
        (_mw_row(extra=1), "unknown keys"),
        (_mw_row(requeued=-1), "non-negative"),
        (_mw_row(completed=7), "must reconcile"),
        (_mw_row(poisoned=2, failed=1), "failure class"),
        (_mw_row(workers=2), ">= 3 workers"),
        (_mw_row(worker_kills=0), "no worker kill"),
        (_mw_row(silent_losses=1, completed=7), "silent_losses"),
        (_mw_row(migrated_resumes=0), "migrated resume"),
        (_mw_row(migrated_bit_identical=False), "not bit-identical"),
        (_mw_row(fairness_ok=False), "starved"),
        (_mw_row(latency_s={"p50": float("inf"), "p95": 1.0,
                            "p99": 2.0}), "finite"),
    ]
    for row, needle in cases:
        p.write_text(json.dumps(row, indent=1) + "\n")
        probs = check_file(p)
        assert probs and any(needle in x for x in probs), (row, probs)
    # a QUICK run may legitimately be thinner — the bars only bind the
    # committed (non-quick) artifact
    p.write_text(json.dumps(
        _mw_row(quick=True, workers=2, worker_kills=0,
                migrated_resumes=0), indent=1) + "\n")
    assert check_file(p) == []


def test_serve_multiworker_soak_artifact_committed():
    """The multi-worker failover evidence (ISSUE 8 acceptance): N>=3
    workers, repeated single-worker kills mid-batch, zero silent
    losses, >= 1 bit-identical cross-worker migrated resume, no tenant
    starved, and the poison bound exercised."""
    from check_results import check_serve_multiworker_soak
    path = RESULTS / "serve_multiworker_soak.json"
    assert path.exists(), \
        "benchmarks/results/serve_multiworker_soak.json missing " \
        "(python benchmarks/serve_multiworker_soak.py)"
    row = json.loads(path.read_text())
    assert check_serve_multiworker_soak(row, path.name) == []
    assert row["workers"] >= 3 and row["worker_kills"] >= 2
    assert row["silent_losses"] == 0
    assert row["migrated_resumes"] >= 1
    assert row["migrated_bit_identical"] is True
    assert row["fairness_ok"] is True
    assert row["poisoned"] >= 1          # the ping-pong bound fired


def test_resilience_overhead_artifact_committed():
    """The checkpoint-tax evidence (acceptance: <5% at n=10 at the
    default cadence) is committed and on schema."""
    path = RESULTS / "resilience_overhead.json"
    assert path.exists(), "benchmarks/results/resilience_overhead.json " \
                          "missing (python -m aclswarm_tpu.resilience" \
                          ".smoke --overhead --out ...)"
    rows = [json.loads(ln) for ln in path.read_text().strip().splitlines()]
    by_name = {r["name"]: r for r in rows}
    head = by_name["checkpoint_overhead_frac_n10"]
    assert head["n"] == 10 and head["value"] < 0.05


# --------------------------------------------- swarmscope artifacts (PR 7)

def test_serve_throughput_artifact_committed():
    """The owed continuous-batching artifact (ROADMAP open item 2(c)):
    >= 3 offered-load levels, request Hz vs bucket occupancy — the
    saturating level must show fuller buckets than the light one."""
    path = RESULTS / "serve_throughput.json"
    assert path.exists(), "benchmarks/results/serve_throughput.json " \
                          "missing (python benchmarks/serve_throughput.py)"
    assert check_file(path) == []
    rows = [json.loads(ln) for ln in path.read_text().strip().splitlines()]
    rows.sort(key=lambda r: r["offered_hz"])
    assert len(rows) >= 3
    assert rows[-1]["occupancy_mean"] > rows[0]["occupancy_mean"]
    assert rows[-1]["value"] > rows[0]["value"]      # Hz grew with load
    assert rows[-1]["rejected"] > 0                  # backpressure engaged


def test_serve_throughput_schema_flags_drift(tmp_path):
    from check_results import check_serve_throughput

    def row(**kw):
        base = {"name": "serve_throughput", "n": 5, "backend": "cpu",
                "offered_hz": 8.0, "value": 7.9, "unit": "Hz",
                "speedup": 1.0,
                "stage_fracs": {"pack": 0.05, "stack": 0.05,
                                "dispatch": 0.4, "device_sync": 0.3,
                                "unpack": 0.05, "resolve": 0.02},
                "host_frac": 0.15,
                "occupancy_mean": 0.25, "occupancy_p95": 0.25,
                "queue_depth_mean": 0.0, "queue_depth_p95": 0.0,
                "accepted": 20, "completed": 20, "rejected": 0,
                "preempted": 0, "deadline_miss": 0, "wall_s": 2.5,
                "quick": False}
        base.update(kw)
        return base

    # at least one level must carry the >= 3x PR-11 speedup bar
    good = [row(offered_hz=h) for h in (2.0, 8.0)] \
        + [row(offered_hz=32.0, speedup=3.2)]
    assert check_serve_throughput(good, "x") == []
    # the speedup bar is schema: a committed artifact with no >= 3x
    # level is rejected
    flat = [row(offered_hz=h, speedup=1.1) for h in (2.0, 8.0, 32.0)]
    assert any("3x" in p or "jump" in p
               for p in check_serve_throughput(flat, "x"))
    # stage_fracs is exact-key-set like everything else
    bad_fr = good[:2] + [row(offered_hz=32.0, speedup=3.2,
                             stage_fracs={"pack": 0.1})]
    assert any("stage_fracs missing" in p
               for p in check_serve_throughput(bad_fr, "x"))
    # exact key set: unknown and missing keys both flagged
    extra = [dict(row(), bogus=1)] + good
    assert any("unknown keys" in p
               for p in check_serve_throughput(extra, "x"))
    gone = [{k: v for k, v in row().items() if k != "occupancy_mean"}] \
        + good
    assert any("missing keys" in p
               for p in check_serve_throughput(gone, "x"))
    # occupancy out of range, completed > accepted, too few levels
    assert any("[0, 1]" in p for p in check_serve_throughput(
        good + [row(occupancy_mean=1.5)], "x"))
    assert any("completed" in p for p in check_serve_throughput(
        good + [row(completed=21)], "x"))
    assert any("offered-load" in p for p in check_serve_throughput(
        [row(), row()], "x"))


def test_telemetry_overhead_artifact_committed():
    """The telemetry-tax evidence (acceptance: on < 5% of trial wall at
    n=10, default cadence; off is separately PROVEN zero-cost via the
    HLO baseline) is committed and on schema."""
    path = RESULTS / "telemetry_overhead.json"
    assert path.exists(), "benchmarks/results/telemetry_overhead.json " \
                          "missing (python -m aclswarm_tpu.telemetry" \
                          ".overhead)"
    assert check_file(path) == []
    rows = [json.loads(ln) for ln in path.read_text().strip().splitlines()]
    by_name = {r["name"]: r for r in rows}
    head = by_name["telemetry_overhead_frac_n10"]
    assert head["n"] == 10 and head["value"] < 0.05


def test_telemetry_overhead_schema_flags_drift(tmp_path):
    from check_results import check_telemetry_overhead

    frac = {"name": "telemetry_overhead_frac_n10", "n": 10,
            "value": 0.02, "unit": "ratio", "wall_off_s": 0.3,
            "wall_on_s": 0.31, "chunks": 79, "reps": 3, "note": "x"}
    pub = {"name": "telemetry_publish_us", "n": 10, "value": 4.0,
           "unit": "us", "note": "x"}
    assert check_telemetry_overhead([frac, pub], "x") == []
    # the acceptance bar IS schema: a regressed fraction fails loudly
    bad = dict(frac, value=0.2)
    assert any("acceptance bar" in p
               for p in check_telemetry_overhead([bad, pub], "x"))
    assert any("missing required row" in p
               for p in check_telemetry_overhead([frac], "x"))
    assert any("unknown keys" in p
               for p in check_telemetry_overhead(
                   [dict(frac, bogus=1), pub], "x"))
    assert any("unknown row name" in p
               for p in check_telemetry_overhead(
                   [frac, pub, {"name": "mystery", "value": 1.0}], "x"))


def test_lock_overhead_artifact_committed():
    """The swarmguard lock-tier tax evidence (acceptance: shipped
    OrderedLock < 2% of serve-round wall vs plain threading.Lock;
    docs/OBSERVABILITY.md) is committed and on schema."""
    path = RESULTS / "lock_overhead.json"
    assert path.exists(), "benchmarks/results/lock_overhead.json " \
                          "missing (python benchmarks/lock_overhead.py)"
    assert check_file(path) == []
    rows = [json.loads(ln) for ln in path.read_text().strip().splitlines()]
    by_name = {r["name"]: r for r in rows}
    assert by_name["lock_overhead_frac_serve"]["value"] < 0.02
    # the microbench row carries all three price points
    pair = by_name["lock_pair_ns"]
    assert pair["value"] > 0 and pair["armed_pair_ns"] > 0


def test_lock_overhead_schema_flags_drift(tmp_path):
    from check_results import check_lock_overhead

    frac = {"name": "lock_overhead_frac_serve", "n": 6, "value": 0.005,
            "unit": "ratio", "wall_plain_s": 1.0, "wall_ordered_s": 1.0,
            "reps": 5, "note": "x"}
    pair = {"name": "lock_pair_ns", "n": 200000, "value": 900.0,
            "unit": "ns", "plain_pair_ns": 200.0,
            "armed_pair_ns": 4000.0, "note": "x"}
    assert check_lock_overhead([frac, pair], "x") == []
    # the acceptance bar IS schema: a regressed fraction fails loudly
    assert any("acceptance bar" in p
               for p in check_lock_overhead(
                   [dict(frac, value=0.05), pair], "x"))
    assert any("missing required row" in p
               for p in check_lock_overhead([frac], "x"))
    assert any("unknown keys" in p
               for p in check_lock_overhead(
                   [dict(frac, bogus=1), pair], "x"))
    assert any("unknown row name" in p
               for p in check_lock_overhead(
                   [frac, pair, {"name": "mystery", "value": 1.0}], "x"))


def _scen_row(kind="completion", **kw):
    base = {"name": f"scenario_wind_gust_{kind}", "kind": kind,
            "n": 10, "family": "wind_gust", "trials": 4, "seed": 1,
            "ticks": 2400, "events": 4, "wall_s": 1.0, "device": "cpu",
            "quick": False, "unit": "frac" if kind == "completion"
            else "ticks",
            "value": 1.0 if kind == "completion" else 120}
    if kind == "recovery":
        base["recovered"] = base["value"] >= 0
    base.update(kw)
    return base


def test_scenario_suite_artifact_committed():
    """The owed per-family completion/recovery artifact
    (docs/SCENARIOS.md): every registry family is represented with
    BOTH kinds, and the committed rows pass the exact-key-set schema."""
    path = RESULTS / "scenario_suite.json"
    assert path.exists(), "benchmarks/results/scenario_suite.json " \
                          "missing (python benchmarks/scenario_suite.py)"
    assert check_file(path) == []
    rows = [json.loads(ln) for ln in path.read_text().strip().splitlines()]
    fams = {r["family"] for r in rows}
    assert len(fams) >= 4
    for fam in fams:
        kinds = {r["kind"] for r in rows if r["family"] == fam}
        assert kinds == {"completion", "recovery"}, (fam, kinds)
    # the families match the registry vocabulary (no orphaned rows)
    from aclswarm_tpu.scenarios import FAMILIES
    assert fams <= set(FAMILIES), fams - set(FAMILIES)


def test_scenario_suite_schema_flags_drift():
    from check_results import check_scenario_suite

    comp, rec = _scen_row(), _scen_row("recovery")
    clean = []
    for fam in ("wind_gust", "goal_drift", "sensor_noise",
                "formation_morph"):
        for kind in ("completion", "recovery"):
            clean.append(_scen_row(kind, family=fam,
                                   name=f"scenario_{fam}_{kind}"))
    assert check_scenario_suite(clean, "x") == []
    # NaN / non-finite values rejected
    assert any("finite" in p for p in check_scenario_suite(
        [dict(comp, value=float("nan")), rec], "x"))
    # completion outside [0, 1] rejected
    assert any("[0, 1]" in p for p in check_scenario_suite(
        [dict(comp, value=1.5), rec], "x"))
    # unknown keys rejected (exact-key-set schema)
    assert any("unknown keys" in p for p in check_scenario_suite(
        [dict(comp, bogus=1), rec], "x"))
    # a family missing its recovery row is drift
    assert any("owes completion AND recovery" in p
               for p in check_scenario_suite([comp], "x"))
    # recovered flag must be consistent with the value
    assert any("inconsistent" in p for p in check_scenario_suite(
        [comp, dict(rec, value=-1)], "x"))
    # a shrunken family spread fails committed artifacts
    assert any("family" in p and ">= 4" in p
               for p in check_scenario_suite([comp, rec], "x"))
    # ... but quick smoke rows are exempt from the spread bar
    q = [_scen_row(quick=True), _scen_row("recovery", quick=True)]
    assert not any(">= 4" in p for p in check_scenario_suite(q, "x"))


# --------------------------------------------------- serve_overload

def _overload_row(mult=10.0, **over):
    row = {
        "name": "serve_overload", "level": f"{mult:g}x",
        "multiplier": mult, "n": 5, "backend": "cpu",
        "capacity_hz": 8.0, "offered_hz": 8.0 * mult,
        "value": 7.5, "unit": "Hz", "p50_s": 1.0, "p99_s": 5.0,
        "offered": 100, "accepted": 40, "completed": 35,
        "timed_out": 2, "cancelled": 3, "shed": 60, "wire_lost": 0,
        "failed_other": 0, "reject_rate": 0.6, "server_rejected": 120,
        "retry_submits": 80, "accepted_after_retry": 10,
        "retry_after_p50": 2.0, "silent_losses": 0, "pm_complete": 40,
        "pm_reconstructed": 40, "crc_rejected": 5,
        "slowloris_dropped": 1, "reconnects": 2, "unresolved": 0,
        "wall_s": 20.0, "quick": False,
    }
    row.update(over)
    return row


def _overload_rows():
    return [_overload_row(0.5, value=4.0, offered=10, accepted=10,
                          completed=10, timed_out=0, cancelled=0,
                          shed=0, reject_rate=0.0, pm_complete=10,
                          pm_reconstructed=10),
            _overload_row(1.0, value=7.0, offered=20, accepted=20,
                          completed=18, timed_out=1, cancelled=1,
                          shed=0, reject_rate=0.0, pm_complete=20,
                          pm_reconstructed=20),
            _overload_row(2.0, value=7.2, offered=40, accepted=30,
                          completed=28, timed_out=1, cancelled=1,
                          shed=10, reject_rate=0.25, pm_complete=30,
                          pm_reconstructed=30),
            _overload_row(10.0)]


def test_serve_overload_artifact_committed():
    """The ISSUE-13 acceptance artifact: committed, on schema, >= 4
    levels up to 10x, zero silent losses, goodput held at 10x."""
    path = RESULTS / "serve_overload.json"
    assert path.exists(), \
        "benchmarks/results/serve_overload.json missing (run " \
        "benchmarks/serve_overload.py)"
    assert check_file(path) == []
    rows = [json.loads(ln) for ln in
            path.read_text().strip().splitlines()]
    mults = {r["multiplier"] for r in rows if not r.get("quick")}
    assert len(mults) >= 4 and max(mults) >= 10.0
    assert all(r["silent_losses"] == 0 for r in rows)


def test_serve_overload_schema_flags_drift():
    from check_results import check_serve_overload

    assert check_serve_overload(_overload_rows(), "x") == []
    # a silent loss is the one forbidden outcome
    rows = _overload_rows()
    rows[3] = dict(rows[3], silent_losses=1)
    assert any("silent_losses must be 0" in p
               for p in check_serve_overload(rows, "x"))
    # goodput collapse at 10x fails the artifact
    rows = _overload_rows()
    rows[3] = dict(rows[3], value=1.0)
    assert any("collapsing" in p
               for p in check_serve_overload(rows, "x"))
    # a 10x level that shed nothing proves nothing
    rows = _overload_rows()
    rows[3] = dict(rows[3], shed=0, completed=95, accepted=100,
                   timed_out=2, cancelled=3, reject_rate=0.0,
                   pm_complete=100, pm_reconstructed=100)
    assert any("shed nothing" in p
               for p in check_serve_overload(rows, "x"))
    # the sweep must reach 10x with >= 4 levels
    assert any(">= 10x" in p
               for p in check_serve_overload(_overload_rows()[:3], "x"))
    assert any(">= 4" in p
               for p in check_serve_overload(_overload_rows()[:3], "x"))
    # the client ledger must reconcile to the offered count
    rows = _overload_rows()
    rows[0] = dict(rows[0], completed=9)
    assert any("must reconcile" in p
               for p in check_serve_overload(rows, "x"))
    # unattributed timelines fail
    rows = _overload_rows()
    rows[3] = dict(rows[3], pm_complete=39)
    assert any("reconstruct complete" in p
               for p in check_serve_overload(rows, "x"))
    # exact key set (unknown keys rejected)
    rows = _overload_rows()
    rows[0] = dict(rows[0], bogus=1)
    assert any("unknown keys" in p
               for p in check_serve_overload(rows, "x"))


# ------------------------------------------ warm-pipeline artifact (PR 16)

def _pipe_rows():
    return [
        {"name": "admm_warm_start", "n": 100, "backend": "cpu",
         "cold_iters": 12, "warm_iters": 2, "iters_speedup": 6.0,
         "cold_ms": 2300.0, "warm_ms": 420.0, "time_speedup": 5.5,
         "gains_maxdiff": 0.0012, "quick": False},
        {"name": "assign_churn", "n": 24, "assignment": "cbaa",
         "warm_tables": False, "assign_eps": 0.0, "assign_every": 30,
         "rematch_every": 60, "drift_speed": 0.08, "ticks": 2400,
         "auctions": 40, "reassigns": 16, "churn_rate": 0.4,
         "lag_rms_m": 1.93, "baseline_parity": True, "quick": False},
        {"name": "assign_churn", "n": 24, "assignment": "cbaa",
         "warm_tables": True, "assign_eps": 0.1, "assign_every": 30,
         "rematch_every": 60, "drift_speed": 0.08, "ticks": 2400,
         "auctions": 40, "reassigns": 4, "churn_rate": 0.1,
         "lag_rms_m": 1.51, "baseline_parity": False, "quick": False},
        {"name": "pipeline_rate", "n": 1000, "mode": "composed",
         "backend": "tpu", "assignment": "sinkhorn", "assign_every": 120,
         "redesign_every": 120, "ticks": 0, "warm_gains": True,
         "tick_ms": 6.13, "stage_ms": {"tick": 6.13, "assign": 1.012,
                                       "gains": 75.33},
         "gains_source": "scale_tpu.json", "value": 147.79,
         "unit": "Hz", "quick": False},
    ]


def test_pipeline_schema_accepts_valid_rows(tmp_path):
    from check_results import check_pipeline_n1000
    assert check_pipeline_n1000(_pipe_rows(), "x") == []
    p = tmp_path / "pipeline_n1000.json"
    p.write_text("\n".join(json.dumps(r) for r in _pipe_rows()) + "\n")
    assert check_file(p) == []


def test_pipeline_schema_flags_drift():
    """Exact key set + the acceptance bars AS schema: the >= 3x warm
    iteration speedup, the bitwise hysteresis-off parity row, and the
    n=1000 >= 100 Hz warm headline are owed by the committed artifact."""
    from check_results import check_pipeline_n1000

    def drop(rows, i, key):
        rows[i] = {k: v for k, v in rows[i].items() if k != key}
        return rows

    assert any("missing keys" in p for p in check_pipeline_n1000(
        drop(_pipe_rows(), 0, "warm_iters"), "x"))
    rows = _pipe_rows()
    rows[3] = dict(rows[3], extra=1)
    assert any("unknown keys" in p
               for p in check_pipeline_n1000(rows, "x"))
    rows = _pipe_rows()
    rows[3] = dict(rows[3], value=float("nan"))
    probs = check_pipeline_n1000(rows, "x")
    assert any("finite" in p for p in probs)
    # NaN kills the headline too
    assert any("headline" in p for p in probs)
    # warm start must keep paying: speedup below the 3x bar on every
    # admm row fails the committed artifact
    rows = _pipe_rows()
    rows[0] = dict(rows[0], warm_iters=10, iters_speedup=1.2)
    assert any("speedup" in p for p in check_pipeline_n1000(rows, "x"))
    # the zero-cost-off proof: the hysteresis-off row must be bitwise
    # parity, and its absence is itself a failure
    rows = _pipe_rows()
    rows[1] = dict(rows[1], baseline_parity=False)
    assert any("bitwise" in p for p in check_pipeline_n1000(rows, "x"))
    # headline: no warm n=1000 row >= 100 Hz fails
    rows = _pipe_rows()
    rows[3] = dict(rows[3], value=80.0)
    assert any("headline" in p for p in check_pipeline_n1000(rows, "x"))
    # churn_rate is a fraction
    rows = _pipe_rows()
    rows[2] = dict(rows[2], churn_rate=1.4)
    assert any("[0, 1]" in p for p in check_pipeline_n1000(rows, "x"))
    # stage_ms is an exact-key nested dict
    rows = _pipe_rows()
    rows[3] = dict(rows[3], stage_ms={"tick": 6.13})
    assert any("stage_ms" in p for p in check_pipeline_n1000(rows, "x"))
    # a QUICK artifact is exempt from the bars, not from the schema
    rows = [dict(r, quick=True) for r in _pipe_rows()]
    rows[0] = dict(rows[0], warm_iters=10, iters_speedup=1.2)
    rows[1] = dict(rows[1], baseline_parity=False)
    rows[3] = dict(rows[3], value=80.0)
    assert check_pipeline_n1000(rows, "x") == []


def test_pipeline_artifact_committed():
    """The ROADMAP item 1 headline evidence: warm-vs-cold ADMM >= 3x,
    the churn/lag hysteresis curve with its bitwise off-parity row, and
    a sustained warm n=1000 pipeline row >= 100 Hz."""
    from check_results import check_pipeline_n1000
    path = RESULTS / "pipeline_n1000.json"
    assert path.exists(), "benchmarks/results/pipeline_n1000.json " \
                          "missing (python benchmarks/pipeline_rate.py " \
                          "--out benchmarks/results/pipeline_n1000.json)"
    assert check_file(path) == []
    rows = [json.loads(ln) for ln in path.read_text().strip().splitlines()]
    admm = [r for r in rows if r["name"] == "admm_warm_start"]
    assert any(r["iters_speedup"] >= 3.0 for r in admm)
    churn = [r for r in rows if r["name"] == "assign_churn"]
    assert any(r["baseline_parity"] for r in churn
               if not r["warm_tables"] and r["assign_eps"] == 0.0)
    heads = [r for r in rows if r["name"] == "pipeline_rate"
             and r["n"] == 1000 and r["warm_gains"]]
    assert any(r["value"] >= 100.0 for r in heads)


# ----------------------------------------------------- router_fleet

def _router_level_row(mult=1.0, **over):
    row = {
        "name": "router_fleet", "level": f"{mult:g}x",
        "multiplier": mult, "n": 5, "backend": "cpu", "workers": 2,
        "capacity_hz": 3.0, "offered_hz": 3.0 * mult, "value": 2.8,
        "unit": "Hz", "p50_s": 1.0, "p99_s": 5.0, "offered": 20,
        "completed": 18, "timed_out": 1, "shed": 1, "cancelled": 0,
        "wire_lost": 0, "failed_other": 0, "unresolved": 0,
        "retry_submits": 2, "client_pid": 100, "router_pid": 200,
        "worker_pids": [300, 301], "separate_client_process": True,
        "wall_s": 10.0, "quick": False,
    }
    row.update(over)
    return row


def _router_drill_row(**over):
    row = {
        "name": "router_fleet", "level": "drill", "multiplier": 1.0,
        "n": 5, "backend": "cpu", "workers": 2, "capacity_hz": 3.0,
        "offered_hz": 3.0, "value": 2, "unit": "kills", "kills": 2,
        "migrations": 3, "detection_ms_max": 40.0, "readmitted": True,
        "restarts": 2, "restart_drained": True,
        "restart_readmitted": True, "bit_identical": True,
        "probe_status": "completed", "probe_failovers": 1,
        "offered": 15, "completed": 14, "timed_out": 0, "shed": 1,
        "cancelled": 0, "wire_lost": 0, "failed_other": 0,
        "unresolved": 0, "client_pid": 101, "router_pid": 200,
        "worker_pids": [300, 301], "separate_client_process": True,
        "journaled_losses": 0, "duplicate_terminals": 1,
        "pm_resolved": 40, "pm_gap_free": 40, "wall_s": 25.0,
        "quick": False,
    }
    row.update(over)
    return row


def _router_rows():
    return [_router_level_row(0.5, offered=10, completed=10,
                              timed_out=0, shed=0),
            _router_level_row(1.0),
            _router_level_row(2.0, offered=40, completed=30,
                              timed_out=2, shed=8),
            _router_drill_row()]


def test_router_fleet_artifact_committed():
    """The ISSUE-17 acceptance artifact: committed, on schema, >= 3
    offered-load levels measured from a separate client process, and
    one drill row with zero journaled losses."""
    path = RESULTS / "router_fleet.json"
    assert path.exists(), \
        "benchmarks/results/router_fleet.json missing (run " \
        "benchmarks/router_fleet.py)"
    assert check_file(path) == []
    rows = [json.loads(ln) for ln in
            path.read_text().strip().splitlines()]
    drill = [r for r in rows if r["level"] == "drill"
             and not r.get("quick")]
    assert len(drill) == 1
    assert drill[0]["journaled_losses"] == 0
    assert drill[0]["bit_identical"] is True
    assert drill[0]["kills"] >= 2 and drill[0]["migrations"] >= 1
    # provenance: three kinds of OS process, pairwise distinct
    for r in rows:
        pids = [r["client_pid"], r["router_pid"], *r["worker_pids"]]
        assert len(set(pids)) == len(pids) >= 4
        assert r["separate_client_process"] is True


def test_router_fleet_schema_flags_drift():
    from check_results import check_router_fleet

    assert check_router_fleet(_router_rows(), "x") == []
    # a journaled loss is the one forbidden outcome
    rows = _router_rows()
    rows[3] = dict(rows[3], journaled_losses=1)
    assert any("journaled_losses must be 0" in p
               for p in check_router_fleet(rows, "x"))
    # a drill whose kills landed on idle processes proves nothing
    rows = _router_rows()
    rows[3] = dict(rows[3], migrations=0)
    assert any("migrated 0" in p
               for p in check_router_fleet(rows, "x"))
    # the migrated probe must resume bit-identical
    rows = _router_rows()
    rows[3] = dict(rows[3], bit_identical=False)
    assert any("bit-identical" in p
               for p in check_router_fleet(rows, "x"))
    # detection latency bar
    rows = _router_rows()
    rows[3] = dict(rows[3], detection_ms_max=5000.0)
    assert any("detection" in p
               for p in check_router_fleet(rows, "x"))
    # pid provenance: collisions and an in-process client both fail
    rows = _router_rows()
    rows[0] = dict(rows[0], client_pid=200)
    assert any("pairwise distinct" in p
               for p in check_router_fleet(rows, "x"))
    rows = _router_rows()
    rows[0] = dict(rows[0], separate_client_process=False)
    assert any("own OS process" in p
               for p in check_router_fleet(rows, "x"))
    # the curve owes >= 3 committed levels and exactly one drill
    assert any(">= 3" in p
               for p in check_router_fleet(_router_rows()[2:], "x"))
    assert any("exactly one committed drill" in p
               for p in check_router_fleet(_router_rows()[:3], "x"))
    # the client ledger must reconcile
    rows = _router_rows()
    rows[1] = dict(rows[1], completed=5)
    assert any("must reconcile" in p
               for p in check_router_fleet(rows, "x"))
    # exact key sets, per row shape
    rows = _router_rows()
    rows[0] = dict(rows[0], bogus=1)
    assert any("unknown keys" in p
               for p in check_router_fleet(rows, "x"))
    rows = _router_rows()
    rows[3] = {k: v for k, v in rows[3].items() if k != "migrations"}
    assert any("missing keys" in p
               for p in check_router_fleet(rows, "x"))
