"""Flight-mode FSM + goal mux tests (`aclswarm_tpu.sim.vehicle`).

Spec: `aclswarm/src/safety.cpp:101-121` (transitions), `:201-318` (per-mode
behavior), `:263-288` (goal mux priority).
"""
import jax.numpy as jnp
import numpy as np
import pytest

from aclswarm_tpu import sim
from aclswarm_tpu.core.types import ControlGains, SafetyParams, make_formation
from aclswarm_tpu.sim import vehicle
from aclswarm_tpu.sim.vehicle import (CMD_GO, CMD_KILL, CMD_LAND, CMD_NONE,
                                      FLYING, LANDING, NOT_FLYING, TAKEOFF)


def _room():
    return SafetyParams(bounds_min=jnp.asarray([-20.0, -20.0, 0.0]),
                        bounds_max=jnp.asarray([20.0, 20.0, 10.0]))


def _inputs_schedule(T, n, cmds: dict):
    """Time-stacked ExternalInputs with commands at given ticks."""
    cmd = np.full((T,), CMD_NONE, np.int32)
    for t, c in cmds.items():
        cmd[t] = c
    return sim.ExternalInputs(
        cmd=jnp.asarray(cmd),
        joy_vel=jnp.zeros((T, n, 3)),
        joy_yawrate=jnp.zeros((T, n)),
        joy_active=jnp.zeros((T, n), bool))


def _ground_swarm(n=4, seed=0):
    rng = np.random.default_rng(seed)
    q0 = np.zeros((n, 3))
    q0[:, :2] = rng.uniform(-5, 5, size=(n, 2))
    ang = np.linspace(0, 2 * np.pi, n, endpoint=False)
    pts = np.stack([4 * np.cos(ang), 4 * np.sin(ang), np.zeros(n)], 1)
    adj = np.ones((n, n)) - np.eye(n)
    formation = make_formation(pts, adj)
    return q0, formation


def test_command_transitions():
    fs = vehicle.init_flight(3, flying=False)
    assert np.all(np.asarray(fs.mode) == NOT_FLYING)
    fs = vehicle.apply_command(fs, jnp.asarray(CMD_GO))
    assert np.all(np.asarray(fs.mode) == TAKEOFF)
    # LAND from TAKEOFF is legal (`safety.cpp:110-114`)
    fs = vehicle.apply_command(fs, jnp.asarray(CMD_LAND))
    assert np.all(np.asarray(fs.mode) == LANDING)
    # KILL from anywhere
    fs = vehicle.apply_command(fs, jnp.asarray(CMD_KILL))
    assert np.all(np.asarray(fs.mode) == NOT_FLYING)
    # LAND has no effect on the ground
    fs = vehicle.apply_command(fs, jnp.asarray(CMD_LAND))
    assert np.all(np.asarray(fs.mode) == NOT_FLYING)


def test_takeoff_ramp_and_completion():
    """GO -> spinup hold -> z ramp at takeoff_inc -> FLYING at altitude."""
    n = 4
    q0, formation = _ground_swarm(n)
    sp = _room()
    cfg = sim.SimConfig(assignment="none", flight_fsm=True,
                        use_colavoid=False)
    st = sim.init_state(q0, flying=False)
    T = 800
    inputs = _inputs_schedule(T, n, {0: CMD_GO})
    final, m = sim.rollout(st, formation, ControlGains(), sp, cfg, T, inputs)
    mode = np.asarray(m.mode)
    q = np.asarray(m.q)

    spinup_ticks = int(round(sp.spinup_time / cfg.control_dt))
    # nothing moves during spinup
    assert np.allclose(q[spinup_ticks - 1, :, 2], 0.0, atol=1e-9)
    assert np.all(mode[spinup_ticks - 1] == TAKEOFF)
    # ramp: z increases by takeoff_inc per tick once spun up
    dz = q[spinup_ticks + 10, :, 2] - q[spinup_ticks + 9, :, 2]
    assert np.allclose(dz, sp.takeoff_inc, atol=1e-9)
    # takeoff completes when the ramp clamps at takeoff_alt (+0 initial alt)
    ramp_ticks = int(np.ceil(sp.takeoff_alt / sp.takeoff_inc))
    done = spinup_ticks + ramp_ticks + 5
    assert np.all(mode[done] == FLYING)
    assert np.all(np.abs(q[done, :, 2] - sp.takeoff_alt) < 1e-6)
    # xy untouched while still in TAKEOFF (control only engages in FLYING)
    t_first_fly = int(np.argmax(np.any(mode == FLYING, axis=1)))
    assert np.allclose(q[t_first_fly - 1, :, :2], q0[:, :2], atol=1e-6)


def test_landing_fast_then_slow_to_ground():
    n = 4
    q0, formation = _ground_swarm(n)
    q0 = q0 + np.array([0.0, 0.0, 1.0])   # hovering at 1 m
    sp = _room()
    cfg = sim.SimConfig(assignment="none", flight_fsm=True,
                        use_colavoid=False)
    st = sim.init_state(q0, flying=True)
    T = 1200
    inputs = _inputs_schedule(T, n, {0: CMD_LAND})
    final, m = sim.rollout(st, formation, ControlGains(), sp, cfg, T, inputs)
    mode = np.asarray(m.mode)
    q = np.asarray(m.q)

    assert np.all(mode[0] == LANDING)
    # fast decrement above the threshold, slow below
    dz_hi = q[1, :, 2] - q[2, :, 2]
    assert np.allclose(dz_hi, sp.landing_fast_dec, atol=1e-9)
    low_t = np.argmax(q[:, 0, 2] < sp.landing_fast_threshold - 0.01)
    dz_lo = q[low_t + 1, :, 2] - q[low_t + 2, :, 2]
    assert np.allclose(dz_lo, sp.landing_slow_dec, atol=1e-9)
    # touches down and powers off; initial_alt for an airborne start is 0
    # (init_flight zeros) so landing runs to the floor
    assert np.all(mode[-1] == NOT_FLYING)
    assert np.all(q[-1, :, 2] < vehicle.LANDING_THRESHOLD + 1e-6)


def test_takeoff_and_land_with_firstorder_dynamics():
    """The ramps carry velocity goals, so a velocity-following dynamics
    model (not just the position-tracking one) completes takeoff/landing."""
    n = 4
    q0, formation = _ground_swarm(n)
    sp = _room()
    cfg = sim.SimConfig(assignment="none", flight_fsm=True,
                        use_colavoid=False, dynamics="firstorder")
    st = sim.init_state(q0, flying=False)
    spinup_ticks = int(round(sp.spinup_time / cfg.control_dt))
    ramp = int(np.ceil(sp.takeoff_alt / sp.takeoff_inc))
    t_land = spinup_ticks + ramp + 300
    T = t_land + 1500
    inputs = _inputs_schedule(T, n, {0: CMD_GO, t_land: CMD_LAND})
    final, m = sim.rollout(st, formation, ControlGains(), sp, cfg, T, inputs)
    mode = np.asarray(m.mode)
    q = np.asarray(m.q)
    # takeoff completes despite the first-order lag
    assert np.all(mode[t_land - 1] == FLYING)
    assert np.all(np.abs(q[t_land - 1, :, 2] - sp.takeoff_alt) < 0.2)
    # landing completes back to the ground
    assert np.all(mode[-1] == NOT_FLYING)
    assert np.all(q[-1, :, 2] < 0.05)


def test_kill_cuts_everything():
    n = 4
    q0, formation = _ground_swarm(n)
    q0 = q0 + np.array([0.0, 0.0, 2.0])
    sp = _room()
    cfg = sim.SimConfig(assignment="none", flight_fsm=True,
                        use_colavoid=False)
    st = sim.init_state(q0, flying=True)
    T = 10
    inputs = _inputs_schedule(T, n, {3: CMD_KILL})
    final, m = sim.rollout(st, formation, ControlGains(), sp, cfg, T, inputs)
    mode = np.asarray(m.mode)
    assert np.all(mode[2] == FLYING)
    assert np.all(mode[3:] == NOT_FLYING)
    # sim's power-cut: vehicle pinned where it was killed
    q = np.asarray(m.q)
    assert np.allclose(q[-1], q[3], atol=1e-9)


def test_joy_overrides_dist():
    """JOY (priority 1) beats DIST (priority 0) in the goal mux."""
    n = 4
    q0, formation = _ground_swarm(n)
    q0 = q0 + np.array([0.0, 0.0, 2.0])
    # real gains would produce a nonzero distcmd; joy must win anyway
    gains = np.zeros((n, n, 3, 3))
    sp = _room()
    cfg = sim.SimConfig(assignment="none", flight_fsm=True,
                        use_colavoid=False)
    st = sim.init_state(q0, flying=True)
    T = 100
    joy = np.zeros((T, n, 3))
    joy[:, :, 0] = 0.4   # fly +x at 0.4 m/s
    inputs = sim.ExternalInputs(
        cmd=jnp.full((T,), CMD_NONE, jnp.int32),
        joy_vel=jnp.asarray(joy),
        joy_yawrate=jnp.zeros((T, n)),
        joy_active=jnp.ones((T, n), bool))
    final, m = sim.rollout(st, formation, ControlGains(), sp, cfg, T, inputs)
    q = np.asarray(m.q)
    dx = q[-1, :, 0] - q0[:, 0]
    # accel-limited (0.5 m/s^2) ramp to 0.4 m/s: 0.24 m covered in 1 s
    assert np.all(np.abs(dx - 0.24) < 0.02)
    assert np.allclose(q[-1, :, 1:], q0[:, 1:], atol=1e-6)


def test_full_lifecycle_ground_to_ground():
    """IDLE -> takeoff -> formation flight -> land, one scanned rollout."""
    from aclswarm_tpu import gains as gainslib
    from aclswarm_tpu.harness import supervisor

    n = 4
    q0, formation = _ground_swarm(n)
    A = gainslib.solve_gains_blocks(formation.points, formation.adjmat)
    formation = formation.replace(gains=A.astype(formation.points.dtype))
    sp = _room()
    cfg = sim.SimConfig(assignment="auction", assign_every=120,
                        flight_fsm=True)
    st = sim.init_state(q0, flying=False)

    spinup_ticks = int(round(sp.spinup_time / cfg.control_dt))
    ramp = int(np.ceil(sp.takeoff_alt / sp.takeoff_inc))
    t_land = spinup_ticks + ramp + 3000
    T = t_land + 1500
    inputs = _inputs_schedule(T, n, {0: CMD_GO, t_land: CMD_LAND})
    final, m = sim.rollout(st, formation, ControlGains(), sp, cfg, T, inputs)
    mode = np.asarray(m.mode)
    q = np.asarray(m.q)

    # airborne phase reaches FLYING for everyone, then lands
    t_flying = spinup_ticks + ramp + 10
    assert np.all(mode[t_flying] == FLYING)
    assert np.all(mode[-1] == NOT_FLYING)
    assert np.all(q[-1, :, 2] < vehicle.LANDING_THRESHOLD + 1e-6)

    # the formation actually converged mid-flight (supervisor oracle over
    # the airborne window)
    fly = slice(t_flying, t_land)
    res = supervisor.evaluate(np.asarray(m.distcmd_norm)[fly],
                              np.asarray(m.ca_active)[fly],
                              q[fly], np.asarray(m.reassigned)[fly],
                              np.asarray(m.assign_valid)[fly],
                              dt=cfg.control_dt)
    assert res.converged

    # assignment never ran before everyone was FLYING
    first_assign = np.argmax(np.asarray(m.reassigned) |
                             ~np.asarray(m.assign_valid))
    all_flying_t = np.argmax(np.all(mode == FLYING, axis=1))
    assert np.sum(np.asarray(m.reassigned)[:all_flying_t]) == 0
