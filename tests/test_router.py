"""swarmrouter — the process-per-worker fleet tier
(`aclswarm_tpu.serve.router` + `serve.procworker`; docs/SERVICE.md
§process mode).

Tier-1 coverage keeps the jax-subprocess cost out: the supervision
protocol (HELLO arbitration, leases, READY) is driven in-process with
raw wire frames and REAL in-process worker cells (a `SwarmService` +
`WireServer` per fake slot), so placement, failover, fencing, and the
journal audit all run at thread-test speed. Exactly one test pays for
real child processes: the duplicate-HELLO race, which must prove that
of two OS processes claiming one slot exactly one is admitted, the
loser exits with a structured refusal, and the loser never writes a
journal frame.
"""
from __future__ import annotations

import json
import os
import subprocess
import sys
import threading
import time
from pathlib import Path

import pytest

from aclswarm_tpu.interop import transport
from aclswarm_tpu.resilience import checkpoint as ckptlib
from aclswarm_tpu.serve import ServiceConfig, SwarmService, wire
from aclswarm_tpu.serve.router import (DEAD, SPAWNING, UP, RouterConfig,
                                       SwarmRouter)
from aclswarm_tpu.serve.service import (bucket_of, read_fence,
                                        write_fence)
from aclswarm_tpu.serve.workers import place_slot

pytestmark = [pytest.mark.serve]

ROLL = {"n": 5, "ticks": 60, "chunk_ticks": 20, "seed": 5}
SLOW_ROLL = {"n": 5, "ticks": 400, "chunk_ticks": 20, "seed": 7}


# ------------------------------------------------------------ placement

class TestPlacement:
    def test_place_slot_accepts_string_uids(self):
        uids = ["0.1", "1.4", "2.2"]
        pick = place_slot(("single", "assign"), uids)
        assert pick in uids
        # deterministic
        assert all(place_slot(("single", "assign"), uids) == pick
                   for _ in range(5))

    def test_place_slot_int_compat(self):
        # thread-fleet placement (int slots) is untouched by the
        # type-agnostic tiebreaker rewrite
        for bucket in [("rollout", 5, 3), ("single", "assign")]:
            pick = place_slot(bucket, [0, 1, 2, 3])
            assert pick in (0, 1, 2, 3)
            assert place_slot(bucket, list(range(4))) == pick

    def test_incarnation_set_minimal_disruption(self):
        """Rendezvous over uids. Death (node removed): only the dead
        node's buckets move. Respawn (incarnation replaced): a bucket
        never moves BETWEEN surviving incarnations — it stays put or
        lands on the newcomer (whose weights are fresh)."""
        old = [f"{s}.1" for s in range(4)]
        survivors = ["0.1", "2.1", "3.1"]       # slot 1 died
        new = ["0.1", "1.2", "2.1", "3.1"]      # slot 1 respawned
        buckets = [("rollout", n, c) for n in (3, 5, 8)
                   for c in (10, 20)] + [("single", "assign")]
        for b in buckets:
            was = place_slot(b, old)
            if was != "1.1":
                assert place_slot(b, survivors) == was
                assert place_slot(b, new) in (was, "1.2")

    def test_bucket_of_groups_all_plain_kinds(self):
        assert bucket_of("assign", {"n": 5}) \
            == bucket_of("assign", {"n": 50})
        assert bucket_of("rollout", ROLL) != bucket_of("assign", ROLL)


# -------------------------------------------------------------- fencing

class TestFence:
    def test_fence_round_trip(self, tmp_path):
        assert read_fence(tmp_path) is None
        write_fence(tmp_path, 3)
        assert read_fence(tmp_path) == 3
        write_fence(tmp_path, 4)
        assert read_fence(tmp_path) == 4

    def test_constructor_refuses_fenced_journal(self, tmp_path):
        write_fence(tmp_path, 5)
        with pytest.raises(RuntimeError, match="fenced"):
            SwarmService(ServiceConfig(journal_dir=str(tmp_path),
                                       incarnation=4), start=False)

    @pytest.mark.slow
    def test_zombie_journal_writes_noop(self, tmp_path):
        """A fenced predecessor's journal writes are loud no-ops: the
        successor's fence freezes the frame set the zombie can touch."""
        svc = SwarmService(ServiceConfig(journal_dir=str(tmp_path),
                                         incarnation=1, max_batch=1))
        svc.submit("rollout", ROLL, tenant="a",
                   request_id="pre-fence").result(timeout=120)
        # successor fences the dir (as procworker does pre-recovery)
        write_fence(tmp_path, 2)
        time.sleep(SwarmService.FENCE_CHECK_S * 3)
        def _frames():
            # journal promise frames only — the flight-recorder span
            # dump at close is telemetry, not a journal write
            return sorted((str(p), p.stat().st_size)
                          for p in tmp_path.rglob("*") if p.is_file()
                          and p.name != "spans_dump.jsonl")

        before = _frames()
        # a fenced process must not take NEW acceptance promises —
        # the submit is refused loudly, never silently journal-less
        from aclswarm_tpu.serve import RejectedError
        with pytest.raises(RejectedError):
            svc.submit("rollout", dict(ROLL, seed=9), tenant="a",
                       request_id="post-fence")
        svc.close(drain=True, timeout=30.0)
        after = _frames()
        assert after == before, \
            "zombie wrote journal frames past the fence"
        assert svc.telemetry.counter("serve_fenced_total").value >= 1


# ------------------------------------------- supervision-wire machinery

def _sup_connect(router):
    host, port = router._sup.address
    return transport.connect_when_ready(host, int(port), grace_s=5.0)


def _hello(chan, slot, inc, pid=None, role="procworker",
           timeout_s=5.0):
    chan.send_bytes(wire._frame(wire.K_HELLO, {
        "client": f"proc.w{slot}.{inc}", "role": role,
        "slot": slot, "incarnation": inc,
        "pid": pid if pid is not None else os.getpid()}))
    chan.flush()
    t_end = time.monotonic() + timeout_s
    while time.monotonic() < t_end:
        try:
            raw = chan.recv_bytes()
        except OSError:
            return None, None        # closed without a verdict
        if raw is not None:
            payload, man = ckptlib.loads(raw, chan.name)
            return man.get("kind"), payload
        time.sleep(0.01)
    return None, None


@pytest.fixture
def bare_router(tmp_path):
    """A router with its supervision plane live but NO children and NO
    front server — the arbitration matrix runs against it with raw
    wire frames."""
    router = SwarmRouter(RouterConfig(journal_root=str(tmp_path),
                                      slots=2, respawn=False,
                                      lease_s=2.0))
    router.start(spawn=False, front=False)
    yield router
    router.close(timeout=10)


class TestArbitration:
    def test_exactly_one_claimant_wins(self, bare_router):
        c1 = _sup_connect(bare_router)
        kind, payload = _hello(c1, 0, 1)
        assert kind == wire.K_HELLO_ACK and payload["accepted"]
        assert payload["lease_s"] == pytest.approx(2.0)
        # second claimant for the SAME slot: structured refusal
        c2 = _sup_connect(bare_router)
        kind2, p2 = _hello(c2, 0, 1)
        assert kind2 == wire.K_ERROR
        assert p2["error"] == "slot_taken"
        assert p2["owner"] == "0.1"
        c1.close()
        c2.close()

    def test_stale_incarnation_refused(self, bare_router):
        c1 = _sup_connect(bare_router)
        assert _hello(c1, 1, 3)[0] == wire.K_HELLO_ACK
        c1.close()                       # connection death -> DEAD
        deadline = time.monotonic() + 5.0
        while time.monotonic() < deadline:
            if any(f["slot"] == 1 and f["state"] == DEAD
                   for f in bare_router.fleet()):
                break
            time.sleep(0.02)
        c2 = _sup_connect(bare_router)
        kind, p = _hello(c2, 1, 2)       # older than gen 3
        assert kind == wire.K_ERROR
        assert p["error"] == "stale_incarnation" and p["current"] == 3
        c2.close()

    def test_unknown_slot_refused(self, bare_router):
        c = _sup_connect(bare_router)
        kind, p = _hello(c, 97, 1)
        assert kind == wire.K_ERROR and "unknown slot" in p["error"]
        c.close()

    def test_non_procworker_hello_dropped(self, bare_router):
        c = _sup_connect(bare_router)
        kind, _ = _hello(c, 0, 1, role="imposter", timeout_s=1.0)
        assert kind is None              # closed without admission
        assert all(f["state"] == DEAD for f in bare_router.fleet())
        c.close()


# ------------------------------------- in-process fleet: the data path

class _FakeWorker:
    """A REAL worker cell (SwarmService + WireServer) living in the
    test process, attached to the router through the genuine
    supervision handshake — everything but the fork."""

    def __init__(self, router, slot, inc, journal_dir, **svc_kw):
        self.slot, self.inc = slot, inc
        write_fence(journal_dir, inc)
        self.svc = SwarmService(ServiceConfig(
            journal_dir=str(journal_dir), incarnation=inc, workers=1,
            **svc_kw))
        self.server = wire.WireServer(self.svc, base=None,
                                      tcp=("127.0.0.1", 0))
        self.chan = _sup_connect(router)
        kind, _ = _hello(self.chan, slot, inc)
        assert kind == wire.K_HELLO_ACK
        self.chan.send_bytes(wire._frame(wire.K_EVENT, {
            "event": "ready", "slot": slot, "incarnation": inc,
            "pid": os.getpid(),
            "wire_port": int(self.server.tcp_address[1])}))
        self.chan.flush()
        self._stop = threading.Event()
        self._beat = threading.Thread(target=self._beats, daemon=True)
        self._beat.start()

    def _beats(self):
        while not self._stop.is_set():
            try:
                self.chan.send_bytes(wire._frame(wire.K_PING, {
                    "slot": self.slot, "incarnation": self.inc,
                    "pid": os.getpid(), "stats": {}}))
                self.chan.flush()
                while self.chan.recv_bytes() is not None:
                    pass                 # drain ctl frames
            except OSError:
                return
            time.sleep(0.3)

    def die(self):
        """Supervision-connection death (the router's signal), while
        the cell itself keeps running — the zombie case."""
        self._stop.set()
        self.chan.close()

    def close(self):
        self._stop.set()
        try:
            self.chan.close()
        except OSError:
            pass
        self.server.close()
        self.svc.close(drain=False, timeout=5.0)


@pytest.fixture
def fleet(tmp_path):
    router = SwarmRouter(RouterConfig(journal_root=str(tmp_path),
                                      slots=2, respawn=False,
                                      lease_s=2.0, max_resubmits=3))
    router.start(spawn=False, front=False)
    workers = [_FakeWorker(router, s, 1, tmp_path / f"w{s}",
                           max_batch=2) for s in range(2)]
    assert router.wait_ready(10.0), router.fleet()
    yield router, workers
    for w in workers:
        w.close()
    router.close(timeout=10)


class TestDataPath:
    def test_submit_routes_and_matches_direct(self, fleet):
        router, _ = fleet
        ref = SwarmService(ServiceConfig(max_batch=1))
        want = ref.submit("rollout", ROLL).result(timeout=120)
        ref.close()
        t = router.submit("rollout", ROLL, tenant="a",
                          request_id="r-parity")
        got = t.result(timeout=120)
        assert got.ok, got.error
        assert got.value["digest"] == want.value["digest"]

    def test_bucket_spread_and_idempotent_attach(self, fleet):
        router, _ = fleet
        t1 = router.submit("assign", {"n": 5, "seed": 1}, tenant="a",
                           request_id="same-rid")
        t2 = router.submit("assign", {"n": 5, "seed": 1}, tenant="a",
                           request_id="same-rid")
        assert t1 is t2                  # duplicate attach, one route
        assert t1.result(timeout=120).ok

    def test_failover_migrates_inflight(self, fleet):
        """Supervision death mid-flight: the route requeues, rendezvous
        re-places it on the survivor, and the result still lands on the
        ORIGINAL front ticket with failovers counted."""
        router, workers = fleet
        t = router.submit("rollout", SLOW_ROLL, tenant="a",
                          request_id="r-migrate")
        deadline = time.monotonic() + 10.0
        uid = ""
        while time.monotonic() < deadline and not uid:
            uid = router.route_uid("r-migrate")
            time.sleep(0.01)
        assert uid, "route never dispatched"
        victim = next(w for w in workers if f"{w.slot}.1" == uid)
        victim.die()
        res = t.result(timeout=120)
        assert res.ok, res.error
        assert res.failovers >= 1
        # the declared death is in the ledger with the route requeued
        assert any(d["uid"] == uid and d["requeued"] >= 1
                   for d in router.deaths)
        # and the survivor carries the fleet
        live = [f for f in router.fleet() if f["state"] == UP]
        assert len(live) == 1 and live[0]["uid"] != uid

    def test_lease_miss_declares_dead(self, fleet):
        router, workers = fleet
        workers[0]._stop.set()           # heartbeats stop, chan stays
        deadline = time.monotonic() + 8.0
        while time.monotonic() < deadline:
            if any(d["slot"] == 0 and "lease" in d["reason"]
                   for d in router.deaths):
                break
            time.sleep(0.05)
        assert any(d["slot"] == 0 and "lease" in d["reason"]
                   for d in router.deaths), router.deaths

    def test_health_aggregates_processes(self, fleet):
        router, _ = fleet
        t = router.submit("health", {}, tenant="_ops",
                          request_id="h1")
        h = t.result(timeout=30).value
        assert h["router"] is True
        assert set(h["processes"]) == {"0.1", "1.1"}
        for row in h["processes"].values():
            assert row["pid"] == os.getpid()
            assert row["incarnation"] == 1

    @pytest.mark.slow
    def test_fleet_journals_reconstruct_gap_free(self, fleet, tmp_path):
        from aclswarm_tpu.telemetry import postmortem

        router, workers = fleet
        ts = [router.submit("rollout", dict(ROLL, seed=100 + i),
                            tenant="a", request_id=f"pm-{i}")
              for i in range(3)]
        for t in ts:
            assert t.result(timeout=120).ok
        for w in workers:
            w.close()
        rep = postmortem.fleet_reconstruct(
            [tmp_path / "w0", tmp_path / "w1"])
        assert rep["losses"] == []
        mine = {r for r in rep["requests"] if r.startswith("pm-")}
        assert mine == {"pm-0", "pm-1", "pm-2"}


# ------------------------------------------------- HELLO-ack identity

class TestHelloAckIdentity:
    def test_server_info_carries_pid_and_incarnation(self, tmp_path):
        svc = SwarmService(ServiceConfig(journal_dir=str(tmp_path),
                                         incarnation=7, max_batch=1))
        srv = wire.WireServer(svc, base=None, tcp=("127.0.0.1", 0))
        c = wire.WireClient(tcp=srv.tcp_address, client_id="idwatch")
        try:
            assert c.server_info["pid"] == os.getpid()
            assert c.server_info["incarnation"] == 7
        finally:
            c.close()
            srv.close()
            svc.close(drain=False, timeout=5.0)

    def test_watch_identity_delta(self):
        from aclswarm_tpu.telemetry.watch import (identities,
                                                  identity_delta)

        h1 = {"pid": 10, "incarnation": 1,
              "processes": {"0.1": {"pid": 20, "incarnation": 1},
                            "1.1": {"pid": 21, "incarnation": 1}}}
        # steady state: silent
        assert identity_delta(identities(h1), identities(h1)) == []
        # worker 1 respawned: new pid, bumped incarnation
        h2 = {"pid": 10, "incarnation": 1,
              "processes": {"0.1": {"pid": 20, "incarnation": 1},
                            "1.2": {"pid": 35, "incarnation": 2}}}
        delta = identity_delta(identities(h1), identities(h2))
        assert len(delta) == 1
        assert "RESPAWN" in delta[0] and "w1" in delta[0]
        assert "20" not in delta[0] and "35" in delta[0]
        # reconnect (same pid + incarnation) is NOT a respawn
        assert identity_delta(identities(h2), identities(h2)) == []


# ------------------------------------- the duplicate-HELLO race (OS)

class TestDuplicateHelloRace:
    def test_two_processes_one_winner(self, tmp_path):
        """Two real OS processes claim the same slot: exactly one is
        admitted, the loser exits 3 with the structured refusal, and
        the loser never writes a journal frame."""
        router = SwarmRouter(RouterConfig(journal_root=str(tmp_path),
                                          slots=1, respawn=False))
        router.start(spawn=False, front=False)
        host, port = router._sup.address
        env = {**os.environ, "JAX_PLATFORMS": "cpu",
               "PYTHONPATH": os.pathsep.join(
                   [str(Path(__file__).resolve().parents[1]),
                    os.environ.get("PYTHONPATH", "")])}
        cmd = [sys.executable, "-m", "aclswarm_tpu.serve.procworker",
               "--slot", "0", "--incarnation", "1",
               "--supervisor", f"{host}:{port}",
               "--handshake-only", "--handshake-hold-s", "2.0"]
        procs = [subprocess.Popen(cmd, stdout=subprocess.PIPE,
                                  stderr=subprocess.STDOUT, text=True,
                                  env=env) for _ in range(2)]
        outs = [p.communicate(timeout=120)[0] for p in procs]
        try:
            verdicts = []
            for p, out in zip(procs, outs):
                row = next(json.loads(ln) for ln in out.splitlines()
                           if ln.startswith("{"))
                verdicts.append((p.returncode, row))
            codes = sorted(rc for rc, _ in verdicts)
            assert codes == [0, 3], (codes, outs)
            admitted = [v for rc, v in verdicts if rc == 0]
            refused = [v for rc, v in verdicts if rc == 3]
            assert admitted[0]["verdict"] == "ADMITTED"
            assert refused[0]["verdict"] == "REFUSED"
            assert refused[0]["error"] in ("slot_taken",
                                           "slot_reserved")
            # the loser never built a service: no journal anywhere
            assert [p for p in Path(tmp_path).rglob("*")
                    if p.is_file()] == []
        finally:
            router.close(timeout=10)


class TestRefusalOutsideLock:
    """Regression: `_admit` used to send the structured refusal while
    HOLDING the router lock — a loser with a wedged socket stalled the
    sweep/respawn path for the whole fleet. The refusal decision is
    made under the lock; the send must happen after release."""

    def test_wedged_loser_does_not_stall_router_lock(self, tmp_path):
        router = SwarmRouter(RouterConfig(journal_root=str(tmp_path),
                                          slots=1, respawn=False))
        in_send = threading.Event()
        release = threading.Event()

        class _WedgedChan:
            name = "wedged-refusal-chan"

            def send_bytes(self, raw):
                in_send.set()
                assert release.wait(10.0), "never released"

            def flush(self):
                pass

            def close(self):
                pass

        # unknown slot -> guaranteed refusal path
        raw = wire._frame(wire.K_HELLO, {
            "client": "proc.w999.0", "role": "procworker",
            "slot": 999, "incarnation": 0, "pid": 1})
        t = threading.Thread(target=router._admit,
                             args=(_WedgedChan(), raw), daemon=True)
        try:
            t.start()
            assert in_send.wait(5.0), "refusal send never started"
            # the refusal send is wedged mid-flight: the router lock
            # must be FREE (pre-fix, this acquire deadlocked until
            # the send timed out)
            assert router._lock.acquire(timeout=2.0), \
                "router lock held across the refusal send"
            router._lock.release()
        finally:
            release.set()
            t.join(5.0)
            router._sup.close()
        assert not t.is_alive()
        snap = router.telemetry.snapshot()["metrics"]
        assert snap["router_hello_refused_total"]["value"] == 1
