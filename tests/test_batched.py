"""Batched multi-trial rollout engine tests.

Pins the three tentpole claims of the batched trials harness:

1. `sim.batched_rollout` (vmap over the trial axis, shared decimation
   phase) is BIT-IDENTICAL to B serial `sim.rollout` calls with the same
   seeds, for every assignment mode and both information models;
2. the on-device supervisor summaries (`sim.summary`) equal host
   recomputation over the full per-tick trace;
3. the batched trials driver (`harness.trials.run_trial_batch` +
   `supervisor.SummaryTrialFSM`) reaches tick-identical FSM decisions to
   the serial reference driver.
"""
import dataclasses

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from aclswarm_tpu import sim
from aclswarm_tpu.core.types import ControlGains, SafetyParams, make_formation
from aclswarm_tpu.harness import supervisor, trials
from aclswarm_tpu.harness.supervisor import NAMES, SummaryTrialFSM, TrialFSM
from aclswarm_tpu.sim import summary as sumlib


def _batch_problem(B, n, seed=0, flying=True, localization=False):
    rng = np.random.default_rng(seed)
    adj = np.ones((n, n)) - np.eye(n)
    forms, states = [], []
    for _ in range(B):
        pts = rng.normal(size=(n, 3)) * 5
        gains = rng.normal(size=(n, n, 3, 3)) * 0.01
        forms.append(make_formation(jnp.asarray(pts), jnp.asarray(adj),
                                    jnp.asarray(gains)))
        states.append(sim.init_state(
            rng.normal(size=(n, 3)) * 5 + np.array([0, 0, 2.0]),
            flying=flying, localization=localization))
    sp = SafetyParams(bounds_min=jnp.asarray([-50.0, -50.0, 0.0]),
                     bounds_max=jnp.asarray([50.0, 50.0, 20.0]))
    bstate = jax.tree.map(lambda *xs: jnp.stack(xs), *states)
    bform = jax.tree.map(lambda *xs: jnp.stack(xs), *forms)
    return states, forms, bstate, bform, sp


METRIC_FIELDS = ("distcmd_norm", "ca_active", "assign_valid", "reassigned",
                 "auctioned", "q", "mode", "v2f")


def _assert_bit_identical(mets, bm, finals, bf):
    for b in range(len(mets)):
        for name in METRIC_FIELDS:
            a = np.asarray(getattr(mets[b], name))
            bb = np.asarray(getattr(bm, name))[:, b]
            assert np.array_equal(a, bb), (b, name)
        np.testing.assert_array_equal(np.asarray(finals[b].swarm.q),
                                      np.asarray(bf.swarm.q)[b])
        np.testing.assert_array_equal(np.asarray(finals[b].swarm.vel),
                                      np.asarray(bf.swarm.vel)[b])
        np.testing.assert_array_equal(np.asarray(finals[b].v2f),
                                      np.asarray(bf.v2f)[b])


@pytest.mark.parametrize("assignment", ["auction", "sinkhorn", "cbaa"])
def test_batched_rollout_bit_parity_truth(assignment):
    """vmap over trials == B serial rollouts, bit for bit (ground-truth
    information model, all three assignment paths)."""
    B, n, T = 3, 6, 130
    states, forms, bstate, bform, sp = _batch_problem(B, n, seed=1)
    cfg = sim.SimConfig(assignment=assignment, assign_every=60,
                        flight_fsm=True)
    finals, mets = [], []
    for s, f in zip(states, forms):
        fs, m = sim.rollout(s, f, ControlGains(), sp, cfg, T)
        finals.append(fs)
        mets.append(m)
    bf, bm = sim.batched_rollout(bstate, bform, ControlGains(), sp, cfg, T)
    _assert_bit_identical(mets, bm, finals, bf)


def test_batched_rollout_bit_parity_flooded():
    """Same parity with the flooded localization model (CBAA consumes the
    estimate tables; the flood cond keys off the shared tick)."""
    B, n, T = 2, 6, 130
    states, forms, bstate, bform, sp = _batch_problem(
        B, n, seed=2, localization=True)
    cfg = sim.SimConfig(assignment="cbaa", assign_every=60,
                        localization="flooded", flight_fsm=True)
    finals, mets = [], []
    for s, f in zip(states, forms):
        fs, m = sim.rollout(s, f, ControlGains(), sp, cfg, T)
        finals.append(fs)
        mets.append(m)
    bf, bm = sim.batched_rollout(bstate, bform, ControlGains(), sp, cfg, T)
    _assert_bit_identical(mets, bm, finals, bf)
    for b in range(B):
        np.testing.assert_array_equal(np.asarray(finals[b].loc.est),
                                      np.asarray(bf.loc.est)[b])
        np.testing.assert_array_equal(np.asarray(finals[b].loc.age),
                                      np.asarray(bf.loc.age)[b])


def test_assign_enabled_gate_holds_assignment():
    """assign_enabled=False freezes v2f and suppresses auction events —
    the batched driver's pre-dispatch hover gate."""
    _, forms, bstate, bform, sp = _batch_problem(2, 6, seed=3)
    cfg = sim.SimConfig(assignment="auction", assign_every=30)
    bstate = bstate.replace(
        assign_enabled=jnp.asarray([True, False]))
    v2f0 = np.asarray(bstate.v2f).copy()
    bf, bm = sim.batched_rollout(bstate, bform, ControlGains(), sp, cfg, 90)
    auct = np.asarray(bm.auctioned)
    assert auct[:, 0].any()            # enabled trial auctions normally
    assert not auct[:, 1].any()        # gated trial never auctions
    assert not np.asarray(bm.reassigned)[:, 1].any()
    np.testing.assert_array_equal(np.asarray(bf.v2f)[1], v2f0[1])


def test_summary_matches_host_recompute():
    """On-device supervisor summaries == host recomputation on the full
    per-tick trace: windowed predicates, takeoff, EWMA distance, and the
    chunk-carry continuity across chunk boundaries."""
    B, n, T, W = 2, 6, 150, 20
    states, forms, bstate, bform, sp = _batch_problem(B, n, seed=4)
    cfg = sim.SimConfig(assignment="auction", assign_every=50)
    _, bm = sim.batched_rollout(bstate, bform, ControlGains(), sp, cfg, T)

    # chunked device reduction (two chunks exercise the carry)
    chunk = 75
    carry = sumlib.init_carry(n, W, dtype=bm.q.dtype, batch=B)
    chunks = []
    for c0 in range(0, T, chunk):
        part = jax.tree.map(lambda x: jnp.moveaxis(x[c0:c0 + chunk], 1, 0),
                            bm)
        summ, carry = jax.vmap(
            lambda m, c: sumlib.summarize_chunk(m, c, W, 1.0,
                                                pose_every=5))(part, carry)
        chunks.append(summ)

    for b in range(B):
        dn = np.asarray(bm.distcmd_norm)[:, b]
        ca = np.asarray(bm.ca_active)[:, b].astype(float)
        q = np.asarray(bm.q)[:, b]
        conv = np.concatenate([np.asarray(c.conv_all[b]) for c in chunks])
        grid = np.concatenate([np.asarray(c.grid_any[b]) for c in chunks])
        toff = np.concatenate([np.asarray(c.taken_off[b]) for c in chunks])
        rm_dn = supervisor.rolling_mean(dn, W)
        rm_ca = supervisor.rolling_mean(ca, W)
        full = ~np.isnan(rm_dn).any(axis=1)      # full-window ticks only
        np.testing.assert_array_equal(
            conv[full], np.all(rm_dn[full] < 1.0, axis=1))
        np.testing.assert_array_equal(
            grid[full], np.any(rm_ca[full] > 0.95, axis=1))
        np.testing.assert_array_equal(
            toff, np.all(np.abs(q[:, :, 2] - 1.0) < 0.05, axis=1))
        # trial-cumulative EWMA distance at the final chunk boundary
        np.testing.assert_allclose(
            np.asarray(chunks[-1].cumdist[b]),
            supervisor.distance_traveled(q), rtol=1e-9, atol=1e-12)
        # decimated pose trace: every pose_every-th tick of each chunk
        qd = np.concatenate([np.asarray(c.q_dec[b]) for c in chunks])
        np.testing.assert_array_equal(
            qd, np.concatenate([q[:chunk][::5], q[chunk:][::5]]))


# --------------------------------------------------------------------------
# SummaryTrialFSM == TrialFSM on synthetic signal traces (incl. gridlock)
# --------------------------------------------------------------------------

def _drive_serial(fsm: TrialFSM, q, dn, ca, events, chunk):
    """The serial driver's FSM loop (`trials.run_trial`): per-tick steps,
    chunk-latency actions, post-dispatch event suppression and the
    formation_just_received injection."""
    T = q.shape[0]
    just_received = False
    pending = False
    for c0 in range(0, T, chunk):
        if fsm.done:
            break
        suppress = False
        if pending:                 # dispatch applied at chunk boundary
            just_received = True
            pending = False
        for t in range(c0, min(c0 + chunk, T)):
            event = bool(events[t])
            if just_received and bool(events[t]):
                event = True
                just_received = False
            event = event and not suppress
            action = fsm.step(q[t], dn[t], ca[t], event)
            if action == "dispatch":
                suppress = True
                pending = True
            if fsm.done:
                break


def _drive_summary(fsm: SummaryTrialFSM, q, dn, ca, events, chunk, W):
    """The batched driver's loop: per-chunk summary arrays only."""
    T = q.shape[0]
    rm_dn = supervisor.rolling_mean(dn, W)
    rm_ca = supervisor.rolling_mean(ca.astype(float), W)
    conv = np.all(np.nan_to_num(rm_dn, nan=np.inf) < 1.0, axis=1)
    grid = np.any(np.nan_to_num(rm_ca, nan=0.0) > 0.95, axis=1)
    toff = np.all(np.abs(q[:, :, 2] - 1.0) < 0.05, axis=1)
    # continuous EWMA cumulative distance (what the device integrates)
    fx, fy = q[0, :, 0].copy(), q[0, :, 1].copy()
    cum = np.zeros((T, q.shape[1]))
    run = np.zeros(q.shape[1])
    for t in range(1, T):
        nx = 0.98 * fx + 0.02 * q[t, :, 0]
        ny = 0.98 * fy + 0.02 * q[t, :, 1]
        run += np.hypot(nx - fx, ny - fy)
        fx, fy = nx, ny
        cum[t] = run
    pending = False
    for c0 in range(0, T, chunk):
        if fsm.done:
            break
        if pending:
            fsm.formation_dispatched()
            pending = False
        e1 = min(c0 + chunk, T)
        acts = fsm.process_chunk(conv[c0:e1], grid[c0:e1], toff[c0:e1],
                                 events[c0:e1], events[c0:e1])
        fsm.observe_cumdist(cum[e1 - 1])
        if "dispatch" in acts:
            pending = True


def _synthetic_trial(T=4200, n=3, dt=0.1, gridlock=False):
    """Takeoff ramp -> auctions every 12 ticks -> (optional long CA burst
    = a gridlock episode) -> convergence -> second formation -> done."""
    q = np.zeros((T, n, 3))
    z = np.clip(np.arange(T) * 0.02, 0.0, 1.0)
    q[:, :, 2] = z[:, None]
    q[:, :, 0] = np.linspace(0, 4, T)[:, None] + np.arange(n)[None, :]
    dn = np.full((T, n), 3.0)
    dn[900:] = 0.1          # converges once flying
    dn[1500:2200] = 3.0     # second formation starts unconverged
    dn[2200:] = 0.1
    ca = np.zeros((T, n), bool)
    if gridlock:
        dn[900:] = 3.0      # never converges while the CA burst runs
        ca[700:1800, 0] = True
        dn[1900:] = 0.1
    events = np.zeros(T, bool)
    events[::12] = True
    return q, dn, ca, events


@pytest.mark.parametrize("gridlock", [False, True])
def test_summary_fsm_matches_trial_fsm(gridlock):
    """Tick-identical lifecycle decisions from per-chunk summaries vs the
    per-tick reference FSM — including the gridlock episode accounting."""
    dt, chunk = 0.1, 60
    W = max(1, int(round(supervisor.BUFFER_SECONDS / dt)))
    q, dn, ca, events = _synthetic_trial(dt=dt, gridlock=gridlock)
    a = TrialFSM(3, 2, takeoff_alt=1.0, dt=dt)
    b = SummaryTrialFSM(3, 2, takeoff_alt=1.0, dt=dt)
    _drive_serial(a, q, dn, ca, events, chunk)
    _drive_summary(b, q, dn, ca, events, chunk, W)
    assert NAMES[a.state] == NAMES[b.state]
    assert a.curr_formation_idx == b.curr_formation_idx
    np.testing.assert_allclose(b.times, a.times, rtol=0, atol=1e-9)
    np.testing.assert_allclose(b.time_avoidance, a.time_avoidance,
                               rtol=0, atol=1e-9)
    assert b.assignments == a.assignments
    assert b.tick_count == a.tick_count
    if gridlock:
        assert a.time_avoidance and a.time_avoidance[0] > 0
    # dist: chunk-boundary quantization + continuous filter (documented)
    np.testing.assert_allclose(b.dist, a.dist, rtol=0.25, atol=0.5)


def test_summary_fsm_trial_timeout():
    """The 600 s watchdog fires on the same tick in both FSMs."""
    dt, chunk = 0.1, 60
    W = max(1, int(round(supervisor.BUFFER_SECONDS / dt)))
    q, dn, ca, events = _synthetic_trial(T=7000, dt=dt)
    dn[:] = 3.0             # never converges -> watchdog
    a = TrialFSM(3, 2, takeoff_alt=1.0, dt=dt)
    b = SummaryTrialFSM(3, 2, takeoff_alt=1.0, dt=dt)
    _drive_serial(a, q, dn, ca, events, chunk)
    _drive_summary(b, q, dn, ca, events, chunk, W)
    assert a.state == supervisor.TrialState.TERMINATE
    assert b.state == supervisor.TrialState.TERMINATE
    assert b.tick_count == a.tick_count
    np.testing.assert_allclose(b.times, a.times, rtol=0, atol=1e-9)


# --------------------------------------------------------------------------
# end-to-end: batched trials driver vs the serial reference driver
# --------------------------------------------------------------------------

def _fsm_outcomes(fsm):
    return (NAMES[fsm.state], [round(t, 6) for t in fsm.times],
            list(fsm.assignments), [round(t, 6) for t in fsm.time_avoidance])


@pytest.mark.slow
def test_batched_driver_matches_serial(tmp_path):
    """Two simform8 trials through `run_trial_batch` reach the same FSM
    outcomes (states, convergence times, assignment counts, gridlock
    episodes) as the serial driver, and the CSV machinery works."""
    base = dict(formation="simform8", trials=2, seed=1, chunk_ticks=120,
                verbose=False)
    cfg_s = trials.TrialConfig(out=str(tmp_path / "s.csv"), **base)
    serial = [trials.run_trial(cfg_s, t) for t in range(2)]
    cfg_b = trials.TrialConfig(out=str(tmp_path / "b.csv"), batch=2, **base)
    batched = trials.run_trial_batch(cfg_b, [0, 1])
    for s, b in zip(serial, batched):
        assert _fsm_outcomes(s) == _fsm_outcomes(b)
        # distance is chunk-quantized in batched mode (documented)
        np.testing.assert_allclose(b.dist, s.dist, rtol=0.25, atol=0.5)
    # the run_trials wrapper writes reference-schema rows in trial order
    stats = trials.run_trials(cfg_b)
    assert stats["trials_completed"] == sum(b.completed for b in batched)


def test_batched_driver_requires_aligned_chunk():
    cfg = trials.TrialConfig(formation="simform8", trials=2, batch=2,
                             chunk_ticks=50, verbose=False)
    with pytest.raises(ValueError, match="multiple of assign_every"):
        trials.run_trial_batch(cfg, [0, 1])


def test_batched_driver_rejects_record_dir(tmp_path):
    cfg = trials.TrialConfig(formation="simform8", trials=2, batch=2,
                             chunk_ticks=120, verbose=False,
                             record_dir=str(tmp_path))
    with pytest.raises(ValueError, match="record_dir"):
        trials.run_trial_batch(cfg, [0, 1])


@pytest.mark.slow
def test_batched_wave_b8_matches_serial(tmp_path):
    """A full B=8 wave (the production batch shape class) against eight
    serial trials — FSM outcome parity at batch scale."""
    base = dict(formation="simform8", trials=8, seed=3, chunk_ticks=120,
                verbose=False)
    cfg_s = trials.TrialConfig(out=str(tmp_path / "s.csv"), **base)
    serial = [trials.run_trial(cfg_s, t) for t in range(8)]
    cfg_b = trials.TrialConfig(out=str(tmp_path / "b.csv"), batch=8, **base)
    batched = trials.run_trial_batch(cfg_b, list(range(8)))
    for s, b in zip(serial, batched):
        assert _fsm_outcomes(s) == _fsm_outcomes(b)


@pytest.mark.slow
def test_batched_rollout_bit_parity_b8():
    """Bit parity at B=8 (the wave size the benchmark artifact uses is
    16; 8 keeps the slow tier tractable on the 1-core CI box)."""
    B, n, T = 8, 6, 130
    states, forms, bstate, bform, sp = _batch_problem(B, n, seed=7)
    cfg = sim.SimConfig(assignment="sinkhorn", assign_every=60)
    finals, mets = [], []
    for s, f in zip(states, forms):
        fs, m = sim.rollout(s, f, ControlGains(), sp, cfg, T)
        finals.append(fs)
        mets.append(m)
    bf, bm = sim.batched_rollout(bstate, bform, ControlGains(), sp, cfg, T)
    _assert_bit_identical(mets, bm, finals, bf)
