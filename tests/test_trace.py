"""swarmtrace unit guarantees (aclswarm_tpu.telemetry.lifecycle /
postmortem / spans crash dump, benchmarks/bench_trend.py;
docs/OBSERVABILITY.md §swarmtrace).

The end-to-end proofs (trace across preemption, cross-worker
migration, the wire) live in tests/test_serve.py and
tests/test_serve_wire.py; this file pins the building blocks: the
event schema refuses malformed records at write time, the stream
survives a torn tail, the postmortem analyzer detects exactly the
violations it claims to (coverage holes, digest drift, trace drift,
missing terminals), the span ring dumps and disarms cleanly, and the
bench-trend gate fires on a >10% regression and only then.
"""
from __future__ import annotations

import json
import sys
from pathlib import Path

import pytest

from aclswarm_tpu.telemetry import (FlightRecorder, LifecycleLog, Span,
                                    SpanDump, TraceContext,
                                    mint_trace_id)
from aclswarm_tpu.telemetry import postmortem
from aclswarm_tpu.telemetry.lifecycle import make_event

REPO = Path(__file__).resolve().parents[1]
sys.path.insert(0, str(REPO / "benchmarks"))

pytestmark = pytest.mark.telemetry


# ------------------------------------------------------------ lifecycle

class TestLifecycleSchema:
    def test_mint_and_context(self):
        a, b = mint_trace_id(), mint_trace_id()
        assert a != b and len(a) == 16 and int(a, 16) >= 0
        ctx = TraceContext.mint("client.submit")
        assert ctx.parent_span == "client.submit" and len(ctx.trace_id) == 16

    def test_unknown_event_and_missing_fields_refused_at_write(self):
        with pytest.raises(ValueError, match="unknown lifecycle event"):
            make_event("teleported", request_id="r", trace_id="t", seq=0)
        with pytest.raises(ValueError, match="missing required"):
            make_event("chunk", request_id="r", trace_id="t", seq=0, k=1)
        with pytest.raises(ValueError, match="needs a request_id"):
            make_event("chunk", request_id=None, trace_id="t", seq=0,
                       k=1, digest=2, worker=0)

    def test_event_envelope(self):
        payload, man = make_event("resolved", request_id="r1",
                                  trace_id="t1", seq=7,
                                  status="completed", chunks=3)
        assert payload["request_id"] == "r1"
        assert payload["trace_id"] == "t1" and payload["seq"] == 7
        assert payload["t_wall"] > 0 and payload["t_mono"] > 0
        assert man["kind"] == "serve_event" and man["event"] == "resolved"

    def test_log_roundtrip_and_torn_tail(self, tmp_path):
        log = LifecycleLog(tmp_path / "events.log")
        tid = mint_trace_id()
        assert log.emit("submitted", request_id="r1", trace_id=tid,
                        kind="rollout", tenant="a")
        assert log.emit("chunk", request_id="r1", trace_id=tid,
                        k=0, digest=0xAB, worker=0)
        assert log.emit("failover", worker="0.1", reason="drill",
                        orphans=1)
        rows, torn = LifecycleLog.read(tmp_path / "events.log")
        assert not torn and [r["event"] for r in rows] \
            == ["submitted", "chunk", "failover"]
        assert rows[0]["trace_id"] == tid and rows[1]["k"] == 0
        assert rows[0]["seq"] == 0 and rows[1]["seq"] == 1
        # torn tail: a crash mid-append loses at most the last record
        raw = (tmp_path / "events.log").read_bytes()
        (tmp_path / "events.log").write_bytes(raw[:-7])
        rows2, torn2 = LifecycleLog.read(tmp_path / "events.log")
        assert torn2 and [r["event"] for r in rows2] \
            == ["submitted", "chunk"]


# ------------------------------------------------------------ postmortem

def _emit_clean_timeline(log: LifecycleLog, rid: str, tid: str,
                         chunks: int = 3, t0: float = 1000.0):
    dt = [t0]

    def e(event, **f):
        dt[0] += 0.1
        log.emit(event, request_id=rid, trace_id=tid, t_wall=dt[0], **f)

    e("submitted", kind="rollout", tenant="a")
    e("admitted", queue_depth=1)
    for k in range(chunks):
        e("batched", worker=0, round=k + 1, batch=1, chunk=k)
        e("chunk", k=k, digest=100 + k, worker=0)
        if k < chunks - 1:
            e("queued", reason="boundary")
    e("resolved", status="completed", chunks=chunks, latency_s=1.0)


class TestPostmortem:
    def test_clean_timeline_reconstructs(self, tmp_path):
        log = LifecycleLog(tmp_path / "events.log")
        tid = mint_trace_id()
        _emit_clean_timeline(log, "r1", tid)
        rep = postmortem.reconstruct(tmp_path)["requests"]["r1"]
        assert rep["complete"] and rep["gap_free"], rep["problems"]
        assert rep["trace_id"] == tid and rep["chunks"] == 3
        assert rep["status"] == "completed"
        st = rep["stages"]
        assert st["queue_wait_s"] == pytest.approx(0.1, abs=1e-6)
        assert st["device_s"] == pytest.approx(0.3, abs=1e-6)
        assert st["batch_wait_s"] == pytest.approx(0.2, abs=1e-6)
        assert st["total_s"] > 0

    def test_chunk_hole_detected(self, tmp_path):
        log = LifecycleLog(tmp_path / "events.log")
        tid = mint_trace_id()
        log.emit("submitted", request_id="r1", trace_id=tid,
                 kind="rollout", tenant="a")
        for k in (0, 2):               # chunk 1 missing
            log.emit("batched", request_id="r1", trace_id=tid,
                     worker=0, round=k, batch=1)
            log.emit("chunk", request_id="r1", trace_id=tid,
                     k=k, digest=k, worker=0)
        log.emit("resolved", request_id="r1", trace_id=tid,
                 status="completed", chunks=2)
        rep = postmortem.reconstruct(tmp_path)["requests"]["r1"]
        assert rep["complete"] and not rep["gap_free"]
        assert any("hole" in p for p in rep["problems"])

    def test_nonidentical_reexecution_detected(self, tmp_path):
        """At-least-once re-execution after a crash restore is legal —
        but ONLY bit-identically. A duplicate chunk with a different
        digest must fail the reconstruction."""
        log = LifecycleLog(tmp_path / "events.log")
        tid = mint_trace_id()
        log.emit("submitted", request_id="r1", trace_id=tid,
                 kind="rollout", tenant="a")
        for dg in (111, 222):          # chunk 0 twice, digests differ
            log.emit("batched", request_id="r1", trace_id=tid,
                     worker=0, round=1, batch=1)
            log.emit("chunk", request_id="r1", trace_id=tid,
                     k=0, digest=dg, worker=0)
        log.emit("resolved", request_id="r1", trace_id=tid,
                 status="completed", chunks=1)
        rep = postmortem.reconstruct(tmp_path)["requests"]["r1"]
        assert rep["duplicate_chunks"] == 1 and not rep["gap_free"]
        assert any("DIFFERENT digest" in p for p in rep["problems"])

    def test_trace_drift_and_missing_terminal_detected(self, tmp_path):
        log = LifecycleLog(tmp_path / "events.log")
        log.emit("submitted", request_id="r1", trace_id="aaaa",
                 kind="rollout", tenant="a")
        log.emit("chunk", request_id="r1", trace_id="bbbb",
                 k=0, digest=1, worker=0)
        rep = postmortem.reconstruct(tmp_path)["requests"]["r1"]
        assert not rep["complete"] and not rep["gap_free"]
        assert any("drift" in p for p in rep["problems"])
        assert any("terminal" in p for p in rep["problems"])

    def test_crash_before_first_batch_is_failover_gap_not_queue(
            self, tmp_path):
        """A request that crashed/recovered before EVER being scheduled
        must show the outage in failover_gap_s — charging it to queue
        wait would hide exactly the incident the tool exists to
        surface (review regression)."""
        log = LifecycleLog(tmp_path / "events.log")
        tid = mint_trace_id()

        def e(event, t, **f):
            log.emit(event, request_id="r1", trace_id=tid, t_wall=t, **f)

        e("submitted", 100.0, kind="rollout", tenant="a")
        e("admitted", 100.1)
        e("queued", 100.2, reason="recovery")    # crash + restart
        e("batched", 105.2, worker=0, round=1, batch=1)
        e("chunk", 105.3, k=0, digest=1, worker=0)
        e("resolved", 105.4, status="completed", chunks=1)
        rep = postmortem.reconstruct(tmp_path)["requests"]["r1"]
        assert rep["gap_free"], rep["problems"]
        st = rep["stages"]
        assert st["failover_gap_s"] == pytest.approx(5.0, abs=1e-6)
        assert st["queue_wait_s"] == pytest.approx(0.1, abs=1e-6)

    def test_accepted_but_traceless_is_loud(self, tmp_path):
        """A req frame with no events is a reconstruction failure, not
        an empty success — the soak counts on this."""
        from aclswarm_tpu.resilience import checkpoint as ckptlib
        (tmp_path / "req_ghost.req").write_bytes(ckptlib.dumps(
            {"params": {}}, ckptlib.make_manifest(
                "serve_req", "-", chunk=0, request_id="ghost",
                tenant="a", req_kind="assign", deadline_s=None,
                t_submit=0.0, trace_id="cafe")))
        rep = postmortem.reconstruct(tmp_path)
        assert rep["accepted"] == 1 and rep["complete"] == 0
        assert any("traceless" in p
                   for p in rep["requests"]["ghost"]["problems"])

    def test_cli_exit_codes(self, tmp_path, capsys):
        log = LifecycleLog(tmp_path / "events.log")
        _emit_clean_timeline(log, "ok1", mint_trace_id())
        assert postmortem.main([str(tmp_path)]) == 0
        out = capsys.readouterr().out
        assert "1 complete, 1 gap-free" in out and "resolved" in out
        log.emit("submitted", request_id="bad", trace_id="x",
                 kind="rollout", tenant="a")     # never resolves
        assert postmortem.main([str(tmp_path)]) == 1

    def test_fleet_all_gates_on_duplicate_terminals(self, tmp_path,
                                                    capsys):
        """Two slot journals, one request terminal in BOTH (the
        router re-placed a dead slot's work while the slot's successor
        independently recovered and honored the same promise). The
        plain merge stays exit 0 — bounded at-least-once duplicate
        compute is legal — but `--all` surfaces duplicate_terminals in
        the summary table and a nonzero count fails the gate."""
        a, b = tmp_path / "slot0", tmp_path / "slot1"
        la = LifecycleLog(a / "events.log")
        lb = LifecycleLog(b / "events.log")
        _emit_clean_timeline(la, "r1", mint_trace_id())
        _emit_clean_timeline(la, "dup", "feed", t0=2000.0)
        _emit_clean_timeline(lb, "dup", "feed", t0=2000.0)
        rep = postmortem.fleet_reconstruct([a, b])
        assert rep["losses"] == [] and \
            rep["duplicate_terminals"] == ["dup"]
        argv = [str(a), str(b)]
        assert postmortem.main(argv) == 0          # merge: legal
        capsys.readouterr()
        assert postmortem.main(argv + ["--all"]) == 1
        out = capsys.readouterr().out
        assert "duplicate_terminals 1" in out
        assert "DUPLICATE: dup" in out

    def test_fleet_all_clean_merge_passes(self, tmp_path, capsys):
        """The duplicate gate must not fail a clean migration-free
        two-journal merge."""
        a, b = tmp_path / "slot0", tmp_path / "slot1"
        _emit_clean_timeline(LifecycleLog(a / "events.log"), "r1",
                             mint_trace_id())
        _emit_clean_timeline(LifecycleLog(b / "events.log"), "r2",
                             mint_trace_id())
        assert postmortem.main([str(a), str(b), "--all"]) == 0
        out = capsys.readouterr().out
        assert "duplicate_terminals 0" in out


# ------------------------------------------------------- span crash dump

class TestSpanCrashDump:
    def test_dump_appends_header_and_rows(self, tmp_path):
        rec = FlightRecorder(capacity=8)
        for i in range(3):
            rec.record(Span(name="serve.round", t_wall=1.0 + i,
                            dur_s=0.5, attrs={"round": i}))
        dump = SpanDump(rec, tmp_path / "spans_dump.jsonl")
        assert dump.dump("test") == 3
        assert dump.dump("again") == 3          # appends accumulate
        lines = [json.loads(ln) for ln in
                 (tmp_path / "spans_dump.jsonl").read_text().splitlines()]
        headers = [ln for ln in lines if "span_dump" in ln]
        assert [h["span_dump"] for h in headers] == ["test", "again"]
        assert headers[0]["spans"] == 3 and headers[0]["recorded"] == 3
        spans = [ln for ln in lines if "span" in ln and "seq" in ln]
        assert len(spans) == 6
        assert spans[0]["span"] == "serve.round"

    def test_uninstalled_dump_is_noop(self, tmp_path):
        rec = FlightRecorder(capacity=4)
        rec.record(Span(name="x", t_wall=1.0, dur_s=0.1))
        dump = SpanDump(rec, tmp_path / "d.jsonl")
        dump.uninstall()
        assert dump.dump("late") == 0
        assert dump.recorder is None     # ring released, not retained
        assert not (tmp_path / "d.jsonl").exists()

    def test_sigterm_chain_restored_and_sigign_respected(self, tmp_path):
        """install/uninstall must leave the SIGTERM disposition exactly
        as found (no unbounded handler chains across service
        lifetimes), and a host's explicit SIG_IGN choice must survive
        the chained handler (review regression)."""
        import signal

        from aclswarm_tpu.telemetry import install_crash_dump

        prev = signal.getsignal(signal.SIGTERM)
        try:
            signal.signal(signal.SIGTERM, signal.SIG_IGN)
            rec = FlightRecorder(capacity=4)
            rec.record(Span(name="x", t_wall=1.0, dur_s=0.1))
            handle = install_crash_dump(rec, tmp_path / "d.jsonl")
            ours = signal.getsignal(signal.SIGTERM)
            assert ours is not signal.SIG_IGN     # hook installed
            # delivering through the hook dumps and then HONORS the
            # host's ignore choice — the process survives
            ours(signal.SIGTERM, None)
            assert (tmp_path / "d.jsonl").exists()
            assert signal.getsignal(signal.SIGTERM) is ours  # no reset
            handle.uninstall()
            assert signal.getsignal(signal.SIGTERM) is signal.SIG_IGN
        finally:
            signal.signal(signal.SIGTERM, prev)

    def test_service_flushes_ring_on_worker_death(self, tmp_path):
        """The worker-death path: the supervisor dumps the span ring to
        the journal when it declares a worker dead — the spans leading
        up to the death survive even though the worker could not flush
        itself (ISSUE 9 satellite)."""
        from aclswarm_tpu.resilience import crash as crashlib
        from aclswarm_tpu.resilience.crash import CrashPlan
        from aclswarm_tpu.serve import (ServiceConfig, SwarmService,
                                        bucket_of, place_slot)

        roll = {"n": 5, "ticks": 60, "chunk_ticks": 20, "seed": 3}
        svc = SwarmService(ServiceConfig(
            workers=2, max_batch=1, quantum_chunks=8,
            journal_dir=str(tmp_path), supervise_poll_s=0.02,
            rejoin_base_s=0.02))
        slot = place_slot(bucket_of("rollout", roll), [0, 1])
        crashlib.arm(CrashPlan(f"serve.w{slot}", 2, "raise"))
        res = svc.submit("rollout", roll).result(timeout=240)
        crashlib.arm(None)
        svc.close()
        assert res.ok and res.failovers >= 1
        dumpf = tmp_path / "spans_dump.jsonl"
        assert dumpf.is_file()
        lines = [json.loads(ln)
                 for ln in dumpf.read_text().splitlines()]
        headers = [ln for ln in lines if "span_dump" in ln]
        assert any("declared dead" in h["span_dump"] for h in headers)
        assert any(ln.get("span") == "serve.round" for ln in lines)


# ------------------------------------------------------------ bench trend

def _write_round(d: Path, n: int, parsed: dict):
    (d / f"BENCH_r{n:02d}.json").write_text(json.dumps(
        {"n": n, "cmd": "python bench.py", "rc": 0,
         "tail": "", "parsed": parsed}))


class TestBenchTrend:
    def test_regression_gate_fires_and_only_then(self, tmp_path):
        import bench_trend

        _write_round(tmp_path, 1, {"metric": "roll_hz", "value": 100.0,
                                   "unit": "Hz"})
        _write_round(tmp_path, 2, {"metric": "roll_hz", "value": 95.0,
                                   "unit": "Hz"})
        lines, reg = bench_trend.trend(tmp_path, 0.10)
        assert reg == 0                      # -5% is inside the bar
        _write_round(tmp_path, 3, {"metric": "roll_hz", "value": 80.0,
                                   "unit": "Hz"})
        lines, reg = bench_trend.trend(tmp_path, 0.10)
        assert reg == 1
        assert any("REGRESSION" in ln for ln in lines)
        assert bench_trend.main(["--dir", str(tmp_path)]) == 1
        assert bench_trend.main(["--dir", str(tmp_path), "--soft"]) == 0

    def test_serve_stage_rows_matched_by_stage_n_backend(self, tmp_path):
        """PR-11 satellite: serve_stage rows must trend per
        (name, stage, n, backend), never name-alone — a regenerated
        breakdown writing pack after unpack would otherwise compare
        the two stages across rounds (fake deltas both ways)."""
        import bench_trend

        def stage_row(stage, value):
            return {"name": "serve_stage", "stage": stage, "n": 5,
                    "backend": "cpu", "value": value, "unit": "s"}

        # same-stage improvement + cross-stage magnitude gap: keyed by
        # name alone, round 2's pack (0.001) vs round 1's unpack (0.9)
        # would read as a 99.9% swing
        _write_round(tmp_path, 1, stage_row("unpack", 0.9))
        _write_round(tmp_path, 2, stage_row("pack", 0.001))
        lines, reg = bench_trend.trend(tmp_path, 0.10)
        assert reg == 0                    # distinct series: no delta
        assert any("stage=pack" in ln for ln in lines)
        assert any("stage=unpack" in ln for ln in lines)
        # a REAL same-stage regression still gates
        _write_round(tmp_path, 3, stage_row("pack", 0.5))
        lines, reg = bench_trend.trend(tmp_path, 0.10)
        assert reg == 1
        # discriminator-free rows keep their bare-name series
        assert bench_trend.series_key(
            {"metric": "roll_hz", "value": 1.0}) == "roll_hz"
        assert bench_trend.series_key(
            stage_row("pack", 1.0)) == "serve_stage [stage=pack, " \
                                       "n=5, backend=cpu]"

    def test_error_rounds_incomparable_and_latency_direction(
            self, tmp_path):
        import bench_trend

        # an errored round must not count as a 100% regression
        _write_round(tmp_path, 1, {"metric": "roll_hz", "value": 100.0,
                                   "unit": "Hz"})
        _write_round(tmp_path, 2, {"metric": "roll_hz", "value": 0.0,
                                   "unit": "Hz", "error": "wedged"})
        lines, reg = bench_trend.trend(tmp_path, 0.10)
        assert reg == 0 and any("incomparable" in ln for ln in lines)
        # lower-better units: a latency DROP is an improvement, a rise
        # past the bar is the regression
        _write_round(tmp_path, 3, {"metric": "lat_s", "value": 2.0,
                                   "unit": "s"})
        _write_round(tmp_path, 4, {"metric": "lat_s", "value": 1.0,
                                   "unit": "s"})
        _, reg = bench_trend.trend(tmp_path, 0.10)
        assert reg == 0
        _write_round(tmp_path, 5, {"metric": "lat_s", "value": 1.5,
                                   "unit": "s"})
        _, reg = bench_trend.trend(tmp_path, 0.10)
        assert reg == 1

    def test_recovered_dip_does_not_gate(self, tmp_path):
        """Only the transition INTO the newest comparable round gates:
        a historical dip the trajectory has since recovered from is
        reported (visible) but must not redden the gate forever
        (review regression)."""
        import bench_trend

        _write_round(tmp_path, 1, {"metric": "roll_hz", "value": 100.0,
                                   "unit": "Hz"})
        _write_round(tmp_path, 2, {"metric": "roll_hz", "value": 80.0,
                                   "unit": "Hz"})
        _write_round(tmp_path, 3, {"metric": "roll_hz", "value": 120.0,
                                   "unit": "Hz"})
        lines, reg = bench_trend.trend(tmp_path, 0.10)
        assert reg == 0, lines
        assert any("since superseded" in ln for ln in lines)
        assert bench_trend.main(["--dir", str(tmp_path)]) == 0

    def test_rounds_ordered_numerically_not_lexically(self, tmp_path):
        """BENCH_r100 sorts before BENCH_r11 lexically; the trend must
        compare rounds in NUMERIC order and gate on the true newest
        round (review regression)."""
        import json as jsonlib

        import bench_trend

        for n, v in ((2, 100.0), (11, 100.0), (100, 80.0)):
            (tmp_path / f"BENCH_r{n:02d}.json").write_text(jsonlib.dumps(
                {"n": n, "cmd": "", "rc": 0, "tail": "",
                 "parsed": {"metric": "roll_hz", "value": v,
                            "unit": "Hz"}}))
        rounds = bench_trend.load_rounds(tmp_path)
        assert [r for r, _ in rounds] == [2, 11, 100]
        _, reg = bench_trend.trend(tmp_path, 0.10)
        assert reg == 1          # r100 IS the newest; its -20% gates

    def test_real_repo_rounds_parse(self):
        import bench_trend

        lines, reg = bench_trend.trend(REPO, 0.10)
        assert any("sinkhorn_assign_n1000_hz" in ln for ln in lines)
        # the committed overload surface contributes its goodput/p99
        # rows at the 1x and 10x levels (ISSUE-13 satellite)
        assert any("serve_overload_goodput" in ln and "level=10x" in ln
                   for ln in lines)
        assert any("serve_overload_p99" in ln and "level=1x" in ln
                   for ln in lines)
        assert reg == 0

    def test_parsed_rows_list_and_overload_pseudo_round(self, tmp_path):
        """ISSUE-13 satellite: captures may carry a ``parsed_rows``
        LIST (multi-metric rounds), overload rows key by their
        ``level`` discriminator, and the committed serve_overload
        artifact joins the trend as the round AFTER the newest capture
        — so a capture carrying the same series gates the artifact's
        transition."""
        import json as jsonlib

        import bench_trend

        def orow(level, name, value, unit):
            return {"name": name, "level": level, "n": 5,
                    "backend": "cpu", "value": value, "unit": unit}

        # round 1: a capture with overload series via parsed_rows
        (tmp_path / "BENCH_r01.json").write_text(jsonlib.dumps(
            {"n": 1, "cmd": "", "rc": 0, "tail": "", "parsed_rows": [
                orow("1x", "serve_overload_goodput", 10.0, "Hz"),
                orow("10x", "serve_overload_goodput", 10.0, "Hz"),
                orow("10x", "serve_overload_p99", 1.0, "s")]}))
        rounds = bench_trend.load_rounds(tmp_path)
        assert len(rounds) == 3
        # levels are distinct series: same name at 1x vs 10x never
        # cross-compares
        k1 = bench_trend.series_key(
            orow("1x", "serve_overload_goodput", 1, "Hz"))
        k10 = bench_trend.series_key(
            orow("10x", "serve_overload_goodput", 1, "Hz"))
        assert k1 != k10 and "level=1x" in k1
        # the committed artifact = the NEXT round: a goodput collapse
        # vs the capture gates
        rdir = tmp_path / "benchmarks" / "results"
        rdir.mkdir(parents=True)
        art = dict(level="10x", multiplier=10.0, n=5, backend="cpu",
                   value=5.0, p99_s=1.05, quick=False)
        (rdir / "serve_overload.json").write_text(jsonlib.dumps(art))
        lines, reg = bench_trend.trend(tmp_path, 0.10)
        assert reg == 1, lines      # 10 -> 5 Hz at 10x: -50% gates
        # a healthy artifact does not
        art["value"] = 10.2
        (rdir / "serve_overload.json").write_text(jsonlib.dumps(art))
        _, reg = bench_trend.trend(tmp_path, 0.10)
        assert reg == 0
        # quick rows never contribute
        art["quick"] = True
        (rdir / "serve_overload.json").write_text(jsonlib.dumps(art))
        assert bench_trend.overload_rows(rdir) == []
