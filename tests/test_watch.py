"""swarmwatch — time-series store, burn-rate engine, alert state
machine, service integration, and CLI (docs/OBSERVABILITY.md
§swarmwatch; marker `telemetry`).

Engine tests drive `evaluate(now=...)` with explicit clocks — no
sleeps, fully deterministic. Service tests pay the SwarmService cost
once per class and assert the live surface (health kind, device-time
accounting, persisted history) end to end.
"""
from __future__ import annotations

import json
import sys
from pathlib import Path

import pytest

from aclswarm_tpu.telemetry import MetricsRegistry
from aclswarm_tpu.telemetry.slo import (FIRING, OK, PENDING, SloEngine,
                                        SloSpec, default_slos)
from aclswarm_tpu.telemetry.timeseries import (Sampler, TimeSeriesStore,
                                               load_store)

sys.path.insert(0, str(Path(__file__).resolve().parents[1] / "benchmarks"))

pytestmark = pytest.mark.telemetry


# ---------------------------------------------------------------------------
# TimeSeriesStore

class TestTimeSeriesStore:
    def test_append_window_latest(self):
        s = TimeSeriesStore(capacity=16)
        for t in range(10):
            s.append("x", float(t), float(t * 2))
        assert s.latest("x") == (9.0, 18.0)
        w = s.window("x", 3.0, now=9.0)
        assert [p[0] for p in w] == [6.0, 7.0, 8.0, 9.0]
        assert s.window("unknown", 3.0) == []
        assert s.latest("unknown") is None

    def test_wraparound_keeps_newest_and_counts_drops(self):
        s = TimeSeriesStore(capacity=4)
        for t in range(7):
            s.append("x", float(t), float(t))
        pts = s.points("x")
        assert [p[0] for p in pts] == [3.0, 4.0, 5.0, 6.0]   # time order
        assert s.dropped == 3

    def test_window_delta_golden_reset_tolerant(self):
        """The docstring's golden case: samples 0,5,9,2,4 — the 9→2
        drop is a counter RESET (restarted worker), contributing the
        post-reset value, never a negative delta."""
        s = TimeSeriesStore(capacity=16)
        for t, v in enumerate([0, 5, 9, 2, 4]):
            s.append("c", float(t), float(v))
        assert s.window_delta("c", 100.0, now=4.0) == 13.0

    def test_rate_across_counter_reset(self):
        s = TimeSeriesStore(capacity=16)
        # 10 events, restart (reset to 2 post-restart events), 4 more:
        # 10 + 2 + 4 = 16 over 4 s — never a negative rate
        for t, v in [(0, 0), (2, 10), (3, 2), (4, 6)]:
            s.append("c", float(t), float(v))
        assert s.window_delta("c", 100.0, now=4.0) == 16.0
        assert s.rate("c", 100.0, now=4.0) == pytest.approx(16.0 / 4.0)
        assert s.rate("c", 100.0, now=4.0) > 0

    def test_underdetermined_windows_are_none_not_zero(self):
        s = TimeSeriesStore(capacity=8)
        assert s.window_delta("c", 10.0) is None
        s.append("c", 0.0, 5.0)
        assert s.window_delta("c", 10.0) is None   # one point: no delta
        assert s.rate("c", 10.0) is None

    def test_nan_sample_refused(self):
        s = TimeSeriesStore(capacity=8)
        s.append("x", 0.0, float("nan"))
        s.append("x", 1.0, float("inf"))
        assert s.points("x") == []

    def test_capacity_validated(self):
        with pytest.raises(ValueError):
            TimeSeriesStore(capacity=1)


# ---------------------------------------------------------------------------
# Sampler + persistence

def _reg_with_traffic() -> MetricsRegistry:
    reg = MetricsRegistry()
    reg.counter("serve_completed_total").inc(3)
    reg.gauge("serve_queue_depth").set(2)
    h = reg.histogram("serve_latency_s", {"tenant": "a"})
    for v in (0.1, 0.2, 0.3):
        h.observe(v)
    return reg


class TestSampler:
    def test_tick_flattens_registry(self):
        reg = _reg_with_traffic()
        store = TimeSeriesStore(capacity=32)
        smp = Sampler(reg, store, interval_s=1.0)
        vals = smp.tick(now=10.0)
        assert vals["serve_completed_total"] == 3.0
        assert vals["serve_queue_depth"] == 2.0
        assert "serve_latency_s{tenant=a}:p99" in vals
        assert "serve_latency_s{tenant=a}:count" in vals
        assert "spans_dropped_total" in vals
        assert store.latest("serve_completed_total") == (10.0, 3.0)
        assert smp.samples == 1 and smp.spent_s > 0

    def test_persist_and_load_store_round_trip(self, tmp_path):
        reg = _reg_with_traffic()
        store = TimeSeriesStore(capacity=32)
        log = tmp_path / "ts" / "timeseries.log"
        smp = Sampler(reg, store, interval_s=1.0, persist_path=log)
        smp.tick(now=1.0)
        reg.counter("serve_completed_total").inc(2)
        smp.tick(now=2.0)
        smp.stop(final_tick=False)
        loaded, ticks, torn = load_store(log)
        assert ticks == 2 and not torn
        assert loaded.points("serve_completed_total") == \
            store.points("serve_completed_total") == [(1.0, 3.0),
                                                      (2.0, 5.0)]

    def test_load_store_drops_torn_tail(self, tmp_path):
        reg = _reg_with_traffic()
        store = TimeSeriesStore(capacity=32)
        log = tmp_path / "timeseries.log"
        smp = Sampler(reg, store, interval_s=1.0, persist_path=log)
        smp.tick(now=1.0)
        smp.tick(now=2.0)
        smp.stop(final_tick=False)
        whole = log.read_bytes()
        log.write_bytes(whole[:-7])       # crash mid-append
        loaded, ticks, torn = load_store(log)
        assert torn and ticks == 1
        assert loaded.latest("serve_completed_total") == (1.0, 3.0)

    def test_hooks_run_and_failures_keep_the_cadence(self):
        reg = _reg_with_traffic()
        store = TimeSeriesStore(capacity=32)
        seen = []

        def probe():
            reg.gauge("serve_queue_depth").set(7)

        def on_sample(t):
            seen.append(t)
            raise RuntimeError("evaluator bug")

        smp = Sampler(reg, store, interval_s=1.0, probe=probe,
                      on_sample=on_sample)
        vals = smp.tick(now=5.0)
        assert vals["serve_queue_depth"] == 7.0   # probe ran first
        assert seen == [5.0]                      # hook ran
        assert smp.tick(now=6.0)                  # failure didn't wedge


# ---------------------------------------------------------------------------
# burn-rate engine + alert state machine

def _avail_spec(**kw) -> SloSpec:
    base = dict(name="availability", kind="availability", mode="burn",
                budget=0.1, burn_threshold=2.0, window_s=10.0,
                short_s=2.0, for_s=0.0, clear_s=2.0)
    base.update(kw)
    return SloSpec(**base)


def _feed(store, t, completed, failed):
    store.append("serve_completed_total", t, float(completed))
    store.append("serve_failed_total", t, float(failed))


class TestBurnRateGolden:
    def test_clean_traffic_burn_is_zero(self):
        store = TimeSeriesStore(capacity=64)
        eng = SloEngine([_avail_spec()], store)
        for t in range(8):
            _feed(store, float(t), completed=t * 5, failed=0)
            assert eng.evaluate(now=float(t)) == []
        v = eng.verdicts()["availability"]
        assert v["state"] == OK
        assert v["burn_short"] == 0.0 and v["burn_long"] == 0.0
        assert v["value"] == 1.0

    def test_golden_burn_value_and_firing(self):
        """50% failures against a 10% budget. Golden values: the alert
        fires at the FIRST evaluation where both windows breach —
        err history [0, 0.5] → mean 0.25 / 0.1 = burn 2.5 on both
        windows (>= threshold 2.0). By the last sample the short
        window holds only err-0.5 points → burn exactly 5.0, the long
        window [0, .5, .5, .5, .5] → mean 0.4 / 0.1 = 4.0."""
        store = TimeSeriesStore(capacity=64)
        events = []
        eng = SloEngine([_avail_spec()], store, emit=events.append)
        _feed(store, 0.0, 0, 0)
        eng.evaluate(now=0.0)
        transitions = []
        for t in range(1, 5):
            _feed(store, float(t), completed=t * 2, failed=t * 2)
            transitions += eng.evaluate(now=float(t))
        assert [e["state"] for e in transitions] == [FIRING]
        ev = transitions[0]
        assert ev["slo"] == "availability"
        assert ev["burn_short"] == pytest.approx(2.5)
        assert ev["burn_long"] == pytest.approx(2.5)
        assert events == transitions        # emit got the same records
        v = eng.verdicts()["availability"]
        assert v["state"] == FIRING
        assert v["burn_short"] == pytest.approx(5.0)
        assert v["burn_long"] == pytest.approx(4.0)

    def test_burn_requires_both_windows(self):
        """A long window still burning but a recovered short window
        must NOT re-breach (the multi-window rule: fast detection
        without paging on history)."""
        store = TimeSeriesStore(capacity=64)
        eng = SloEngine([_avail_spec(for_s=100.0)], store)
        _feed(store, 0.0, 0, 0)
        eng.evaluate(now=0.0)
        # errors for 4 samples, then clean recovery
        comp = fail = 0
        for t in range(1, 5):
            comp, fail = comp + 1, fail + 1
            _feed(store, float(t), comp, fail)
            eng.evaluate(now=float(t))
        assert eng._cells[("availability", "")].state == PENDING
        for t in range(5, 8):
            comp += 10
            _feed(store, float(t), comp, fail)
            eng.evaluate(now=float(t))
        cell = eng._cells[("availability", "")]
        # short window clean -> breach gone -> pending flap suppressed
        assert cell.state == OK
        assert cell.burn_short < 2.0 < cell.burn_long


def _worker_spec(**kw) -> SloSpec:
    base = dict(name="worker_up", kind="worker_up", mode="level",
                budget=1e-6, window_s=10.0, short_s=2.0, for_s=0.0,
                clear_s=2.0)
    base.update(kw)
    return SloSpec(**base)


class TestAlertStateMachine:
    def _up(self, store, t, w0=1.0, w1=1.0):
        store.append("serve_worker_up{worker=0}", t, w0)
        store.append("serve_worker_up{worker=1}", t, w1)

    def test_fire_and_resolve_per_label(self):
        store = TimeSeriesStore(capacity=64)
        events = []
        eng = SloEngine([_worker_spec()], store, emit=events.append)
        self._up(store, 0.0)
        assert eng.evaluate(now=0.0) == []
        self._up(store, 1.0, w0=0.0)           # worker 0 dies
        tr = eng.evaluate(now=1.0)
        assert [(e["state"], e["labels"]) for e in tr] == \
            [("firing", "{worker=0}")]
        v = eng.verdicts()["worker_up"]
        assert v["state"] == FIRING
        assert v["labels"] == {"{worker=0}": FIRING, "{worker=1}": OK}
        self._up(store, 2.0)                   # rejoin
        assert eng.evaluate(now=2.0) == []     # clear dwell not yet met
        self._up(store, 4.5)
        tr = eng.evaluate(now=4.5)
        assert [e["state"] for e in tr] == ["resolved"]
        assert eng.verdicts()["worker_up"]["state"] == OK
        assert eng.verdicts()["worker_up"]["fired"] == 1
        assert eng.firing() == []

    def test_flap_suppression_pending_never_fires(self):
        store = TimeSeriesStore(capacity=64)
        events = []
        eng = SloEngine([_worker_spec(for_s=3.0)], store,
                        emit=events.append)
        self._up(store, 0.0)
        eng.evaluate(now=0.0)
        self._up(store, 1.0, w0=0.0)           # blip
        assert eng.evaluate(now=1.0) == []     # pending, dwell unmet
        assert eng._cells[("worker_up", "{worker=0}")].state == PENDING
        self._up(store, 2.0)                   # recovered inside dwell
        assert eng.evaluate(now=2.0) == []
        assert eng._cells[("worker_up", "{worker=0}")].state == OK
        assert events == []                    # the flap left no record

    def test_dwell_fires_after_for_s(self):
        store = TimeSeriesStore(capacity=64)
        eng = SloEngine([_worker_spec(for_s=3.0)], store)
        self._up(store, 0.0)
        eng.evaluate(now=0.0)
        for t in (1.0, 2.0, 3.0):
            self._up(store, t, w0=0.0)
            assert eng.evaluate(now=t) == []
        self._up(store, 4.0, w0=0.0)           # dwell (3s) met at 4.0
        tr = eng.evaluate(now=4.0)
        assert [e["state"] for e in tr] == ["firing"]

    def test_rebreach_resets_the_clear_clock(self):
        store = TimeSeriesStore(capacity=64)
        eng = SloEngine([_worker_spec(clear_s=2.0)], store)
        self._up(store, 0.0)
        eng.evaluate(now=0.0)
        self._up(store, 1.0, w0=0.0)
        assert len(eng.evaluate(now=1.0)) == 1      # firing
        self._up(store, 2.0)                        # clear starts
        eng.evaluate(now=2.0)
        self._up(store, 3.0, w0=0.0)                # re-breach!
        eng.evaluate(now=3.0)
        self._up(store, 4.5)                        # clear restarts
        assert eng.evaluate(now=4.5) == []          # old clock was reset
        self._up(store, 6.6)
        tr = eng.evaluate(now=6.6)
        assert [e["state"] for e in tr] == ["resolved"]

    def test_alert_counter_rides_the_registry(self):
        reg = MetricsRegistry()
        store = TimeSeriesStore(capacity=64)
        eng = SloEngine([_worker_spec()], store, registry=reg)
        self._up(store, 0.0, w0=0.0)
        eng.evaluate(now=0.0)
        snap = reg.snapshot()["metrics"]
        assert snap["watch_alerts_total{slo=worker_up,state=firing}"][
            "value"] == 1


class TestSpecValidation:
    def test_bad_specs_refused(self):
        with pytest.raises(ValueError):
            SloSpec(name="x", kind="availability", mode="sideways")
        with pytest.raises(ValueError):
            SloSpec(name="x", kind="availability", budget=0.0)
        with pytest.raises(ValueError):
            SloSpec(name="x", kind="availability", short_s=60.0,
                    window_s=30.0)
        store = TimeSeriesStore(capacity=8)
        with pytest.raises(ValueError):
            SloEngine([SloSpec(name="x", kind="nope")], store)
        spec = default_slos()[0]
        with pytest.raises(ValueError):
            SloEngine([spec, spec], store)      # duplicate names

    def test_default_catalog_covers_the_offline_bars(self):
        names = {s.name for s in default_slos()}
        assert names == {"availability", "latency_p99", "goodput",
                         "silent_loss", "worker_up", "queue_saturation"}


# ---------------------------------------------------------------------------
# span-drop export satellite

class TestSpanDropExport:
    def test_dropped_spans_are_first_class_metrics(self):
        reg = MetricsRegistry(spans=2)
        for i in range(5):
            with reg.span("w"):
                pass
        text = reg.prometheus_text()
        assert "spans_recorded_total 5" in text
        assert "spans_dropped_total 3" in text
        rows = [json.loads(ln) for ln in reg.to_jsonl().splitlines()]
        census = [r for r in rows
                  if r.get("name") == "spans_dropped_total"]
        assert census and census[0]["value"] == 3

    def test_span_dump_carries_drops(self, tmp_path):
        from aclswarm_tpu.telemetry.spans import (FlightRecorder, Span,
                                                  SpanDump)
        rec = FlightRecorder(capacity=2)
        for i in range(4):
            rec.record(Span(name="s", t_wall=float(i), dur_s=0.0))
        dump = SpanDump(rec, tmp_path / "d.jsonl")
        assert dump.drops == 0
        assert dump.dump("test") == 2
        assert dump.drops == 2
        header = json.loads(
            (tmp_path / "d.jsonl").read_text().splitlines()[0])
        assert header["dropped"] == 2


# ---------------------------------------------------------------------------
# service integration (one service per test — kept tiny)

class TestServeIntegration:
    def test_queue_depth_gauge_is_fresh_off_boundaries(self):
        """The freshness regression (satellite): an idle service (no
        worker running — start=False, so there are NO chunk boundaries)
        must still show current depth on submit/cancel."""
        from aclswarm_tpu.serve import ServiceConfig, SwarmService

        svc = SwarmService(ServiceConfig(max_batch=1), start=False)
        g = lambda: svc.telemetry.gauge("serve_queue_depth").value  # noqa: E731
        assert g() == 0
        t1 = svc.submit("assign", {"n": 4, "seed": 0}, tenant="a",
                        request_id="w-a")
        assert g() == 1          # fresh at submit, no boundary needed
        svc.submit("assign", {"n": 4, "seed": 1}, tenant="b",
                   request_id="w-b")
        assert g() == 2
        assert svc.cancel("w-a") == "queued"
        assert g() == 1          # fresh at cancel too
        svc.close(drain=False, timeout=1.0)
        assert t1.done

    def test_watch_service_end_to_end(self, tmp_path):
        """health kind + per-tenant device accounting + persisted
        history + CLI replay + postmortem --all, one service."""
        from aclswarm_tpu.serve import ServiceConfig, SwarmService
        from aclswarm_tpu.telemetry import postmortem
        from aclswarm_tpu.telemetry import watch as watchcli

        d = tmp_path / "journal"
        svc = SwarmService(ServiceConfig(
            max_batch=1, journal_dir=str(d), watch=True,
            watch_interval_s=0.05))
        res = svc.submit("assign", {"n": 5, "seed": 0},
                         tenant="alpha").result(120)
        assert res.ok
        svc.watch.sampler.tick()          # deterministic extra sample
        h = svc.submit("health", {}, tenant="ops").result(60)
        assert h.ok and h.value["watch_enabled"]
        verdicts = h.value["watch"]["verdicts"]
        assert set(verdicts) == {s.name for s in default_slos()}
        assert h.value["workers"]["total"] == 1
        assert h.value["watch"]["firing"] == []
        # per-tenant device-time accounting: the assign's execution
        # wall landed on its tenant+kind counter
        st = svc.serve_stats()
        assert st.device_s.get("alpha", {}).get("assign", 0.0) > 0.0
        assert "health" in st.device_s.get("ops", {})
        svc.close()
        # history survives the process boundary: disk alone
        loaded, ticks, torn = load_store(d / "timeseries.log")
        assert ticks > 0 and not torn
        assert loaded.latest("serve_completed_total")[1] >= 2.0
        assert watchcli.main(["--log", str(d / "timeseries.log")]) == 0
        assert postmortem.main([str(d), "--all"]) == 0

    def test_health_kind_without_watch_still_reports_liveness(self):
        from aclswarm_tpu.serve import ServiceConfig, SwarmService

        with SwarmService(ServiceConfig(max_batch=1)) as svc:
            h = svc.submit("health", {}).result(60)
            assert h.ok
            assert h.value["watch_enabled"] is False
            assert h.value["watch"] is None
            assert h.value["workers"]["total"] == 1
            assert h.value["alive"] is True


# ---------------------------------------------------------------------------
# watch CLI + schema guard

class TestWatchCli:
    def test_replay_surfaces_alert_transitions(self, tmp_path):
        """A persisted history containing a worker death must replay to
        the same firing/resolved pair the live engine produced."""
        reg = MetricsRegistry()
        up = reg.gauge("serve_worker_up", {"worker": "0"})
        store = TimeSeriesStore(capacity=64)
        log = tmp_path / "timeseries.log"
        smp = Sampler(reg, store, interval_s=1.0, persist_path=log)
        up.set(1)
        smp.tick(now=0.0)
        up.set(0)
        smp.tick(now=1.0)
        up.set(1)
        for t in (2.0, 3.0, 4.0, 5.0):
            smp.tick(now=t)
        smp.stop(final_tick=False)
        from aclswarm_tpu.telemetry.watch import replay_log
        rep = replay_log(log)
        assert rep["ticks"] == 6 and not rep["torn_tail"]
        states = [(e["slo"], e["state"]) for e in rep["transitions"]]
        assert states == [("worker_up", "firing"),
                          ("worker_up", "resolved")]
        assert rep["firing"] == []

    def test_cli_exit_codes(self, tmp_path):
        from aclswarm_tpu.telemetry import watch as watchcli

        assert watchcli.main(["--log", str(tmp_path / "nope.log")]) == 2
        assert watchcli.main(["--tcp", "not-an-address"]) == 2


class TestSloDetectionSchema:
    GOOD = {
        "name": "slo_detection", "n": 8, "backend": "cpu", "workers": 3,
        "tenants": 3, "accepted": 7, "completed": 7, "silent_losses": 0,
        "kills": 3, "detected": 3, "already_firing": 0,
        "alerts_fired": 3, "alerts_resolved": 3,
        "detection_s": {"p50": 0.05, "p95": 0.14, "max": 0.15},
        "bound_s": 2.0, "watch_interval_s": 0.2,
        "sampler_overhead_frac": 0.008, "sampler_samples": 95,
        "persist_lost": 0, "persisted_ticks": 96, "series": 100,
        "control_accepted": 7, "control_completed": 7,
        "false_positives": 0, "control_overhead_frac": 0.007,
        "wall_s": 22.0, "quick": False,
    }

    def _check(self, **patch):
        from check_results import check_slo_detection
        row = dict(self.GOOD)
        row.update(patch)
        return check_slo_detection(row, "t")

    def test_good_row_passes(self):
        assert self._check() == []

    def test_bars_enforced_as_schema(self):
        assert self._check(detected=2)                      # missed kill
        assert self._check(false_positives=1)               # noisy alarm
        assert self._check(sampler_overhead_frac=0.03)      # overhead
        assert self._check(
            detection_s={"p50": 0.05, "p95": 0.14, "max": 2.5})  # > bound
        assert self._check(bound_s=60.0)            # not a real bound
        assert self._check(persisted_ticks=0)       # history unreadable
        assert self._check(silent_losses=1)
        assert self._check(kills=2, detected=2)     # committed owes >= 3
        assert self._check(extra_key=1)             # exact key set
        assert self._check(completed=6)             # ledger reconciles

    def test_committed_artifact_on_disk_passes(self):
        from check_results import RESULTS, check_file
        path = RESULTS / "slo_detection.json"
        assert path.exists(), "committed slo_detection.json missing"
        assert check_file(path) == []


class TestBenchTrendRows:
    def test_slo_detection_joins_the_trend(self, tmp_path):
        import bench_trend
        res = tmp_path
        (res / "slo_detection.json").write_text(json.dumps(
            dict(TestSloDetectionSchema.GOOD)))
        rows = bench_trend.slo_detection_rows(res)
        assert rows == [{"name": "slo_detection_p95", "value": 0.14,
                         "unit": "s", "n": 8, "backend": "cpu"}]
        # quick captures must not pollute the trend
        (res / "slo_detection.json").write_text(json.dumps(
            dict(TestSloDetectionSchema.GOOD, quick=True)))
        assert bench_trend.slo_detection_rows(res) == []
