"""Record/replay cross-validation: device CBAA vs the sequential oracle.

The reference pattern (`auctioneer.cpp:577-597` binary dumps +
`matlab/test_alignment.m:14-31` replay through `CBAA_aclswarm.m`), applied
to this framework: auctions recorded from real closed-loop rollouts are
replayed through the independent per-vehicle NumPy implementation
(`assignment/cbaa_ref.py`), and the bulk-synchronous device kernel must
make identical decisions.
"""
import numpy as np
import pytest

from aclswarm_tpu import gains as gainslib
from aclswarm_tpu import sim
from aclswarm_tpu.assignment import cbaa_ref, replay
from aclswarm_tpu.core.types import ControlGains, SafetyParams, make_formation
from aclswarm_tpu.harness import formgen

import jax.numpy as jnp


def _rollout_records(seed, n=7, fc=False, ticks=600, assign_every=30):
    rng = np.random.default_rng(seed)
    adj = formgen.random_adjmat(np.random.default_rng(seed), n, fc=fc)
    pts = formgen.sample_cylinder_points(rng, n, 12, 12, 2, min_dist=2.0)
    A = gainslib.solve_gains_blocks(pts, adj)
    f = make_formation(pts, adj, A)
    sp = SafetyParams(bounds_min=jnp.asarray([-50.0, -50.0, 0.0]),
                      bounds_max=jnp.asarray([50.0, 50.0, 20.0]))
    cfg = sim.SimConfig(assignment="cbaa", assign_every=assign_every)
    q0 = rng.normal(size=(n, 3)) * 4 + [0, 0, 3]
    st = sim.init_state(q0)
    _, m = sim.rollout(st, f, ControlGains(), sp, cfg, ticks)
    return replay.record_auctions(m, q0, np.arange(n), f)


@pytest.mark.slow
def test_replay_hundred_recorded_auctions():
    """>= 100 auctions recorded from random rollouts (sparse and complete
    graphs): the device kernel and the sequential oracle agree on every
    validity flag and every valid assignment."""
    records = []
    for seed in range(6):
        records += _rollout_records(seed, fc=(seed % 2 == 0))
    assert len(records) >= 100, len(records)
    n_valid = 0
    for rec in records:
        out = replay.replay_record(rec)
        assert out["match"], rec
        # and the recorded rollout outcome matches the replayed decision:
        # a valid auction's result is what the engine adopted
        if out["device_valid"]:
            n_valid += 1
            v2f = np.empty(len(rec.v2f_prev), dtype=int)
            v2f[out["device_f2v"]] = np.arange(len(rec.v2f_prev))
            np.testing.assert_array_equal(v2f, rec.v2f_new)
    # the overwhelming majority of auctions in a healthy rollout are valid
    assert n_valid >= 0.9 * len(records), (n_valid, len(records))


def test_record_roundtrip(tmp_path):
    records = _rollout_records(9, n=6, ticks=200)
    assert records
    path = tmp_path / "auctions.npz"
    replay.save_records(records, path)
    loaded = replay.load_records(path)
    assert len(loaded) == len(records)
    for a, b in zip(records, loaded):
        np.testing.assert_array_equal(a.q, b.q)
        np.testing.assert_array_equal(a.v2f_new, b.v2f_new)


def test_oracle_standalone_sanity():
    """The oracle alone: valid permutation on a clean instance, and the
    nearest-assignment structure on a well-separated swarm."""
    n = 5
    rng = np.random.default_rng(0)
    pts = np.stack([np.arange(n) * 5.0, np.zeros(n), np.zeros(n)], 1)
    q = pts + rng.normal(size=(n, 3)) * 0.1
    out = cbaa_ref.cbaa_oracle(q, pts, np.ones((n, n)) - np.eye(n),
                               np.arange(n))
    assert out["valid"]
    np.testing.assert_array_equal(out["v2f"], np.arange(n))
