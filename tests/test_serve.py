"""swarmserve request-lifecycle guarantees (aclswarm_tpu.serve;
docs/SERVICE.md).

The contract under test, edge by edge: duplicate submissions are
idempotent, queue-full rejection is loud and carries a retry-after hint,
deadlines expiring DURING a multi-chunk rollout terminate with a
structured error at the next boundary, checkpoint-backed preemption
resumes bit-identically under an active `FaultSchedule`, a worker that
dies mid-batch loses nothing a journal recovery cannot honor, tenants
cannot starve each other, and an all-tenants-idle `close` is a clean
shutdown. Soak-sized runs are marked `slow` (tier-1 duration guard).
"""
from __future__ import annotations

import subprocess
import sys
import time
from pathlib import Path

import numpy as np
import pytest

from aclswarm_tpu.resilience import crash as crashlib
from aclswarm_tpu.resilience.crash import CrashPlan
from aclswarm_tpu.serve import (COMPLETED, FAILED, TIMED_OUT,
                                RejectedError, ServiceConfig,
                                SwarmService, Ticket, submit_and_wait)

REPO = Path(__file__).resolve().parents[1]

pytestmark = pytest.mark.serve

ROLL = {"n": 5, "ticks": 60, "chunk_ticks": 20, "seed": 5}
ROLL_FAULTED = {"n": 5, "ticks": 80, "chunk_ticks": 20, "seed": 6,
                "faults": {"dropout_frac": 0.4, "drop_tick": 15,
                           "rejoin_tick": 45}}


@pytest.fixture(autouse=True)
def _disarm_crash():
    yield
    crashlib.arm(None)


@pytest.fixture
def svc():
    s = SwarmService(ServiceConfig(max_batch=2, quantum_chunks=2))
    yield s
    s.close()


# ------------------------------------------------------------- lifecycle

class TestLifecycle:
    def test_rollout_completes_and_streams_chunks(self, svc):
        t = svc.submit("rollout", ROLL, tenant="a")
        res = t.result(timeout=240)
        assert res.status == COMPLETED and res.ok
        assert res.value["q"].shape == (5, 3)
        assert res.value["ticks"] == 60 and res.chunks == 3
        events = list(t.stream(timeout=1))
        assert [e.payload["chunk"] for e in events] == [0, 1, 2]
        # the stream's running digest ends at the result digest
        assert events[-1].payload["digest"] == res.value["digest"]

    def test_mixed_kinds_complete(self, svc):
        ta = svc.submit("assign", {"n": 10, "seed": 1}, tenant="a")
        tg = svc.submit("gains", {"n": 5, "seed": 2}, tenant="b")
        ra, rg = ta.result(240), tg.result(240)
        assert ra.ok and sorted(np.asarray(ra.value["perm"])) \
            == list(range(10))
        assert rg.ok and rg.value["gains"].shape == (15, 15)

    def test_unknown_kind_and_bad_params_refused_at_submit(self, svc):
        with pytest.raises(ValueError, match="unknown request kind"):
            svc.submit("nope", {})
        with pytest.raises(ValueError, match="multiple of"):
            svc.submit("rollout", {"n": 5, "ticks": 40, "chunk_ticks": 30,
                                   "assign_every": 20})
        # non-chunk-aligned ticks would silently over-run (chunks run
        # whole): refused at the door, not rounded up
        with pytest.raises(ValueError, match="chunks run whole"):
            svc.submit("rollout", {"n": 5, "ticks": 50,
                                   "chunk_ticks": 20})
        with pytest.raises(ValueError, match="faults"):
            svc.submit("rollout", dict(ROLL, faults={"bogus_key": 1}))

    def test_duplicate_submission_idempotent(self, svc):
        t1 = svc.submit("rollout", ROLL, tenant="a", request_id="dup")
        t2 = svc.submit("rollout", ROLL, tenant="a", request_id="dup")
        assert t1 is t2                      # one ticket, one execution
        res = t1.result(timeout=240)
        assert res.ok
        # resubmitting AFTER completion still resolves to the same work
        t3 = svc.submit("rollout", ROLL, tenant="a", request_id="dup")
        assert t3.result(timeout=5).value["digest"] \
            == res.value["digest"]
        assert svc.stats["accepted"] == 1 and svc.stats["completed"] == 1

    def test_racing_duplicate_submits_one_execution(self, svc):
        """The id reservation is atomic with the duplicate check: N
        threads slamming one request_id simultaneously get ONE ticket
        and ONE execution (regression: check and insert used to live in
        separate lock acquisitions)."""
        import threading
        tickets, barrier = [], threading.Barrier(8)

        def go():
            barrier.wait()
            tickets.append(svc.submit("rollout", ROLL, tenant="a",
                                      request_id="race"))

        threads = [threading.Thread(target=go) for _ in range(8)]
        for th in threads:
            th.start()
        for th in threads:
            th.join(30)
        assert len(tickets) == 8
        assert all(t is tickets[0] for t in tickets)
        assert tickets[0].result(timeout=240).ok
        assert svc.stats["accepted"] == 1

    def test_stream_timeout_raises_and_end_marker_sticky(self, svc):
        t = svc.submit("rollout", ROLL, tenant="a")
        assert t.result(timeout=240).ok
        assert [e.payload["chunk"] for e in t.stream(timeout=1)] \
            == [0, 1, 2]
        # events are consumed once, but the end marker is sticky: a
        # second stream terminates instead of blocking forever
        assert list(t.stream(timeout=1)) == []
        # a lapsed per-event timeout is a TimeoutError, not the queue
        # module's internal exception
        with pytest.raises(TimeoutError, match="no chunk event"):
            next(Ticket("never-resolved").stream(timeout=0.05))

    def test_stats_kind_scrapes_the_service(self, svc):
        """The built-in `stats` kind: the swarmscope scrape surface as
        an ordinary request — prometheus text and the snapshot dict
        both resolve as codec-serializable values (the wire half lives
        in tests/test_serve_wire.py)."""
        assert svc.submit("assign", {"n": 6, "seed": 1}).result(240).ok
        rp = svc.submit("stats", {"format": "prometheus"}).result(120)
        assert rp.ok and "serve_accepted_total" in rp.value["text"]
        assert "# TYPE" in rp.value["text"]
        rs = svc.submit("stats", {"format": "snapshot"}).result(120)
        assert rs.ok
        assert rs.value["snapshot"]["metrics"][
            "serve_accepted_total"]["value"] >= 2
        assert rs.value["serve"]["accepted"] >= 2
        rbad = svc.submit("stats", {"format": "nope"}).result(120)
        assert not rbad.ok and rbad.error.code == "execution_failed"

    def test_terminal_requests_retire_to_bounded_cache(self):
        """An always-on service keeps NO per-request state after a
        request terminates: the job map empties and the idempotency
        cache is bounded by done_retention (oldest evicted)."""
        svc = SwarmService(ServiceConfig(done_retention=2))
        results = [
            svc.submit("assign", {"n": 6, "seed": i},
                       request_id=f"r{i}").result(timeout=240)
            for i in range(4)]
        assert all(r.ok for r in results)
        assert svc._jobs == {}
        assert set(svc._done_prior) == {"r2", "r3"}
        # idempotent replay still served from the bounded cache
        replay = svc.submit("assign", {"n": 6, "seed": 3},
                            request_id="r3").result(timeout=5)
        assert replay.ok and svc.stats["accepted"] == 4
        svc.close()


# ------------------------------------------- admission and backpressure

class TestAdmission:
    def test_queue_full_rejection_with_retry_after(self):
        # worker not started: the queue cannot drain, so the caps bind
        svc = SwarmService(ServiceConfig(max_queue_per_tenant=2,
                                         max_queue_total=3), start=False)
        svc.submit("rollout", ROLL, tenant="a")
        svc.submit("rollout", ROLL, tenant="a")
        with pytest.raises(RejectedError) as ei:
            svc.submit("rollout", ROLL, tenant="a")   # per-tenant cap
        assert ei.value.retry_after_s > 0
        assert "cap" in str(ei.value)
        svc.submit("rollout", ROLL, tenant="b")       # other tenant fits
        with pytest.raises(RejectedError) as ei:
            svc.submit("rollout", ROLL, tenant="c")   # global cap
        assert "global cap" in str(ei.value)
        assert svc.stats["rejected"] == 2 and svc.stats["accepted"] == 3

    def test_rejected_work_is_not_owed(self, tmp_path):
        """A rejected submit journals NOTHING: recovery must not
        resurrect work the client was told to retry elsewhere."""
        svc = SwarmService(ServiceConfig(max_queue_per_tenant=1,
                                         journal_dir=str(tmp_path)),
                          start=False)
        svc.submit("rollout", ROLL, tenant="a", request_id="kept")
        with pytest.raises(RejectedError):
            svc.submit("rollout", ROLL, tenant="a", request_id="bounced")
        reqs = {p.name for p in tmp_path.glob("req_*.req")}
        assert reqs == {"req_kept.req"}


# ----------------------------------------------------------- deadlines

class TestDeadlines:
    def test_deadline_expiring_during_chunks(self, svc):
        """A deadline that lapses MID-ROLLOUT terminates the request at
        the next chunk boundary with a structured error — partial work
        is cancelled, the service moves on, other requests are
        unaffected."""
        # warm the bucket first: the first batched_rollout compile
        # (~2 s on this host) would otherwise eat the whole deadline
        # before the long job's first chunk — the test is about a
        # deadline lapsing DURING chunks, not during a cold compile
        assert svc.submit("rollout", dict(ROLL, ticks=20, seed=1),
                          tenant="warm").result(timeout=240).ok
        # long job with a deadline it cannot meet, short job without
        tshort = svc.submit("rollout", dict(ROLL, seed=9), tenant="b")
        # 5000 chunks: unfinishable inside the deadline even on the
        # staged path (PR 11 made 20-tick rounds sub-millisecond — a
        # 500-chunk job started COMPLETING inside the old 2 s window)
        tlong = svc.submit(
            "rollout", {"n": 5, "ticks": 100_000, "chunk_ticks": 20,
                        "seed": 8},
            tenant="a", deadline_s=2.0)
        rlong = tlong.result(timeout=240)
        assert rlong.status == TIMED_OUT and not rlong.ok
        assert rlong.error.code == "deadline_exceeded"
        assert "chunk boundary" in rlong.error.message
        assert 0 < rlong.chunks < 5000     # it ran, then was cancelled
        assert tshort.result(timeout=240).ok

    def test_expired_on_arrival(self, svc):
        r = svc.submit("rollout", ROLL, deadline_s=0.0).result(timeout=60)
        assert r.status == TIMED_OUT and r.chunks == 0
        assert r.error.code == "deadline_exceeded"


# --------------------------------------------- preemption + bit-parity

class TestPreemption:
    def test_preempt_then_resume_bit_parity_under_faults(self):
        """Two tenants contend for ONE batch slot with a 1-chunk
        quantum: both rollouts (one carrying an active FaultSchedule)
        are preempted through the checkpoint codec repeatedly, and both
        finish bit-identical to uncontended solo runs."""
        ref = SwarmService(ServiceConfig(max_batch=4))
        r_ref = [ref.submit("rollout", p).result(240)
                 for p in (ROLL_FAULTED, dict(ROLL, seed=7))]
        ref.close()

        svc = SwarmService(ServiceConfig(max_batch=1, quantum_chunks=1))
        ta = svc.submit("rollout", ROLL_FAULTED, tenant="a")
        tb = svc.submit("rollout", dict(ROLL, seed=7), tenant="b")
        ra, rb = ta.result(timeout=240), tb.result(timeout=240)
        svc.close()
        assert ra.preemptions > 0 and rb.preemptions > 0
        assert svc.stats["preempted"] >= 2
        for got, want in ((ra, r_ref[0]), (rb, r_ref[1])):
            assert got.ok
            assert got.value["digest"] == want.value["digest"]
            assert got.value["chunk_digests"] == want.value["chunk_digests"]
            assert np.array_equal(got.value["q"], want.value["q"])


# ------------------------------------------- staged-round parity (PR 11)

class TestStagedParity:
    """The staged device-bound round (serve.staging: submit-time prep,
    donated staging buffers, double-buffered pipelining, batched
    unpack) must be BIT-IDENTICAL to the PR-9 pack-at-round-time path,
    which is kept behind ``ServiceConfig(staging=False)`` exactly as
    this reference."""

    def _legacy(self, specs):
        svc = SwarmService(ServiceConfig(max_batch=2, staging=False))
        out = [svc.submit("rollout", s).result(timeout=240)
               for s in specs]
        svc.close()
        assert all(r.ok for r in out)
        return out

    @pytest.mark.parametrize("pipeline", [True, False])
    def test_staged_rounds_bit_identical_to_legacy(self, pipeline):
        specs = [ROLL, ROLL_FAULTED]
        want = self._legacy(specs)
        svc = SwarmService(ServiceConfig(max_batch=2,
                                         pipeline=pipeline))
        ts = [svc.submit("rollout", s) for s in specs]
        got = [t.result(timeout=240) for t in ts]
        svc.close()
        for g, w in zip(got, want):
            assert g.ok
            assert g.value["digest"] == w.value["digest"]
            assert g.value["chunk_digests"] == w.value["chunk_digests"]
            assert np.array_equal(g.value["q"], w.value["q"])

    def test_staged_parity_across_preemption_resume(self):
        """Contended staged rounds (1-slot batch, 1-chunk quantum —
        both jobs evicted through the codec repeatedly) still match
        the legacy path bit for bit."""
        specs = [ROLL_FAULTED, dict(ROLL, seed=7)]
        want = self._legacy(specs)
        svc = SwarmService(ServiceConfig(max_batch=1, quantum_chunks=1))
        ts = [svc.submit("rollout", s, tenant=f"t{i}")
              for i, s in enumerate(specs)]
        got = [t.result(timeout=240) for t in ts]
        svc.close()
        assert any(r.preemptions > 0 for r in got)
        for g, w in zip(got, want):
            assert g.ok
            assert g.value["digest"] == w.value["digest"]
            assert g.value["chunk_digests"] == w.value["chunk_digests"]

    def test_staged_parity_across_worker_kill_migration(self, tmp_path):
        """A staged rollout migrated off a killed worker (checkpoint
        codec, quarantine, re-staging on the survivor) matches the
        legacy path bit for bit — the PR-8 chaos bar holds over the
        pipelined path."""
        from aclswarm_tpu.serve import bucket_of, place_slot

        want = self._legacy([ROLL_FAULTED])[0]
        svc = SwarmService(ServiceConfig(
            workers=2, max_batch=1, quantum_chunks=8,
            journal_dir=str(tmp_path), supervise_poll_s=0.02,
            rejoin_base_s=0.05))
        slot = place_slot(bucket_of("rollout", ROLL_FAULTED), [0, 1])
        crashlib.arm(CrashPlan(f"serve.w{slot}", 2, "raise"))
        got = svc.submit("rollout", ROLL_FAULTED).result(timeout=240)
        crashlib.arm(None)
        svc.close()
        assert got.ok and got.failovers >= 1
        assert got.value["digest"] == want.value["digest"]
        assert got.value["chunk_digests"] == want.value["chunk_digests"]


# ----------------------------------------------------- swarmtrace continuity


class TestTraceContinuity:
    def test_trace_id_constant_across_preemption_resume(self, tmp_path):
        """One trace_id names the request across checkpoint-backed
        preemption: the id minted at submit survives every eviction +
        codec restore, and the journal timeline shows the preempted →
        resumed arc gap-free (ISSUE 9 satellite)."""
        from aclswarm_tpu.telemetry import postmortem

        svc = SwarmService(ServiceConfig(max_batch=1, quantum_chunks=1,
                                         journal_dir=str(tmp_path)))
        ta = svc.submit("rollout", ROLL_FAULTED, tenant="a",
                        request_id="pa")
        tb = svc.submit("rollout", dict(ROLL, seed=7), tenant="b",
                        request_id="pb")
        ra, rb = ta.result(timeout=240), tb.result(timeout=240)
        svc.close()
        assert ra.ok and rb.ok and ra.preemptions > 0
        assert ra.trace_id and ra.trace_id != rb.trace_id
        rep = postmortem.reconstruct(tmp_path)
        assert rep["complete"] == 2 and rep["gap_free"] == 2
        pa = rep["requests"]["pa"]
        assert pa["trace_id"] == ra.trace_id
        assert pa["preemptions"] >= 1 and pa["resumes"] >= 1
        assert pa["stages"]["preempted_s"] > 0

    def test_trace_id_constant_across_crash_recovery(self, tmp_path):
        """Process-death continuity: the trace_id minted before the
        worker died is the one the RECOVERED service resumes under —
        and the reconstructed timeline is one causally-ordered story
        spanning both incarnations (extends TestRecovery's drill with
        the swarmtrace audit)."""
        from aclswarm_tpu.telemetry import postmortem

        svc = SwarmService(ServiceConfig(max_batch=1, quantum_chunks=1,
                                         journal_dir=str(tmp_path),
                                         max_worker_restarts=0,
                                         supervise_poll_s=0.02))
        crashlib.arm(CrashPlan("serve", 2, "raise"))
        t0 = svc.submit("rollout", ROLL_FAULTED, tenant="a",
                        request_id="roll")
        deadline = time.monotonic() + 60
        while svc.alive and time.monotonic() < deadline:
            time.sleep(0.05)
        assert not svc.alive
        crashlib.arm(None)
        tid_before = svc._jobs["roll"].req.trace_id

        svc2 = SwarmService(ServiceConfig(max_batch=1,
                                          journal_dir=str(tmp_path)))
        res = svc2.submit("rollout", ROLL_FAULTED,
                          request_id="roll").result(timeout=240)
        svc2.close()
        assert res.ok and res.resumed
        assert res.trace_id == tid_before     # survived the process
        rep = postmortem.reconstruct(tmp_path)["requests"]["roll"]
        assert rep["complete"] and rep["gap_free"], rep["problems"]
        assert rep["trace_id"] == tid_before
        # the crash gap is visible: queued(recovery) -> batched
        assert rep["stages"]["failover_gap_s"] > 0
        # events span BOTH pids (the killed worker's and recovery's)
        pids = {r["pid"] for r in postmortem.load_journal(
            tmp_path).events if "pid" in r}
        assert len(pids) == 1        # in-process drill: one pid, but
        #                              the recovery events follow the
        #                              crash events in file order
        assert rep["resumes"] >= 1

    def test_result_trace_id_empty_without_explicit_and_minted(self):
        svc = SwarmService(ServiceConfig(), start=False)
        t = svc.submit("assign", {"n": 6}, trace_id="feedface00000001")
        assert svc._jobs[t.request_id].req.trace_id \
            == "feedface00000001"
        t2 = svc.submit("assign", {"n": 6, "seed": 2})
        assert len(svc._jobs[t2.request_id].req.trace_id) == 16
        svc.close(drain=False)


# ------------------------------------------------- crash + journal recovery

class TestRecovery:
    def test_worker_death_mid_batch_loses_nothing(self, tmp_path):
        """In-process crash drill (the subprocess SIGKILL proof lives in
        `serve.smoke`/`serve_soak`): the worker dies mid-batch via an
        injected crash; a new service on the same journal re-admits
        every accepted request, resumes the rollout from its checkpoint,
        and terminates all of them. ``max_worker_restarts=0`` retires
        the slot on its first death (circuit open immediately) — the
        recovery-by-new-process scenario, as opposed to the in-process
        failover the multiworker tests prove."""
        svc = SwarmService(ServiceConfig(max_batch=1, quantum_chunks=1,
                                         journal_dir=str(tmp_path),
                                         max_worker_restarts=0,
                                         supervise_poll_s=0.02))
        # round 3 on the PIPELINED schedule: round 1 dispatches the
        # rollout's chunk 1 (pending), round 2 runs the assign while
        # chunk 1 resolves + checkpoints, round 3 re-picks the rollout
        # — the kill lands with chunk 1 durable and chunk 2 in flight
        # (the same "one chunk survives" shape the old round-2 kill
        # produced on the sequential schedule)
        crashlib.arm(CrashPlan("serve", 3, "raise"))
        svc.submit("rollout", ROLL_FAULTED, tenant="a",
                   request_id="roll")
        svc.submit("assign", {"n": 10, "seed": 4}, tenant="b",
                   request_id="asg")
        deadline = time.monotonic() + 60
        while svc.alive and time.monotonic() < deadline:
            time.sleep(0.05)
        assert not svc.alive                   # died mid-batch, slot
        #                                        retired: fleet dead
        done = {p.name for p in tmp_path.glob("req_*.done")}
        reqs = {p.name for p in tmp_path.glob("req_*.req")}
        assert reqs == {"req_roll.req", "req_asg.req"}
        assert len(done) < 2                   # work genuinely in flight

        svc2 = SwarmService(ServiceConfig(max_batch=1,
                                          journal_dir=str(tmp_path)))
        # recovered requests are serviced without any resubmission;
        # duplicate submits attach to the recovered jobs
        t_roll = svc2.submit("rollout", ROLL_FAULTED, request_id="roll")
        t_asg = svc2.submit("assign", {"n": 10, "seed": 4},
                            request_id="asg")
        r_roll, r_asg = t_roll.result(timeout=240), \
            t_asg.result(timeout=240)
        svc2.close()
        assert r_roll.ok and r_asg.ok
        assert r_roll.resumed                  # checkpoint, not restart
        assert svc2.stats["resumed"] == 1

        ref = SwarmService(ServiceConfig())
        want = ref.submit("rollout", ROLL_FAULTED).result(240)
        ref.close()
        assert r_roll.value["digest"] == want.value["digest"]
        assert np.array_equal(r_roll.value["q"], want.value["q"])

    def test_resubmit_after_restart_replays_journaled_result(
            self, tmp_path):
        svc = SwarmService(ServiceConfig(journal_dir=str(tmp_path)))
        want = svc.submit("rollout", ROLL,
                          request_id="r1").result(timeout=240)
        svc.close()
        svc2 = SwarmService(ServiceConfig(journal_dir=str(tmp_path)),
                            start=False)
        got = svc2.submit("rollout", ROLL, request_id="r1").result(1)
        assert got.ok and got.value["digest"] == want.value["digest"]
        assert svc2.stats["accepted"] == 0     # replayed, not re-run


# ----------------------------------------------- warm gains over the wire

class TestGainsWarmCarry:
    """ADMM warm start riding the request (ROADMAP item 1): ``warm``
    bootstraps carry threading with gains BITWISE equal to the legacy
    path (cold seed == cold solve), the returned carry is codec-plain
    numpy, and re-submitting it re-seeds the next design."""

    def test_warm_bootstrap_bitwise_legacy_then_reseed(self, svc):
        legacy = svc.submit("gains", {"n": 5, "seed": 3}, tenant="a") \
            .result(240)
        warm = svc.submit("gains", {"n": 5, "seed": 3, "warm": True},
                          tenant="a").result(240)
        assert legacy.ok and warm.ok
        assert "carry" not in legacy.value
        assert np.array_equal(warm.value["gains"], legacy.value["gains"])
        carry = warm.value["carry"]
        assert all(isinstance(v, np.ndarray) for v in carry.values())
        re = svc.submit("gains", {"n": 5, "seed": 3, "carry": carry},
                        tenant="a").result(240)
        assert re.ok and "carry" in re.value
        np.testing.assert_allclose(re.value["gains"],
                                   legacy.value["gains"], atol=5e-3)


# ------------------------------------------------- fairness + shutdown

class TestFairnessAndShutdown:
    def test_flooding_tenant_cannot_starve_another(self):
        """Tenant a queues 6 rollouts; tenant b's single request lands
        LAST — round-robin slots must still finish b well before a's
        backlog drains."""
        svc = SwarmService(ServiceConfig(max_batch=1, quantum_chunks=1,
                                         max_queue_per_tenant=8),
                           start=False)
        flood = [svc.submit("rollout", dict(ROLL, seed=50 + i),
                            tenant="a") for i in range(6)]
        tb = svc.submit("rollout", dict(ROLL, seed=99), tenant="b")
        svc.start()
        rb = tb.result(timeout=240)
        assert rb.ok
        done_of_a = sum(1 for t in flood if t.done)
        assert done_of_a < 6, "tenant b waited behind tenant a's flood"
        for t in flood:
            assert t.result(timeout=240).ok
        svc.close()

    def test_all_tenants_idle_clean_shutdown(self):
        svc = SwarmService(ServiceConfig())
        assert svc.submit("assign", {"n": 8}).result(timeout=240).ok
        svc.close()                      # drain: idle -> workers exit
        assert not svc.alive
        # a clean drain-exit is NOT a worker death: no failover fired
        assert svc.stats["failovers"] == 0
        svc.close()                      # idempotent
        with pytest.raises(RejectedError, match="shutdown"):
            svc.submit("assign", {"n": 8})

    def test_nondrain_close_resolves_queued_with_structured_error(self):
        svc = SwarmService(ServiceConfig(), start=False)
        t = svc.submit("rollout", ROLL)
        svc.close(drain=False)
        r = t.result(timeout=5)
        assert r.status == FAILED
        assert r.error.code == "service_shutdown"

    def test_drain_timeout_is_loud_not_silent(self):
        """A drain that cannot finish within close()'s timeout resolves
        the abandoned tickets with an error NAMING the drain timeout
        (regression: the broken run-to-terminal promise used to look
        identical to a never-scheduled shutdown)."""
        svc = SwarmService(ServiceConfig(max_batch=1))
        t = svc.submit("rollout", {"n": 5, "ticks": 10_000,
                                   "chunk_ticks": 20, "seed": 3})
        time.sleep(0.3)                 # let the worker go resident
        svc.close(drain=True, timeout=0.2)
        r = t.result(timeout=60)
        assert r.status == FAILED
        assert r.error.code == "service_shutdown"
        assert "abandoned the drain" in r.error.message


# ------------------------------------------------------- client helpers

class TestSubmitAndWait:
    def test_structured_nonanswers(self):
        """Rejection, a dead worker, and client impatience all come back
        as structured failed Results — never an exception, never a
        hang."""
        # dead worker: a never-started service cannot resolve tickets
        svc = SwarmService(ServiceConfig(), start=False)
        r = submit_and_wait(svc, "assign", {"n": 6}, poll_s=0.05,
                            client_timeout_s=10.0)
        assert r.status == FAILED and r.error.code == "worker_died"
        svc.close(drain=False)
        # queue full: the retry-after hint survives the translation
        # (reject_retries=0 = the raw pre-ISSUE-13 surface)
        svc2 = SwarmService(ServiceConfig(max_queue_per_tenant=1),
                            start=False)
        svc2.submit("assign", {"n": 6})
        r2 = submit_and_wait(svc2, "assign", {"n": 6},
                             reject_retries=0)
        assert r2.status == FAILED and r2.error.code == "queue_full"
        assert r2.error.detail["retry_after_s"] > 0
        svc2.close(drain=False)

    def test_retry_after_honored_by_default(self):
        """ISSUE-13 satellite: a queue_full rejection sleeps out the
        hint (deterministic crc32 jitter) and re-submits — callers see
        the eventual result, not raw backpressure. Exhausted budgets
        still surface the structured queue_full."""
        import threading

        svc = SwarmService(ServiceConfig(max_queue_per_tenant=1,
                                         max_batch=1,
                                         idle_poll_s=0.01),
                           start=False)
        svc.submit("assign", {"n": 6, "seed": 1})   # pins the cap slot
        starter = threading.Timer(0.6, svc.start)
        starter.start()
        r = submit_and_wait(svc, "assign", {"n": 6, "seed": 2},
                            reject_retries=16, client_timeout_s=120)
        starter.join()
        assert r.ok, r.error
        assert svc.stats["rejected"] >= 1   # the backpressure was real
        svc.close()
        # exhausted budget: the structured queue_full surfaces, after
        # exactly the bounded number of re-submits
        svc2 = SwarmService(ServiceConfig(max_queue_per_tenant=1),
                            start=False)
        svc2.submit("assign", {"n": 6})
        r2 = submit_and_wait(svc2, "assign", {"n": 6},
                             reject_retries=2, max_retry_wait_s=0.05)
        assert r2.status == FAILED and r2.error.code == "queue_full"
        assert svc2.stats["rejected"] == 3      # 1 try + 2 retries
        svc2.close(drain=False)

    def test_client_timeout_while_service_still_owes(self):
        svc = SwarmService(ServiceConfig())
        r = submit_and_wait(
            svc, "rollout", {"n": 5, "ticks": 10_000, "chunk_ticks": 20,
                             "seed": 1},
            poll_s=0.1, client_timeout_s=0.3)
        assert r.status == FAILED and r.error.code == "client_timeout"
        svc.close(drain=False)


# -------------------------------------------- multi-worker + failover

MW_ROLL = {"n": 5, "ticks": 80, "chunk_ticks": 20, "seed": 6}


def _mw_bucket():
    from aclswarm_tpu.serve import bucket_of
    return bucket_of("rollout", MW_ROLL)


def _mw_config(**kw):
    base = dict(workers=2, max_batch=1, quantum_chunks=8,
                supervise_poll_s=0.02, rejoin_base_s=0.02,
                rejoin_max_s=0.2)
    base.update(kw)
    return ServiceConfig(**base)


class TestMultiWorker:
    def test_place_slot_deterministic_and_minimal_rematch(self):
        """Rendezvous placement: deterministic, total over buckets, and
        removing one slot re-matches ONLY the buckets it owned."""
        from aclswarm_tpu.serve import place_slot
        buckets = [("rollout", n, 20, "auction", 20)
                   for n in (5, 8, 16, 100)] + [("single", "assign")]
        before = {b: place_slot(b, [0, 1, 2]) for b in buckets}
        assert before == {b: place_slot(b, [0, 1, 2]) for b in buckets}
        assert all(s in (0, 1, 2) for s in before.values())
        dead = 0
        after = {b: place_slot(b, [1, 2]) for b in buckets}
        for b in buckets:
            if before[b] != dead:
                assert after[b] == before[b], \
                    "a surviving slot's bucket re-matched needlessly"
            else:
                assert after[b] in (1, 2)
        assert place_slot(("x",), []) is None

    def test_submit_and_wait_returns_migrated_result_not_worker_died(
            self):
        """Client-side liveness THROUGH a failover: a worker kill
        mid-rollout must surface the migrated result — the service is
        degraded, not dead, so `submit_and_wait` must keep waiting
        instead of reporting worker_died."""
        ref = SwarmService(ServiceConfig())
        want = ref.submit("rollout", MW_ROLL).result(240)
        ref.close()

        svc = SwarmService(_mw_config())
        from aclswarm_tpu.serve import place_slot
        slot = place_slot(_mw_bucket(), [0, 1])
        crashlib.arm(CrashPlan(f"serve.w{slot}", 2, "raise"))
        r = submit_and_wait(svc, "rollout", MW_ROLL, poll_s=0.1,
                            client_timeout_s=240)
        assert r.ok, f"expected migrated result, got {r.error}"
        assert r.failovers >= 1
        assert r.value["digest"] == want.value["digest"]
        assert svc.stats["failovers"] >= 1
        assert svc.stats["requeued"] >= 1
        svc.close()

    def test_stream_survives_migration_no_sticky_end_marker(self):
        """`Ticket.stream` across a worker kill: chunk events stay
        contiguous (no repeats, no gaps), and the stream does NOT
        terminate mid-migration — only the terminal result closes it."""
        svc = SwarmService(_mw_config())
        from aclswarm_tpu.serve import place_slot
        slot = place_slot(_mw_bucket(), [0, 1])
        crashlib.arm(CrashPlan(f"serve.w{slot}", 2, "raise"))
        t = svc.submit("rollout", MW_ROLL, tenant="a")
        chunks = [ev.payload["chunk"] for ev in t.stream(timeout=240)]
        # the stream only ended because the request resolved
        assert t.done and t.result(timeout=5).ok
        assert chunks == list(range(MW_ROLL["ticks"]
                                    // MW_ROLL["chunk_ticks"]))
        svc.close()

    def test_poisoned_request_bounded_and_fleet_survives(self):
        """A request that kills every worker it touches terminates with
        a structured `poisoned` error after max_worker_exclusions
        distinct kills — and the fleet keeps serving other tenants."""
        from aclswarm_tpu.resilience import InjectedCrash
        svc = SwarmService(_mw_config(max_worker_exclusions=2,
                                      max_worker_restarts=6))
        svc.register("poison", lambda p: (_ for _ in ()).throw(
            InjectedCrash("poison")))
        tp = svc.submit("poison", {}, tenant="evil")
        rp = tp.result(timeout=120)
        assert rp.status == FAILED and rp.error.code == "poisoned"
        assert rp.failovers == 2
        assert svc.stats["poisoned"] == 1
        # bystander work still completes on the (respawned) fleet
        assert svc.submit("assign", {"n": 8, "seed": 2},
                          tenant="good").result(timeout=120).ok
        assert svc.alive
        svc.close()

    def test_poison_bound_holds_under_pipelined_load(self):
        """The pipelined poison corner (PR-11 review finding): at
        max_batch=1 with other work always in flight every pick is
        solo, and a dead worker leaves TWO rounds' orphans — without
        quarantine isolation no solo kill could ever be attributed
        unambiguously and the poison request would ping-pong workers
        into the circuit breaker. Suspect rounds never overlap another
        round, so the bound still trips and the bystanders complete."""
        from aclswarm_tpu.resilience import InjectedCrash

        svc = SwarmService(_mw_config(max_worker_exclusions=2,
                                      max_worker_restarts=12))
        svc.register("poison", lambda p: (_ for _ in ()).throw(
            InjectedCrash("poison")))
        rolls = [svc.submit("rollout", dict(MW_ROLL, seed=80 + i),
                            tenant=f"t{i % 2}") for i in range(3)]
        rp = svc.submit("poison", {}, tenant="evil").result(timeout=240)
        assert rp.status == FAILED and rp.error.code == "poisoned"
        for t in rolls:
            assert t.result(timeout=240).ok
        assert svc.stats["poisoned"] == 1
        assert svc.alive
        svc.close()

    def test_innocent_batch_mates_of_kills_are_exonerated_not_poisoned(
            self):
        """Quarantine semantics: two healthy rollouts share the batch
        the scripted kills keep orphaning. They become suspects, run
        their quarantine rounds solo, get exonerated by the surviving
        chunk, and COMPLETE bit-identically — only solo-implicated
        kills count toward the poison bound, so innocents never reach
        it (regression: with a plain exclusion count, batch-mates of
        two kills terminated `poisoned` despite being healthy)."""
        specs = [dict(MW_ROLL, seed=41), dict(MW_ROLL, seed=42)]
        ref = SwarmService(ServiceConfig(max_batch=4))
        want = [ref.submit("rollout", s).result(240) for s in specs]
        ref.close()

        from aclswarm_tpu.serve import place_slot
        svc = SwarmService(_mw_config(max_batch=2,
                                      max_worker_exclusions=2,
                                      max_worker_restarts=9))
        # kill the bucket owner twice: both rollouts are in-flight
        # batch-mates each time (rounds interleave solo quarantine
        # rounds in between, where exoneration happens)
        slot = place_slot(_mw_bucket(), [0, 1])
        crashlib.arm(None)
        from aclswarm_tpu.resilience import arm_many
        from aclswarm_tpu.resilience.crash import CrashPlan as CP
        arm_many([CP(f"serve.w{slot}", 2, "raise"),
                  CP(f"serve.w{slot}", 5, "raise")])
        ts = [svc.submit("rollout", s, tenant="a") for s in specs]
        res = [t.result(timeout=240) for t in ts]
        arm_many([])
        assert svc.stats["failovers"] >= 1
        assert svc.stats["poisoned"] == 0
        for r, w in zip(res, want):
            assert r.ok, f"innocent batch-mate terminated: {r.error}"
            assert r.value["digest"] == w.value["digest"]
        assert any(r.failovers >= 1 for r in res)
        svc.close()

    def test_trace_id_constant_across_worker_migration(self, tmp_path):
        """swarmtrace across a cross-worker migration: the trace_id
        minted at submit rides the checkpoint-codec migration to the
        surviving worker, and the postmortem reconstructs one gap-free
        timeline with the migrated/resumed arc and a non-zero failover
        gap in the stage breakdown (ISSUE 9 satellite)."""
        from aclswarm_tpu.telemetry import postmortem

        svc = SwarmService(_mw_config(journal_dir=str(tmp_path)))
        from aclswarm_tpu.serve import place_slot
        slot = place_slot(_mw_bucket(), [0, 1])
        crashlib.arm(CrashPlan(f"serve.w{slot}", 2, "raise"))
        res = svc.submit("rollout", MW_ROLL, tenant="a",
                         request_id="mig").result(timeout=240)
        crashlib.arm(None)
        svc.close()
        assert res.ok and res.failovers >= 1
        rep = postmortem.reconstruct(tmp_path)["requests"]["mig"]
        assert rep["complete"] and rep["gap_free"], rep["problems"]
        assert rep["trace_id"] == res.trace_id
        assert rep["migrations"] >= 1 and rep["resumes"] >= 1
        assert rep["stages"]["failover_gap_s"] >= 0
        # two distinct workers appear in the chunk events — the trace
        # genuinely crossed the migration
        rows = [r for r in postmortem.load_journal(tmp_path).events
                if r.get("request_id") == "mig"
                and r.get("event") == "chunk"]
        assert len({r["worker"] for r in rows}) == 2

    def test_retry_after_scales_with_surviving_capacity(self):
        """Graceful degradation: the EWMA backpressure hint re-derives
        from surviving capacity — half the fleet dead doubles the
        drain estimate for the same backlog."""
        from aclswarm_tpu.serve.admission import AdmissionControl
        adm = AdmissionControl(8, 32)
        adm.note_service(1.0)       # pull the EWMA somewhere known

        class _J:
            def __init__(self):
                self.req = type("R", (), {"tenant": "t"})()
                self.held = False
                self.bucket = ("x",)
        adm.admit(_J(), force=True)
        full = adm.retry_after()
        adm.set_capacity(alive=1, total=2)
        assert adm.retry_after() == pytest.approx(min(30.0, 2 * full))
        adm.set_capacity(alive=0, total=2)
        assert adm.retry_after() == 30.0    # ceiling while fleet is down
        adm.set_capacity(alive=2, total=2)
        assert adm.retry_after() == pytest.approx(full)

    def test_cancel_queued_now_resident_at_boundary(self):
        """`cancel` (the wire layer's disconnect semantics): a QUEUED
        request cancels immediately with a structured error; a RESIDENT
        request is never cancelled mid-batch — it terminates at its
        next chunk boundary."""
        svc = SwarmService(ServiceConfig(max_batch=1), start=False)
        t1 = svc.submit("rollout", dict(ROLL, seed=31), tenant="a",
                        request_id="c1")
        assert svc.cancel("c1", "client vanished")
        r1 = t1.result(timeout=5)
        assert r1.status == FAILED and r1.error.code == "cancelled"
        assert "client vanished" in r1.error.message
        assert not svc.cancel("c1")          # already terminal
        assert not svc.cancel("nonexistent")
        # resident: a long rollout is mid-batch when cancel arrives
        t2 = svc.submit("rollout", {"n": 5, "ticks": 10_000,
                                    "chunk_ticks": 20, "seed": 32},
                        tenant="a", request_id="c2")
        svc.start()
        # wait until it has produced at least one chunk (resident)
        next(iter(t2.stream(timeout=120)))
        assert svc.cancel("c2", "client vanished mid-run")
        r2 = t2.result(timeout=120)
        assert r2.status == FAILED and r2.error.code == "cancelled"
        assert r2.chunks >= 1               # boundary cancel, not mid-
        svc.close()

    def test_worker_telemetry_and_compact_fleet_keys(self):
        """Per-worker ServeStats ride the registry: worker_up gauges,
        failover/requeue/poisoned counters, per-worker occupancy — and
        `compact()` carries the bench-row fleet keys."""
        svc = SwarmService(_mw_config(workers=2))
        assert svc.submit("rollout", dict(ROLL, seed=77)).result(240).ok
        st = svc.serve_stats()
        assert st.workers == 2 and st.workers_up == 2
        assert set(st.per_worker) <= {"0", "1"}
        assert sum(w["chunks"] for w in st.per_worker.values()) >= 3
        c = st.compact()
        assert c["workers"] == 2 and c["failovers"] == 0
        from aclswarm_tpu.serve import ServeStats
        assert set(ServeStats.empty_compact()) == set(c)
        svc.close()


# ----------------------------------------------------------- soak sizes

@pytest.mark.slow
def test_serve_soak_quick_subprocess():
    """The full chaos soak (SIGKILL + recovery + ledger audit +
    bit-parity) in quick sizing — the tier-2 end-to-end proof."""
    r = subprocess.run(
        [sys.executable, str(REPO / "benchmarks" / "serve_soak.py"),
         "--quick", "--out", ""],
        capture_output=True, text=True, timeout=570, cwd=str(REPO))
    assert r.returncode == 0, f"{r.stdout}\n{r.stderr}"
    assert '"silent_losses": 0' in r.stdout
    assert '"resume_bit_identical": true' in r.stdout


@pytest.mark.slow
def test_serve_multiworker_soak_quick_subprocess():
    """The multi-worker chaos soak (repeated worker kills + poison +
    migration parity + fairness audit) in quick sizing."""
    r = subprocess.run(
        [sys.executable,
         str(REPO / "benchmarks" / "serve_multiworker_soak.py"),
         "--quick", "--out", ""],
        capture_output=True, text=True, timeout=570, cwd=str(REPO))
    assert r.returncode == 0, f"{r.stdout}\n{r.stderr}"
    assert '"silent_losses": 0' in r.stdout
    assert '"migrated_bit_identical": true' in r.stdout
    assert '"fairness_ok": true' in r.stdout


@pytest.mark.slow
def test_serve_multiworker_smoke_subprocess():
    """The scripts/check.sh multi-worker failover smoke stays green."""
    r = subprocess.run(
        [sys.executable, "-m", "aclswarm_tpu.serve.smoke",
         "--multiworker"],
        capture_output=True, text=True, timeout=570, cwd=str(REPO))
    assert r.returncode == 0, f"{r.stdout}\n{r.stderr}"
    assert "PASS" in r.stdout


@pytest.mark.slow
def test_serve_smoke_subprocess():
    """The scripts/check.sh serve smoke (SIGKILL the worker process,
    recover, zero losses, bit-identical resume) stays green."""
    r = subprocess.run(
        [sys.executable, "-m", "aclswarm_tpu.serve.smoke"],
        capture_output=True, text=True, timeout=570, cwd=str(REPO))
    assert r.returncode == 0, f"{r.stdout}\n{r.stderr}"
    assert "PASS" in r.stdout
