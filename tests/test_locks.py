"""swarmguard runtime tier: OrderedLock/OrderedRLock contract
(docs/STATIC_ANALYSIS.md §host-side concurrency).

Covers: rank enforcement (increasing order legal, inversion raises,
same-family nesting raises), a genuine two-thread deliberate inversion
detected via the first-seen nesting graph BEFORE either thread blocks,
re-entrancy (OrderedRLock legal, OrderedLock self-deadlock reported),
the hold/wait histogram contract into MetricsRegistry, cross-thread
held-set reporting, and the disarmed fast path staying check-free.
"""
import threading

import pytest

from aclswarm_tpu.telemetry import MetricsRegistry
from aclswarm_tpu.utils import locks as lockmod
from aclswarm_tpu.utils.locks import (LockOrderViolation, OrderedLock,
                                      OrderedRLock, register_rank)

pytestmark = pytest.mark.locks


@pytest.fixture(autouse=True)
def armed_detector():
    """Each test runs armed with a CLEAN nesting graph and held-set
    table (the first-seen edge graph is process-global on purpose —
    tests must not inherit each other's history)."""
    lockmod.arm()
    with lockmod._EDGES_GUARD:
        saved = {k: set(v) for k, v in lockmod._EDGES.items()}
        lockmod._EDGES.clear()
    try:
        yield
    finally:
        with lockmod._EDGES_GUARD:
            lockmod._EDGES.clear()
            lockmod._EDGES.update(saved)
        lockmod.disarm()


class TestRankEnforcement:
    def test_increasing_order_legal(self):
        a = OrderedLock("serve.service")        # rank 20
        b = OrderedLock("telemetry.registry")   # rank 80
        with a:
            with b:
                assert lockmod.held_families() == (
                    "serve.service", "telemetry.registry")
        assert lockmod.held_families() == ()

    def test_inversion_raises_structured(self):
        a = OrderedLock("serve.service")        # rank 20
        b = OrderedLock("telemetry.registry")   # rank 80
        with b:
            with pytest.raises(LockOrderViolation) as ei:
                a.acquire()
        v = ei.value
        assert v.kind == "rank"
        assert v.family == "serve.service"
        assert v.rank == 20
        assert v.held == ("telemetry.registry",)
        # the offender never acquired: the fleet is not wedged
        assert not a.locked()

    def test_same_family_nesting_raises(self):
        """Two per-metric locks (one family, one rank) have no defined
        mutual order — nesting them is the classic AB/BA deadlock."""
        m1 = OrderedLock("telemetry.metric")
        m2 = OrderedLock("telemetry.metric")
        with m1:
            with pytest.raises(LockOrderViolation) as ei:
                m2.acquire()
        assert ei.value.kind == "rank"

    def test_rank_registry_conflict_raises(self):
        register_rank("test.family.x", 33)
        register_rank("test.family.x", 33)      # idempotent re-pin
        with pytest.raises(ValueError):
            register_rank("test.family.x", 44)

    def test_unranked_families_skip_rank_test(self):
        a = OrderedLock("test.unranked.a")
        b = OrderedLock("test.unranked.b")
        with a:
            with b:
                pass            # first nesting: records the edge only


class TestCycleDetection:
    def test_two_thread_deliberate_inversion(self):
        """Thread 1 nests A->B (recording the edge); thread 2 then
        tries B->A. The detector must refuse thread 2's inner acquire
        — catching the deadlock pattern even though no rank was ever
        declared for either family, and WITHOUT needing the two
        threads to actually collide."""
        a = OrderedLock("test.cyc.a")
        b = OrderedLock("test.cyc.b")
        t1_done = threading.Event()
        caught: list = []

        def t1():
            with a:
                with b:
                    pass
            t1_done.set()

        def t2():
            t1_done.wait(5.0)
            try:
                with b:
                    with a:         # closes the a->b cycle
                        pass
            except LockOrderViolation as e:
                caught.append(e)

        th1 = threading.Thread(target=t1)
        th2 = threading.Thread(target=t2)
        th1.start(); th1.join(5.0)
        th2.start(); th2.join(5.0)
        assert len(caught) == 1
        assert caught[0].kind == "cycle"
        assert caught[0].family == "test.cyc.a"

    def test_peer_held_sets_in_report(self):
        """The violation snapshot names what OTHER threads hold — the
        would-be deadlock peer is in the report, not just the
        offender."""
        a = OrderedLock("serve.service")
        b = OrderedLock("telemetry.registry")
        peer_in = threading.Event()
        release = threading.Event()

        def peer():
            with a:
                peer_in.set()
                release.wait(5.0)

        th = threading.Thread(target=peer, name="peer-thread")
        th.start()
        assert peer_in.wait(5.0)
        try:
            with b:
                with pytest.raises(LockOrderViolation) as ei:
                    OrderedLock("serve.service").acquire()
            assert any("serve.service" in fams
                       for fams in ei.value.peers.values())
        finally:
            release.set()
            th.join(5.0)


class TestReentrancy:
    def test_rlock_reenters(self):
        r = OrderedRLock("serve.service")
        with r:
            with r:                 # legal re-entry, no violation
                assert lockmod.held_families() == ("serve.service",)
            assert r.locked()
        assert not r.locked()

    def test_plain_lock_self_deadlock_reported(self):
        lk = OrderedLock("serve.service")
        with lk:
            with pytest.raises(LockOrderViolation) as ei:
                lk.acquire()
        assert ei.value.kind == "self"

    def test_rlock_release_order(self):
        """Held-set entry survives until the OUTERMOST release."""
        r = OrderedRLock("serve.pool")
        inner = OrderedLock("telemetry.metric")
        with r:
            r.acquire()
            r.release()
            with inner:             # rank 90 > 40: still legal
                pass
            assert lockmod.held_families() == ("serve.pool",)
        assert lockmod.held_families() == ()


class TestHistogramContract:
    def test_hold_and_wait_observed(self):
        reg = MetricsRegistry()
        lk = OrderedLock("test.metrics", registry=reg)
        with lk:
            pass
        with lk:
            pass
        snap = reg.snapshot()["metrics"]
        hold = snap["lock_hold_s{name=test.metrics}"]
        wait = snap["lock_wait_s{name=test.metrics}"]
        # one wait + one hold observation per completed acquire/release
        assert hold["count"] == 2 and wait["count"] == 2
        assert hold["sum"] >= 0 and wait["sum"] >= 0

    def test_wait_measures_contention(self):
        reg = MetricsRegistry()
        lk = OrderedLock("test.contend", registry=reg)
        entered = threading.Event()
        release = threading.Event()

        def holder():
            with lk:
                entered.set()
                release.wait(5.0)

        th = threading.Thread(target=holder)
        th.start()
        assert entered.wait(5.0)
        t = threading.Timer(0.05, release.set)
        t.start()
        with lk:                    # blocks ~50 ms behind the holder
            pass
        th.join(5.0)
        row = reg.snapshot()["metrics"]["lock_wait_s{name=test.contend}"]
        assert row["count"] == 2
        assert row["max"] >= 0.03   # the contended acquire showed up

    def test_no_registry_no_histograms(self):
        lk = OrderedLock("test.bare")
        with lk:
            pass                    # simply must not blow up

    def test_rlock_holds_once_per_outermost(self):
        reg = MetricsRegistry()
        r = OrderedRLock("test.rehold", registry=reg)
        with r:
            with r:
                pass
        row = reg.snapshot()["metrics"]["lock_hold_s{name=test.rehold}"]
        assert row["count"] == 1    # hold time = outermost span only


class TestDisarmedFastPath:
    def test_disarmed_inversion_not_checked(self):
        """Disarmed = production fast path: no rank check runs (the
        static tier + armed smokes own correctness; production pays
        only the histogram feed)."""
        lockmod.disarm()
        a = OrderedLock("serve.service")
        b = OrderedLock("telemetry.registry")
        with b:
            with a:                 # inverted — but not checked
                pass

    def test_env_arming(self, monkeypatch):
        monkeypatch.setenv("ACLSWARM_LOCK_DEBUG", "1")
        assert lockmod._env_armed()
        monkeypatch.setenv("ACLSWARM_LOCK_DEBUG", "0")
        assert not lockmod._env_armed()
        monkeypatch.delenv("ACLSWARM_LOCK_DEBUG")
        assert not lockmod._env_armed()
