"""swarmproto: the protocol spec, the JC2xx conformance lint, the
explicit-state model checker, and journal trace refinement.

Four layers of the same protocol, tested against each other: the
declarative transition table accepts exactly the legal request
histories; the linter fires on the known-bad fixtures and stays at
zero across serve/ + resilience/; every deliberate protocol mutation
trips exactly its property with a minimal counterexample naming the
crashing boundary; and journals — synthetic and real — refine into
accepted protocol traces.
"""
from __future__ import annotations

from pathlib import Path

import pytest

from aclswarm_tpu.analysis import model as modelmod
from aclswarm_tpu.analysis import protocol as protomod
from aclswarm_tpu.telemetry import LifecycleLog, lifecycle, mint_trace_id

FIXTURES = Path(__file__).parent / "fixtures" / "jaxcheck"

pytestmark = pytest.mark.analysis


# ------------------------------------------------------ declarative spec

CLEAN = ["submitted", "admitted", "batched", "chunk", "queued",
         "batched", "chunk", "checkpointed", "resolved"]


class TestProtocolSpec:
    def test_alphabet_is_exactly_the_request_vocabulary(self):
        alphabet = {ev for edges in protomod.TRANSITIONS.values()
                    for ev in edges}
        assert alphabet == set(lifecycle.EVENTS)

    def test_optionals_cover_every_event(self):
        assert set(protomod.OPTIONAL_FIELDS) == set(protomod.VOCABULARY)

    def test_clean_trace_accepted_and_terminal(self):
        ok, phase, problem = protomod.accepts(CLEAN)
        assert ok and problem is None
        assert phase == protomod.TERMINAL_PHASE

    def test_prefix_closed(self):
        """Crash-at-any-boundary: every prefix of a legal history is
        itself a legal (incomplete) history."""
        for cut in range(len(CLEAN) + 1):
            ok, phase, problem = protomod.accepts(CLEAN[:cut])
            assert ok, f"prefix {CLEAN[:cut]} rejected: {problem}"

    def test_terminal_exactly_once(self):
        ok, _, problem = protomod.accepts(CLEAN + ["resolved"])
        assert not ok and "'resolved'" in problem

    def test_nothing_before_submitted(self):
        ok, _, problem = protomod.accepts(["batched"])
        assert not ok and "phase 'init'" in problem

    def test_cancel_then_resolve_via_finishing(self):
        ok, phase, _ = protomod.accepts(
            ["submitted", "admitted", "cancelled", "resolved"])
        assert ok and phase == protomod.TERMINAL_PHASE

    def test_fragment_accepts_mid_stream_slice(self):
        """A migrated request's slice in the SURVIVOR's journal starts
        mid-protocol — legal as a fragment, illegal from init."""
        slice_ = ["batched", "chunk", "resolved"]
        ok, _, _ = protomod.accepts(slice_)
        assert not ok
        ok, problem = protomod.accepts_fragment(slice_)
        assert ok, problem

    def test_fragment_still_rejects_impossible_orders(self):
        ok, problem = protomod.accepts_fragment(
            ["resolved", "submitted"])
        assert not ok and "illegal in every reachable phase" in problem


# ------------------------------------------------------ conformance lint

def _by_file(violations):
    out = {}
    for v in violations:
        out.setdefault(Path(v.path).name, []).append(v)
    return out


class TestProtocolFixtures:
    @pytest.fixture(scope="class")
    def fired(self):
        return _by_file(protomod.check_paths(
            [FIXTURES / f for f in ("bad_jc201.py", "bad_jc202.py",
                                    "bad_jc203.py", "bad_jc204.py")]))

    @pytest.mark.parametrize("fixture,rule,count", [
        ("bad_jc201.py", "JC201", 1),
        ("bad_jc202.py", "JC202", 3),
        ("bad_jc203.py", "JC203", 2),
        ("bad_jc204.py", "JC204", 3),
    ])
    def test_rule_fires(self, fired, fixture, rule, count):
        vs = fired.get(fixture, [])
        assert [v.rule for v in vs] == [rule] * count, \
            f"{fixture}: expected {count}x{rule}, got {vs}"

    def test_fixture_lines_match_annotations(self, fired):
        for fname, vs in fired.items():
            src = (FIXTURES / fname).read_text().splitlines()
            for v in vs:
                assert v.rule in src[v.line - 1], \
                    f"{fname}:{v.line} fired {v.rule} on an " \
                    f"unannotated line: {src[v.line - 1]!r}"

    def test_clean_cases_stay_quiet(self, fired):
        """Durable-then-reply, ctor writes, emitting helpers, locked
        once-guards, splat emissions: annotated `clean` lines must not
        fire."""
        for fname, vs in fired.items():
            src = (FIXTURES / fname).read_text().splitlines()
            for v in vs:
                assert "clean" not in src[v.line - 1], \
                    f"{fname}:{v.line} fired on a clean line"

    def test_pragma_suppresses(self, fired):
        """`# jaxcheck: disable=JC204` waives the reviewed line."""
        for vs in fired.values():
            for v in vs:
                src = Path(v.path).read_text().splitlines()
                assert "disable=" + v.rule not in src[v.line - 1]


class TestProtocolRepo:
    def test_serve_and_resilience_sweep_clean(self):
        """The acceptance bar: zero unsuppressed JC201-JC204 across
        serve/ + resilience/, INCLUDING vocabulary coverage (every
        event in the schema has a real emission site)."""
        violations = protomod.check_paths(protomod.default_paths(),
                                          coverage=True)
        assert violations == [], "\n".join(str(v) for v in violations)

    def test_cli_exit_codes(self, tmp_path):
        assert protomod.main(["-q", str(FIXTURES / "bad_jc204.py")]) == 1
        clean = tmp_path / "clean.py"
        clean.write_text("def f():\n    return 1\n")
        assert protomod.main(["-q", str(clean)]) == 0

    def test_lint_all_merges_tiers(self, capsys):
        """`lint --all` runs JC0xx + JC1xx + JC2xx over their default
        paths with one merged exit surface."""
        from aclswarm_tpu.analysis import lint as lintmod
        assert lintmod.main(["--all"]) == 0
        out = capsys.readouterr()
        assert "jaxcheck:" in out.out
        assert "jaxcheck-concurrency:" in out.out
        assert "swarmproto:" in out.out + out.err


# ----------------------------------------------------- the model checker

class TestModelChecker:
    def test_all_properties_hold_on_2x2(self):
        res = modelmod.check(modelmod.ModelConfig())
        assert res.ok, modelmod.render_trace(res)
        assert res.states > 100    # the space is genuinely explored

    @pytest.mark.parametrize("mutation,expected",
                             sorted(modelmod.MUTATIONS.items()))
    def test_mutation_trips_exactly_its_property(self, mutation,
                                                 expected):
        """Each deliberate protocol mutation — drop the done-frame
        append, skip the fence check, remove a once-guard — must trip
        precisely the property built to catch it, with a non-empty
        minimal trace."""
        res = modelmod.check(modelmod.ModelConfig(mutation=mutation))
        assert not res.ok, f"{mutation} tripped nothing"
        assert res.property == expected, \
            f"{mutation} tripped {res.property}, expected {expected}"
        assert res.trace, "counterexample trace is empty"

    def test_counterexample_names_property_and_steps(self):
        res = modelmod.check(
            modelmod.ModelConfig(mutation="double_resolve"))
        text = modelmod.render_trace(res)
        assert "PROPERTY VIOLATED: P3" in text
        assert "terminal-once" in text
        assert f"trace ({len(res.trace)} steps)" in text
        # every step is numbered in order
        for i in range(1, len(res.trace) + 1):
            assert f"{i:2d}. " in text

    def test_skip_fence_counterexample_names_the_boundary(self):
        """The P4 counterexample's crash step must say WHICH boundary
        the SIGKILL interrupted — that is the line a human replays."""
        res = modelmod.check(
            modelmod.ModelConfig(mutation="skip_fence"))
        assert res.property == "P4"
        text = modelmod.render_trace(res)
        assert "<- boundary: after" in text
        assert any("zombie_write" in label
                   for label, *_ in res.trace)

    def test_drop_done_frame_is_a_lost_request(self):
        res = modelmod.check(
            modelmod.ModelConfig(mutation="drop_done_frame"))
        assert res.property == "P1"
        assert "[dropped]" in " ".join(l for l, *_ in res.trace)

    def test_mutated_transitions_need_their_schedule(self):
        """With no crash budget the fence mutation has no zombie to
        land — the checker must prove the MUTATED system correct under
        schedules that never reach the hole (no false alarms)."""
        res = modelmod.check(modelmod.ModelConfig(
            mutation="skip_fence", crashes=0, failovers=0,
            zombie=False))
        assert res.ok


# ----------------------------------------------------- trace refinement

def _emit_history(log: LifecycleLog, rid: str, events) -> None:
    tid = mint_trace_id()
    t = [1000.0]
    defaults = {
        "submitted": {"kind": "rollout", "tenant": "a"},
        "admitted": {},
        "queued": {"reason": "boundary"},
        "batched": {"worker": 0, "round": 1, "batch": 1},
        "chunk": {"k": 0, "digest": 7, "worker": 0},
        "checkpointed": {"chunk": 0, "durable": True},
        "migrated": {"dead_worker": 0, "chunk": 0},
        "resumed": {"from_chunk": 0},
        "preempted": {"chunk": 0},
        "deadline": {"chunk": 0},
        "cancelled": {"reason": "client"},
        "poisoned": {},
        "resolved": {"status": "completed", "chunks": 1},
    }
    for ev in events:
        t[0] += 0.1
        log.emit(ev, request_id=rid, trace_id=tid, t_wall=t[0],
                 **defaults[ev])


class TestRefinement:
    def test_synthetic_clean_journal_refines(self, tmp_path):
        log = LifecycleLog(tmp_path / "events.log")
        _emit_history(log, "r1", CLEAN)
        assert modelmod.refine_dir(tmp_path) == []

    def test_protocol_violating_journal_is_caught(self, tmp_path):
        log = LifecycleLog(tmp_path / "events.log")
        _emit_history(log, "r1", CLEAN + ["resolved"])   # terminal twice
        problems = modelmod.refine_dir(tmp_path)
        assert len(problems) == 1 and "illegal" in problems[0]

    def test_fleet_slices_refine_as_fragments(self, tmp_path):
        """A migrated request: acceptance + first chunk in slot0's
        journal, resumption + terminal in slot1's. Each slice refines
        as a fragment; slot1's would be ILLEGAL from init."""
        a, b = tmp_path / "slot0", tmp_path / "slot1"
        _emit_history(LifecycleLog(a / "events.log"), "r1",
                      ["submitted", "admitted", "batched", "chunk"])
        _emit_history(LifecycleLog(b / "events.log"), "r1",
                      ["batched", "resumed", "chunk", "resolved"])
        assert modelmod.refine_dir(b) != []       # not valid from init
        rep = modelmod.refine_tree(tmp_path)      # siblings = one fleet
        assert rep["journals"] == 2 and rep["problems"] == []

    def test_refine_cli_exit_codes(self, tmp_path, capsys):
        good = tmp_path / "good"
        _emit_history(LifecycleLog(good / "events.log"), "r1", CLEAN)
        assert modelmod.main(["--refine", str(good), "-q"]) == 0
        bad = tmp_path / "bad"
        _emit_history(LifecycleLog(bad / "events.log"), "r1",
                      ["submitted", "submitted"])
        assert modelmod.main(["--refine", str(bad), "-q"]) == 1
        out = capsys.readouterr().out
        assert "REFINEMENT FAIL" in out
        empty = tmp_path / "empty"
        empty.mkdir()
        assert modelmod.main(["--refine", str(empty), "-q"]) == 1

    def test_real_service_journal_refines(self, tmp_path):
        """End to end: a live SwarmService journal — acceptance frames,
        lifecycle events, terminal — replays as an accepted, complete
        protocol trace."""
        from aclswarm_tpu.serve import ServiceConfig, SwarmService
        svc = SwarmService(ServiceConfig(max_batch=2,
                                         journal_dir=str(tmp_path)))
        try:
            t = svc.submit("assign", {"n": 8, "seed": 3}, tenant="a")
            assert t.result(timeout=120).ok
        finally:
            svc.close()
        assert modelmod.refine_dir(tmp_path) == []
