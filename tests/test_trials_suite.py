"""benchmarks/trials_suite file mechanics: crash-safe CSV writes and the
expected-completion exit gate (stubbed trials — no device, no rollouts)."""
import importlib.util
import json
import sys
from pathlib import Path

REPO = Path(__file__).resolve().parents[1]


def _load(tmp_path, monkeypatch):
    spec = importlib.util.spec_from_file_location(
        "trials_suite", REPO / "benchmarks" / "trials_suite.py")
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    monkeypatch.setattr(mod, "RESULTS", tmp_path)
    return mod


def test_atomic_replace_on_success(tmp_path, monkeypatch):
    mod = _load(tmp_path, monkeypatch)

    def fake_run_trials(cfg):
        with open(cfg.out, "a") as fh:
            fh.write("0,1.0\n")
        return {"completion_pct": 100.0, "trials_completed": 1,
                "trials": cfg.trials}

    monkeypatch.setattr(mod.triallib, "run_trials", fake_run_trials)
    stats = mod.run_config("x", dict(formation="swarm6_3d"), 1)
    out = tmp_path / "trials_x.csv"
    assert out.read_text() == "0,1.0\n"
    assert not (tmp_path / ".trials_x.csv.tmp").exists()
    assert stats["config"]["csv"] == "trials_x.csv"


def test_crash_keeps_committed_csv(tmp_path, monkeypatch):
    """A wedge/crash mid-config (observed: the device tunnel hanging
    before trial 0 finished) must leave the committed CSV untouched."""
    mod = _load(tmp_path, monkeypatch)
    out = tmp_path / "trials_x.csv"
    out.write_text("committed,evidence\n")

    def crashing_run_trials(cfg):
        with open(cfg.out, "a") as fh:
            fh.write("partial\n")
        raise RuntimeError("tunnel wedge")

    monkeypatch.setattr(mod.triallib, "run_trials", crashing_run_trials)
    try:
        mod.run_config("x", dict(formation="swarm6_3d"), 1)
    except RuntimeError:
        pass
    assert out.read_text() == "committed,evidence\n"
    # and the next (successful) run cleans the stale temp up
    def ok_run_trials(cfg):
        with open(cfg.out, "a") as fh:
            fh.write("fresh\n")
        return {"completion_pct": 100.0}
    monkeypatch.setattr(mod.triallib, "run_trials", ok_run_trials)
    mod.run_config("x", dict(formation="swarm6_3d"), 1)
    assert out.read_text() == "fresh\n"


def test_zero_completion_keeps_committed_csv(tmp_path, monkeypatch):
    mod = _load(tmp_path, monkeypatch)
    out = tmp_path / "trials_x.csv"
    out.write_text("committed,evidence\n")

    def empty_run_trials(cfg):
        return {"completion_pct": 0.0}    # no row ever appended

    monkeypatch.setattr(mod.triallib, "run_trials", empty_run_trials)
    stats = mod.run_config("x", dict(formation="swarm6_3d"), 1)
    assert out.read_text() == "committed,evidence\n"
    assert stats["csv_kept_from_prior_run"] is True


def test_batch_and_wall_clock_recorded(tmp_path, monkeypatch):
    """--batch wires TrialConfig.batch (capped at m) with the chunk
    auto-aligned to the auction period, and the summary row records the
    batch size + per-trial wall clock."""
    mod = _load(tmp_path, monkeypatch)
    seen = {}

    def fake_run_trials(cfg):
        seen["batch"] = cfg.batch
        seen["chunk"] = cfg.chunk_ticks
        seen["assign_every"] = cfg.assign_every
        with open(cfg.out, "a") as fh:
            fh.write("0,1.0\n")
        return {"completion_pct": 100.0, "trials_completed": 3,
                "trials": cfg.trials}

    monkeypatch.setattr(mod.triallib, "run_trials", fake_run_trials)
    stats = mod.run_config("x", dict(formation="swarm6_3d"), 3, batch=8)
    assert seen["batch"] == 3                       # capped at m
    assert seen["chunk"] % seen["assign_every"] == 0
    assert stats["batch"] == 3
    assert stats["wall_s_per_trial"] >= 0.0
    # serial runs keep recording batch=1 so evidence stays distinguishable
    stats = mod.run_config("x", dict(formation="swarm6_3d"), 3)
    assert stats["batch"] == 1


def test_expected_pct_gate():
    """Dispositioned sub-100 rows pass the gate at their documented
    completion; anything below trips it."""
    spec = importlib.util.spec_from_file_location(
        "trials_suite", REPO / "benchmarks" / "trials_suite.py")
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    summary = json.load(open(
        REPO / "benchmarks" / "results" / "trials_summary.json"))
    bad = [k for k, v in summary["configs"].items()
           if v["completion_pct"] < mod.EXPECTED_PCT.get(k, 100.0)]
    assert bad == [], bad
    # a row below its expectation is flagged
    assert 60.0 < mod.EXPECTED_PCT["simform100_cbaa_flooded_escapes"]
