"""Closed-loop simulation tests — the minimum end-to-end slice
(SURVEY.md §7): formation library -> assignment -> control law -> dynamics
scan -> supervisor predicates, all jitted on a single device.

The swarm6_3d group with its committed golden gain matrices
(`aclswarm/param/formations.yaml:141-250`) is the reference's README demo
config; convergence of this loop is the reference's own definition of a
successful trial (`aclswarm_sim/nodes/supervisor.py` predicates).
"""
import os

import jax.numpy as jnp
import numpy as np
import pytest

from aclswarm_tpu import harness, sim
from aclswarm_tpu.core import perm as permutil
from aclswarm_tpu.core import geometry
from aclswarm_tpu.core.types import (ControlGains, SafetyParams,
                                     make_formation)
from aclswarm_tpu.harness import supervisor

REF_FORMATIONS = "/root/reference/aclswarm/param/formations.yaml"

needs_reference = pytest.mark.skipif(
    not os.path.exists(REF_FORMATIONS),
    reason="reference formation library not mounted")


def room_params():
    # a roomy flight volume so bounds don't bind in the convergence tests
    return SafetyParams(
        bounds_min=jnp.asarray([-20.0, -20.0, 0.0]),
        bounds_max=jnp.asarray([20.0, 20.0, 10.0]),
        max_vel_xy=2.0, max_vel_z=1.0, max_accel_xy=2.0, max_accel_z=2.0,
        d_avoid_thresh=1.2, r_keep_out=0.45)


def spread_start(n, seed, span=6.0, alt=1.5):
    """Non-overlapping takeoff-like initial positions on a ring + jitter."""
    rng = np.random.default_rng(seed)
    ang = np.linspace(0, 2 * np.pi, n, endpoint=False)
    q0 = np.stack([span * np.cos(ang), span * np.sin(ang),
                   np.full(n, alt)], axis=1)
    return q0 + rng.normal(scale=0.3, size=(n, 3)) * [1, 1, 0.1]


def shape_error(q, points, v2f):
    """RMS residual between the swarm and the best-aligned formation.

    The control law is invariant to xy rotation+translation AND z translation
    (gains kernel, SURVEY.md §2.1 C5), so the residual is computed after a
    2D alignment plus z mean-centering — the same invariance class.
    """
    q_form = permutil.veh_to_formation_order(jnp.asarray(q), v2f)
    aligned = geometry.align(jnp.asarray(points), q_form, d=2)
    resid = q_form - aligned
    resid = resid.at[:, 2].add(-jnp.mean(resid[:, 2]))
    return float(jnp.sqrt(jnp.mean(jnp.sum(resid ** 2, -1))))


@needs_reference
class TestSwarm6_3dConvergence:
    @pytest.fixture(scope="class")
    def pyramid(self):
        return harness.load_formation("Pentagonal Pyramid",
                                      path=REF_FORMATIONS, group="swarm6_3d")

    def _run(self, spec, seed, assignment="auction", ticks=4500):
        f = spec.to_device()
        st = sim.init_state(spread_start(spec.n, seed))
        cfg = sim.SimConfig(assignment=assignment)
        final, m = sim.rollout(st, f, ControlGains(), room_params(), cfg,
                               ticks)
        res = supervisor.evaluate(
            np.asarray(m.distcmd_norm), np.asarray(m.ca_active),
            np.asarray(m.q), np.asarray(m.reassigned),
            np.asarray(m.assign_valid), cfg.control_dt)
        return final, m, res

    def test_converges_with_auction(self, pyramid):
        final, m, res = self._run(pyramid, seed=0)
        assert res.converged, f"never converged: {res}"
        assert res.convergence_time_s < 40.0, res.convergence_time_s
        err = shape_error(final.swarm.q, pyramid.points, final.v2f)
        assert err < 0.35, f"shape error {err:.3f} m"

    def test_converges_with_cbaa(self, pyramid):
        final, m, res = self._run(pyramid, seed=1, assignment="cbaa")
        assert res.converged, f"never converged: {res}"
        err = shape_error(final.swarm.q, pyramid.points, final.v2f)
        assert err < 0.35, f"shape error {err:.3f} m"

    def test_scrambled_start_reassigns(self, pyramid):
        # start vehicles near the WRONG formation points; the auction must
        # discover a better-than-identity assignment
        rng = np.random.default_rng(3)
        scramble = rng.permutation(pyramid.n).astype(np.int32)
        q0 = pyramid.points[scramble] + [4.0, 4.0, 1.5]
        st = sim.init_state(q0 + rng.normal(scale=0.05, size=q0.shape))
        f = pyramid.to_device()
        cfg = sim.SimConfig(assignment="auction")
        final, m = sim.rollout(st, f, ControlGains(), room_params(), cfg, 300)
        v2f = np.asarray(final.v2f)
        # vehicle v sits at formation point scramble[v] (translated). The
        # pyramid's pentagonal symmetry admits several equally-optimal
        # assignments, so check optimality, not equality: under the final
        # alignment, the chosen assignment must cost no more than the LAP
        # oracle's optimum (and far less than identity).
        from aclswarm_tpu.assignment import lapjv
        q_form = permutil.veh_to_formation_order(final.swarm.q, final.v2f)
        paligned = np.asarray(geometry.align(jnp.asarray(pyramid.points),
                                             q_form, d=2))
        cost = np.linalg.norm(np.asarray(final.swarm.q)[:, None]
                              - paligned[None, :], axis=-1)
        achieved = cost[np.arange(6), v2f].sum()
        optimal = cost[np.arange(6), lapjv(cost)].sum()
        identity_cost = np.trace(cost)
        assert achieved <= optimal + 1e-6, (achieved, optimal)
        assert achieved < identity_cost
        assert np.any(np.asarray(m.reassigned))

    def test_no_gridlock_reported(self, pyramid):
        _, _, res = self._run(pyramid, seed=4)
        assert not res.gridlocked
        assert res.invalid_auctions == 0

    def test_assign_hysteresis(self, pyramid):
        """assign_eps: the first post-commit auction always lands
        (`formation_just_received_`, `auctioneer.cpp:310-316`), later
        near-tie reshuffles are rejected by the margin, clear improvements
        pass, and eps=0 reproduces accept-any-different semantics."""
        rng = np.random.default_rng(3)
        scramble = rng.permutation(pyramid.n).astype(np.int32)
        q0 = pyramid.points[scramble] + [4.0, 4.0, 1.5]
        st = sim.init_state(q0 + rng.normal(scale=0.05, size=q0.shape))
        f = pyramid.to_device()
        # margin nothing can beat: the tick-0 auction is still accepted
        # (formation-just-received bypass), every later one is vetoed, so
        # the assignment is frozen at the first auction's result
        cfg = sim.SimConfig(assignment="auction", assign_eps=0.999)
        final, m = sim.rollout(st, f, ControlGains(), room_params(), cfg,
                               300)
        reassigned = np.asarray(m.reassigned)
        assert not np.any(reassigned[1:])           # frozen after tick 0
        first_v2f = np.asarray(m.v2f)[0]
        assert np.array_equal(np.asarray(final.v2f), first_v2f)
        assert not bool(np.asarray(final.first_auction))
        # a 1% margin still lets the scrambled start's large improvement in
        cfg = sim.SimConfig(assignment="auction", assign_eps=0.01)
        final, m = sim.rollout(st, f, ControlGains(), room_params(), cfg,
                               300)
        assert np.any(np.asarray(m.reassigned))
        assert not np.array_equal(np.asarray(final.v2f),
                                  np.arange(pyramid.n))

    def test_first_auction_bypass_cleared_only_by_valid_auction(self,
                                                                pyramid):
        """The bypass persists across ticks with no auction and is cleared
        by the first valid one."""
        rng = np.random.default_rng(5)
        q0 = pyramid.points + rng.normal(scale=0.05, size=(pyramid.n, 3))
        st = sim.init_state(q0 + [2.0, 0.0, 1.0])
        f = pyramid.to_device()
        cfg = sim.SimConfig(assignment="auction", assign_every=50)
        # ticks 1..49 run no auction -> flag stays up
        mid, _ = sim.rollout(st.replace(tick=st.tick + 1), f,
                             ControlGains(), room_params(), cfg, 10)
        assert bool(np.asarray(mid.first_auction))
        # the tick-0 auction clears it
        post, _ = sim.rollout(st, f, ControlGains(), room_params(), cfg, 1)
        assert not bool(np.asarray(post.first_auction))


class TestFormationLoader:
    def test_own_library_loads(self):
        """The shipped swarm6_3d is the reference demo group like-for-like:
        three formations on the reference's SPARSE per-formation graphs
        (`/root/reference/aclswarm/param/formations.yaml:141-250`; no
        group-level key, so the per-formation matrices load)."""
        group = harness.load_group(group="swarm6_3d")
        names = [f.name for f in group]
        assert names == ["Pentagonal Pyramid", "Triangular Prism",
                         "Slanted Plane"]
        fm = group[0]
        assert fm.points.shape == (6, 3)
        want = np.array([[0, 0, 1, 1, 0, 1], [0, 0, 1, 0, 0, 1],
                         [1, 1, 0, 1, 0, 0], [1, 0, 1, 0, 1, 0],
                         [0, 0, 0, 1, 0, 1], [1, 1, 0, 0, 1, 0]])
        np.testing.assert_allclose(fm.adjmat, want)
        # the committed gains were designed for the sparse graph: zero
        # 3x3 blocks exactly on the non-edges
        for i in range(6):
            for j in range(6):
                if i != j and not want[i, j]:
                    np.testing.assert_allclose(
                        fm.gains[3 * i:3 * i + 3, 3 * j:3 * j + 3], 0.0,
                        atol=1e-9)

    def test_scale_applied_to_points_only(self, tmp_path):
        """Loader multiplies points by the formation's scale and leaves the
        gains untouched (`operator.py:155-157`)."""
        import yaml
        pts = [[0.0, 0.0, 0.0], [2.0, 0.0, 0.0], [0.0, 2.0, 0.0]]
        gains = np.arange(81, dtype=float).reshape(9, 9)
        lib = {"g": {"agents": 3, "adjmat": "fc", "formations": [
            {"name": "tri", "scale": 1.5, "points": pts,
             "gains": gains.tolist()}]}}
        path = tmp_path / "lib.yaml"
        path.write_text(yaml.safe_dump(lib))
        fm = harness.load_formation("tri", path=str(path), group="g")
        np.testing.assert_allclose(fm.points, 1.5 * np.asarray(pts))
        np.testing.assert_allclose(fm.gains, gains)

    @needs_reference
    def test_reference_library_group_fc_override(self):
        # swarm6_3d in the reference carries per-formation adjmats AND a
        # group-level 'fc' — operator semantics say fc wins
        # (`operator.py:95-109`)
        fm = harness.load_formation("Pentagonal Pyramid",
                                    path=REF_FORMATIONS, group="swarm6_3d")
        np.testing.assert_allclose(fm.adjmat, np.ones((6, 6)) - np.eye(6))
        assert fm.gains is not None and fm.gains.shape == (18, 18)

    @needs_reference
    def test_reference_gains_zero_blocks_match_sparse_graph(self):
        # the committed gains respect the formation's own (sparse) adjmat
        import yaml
        with open(REF_FORMATIONS) as fh:
            lib = yaml.safe_load(fh)
        spec = lib["swarm6_3d"]["formations"][0]
        gains = np.asarray(spec["gains"])
        adj = np.asarray(spec["adjmat"])
        for i in range(6):
            for j in range(6):
                if i != j and not adj[i, j]:
                    block = gains[3 * i:3 * i + 3, 3 * j:3 * j + 3]
                    np.testing.assert_allclose(block, 0.0, atol=1e-12)


class TestSupervisor:
    def test_rolling_mean(self):
        x = np.arange(10, dtype=float)[:, None]
        rm = supervisor.rolling_mean(x, 3)
        assert np.isnan(rm[0, 0]) and np.isnan(rm[1, 0])
        np.testing.assert_allclose(rm[2, 0], 1.0)
        np.testing.assert_allclose(rm[9, 0], 8.0)

    def test_convergence_fsm_timing(self):
        # quiet command from the start: the FSM spends 1 s in FLYING before
        # predicates run (FORMATION_RECEIVED_WAIT), 1 s filling the buffer,
        # then 1 s confirming IN_FORMATION (CONVERGED_WAIT) — so the logged
        # convergence time is ~3 s, dwell included, as in the reference CSV
        T, n, dt = 400, 3, 0.01
        cmd = np.zeros((T, n))
        res = supervisor.evaluate(
            cmd, np.zeros((T, n)), np.zeros((T, n, 3)),
            np.zeros(T, bool), np.ones(T, bool), dt)
        assert res.converged
        assert res.convergence_time_s == pytest.approx(3.0, abs=0.05)

    def test_unconverged_when_loud(self):
        T, n, dt = 400, 3, 0.01
        res = supervisor.evaluate(
            np.full((T, n), 5.0), np.zeros((T, n)), np.zeros((T, n, 3)),
            np.zeros(T, bool), np.ones(T, bool), dt)
        assert not res.converged
        assert res.convergence_time_s is None

    def test_gridlock_episode_and_recovery(self):
        T, n, dt = 600, 2, 0.01
        ca = np.zeros((T, n))
        ca[100:250, 1] = 1.0  # vehicle 1 stuck in avoidance 1.5 s
        res = supervisor.evaluate(
            np.ones((T, n)) * 5.0, ca, np.zeros((T, n, 3)),
            np.zeros(T, bool), np.ones(T, bool), dt)
        # entered GRIDLOCK but recovered (no 90 s persistence)
        assert res.gridlocked
        assert not res.gridlock_terminated
        assert not res.converged  # command stays loud
        # episode: enters when the 1 s buffer fills with CA-active (t≈2.0 s),
        # leaves once the fresh in-state buffer reads clear (t≈3.0 s)
        assert res.last_gridlock_episode_s == pytest.approx(1.0, abs=0.1)
        np.testing.assert_allclose(res.time_in_avoidance_s, [0.0, 1.5])

    def test_gridlock_termination_after_90s(self):
        dt = 0.01
        T = int(100.0 / dt)
        n = 2
        ca = np.zeros((T, n))
        ca[100:, 0] = 1.0  # vehicle 0 in avoidance forever
        res = supervisor.evaluate(
            np.ones((T, n)) * 5.0, ca, np.zeros((T, n, 3)),
            np.zeros(T, bool), np.ones(T, bool), dt)
        assert res.gridlocked and res.gridlock_terminated
        assert not res.converged

    def test_distance_traveled_suppresses_jitter(self):
        rng = np.random.default_rng(0)
        T, n = 500, 2
        q = np.zeros((T, n, 3))
        # vehicle 0 hovers with sensor jitter; vehicle 1 moves 5 m in x
        q[:, 0, :2] = rng.normal(scale=0.005, size=(T, 2))
        q[:, 1, 0] = np.linspace(0, 5, T)
        d = supervisor.distance_traveled(q)
        # the EWMA filter suppresses ~5 mm jitter to cm-scale totals while
        # real travel passes through nearly unattenuated
        assert d[0] < 0.1
        assert 4.0 < d[1] < 5.1
        assert d[1] > 40 * d[0]


class TestGridlockFromDynamics:
    """Gridlock produced by the *closed-loop dynamics*, not synthetic
    ca_active series (round-1 review weak #6): with CBAA assignment on a
    ring+chord graph, seed-7 initial conditions deadlock the swarm in
    mutual collision avoidance, and the supervisor's oracle detects it
    from the rollout's own signals (SURVEY.md hard part 4)."""

    def _rollout(self, seed, assignment):
        from aclswarm_tpu import gains as gainslib
        rng = np.random.default_rng(seed)
        n = 6
        adj = np.zeros((n, n))
        for i in range(n):
            adj[i, (i + 1) % n] = adj[(i + 1) % n, i] = 1
        adj[0, 3] = adj[3, 0] = 1
        ang = np.linspace(0, 2 * np.pi, n, endpoint=False)
        pts = np.stack([3 * np.cos(ang), 3 * np.sin(ang),
                        np.full(n, 1.5)], 1)
        G = np.asarray(gainslib.solve_gains(pts, adj))
        formation = make_formation(pts, adj, G)
        q0 = rng.normal(size=(n, 3)) * 2.0
        q0[:, 2] = 1.5
        cfg = sim.SimConfig(assignment=assignment, dynamics="firstorder")
        state = sim.init_state(jnp.asarray(q0))
        _, metrics = sim.rollout(state, formation, ControlGains(),
                                 SafetyParams(), cfg, 3000)
        return metrics

    def test_cbaa_seed7_gridlocks_and_supervisor_detects(self):
        m = self._rollout(7, "cbaa")
        res = supervisor.evaluate(
            np.asarray(m.distcmd_norm), np.asarray(m.ca_active),
            np.asarray(m.q), np.asarray(m.reassigned),
            np.asarray(m.assign_valid), 0.01)
        assert res.gridlocked          # emerged from the dynamics
        assert not res.converged
        # every vehicle is avoidance-locked at the end
        assert np.asarray(m.ca_active)[-100:].mean() > 0.95

    def test_centralized_auction_escapes_same_seed(self):
        """The centralized-vs-decentralized comparison the reference's
        toggle exists for: exact reassignment breaks the deadlock the
        consensus auction cannot."""
        m = self._rollout(7, "auction")
        res = supervisor.evaluate(
            np.asarray(m.distcmd_norm), np.asarray(m.ca_active),
            np.asarray(m.q), np.asarray(m.reassigned),
            np.asarray(m.assign_valid), 0.01)
        assert res.converged
        assert not res.gridlock_terminated


class TestDoubleIntegratorDynamics:
    """`dynamics='doubleint'`: the SysDynam.m-style second-order vehicle."""

    def _setup(self):
        from aclswarm_tpu import gains as gainslib
        n = 4
        pts = np.array([[0., 0, 1], [2, 0, 1], [2, 2, 1], [0, 2, 1]])
        adj = np.ones((n, n)) - np.eye(n)
        G = np.asarray(gainslib.solve_gains(pts, adj))
        formation = make_formation(pts, adj, G)
        rng = np.random.default_rng(4)
        q0 = rng.normal(size=(n, 3)) * 1.5
        q0[:, 2] = 1.0
        return formation, jnp.asarray(q0)

    def test_converges(self):
        formation, q0 = self._setup()
        cfg = sim.SimConfig(dynamics="doubleint")
        state = sim.init_state(q0)
        state, metrics = sim.rollout(state, formation, ControlGains(),
                                     SafetyParams(), cfg, 3000)
        dn = np.asarray(metrics.distcmd_norm)[-100:]
        assert dn.mean() < 0.3
        # velocities die down at the fixed point (second-order settle)
        assert np.abs(np.asarray(state.swarm.vel)).max() < 0.1

    @pytest.mark.slow
    def test_velocity_is_continuous(self):
        """A double integrator cannot jump velocity: per-tick delta is
        bounded by acc*dt (unlike 'tracking', which teleports to goals)."""
        formation, q0 = self._setup()
        cfg = sim.SimConfig(dynamics="doubleint")
        state = sim.init_state(q0)
        vels = [np.asarray(state.swarm.vel)]
        for _ in range(50):
            state, _ = sim.step(state, formation, ControlGains(),
                                SafetyParams(), cfg)
            vels.append(np.asarray(state.swarm.vel))
        dv = np.diff(np.stack(vels), axis=0)
        # |acc| <= kp*|err| + kd*|verr|; with this geometry the bound is
        # loose at ~60 m/s^2 -> 0.6 m/s per 10 ms tick
        assert np.abs(dv).max() < 0.6

    def test_second_order_lags_first_order(self):
        """Response character: from rest, the double integrator moves less
        in the first few ticks than the first-order lag (finite initial
        acceleration vs immediate velocity)."""
        formation, q0 = self._setup()
        d1 = sim.SimConfig(dynamics="firstorder")
        d2 = sim.SimConfig(dynamics="doubleint")
        s1, m1 = sim.rollout(sim.init_state(q0), formation, ControlGains(),
                             SafetyParams(), d1, 5)
        s2, m2 = sim.rollout(sim.init_state(q0), formation, ControlGains(),
                             SafetyParams(), d2, 5)
        moved1 = np.abs(np.asarray(s1.swarm.q) - np.asarray(q0)).sum()
        moved2 = np.abs(np.asarray(s2.swarm.q) - np.asarray(q0)).sum()
        assert moved2 < moved1
