"""jaxcheck tier-1: AST lint (layer 1) + trace audit (layer 2).

Three claims, per docs/STATIC_ANALYSIS.md:
- every rule JC001–JC005 FIRES on the known-bad fixtures
  (`tests/fixtures/jaxcheck/`), and the escape hatch suppresses;
- the linter reports ZERO violations on `aclswarm_tpu/` itself;
- every registered jitted entry point traces with no implicit host
  transfers, compiles nothing on a second identical call, and emits no
  f64 output leaves (n=5/B=2 grid in tier-1; the n=16/B=4 cross
  product under `-m slow`).
"""
from functools import partial
from pathlib import Path

import numpy as np
import pytest

import jax

from aclswarm_tpu.analysis import concurrency as concmod
from aclswarm_tpu.analysis import lint as lintmod
from aclswarm_tpu.analysis import trace_audit as ta

pytestmark = pytest.mark.analysis

FIXTURES = Path(__file__).parent / "fixtures" / "jaxcheck"
PACKAGE = Path(__file__).parents[1] / "aclswarm_tpu"


def _by_file(violations):
    out = {}
    for v in violations:
        out.setdefault(Path(v.path).name, []).append(v)
    return out


class TestLintFixtures:
    """Each rule fires on known-bad code — and only where expected."""

    @pytest.fixture(scope="class")
    def fired(self):
        return _by_file(lintmod.lint_paths([FIXTURES]))

    @pytest.mark.parametrize("fixture,rule,count", [
        ("bad_jc001.py", "JC001", 5),
        ("bad_jc002.py", "JC002", 3),
        ("bad_jc003.py", "JC003", 4),
        ("bad_jc004.py", "JC004", 3),
        ("bad_jc005.py", "JC005", 2),
        ("bad_jc006.py", "JC006", 3),
        ("bad_jc006_scenario.py", "JC006", 2),
    ])
    def test_rule_fires(self, fired, fixture, rule, count):
        vs = fired.get(fixture, [])
        assert [v.rule for v in vs] == [rule] * count, \
            f"{fixture}: expected {count}x{rule}, got {vs}"

    def test_fixture_lines_match_annotations(self, fired):
        """Every violation lands on a line whose comment names its rule —
        and every `# JCnnn` annotation in the fixtures is hit."""
        for fname, vs in fired.items():
            src = (FIXTURES / fname).read_text().splitlines()
            for v in vs:
                assert v.rule in src[v.line - 1], \
                    f"{fname}:{v.line} fired {v.rule} on an " \
                    f"unannotated line: {src[v.line - 1]!r}"

    def test_escape_hatch_suppresses(self, fired):
        assert "suppressed.py" not in fired

    def test_file_level_pragma_suppresses(self, fired):
        """`# jaxcheck: disable-file=JC001,JC004` silences those rules
        for the whole file (the fixture would otherwise fire both)."""
        assert "disable_file.py" not in fired

    def test_host_only_code_not_flagged(self, fired):
        """Reachability matters: host-side code using the same calls is
        legal (the `host_only` defs carry no annotation)."""
        for fname in ("bad_jc001.py", "bad_jc004.py"):
            src = (FIXTURES / fname).read_text().splitlines()
            for v in fired[fname]:
                assert "host_only" not in src[v.line - 1]


class TestConcurrencyFixtures:
    """The host-side concurrency tier (JC101-JC103) fires on known-bad
    code — and the entry-contract / suppression / CV-wait subtleties
    stay quiet where annotated clean."""

    @pytest.fixture(scope="class")
    def fired(self):
        return _by_file(concmod.check_paths(
            [FIXTURES / f for f in ("bad_jc101.py", "bad_jc102.py",
                                    "bad_jc103.py")]))

    @pytest.mark.parametrize("fixture,rule,count", [
        ("bad_jc101.py", "JC101", 3),
        ("bad_jc102.py", "JC102", 4),
        ("bad_jc103.py", "JC103", 8),
    ])
    def test_rule_fires(self, fired, fixture, rule, count):
        vs = fired.get(fixture, [])
        assert [v.rule for v in vs] == [rule] * count, \
            f"{fixture}: expected {count}x{rule}, got {vs}"

    def test_fixture_lines_match_annotations(self, fired):
        for fname, vs in fired.items():
            src = (FIXTURES / fname).read_text().splitlines()
            for v in vs:
                assert v.rule in src[v.line - 1], \
                    f"{fname}:{v.line} fired {v.rule} on an " \
                    f"unannotated line: {src[v.line - 1]!r}"

    def test_entry_contract_helper_clean(self, fired):
        """`_locked_helper` accesses a guarded field bare, but every
        call site holds the lock: the intersection propagation must
        keep it quiet."""
        src = (FIXTURES / "bad_jc101.py").read_text().splitlines()
        for v in fired["bad_jc101.py"]:
            assert "_locked_helper" not in src[v.line - 1]

    def test_suppression_dissolves_cycle(self, fired):
        """A JC102 pragma removes the EDGE: the partner nesting in
        `Suppressed.pq` must not keep reporting the waived cycle."""
        src = (FIXTURES / "bad_jc102.py").read_text().splitlines()
        flagged = {src[v.line - 1] for v in fired["bad_jc102.py"]}
        assert not any("partner edge waived" in s for s in flagged)

    def test_alias_and_queue_quiet_cases(self, fired):
        """The JC103 catalog extension must not overreach: a rebound
        alias and the non-blocking `q.get(block=False)` stay quiet."""
        src = (FIXTURES / "bad_jc103.py").read_text().splitlines()
        flagged = {src[v.line - 1] for v in fired["bad_jc103.py"]}
        assert not any("clean" in s for s in flagged), flagged

    def test_inferred_guard_reports_writes_only(self, fired):
        """The Tally class has no annotations: only the unlocked WRITE
        reports (line annotated `inferred guarded-by`)."""
        vs = [v for v in fired["bad_jc101.py"] if v.line > 40]
        src = (FIXTURES / "bad_jc101.py").read_text().splitlines()
        assert len(vs) == 1 and "inferred" in src[vs[0].line - 1]


class TestConcurrencyRepo:
    def test_host_dirs_are_clean(self):
        """The acceptance bar: zero unsuppressed JC101-JC103 across
        serve/, telemetry/, resilience/, interop/."""
        violations = concmod.check_paths(concmod.default_paths())
        assert violations == [], "\n".join(str(v) for v in violations)

    def test_cli_exit_codes(self, tmp_path):
        bad = tmp_path / "bad.py"
        bad.write_text(
            "import threading, time\n"
            "from aclswarm_tpu.utils.locks import OrderedLock\n"
            "class S:\n"
            "    def __init__(self):\n"
            "        self._lock = OrderedLock('serve.x')\n"
            "    def f(self):\n"
            "        with self._lock:\n"
            "            time.sleep(1)\n")
        assert concmod.main([str(bad)]) == 1
        assert lintmod.main(["--concurrency", str(PACKAGE / "serve")]) \
            == 0


class TestLintErgonomics:
    def test_one_report_per_site_across_call_paths(self, tmp_path):
        """A helper reachable from several jit roots (and via a nested
        def) reports each offending line ONCE — the (file, line, rule)
        dedupe plus the nested-def body exclusion."""
        f = tmp_path / "multipath.py"
        f.write_text(
            "import jax\n"
            "def helper(x):\n"
            "    return x.item()\n"
            "@jax.jit\n"
            "def root_a(x):\n"
            "    def inner(y):\n"
            "        return helper(y)\n"
            "    return inner(x)\n"
            "@jax.jit\n"
            "def root_b(x):\n"
            "    return helper(x)\n")
        vs = lintmod.lint_paths([f])
        assert [(v.line, v.rule) for v in vs] == [(3, "JC001")], vs

    def test_disable_file_all_rules(self, tmp_path):
        f = tmp_path / "vendored.py"
        f.write_text(
            "# jaxcheck: disable-file\n"
            "import jax\n"
            "@jax.jit\n"
            "def f(x):\n"
            "    return x.item()\n")
        assert lintmod.lint_paths([f]) == []

    def test_nested_def_defaults_still_scanned(self, tmp_path):
        """A nested def's decorators and argument DEFAULTS evaluate in
        the enclosing scope during its trace — skipping the nested body
        (the dedupe fix) must not silence violations that live there."""
        f = tmp_path / "nested_default.py"
        f.write_text(
            "import jax\n"
            "@jax.jit\n"
            "def root(x):\n"
            "    def inner(y=x.item()):\n"
            "        return y\n"
            "    return inner()\n")
        vs = lintmod.lint_paths([f])
        assert [(v.line, v.rule) for v in vs] == [(4, "JC001")], vs

    def test_jc006_keyword_operand_checked(self, tmp_path):
        """`jnp.sum(a=q)` (keyword-passed operand) must not escape the
        rule, and `jnp.sum(q, where=alive)` must pass it."""
        f = tmp_path / "kw.py"
        f.write_text(
            "# jaxcheck: fault-aware-file\n"
            "import jax.numpy as jnp\n"
            "def g(q, alive):\n"
            "    return jnp.sum(a=q)\n")
        assert [v.rule for v in lintmod.lint_paths([f])] == ["JC006"]

    def test_jc006_module_scope(self, tmp_path):
        """Without the fault-aware-file opt-in (and outside the scoped
        subpackages), JC006 stays silent even on mask-handling code."""
        f = tmp_path / "elsewhere.py"
        f.write_text(
            "import jax.numpy as jnp\n"
            "def g(q, alive):\n"
            "    return jnp.mean(q)\n")
        assert lintmod.lint_paths([f]) == []
        f2 = tmp_path / "opted_in.py"
        f2.write_text(
            "# jaxcheck: fault-aware-file\n"
            "import jax.numpy as jnp\n"
            "def g(q, alive):\n"
            "    return jnp.mean(q)\n")
        assert [v.rule for v in lintmod.lint_paths([f2])] == ["JC006"]


class TestLintRepo:
    def test_package_is_clean(self):
        """The acceptance bar: zero violations across aclswarm_tpu/."""
        violations = lintmod.lint_paths([PACKAGE])
        assert violations == [], "\n".join(str(v) for v in violations)

    def test_cli_exit_codes(self, tmp_path):
        bad = tmp_path / "bad.py"
        bad.write_text(
            "import jax\n@jax.jit\ndef f(x):\n    return x.item()\n")
        assert lintmod.main([str(bad)]) == 1
        assert lintmod.main([str(PACKAGE)]) == 0


# The heaviest whole-rollout audit entries (batched summaries and the
# scenario-general variants, ~10-15 s each) additionally carry `slow`
# to respect the tier-1 duration guard; their HLO digests stay covered
# every tier-1 run by TestZeroCostOff against the committed baseline,
# and `scripts/check.sh` runs the full audit.
_HEAVY_AUDIT_ENTRIES = {
    "sim.summary.batched_rollout_summary[scenario]",
    "sim.engine.batched_rollout[scenario]",
    "sim.summary.batched_rollout_summary[checked]",
    "sim.summary.batched_rollout_summary[telemetry]",
    "sim.summary.batched_rollout_summary",
}


class TestTraceAudit:
    """Layer 2 on the tier-1 grid (n=5, B=2, all three solvers, faults
    on/off, truth + flooded localization)."""

    @pytest.mark.parametrize(
        "entry",
        [pytest.param(e, marks=pytest.mark.slow, id=e.name)
         if e.name in _HEAVY_AUDIT_ENTRIES else pytest.param(e, id=e.name)
         for e in ta.ENTRY_POINTS])
    def test_entry_clean(self, entry):
        seen = set()
        reports = []
        for gp in ta.iter_grid():
            key = tuple(getattr(gp, a) for a in entry.axes)
            if key in seen:
                continue
            seen.add(key)
            try:
                reports.append(ta.audit_entry(entry, gp))
            except ta.Skip:
                continue
        assert reports, f"{entry.name}: no grid point ran"
        for r in reports:
            assert not r.recompiled, \
                f"{r.name} {r.grid}: second identical call compiled " \
                f"again (cache entries: {r.n_compiles})"
            assert not r.f64_leaves, \
                f"{r.name} {r.grid}: f64 leaves {r.f64_leaves} " \
                f"in output avals {r.out_dtypes}"

    @pytest.mark.slow
    def test_full_grid(self):
        bad = [r for r in ta.audit_all(slow=True) if not r.ok]
        assert bad == [], bad


class TestZeroCostOff:
    """The swarmcheck guarantee: `check_mode='off'` lowers every
    registered entry point to HLO bit-identical to the committed
    pre-swarmcheck baseline (`analysis/hlo_baseline.json`)."""

    def test_off_mode_matches_baseline(self):
        z = ta.verify_zero_cost_off()
        if z["skipped"]:
            pytest.skip(z["skipped"])
        assert z["checked"] > 0
        assert z["mismatches"] == [], \
            "check_mode=off no longer lowers to the pre-swarmcheck " \
            f"HLO: {z['mismatches']} (if the compiled surface changed " \
            "INTENTIONALLY, regenerate with `python -m " \
            "aclswarm_tpu.analysis.trace_audit --write-hlo-baseline` " \
            "and commit the diff)"
        assert z["uncovered"] == [], \
            f"baseline digests with no producing entry: {z['uncovered']}"
        assert z["unverified"] == [], \
            "baseline entries with no committed digest (a new entry " \
            "point is not proven zero-cost until --write-hlo-baseline " \
            f"runs): {z['unverified']}"

    def test_skipped_builder_surfaces_as_uncovered(self, monkeypatch,
                                                   tmp_path):
        """A committed digest whose builder now raises Skip must land in
        `uncovered` (loud), not silently drop out of the proof."""
        import json
        base = {"jax_version": jax.__version__,
                "backend": jax.default_backend(),
                "digests": {"fake.entry|n=5": "0" * 64}}
        p = tmp_path / "hlo_baseline.json"
        p.write_text(json.dumps(base))

        def skipper(gp):
            raise ta.Skip("unsupported combo")

        fake = ta.EntryPoint(name="fake.entry", fn=lambda x: x,
                             static_argnames=(), build=skipper,
                             axes=("n",))
        # a second (buildable) entry with NO committed digest: must
        # surface as unverified, not silently pass — while a Skip-only
        # cell with no digest stays silent (the capture legitimately
        # skipped it too)
        fresh = ta.EntryPoint(
            name="fresh.entry", fn=lambda x: x, static_argnames=(),
            build=lambda gp: ((np.zeros((2,), np.float32),), {}),
            axes=("n",))
        monkeypatch.setattr(ta, "HLO_BASELINE_PATH", p)
        monkeypatch.setattr(ta, "ENTRY_POINTS", [fake, fresh])
        z = ta.verify_zero_cost_off()
        assert z["skipped"] is None
        assert z["uncovered"] == ["fake.entry|n=5"]
        assert z["unverified"] == ["fresh.entry|n=5"]

    def test_checked_mode_differs_from_baseline(self):
        """Teeth: the sanitizer-on program must NOT equal the baseline
        program — if it did, the off-mode proof would prove nothing."""
        on = next(e for e in ta.ENTRY_POINTS
                  if e.name == "sim.engine.rollout[checked]")
        off = next(e for e in ta.ENTRY_POINTS
                   if e.name == "sim.engine.rollout")
        gp = next(iter(ta.iter_grid()))
        assert ta.hlo_digest(on, gp) != ta.hlo_digest(off, gp)


class TestWeakTypeRegression:
    """Satellite of the JC003 sweep: `init_state` now pins a strong
    canonical dtype, so list / int / f32-array callers all produce the
    SAME avals and the rollout never retraces (the silent-recompile
    defect the dtype-less `jnp.asarray(q0)` used to cause)."""

    Q = [[0.0, 0.0, 2.0], [2.0, 0.0, 2.0], [0.0, 2.0, 2.0],
         [2.0, 2.0, 2.0], [1.0, 1.0, 2.0]]

    def _states(self):
        from aclswarm_tpu import sim
        return [
            sim.init_state(self.Q),                              # list
            sim.init_state(np.asarray(self.Q, np.float32)),      # f32
            sim.init_state([[int(x) for x in row]
                            for row in self.Q]),                 # int list
        ]

    def test_identical_avals(self):
        with ta.f32_mode():
            trees = [jax.tree.map(
                lambda x: None if x is None else (x.shape, str(x.dtype)),
                s, is_leaf=lambda x: x is None) for s in self._states()]
        assert trees[0] == trees[1] == trees[2]

    def test_rollout_traces_once(self):
        """Trace twice with differently-sourced (but equal) states:
        zero recompiles."""
        from aclswarm_tpu.sim import engine
        with ta.f32_mode():
            states = self._states()
            cfg = ta._sim_cfg(ta.GridPoint())
            form = ta._formation(len(self.Q))
            from aclswarm_tpu.core.types import ControlGains
            w = jax.jit(partial(engine.rollout.__wrapped__),
                        static_argnames=("n_ticks", "cfg"))
            for s in states:
                w(s, form, ControlGains(), ta._sparams(),
                  cfg=cfg, n_ticks=2)
            assert w._cache_size() == 1

    def test_localization_table_dtype(self):
        from aclswarm_tpu.sim import localization as loc
        with ta.f32_mode():
            t1 = loc.init_table(self.Q)
            t2 = loc.init_table(np.asarray(self.Q, np.float32))
        assert t1.est.dtype == t2.est.dtype == np.float32
        assert t1.age.dtype == np.int32
