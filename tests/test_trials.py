"""Trial harness tests: random formation generator, config layering, the
full trial FSM, and end-to-end Monte-Carlo trials.

Specs: `aclswarm_sim/nodes/generate_random_formation.py` (formgen),
`aclswarm_sim/nodes/supervisor.py` (FSM), `trials.sh`/`trial.sh` (driver),
`analyze_simtrials.m` (analysis), SURVEY.md §5.6 (config layers).
"""
import dataclasses

import numpy as np
import pytest

from aclswarm_tpu.core import config as configlib
from aclswarm_tpu.harness import formgen, supervisor, trials
from aclswarm_tpu.harness.supervisor import TrialFSM, TrialState


# ---------------------------------------------------------------- formgen

def test_formgen_spacing_seed_and_format():
    group = formgen.generate_group(10, seed=42, l=15, w=15, h=2)
    assert group["agents"] == 10
    assert len(group["formations"]) == 2
    for f in group["formations"]:
        pts = np.asarray(f["points"])
        assert pts.shape == (10, 3)
        # box bounds (generate_random_formation.py:20-24)
        assert np.all(np.abs(pts[:, 0]) <= 7.5)
        assert np.all((pts[:, 2] >= 0) & (pts[:, 2] <= 2))
        # cylinder non-overlap: pairwise xy distance >= min_dist
        d = np.linalg.norm(pts[:, None, :2] - pts[None, :, :2], axis=-1)
        d[np.eye(10, dtype=bool)] = np.inf
        assert d.min() >= 2.0
    # determinism + seed sensitivity
    again = formgen.generate_group(10, seed=42, l=15, w=15, h=2)
    assert group == again
    other = formgen.generate_group(10, seed=43, l=15, w=15, h=2)
    assert group != other


def test_formgen_adjmat_rules():
    rng = np.random.default_rng(0)
    # n < 5 is always fully connected (generate_random_formation.py:118-120)
    A = formgen.random_adjmat(rng, 4, fc=False)
    assert np.array_equal(A, np.ones((4, 4)) - np.eye(4))
    # sparse removals: symmetric, zero diagonal, at most n-4 edges removed
    for _ in range(20):
        n = 10
        A = formgen.random_adjmat(rng, n, fc=False)
        assert np.array_equal(A, A.T)
        assert np.all(np.diag(A) == 0)
        removed = (n * (n - 1)) // 2 - int(A.sum()) // 2
        assert 0 <= removed <= n - 4


def test_formgen_graphs_stay_rigid():
    """The <= n-4 removal rule keeps generic 2D rigidity — check with the
    rigidity-matrix rank on the sampled (generic) points."""
    for seed in range(8):
        specs = formgen.generate_specs(12, seed=seed)
        for s in specs:
            assert formgen.is_rigid_2d(s.points, s.adjmat), seed


def test_rigidity_check_detects_flexible_graph():
    rng = np.random.default_rng(1)
    pts = rng.normal(size=(6, 3))
    # a path graph is flexible
    A = np.zeros((6, 6))
    for i in range(5):
        A[i, i + 1] = A[i + 1, i] = 1
    assert not formgen.is_rigid_2d(pts, A)
    # the complete graph is rigid
    assert formgen.is_rigid_2d(pts, np.ones((6, 6)) - np.eye(6))


# ----------------------------------------------------------------- config

def test_config_layering(tmp_path):
    p = tmp_path / "trial.yaml"
    p.write_text("formation: simform8\ntrials: 5\ntau: 0.2\n")
    cfg = configlib.load_layers(trials.TrialConfig, file=p,
                                overrides=["trials=7", "seed=3",
                                           "colavoid_neighbors=none"])
    assert cfg.formation == "simform8"   # file beats default
    assert cfg.trials == 7               # cli beats file
    assert cfg.tau == 0.2
    assert cfg.seed == 3
    assert cfg.colavoid_neighbors is None
    # defaults fill the rest
    assert cfg.assignment == "auction"
    with pytest.raises(KeyError):
        configlib.load_layers(trials.TrialConfig, overrides=["nope=1"])


def test_scale_knobs_thread_through(monkeypatch):
    """The simform1000 scale knobs (velocity caps, trial budget, scale
    deadbands — all reference launch-file parameters) must reach the
    SafetyParams / TrialFSM / ControlGains actually used by the trial."""
    captured = {}
    import aclswarm_tpu.sim as sim

    real_rollout = sim.rollout

    def spy_rollout(state, formation, cgains, sparams, cfg, n, inputs=None):
        captured["cgains"] = cgains
        captured["sparams"] = sparams
        captured["formation"] = formation
        return real_rollout(state, formation, cgains, sparams, cfg, n,
                            inputs)

    monkeypatch.setattr(sim, "rollout", spy_rollout)
    cfg = trials.TrialConfig(formation="swarm4", trials=1, seed=1,
                             max_vel_xy=2.0, max_vel_z=1.0,
                             trial_timeout=30.0, e_xy_thr=1.0, e_z_thr=0.3,
                             kd=0.001, gain_scale=0.5,
                             verbose=False, out="/dev/null")
    fsm = trials.run_trial(cfg, 0)
    assert fsm.trial_timeout == 30.0
    assert float(captured["sparams"].max_vel_xy) == 2.0
    assert float(captured["sparams"].max_vel_z) == 1.0
    assert float(captured["cgains"].e_xy_thr) == 1.0
    assert float(captured["cgains"].e_z_thr) == 0.3
    assert float(captured["cgains"].kd) == 0.001
    # gain_scale multiplies the designed/library gains on dispatch (the
    # captured formation is whichever the trial last flew)
    from aclswarm_tpu.harness import formations as formlib
    got = np.asarray(captured["formation"].gains)
    cands = [0.5 * np.asarray(trials._gains_for(s)).reshape(
        4, 3, 4, 3).transpose(0, 2, 1, 3)
        for s in formlib.load_group(None, "swarm4")]
    assert any(np.allclose(got, c, rtol=1e-6) for c in cands)
    # 30 s budget: the 2-formation swarm4 cycle cannot finish -> TERMINATE
    assert fsm.done


def test_config_roundtrip_yaml(tmp_path):
    cfg = trials.TrialConfig(formation="simform6", trials=2, seed=9)
    out = tmp_path / "resolved.yaml"
    configlib.to_yaml(cfg, out)
    cfg2 = configlib.load_layers(trials.TrialConfig, file=out)
    assert cfg2 == cfg


# ---------------------------------------------------------------- TrialFSM

def _tick_n(fsm, k, q, dn, ca, ev=False):
    acts = []
    for _ in range(k):
        acts.append(fsm.step(q, dn, ca, ev))
        ev = False
    return acts


def test_trial_fsm_happy_path():
    """IDLE -> TAKING_OFF -> HOVERING -> WAITING -> FLYING -> IN_FORMATION
    -> HOVERING -> ... -> COMPLETE with reference timing semantics."""
    n, dt = 3, 0.1
    fsm = TrialFSM(n, n_formations=1, takeoff_alt=1.0, dt=dt)
    ground = np.zeros((n, 3))
    air = np.array([[0, 0, 1.0]] * n)
    quiet = np.zeros(n)
    no_ca = np.zeros(n, bool)

    assert fsm.step(ground, quiet, no_ca, False) == "takeoff"
    assert fsm.state == TrialState.TAKING_OFF
    # not at altitude yet
    _tick_n(fsm, 5, ground, quiet, no_ca)
    assert fsm.state == TrialState.TAKING_OFF
    fsm.step(air, quiet, no_ca, False)
    assert fsm.state == TrialState.HOVERING
    # HOVER_WAIT (5 s) then dispatch formation 0
    acts = _tick_n(fsm, int(5 / dt) + 1, air, quiet, no_ca)
    assert acts[-1] == "dispatch"
    assert fsm.curr_formation_idx == 0
    assert fsm.state == TrialState.WAITING_ON_ASSIGNMENT
    # assignment event -> FLYING, logging starts
    fsm.step(air, quiet, no_ca, True)
    assert fsm.state == TrialState.FLYING
    assert fsm.is_logging and fsm.assignments == [1]
    # 1 s formation wait + 1 s convergence buffer -> IN_FORMATION
    _tick_n(fsm, int(2 / dt) + 2, air, quiet, no_ca)
    assert fsm.state == TrialState.IN_FORMATION
    # CONVERGED_WAIT -> back to HOVERING, logging stopped
    _tick_n(fsm, int(1 / dt) + 1, air, quiet, no_ca)
    assert fsm.state == TrialState.HOVERING
    assert not fsm.is_logging
    assert len(fsm.times) == 1 and fsm.times[0] > 0
    # all formations done -> COMPLETE after hover wait
    _tick_n(fsm, int(5 / dt) + 1, air, quiet, no_ca)
    assert fsm.completed
    row = fsm.csv_row(0)
    assert len(row) == 1 + n + 3 * 1


def test_trial_fsm_assignment_timeout():
    n, dt = 3, 0.1
    fsm = TrialFSM(n, 1, takeoff_alt=1.0, dt=dt)
    air = np.array([[0, 0, 1.0]] * n)
    quiet = np.zeros(n)
    no_ca = np.zeros(n, bool)
    fsm.step(np.zeros((n, 3)), quiet, no_ca, False)       # takeoff
    fsm.step(air, quiet, no_ca, False)                    # -> HOVERING
    _tick_n(fsm, int(5 / dt) + 1, air, quiet, no_ca)      # -> WAITING
    assert fsm.state == TrialState.WAITING_ON_ASSIGNMENT
    # no assignment ever arrives -> TERMINATE after 20 s
    _tick_n(fsm, int(supervisor.ASSIGNMENT_TIMEOUT / dt) + 2,
            air, quiet, no_ca)
    assert fsm.state == TrialState.TERMINATE


def test_trial_fsm_gridlock_episode_logged():
    n, dt = 2, 0.1
    fsm = TrialFSM(n, 1, takeoff_alt=1.0, dt=dt)
    air = np.array([[0, 0, 1.0]] * n)
    quiet = np.zeros(n)
    loud = np.full(n, 5.0)
    no_ca = np.zeros(n, bool)
    all_ca = np.ones(n, bool)
    fsm.step(np.zeros((n, 3)), quiet, no_ca, False)
    fsm.step(air, quiet, no_ca, False)
    _tick_n(fsm, int(5 / dt) + 1, air, quiet, no_ca)
    fsm.step(air, quiet, no_ca, True)                     # -> FLYING
    # not converged + full CA buffer -> GRIDLOCK
    _tick_n(fsm, int(2 / dt) + 2, air, loud, all_ca)
    assert fsm.state == TrialState.GRIDLOCK
    # leave gridlock (buffer must refill with quiet CA), then converge
    _tick_n(fsm, int(1 / dt) + 1, air, quiet, no_ca)
    assert fsm.state == TrialState.FLYING
    _tick_n(fsm, int(2 / dt) + 2, air, quiet, no_ca)
    assert fsm.state == TrialState.IN_FORMATION
    _tick_n(fsm, int(1 / dt) + 1, air, quiet, no_ca)
    _tick_n(fsm, int(5 / dt) + 1, air, quiet, no_ca)
    assert fsm.completed
    # the gridlock episode duration landed in time_avoidance
    assert fsm.time_avoidance[0] > 0


# ------------------------------------------------------------- end-to-end

@pytest.mark.slow
def test_monte_carlo_simform_trial(tmp_path):
    """Seeded simformN trial completes, writes the reference CSV schema,
    and the analysis reduces it (`analyze_simtrials.m:38-59`)."""
    out = tmp_path / "mc.csv"
    cfg = trials.TrialConfig(formation="simform8", trials=2, seed=1,
                             out=str(out), verbose=False)
    stats = trials.run_trials(cfg)
    assert stats["trials_completed"] == 2
    assert stats["completion_pct"] == 100.0
    data = np.loadtxt(out, delimiter=",", ndmin=2)
    n, f = 8, 2
    assert data.shape == (2, 1 + n + 3 * f)
    # trial numbers, positive convergence times, assignment counts >= 1
    assert list(data[:, 0]) == [0.0, 1.0]
    assert np.all(data[:, 1 + n:1 + n + f] > 0)
    assert np.all(data[:, 1 + n + 2 * f:] >= 1)
    # determinism: same seed -> identical trial outcome
    out2 = tmp_path / "mc2.csv"
    cfg2 = dataclasses.replace(cfg, out=str(out2), trials=1)
    trials.run_trials(cfg2)
    data2 = np.loadtxt(out2, delimiter=",", ndmin=2)
    np.testing.assert_allclose(data2[0], data[0], rtol=1e-12)


def test_trials_cli(tmp_path):
    out = tmp_path / "cli.csv"
    rc = trials.main(["-f", "simform6", "-m", "1", "-s", "2",
                      "-o", str(out), "--set", "verbose=false"])
    assert rc == 0
    assert out.exists()
    # analysis entry point over the written file
    rc = trials.main(["--analyze", str(out), "-n", "6", "-m", "1"])
    assert rc == 0


def test_library_group_trial_runs(tmp_path):
    """A library-group trial (swarm4, precalc'd gains, complete graph) runs
    the full lifecycle through the driver."""
    out = tmp_path / "sw4.csv"
    cfg = trials.TrialConfig(formation="swarm4", trials=1, seed=3,
                             out=str(out), verbose=False)
    stats = trials.run_trials(cfg)
    assert stats["trials_completed"] == 1


def test_sparse_library_group_trial_runs(tmp_path):
    """The shipped library's sparse-adjacency groups (swarm6_sparse: ring +
    chords, 2n-3 edges) fly the full trial lifecycle — the non-complete
    graph path exercised by the *shipped* library, not only by tests
    reading the reference's yaml (round-1 review weak #7)."""
    out = tmp_path / "sw6s.csv"
    cfg = trials.TrialConfig(formation="swarm6_sparse", trials=1, seed=3,
                             out=str(out), verbose=False)
    stats = trials.run_trials(cfg)
    assert stats["trials_completed"] == 1
    # sanity: the group really is sparse
    from aclswarm_tpu.harness import formations as formlib
    specs = formlib.load_group(None, "swarm6_sparse")
    adj = np.asarray(specs[0].adjmat)
    assert adj.sum() / 2 == 2 * 6 - 3


def test_swarm15_group_trial_runs(tmp_path):
    """The swarm15 group (parity with the reference's largest committed
    group, mitacl15: 3 formations over a 33-edge sparse graph, precalc'd
    gains) flies its full 3-formation cycle."""
    out = tmp_path / "sw15.csv"
    cfg = trials.TrialConfig(formation="swarm15", trials=1, seed=2,
                             out=str(out), verbose=False)
    stats = trials.run_trials(cfg)
    assert stats["trials_completed"] == 1
    assert stats["formations_per_trial"] == 3
    from aclswarm_tpu.harness import formations as formlib
    specs = formlib.load_group(None, "swarm15")
    assert len(specs) == 3 and specs[0].n == 15
    assert all(s.gains is not None for s in specs)   # precalc'd
    adj = np.asarray(specs[0].adjmat)
    assert adj.sum() / 2 == 33
    for s in specs:
        assert formgen.is_rigid_2d(s.points, s.adjmat)


def test_swarm100_scale_group_loads_and_solves():
    """The 100-agent scale group (`mitacl100.m` analogue) ships no gains;
    the dispatch path designs them on device and they validate."""
    from aclswarm_tpu import gains as gainslib
    from aclswarm_tpu.harness import formations as formlib
    specs = formlib.load_group(None, "swarm100")
    assert len(specs) == 2
    for spec in specs:
        assert spec.n == 100
        assert spec.gains is None
    A = np.asarray(gainslib.solve_gains(specs[0].points, specs[0].adjmat))
    v = gainslib.validate_gains(A, np.asarray(specs[0].points), tol=1e-4)
    assert v["no_positive"] and v["kernel_ok"]


def test_flagship_swarm6_3d_trial_completes(tmp_path):
    """The flagship demo group (BASELINE.md config #1) completes under the
    honest second-order dynamics — since round 4 this is the reference's
    exact demo cycle (Pentagonal Pyramid / Triangular Prism / Slanted
    Plane) on its SPARSE per-formation graphs. Also the load-time
    feasibility regression ground: round 2's gridlocked-Octahedron
    failure mode (stacked xy columns) is now rejected at library load."""
    out = tmp_path / "sw6.csv"
    cfg = trials.TrialConfig(formation="swarm6_3d", trials=2, seed=1,
                             dynamics="doubleint", out=str(out),
                             verbose=False)
    stats = trials.run_trials(cfg)
    assert stats["completion_pct"] == 100.0
    data = np.loadtxt(out, delimiter=",", ndmin=2)
    # [trial, dist x 6, (time, time_avoidance, assignments) x 3 formations]
    assert data.shape == (2, 1 + 6 + 3 * 3)


def test_shipped_library_formations_are_feasible():
    """Every shipped formation keeps min planar point separation above
    r_keep_out — the reachability precondition of the planar-cylinder
    avoidance model (all reference demo formations satisfy >= 1.5)."""
    import yaml
    from aclswarm_tpu.core.types import SafetyParams
    from aclswarm_tpu.harness import formations as formlib
    r = float(SafetyParams().r_keep_out)
    lib = yaml.safe_load(open(formlib.DEFAULT_LIBRARY))
    groups = [k for k, v in lib.items() if isinstance(v, dict)]
    assert groups
    for g in groups:
        for spec in formlib.load_group(None, g):
            sep = formlib.min_planar_separation(spec.points)
            assert sep > r, (g, spec.name, sep)


def test_infeasible_formation_rejected():
    """The driver refuses a formation planar avoidance can never reach."""
    from aclswarm_tpu.harness import formations as formlib
    stacked = formlib.FormationSpec(
        name="stack", points=np.array([[0.0, 0, 0], [0, 0, 2], [3, 0, 0]]),
        adjmat=np.ones((3, 3)) - np.eye(3), gains=None)
    with pytest.raises(ValueError, match="permanent mutual collision"):
        formlib.check_feasible(stacked, 1.2)


def test_flooded_localization_trial_completes(tmp_path):
    """Driver-level end-to-end with the real information model: CBAA
    assignment consuming flooded localization estimates, full lifecycle
    through takeoff and formation cycling."""
    out = tmp_path / "flood.csv"
    # seed 5: seed 3 gridlocks under CBAA on this group (identically in
    # truth and flooded modes — the information model does not cause it)
    cfg = trials.TrialConfig(formation="swarm6_sparse", trials=1, seed=5,
                             assignment="cbaa", localization="flooded",
                             out=str(out), verbose=False)
    stats = trials.run_trials(cfg)
    assert stats["trials_completed"] == 1


def test_admm_carry_payload_roundtrips_codec(tmp_path):
    """The dispatch carry crosses the trials checkpoint as codec-plain
    numpy (`_carry_payload`/`_carry_restore`): bit-exact round-trip
    through the resilience checkpoint file, None staying None (a trial
    that never dispatched), and the restored carry re-seeding
    `solve_gains` bitwise-identically to the original."""
    import jax.numpy as jnp

    from aclswarm_tpu import gains as gainslib
    from aclswarm_tpu.resilience import checkpoint as ckptlib

    rng = np.random.default_rng(2)
    n = 8
    pts = rng.normal(size=(n, 3)) * 4
    adj = np.ones((n, n)) - np.eye(n)
    carry0 = gainslib.init_carry(n, gainslib.planar_of(pts))
    g, carry = gainslib.solve_gains(pts, adj, carry=carry0)

    assert trials._carry_payload(None) is None
    assert trials._carry_restore(None) is None
    payload = {"admm_carry": trials._carry_payload(carry),
               "none_carry": trials._carry_payload(None)}
    path = ckptlib.write_checkpoint(
        tmp_path, "t", payload, ckptlib.make_manifest("t", "h", chunk=0))
    loaded, _ = ckptlib.load_checkpoint(path)
    assert loaded["none_carry"] is None
    back = trials._carry_restore(loaded["admm_carry"])
    for a, b in zip(back, carry):
        assert np.array_equal(np.asarray(a), np.asarray(b))
    # the restored carry seeds the next dispatch bitwise like the live one
    g_live, _ = gainslib.solve_gains(pts, adj, carry=carry)
    g_back, _ = gainslib.solve_gains(pts, adj, carry=back)
    assert np.array_equal(np.asarray(g_live), np.asarray(g_back))


def test_cbaa_tables_roundtrip_codec(tmp_path):
    """`CbaaTables` (the engine's cross-auction warm state) round-trips
    the checkpoint codec bit-exactly — it rides `SimState` through
    resilience saves and serve preemption exactly like FaultSchedule."""
    import jax.numpy as jnp

    from aclswarm_tpu.assignment import cbaa
    from aclswarm_tpu.resilience import checkpoint as ckptlib

    tab = cbaa.CbaaTables(
        price=jnp.asarray(np.random.default_rng(4).random((6, 6))),
        who=jnp.asarray(np.arange(36, dtype=np.int32).reshape(6, 6) % 6))
    payload = {k: np.asarray(v) for k, v in tab._asdict().items()}
    path = ckptlib.write_checkpoint(
        tmp_path, "t", payload, ckptlib.make_manifest("t", "h", chunk=1))
    loaded, _ = ckptlib.load_checkpoint(path)
    back = cbaa.CbaaTables(**{k: jnp.asarray(v)
                              for k, v in loaded.items()})
    for a, b in zip(back, tab):
        assert np.array_equal(np.asarray(a), np.asarray(b))
        assert np.asarray(a).dtype == np.asarray(b).dtype
