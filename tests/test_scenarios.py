"""swarmscenario tests (`aclswarm_tpu.scenarios`; docs/SCENARIOS.md).

Pins the subsystem's contracts:

1. **No-scenario parity**: a rollout carrying `no_scenario(n)` is
   BIT-IDENTICAL to one carrying ``scenario=None`` — serial, batched,
   flooded, composed with a FaultSchedule, and resumed from a
   checkpoint codec round trip (every axis application is a `where`
   whose inert case is the pass-through operand).
2. **Axis semantics**: obstacles cast sectors only while active, wind
   displaces (but never thaws a dead vehicle), sensor noise perturbs
   only the flooded estimates, sequence stages and drift move the
   effective formation, byzantine corruption changes assignments while
   every output stays a permutation, and the re-matching cadence
   throttles accepted auctions.
3. **One compiled program**: heterogeneous scenarios across a batch
   match their serial runs bit for bit.
4. **Registry + fuzzer + serve**: families sample deterministically and
   validate at the door; a quick-seed fuzz subset runs with the
   swarmcheck oracle on (full >= 50-composition sweep marked slow);
   scenario requests flow end-to-end through swarmserve and postmortem
   reconstruction.
"""
import sys
from pathlib import Path

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from aclswarm_tpu import faults, scenarios as scn, sim
from aclswarm_tpu.core.types import (ControlGains, SafetyParams,
                                     make_formation)
from aclswarm_tpu.scenarios import timeline as tl
from aclswarm_tpu.sim import summary as sumlib

pytestmark = pytest.mark.scenario

METRIC_FIELDS = ("distcmd_norm", "ca_active", "assign_valid", "reassigned",
                 "auctioned", "q", "mode", "v2f")

N, T = 6, 130
ASSIGN_EVERY = 60


def _problem(B=1, n=N, seed=0, localization=False, scenarios=None,
             scheds=None):
    rng = np.random.default_rng(seed)
    adj = np.ones((n, n)) - np.eye(n)
    forms, states = [], []
    for b in range(B):
        pts = rng.normal(size=(n, 3)) * 5
        gains = rng.normal(size=(n, n, 3, 3)) * 0.01
        forms.append(make_formation(jnp.asarray(pts), jnp.asarray(adj),
                                    jnp.asarray(gains)))
        states.append(sim.init_state(
            rng.normal(size=(n, 3)) * 5 + np.array([0, 0, 2.0]),
            localization=localization,
            faults=None if scheds is None else scheds[b],
            scenario=None if scenarios is None else scenarios[b]))
    sp = SafetyParams(bounds_min=jnp.asarray([-50.0, -50.0, 0.0]),
                      bounds_max=jnp.asarray([50.0, 50.0, 20.0]))
    return states, forms, sp


def _stack(xs):
    return jax.tree.map(lambda *ls: jnp.stack(ls), *xs)


def _cfg(**kw):
    kw.setdefault("assignment", "auction")
    kw.setdefault("assign_every", ASSIGN_EVERY)
    return sim.SimConfig(**kw)


def _assert_rollouts_equal(m1, m2, f1, f2):
    for name in METRIC_FIELDS:
        np.testing.assert_array_equal(np.asarray(getattr(m1, name)),
                                      np.asarray(getattr(m2, name)), name)
    np.testing.assert_array_equal(np.asarray(f1.swarm.q),
                                  np.asarray(f2.swarm.q))
    np.testing.assert_array_equal(np.asarray(f1.swarm.vel),
                                  np.asarray(f2.swarm.vel))
    np.testing.assert_array_equal(np.asarray(f1.v2f), np.asarray(f2.v2f))


def _dt():
    return jnp.result_type(float)


# --------------------------------------------------------------------------
# 1. no_scenario == scenario=None, bit for bit
# --------------------------------------------------------------------------

@pytest.mark.parametrize("assignment", ["auction", "sinkhorn", "cbaa"])
def test_no_scenario_bit_parity_serial(assignment):
    states, forms, sp = _problem(seed=1)
    cfg = _cfg(assignment=assignment, flight_fsm=True)
    nos = scn.no_scenario(N, dtype=_dt())
    f1, m1 = sim.rollout(states[0], forms[0], ControlGains(), sp, cfg, T)
    f2, m2 = sim.rollout(states[0].replace(scenario=nos), forms[0],
                         ControlGains(), sp, cfg, T)
    _assert_rollouts_equal(m1, m2, f1, f2)
    assert m1.scen_event is None
    assert not np.asarray(m2.scen_event).any()


def test_no_scenario_bit_parity_flooded_with_faults():
    """Composed with the fault subsystem under the flooded information
    model: estimate tables bit-identical too."""
    scheds = [faults.sample_schedule(7, N, dropout_frac=0.3, drop_tick=30,
                                     rejoin_tick=90, link_loss=0.2)]
    states, forms, sp = _problem(seed=2, localization=True, scheds=scheds)
    cfg = _cfg(assignment="cbaa", localization="flooded", flight_fsm=True)
    nos = scn.no_scenario(N, dtype=_dt())
    f1, m1 = sim.rollout(states[0], forms[0], ControlGains(), sp, cfg, T)
    f2, m2 = sim.rollout(states[0].replace(scenario=nos), forms[0],
                         ControlGains(), sp, cfg, T)
    _assert_rollouts_equal(m1, m2, f1, f2)
    np.testing.assert_array_equal(np.asarray(m1.alive),
                                  np.asarray(m2.alive))
    np.testing.assert_array_equal(np.asarray(f1.loc.est),
                                  np.asarray(f2.loc.est))


def test_no_scenario_bit_parity_batched():
    B = 3
    states, forms, sp = _problem(B, seed=3)
    cfg = _cfg()
    bstate, bform = _stack(states), _stack(forms)
    nos = [scn.no_scenario(N, dtype=_dt()) for _ in range(B)]
    bstate_nos = jax.tree.map(jnp.copy, bstate).replace(
        scenario=_stack(nos))
    bf1, bm1 = sim.batched_rollout(bstate, bform, ControlGains(), sp,
                                   cfg, T)
    bf2, bm2 = sim.batched_rollout(bstate_nos, bform, ControlGains(), sp,
                                   cfg, T)
    _assert_rollouts_equal(bm1, bm2, bf1, bf2)


def test_no_scenario_bit_parity_resumed_from_checkpoint():
    """Chunked + codec round trip mid-run: chunk 1 -> checkpoint ->
    restore -> chunk 2 equals the uninterrupted scenario=None run."""
    from aclswarm_tpu.resilience import checkpoint as ckptlib

    states, forms, sp = _problem(seed=4)
    cfg = _cfg()
    half = T - T // 2
    f_ref, m_ref = sim.rollout(states[0], forms[0], ControlGains(), sp,
                               cfg, T)
    nos = scn.no_scenario(N, dtype=_dt())
    mid, _ = sim.rollout(states[0].replace(scenario=nos), forms[0],
                         ControlGains(), sp, cfg, T // 2)
    blob = ckptlib.dumps({"state": ckptlib.tree_arrays(mid)},
                         ckptlib.make_manifest("test", "h", chunk=1))
    payload, _ = ckptlib.loads(blob, "<mem>")
    template = states[0].replace(scenario=nos)
    restored = ckptlib.restore_tree(template, payload["state"],
                                    what="SimState")
    f2, m2 = sim.rollout(restored, forms[0], ControlGains(), sp, cfg,
                         half)
    np.testing.assert_array_equal(np.asarray(f_ref.swarm.q),
                                  np.asarray(f2.swarm.q))
    np.testing.assert_array_equal(np.asarray(f_ref.v2f),
                                  np.asarray(f2.v2f))
    for name in ("q", "v2f"):
        np.testing.assert_array_equal(
            np.asarray(getattr(m_ref, name))[T // 2:],
            np.asarray(getattr(m2, name)), name)


# --------------------------------------------------------------------------
# 2. heterogeneous batched scenarios == serial
# --------------------------------------------------------------------------

@pytest.mark.slow
def test_heterogeneous_scenarios_batched_matches_serial():
    """The tentpole claim one axis up from faults: trials carrying
    DIFFERENT scenario compositions run in ONE compiled vmapped scan,
    bit-identical per trial to their serial rollouts."""
    dt = _dt()
    scens = [
        scn.no_scenario(N, dtype=dt),
        scn.compose(N, 11, {"wind": dict(wind=0.2, onset_frac=0.2)},
                    dtype=dt, horizon=T),
        scn.compose(N, 12, {"obstacles": dict(count=2, radius=1.0),
                            "drift": dict(speed=0.05,
                                          rematch_every=120)},
                    dtype=dt, horizon=T),
        scn.compose(N, 13, {"byzantine": dict(frac=0.3, sigma=2.0),
                            "sequence": dict(stages=2)},
                    dtype=dt, horizon=T),
    ]
    B = len(scens)
    states, forms, sp = _problem(B, seed=5, scenarios=scens)
    cfg = _cfg()
    bstate, bform = _stack(states), _stack(forms)
    bf, bm = sim.batched_rollout(bstate, bform, ControlGains(), sp, cfg,
                                 T)
    for b in range(B):
        fs_, ms_ = sim.rollout(states[b], forms[b], ControlGains(), sp,
                               cfg, T)
        for name in METRIC_FIELDS:
            np.testing.assert_array_equal(
                np.asarray(getattr(bm, name))[:, b],
                np.asarray(getattr(ms_, name)), f"trial {b}: {name}")


# --------------------------------------------------------------------------
# 3. axis semantics
# --------------------------------------------------------------------------

def test_obstacle_pops_up_moves_and_vanishes():
    dt = _dt()
    scen = scn.no_scenario(N, dtype=dt).replace(
        obs_center=jnp.zeros((tl.DEFAULT_MAX_OBSTACLES, 3), dt)
            .at[0].set(jnp.asarray([1.0, 0.0, 2.0], dt)),
        obs_vel=jnp.zeros((tl.DEFAULT_MAX_OBSTACLES, 3), dt)
            .at[0].set(jnp.asarray([0.5, 0.0, 0.0], dt)),
        obs_radius=jnp.zeros((tl.DEFAULT_MAX_OBSTACLES,), dt).at[0]
            .set(1.2),
        obs_appear=jnp.full((tl.DEFAULT_MAX_OBSTACLES,), tl.NEVER,
                            jnp.int32).at[0].set(10),
        obs_vanish=jnp.full((tl.DEFAULT_MAX_OBSTACLES,), tl.NEVER,
                            jnp.int32).at[0].set(50))
    pos, act = tl.obstacles_at(scen, 0, 0.01)
    assert not bool(np.asarray(act)[0])
    pos, act = tl.obstacles_at(scen, 20, 0.01)
    assert bool(np.asarray(act)[0])
    np.testing.assert_allclose(np.asarray(pos)[0, 0], 1.0 + 0.5 * 0.2)
    _, act = tl.obstacles_at(scen, 50, 0.01)
    assert not bool(np.asarray(act)[0])
    # events fire exactly at appear and vanish
    for t, want in ((9, False), (10, True), (11, False), (50, True)):
        assert bool(np.asarray(tl.scenario_event_at(scen, t))) is want, t


@pytest.mark.slow
def test_obstacle_casts_sector_for_head_on_vehicle():
    from aclswarm_tpu import control

    q = jnp.asarray([[0.0, 0.0, 2.0], [40.0, 40.0, 2.0]], _dt())
    vel = jnp.asarray([[0.5, 0.0, 0.0], [0.5, 0.0, 0.0]], _dt())
    sp = SafetyParams()
    obs = (jnp.asarray([[1.4, 0.0, 2.0]], _dt()),
           jnp.asarray([1.2], _dt()), jnp.asarray([True]))
    v_out, mod = control.collision_avoidance(q, vel, sp, obstacles=obs)
    assert bool(np.asarray(mod)[0])        # vehicle 0 flies at the cylinder
    assert not bool(np.asarray(mod)[1])    # vehicle 1 is far away
    # inactive obstacle: output bit-identical to no obstacles at all
    obs_off = (obs[0], obs[1], jnp.asarray([False]))
    v_ref, mod_ref = control.collision_avoidance(q, vel, sp)
    v_off, mod_off = control.collision_avoidance(q, vel, sp,
                                                 obstacles=obs_off)
    np.testing.assert_array_equal(np.asarray(v_ref), np.asarray(v_off))
    np.testing.assert_array_equal(np.asarray(mod_ref),
                                  np.asarray(mod_off))


def test_wind_displaces_but_dead_vehicles_stay_frozen():
    dt = _dt()
    wind = scn.no_scenario(N, dtype=dt).replace(
        wind_vel=jnp.asarray([0.2, 0.0, 0.0], dt),
        wind_tick=jnp.asarray(0, jnp.int32))
    sched = faults.sample_schedule(3, N, dropout_frac=0.5, drop_tick=20)
    scheds = [sched]
    states, forms, sp = _problem(seed=6, scheds=scheds)
    cfg = _cfg()
    st = states[0].replace(scenario=wind)
    f1, m1 = sim.rollout(jax.tree.map(jnp.copy, states[0]), forms[0],
                         ControlGains(), sp, cfg, T)
    f2, m2 = sim.rollout(st, forms[0], ControlGains(), sp, cfg, T)
    # wind changed the trajectory...
    assert not np.array_equal(np.asarray(f1.swarm.q),
                              np.asarray(f2.swarm.q))
    # ...but dead vehicles stay frozen under wind (freeze wins)
    alive = np.asarray(m2.alive)            # (T, n)
    q = np.asarray(m2.q)
    dead_rows = ~alive[-1]
    assert dead_rows.any()
    np.testing.assert_array_equal(q[-1][dead_rows], q[25][dead_rows])


@pytest.mark.slow
def test_sensor_noise_perturbs_only_flooded_estimates():
    dt = _dt()
    noisy = scn.no_scenario(N, dtype=dt).replace(
        noise_std=jnp.asarray(0.2, dt),
        noise_tick=jnp.asarray(40, jnp.int32),
        key=jnp.asarray(tl.key_leaves(9), jnp.uint32))
    states, forms, sp = _problem(seed=7, localization=True)
    cfg = _cfg(assignment="cbaa", localization="flooded")
    f1, m1 = sim.rollout(jax.tree.map(jnp.copy, states[0]), forms[0],
                         ControlGains(), sp, cfg, T)
    f2, m2 = sim.rollout(states[0].replace(scenario=noisy), forms[0],
                         ControlGains(), sp, cfg, T)
    # before onset the runs agree; after onset the estimates differ
    np.testing.assert_array_equal(np.asarray(m1.q)[:40],
                                  np.asarray(m2.q)[:40])
    assert not np.array_equal(np.asarray(f1.loc.est),
                              np.asarray(f2.loc.est))


def test_sequence_and_drift_move_effective_formation():
    dt = _dt()
    base = jnp.asarray(np.random.default_rng(0).normal(size=(N, 3)), dt)
    stage_pts = jnp.asarray(np.ones((2, N, 3)), dt) * 7.0
    scen = scn.no_scenario(N, dtype=dt).replace(
        seq_points=stage_pts,
        seq_tick=jnp.asarray([50, tl.NEVER], jnp.int32),
        drift_vel=jnp.asarray([0.1, 0.0, 0.0], dt),
        drift_tick=jnp.asarray(100, jnp.int32))
    pts, changed = tl.formation_points_at(scen, base, 0, 0.01)
    np.testing.assert_array_equal(np.asarray(pts), np.asarray(base))
    assert not bool(np.asarray(changed))
    pts, changed = tl.formation_points_at(scen, base, 60, 0.01)
    assert bool(np.asarray(changed))
    np.testing.assert_allclose(np.asarray(pts), 7.0)
    pts, _ = tl.formation_points_at(scen, base, 200, 0.01)
    np.testing.assert_allclose(np.asarray(pts)[:, 0],
                               7.0 + 0.1 * 1.0, rtol=1e-6)
    assert bool(np.asarray(tl.scenario_event_at(scen, 50)))
    assert bool(np.asarray(tl.scenario_event_at(scen, 100)))
    assert not bool(np.asarray(tl.scenario_event_at(scen, 75)))


def test_rematch_cadence_throttles_accepted_auctions():
    dt = _dt()
    # drift keeps the fleet re-matching; cadence 120 admits only every
    # other scheduled auction (assign_every=60)
    scen = scn.no_scenario(N, dtype=dt).replace(
        rematch_every=jnp.asarray(120, jnp.int32))
    states, forms, sp = _problem(seed=8)
    cfg = _cfg()
    _, m = sim.rollout(states[0].replace(scenario=scen), forms[0],
                       ControlGains(), sp, cfg, T)
    auct = np.nonzero(np.asarray(m.auctioned))[0]
    assert list(auct) == [t for t in range(T)
                          if t % ASSIGN_EVERY == 0 and t % 120 == 0]
    # cadence 0 = the engine's own cadence, bit-identical
    _, m0 = sim.rollout(states[0].replace(
        scenario=scn.no_scenario(N, dtype=dt)), forms[0],
        ControlGains(), sp, cfg, T)
    assert np.nonzero(np.asarray(m0.auctioned))[0].tolist() == [
        t for t in range(T) if t % ASSIGN_EVERY == 0]


def test_byzantine_corrupts_assignment_but_extraction_stays_honest():
    dt = _dt()
    byz = scn.no_scenario(N, dtype=dt).replace(
        byz_mask=jnp.asarray([True, True, False, False, False, False]),
        byz_std=jnp.asarray(8.0, dt),
        byz_tick=jnp.asarray(0, jnp.int32),
        key=jnp.asarray(tl.key_leaves(21), jnp.uint32))
    states, forms, sp = _problem(seed=9)
    cfg = _cfg(check_mode="on")
    q0 = np.asarray(states[0].swarm.q).copy()
    q0[:, 2] = np.abs(q0[:, 2]) + 2.0      # airborne: inside the room
    st_clean = sim.init_state(q0, checks=True)
    st_byz = st_clean.replace(scenario=byz)
    _, m1 = sim.rollout(st_clean, forms[0], ControlGains(), sp, cfg, T)
    _, m2 = sim.rollout(st_byz, forms[0], ControlGains(), sp, cfg, T)
    # the lies changed at least one accepted assignment...
    assert not np.array_equal(np.asarray(m1.v2f), np.asarray(m2.v2f))
    # ...but the sanitizer stayed silent: every extraction is honest
    # (a permutation) and every contract held
    assert np.asarray(m2.inv_code).max() == 0
    for row in np.asarray(m2.v2f).reshape(-1, N):
        assert sorted(row) == list(range(N))


def test_scen_points_contract_trips_on_corrupt_table():
    """The new swarmcheck contract: a NaN morph table is caught at the
    tick its stage activates, blamed on scen_points (regression pin for
    the fuzzer's oracle)."""
    from aclswarm_tpu.analysis import invariants as invlib

    dt = _dt()
    S = tl.DEFAULT_MAX_STAGES
    bad_tables = jnp.full((S, N, 3), jnp.nan, dt)
    scen = scn.no_scenario(N, dtype=dt).replace(
        seq_points=bad_tables,
        seq_tick=jnp.asarray([40] + [tl.NEVER] * (S - 1), jnp.int32))
    states, forms, sp = _problem(seed=10)
    q0 = np.asarray(states[0].swarm.q).copy()
    q0[:, 2] = np.abs(q0[:, 2]) + 2.0      # airborne: inside the room
    st = sim.init_state(q0, checks=True, scenario=scen)
    cfg = _cfg(check_mode="on")
    _, m = sim.rollout(st, forms[0], ControlGains(), sp, cfg, T)
    codes = np.asarray(m.inv_code)
    with pytest.raises(invlib.InvariantViolation) as ei:
        invlib.raise_on_violation(codes, trial=0)
    assert ei.value.contract.id == "scen_points"
    assert ei.value.tick == 40


# --------------------------------------------------------------------------
# 4. recovery clock, registry, fuzzer, serve
# --------------------------------------------------------------------------

@pytest.mark.slow
def test_scenario_events_feed_recovery_clock():
    dt = _dt()
    B = 2
    wind = scn.compose(N, 31, {"wind": dict(wind=0.2, onset_frac=0.25)},
                       dtype=dt, horizon=120)
    scens = [wind] * B
    # converged start: the formation IS the cloud, so the wind onset is
    # the only disturbance and the clock measures re-absorption
    rng = np.random.default_rng(0)
    pts = rng.normal(size=(N, 3)) * 4 + np.array([0, 0, 3.0])
    form = make_formation(jnp.asarray(pts, dt),
                          jnp.asarray(np.ones((N, N)) - np.eye(N), dt))
    sp = SafetyParams(bounds_min=jnp.asarray([-50.0, -50.0, 0.0]),
                      bounds_max=jnp.asarray([50.0, 50.0, 20.0]))
    states = [sim.init_state(jnp.asarray(pts, dt), scenario=s)
              for s in scens]
    bstate, bform = _stack(states), _stack([form] * B)
    carry = sumlib.init_carry(N, 5, dtype=dt, batch=B)
    cfg = _cfg(assign_every=30)
    _, carry, summ = sumlib.batched_rollout_summary(
        bstate, carry, bform, ControlGains(), sp, cfg, 120, None, 0,
        window=5, takeoff_alt=3.0)
    ev = np.asarray(summ.scen_event)
    rec = np.asarray(summ.recovery_ticks)
    assert summ.fault_event is None and summ.n_alive is None
    assert ev[:, 30].all() and ev.sum(axis=1).tolist() == [1, 1]
    assert (rec >= 0).any()


def test_registry_families_sample_deterministic_and_validate():
    dt = _dt()
    for name, fam in scn.FAMILIES.items():
        s1 = scn.sample(name, 5, N, dtype=dt, horizon=200)
        s2 = scn.sample(name, 5, N, dtype=dt, horizon=200)
        for l1, l2 in zip(jax.tree.leaves(s1), jax.tree.leaves(s2)):
            np.testing.assert_array_equal(np.asarray(l1),
                                          np.asarray(l2), name)
        assert s1.n == N
        assert s1.max_obstacles == tl.DEFAULT_MAX_OBSTACLES
        assert s1.max_stages == tl.DEFAULT_MAX_STAGES
        assert fam.localization in ("truth", "flooded")
    with pytest.raises(ValueError, match="unknown scenario family"):
        scn.validate("nope")
    with pytest.raises(ValueError, match="no parameter"):
        scn.validate("wind_gust", {"wind.bogus": 1.0})
    # overrides are range-checked, not just name-checked: an
    # out-of-envelope scenario is a refused request, never a served one
    with pytest.raises(ValueError, match="outside the"):
        scn.validate("sensor_noise", {"noise.sigma": 1e6})
    with pytest.raises(ValueError, match="outside the"):
        scn.validate("wind_gust", {"wind.wind": True})
    scn.validate("wind_gust", {"wind.wind": 0.2})   # in-space: fine
    with pytest.raises(ValueError, match="unknown scenario axis"):
        scn.compose(N, 1, {"bogus": {}})


def test_fuzz_quick_seed_subset_zero_violations():
    """Tier-1 slice of the invariant-oracle fuzzer (the full >= 50
    sweep runs in test_fuzz_full_sweep, marked slow, and in
    scripts/check.sh as a smoke)."""
    sys.path.insert(0, str(Path(__file__).resolve().parents[1]
                           / "benchmarks"))
    import scenario_fuzz

    bad = scenario_fuzz.run_fuzz(4, n=N, ticks=240, batch=4,
                                 verbose=False)
    assert bad == []


@pytest.mark.slow
def test_fuzz_full_sweep_zero_violations():
    sys.path.insert(0, str(Path(__file__).resolve().parents[1]
                           / "benchmarks"))
    import scenario_fuzz

    bad = scenario_fuzz.run_fuzz(50, n=8, ticks=480, batch=4,
                                 verbose=False)
    assert bad == []


@pytest.mark.slow
def test_serve_scenario_requests_end_to_end(tmp_path):
    """Acceptance: a scenario request flows admission -> staged round ->
    journal -> postmortem; it shares the bucket (one compiled program)
    with a plain rollout; malformed scenarios are refused at the door."""
    from aclswarm_tpu.serve.service import (ServiceConfig, SwarmService,
                                            bucket_of)
    from aclswarm_tpu.telemetry import postmortem

    plain = {"n": 5, "ticks": 40, "chunk_ticks": 20, "seed": 3}
    kind_params = {"n": 5, "ticks": 40, "chunk_ticks": 20, "seed": 3,
                   "family": "crossing_obstacle", "horizon": 40}
    nested = dict(plain, scenario={"family": "wind_gust", "seed": 4,
                                   "horizon": 40,
                                   "params": {"wind.wind": 0.2}})
    # one compiled program: all three land in the SAME bucket
    assert bucket_of("scenario", kind_params) \
        == bucket_of("rollout", plain) == bucket_of("rollout", nested)

    svc = SwarmService(ServiceConfig(journal_dir=str(tmp_path),
                                     max_batch=4))
    try:
        with pytest.raises(ValueError, match="unknown scenario family"):
            svc.submit("rollout", dict(plain,
                                       scenario={"family": "nope"}))
        with pytest.raises(ValueError, match="no parameter"):
            svc.submit("scenario", dict(kind_params,
                                        params={"obstacles.bogus": 1}))
        # a flooded-model family would be a silent no-op on the serve
        # engine (truth localization, no estimate tables) — refused
        with pytest.raises(ValueError, match="flooded"):
            svc.submit("scenario", dict(kind_params,
                                        family="sensor_noise"))
        t1 = svc.submit("rollout", plain, request_id="plain")
        t2 = svc.submit("scenario", kind_params, request_id="kind")
        t3 = svc.submit("rollout", nested, request_id="nested")
        rs = [t.result(120) for t in (t1, t2, t3)]
        assert all(r.ok for r in rs), rs
        # the scenarios actually bit: outputs differ from the plain run
        assert not np.array_equal(rs[0].value["q"], rs[2].value["q"])
    finally:
        svc.close()
    rep = postmortem.reconstruct(str(tmp_path))
    assert rep["accepted"] == 3
    assert rep["complete"] == rep["gap_free"] == 3, rep


@pytest.mark.slow
def test_sharded_scenario_rollout_bit_parity():
    """Agent-axis GSPMD sharding (virtual 8-device mesh): a
    scenario-carrying state placed by `mesh.shard_problem` (byz mask
    row-sharded, tables/tracks replicated) rolls out bit-identically
    to the unsharded run."""
    from aclswarm_tpu.parallel import mesh as meshlib

    n = 16
    rng = np.random.default_rng(0)
    q0 = rng.normal(size=(n, 3)) * 3
    q0[:, 2] = np.abs(q0[:, 2]) + 2.0
    ang = np.linspace(0, 2 * np.pi, n, endpoint=False)
    pts = np.stack([6 * np.cos(ang), 6 * np.sin(ang),
                    np.full(n, 2.0)], 1)
    form = make_formation(pts, np.ones((n, n)) - np.eye(n))
    sp = SafetyParams(bounds_min=jnp.asarray([-50.0, -50.0, 0.0]),
                      bounds_max=jnp.asarray([50.0, 50.0, 10.0]))
    cfg = sim.SimConfig(assignment="auction", assign_every=4)
    scen = scn.sample("kitchen_sink", 3, n, horizon=16)
    f_ref, m_ref = sim.rollout(sim.init_state(q0, scenario=scen), form,
                               ControlGains(), sp, cfg, 16)
    mesh = meshlib.make_mesh()
    st_s, form_s, _, _ = meshlib.shard_problem(
        sim.init_state(q0, scenario=scen), form, mesh)
    f_shd, m_shd = sim.rollout(st_s, form_s, ControlGains(), sp, cfg, 16)
    np.testing.assert_array_equal(np.asarray(f_ref.swarm.q),
                                  np.asarray(f_shd.swarm.q))
    np.testing.assert_array_equal(np.asarray(m_ref.v2f),
                                  np.asarray(m_shd.v2f))
