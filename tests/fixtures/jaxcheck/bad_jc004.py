"""JC004 fixture: host nondeterminism baked into compiled paths."""
import random
import time

import jax
import numpy as np


@jax.jit
def stamped(x):
    return x + time.time()                      # JC004 (time.time)


@jax.jit
def np_randomness(x):
    return x + np.random.normal()               # JC004 (np.random)


def vmapped_body(x):
    return x * random.random()                  # JC004 (stdlib random)


def host_driver(xs):
    return jax.vmap(vmapped_body)(xs)


def host_only_timing():
    # NOT reachable from jit: benchmarks may time on the host freely
    t0 = time.perf_counter()
    return time.perf_counter() - t0
