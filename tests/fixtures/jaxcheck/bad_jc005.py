"""JC005 fixture: donated-argument read-after-donate."""
from functools import partial

import jax
import jax.numpy as jnp


@partial(jax.jit, donate_argnums=(0,))
def consume(state, delta):
    return state + delta


def bad_caller(state, delta):
    out = consume(state, delta)
    return out + state.sum()                    # JC005 (state donated above)


def good_caller(state, delta):
    state = consume(state, delta)               # ok: donate-and-rebind
    return state + consume(state, delta)


def good_chunked(state, deltas):
    for d in deltas:
        state = consume(state, d)               # ok: rebound every pass
    return state
