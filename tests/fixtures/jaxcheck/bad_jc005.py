"""JC005 fixture: donated-argument read-after-donate."""
from functools import partial

import jax
import jax.numpy as jnp


@partial(jax.jit, donate_argnums=(0,))
def consume(state, delta):
    return state + delta


def bad_caller(state, delta):
    out = consume(state, delta)
    return out + state.sum()                    # JC005 (state donated above)


def good_caller(state, delta):
    state = consume(state, delta)               # ok: donate-and-rebind
    return state + consume(state, delta)


def good_chunked(state, deltas):
    for d in deltas:
        state = consume(state, d)               # ok: rebound every pass
    return state


# --- the serve.staging shape (PR 11): a donated staging store -------------

@partial(jax.jit, donate_argnums=(0,))
def write_row(store, row, slot):
    return jax.tree.map(lambda b, r: b.at[slot].set(r), store, row)


def bad_staging_pack(store, row):
    write_row(store, row, 0)
    return jax.tree.map(lambda b: b[0], store)  # JC005 (store donated above)


def good_staging_pack(store, rows):
    for i, row in enumerate(rows):
        store = write_row(store, row, i)        # ok: the staging idiom —
        #                                         donate, rebind, reuse
    return store
