"""JC102 fixture: lock-order cycles.

`TwoLocks` closes a cycle lexically; `ViaCall` closes one THROUGH the
call graph (the x->y edge exists only because `step` calls `_helper`
with x held). `Suppressed` shows the edge-level pragma: declaring one
nesting safe dissolves the cycle, so the partner site stays quiet too.
"""
import threading


class TwoLocks:
    def __init__(self):
        self._la = threading.Lock()
        self._lb = threading.Lock()

    def ab(self):
        with self._la:
            with self._lb:                  # JC102 (a->b edge of cycle)
                pass

    def ba(self):
        with self._lb:
            with self._la:                  # JC102 (b->a closes cycle)
                pass


class ViaCall:
    def __init__(self):
        self._x = threading.Lock()
        self._y = threading.Lock()

    def step(self):
        with self._x:
            self._helper()                  # JC102 (x->y via call graph)

    def _helper(self):
        with self._y:
            pass

    def back(self):
        with self._y:
            with self._x:                   # JC102 (y->x closes cycle)
                pass


class Suppressed:
    def __init__(self):
        self._p = threading.Lock()
        self._q = threading.Lock()

    def pq(self):
        with self._p:
            with self._q:
                pass                        # clean: partner edge waived

    def qp(self):
        # justified: startup-only path, never concurrent with pq()
        with self._q:
            with self._p:   # jaxcheck: disable=JC102
                pass
