"""JC103 fixture: blocking calls under a service-tier lock.

The lock's OrderedLock family starts with ``serve.`` which marks it
service-tier regardless of module path. `_flush` has one locked and
one unlocked call site, so the report lands on the locked CALL SITE
(the entry-held intersection is empty); `_fsync_always_locked` is
entry-held, so the report lands on the primitive itself. ``cv.wait()``
on the condition you hold is the CV protocol and stays quiet.

The alias cases bind the blocking callable to a local name first
(``w = evt.wait``; ``f = os.fsync``) — the call site then carries no
attribute to match, so the binding site supplies the identity. The
queue case types ``q`` from its stdlib ctor: bare ``.get`` is not in
the method catalog (every dict read would match), only queue-typed
receivers report, and only in the blocking form.
"""
import os
import queue
import threading
import time

from aclswarm_tpu.utils.locks import OrderedLock


class Service:
    def __init__(self, sock, fd):
        self._lock = OrderedLock("serve.fixture")
        self._cv = threading.Condition()
        self._evt = threading.Event()
        self._sock = sock
        self._fd = fd

    def bad_sleep(self):
        with self._lock:
            time.sleep(0.1)                 # JC103 (sleep under lock)

    def bad_send(self, payload):
        with self._lock:
            self._sock.sendall(payload)     # JC103 (socket under lock)

    def bad_wait(self):
        with self._lock:
            self._evt.wait(1.0)             # JC103 (event wait under lock)

    def bad_transitive(self):
        with self._lock:
            self._flush()                   # JC103 (call into fsync path)

    def flush_unlocked(self):
        self._flush()                       # clean: no lock held

    def _flush(self):
        os.fsync(self._fd)

    def always_locked_sync(self):
        with self._lock:
            self._fsync_always_locked()

    def _fsync_always_locked(self):
        # entry-held under the service lock: the primitive reports
        os.fsync(self._fd)                  # JC103 (fsync entry-held)

    def good_outside(self, payload):
        with self._lock:
            data = payload
        self._sock.sendall(data)            # clean: lock released first

    def cv_wait_ok(self):
        with self._cv:
            self._cv.wait(0.5)              # clean: waiting releases cv

    def suppressed_send(self, payload):
        # justified: single-writer socket with a bounded frame size
        with self._lock:
            self._sock.sendall(payload)  # jaxcheck: disable=JC103

    def bad_alias_wait(self):
        w = self._evt.wait
        with self._lock:
            w(1.0)                          # JC103 (aliased event wait)

    def bad_alias_fsync(self):
        f = os.fsync
        with self._lock:
            f(self._fd)                     # JC103 (aliased fsync)

    def bad_queue_get(self):
        q = queue.Queue()
        with self._lock:
            return q.get(timeout=1.0)       # JC103 (queue get under lock)

    def queue_get_nonblocking_ok(self):
        q = queue.Queue()
        with self._lock:
            try:
                return q.get(block=False)   # clean: returns immediately
            except queue.Empty:
                return None

    def alias_rebound_ok(self):
        w = self._evt.wait
        w = self._make_payload              # rebound: no longer blocking
        with self._lock:
            return w()                      # clean

    def _make_payload(self):
        return b""
