"""JC103 fixture: blocking calls under a service-tier lock.

The lock's OrderedLock family starts with ``serve.`` which marks it
service-tier regardless of module path. `_flush` has one locked and
one unlocked call site, so the report lands on the locked CALL SITE
(the entry-held intersection is empty); `_fsync_always_locked` is
entry-held, so the report lands on the primitive itself. ``cv.wait()``
on the condition you hold is the CV protocol and stays quiet.
"""
import os
import threading
import time

from aclswarm_tpu.utils.locks import OrderedLock


class Service:
    def __init__(self, sock, fd):
        self._lock = OrderedLock("serve.fixture")
        self._cv = threading.Condition()
        self._evt = threading.Event()
        self._sock = sock
        self._fd = fd

    def bad_sleep(self):
        with self._lock:
            time.sleep(0.1)                 # JC103 (sleep under lock)

    def bad_send(self, payload):
        with self._lock:
            self._sock.sendall(payload)     # JC103 (socket under lock)

    def bad_wait(self):
        with self._lock:
            self._evt.wait(1.0)             # JC103 (event wait under lock)

    def bad_transitive(self):
        with self._lock:
            self._flush()                   # JC103 (call into fsync path)

    def flush_unlocked(self):
        self._flush()                       # clean: no lock held

    def _flush(self):
        os.fsync(self._fd)

    def always_locked_sync(self):
        with self._lock:
            self._fsync_always_locked()

    def _fsync_always_locked(self):
        # entry-held under the service lock: the primitive reports
        os.fsync(self._fd)                  # JC103 (fsync entry-held)

    def good_outside(self, payload):
        with self._lock:
            data = payload
        self._sock.sendall(data)            # clean: lock released first

    def cv_wait_ok(self):
        with self._cv:
            self._cv.wait(0.5)              # clean: waiting releases cv

    def suppressed_send(self, payload):
        # justified: single-writer socket with a bounded frame size
        with self._lock:
            self._sock.sendall(payload)  # jaxcheck: disable=JC103
