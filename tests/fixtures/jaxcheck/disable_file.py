"""File-level escape hatch fixture: every rule below would fire, but the
file-wide pragma silences the named ones for the whole file.

# jaxcheck: disable-file=JC001,JC004
"""
import random
import time

import jax
import numpy as np


@jax.jit
def would_trip_jc001(x):
    y = np.asarray(x)           # JC001, file-disabled
    return y.item()             # JC001, file-disabled


@jax.jit
def would_trip_jc004(x):
    return x * time.time() + random.random()    # JC004 x2, file-disabled
