"""JC006 fixture: unmasked reductions in fault-aware code.

This file is not under the fault-aware module prefixes, so it opts in:
# jaxcheck: fault-aware-file
"""
import jax.numpy as jnp


def masked_ok(q, alive):
    dn = jnp.where(alive, jnp.linalg.norm(q, axis=-1), 0.0)
    return jnp.sum(dn)                  # ok: alive feeds the operand


def transitively_ok(cost, alive):
    pinned = jnp.where(alive[:, None], cost, 0.0)
    scores = pinned * 2.0
    return jnp.min(scores)              # ok: alive reaches via two hops


def rebinding_ok(cost, pin, forbid):
    cost = cost + 0.0
    cost = jnp.where(pin | forbid, 0.0, cost)
    return jnp.max(cost)                # ok: flow-insensitive rebinding


def bad_mean(q, alive):
    return jnp.mean(q)                  # JC006


def bad_argmin(cost, link_mask):
    idx = jnp.argmin(cost, axis=1)      # JC006
    return idx


def bad_sum_local(q, who):
    dead = who < 0
    total = jnp.sum(q)                  # JC006
    return jnp.where(dead, 0.0, total)


def where_kwarg_ok(q, alive):
    return jnp.sum(q, where=alive)      # ok: native masked reduction


def no_mask_in_scope(q):
    return jnp.max(q)                   # ok: handles no mask -> exempt


def suppressed_site(q, alive):
    return jnp.sum(q)                   # jaxcheck: disable=JC006
