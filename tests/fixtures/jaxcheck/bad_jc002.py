"""JC002 fixture: Python control flow on traced values."""
import jax
import jax.numpy as jnp


@jax.jit
def branch_on_arg(x, threshold: float = 0.5):
    if x > threshold:                           # JC002 (x traced)
        return x * 2
    return x


@jax.jit
def while_on_arg(x):
    while x < 10.0:                             # JC002 (x traced)
        x = x + 1.0
    return x


@jax.jit
def ifexp_on_arg(q):
    return q * 2 if q.sum() else q              # JC002 (q traced)


@jax.jit
def allowed_patterns(x, mask=None, n_iters: int = 5, mode: str = "fast"):
    if mask is None:                            # ok: is-None dispatch
        mask = jnp.ones_like(x)
    if mode == "fast":                          # ok: string mode switch
        x = x * 2
    if n_iters > 3:                             # ok: static annotation
        x = x + 1
    if x.ndim == 2:                             # ok: shape introspection
        x = x[0]
    return x * mask
