"""JC006 fixture, scenario flavor: the rule must see the NEW mask axes
(`aclswarm_tpu.scenarios` — byzantine masks, obstacle activity masks)
exactly as it sees the fault model's alive/link masks.

This file is not under the fault-aware module prefixes, so it opts in:
# jaxcheck: fault-aware-file
"""
import jax.numpy as jnp


def byz_masked_ok(cost, byz_mask):
    honest = jnp.where(byz_mask[:, None], 0.0, cost)
    return jnp.sum(honest)              # ok: byz_mask feeds the operand


def obstacle_masked_ok(d, obs_mask):
    return jnp.min(d, where=obs_mask, initial=jnp.inf)  # ok: native mask


def bad_byz_mean(scores, byz_mask):
    return jnp.mean(scores)             # JC006


def bad_obstacle_argmin(d, obs_mask):
    nearest = jnp.argmin(d, axis=1)     # JC006
    return jnp.where(obs_mask[nearest], -1, nearest)


def no_mask_in_scope(seq_points):
    return jnp.max(seq_points)          # ok: handles no mask -> exempt
