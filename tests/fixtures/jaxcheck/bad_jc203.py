"""JC203 fixture: terminal state reachable twice.

A terminal once-guard is a flag TEST (bail if already terminal)
followed by a flag COMMIT. Unless both sit under one held lock, two
racing resolvers (worker vs recovery vs wire reader) can both pass the
check-then-act window and publish different terminal results. The
report lands on the TEST line. A guard with no commit in the same
function is an early-bail, not a race.
"""
import threading


class RacyTicket:
    def __init__(self):
        self._done = threading.Event()
        self._result = None
        self._resolve_lock = threading.Lock()

    def racy_resolve(self, result):
        if self._done.is_set():          # JC203 (test+commit unlocked)
            return
        self._result = result
        self._done.set()

    def locked_resolve_ok(self, result):
        with self._resolve_lock:
            if self._done.is_set():
                return                   # clean: one critical section
            self._result = result
            self._done.set()

    def guard_only_ok(self):
        if self._done.is_set():
            return True                  # clean: no commit here
        return False


class RacyJob:
    def racy_finish(self, job, outcome):
        if job.finished:                 # JC203 (flag store races)
            return
        job.outcome = outcome
        job.finished = True              # jaxcheck: disable=JC202

    def locked_finish_ok(self, job, lock, outcome):
        with lock:
            if job.finished:
                return                   # clean: shared lock
            job.outcome = outcome
            job.finished = True          # jaxcheck: disable=JC202
