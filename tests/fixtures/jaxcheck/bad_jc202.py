"""JC202 fixture: state transition without a lifecycle event.

A ``_jobs`` map mutation or a ``status``/``finished`` store in a scope
(function body or except-handler body) with no schema'd emission in
that same scope is a journal-invisible state change — the postmortem
reconstructs it as a gap or a loss. Emissions count when made directly
OR through a call into a (transitively) emitting helper. Constructors
are pre-protocol and exempt.
"""


class BadService:
    def __init__(self):
        self._jobs = {}                  # clean: ctor is pre-protocol
        self._log = None

    def silent_drop(self, rid):
        self._jobs.pop(rid, None)        # JC202 (no emission in scope)

    def silent_status(self, job):
        job.status = "failed"            # JC202 (store never journaled)

    def journaled_drop(self, rid, job):
        self._jobs.pop(rid, None)        # clean: emission in same scope
        self._journal_event("resolved", job, status="failed", chunks=0)

    def silent_handler(self, rid, job):
        try:
            self._journal_event("queued", job, reason="boundary")
        except OSError:
            del self._jobs[rid]          # JC202 (handler scope is silent)

    def helper_emits_ok(self, rid, job):
        self._jobs[rid] = job            # clean: _note() emits for us
        self._note(job)

    def _note(self, job):
        self._journal_event("admitted", job)

    def _journal_event(self, event, job, **fields):
        return event, job, fields
