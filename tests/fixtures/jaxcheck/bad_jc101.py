"""JC101 fixture: guarded-field access outside its lock.

`Store` uses explicit ``# guarded-by:`` annotations; `Tally` has none
and exercises the inference path (>= 5 accesses, >= 80% under one
lock, an unlocked WRITE reports). `_locked_helper` proves the
entry-contract propagation: every call site holds the lock, so its
bare access is clean.
"""
import threading


class Store:
    def __init__(self):
        self._lock = threading.Lock()
        self.items = {}                     # guarded-by: _lock
        self.count = 0                      # guarded-by: _lock

    def good_put(self, k, v):
        with self._lock:
            self.items[k] = v
            self.count += 1

    def bad_read(self):
        return len(self.items)              # JC101 (read outside lock)

    def bad_write(self):
        self.count += 1                     # JC101 (write outside lock)

    def _locked_helper(self):
        # clean: every call site holds _lock (entry contract)
        self.count -= 1

    def drain(self):
        with self._lock:
            self._locked_helper()
            self.items.clear()

    def snapshot(self):
        # justified: racy sampled read, staleness is acceptable
        return self.count   # jaxcheck: disable=JC101


class Tally:
    """No annotations: the majority-locked pattern is inferred."""

    def __init__(self):
        self._mu = threading.Lock()
        self.total = 0

    def add(self, x):
        with self._mu:
            self.total += x

    def sub(self, x):
        with self._mu:
            self.total -= x

    def double(self):
        with self._mu:
            self.total *= 2

    def read(self):
        with self._mu:
            return self.total

    def racy_reset(self):
        self.total = 0                      # JC101 (inferred guarded-by)
