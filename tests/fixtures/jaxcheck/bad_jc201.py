"""JC201 fixture: journal-write-after-promise.

The durable done-frame must land BEFORE the client-visible
``_resolve`` — a crash between a premature reply and its journal
record is a silently lost request (the client saw a terminal the
recovery cannot reconstruct). The report lands on the DURABLE line
(the append that arrived too late). A ``return``/``raise`` between
the two is a path barrier: reply-and-bail on one path, journal on
another, is clean.
"""


def _write_frame(path, payload, manifest):
    return path, payload, manifest


class BadFinisher:
    def reply_before_journal(self, job, result):
        job.ticket._resolve(result)
        _write_frame("done", result, {})        # JC201 (append after reply)

    def journal_then_reply_ok(self, job, result):
        _write_frame("done", result, {})
        job.ticket._resolve(result)             # clean: durable first

    def barrier_ok(self, job, result):
        if job.rejected:
            job.ticket._resolve(result)
            return                              # path ends here
        _write_frame("done", result, {})        # clean: other path
