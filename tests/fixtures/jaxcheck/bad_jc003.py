"""JC003 fixture: dtype-less array creation (weak types -> recompiles)."""
import jax
import jax.numpy as jnp
from flax import struct


@jax.jit
def weak_scalar(x):
    return x + jnp.asarray(1.0)                 # JC003 (weak float scalar)


@jax.jit
def caller_dtype(q0):
    return jnp.asarray(q0) * 2                  # JC003 (inherits caller)


@jax.jit
def weak_list(x):
    return x + jnp.array([0.0, 0.0, 1.0])       # JC003 (literal list)


@struct.dataclass
class Carry:
    flag: jnp.ndarray = struct.field(
        default_factory=lambda: jnp.asarray(True))   # ok: bool not weak
    level: jnp.ndarray = struct.field(
        default_factory=lambda: jnp.asarray(0.0))    # JC003 (weak factory)


@jax.jit
def explicit_ok(x):
    a = jnp.asarray(1.0, jnp.float32)           # ok: explicit dtype
    b = jnp.array([1.0, 2.0], dtype=x.dtype)    # ok: dtype kwarg
    c = jnp.asarray(x.sum() * 2)                # ok: traced expression
    return a + b + c
