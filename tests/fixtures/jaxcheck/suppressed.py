"""Escape-hatch fixture: every violation here is explicitly disabled."""
import jax
import numpy as np


@jax.jit
def intentional_sync(x):
    return np.asarray(x)          # jaxcheck: disable=JC001


@jax.jit
def intentional_branch(x):
    if x > 0:                     # jaxcheck: disable
        return x
    return -x


@jax.jit
def multi_rule(x):
    import jax.numpy as jnp
    return jnp.asarray(float(x))  # jaxcheck: disable=JC001,JC003
