"""JC204 fixture: event-vocabulary drift.

Emissions with a literal event name are checked against
`telemetry.lifecycle.EVENTS`/`FLEET_EVENTS` at lint time: unknown
names, literal fields outside the event's schema (required +
documented-optional + envelope), and missing required fields all
report. A ``**splat`` waives only the missing-required check (the
fields are not statically knowable); the suppression pragma waives a
reviewed exception.
"""


class BadEmitter:
    def __init__(self, log):
        self._log = log

    def unknown_event(self, rid):
        self._log.emit("teleported", request_id=rid)      # JC204 (name)

    def extra_field(self, rid):
        self._log.emit("admitted", request_id=rid,  # JC204 (extra field)
                       vibe="good")

    def missing_required(self, rid):
        self._log.emit("chunk", request_id=rid, k=0)      # JC204 (missing)

    def splat_ok(self, rid, fields):
        self._log.emit("chunk", request_id=rid, **fields)   # clean

    def clean_emit(self, rid):
        self._log.emit("queued", request_id=rid,
                       reason="boundary")                   # clean

    def waived_emit(self, rid):
        self._log.emit("warped", request_id=rid)  # jaxcheck: disable=JC204
