"""JC001 fixture: host syncs reachable from jit (every one must fire)."""
import jax
import jax.numpy as jnp
import numpy as np


@jax.jit
def direct_item(x):
    return x.sum().item()                       # JC001 (.item)


@jax.jit
def direct_float(x):
    return float(x[0]) * 2.0                    # JC001 (float)


@jax.jit
def np_pull(x):
    return np.asarray(x) + 1                    # JC001 (np.asarray)


def helper(x):
    # not itself decorated — reachable from jitted `via_helper` below
    return jax.device_get(x)                    # JC001 (device_get)


@jax.jit
def via_helper(x):
    return helper(x * 2)


def scan_body(c, x):
    jax.block_until_ready(c)                    # JC001 (block_until_ready)
    return c + x, None


def host_driver(xs):
    # scan body executes in a compiled context even without @jit
    return jax.lax.scan(scan_body, jnp.float32(0.0), xs)


def host_only(x):
    # NOT reachable from any jit root: must NOT fire
    return float(np.asarray(x).sum())
