"""Utility tests: timing/profiling (§5.1) and logging (§5.5)."""
import logging
import time

import jax.numpy as jnp
import numpy as np
import pytest

from aclswarm_tpu.utils import (Stopwatch, get_logger, median_time,
                                readback_sync, timing)


class TestTiming:
    def test_readback_sync_returns_scalar(self):
        assert readback_sync(jnp.arange(5.0)) == 0.0
        assert readback_sync((jnp.full((2, 2), 3.0), jnp.zeros(1))) == 3.0

    def test_median_time_measures(self):
        def fn(x):
            time.sleep(0.01)
            return x
        dt = median_time(fn, jnp.zeros(1), per=1, reps=3)
        assert 0.005 < dt < 0.5

    def test_median_time_divides_by_per(self):
        def fn(x):
            time.sleep(0.02)
            return x
        dt = median_time(fn, jnp.zeros(1), per=10, reps=2)
        assert dt < 0.01

    def test_stopwatch_phases(self):
        sw = Stopwatch()
        with sw.phase("a"):
            time.sleep(0.005)
        with sw.phase("b"):
            pass
        names = [n for n, _ in sw.phases]
        assert names == ["a", "b"]
        assert sw.phases[0][1] >= 0.005
        lines = []
        sw.report(lines.append)
        assert len(lines) == 2 and lines[0].startswith("a:")


class TestTimingStats:
    """`timing_stats` is the single home every benchmark imports; its
    contract (warmup call, rep spread, per-division) is load-bearing for
    the committed artifacts' jitter columns."""

    def test_keys_and_ordering(self):
        stats = timing.timing_stats(lambda x: x, jnp.zeros(1), reps=4)
        assert set(stats) == {"median_s", "min_s", "max_s", "reps"}
        assert stats["min_s"] <= stats["median_s"] <= stats["max_s"]
        assert stats["reps"] == 4

    def test_warmup_not_measured(self):
        """The first (compile/warmup) call must not pollute the stats."""
        calls = []

        def fn(x):
            calls.append(time.perf_counter())
            if len(calls) == 1:
                time.sleep(0.05)        # a 'compile' on the warmup call
            return x

        stats = timing.timing_stats(fn, jnp.zeros(1), reps=3)
        assert len(calls) == 4          # 1 warmup + 3 reps
        assert stats["max_s"] < 0.05

    def test_per_divides_every_stat(self):
        def fn(x):
            time.sleep(0.02)
            return x

        s1 = timing.timing_stats(fn, jnp.zeros(1), per=1, reps=2)
        s10 = timing.timing_stats(fn, jnp.zeros(1), per=10, reps=2)
        assert s10["median_s"] < s1["median_s"] / 5
        assert s10["max_s"] < 0.01

    def test_median_time_matches_stats(self):
        dt = median_time(lambda x: x, jnp.zeros(1), reps=3)
        assert isinstance(dt, float) and dt >= 0.0

    def test_readback_sync_is_a_barrier(self):
        """readback_sync must return a host float of the FIRST leaf —
        the digest contract `parallel.launch` relies on."""
        out = readback_sync({"a": jnp.full((3,), 7.5), "b": jnp.zeros(2)})
        assert isinstance(out, float) and out == 7.5

    @pytest.mark.slow
    def test_trace_writes_profile(self, tmp_path):
        """`timing.trace` wraps jax.profiler start/stop: the logdir must
        exist and contain a capture afterwards. Slow tier: the profiler
        capture is ~24 s of tier-1 wall for an infrastructure (not
        product-logic) check — re-marked when the tier-1 duration guard
        crossed 80% of its budget at PR 15."""
        logdir = tmp_path / "prof"
        with timing.trace(str(logdir)):
            readback_sync(jnp.arange(8.0) * 2.0)
        files = list(logdir.rglob("*"))
        assert files, "profiler trace produced no output"


class TestLogging:
    def test_logger_hierarchy(self):
        log = get_logger("interop.bridge")
        assert log.name == "aclswarm_tpu.interop.bridge"
        root = logging.getLogger("aclswarm_tpu")
        assert root.handlers  # configured once

    def test_env_level_spec(self, monkeypatch):
        import aclswarm_tpu.utils.log as loglib
        monkeypatch.setattr(loglib, "_configured", False)
        monkeypatch.setenv("ACLSWARM_LOG",
                           "debug,aclswarm_tpu.sim=warning")
        loglib._configure()
        assert logging.getLogger("aclswarm_tpu").level == logging.DEBUG
        assert logging.getLogger("aclswarm_tpu.sim").level == logging.WARNING
        # restore defaults for other tests
        logging.getLogger("aclswarm_tpu").setLevel(logging.INFO)
        logging.getLogger("aclswarm_tpu.sim").setLevel(logging.NOTSET)

    def test_messages_flow(self, caplog):
        log = get_logger("test.flow")
        with caplog.at_level(logging.INFO, logger="aclswarm_tpu"):
            log.info("hello %d", 7)
        assert any("hello 7" in r.message for r in caplog.records)


class TestJittered:
    """`utils.retry.jittered` — the retry-after form of the policy
    jitter (ISSUE-13 satellite): deterministic, bounded, de-aligned
    across seeds."""

    def test_deterministic_and_bounded(self):
        from aclswarm_tpu.utils.retry import jittered

        for seed in (0, 1, 0xDEAD):
            for attempt in range(5):
                d1 = jittered(2.0, seed, attempt)
                d2 = jittered(2.0, seed, attempt)
                assert d1 == d2                     # replayable
                assert 2.0 <= d1 < 2.0 * 1.25      # base + frac bound

    def test_dealigns_across_seeds_and_attempts(self):
        from aclswarm_tpu.utils.retry import jittered

        ds = {round(jittered(1.0, seed, 0), 9) for seed in range(16)}
        assert len(ds) > 8          # a herd of seeds spreads out
        assert jittered(1.0, 3, 0) != jittered(1.0, 3, 1)
        # zero stays zero; frac=0 disables the jitter entirely
        assert jittered(0.0, 1, 0) == 0.0
        assert jittered(5.0, 1, 2, frac=0.0) == 5.0
