"""Utility tests: timing/profiling (§5.1) and logging (§5.5)."""
import logging
import time

import jax.numpy as jnp
import numpy as np
import pytest

from aclswarm_tpu.utils import (Stopwatch, get_logger, median_time,
                                readback_sync)


class TestTiming:
    def test_readback_sync_returns_scalar(self):
        assert readback_sync(jnp.arange(5.0)) == 0.0
        assert readback_sync((jnp.full((2, 2), 3.0), jnp.zeros(1))) == 3.0

    def test_median_time_measures(self):
        def fn(x):
            time.sleep(0.01)
            return x
        dt = median_time(fn, jnp.zeros(1), per=1, reps=3)
        assert 0.005 < dt < 0.5

    def test_median_time_divides_by_per(self):
        def fn(x):
            time.sleep(0.02)
            return x
        dt = median_time(fn, jnp.zeros(1), per=10, reps=2)
        assert dt < 0.01

    def test_stopwatch_phases(self):
        sw = Stopwatch()
        with sw.phase("a"):
            time.sleep(0.005)
        with sw.phase("b"):
            pass
        names = [n for n, _ in sw.phases]
        assert names == ["a", "b"]
        assert sw.phases[0][1] >= 0.005
        lines = []
        sw.report(lines.append)
        assert len(lines) == 2 and lines[0].startswith("a:")


class TestLogging:
    def test_logger_hierarchy(self):
        log = get_logger("interop.bridge")
        assert log.name == "aclswarm_tpu.interop.bridge"
        root = logging.getLogger("aclswarm_tpu")
        assert root.handlers  # configured once

    def test_env_level_spec(self, monkeypatch):
        import aclswarm_tpu.utils.log as loglib
        monkeypatch.setattr(loglib, "_configured", False)
        monkeypatch.setenv("ACLSWARM_LOG",
                           "debug,aclswarm_tpu.sim=warning")
        loglib._configure()
        assert logging.getLogger("aclswarm_tpu").level == logging.DEBUG
        assert logging.getLogger("aclswarm_tpu.sim").level == logging.WARNING
        # restore defaults for other tests
        logging.getLogger("aclswarm_tpu").setLevel(logging.INFO)
        logging.getLogger("aclswarm_tpu.sim").setLevel(logging.NOTSET)

    def test_messages_flow(self, caplog):
        log = get_logger("test.flow")
        with caplog.at_level(logging.INFO, logger="aclswarm_tpu"):
            log.info("hello %d", 7)
        assert any("hello 7" in r.message for r in caplog.records)
