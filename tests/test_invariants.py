"""swarmcheck runtime tier: compiled-in invariant contracts.

Four claims (docs/STATIC_ANALYSIS.md, runtime tier):

- **clean-system positives**: every solver x fault combination runs the
  checked rollout with a zero violation code — the contracts hold on
  the real system (no false positives), serial and batched;
- **mutation coverage**: each seeded corruption (duplicate assignment
  row, NaN pose injected mid-rollout, asymmetric adjacency, stale alive
  mask after a rejoin) trips EXACTLY its contract, in both serial and
  B>=2 batched rollouts, attributed to the right trial index and tick;
- **surfacing**: the per-tick codes ride `StepMetrics`/`ChunkSummary`
  and the drivers raise a structured `InvariantViolation`;
- **zero-cost-off** is proven separately in
  `tests/test_analysis.py::TestZeroCostOff` (HLO digest equality).

The heavy n>=16 full contract grid is marked `slow`.
"""
from functools import partial

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from aclswarm_tpu import faults, sim
from aclswarm_tpu.analysis import invariants as invlib
from aclswarm_tpu.analysis import trace_audit as ta
from aclswarm_tpu.core.types import ControlGains
from aclswarm_tpu.sim import engine

pytestmark = pytest.mark.invariants

N = 5
TICKS = 6


def _problem(n=N, seed=0):
    return ta._scatter(n, seed), ta._formation(n), ta._sparams()


def _cfg(assignment="auction", **kw):
    kw.setdefault("assign_every", 2)
    return sim.SimConfig(assignment=assignment, check_mode="on", **kw)


def _fresh_rollout():
    """A private jit wrapper so monkeypatched solver functions are
    actually traced (the module-level `sim.rollout` caches the honest
    program)."""
    return jax.jit(partial(engine.rollout.__wrapped__),
                   static_argnames=("n_ticks", "cfg"))


def _fresh_batched():
    return jax.jit(partial(engine.batched_rollout.__wrapped__),
                   static_argnames=("n_ticks", "cfg"))


def _first(codes):
    return invlib.first_violation(np.asarray(codes))


def _stack(*trees):
    return jax.tree.map(lambda *xs: jnp.stack(xs), *trees)


# ---------------------------------------------------------------------------
# clean-system positives

class TestCleanSystem:
    @pytest.mark.parametrize("solver", ["auction", "sinkhorn", "cbaa"])
    @pytest.mark.parametrize("faulted", [False, True],
                             ids=["nofaults", "faults"])
    def test_serial_rollout_clean(self, solver, faulted):
        q0, form, sp = _problem()
        sched = faults.sample_schedule(3, N, dropout_frac=0.4, drop_tick=1,
                                       rejoin_tick=3) if faulted else None
        state = sim.init_state(q0, faults=sched, checks=True)
        st, m = sim.rollout(state, form, ControlGains(), sp,
                            _cfg(solver), TICKS)
        assert int(st.inv.code) == 0, \
            f"clean system violated {_first(m.inv_code)}"
        assert int(st.inv.tick) == -1
        assert np.all(np.asarray(m.inv_code) == 0)

    def test_batched_rollout_clean(self):
        q0a, form, sp = _problem(seed=0)
        q0b = ta._scatter(N, 1)
        sched = faults.sample_schedule(3, N, dropout_frac=0.4, drop_tick=1,
                                       rejoin_tick=3)
        bstate = _stack(
            sim.init_state(q0a, faults=faults.no_faults(N), checks=True),
            sim.init_state(q0b, faults=sched, checks=True))
        bform = _stack(form, form)
        st, m = sim.batched_rollout(bstate, bform, ControlGains(), sp,
                                    _cfg(), TICKS)
        assert np.asarray(st.inv.code).tolist() == [0, 0]


# ---------------------------------------------------------------------------
# mutation coverage, serial

class TestMutationsSerial:
    def test_duplicate_assignment_row(self, monkeypatch):
        """A solver bug returning a duplicated row must trip assign_perm
        the tick the corrupted assignment is taken."""
        from aclswarm_tpu.assignment import auction
        orig = auction.auction_lap.__wrapped__ \
            if hasattr(auction.auction_lap, "__wrapped__") \
            else auction.auction_lap

        def corrupted(benefit, **kw):
            res = orig(benefit, **kw)
            return res._replace(
                row_to_col=res.row_to_col.at[1].set(res.row_to_col[0]))

        monkeypatch.setattr(auction, "auction_lap", corrupted)
        q0, form, sp = _problem()
        state = sim.init_state(q0, checks=True)
        st, m = _fresh_rollout()(state, form, ControlGains(), sp,
                                 cfg=_cfg("auction"), n_ticks=TICKS)
        tick, contract = _first(m.inv_code)
        assert contract.id == "assign_perm"
        assert tick == 0            # first auction tick takes the corrupt row
        assert int(st.inv.tick) == 0

    def test_nan_pose_injection_mid_rollout(self):
        """A NaN sneaking into the velocity pipeline mid-rollout trips
        state_finite at the injection tick — and is blamed on
        state_finite, not the out-of-bounds its NaN comparisons imply."""
        q0, form, sp = _problem()
        k = 3
        joy_vel = np.zeros((TICKS, N, 3), np.float64)
        joy_vel[k, 0, :] = np.nan
        joy_active = np.zeros((TICKS, N), bool)
        joy_active[k, 0] = True
        inputs = sim.ExternalInputs(
            cmd=jnp.zeros((TICKS,), jnp.int32),
            joy_vel=jnp.asarray(joy_vel, q0.dtype),
            joy_yawrate=jnp.zeros((TICKS, N), q0.dtype),
            joy_active=jnp.asarray(joy_active))
        state = sim.init_state(q0, checks=True)
        st, m = sim.rollout(state, form, ControlGains(), sp, _cfg(), TICKS,
                            inputs)
        tick, contract = _first(m.inv_code)
        assert contract.id == "state_finite"
        assert tick == k
        assert int(st.inv.tick) == k

    def test_asymmetric_adjacency(self):
        q0, form, sp = _problem()
        adj = np.asarray(form.adjmat).copy()
        adj[0, 1] = 0.0             # break symmetry
        state = sim.init_state(q0, checks=True)
        st, m = sim.rollout(state, form.replace(adjmat=jnp.asarray(adj)),
                            ControlGains(), sp, _cfg(), TICKS)
        tick, contract = _first(m.inv_code)
        assert contract.id == "adj_sym"
        assert tick == 0

    def test_stale_alive_mask_after_rejoin(self, monkeypatch):
        """An engine regression feeding a one-tick-stale alive mask must
        trip mask_consistency at the first mask flip. Works because the
        contract recomputes the reference mask from the raw schedule
        leaves instead of calling the (patched) `alive_at`."""
        from aclswarm_tpu.faults import schedule as faultlib
        orig = faultlib.alive_at

        def stale(sched, tick):
            return orig(sched, jnp.asarray(tick, jnp.int32) - 1)

        monkeypatch.setattr(engine.faultlib, "alive_at", stale)
        q0, form, sp = _problem()
        drop = 2
        sched = faults.sample_schedule(3, N, dropout_frac=0.4,
                                       drop_tick=drop, rejoin_tick=4)
        state = sim.init_state(q0, faults=sched, checks=True)
        st, m = _fresh_rollout()(state, form, ControlGains(), sp,
                                 cfg=_cfg(), n_ticks=TICKS)
        tick, contract = _first(m.inv_code)
        assert contract.id == "mask_consistency"
        assert tick == drop         # the first tick the stale mask differs


# ---------------------------------------------------------------------------
# mutation coverage, batched (B=2; trial 1 corrupted, trial 0 clean)

class TestMutationsBatched:
    def _assert_trial1_only(self, metrics, contract_id, tick):
        codes = np.asarray(metrics.inv_code)     # (T, B)
        assert np.all(codes[:, 0] == 0), "clean trial polluted"
        got_tick, contract = _first(codes[:, 1])
        assert contract.id == contract_id
        assert got_tick == tick

    def test_duplicate_assignment_row(self):
        """Data-driven: trial 1 starts on a non-permutation with the
        auto-auction gated off (the hover phase), so nothing repairs it."""
        q0, form, sp = _problem()
        s0 = sim.init_state(q0, checks=True)
        s1 = sim.init_state(ta._scatter(N, 1),
                            v2f0=np.array([1, 1, 2, 3, 4]), checks=True)
        bstate = _stack(s0, s1).replace(
            assign_enabled=jnp.asarray([False, False]))
        st, m = sim.batched_rollout(bstate, _stack(form, form),
                                    ControlGains(), sp, _cfg(), TICKS)
        self._assert_trial1_only(m, "assign_perm", 0)
        assert np.asarray(st.inv.code).tolist()[0] == 0
        assert int(np.asarray(st.inv.tick)[1]) == 0

    def test_nan_pose_injection_mid_rollout(self):
        q0, form, sp = _problem()
        k = 3
        joy_vel = np.zeros((TICKS, 2, N, 3), np.float64)
        joy_vel[k, 1, 0, :] = np.nan
        joy_active = np.zeros((TICKS, 2, N), bool)
        joy_active[k, 1, 0] = True
        inputs = sim.ExternalInputs(
            cmd=jnp.zeros((TICKS, 2), jnp.int32),
            joy_vel=jnp.asarray(joy_vel, q0.dtype),
            joy_yawrate=jnp.zeros((TICKS, 2, N), q0.dtype),
            joy_active=jnp.asarray(joy_active))
        bstate = _stack(sim.init_state(q0, checks=True),
                        sim.init_state(ta._scatter(N, 1), checks=True))
        st, m = sim.batched_rollout(bstate, _stack(form, form),
                                    ControlGains(), sp, _cfg(), TICKS,
                                    inputs)
        self._assert_trial1_only(m, "state_finite", k)

    def test_asymmetric_adjacency(self):
        q0, form, sp = _problem()
        adj = np.asarray(form.adjmat).copy()
        adj[0, 1] = 0.0
        form_bad = form.replace(adjmat=jnp.asarray(adj))
        bstate = _stack(sim.init_state(q0, checks=True),
                        sim.init_state(ta._scatter(N, 1), checks=True))
        st, m = sim.batched_rollout(bstate, _stack(form, form_bad),
                                    ControlGains(), sp, _cfg(), TICKS)
        self._assert_trial1_only(m, "adj_sym", 0)

    def test_stale_alive_mask_after_rejoin(self, monkeypatch):
        """Trial 0 carries the no-fault schedule (stale == fresh, never
        trips); trial 1 has a real drop/rejoin window, so only it sees
        the stale-mask inconsistency."""
        from aclswarm_tpu.faults import schedule as faultlib
        orig = faultlib.alive_at

        def stale(sched, tick):
            return orig(sched, jnp.asarray(tick, jnp.int32) - 1)

        monkeypatch.setattr(engine.faultlib, "alive_at", stale)
        q0, form, sp = _problem()
        drop = 2
        sched = faults.sample_schedule(3, N, dropout_frac=0.4,
                                       drop_tick=drop, rejoin_tick=4)
        bstate = _stack(
            sim.init_state(q0, faults=faults.no_faults(N), checks=True),
            sim.init_state(ta._scatter(N, 1), faults=sched, checks=True))
        st, m = _fresh_batched()(bstate, _stack(form, form),
                                 ControlGains(), sp, cfg=_cfg(),
                                 n_ticks=TICKS)
        self._assert_trial1_only(m, "mask_consistency", drop)


# ---------------------------------------------------------------------------
# surfacing: summary pass-through + driver raise + decode helpers

class TestSurfacing:
    def test_summary_passes_codes_through(self):
        from aclswarm_tpu.sim import summary as sumlib
        q0, form, sp = _problem()
        adj = np.asarray(form.adjmat).copy()
        adj[0, 1] = 0.0
        form_bad = form.replace(adjmat=jnp.asarray(adj))
        bstate = _stack(sim.init_state(q0, checks=True),
                        sim.init_state(ta._scatter(N, 1), checks=True))
        carry = sumlib.init_carry(N, window=3, dtype=q0.dtype, batch=2)
        st, carry, summ = sumlib.batched_rollout_summary(
            bstate, carry, _stack(form, form_bad), ControlGains(), sp,
            _cfg(), TICKS, None, 0, window=3,
            takeoff_alt=jnp.asarray(1.0, q0.dtype))
        codes = np.asarray(summ.inv_code)
        assert codes.shape == (2, TICKS)
        assert np.all(codes[0] == 0)
        assert _first(codes[1])[1].id == "adj_sym"

    def test_summary_off_mode_has_no_codes(self):
        from aclswarm_tpu.sim import summary as sumlib
        q0, form, sp = _problem()
        bstate = _stack(sim.init_state(q0),
                        sim.init_state(ta._scatter(N, 1)))
        carry = sumlib.init_carry(N, window=3, dtype=q0.dtype, batch=2)
        st, carry, summ = sumlib.batched_rollout_summary(
            bstate, carry, _stack(form, form), ControlGains(), sp,
            sim.SimConfig(assignment="auction", assign_every=2), TICKS,
            None, 0, window=3, takeoff_alt=jnp.asarray(1.0, q0.dtype))
        assert summ.inv_code is None

    def test_raise_on_violation(self):
        codes = np.zeros(10, np.int32)
        invlib.raise_on_violation(codes, trial=4)      # clean: no-op
        codes[7] = invlib.CODES["state_finite"]
        with pytest.raises(invlib.InvariantViolation) as ei:
            invlib.raise_on_violation(codes, trial=4, tick0=100)
        e = ei.value
        assert e.contract.id == "state_finite"
        assert e.tick == 107 and e.trial == 4
        assert "trial 4" in str(e) and "tick 107" in str(e)
        assert "state_finite" in str(e)

    def test_first_violation_decodes_unknown_codes_loudly(self):
        codes = np.array([0, 99], np.int32)
        tick, contract = invlib.first_violation(codes)
        assert tick == 1 and contract.code == 99
        assert contract.id == "unknown"

    def test_checked_state_required(self):
        """cfg.check_mode='on' without init_state(checks=True) fails
        loudly at trace time, mirroring the flooded-localization rule."""
        q0, form, sp = _problem()
        state = sim.init_state(q0)          # no carry allocated
        with pytest.raises(ValueError, match="checks=True"):
            sim.rollout(state, form, ControlGains(), sp, _cfg(), 2)

    def test_unknown_check_mode_rejected(self):
        q0, form, sp = _problem()
        state = sim.init_state(q0, checks=True)
        cfg = sim.SimConfig(assignment="auction", assign_every=2,
                            check_mode="sometimes")
        with pytest.raises(ValueError, match="check_mode"):
            sim.rollout(state, form, ControlGains(), sp, cfg, 2)


# ---------------------------------------------------------------------------
# solver-level contracts: sinkhorn marginals + admm residual

class TestSolverContracts:
    def test_sinkhorn_marginals_clean_on_converged_plan(self):
        from aclswarm_tpu.assignment import sinkhorn
        rng = np.random.default_rng(0)
        q = rng.normal(size=(8, 3))
        p = rng.normal(size=(8, 3))
        res = sinkhorn.sinkhorn_assign(q, p)
        row_err, col_err = sinkhorn.marginal_errors(res.plan_log)
        assert not bool(invlib.sinkhorn_marginals_violated(row_err,
                                                           col_err))

    def test_sinkhorn_marginals_trip_on_garbage_plan(self):
        from aclswarm_tpu.assignment import sinkhorn
        n = 8
        # "plan" with mass n per row instead of 1/n: marginal errs ~ n
        garbage = jnp.zeros((n, n))
        row_err, col_err = sinkhorn.marginal_errors(garbage)
        assert bool(invlib.sinkhorn_marginals_violated(row_err, col_err))

    def test_marginal_errors_exact_on_uniform_plan(self):
        from aclswarm_tpu.assignment import sinkhorn
        n = 8
        uniform = jnp.full((n, n), -2.0 * np.log(n))
        row_err, col_err = sinkhorn.marginal_errors(uniform)
        assert float(row_err) < 1e-9 and float(col_err) < 1e-9

    def test_admm_check_on_equals_off_and_stays_clean(self):
        from aclswarm_tpu.gains import admm
        n = 6
        ang = np.linspace(0, 2 * np.pi, n, endpoint=False)
        pts = np.stack([3 * np.cos(ang), 3 * np.sin(ang),
                        np.full(n, 2.0)], 1)
        adj = np.ones((n, n)) - np.eye(n)
        adj[0, 2] = adj[2, 0] = 0
        g_off = np.asarray(admm.solve_gains(pts, adj))
        g_on = np.asarray(admm.solve_gains(pts, adj, check_mode="on"))
        assert np.array_equal(g_off, g_on)

    def test_admm_residual_predicate(self):
        """The projection-form iteration is empirically net-decreasing
        under every parameterization tried (the contract guards future
        regressions), so the violation predicate is pinned directly."""
        t, f = jnp.asarray(True), jnp.asarray(False)
        one, two = jnp.asarray(1.0), jnp.asarray(2.0)
        assert bool(invlib.admm_residual_violated(one, two, f))
        assert not bool(invlib.admm_residual_violated(one, two, t))
        assert not bool(invlib.admm_residual_violated(two, one, f))
        assert not bool(invlib.admm_residual_violated(one, one, f))

    def test_admm_unknown_check_mode_rejected(self):
        from aclswarm_tpu.gains import admm
        pts = np.zeros((4, 3))
        adj = np.ones((4, 4)) - np.eye(4)
        with pytest.raises(ValueError, match="check_mode"):
            admm.solve_gains(pts, adj, check_mode="On")

    def test_admm_raise_path(self, monkeypatch):
        """solve_gains(check_mode='on') raises the structured violation
        when the contract fires (wire test: predicate forced true)."""
        from aclswarm_tpu.gains import admm
        monkeypatch.setattr(
            admm.invlib, "admm_residual_violated",
            lambda first, last, stopped: jnp.asarray(True))
        n = 7     # distinct shape: forces a retrace under the patch
        ang = np.linspace(0, 2 * np.pi, n, endpoint=False)
        pts = np.stack([3 * np.cos(ang), 3 * np.sin(ang),
                        np.full(n, 2.0)], 1)
        adj = np.ones((n, n)) - np.eye(n)
        adj[0, 2] = adj[2, 0] = 0
        with pytest.raises(invlib.InvariantViolation) as ei:
            admm.solve_gains(pts, adj, check_mode="on")
        assert ei.value.contract.id == "admm_residual"


# ---------------------------------------------------------------------------
# driver integration (serial trials loop with the sanitizer compiled in)

class TestDriverIntegration:
    @pytest.mark.slow
    def test_run_trial_checked_happy_path(self):
        """A short checked trial completes its chunk loop without a
        violation: the driver wiring (init_state(checks=True), per-chunk
        raise_on_violation) runs on the happy path. The 2 s timeout
        terminates the trial long before convergence — FSM outcome is
        irrelevant here, only that the sanitizer stayed quiet."""
        from aclswarm_tpu.harness import trials as trialmod
        cfg = trialmod.TrialConfig(formation="swarm4", trials=1,
                                   seed=1, check_mode="on",
                                   dynamics="tracking",
                                   trial_timeout=2.0, verbose=False)
        fsm = trialmod.run_trial(cfg, 0)
        assert fsm.done


# ---------------------------------------------------------------------------
# heavy sweep

@pytest.mark.slow
class TestHeavyGrid:
    @pytest.mark.parametrize("solver", ["auction", "sinkhorn", "cbaa"])
    @pytest.mark.parametrize("faulted", [False, True],
                             ids=["nofaults", "faults"])
    @pytest.mark.parametrize("loc", ["truth", "flooded"])
    def test_n16_full_contract_grid(self, solver, faulted, loc):
        n = 16
        q0 = ta._scatter(n)
        form = ta._formation(n)
        sp = ta._sparams()
        sched = faults.sample_schedule(
            7, n, dropout_frac=0.25, drop_tick=2, rejoin_tick=6,
            link_loss=0.2) if faulted else None
        state = sim.init_state(q0, localization=loc == "flooded",
                               faults=sched, checks=True)
        cfg = sim.SimConfig(assignment=solver, assign_every=2,
                            localization=loc, flood_every=2,
                            check_mode="on")
        st, m = sim.rollout(state, form, ControlGains(), sp, cfg, 10)
        assert int(st.inv.code) == 0, _first(m.inv_code)
