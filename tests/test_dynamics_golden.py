"""Dynamics golden: the engine's closed loop vs `FormCtrlDynam.m`.

The one closed-loop dynamics spec portable without ROS is the reference's
MATLAB simulation (`aclswarm/matlab/FormCtrlDynam.m:96-151` driving
`Helpers/SysDynam.m:104-151`):

    u   = sat( A q + F q )          per-agent speed saturation to vSat
    F   = g * atan(adj .* (Dc-Dd)) + diag(-rowsum)     (g = 2)
    qdot = v
    vdot = u - v

i.e. a double integrator whose acceleration tracks the commanded velocity
with unit gain — exactly the engine's ``doubleint`` model with
``kp_track=0, kd_track=1`` once the safety shaping is opened up (no accel
limit, no avoidance, unbounded room). This file pins that equivalence two
ways:

1. *exact discretization*: an independent loop-form NumPy integrator of the
   MATLAB equations, stepped with the same semi-implicit Euler the engine
   uses, must match the engine trajectory to f64 round-off;
2. *continuous limit*: a fine-step RK4 integration of the same ODE (the
   `ode45` analogue) must stay within discretization tolerance of the
   engine's 100 Hz trajectory, and both must converge to the planted
   formation.

Assignment is held fixed (identity): `FormCtrlDynam.m` supports
``runAssign=false`` and the assignment machinery has its own replay oracle
(`tests/test_replay.py`). Collision avoidance off mirrors the script's
``runColAvoid=false`` default.
"""
from __future__ import annotations

import numpy as np
import pytest

import jax.numpy as jnp

from aclswarm_tpu import sim
from aclswarm_tpu.core.types import ControlGains, SafetyParams, make_formation

VSAT = 3.0   # FormCtrlDynam.m:64 vSat
G = 2.0      # SysDynam.m:119 scale-control gain


def _pentagon(n=5, r=3.0):
    ang = np.linspace(0, 2 * np.pi, n, endpoint=False)
    return np.stack([r * np.cos(ang), r * np.sin(ang), np.zeros(n)], 1)


def _setup(n=5, seed=2):
    """Shared inputs: planted 2D formation, complete graph, solver gains,
    random starts in a 5 x 5 box (`FormCtrlDynam.m:40` rng(2), 2D)."""
    from aclswarm_tpu import gains as gainslib

    pts = _pentagon(n)
    adj = np.ones((n, n)) - np.eye(n)
    A = np.asarray(gainslib.solve_gains(pts, adj), np.float64)
    rng = np.random.default_rng(seed)
    q0 = np.zeros((n, 3))
    q0[:, :2] = rng.uniform(0, 5, (n, 2))
    dstar = np.linalg.norm(pts[:, None, :2] - pts[None, :, :2], axis=-1)
    return pts, adj, A, q0, dstar


def _matlab_u(q, A, adj, dstar):
    """`SysDynam.m:104-137` control, loop form per agent (independent of the
    engine's batched einsum path)."""
    n = q.shape[0]
    u = np.zeros_like(q)
    for i in range(n):
        for j in range(n):
            if i == j or not adj[i, j]:
                continue
            qij = q[j] - q[i]
            u[i] += A[3 * i:3 * i + 3, 3 * j:3 * j + 3] @ qij
            e = np.hypot(qij[0], qij[1]) - dstar[i, j]
            f = G * np.arctan(e)
            u[i, :2] += f * qij[:2]
    # per-agent planar speed saturation (`SysDynam.m:141-148`; 2D there)
    for i in range(n):
        s = np.hypot(u[i, 0], u[i, 1])
        if s > VSAT:
            u[i, :2] *= VSAT / s
    return u


def _host_euler(q0, A, adj, dstar, dt, ticks):
    """Semi-implicit Euler on qdot=v, vdot=u-v (the engine's stepping)."""
    q = q0.copy()
    v = np.zeros_like(q)
    traj = np.empty((ticks, *q.shape))
    for k in range(ticks):
        u = _matlab_u(q, A, adj, dstar)
        v = v + (u - v) * dt
        q = q + v * dt
        traj[k] = q
    return traj


def _host_rk4(q0, A, adj, dstar, dt, ticks):
    """Classic RK4 on the same ODE (the `ode45` analogue)."""
    def f(state):
        q, v = state
        u = _matlab_u(q, A, adj, dstar)
        return (v, u - v)

    q, v = q0.copy(), np.zeros_like(q0)
    traj = np.empty((ticks, *q.shape))
    for k in range(ticks):
        s0 = (q, v)
        k1 = f(s0)
        k2 = f((q + dt / 2 * k1[0], v + dt / 2 * k1[1]))
        k3 = f((q + dt / 2 * k2[0], v + dt / 2 * k2[1]))
        k4 = f((q + dt * k3[0], v + dt * k3[1]))
        q = q + dt / 6 * (k1[0] + 2 * k2[0] + 2 * k3[0] + k4[0])
        v = v + dt / 6 * (k1[1] + 2 * k2[1] + 2 * k3[1] + k4[1])
        traj[k] = q
    return traj


def _engine_traj(pts, adj, A, q0, dt, ticks):
    """The engine's `doubleint` loop with safety shaping opened up to the
    MATLAB model: no accel limit, no room, no avoidance, fixed assignment."""
    big = 1e18
    sparams = SafetyParams(
        bounds_min=jnp.asarray([-big, -big, -big]),
        bounds_max=jnp.asarray([big, big, big]),
        max_vel_xy=VSAT, max_vel_z=VSAT,
        max_accel_xy=big, max_accel_z=big)
    cgains = ControlGains(K1_xy=G, K2_xy=1.0, K1_z=0.0, K2_z=1.0,
                          e_xy_thr=0.0, e_z_thr=0.0, kp=1.0, kd=0.0)
    cfg = sim.SimConfig(control_dt=dt, assignment="none",
                        dynamics="doubleint", kp_track=0.0, kd_track=1.0,
                        use_colavoid=False)
    formation = make_formation(pts, adj, A)
    state = sim.init_state(jnp.asarray(q0))
    _, metrics = sim.rollout(state, formation, cgains, sparams, cfg, ticks)
    return np.asarray(metrics.q)


def test_doubleint_matches_matlab_loop_exactly():
    """Same discretization, independent implementations: f64 round-off."""
    pts, adj, A, q0, dstar = _setup()
    dt, ticks = 0.01, 800
    ours = _engine_traj(pts, adj, A, q0, dt, ticks)
    golden = _host_euler(q0, A, adj, dstar, dt, ticks)
    np.testing.assert_allclose(ours, golden, atol=1e-9)


@pytest.mark.slow
def test_doubleint_tracks_continuous_ode_and_converges():
    """The 100 Hz semi-implicit Euler stays within discretization error of
    the fine-step RK4 solution of the MATLAB ODE, and both reach the
    planted pentagon (shape convergence, `FormCtrlDynam.m`'s end state)."""
    pts, adj, A, q0, dstar = _setup()
    T = 30.0
    ours = _engine_traj(pts, adj, A, q0, 0.01, int(T / 0.01))
    fine = _host_rk4(q0, A, adj, dstar, 0.002, int(T / 0.002))
    # discretization gap, worst tick (compare at common times)
    gap = np.abs(ours[4::5] - fine[24::25]).max()
    assert gap < 0.05, gap
    # converged to the formation shape: pairwise distances match dstar
    qf = ours[-1]
    dc = np.linalg.norm(qf[:, None, :2] - qf[None, :, :2], axis=-1)
    assert np.abs(dc - dstar).max() < 1e-2
    # z untouched (2D case embedded in the 3D stack)
    assert np.abs(ours[..., 2]).max() == 0.0


def test_doubleint_is_default_trial_dynamics():
    """Trials default to the honest second-order model (`doubleint`), not
    goal teleportation (round-2 weak #7)."""
    from aclswarm_tpu.harness.trials import TrialConfig
    assert TrialConfig().dynamics == "doubleint"
