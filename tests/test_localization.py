"""Localization layer tests (L3): timestamped flooding, newest-wins merge,
multi-hop propagation, and the flooded information model end-to-end.

Spec anchors: `aclswarm/src/vehicle_tracker.cpp:31-45` (strictly-newer-wins
merge), `aclswarm/src/localization_ros.cpp:101-148` (own-state feed + 50 Hz
flood), `:152-185` (comm graph follows adjmat∘assignment).
"""
import jax.numpy as jnp
import numpy as np
import pytest

from aclswarm_tpu import sim
from aclswarm_tpu.core import perm as permutil
from aclswarm_tpu.core.types import (ControlGains, SafetyParams,
                                     make_formation)
from aclswarm_tpu.sim import localization as loc


def line_graph(n):
    adj = np.zeros((n, n))
    for i in range(n - 1):
        adj[i, i + 1] = adj[i + 1, i] = 1
    return jnp.asarray(adj)


class TestFlood:
    def test_self_observation_is_truth(self):
        q0 = jnp.asarray(np.random.default_rng(0).normal(size=(5, 3)))
        t = loc.init_table(jnp.zeros((5, 3)))
        t = loc.observe_self(t, q0)
        np.testing.assert_allclose(np.asarray(t.est)[np.arange(5),
                                                     np.arange(5)], q0)
        assert np.all(np.asarray(t.age)[np.arange(5), np.arange(5)] == 0)

    def test_one_hop_per_flood(self):
        """On a line graph, news of vehicle 0's move reaches vehicle k after
        exactly k flood rounds (the multi-hop propagation of
        `localization_ros.cpp:132-148`: each round re-publishes the merged
        vector one hop further)."""
        n = 5
        adj = line_graph(n)
        v2f = permutil.identity(n)
        q = jnp.zeros((n, 3)).at[:, 0].set(jnp.arange(n, dtype=jnp.float64))
        t = loc.init_table(q)
        # vehicle 0 moves; everyone else still believes the census position
        q_new = q.at[0, 1].set(7.0)
        comm = loc.comm_mask(adj, v2f)
        t = loc.observe_self(t, q_new)
        for hop in range(1, n):
            t = loc.EstimateTable(est=t.est, age=t.age + 1)
            t = loc.observe_self(t, q_new)
            t = loc.flood(t, comm)
            est = np.asarray(t.est)
            for v in range(n):
                knows = est[v, 0, 1] == 7.0
                assert knows == (v <= hop), (v, hop)

    def test_strictly_newer_wins(self):
        """A stale incoming estimate must not overwrite a fresher stored one
        (`vehicle_tracker.cpp:31-45` strict > comparison)."""
        n = 3
        adj = line_graph(n)  # 0-1-2
        v2f = permutil.identity(n)
        comm = loc.comm_mask(adj, v2f)
        t = loc.init_table(jnp.zeros((n, 3)))
        # vehicle 1 holds a fresh estimate of vehicle 2 (age 1); vehicle 0
        # holds a stale one (age 5) with a different value
        est = t.est.at[1, 2, 0].set(42.0).at[0, 2, 0].set(-1.0)
        age = t.age.at[1, 2].set(1).at[0, 2].set(5)
        t = loc.EstimateTable(est=est, age=age)
        t2 = loc.flood(t, comm)
        # 0 hears 1: takes the fresher 42 estimate
        assert float(t2.est[0, 2, 0]) == 42.0
        assert int(t2.age[0, 2]) == 1
        # 1 hears 0 and 2: 2's self-entry (age 0) beats everything
        assert float(t2.est[1, 2, 0]) == 0.0
        # equal ages do NOT overwrite (strict): give 0 and 1 equal-age
        # conflicting estimates of 2 and check both keep their own
        est = t.est.at[1, 2, 0].set(42.0).at[0, 2, 0].set(-1.0)
        age = t.age.at[1, 2].set(3).at[0, 2].set(3).at[2, 2].set(9)
        t3 = loc.flood(loc.EstimateTable(est=est, age=age), comm)
        assert float(t3.est[0, 2, 0]) == -1.0

    def test_blocked_merge_bit_identical_to_dense(self):
        """`target_block` is a pure memory shape change: blocked and dense
        floods must produce bit-identical tables for every block size,
        including non-divisors (the n=1000 scale mode's correctness
        contract; same scheme as CBAA's task_block)."""
        n = 17
        rng = np.random.default_rng(3)
        adj = (rng.random((n, n)) < 0.3).astype(float)
        adj = np.triu(adj, 1)
        adj = adj + adj.T
        v2f = jnp.asarray(rng.permutation(n).astype(np.int32))
        comm = loc.comm_mask(jnp.asarray(adj), v2f)
        t = loc.EstimateTable(
            est=jnp.asarray(rng.normal(size=(n, n, 3))),
            age=jnp.asarray(rng.integers(0, 50, (n, n)), jnp.int32))
        dense = loc.flood(t, comm)
        for B in (1, 4, 5, 16, 17, 32):
            blocked = loc.flood(t, comm, target_block=B)
            np.testing.assert_array_equal(np.asarray(dense.est),
                                          np.asarray(blocked.est), err_msg=str(B))
            np.testing.assert_array_equal(np.asarray(dense.age),
                                          np.asarray(blocked.age), err_msg=str(B))

    @pytest.mark.slow
    def test_blocked_merge_large_n_smoke(self):
        """n=500 flood round through the blocked merge: the scale mode
        runs without the dense (n, n, n) broadcast (500 MB here, 4 GB at
        the n=1000 north star) and still matches a spot-checked dense
        column (round-2 weak #4: the memory-bounding machinery must be
        demonstrated at the scale it exists for)."""
        n = 500
        rng = np.random.default_rng(9)
        adj = (rng.random((n, n)) < 0.02).astype(float)
        adj = np.triu(adj, 1)
        adj = adj + adj.T
        v2f = permutil.identity(n)
        comm = loc.comm_mask(jnp.asarray(adj), v2f)
        t = loc.EstimateTable(
            est=jnp.asarray(rng.normal(size=(n, n, 3)),
                            jnp.float32),
            age=jnp.asarray(rng.integers(0, 30, (n, n)), jnp.int32))
        out = loc.flood(t, comm, target_block=64)
        # spot-check receiver 0 against a NumPy dense merge
        age = np.asarray(t.age)
        cm = np.asarray(comm)
        cand = np.where(cm[0][:, None], age, 1 << 30)
        best = cand.min(axis=0)
        take = best < age[0]
        np.testing.assert_array_equal(
            np.asarray(out.age)[0], np.where(take, best, age[0]))

    def test_stripe_merge_bit_identical_to_full(self):
        """A stripe flood equals the full flood restricted to the stripe's
        columns, and leaves every other column untouched (the phased-flood
        correctness contract, `SimConfig.flood_phases`)."""
        n = 13
        rng = np.random.default_rng(5)
        adj = (rng.random((n, n)) < 0.4).astype(float)
        adj = np.triu(adj, 1)
        adj = adj + adj.T
        comm = loc.comm_mask(jnp.asarray(adj), permutil.identity(n))
        t = loc.EstimateTable(
            est=jnp.asarray(rng.normal(size=(n, n, 3))),
            age=jnp.asarray(rng.integers(0, 50, (n, n)), jnp.int32))
        full = loc.flood(t, comm)
        for start, width in ((0, 5), (5, 5), (8, 5), (0, 13), (6, 7)):
            s = loc.flood(t, comm, stripe=(start, width))
            sl = slice(start, start + width)
            np.testing.assert_array_equal(np.asarray(s.est[:, sl]),
                                          np.asarray(full.est[:, sl]))
            np.testing.assert_array_equal(np.asarray(s.age[:, sl]),
                                          np.asarray(full.age[:, sl]))
            # untouched outside the stripe
            mask = np.ones(n, bool)
            mask[sl] = False
            np.testing.assert_array_equal(np.asarray(s.est[:, mask]),
                                          np.asarray(t.est[:, mask]))
            np.testing.assert_array_equal(np.asarray(s.age[:, mask]),
                                          np.asarray(t.age[:, mask]))
        # stripe + target_block compose (the n=1000 phased scale mode)
        s = loc.flood(t, comm, target_block=3, stripe=(2, 7))
        np.testing.assert_array_equal(np.asarray(s.est[:, 2:9]),
                                      np.asarray(full.est[:, 2:9]))

    def test_phased_tick_refreshes_every_target_each_window(self):
        """Over one flood_every window, tick_phased merges every target
        exactly once — per-entry cadence identical to the bulk flood."""
        n = 8
        rng = np.random.default_rng(7)
        q = jnp.asarray(rng.normal(size=(n, 3)))
        adj = jnp.asarray(np.ones((n, n)) - np.eye(n))
        v2f = permutil.identity(n)
        bulk = phased = loc.init_table(q)
        # age the tables so merges visibly refresh entries
        bulk = loc.EstimateTable(est=bulk.est, age=bulk.age + 40)
        phased = loc.EstimateTable(est=phased.est, age=phased.age + 40)
        for t in range(4):
            bulk = loc.tick(bulk, q, adj, v2f, do_flood=(t % 2) == 0)
            phased = loc.tick_phased(phased, q, adj, v2f, t,
                                     flood_every=2, phases=2)
        # static swarm: both reach the same steady table after one window
        np.testing.assert_array_equal(np.asarray(bulk.est),
                                      np.asarray(phased.est))
        # ages agree up to the stripe's phase shift within the window
        assert int(jnp.max(phased.age)) <= int(jnp.max(bulk.age)) + 1

    def test_phased_must_divide_flood_every(self):
        n = 4
        t = loc.init_table(jnp.zeros((n, 3)))
        with pytest.raises(ValueError):
            loc.tick_phased(t, jnp.zeros((n, 3)),
                            jnp.ones((n, n)), permutil.identity(n), 0,
                            flood_every=2, phases=3)

    def test_comm_graph_follows_assignment(self):
        """v hears w iff their formation points are adjacent
        (`localization_ros.cpp:152-185`)."""
        n = 4
        adj = line_graph(n)  # formation pts 0-1-2-3
        v2f = jnp.asarray([2, 0, 3, 1], jnp.int32)
        comm = np.asarray(loc.comm_mask(adj, v2f))
        for v in range(n):
            for w in range(n):
                assert comm[v, w] == bool(
                    adj[int(v2f[v]), int(v2f[w])] > 0)

    def test_no_graph_no_flood(self):
        """With an empty adjmat (pre-dispatch), estimates only age."""
        n = 3
        t = loc.init_table(jnp.ones((n, 3)))
        q = jnp.full((n, 3), 2.0)
        t = loc.tick(t, q, jnp.zeros((n, n)), permutil.identity(n),
                     jnp.asarray(True))
        est = np.asarray(t.est)
        off = ~np.eye(n, dtype=bool)
        assert np.all(est[off] == 1.0)      # off-diagonal stays at census
        assert np.all(np.asarray(t.age)[off] == 1)


class TestFloodedRollout:
    """End-to-end: the engine's 'flooded' information model."""

    def _setup(self, seed=3):
        rng = np.random.default_rng(seed)
        n = 6
        # sparse ring+chords graph so multi-hop staleness exists
        adj = np.zeros((n, n))
        for i in range(n):
            adj[i, (i + 1) % n] = adj[(i + 1) % n, i] = 1
        adj[0, 3] = adj[3, 0] = 1
        pts = np.array([[np.cos(a), np.sin(a), 1.5]
                        for a in np.linspace(0, 2 * np.pi, n, endpoint=False)])
        pts[:, :2] *= 3.0
        from aclswarm_tpu import gains as gainslib
        G = gainslib.solve_gains(jnp.asarray(pts), jnp.asarray(adj))
        formation = make_formation(pts, adj, np.asarray(G))
        q0 = rng.normal(size=(n, 3)) * 2.0
        q0[:, 2] = 1.5
        return n, formation, jnp.asarray(q0)

    def test_estimates_differ_from_truth_midflight(self):
        """The layer must DO something: while vehicles move, multi-hop
        estimates lag the true state (VERDICT r1 item 5 'done' criterion)."""
        n, formation, q0 = self._setup()
        cfg = sim.SimConfig(assignment="cbaa", localization="flooded",
                            dynamics="firstorder")
        state = sim.init_state(q0, localization=True)
        state, _ = sim.rollout(state, formation, ControlGains(),
                               SafetyParams(), cfg, 50)
        stale = np.asarray(loc.staleness(state.loc, state.swarm.q))
        off = ~np.eye(n, dtype=bool)
        # mid-flight, someone's belief about someone else must lag truth
        assert stale[off].max() > 1e-3
        # own entries lag by at most one control tick of motion (the table
        # snapshots the autopilot state at the top of the tick, then the
        # dynamics integrate) — bounded by vmax * dt, far fresher than the
        # multi-hop flood path
        assert stale[~off].max() < 0.02

    def test_convergence_under_flooded_localization(self):
        """swarm converges to formation shape with the real information
        model (CBAA + flooded estimates), matching the reference SIL."""
        n, formation, q0 = self._setup()
        cfg = sim.SimConfig(assignment="cbaa", localization="flooded",
                            dynamics="firstorder")
        state = sim.init_state(q0, localization=True)
        state, metrics = sim.rollout(state, formation, ControlGains(),
                                     SafetyParams(), cfg, 4000)
        # converged: distributed command ~0 for everyone
        dn = np.asarray(metrics.distcmd_norm)[-100:]
        assert dn.mean() < 0.25, dn.mean()
        # estimates have converged too (static swarm => floods catch up)
        stale = np.asarray(loc.staleness(state.loc, state.swarm.q))
        assert stale.max() < 0.05

    def test_truth_and_flooded_agree_when_static(self):
        """A hovering swarm (no motion) has zero estimate error, so the
        flooded control command equals the truth-mode command."""
        n, formation, q0 = self._setup()
        from aclswarm_tpu import control
        from aclswarm_tpu.core.types import SwarmState
        swarm = SwarmState(q=q0, vel=jnp.zeros_like(q0))
        v2f = permutil.identity(n)
        table = loc.init_table(q0)
        u_truth = control.compute(swarm, formation, v2f, ControlGains())
        u_flood = control.compute(swarm, formation, v2f, ControlGains(),
                                  rel=loc.relative_views(table))
        np.testing.assert_allclose(np.asarray(u_truth), np.asarray(u_flood),
                                   atol=1e-12)

    def test_flooded_requires_table(self):
        n, formation, q0 = self._setup()
        cfg = sim.SimConfig(localization="flooded")
        state = sim.init_state(q0, localization=False)
        with pytest.raises(ValueError, match="flooded"):
            sim.step(state, formation, ControlGains(), SafetyParams(), cfg)
