"""Geometry kernel tests.

Mirrors the reference's validation style: algebraic invariants + cross-checks
against an independent implementation (`aclswarm/matlab/test_alignment.m`,
`aclswarm/src/aclswarm/assignment.py:143-156` self-tests).
"""
import jax
import jax.numpy as jnp
import numpy as np
import pytest

from aclswarm_tpu.core import geometry, perm
from aclswarm_tpu.core.types import gains_from_flat, gains_to_flat


def rot2(th):
    c, s = np.cos(th), np.sin(th)
    return np.array([[c, -s], [s, c]])


class TestPdistmat:
    def test_matches_numpy(self):
        rng = np.random.default_rng(0)
        x = rng.normal(size=(10, 3))
        D = geometry.pdistmat(jnp.asarray(x))
        Dnp = np.linalg.norm(x[:, None, :] - x[None, :, :], axis=-1)
        # the |x|^2-2xy trick loses ~sqrt(eps) near zero, like the reference
        np.testing.assert_allclose(np.asarray(D), Dnp, atol=1e-7)

    def test_zero_diagonal(self):
        x = jnp.asarray(np.random.default_rng(1).normal(size=(7, 2)))
        D = geometry.pdistmat(x)
        np.testing.assert_allclose(np.asarray(jnp.diag(D)), 0.0, atol=1e-12)


class TestArun:
    def test_recovers_planted_2d_transform(self):
        rng = np.random.default_rng(2)
        p = rng.normal(size=(8, 3))
        R2 = rot2(0.7)
        t2 = np.array([1.5, -2.0])
        q = p.copy()
        q[:, :2] = p[:, :2] @ R2.T + t2
        R, t = geometry.arun(jnp.asarray(p), jnp.asarray(q), d=2)
        np.testing.assert_allclose(np.asarray(R)[:2, :2], R2, atol=1e-8)
        np.testing.assert_allclose(np.asarray(t)[:2], t2, atol=1e-8)
        # z untouched for d=2
        np.testing.assert_allclose(np.asarray(R)[2, 2], 1.0)
        assert float(t[2]) == 0.0

    def test_recovers_planted_3d_transform(self):
        rng = np.random.default_rng(3)
        p = rng.normal(size=(12, 3))
        # random proper rotation via QR
        A = rng.normal(size=(3, 3))
        Q, _ = np.linalg.qr(A)
        if np.linalg.det(Q) < 0:
            Q[:, 0] *= -1
        t3 = np.array([0.3, 4.0, -1.0])
        q = p @ Q.T + t3
        R, t = geometry.arun(jnp.asarray(p), jnp.asarray(q), d=3)
        np.testing.assert_allclose(np.asarray(R), Q, atol=1e-8)
        np.testing.assert_allclose(np.asarray(t), t3, atol=1e-8)

    def test_no_reflection(self):
        # mirrored clouds must still produce a proper rotation (det +1),
        # per the det-correction in matlab/Helpers/arun.m:14-22
        rng = np.random.default_rng(4)
        p = rng.normal(size=(6, 3))
        q = p.copy()
        q[:, 0] *= -1.0  # reflect
        R, _ = geometry.arun(jnp.asarray(p), jnp.asarray(q), d=3)
        assert float(jnp.linalg.det(R)) == pytest.approx(1.0, abs=1e-8)

    def test_weighted_subset_equals_sliced(self):
        rng = np.random.default_rng(5)
        p = rng.normal(size=(9, 3))
        q = rng.normal(size=(9, 3))
        mask = np.zeros(9)
        sel = [0, 2, 3, 7]
        mask[sel] = 1.0
        Rw, tw = geometry.arun(jnp.asarray(p), jnp.asarray(q),
                               w=jnp.asarray(mask), d=2)
        Rs, ts = geometry.arun(jnp.asarray(p[sel]), jnp.asarray(q[sel]), d=2)
        np.testing.assert_allclose(np.asarray(Rw), np.asarray(Rs), atol=1e-10)
        np.testing.assert_allclose(np.asarray(tw), np.asarray(ts), atol=1e-10)


class TestAlignLocal:
    def test_full_graph_matches_global_align(self):
        # with a complete graph and identity assignment every agent sees the
        # whole swarm, so local alignment == global alignment for all agents
        rng = np.random.default_rng(6)
        n = 6
        p = rng.normal(size=(n, 3))
        q = rng.normal(size=(n, 3))
        adj = np.ones((n, n)) - np.eye(n)
        v2f = perm.identity(n)
        out = geometry.align_formation_local(
            jnp.asarray(q), jnp.asarray(p), jnp.asarray(adj), v2f)
        ref = geometry.align(jnp.asarray(p), jnp.asarray(q), d=2)
        for i in range(n):
            np.testing.assert_allclose(np.asarray(out[i]), np.asarray(ref),
                                       atol=1e-9)

    def test_respects_assignment_permutation(self):
        # scramble vehicles; aligning with the correct assignment must match
        # aligning the unscrambled swarm
        rng = np.random.default_rng(7)
        n = 5
        p = rng.normal(size=(n, 3))
        q_form = rng.normal(size=(n, 3))
        v2f = jnp.asarray(np.array([2, 0, 3, 1, 4], dtype=np.int32))
        q_veh = np.asarray(q_form)[np.asarray(v2f)]  # vehicle v sits at its pt
        adj = np.ones((n, n)) - np.eye(n)
        out = geometry.align_formation_local(
            jnp.asarray(q_veh), jnp.asarray(p), jnp.asarray(adj), v2f)
        ref = geometry.align(jnp.asarray(p), jnp.asarray(q_form), d=2)
        for i in range(n):
            np.testing.assert_allclose(np.asarray(out[i]), np.asarray(ref),
                                       atol=1e-9)

    def test_jit_compatible(self):
        rng = np.random.default_rng(8)
        n = 4
        f = jax.jit(geometry.align_formation_local)
        out = f(jnp.asarray(rng.normal(size=(n, 3))),
                jnp.asarray(rng.normal(size=(n, 3))),
                jnp.asarray(np.ones((n, n)) - np.eye(n)),
                perm.identity(n))
        assert out.shape == (n, n, 3)


class TestPerm:
    def test_invert_roundtrip(self):
        p = jnp.asarray(np.array([2, 0, 1, 4, 3], dtype=np.int32))
        pi = perm.invert(p)
        np.testing.assert_array_equal(np.asarray(p[pi]), np.arange(5))
        np.testing.assert_array_equal(np.asarray(pi[p]), np.arange(5))

    def test_order_conversions(self):
        rng = np.random.default_rng(9)
        x = jnp.asarray(rng.normal(size=(5, 3)))
        v2f = jnp.asarray(np.array([2, 0, 1, 4, 3], dtype=np.int32))
        xf = perm.veh_to_formation_order(x, v2f)
        # row v must land at row v2f[v]
        for v in range(5):
            np.testing.assert_allclose(np.asarray(xf[int(v2f[v])]),
                                       np.asarray(x[v]))
        back = perm.formation_to_veh_order(xf, v2f)
        np.testing.assert_allclose(np.asarray(back), np.asarray(x))

    def test_is_valid(self):
        assert bool(perm.is_valid(jnp.asarray([1, 0, 2])))
        assert not bool(perm.is_valid(jnp.asarray([1, 1, 2])))
        assert not bool(perm.is_valid(jnp.asarray([-1, 0, 2])))
        assert not bool(perm.is_valid(jnp.asarray([0, 1, 3])))


class TestGainLayout:
    def test_flat_roundtrip(self):
        rng = np.random.default_rng(10)
        n = 4
        flat = jnp.asarray(rng.normal(size=(3 * n, 3 * n)))
        blocks = gains_from_flat(flat)
        # block (i, j) is the reference's A.block<3,3>(3i, 3j)
        for i in range(n):
            for j in range(n):
                np.testing.assert_allclose(
                    np.asarray(blocks[i, j]),
                    np.asarray(flat[3 * i:3 * i + 3, 3 * j:3 * j + 3]))
        np.testing.assert_allclose(np.asarray(gains_to_flat(blocks)),
                                   np.asarray(flat))
