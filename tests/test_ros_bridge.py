"""ROS `aclswarm_msgs` adapter tests — fake-rospy loopback, no ROS.

The done-criterion from the round-3 review: a test drives `TpuPlanner`
through the ACTUAL `aclswarm_msgs` field layouts (points as
geometry_msgs/Point[], adjmat/gains as 2D MultiArrays with the
`utils.h:83-126` layout convention, estimates as PointStamped[]) over an
in-process rospy fake, so the real-ROS deployment is a pure import swap
(`ros_bridge.main`).
"""
import numpy as np
import pytest

from aclswarm_tpu.interop import messages as m
from aclswarm_tpu.interop import ros_bridge as rb
from aclswarm_tpu.interop.ros_fakes import FakeMsgs, FakeRospy, Time

RNG = np.random.default_rng(0)


def _wire_formation(n=4, gains="zeros", name="sq"):
    pts = np.array([[0.0, 0, 1], [2, 0, 1], [2, 2, 1], [0, 2, 1]])[:n]
    adj = np.ones((n, n), np.uint8) - np.eye(n, dtype=np.uint8)
    g = None
    if gains == "zeros":
        g = np.zeros((3 * n, 3 * n), np.float32)
    elif gains == "solve":
        from aclswarm_tpu import gains as gainslib
        g = np.asarray(gainslib.solve_gains(pts, adj), np.float32)
    return m.Formation(header=m.Header(seq=1, stamp=0.5, frame_id="world"),
                       name=name, points=pts, adjmat=adj, gains=g)


class TestConverters:
    def test_formation_roundtrip(self):
        fm = _wire_formation(gains="zeros")
        ros = rb.formation_to_ros(fm, FakeMsgs, stamp=Time(0.5))
        # the ros message carries the operator's exact layout
        assert [d.label for d in ros.adjmat.layout.dim] == ["rows", "cols"]
        assert ros.adjmat.layout.dim[0].stride == 16
        assert ros.adjmat.layout.dim[1].stride == 4
        assert len(ros.points) == 4 and ros.points[1].x == 2.0
        back = rb.formation_from_ros(ros)
        np.testing.assert_array_equal(back.points, fm.points)
        np.testing.assert_array_equal(back.adjmat, fm.adjmat)
        np.testing.assert_array_equal(back.gains, fm.gains)
        assert back.name == "sq"

    def test_formation_without_gains(self):
        fm = _wire_formation(gains=None)
        back = rb.formation_from_ros(rb.formation_to_ros(fm, FakeMsgs))
        assert back.gains is None   # empty array = solve on commit

    def test_multiarray_layout_faithful_decode(self):
        """Decode honors data_offset and a row stride wider than cols —
        the C++ convention (`utils.h:83-94`), not just flat reshape."""
        msg = FakeMsgs.UInt8MultiArray()
        rows, cols, stride, off = 2, 3, 5, 4
        d0, d1 = (FakeMsgs.MultiArrayDimension(),
                  FakeMsgs.MultiArrayDimension())
        d0.size, d0.stride = rows, rows * stride
        d1.size, d1.stride = cols, stride
        msg.layout.dim = [d0, d1]
        msg.layout.data_offset = off
        data = np.zeros(off + rows * stride, np.uint8)
        want = np.arange(1, 7, dtype=np.uint8).reshape(2, 3)
        for i in range(rows):
            data[off + i * stride: off + i * stride + cols] = want[i]
        msg.data = data.tolist()
        np.testing.assert_array_equal(
            rb._decode_multiarray(msg, np.uint8), want)

    def test_estimates_roundtrip(self):
        est = m.VehicleEstimates(
            header=m.Header(seq=3, stamp=1.25),
            positions=RNG.normal(size=(5, 3)), stamps=RNG.random(5))
        ros = rb.estimates_to_ros(est, FakeMsgs)
        assert len(ros.positions) == 5
        assert ros.positions[2].header.stamp.to_sec() == \
            pytest.approx(est.stamps[2])
        back = rb.estimates_from_ros(ros)
        np.testing.assert_allclose(back.positions, est.positions)
        np.testing.assert_allclose(back.stamps, est.stamps)

    def test_estimates_wrong_n_rejected(self):
        est = m.VehicleEstimates(header=m.Header(),
                                 positions=np.zeros((3, 3)),
                                 stamps=np.zeros(3))
        ros = rb.estimates_to_ros(est, FakeMsgs)
        with pytest.raises(ValueError):
            rb.estimates_from_ros(ros, n=4)

    def test_cbaa_roundtrip(self):
        bid = m.CBAA(header=m.Header(seq=2, stamp=0.1), auction_id=7,
                     iter=3, price=RNG.random(6).astype(np.float32),
                     who=RNG.integers(-1, 6, 6).astype(np.int32))
        back = rb.cbaa_from_ros(rb.cbaa_to_ros(bid, FakeMsgs))
        assert back.auction_id == 7 and back.iter == 3
        np.testing.assert_allclose(back.price, bid.price, rtol=1e-6)
        np.testing.assert_array_equal(back.who, bid.who)

    def test_assignment_roundtrip_and_uint8_limit(self):
        perm = np.array([2, 0, 3, 1], np.int32)
        ros = rb.assignment_to_ros(perm, FakeMsgs)
        assert ros.data == [2, 0, 3, 1]       # bare data, no layout
        assert ros.layout.dim == []
        np.testing.assert_array_equal(rb.assignment_from_ros(ros), perm)
        with pytest.raises(ValueError):
            rb.assignment_to_ros(np.arange(300), FakeMsgs)

    def test_flightmode_mapping(self):
        q = FakeMsgs.QuadFlightMode()
        for ros_mode, wire in ((q.GO, m.MODE_GO), (q.LAND, m.MODE_LAND),
                               (q.KILL, m.MODE_KILL)):
            q.mode = ros_mode
            assert rb.flightmode_from_ros(q).mode == wire
        q.mode = q.ESTOP                      # unmapped enum: neutral
        assert rb.flightmode_from_ros(q).mode == 0


class _SwarmSide:
    """The rest of the ROS graph, faked: per-vehicle localization
    publishers feeding `<veh>/vehicle_estimates`, and first-order
    vehicles consuming `<veh>/distcmd`."""

    def __init__(self, ros, vehs, q0, dt=0.01, tau=0.15):
        self.ros, self.vehs, self.dt, self.tau = ros, vehs, dt, tau
        self.q = np.asarray(q0, float).copy()
        self.vel = np.zeros_like(self.q)
        n = len(vehs)
        self.pub_est = [ros.Publisher(f"/{v}/vehicle_estimates",
                                      FakeMsgs.VehicleEstimates)
                        for v in vehs]
        self.n = n
        self.k = 0

    def publish_estimates(self):
        for v, pub in enumerate(self.pub_est):
            est = m.VehicleEstimates(
                header=m.Header(seq=self.k, stamp=self.k * self.dt),
                positions=self.q, stamps=np.full(self.n, self.k * self.dt))
            pub.publish(rb.estimates_to_ros(est, FakeMsgs))

    def consume_distcmd(self):
        moved = 0.0
        for v, veh in enumerate(self.vehs):
            pub = self.ros.pubs[f"/{veh}/distcmd"]
            if not pub.published:
                continue
            cmd = pub.published[-1].vector
            u = np.array([cmd.x, cmd.y, cmd.z])
            self.vel[v] += (self.dt / self.tau) * (u - self.vel[v])
            moved = max(moved, float(np.abs(u).max()))
        self.q += self.vel * self.dt
        self.k += 1
        return moved


class TestLoopback:
    def _node(self, ros=None, **kw):
        vehs = ["SQ01s", "SQ02s", "SQ03s", "SQ04s"]
        ros = ros or FakeRospy(params={"/vehs": vehs})
        node = rb.run(ros, FakeMsgs, **kw)
        assert ros.node_name == "coordination_tpu"
        assert len(ros.timers) == 1      # the control timer owns step()
        return ros, node, vehs

    @pytest.mark.slow
    def test_formation_to_convergence_over_ros_graph(self):
        """The full SIL shape on a fake graph: operator publishes
        /formation, localization publishes vehicle_estimates, the TPU
        node publishes per-vehicle distcmd + assignment, vehicles fly to
        convergence."""
        ros, node, vehs = self._node(assign_every=50)
        fm = _wire_formation(gains="solve")
        rng = np.random.default_rng(4)
        q0 = np.asarray(fm.points)[rng.permutation(4)] \
            + rng.normal(scale=0.05, size=(4, 3)) + [3.0, 1.0, 0.0]
        swarm = _SwarmSide(ros, vehs, q0)

        # before any estimates: step publishes nothing (not ready)
        assert node.step() is None
        assert not ros.pubs["/SQ01s/distcmd"].published

        # operator dispatch through the REAL message layout
        ros.pubs.setdefault(
            "/formation", ros.Publisher("/formation", FakeMsgs.Formation))
        ros.pubs["/formation"].publish(
            rb.formation_to_ros(fm, FakeMsgs, stamp=Time(0.0)))

        for _ in range(1200):
            swarm.publish_estimates()
            node.step()
            swarm.consume_distcmd()
        # assignment published per vehicle as UInt8MultiArray
        asn = ros.pubs["/SQ03s/assignment"].published
        assert asn, "no assignment published"
        perm = rb.assignment_from_ros(asn[0])
        assert sorted(perm.tolist()) == list(range(4))
        # converged: the last distcmds are small
        last = ros.pubs["/SQ01s/distcmd"].published[-1].vector
        u = np.linalg.norm([[last.x, last.y, last.z]])
        assert u < 0.3, u
        # vehicles actually sit on an aligned square (pairwise distances)
        from scipy.spatial.distance import pdist
        got = np.sort(pdist(swarm.q))
        want = np.sort(pdist(np.asarray(fm.points)))
        np.testing.assert_allclose(got, want, atol=0.25)

    def test_kill_over_globalflightmode(self):
        ros, node, vehs = self._node(assign_every=10)
        fm = _wire_formation(gains="zeros")
        # stretched square: range errors drive the atan scale term, so the
        # command is nonzero even with zero linear gains
        swarm = _SwarmSide(ros, vehs, np.asarray(fm.points) * 1.6)
        pub_form = ros.Publisher("/formation", FakeMsgs.Formation)
        pub_mode = ros.Publisher("/globalflightmode",
                                 FakeMsgs.QuadFlightMode)
        pub_form.publish(rb.formation_to_ros(fm, FakeMsgs))
        swarm.publish_estimates()
        node.step()
        assert swarm.consume_distcmd() > 0.0
        kill = FakeMsgs.QuadFlightMode()
        kill.mode = FakeMsgs.QuadFlightMode.KILL
        pub_mode.publish(kill)
        swarm.publish_estimates()
        node.step()
        last = ros.pubs["/SQ02s/distcmd"].published[-1].vector
        assert last.x == last.y == last.z == 0.0    # e-stop cut this tick

    def test_central_assignment_param_path(self):
        """/operator/central_assignment true: the node subscribes
        /central_assignment and adopts the operator's pushed permutation
        instead of auctioning (`coordination_ros.cpp:46-51,330-343`)."""
        vehs = ["SQ01s", "SQ02s", "SQ03s", "SQ04s"]
        ros = FakeRospy(params={"/vehs": vehs,
                                "/operator/central_assignment": True})
        ros, node, vehs = self._node(ros=ros, assign_every=5)
        assert node.planner.central_assignment
        fm = _wire_formation(gains="zeros")
        rng = np.random.default_rng(9)
        swarm = _SwarmSide(ros, vehs,
                           np.asarray(fm.points)[rng.permutation(4)])
        ros.Publisher("/formation", FakeMsgs.Formation).publish(
            rb.formation_to_ros(fm, FakeMsgs))
        pub_central = ros.Publisher("/central_assignment",
                                    FakeMsgs.UInt8MultiArray)
        # no push yet -> no auction, no assignment ever
        for _ in range(8):
            swarm.publish_estimates()
            node.step()
            swarm.consume_distcmd()
        assert not ros.pubs["/SQ01s/assignment"].published
        pushed = np.array([1, 2, 3, 0], np.int32)
        pub_central.publish(rb.assignment_to_ros(pushed, FakeMsgs))
        got = None
        for _ in range(8):
            swarm.publish_estimates()
            got = node.step() or got
            swarm.consume_distcmd()
        assert got is not None
        np.testing.assert_array_equal(got.perm, pushed)
        np.testing.assert_array_equal(
            rb.assignment_from_ros(
                ros.pubs["/SQ04s/assignment"].published[-1]), pushed)

    def test_shm_backend_two_process_deployment(self):
        """The full deployment composition: fake-ROS graph -> adapter
        node -> ShmPlannerClient -> shm rings -> planner daemon
        subprocess -> back. One wire, two processes, real field layouts
        end to end."""
        import pathlib
        import subprocess
        import sys
        import time
        import uuid

        from aclswarm_tpu.interop.ros_bridge import ShmPlannerClient

        ns = f"/aswros-{uuid.uuid4().hex[:8]}"
        repo = str(pathlib.Path(__file__).resolve().parents[1])
        n = 4
        child = subprocess.Popen(
            [sys.executable, "-m", "aclswarm_tpu.interop.bridge",
             "--n", str(n), "--ns", ns, "--assign-every", "5",
             "--idle-timeout", "120"], cwd=repo)
        client = None
        try:
            client = ShmPlannerClient(n, ns, connect_timeout_s=60)
            vehs = ["SQ01s", "SQ02s", "SQ03s", "SQ04s"]
            ros = FakeRospy(params={"/vehs": vehs})
            node = rb.run(ros, FakeMsgs, planner=client)
            fm = _wire_formation(gains="zeros")
            rng = np.random.default_rng(21)
            swarm = _SwarmSide(ros, vehs,
                               np.asarray(fm.points)[rng.permutation(n)]
                               + [2.0, 1.0, 0.0])
            ros.Publisher("/formation", FakeMsgs.Formation).publish(
                rb.formation_to_ros(fm, FakeMsgs))
            got_asn = False
            deadline = time.time() + 120
            for k in range(40):
                swarm.publish_estimates()
                node.step()
                swarm.consume_distcmd()
                if ros.pubs["/SQ01s/assignment"].published:
                    got_asn = True
                    break
                if time.time() > deadline:
                    break
            assert got_asn, "no assignment made it through the composed " \
                            "ROS->shm->daemon path"
            perm = rb.assignment_from_ros(
                ros.pubs["/SQ01s/assignment"].published[0])
            assert sorted(perm.tolist()) == list(range(n))
            # distcmds flowed end-to-end
            assert ros.pubs["/SQ02s/distcmd"].published
        finally:
            if client is not None:
                fm = _wire_formation(gains=None, name="__shutdown__")
                client.handle_formation(fm)
                client.close()
            child.terminate()
            try:
                child.wait(timeout=30)
            except subprocess.TimeoutExpired:
                child.kill()
                child.wait(timeout=30)

    def test_on_commit_gain_solve_over_ros(self):
        """A Formation with empty gains triggers the on-device ADMM solve
        at commit (`coordination_ros.cpp:112-119`) — through the ROS
        layout's 'empty Float32MultiArray' convention."""
        ros, node, vehs = self._node(assign_every=10)
        fm = _wire_formation(gains=None)
        swarm = _SwarmSide(ros, vehs, np.asarray(fm.points) + 0.3)
        ros.Publisher("/formation", FakeMsgs.Formation).publish(
            rb.formation_to_ros(fm, FakeMsgs))
        swarm.publish_estimates()
        out = node.step()
        assert out is not None            # first auction published
        assert node.planner.formation is not None
        g = np.asarray(node.planner.formation.gains)
        assert np.any(g != 0.0)           # real solved gains


class TestRound5Additions:
    """Round-5 adapter behaviors: wide Int32 assignments, the explicit
    zero-cmd before a blocking commit, live rviz markers, and the
    per-vehicle (faithful) information model."""

    def test_assignment_wide_int32_roundtrip(self):
        perm = np.random.default_rng(5).permutation(300).astype(np.int32)
        ros = rb.assignment_to_ros(perm, FakeMsgs, wide=True)
        assert isinstance(ros, FakeMsgs.Int32MultiArray)
        np.testing.assert_array_equal(rb.assignment_from_ros(ros), perm)

    def test_wide_assignment_loopback_n300(self):
        """n=300 rides the ROS wire end-to-end: the adapter auto-widens
        to Int32MultiArray (the reference's uint8 wire caps at 255,
        `utils.h:25`)."""
        n = 300
        vehs = [f"SQ{i:03d}s" for i in range(n)]
        ros = FakeRospy(params={"/vehs": vehs})
        node = rb.run(ros, FakeMsgs, assign_every=5)
        assert node.wide_assignment
        rng = np.random.default_rng(7)
        pts = rng.uniform(-20, 20, size=(n, 3))
        adj = np.ones((n, n), np.uint8) - np.eye(n, dtype=np.uint8)
        fm = m.Formation(header=m.Header(), name="big", points=pts,
                         adjmat=adj,
                         gains=np.zeros((3 * n, 3 * n), np.float32))
        ros.Publisher("/formation", FakeMsgs.Formation).publish(
            rb.formation_to_ros(fm, FakeMsgs))
        q = pts[rng.permutation(n)]
        est = m.VehicleEstimates(header=m.Header(), positions=q,
                                 stamps=np.zeros(n))
        ros_est = rb.estimates_to_ros(est, FakeMsgs)
        for v in range(n):
            pub = ros.Publisher(f"/{vehs[v]}/vehicle_estimates",
                                FakeMsgs.VehicleEstimates)
            pub.publish(ros_est)
        out = node.step()
        assert out is not None
        asn = ros.pubs[f"/{vehs[0]}/assignment"].published
        assert asn and isinstance(asn[0], FakeMsgs.Int32MultiArray)
        perm = rb.assignment_from_ros(asn[0])
        assert sorted(perm.tolist()) == list(range(n))
        assert int(perm.max()) > 255      # actually exercises the width

    def test_zero_cmd_precedes_commit_solve(self):
        """On a formation commit the node publishes one explicit zero
        distcmd to every vehicle BEFORE blocking on the (possibly long)
        gain solve — the reference's stop-and-zero failsafe
        (`coordination_ros.cpp:102-106`)."""
        vehs = ["SQ01s", "SQ02s", "SQ03s", "SQ04s"]
        ros = FakeRospy(params={"/vehs": vehs})
        node = rb.run(ros, FakeMsgs)
        seen_at_commit = {}

        orig = node.planner.handle_formation

        def spying_commit(fm):
            for v in vehs:
                seen_at_commit[v] = list(ros.pubs[f"/{v}/distcmd"].published)
            return orig(fm)

        node.planner.handle_formation = spying_commit
        pts = np.array([[0.0, 0, 1], [2, 0, 1], [2, 2, 1], [0, 2, 1]])
        adj = np.ones((4, 4), np.uint8) - np.eye(4, dtype=np.uint8)
        fm = m.Formation(header=m.Header(), name="sq", points=pts,
                         adjmat=adj, gains=None)     # gains=None -> solve
        # estimates first, so the post-commit tick also publishes
        est = m.VehicleEstimates(header=m.Header(), positions=pts + 0.5,
                                 stamps=np.zeros(4))
        for v in vehs:
            ros.Publisher(f"/{v}/vehicle_estimates",
                          FakeMsgs.VehicleEstimates).publish(
                rb.estimates_to_ros(est, FakeMsgs))
        ros.Publisher("/formation", FakeMsgs.Formation).publish(
            rb.formation_to_ros(fm, FakeMsgs))
        node.step()
        for v in vehs:
            msgs_before = seen_at_commit[v]
            assert len(msgs_before) == 1      # the zero was already out
            vec = msgs_before[0].vector
            assert vec.x == vec.y == vec.z == 0.0
            # and the post-solve tick published the real command after it
            assert len(ros.pubs[f"/{v}/distcmd"].published) >= 2

    def test_viz_marker_traffic(self):
        """--viz publishes the reference viz node's MarkerArrays
        (`viz_commands.py:36-50`): distcmd arrows, aligned-formation
        spheres, quad meshes, and the operator's room bounds
        (`operator.py:248-292`)."""
        vehs = ["SQ01s", "SQ02s", "SQ03s", "SQ04s"]
        ros = FakeRospy(params={"/vehs": vehs})
        node = rb.run(ros, FakeMsgs, viz=True)
        # room bounds latched at construction (planner exposes sparams)
        room = ros.pubs["/operator/room_bounds"].published
        assert len(room) == 1 and len(room[0].markers) == 4
        assert all(mk.type == FakeMsgs.Marker.CUBE
                   for mk in room[0].markers)

        fm = _wire_formation(gains="zeros")
        ros.Publisher("/formation", FakeMsgs.Formation).publish(
            rb.formation_to_ros(fm, FakeMsgs))
        swarm = _SwarmSide(ros, vehs, np.asarray(fm.points) * 1.5)
        for _ in range(2):
            swarm.publish_estimates()
            node.step()
            swarm.consume_distcmd()
        arrows = ros.pubs["viz_dist_cmd"].published
        assert arrows, "no distcmd arrow MarkerArray traffic"
        arr = arrows[-1]
        assert len(arr.markers) == 4
        assert arr.markers[0].type == FakeMsgs.Marker.ARROW
        assert arr.markers[1].header.frame_id == "SQ02s"  # vehicle frame
        assert len(arr.markers[0].points) == 2            # origin -> 0.5u
        spheres = ros.pubs["viz_central_alignment"].published
        assert spheres and len(spheres[-1].markers) == 4
        assert spheres[-1].markers[0].type == FakeMsgs.Marker.SPHERE
        meshes = ros.pubs["viz_mesh"].published
        assert meshes
        assert meshes[-1].markers[0].mesh_resource.endswith("quadrotor.dae")

    def test_perveh_information_model_consumes_own_tables(self):
        """The faithful model: vehicle v's distcmd is computed from v's
        OWN flood-propagated estimate table, not the fused swarm state —
        biasing one vehicle's table visibly changes only the consumers of
        that table (ADVICE r4: like-for-like coordination-layer swap)."""
        def run_once(information_model, bias):
            vehs = ["SQ01s", "SQ02s", "SQ03s", "SQ04s"]
            ros = FakeRospy(params={"/vehs": vehs})
            node = rb.run(ros, FakeMsgs,
                          information_model=information_model)
            fm = _wire_formation(gains="solve")
            ros.Publisher("/formation", FakeMsgs.Formation).publish(
                rb.formation_to_ros(fm, FakeMsgs))
            q = np.asarray(fm.points) * 1.4
            pubs = [ros.Publisher(f"/{v}/vehicle_estimates",
                                  FakeMsgs.VehicleEstimates)
                    for v in vehs]
            for v, pub in enumerate(pubs):
                table = q.copy()
                if v == 0 and bias:
                    # vehicle 0's beliefs about OTHERS are stale/shifted;
                    # its self-estimate (the autopilot feed) stays exact
                    table[1:] += np.array([0.8, -0.4, 0.0])
                est = m.VehicleEstimates(header=m.Header(),
                                         positions=table,
                                         stamps=np.zeros(4))
                pub.publish(rb.estimates_to_ros(est, FakeMsgs))
            node.step()
            out = {}
            for v in vehs:
                vec = ros.pubs[f"/{v}/distcmd"].published[-1].vector
                out[v] = np.array([vec.x, vec.y, vec.z])
            return out

        clean = run_once("perveh", bias=False)
        biased = run_once("perveh", bias=True)
        fused = run_once("fused", bias=True)
        # under the faithful model the bias lives in vehicle 0's own view:
        # its command moves, the others' commands do not
        assert not np.allclose(clean["SQ01s"], biased["SQ01s"])
        for v in ("SQ02s", "SQ03s", "SQ04s"):
            np.testing.assert_allclose(clean[v], biased[v], atol=1e-6)
        # the fused model cannot see the bias at all (only self-estimates
        # feed it) — every vehicle behaves as in the clean run
        for v in ("SQ01s", "SQ02s", "SQ03s", "SQ04s"):
            np.testing.assert_allclose(fused[v], clean[v], atol=1e-6)

    def test_cbaa_with_perveh_tables_over_ros(self):
        """The fully-faithful mode on the ROS wire: decentralized CBAA
        auctions aligning on each vehicle's OWN estimate table (the
        round-5 perveh information model feeds `engine.assign`'s est
        path), closed-loop to convergence."""
        vehs = ["SQ01s", "SQ02s", "SQ03s", "SQ04s"]
        ros = FakeRospy(params={"/vehs": vehs})
        node = rb.run(ros, FakeMsgs, assignment="cbaa", assign_every=25)
        assert node._use_est
        fm = _wire_formation(gains="solve")
        rng = np.random.default_rng(13)
        q0 = np.asarray(fm.points)[rng.permutation(4)] \
            + rng.normal(scale=0.05, size=(4, 3)) + [2.0, -1.0, 0.0]
        swarm = _SwarmSide(ros, vehs, q0)
        ros.Publisher("/formation", FakeMsgs.Formation).publish(
            rb.formation_to_ros(fm, FakeMsgs))
        got = None
        for _ in range(800):
            swarm.publish_estimates()
            got = node.step() or got
            swarm.consume_distcmd()
        assert got is not None, "no CBAA assignment published"
        assert sorted(got.perm.tolist()) == list(range(4))
        last = ros.pubs["/SQ01s/distcmd"].published[-1].vector
        assert np.linalg.norm([last.x, last.y, last.z]) < 0.3
        from scipy.spatial.distance import pdist
        np.testing.assert_allclose(np.sort(pdist(swarm.q)),
                                   np.sort(pdist(np.asarray(fm.points))),
                                   atol=0.25)

    def test_cbaa_auction_consumes_est_tables(self):
        """The est path is observable in the AUCTION itself (not just the
        control law): a vehicle whose table disagrees with ground truth
        changes the CBAA outcome vs the truth-fed auction."""
        from aclswarm_tpu.interop.planner import TpuPlanner
        n = 4
        pts = np.array([[0.0, 0, 1], [4, 0, 1], [4, 4, 1], [0, 4, 1]])
        adj = np.ones((n, n)) - np.eye(n)
        planner = TpuPlanner(n, assignment="cbaa", assign_every=1)
        planner.handle_formation(m.Formation(
            header=m.Header(), name="sq", points=pts, adjmat=adj,
            gains=np.zeros((3 * n, 3 * n), np.float32)))
        # both v0 and v1 are nearest to formation point 0, v0 closer —
        # a CONTESTED task, so v0's bid strength decides the outcome
        # (uncontested geometries are provably robust to one agent's
        # table: the consensus hands every agent its unopposed task
        # regardless of its price — which is CBAA working as designed)
        q = np.array([[0.2, 0.2, 1.0], [0.9, 0.9, 1.0],
                      [4.0, 4.0, 1.0], [0.0, 4.0, 1.0]])
        truth_tbl = np.broadcast_to(q, (n, n, 3)).copy()
        out_truth = planner.tick(q, est=truth_tbl)
        # reset and rerun with vehicle 0 holding a NON-RIGID distortion
        # (rigid transforms would be absorbed by its local alignment):
        # it believes the others sit 10x away, so its aligned formation
        # lands far from it, its 1/(dist) bids collapse, and the
        # consensus outcome (a valid permutation under truth) must
        # change — mild distortions are absorbed by the other agents'
        # bids, which is itself the consensus working as designed
        planner.v2f = np.arange(n)
        planner._ticks_since_commit = 0
        planner._await_first_accept = True
        est = truth_tbl.copy()
        est[0, 1:] = est[0, 1:] * 10.0
        out_biased = planner.tick(q, est=est)
        # under truth v0 wins the contested point; with its collapsed
        # bids v1 takes it and v0 is pushed to point 1
        np.testing.assert_array_equal(out_truth.assignment, [0, 1, 2, 3])
        np.testing.assert_array_equal(out_biased.assignment, [1, 0, 2, 3])
