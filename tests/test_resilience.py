"""Resilient execution layer (aclswarm_tpu.resilience; docs/RESILIENCE.md).

The headline guarantee, proven here at every layer: a rollout
interrupted at a chunk boundary (exception or SIGKILL) and resumed from
its checkpoint produces BIT-IDENTICAL trajectories, summaries, and
invariant codes to an uninterrupted run — serial and B>=2 batched, with
and without a `FaultSchedule`. Plus: the checkpoint codec and its loud
manifest rejection (wrong config, wrong dtype, version skew, corrupt
file — never a silent restart-from-zero), the unified retry policy, and
the chunk executor's degrade-don't-die path.
"""
from __future__ import annotations

import dataclasses
import signal
import subprocess
import sys
from pathlib import Path

import numpy as np
import pytest

from aclswarm_tpu.resilience import checkpoint as ckptlib
from aclswarm_tpu.resilience import crash as crashlib
from aclswarm_tpu.resilience import (ChunkExecutor, CheckpointCorrupt,
                                     CheckpointMismatch, CrashPlan,
                                     InjectedCrash)
from aclswarm_tpu.utils import retry as retrylib

REPO = Path(__file__).resolve().parents[1]

pytestmark = pytest.mark.resilience


@pytest.fixture(autouse=True)
def _disarm_crash():
    yield
    crashlib.arm(None)


# ---------------------------------------------------------------- codec

class TestCodec:
    def _payload(self):
        return {"arrays": [np.arange(6, dtype=np.int32).reshape(2, 3),
                           np.asarray(2.5, np.float64),
                           np.ones((3,), bool)],
                "scalar": 7, "f": 0.1, "s": "x", "none": None,
                "nested": {"deep": [1, 2, {"a": np.float32(1.5)}]}}

    def test_roundtrip_bit_exact(self, tmp_path):
        p = ckptlib.write_checkpoint(
            tmp_path, "t", self._payload(),
            ckptlib.make_manifest("test", "h", chunk=3))
        payload, man = ckptlib.load_checkpoint(p)
        assert man["chunk"] == 3 and man["kind"] == "test"
        ref = self._payload()
        for a, b in zip(ref["arrays"], payload["arrays"]):
            assert a.dtype == b.dtype and a.shape == b.shape
            assert np.array_equal(a, b)
        assert payload["scalar"] == 7 and payload["f"] == 0.1
        assert payload["s"] == "x" and payload["none"] is None
        assert payload["nested"]["deep"][2]["a"] == np.float32(1.5)
        # atomic write leaves no temp file behind
        assert not list(tmp_path.glob("*.tmp"))

    def test_truncation_and_corruption_raise(self, tmp_path):
        p = ckptlib.write_checkpoint(
            tmp_path, "t", self._payload(),
            ckptlib.make_manifest("test", "h", chunk=1))
        buf = p.read_bytes()
        p.write_bytes(buf[:len(buf) // 2])
        with pytest.raises(CheckpointCorrupt):
            ckptlib.load_checkpoint(p)
        flipped = bytearray(buf)
        flipped[len(buf) // 2] ^= 0xFF
        p.write_bytes(bytes(flipped))
        with pytest.raises(CheckpointCorrupt):
            ckptlib.load_checkpoint(p)
        p.write_bytes(b"\x00" * 64)
        with pytest.raises(CheckpointCorrupt, match="magic"):
            ckptlib.load_checkpoint(p)

    def test_retention_bounded_and_latest(self, tmp_path):
        for c in range(5):
            ckptlib.write_checkpoint(
                tmp_path, "t", {"c": c},
                ckptlib.make_manifest("test", "h", chunk=c), keep=2)
        left = sorted(tmp_path.glob("t.c*.ckpt"))
        assert len(left) == 2
        latest = ckptlib.latest_checkpoint(tmp_path, "t")
        payload, man = ckptlib.load_checkpoint(latest)
        assert man["chunk"] == 4 and payload["c"] == 4
        assert ckptlib.clear_checkpoints(tmp_path, "t") == 2
        assert ckptlib.latest_checkpoint(tmp_path, "t") is None


# ------------------------------------------------- manifest rejection

class TestManifestRejection:
    """Each wrong-checkpoint class fails LOUDLY with the offending
    fields — never a silent restart-from-zero (satellite #3)."""

    def _write(self, tmp_path, **over):
        man = ckptlib.make_manifest("trial", "confhash", chunk=2, trial=0)
        man.update(over)
        return ckptlib.write_checkpoint(tmp_path, "t", {"x": 1}, man)

    def _expect(self, **over):
        e = ckptlib.expected_manifest("trial", "confhash", trial=0)
        e.update(over)
        return e

    def test_wrong_config_hash(self, tmp_path):
        p = self._write(tmp_path)
        with pytest.raises(CheckpointMismatch) as ei:
            ckptlib.load_checkpoint(p, self._expect(config_hash="other"))
        assert [m[0] for m in ei.value.mismatches] == ["config_hash"]

    def test_wrong_dtype_fingerprint(self, tmp_path):
        p = self._write(tmp_path, dtype="x64=False,float=float32")
        with pytest.raises(CheckpointMismatch) as ei:
            ckptlib.load_checkpoint(p, self._expect())
        assert [m[0] for m in ei.value.mismatches] == ["dtype"]

    def test_version_skew(self, tmp_path):
        p = self._write(tmp_path, code_version="0.0.0-older")
        with pytest.raises(CheckpointMismatch) as ei:
            ckptlib.load_checkpoint(p, self._expect())
        assert [m[0] for m in ei.value.mismatches] == ["code_version"]

    def test_wrong_kind(self, tmp_path):
        p = self._write(tmp_path)
        with pytest.raises(CheckpointMismatch):
            ckptlib.load_checkpoint(
                p, ckptlib.expected_manifest("trial_batch", "confhash"))

    def test_restore_tree_validates_leaves(self):
        import jax.numpy as jnp
        template = {"a": jnp.zeros((2, 3)), "b": jnp.zeros((), jnp.int32)}
        good = [np.ones((2, 3)), np.asarray(5, np.int32)]
        out = ckptlib.restore_tree(template, good)
        assert np.array_equal(np.asarray(out["a"]), np.ones((2, 3)))
        with pytest.raises(CheckpointMismatch, match="n_leaves"):
            ckptlib.restore_tree(template, good[:1])
        with pytest.raises(CheckpointMismatch, match="dtype"):
            ckptlib.restore_tree(
                template, [np.ones((2, 3)), np.asarray(5, np.int64)])
        with pytest.raises(CheckpointMismatch, match="shape"):
            ckptlib.restore_tree(
                template, [np.ones((2, 4)), np.asarray(5, np.int32)])
        # batch_flex relaxes ONLY the leading axis
        flexed = ckptlib.restore_tree(
            template, [np.ones((1, 3)), np.asarray(5, np.int32)],
            batch_flex=True)
        assert flexed["a"].shape == (1, 3)
        with pytest.raises(CheckpointMismatch, match="shape"):
            ckptlib.restore_tree(
                template, [np.ones((2, 4)), np.asarray(5, np.int32)],
                batch_flex=True)


# -------------------------------------------- append log (torn tail)

class TestFrameLog:
    """The append-only frame log's recovery contract (docs/RESILIENCE
    .md): a truncated/CRC-failing TRAILING record is a crash mid-append
    and reads as clean EOF; any non-trailing corruption raises
    `CheckpointCorrupt` loudly."""

    def _log(self, path, n=3):
        for i in range(n):
            ckptlib.append_frame(
                path, {"i": i, "blob": np.arange(4) + i},
                ckptlib.make_manifest("ev", "-", chunk=i, event="e"))
        return path.read_bytes()

    def test_roundtrip_and_clean_eof(self, tmp_path):
        p = tmp_path / "events.log"
        self._log(p, 3)
        frames, torn = ckptlib.read_frame_log(p)
        assert not torn and len(frames) == 3
        assert [m["chunk"] for _, m in frames] == [0, 1, 2]
        assert np.array_equal(frames[2][0]["blob"], np.arange(4) + 2)
        empty = tmp_path / "empty.log"
        empty.write_bytes(b"")
        assert ckptlib.read_frame_log(empty) == ([], False)

    @pytest.mark.parametrize("cut", [1, 2, 3])
    def test_truncated_tail_is_clean_eof(self, tmp_path, cut):
        """Byte-level truncation anywhere inside the LAST record —
        mid-length-prefix, mid-header, mid-body — drops exactly that
        record and flags the torn tail."""
        p = tmp_path / "events.log"
        buf = self._log(p, 3)
        # find the start of record 3: re-read 2-record log length
        p2 = tmp_path / "two.log"
        two = self._log(p2, 2)
        offsets = {1: len(two) + 2,          # inside record 3's prefix
                   2: len(two) + 6,          # inside its frame header
                   3: len(buf) - 3}          # inside its body/CRC
        p.write_bytes(buf[:offsets[cut]])
        frames, torn = ckptlib.read_frame_log(p)
        assert torn and len(frames) == 2
        assert [m["chunk"] for _, m in frames] == [0, 1]

    def test_crc_failing_trailing_record_is_clean_eof(self, tmp_path):
        p = tmp_path / "events.log"
        buf = bytearray(self._log(p, 3))
        buf[-1] ^= 0xFF                      # corrupt the LAST record
        p.write_bytes(bytes(buf))
        frames, torn = ckptlib.read_frame_log(p)
        assert torn and len(frames) == 2

    def test_non_trailing_corruption_raises_loudly(self, tmp_path):
        p = tmp_path / "events.log"
        buf = bytearray(self._log(p, 3))
        two = self._log(tmp_path / "two.log", 2)
        buf[len(two) - 8] ^= 0xFF            # corrupt record 2's body
        p.write_bytes(bytes(buf))
        with pytest.raises(CheckpointCorrupt, match="non-trailing"):
            ckptlib.read_frame_log(p)

    def test_serve_recovery_tolerates_torn_events_log(self, tmp_path):
        """End to end: a serve journal whose events.log ends mid-append
        recovers cleanly (counters from the intact records), while
        mid-log corruption fails recovery loudly."""
        from aclswarm_tpu.serve import ServiceConfig, SwarmService

        log = tmp_path / "events.log"
        for i in range(3):
            ckptlib.append_frame(
                log, {"request_id": f"r{i}", "dead_worker": "0.1",
                      "chunk": i},
                ckptlib.make_manifest("serve_event", "-", chunk=0,
                                      event="requeue", t_wall=0.0))
        buf = log.read_bytes()
        log.write_bytes(buf[:-5])            # torn trailing append
        svc = SwarmService(ServiceConfig(journal_dir=str(tmp_path)),
                           start=False)
        assert svc.stats["requeued"] == 2    # intact records recovered
        svc.close(drain=False)
        # non-trailing corruption: recovery must NOT silently continue
        # (byte 30 sits in record 0's CRC-covered body; the reserved
        # header bytes are deliberately NOT covered)
        bad = bytearray(buf)
        bad[30] ^= 0xFF
        log.write_bytes(bytes(bad))
        with pytest.raises(CheckpointCorrupt):
            SwarmService(ServiceConfig(journal_dir=str(tmp_path)),
                         start=False)


# ------------------------------------------- swarmtrace manifest carriage


class TestTraceManifests:
    """The trace_id's survival vehicle is the checkpoint manifest
    (docs/OBSERVABILITY.md §swarmtrace): it must round-trip the codec
    bit-exactly, ride `write_checkpoint`/`load_checkpoint` retention,
    and coexist with the manifest-validation contract (an expected
    subset that does NOT name trace_id must still accept the frame —
    a pre-trace resumer can read a traced checkpoint)."""

    def test_trace_id_roundtrips_the_codec_and_files(self, tmp_path):
        man = ckptlib.make_manifest("serve_rollout", "cfg", chunk=2,
                                    request_id="r1",
                                    trace_id="feedbeefcafe0001")
        payload = {"state": [np.arange(3.0)], "crc": 7}
        _, got = ckptlib.loads(ckptlib.dumps(payload, man))
        assert got["trace_id"] == "feedbeefcafe0001"
        ckptlib.write_checkpoint(tmp_path, "req_r1", payload, man)
        path = ckptlib.latest_checkpoint(tmp_path, "req_r1")
        _, man2 = ckptlib.load_checkpoint(
            path, expected=ckptlib.expected_manifest(
                "serve_rollout", "cfg", request_id="r1"))
        assert man2["trace_id"] == "feedbeefcafe0001"
        # a WRONG expected trace_id still rejects loudly
        with pytest.raises(ckptlib.CheckpointMismatch, match="trace_id"):
            ckptlib.load_checkpoint(path, expected={
                "trace_id": "0000000000000000"})

    def test_lifecycle_events_share_the_frame_log_contract(
            self, tmp_path):
        """The swarmtrace stream IS a frame log: a torn tail reads as
        clean EOF (crash mid-append loses at most one record), and
        mid-log corruption still raises — the same recovery semantics
        the serve journal's worker ledger proved in PR 8."""
        from aclswarm_tpu.telemetry import LifecycleLog

        p = tmp_path / "events.log"
        log = LifecycleLog(p)
        log.emit("submitted", request_id="r1", trace_id="t1",
                 kind="rollout", tenant="a")
        log.emit("chunk", request_id="r1", trace_id="t1", k=0,
                 digest=1, worker=0)
        log.emit("resolved", request_id="r1", trace_id="t1",
                 status="completed", chunks=1)
        rows, torn = LifecycleLog.read(p)
        assert not torn and len(rows) == 3
        # torn tail
        buf = p.read_bytes()
        p.write_bytes(buf[:-5])
        rows, torn = LifecycleLog.read(p)
        assert torn and [r["event"] for r in rows] \
            == ["submitted", "chunk"]
        # mid-log corruption is NOT skippable
        bad = bytearray(buf)
        bad[30] ^= 0xFF
        p.write_bytes(bytes(bad))
        with pytest.raises(ckptlib.CheckpointCorrupt,
                           match="non-trailing"):
            LifecycleLog.read(p)


# ------------------------------------------------ multi-plan crash arming

class TestMultiPlanArming:
    def test_decode_many_and_each_plan_one_shot(self):
        plans = CrashPlan.decode_many("serve.w0:2:raise,serve.w1:5")
        assert plans == [CrashPlan("serve.w0", 2, "raise"),
                         CrashPlan("serve.w1", 5, "raise")]
        crashlib.arm_many(plans)
        crashlib.maybe_crash("serve.w0", 1)      # no match: no-op
        with pytest.raises(InjectedCrash):
            crashlib.maybe_crash("serve.w0", 2)
        # consuming one plan leaves the OTHER armed (repeated kills)
        crashlib.maybe_crash("serve.w0", 2)      # spent: no-op
        with pytest.raises(InjectedCrash):
            crashlib.maybe_crash("serve.w1", 5)
        assert crashlib.active_plans() == []

    def test_env_multi_plan_consumed_one_at_a_time(self, monkeypatch):
        monkeypatch.setenv(crashlib.ENV_VAR, "a:1:raise,b:2:raise")
        with pytest.raises(InjectedCrash):
            crashlib.maybe_crash("a", 1)
        # only the matched spec was removed from the env
        import os
        assert os.environ[crashlib.ENV_VAR] == "b:2:raise"
        with pytest.raises(InjectedCrash):
            crashlib.maybe_crash("b", 2)
        assert crashlib.ENV_VAR not in os.environ

    def test_single_plan_api_unchanged(self):
        crashlib.arm(CrashPlan("t", 1))
        assert crashlib.active_plan() == CrashPlan("t", 1)
        crashlib.arm(None)
        assert crashlib.active_plan() is None


# ----------------------------------------------------------- retry layer

class TestRetry:
    def test_deterministic_jitter(self):
        pol = retrylib.RetryPolicy(base_s=0.1, factor=2.0, max_s=1.0,
                                   jitter=0.5, seed=3)
        d = [retrylib.delay_for(pol, k) for k in range(4)]
        assert d == [retrylib.delay_for(pol, k) for k in range(4)]
        assert d[1] > d[0] and all(x <= 1.5 for x in d)
        other = dataclasses.replace(pol, seed=4)
        assert [retrylib.delay_for(other, k) for k in range(4)] != d

    def test_retry_call_retries_then_succeeds(self):
        calls, slept = [], []

        def flaky():
            calls.append(1)
            if len(calls) < 3:
                raise RuntimeError("UNAVAILABLE: try again")
            return "ok"

        out = retrylib.retry_call(
            flaky, policy=retrylib.RetryPolicy(attempts=4),
            sleep=slept.append)
        assert out == "ok" and len(calls) == 3 and len(slept) == 2

    def test_retry_call_exhausts_and_respects_predicate(self):
        def always(): raise RuntimeError("UNAVAILABLE")
        with pytest.raises(RuntimeError):
            retrylib.retry_call(
                always, policy=retrylib.RetryPolicy(attempts=3),
                sleep=lambda s: None)

        calls = []

        def bug():
            calls.append(1)
            raise ValueError("a plain bug")

        with pytest.raises(ValueError):
            retrylib.retry_call(
                bug, policy=retrylib.RetryPolicy(attempts=5),
                retryable=lambda e: "UNAVAILABLE" in str(e),
                sleep=lambda s: None)
        assert len(calls) == 1          # non-retryable: no second try

    def test_budget_cap(self):
        clock = [0.0]

        def always(): raise RuntimeError("x")
        with pytest.raises(RuntimeError):
            retrylib.retry_call(
                always,
                policy=retrylib.RetryPolicy(attempts=100, base_s=10.0,
                                            budget_s=5.0),
                clock=lambda: clock[0], sleep=lambda s: None)

    def test_poll_until(self):
        clock = [0.0]
        state = {"n": 0}

        def tick(s):
            clock[0] += s

        def ready():
            state["n"] += 1
            return state["n"] >= 3

        assert retrylib.poll_until(ready, grace_s=10.0, poll_s=1.0,
                                   clock=lambda: clock[0], sleep=tick)
        state["n"] = -10**9
        clock[0] = 0.0
        assert not retrylib.poll_until(ready, grace_s=3.0, poll_s=1.0,
                                       clock=lambda: clock[0], sleep=tick)

    def test_poll_until_never_overshoots_deadline(self):
        """Regression (PR 8): the deadline is computed once from the
        monotonic clock and the FINAL sleep is capped to the remaining
        budget — a poll interval larger than the grace must not
        overshoot (the old loop slept the full poll_s past the
        boundary: grace_s=0.01 with poll_s=1.0 waited ~1 s)."""
        clock = [0.0]
        sleeps: list[float] = []

        def tick(s):
            sleeps.append(s)
            clock[0] += s

        assert not retrylib.poll_until(
            lambda: False, grace_s=0.01, poll_s=1.0,
            clock=lambda: clock[0], sleep=tick)
        assert clock[0] == pytest.approx(0.01)     # not 1.0
        assert sleeps == [pytest.approx(0.01)]     # capped final sleep

        # a poll_s that does not divide the grace: last sleep is the
        # exact remainder, total wait == grace
        clock[0] = 0.0
        sleeps.clear()
        assert not retrylib.poll_until(
            lambda: False, grace_s=2.5, poll_s=1.0,
            clock=lambda: clock[0], sleep=tick)
        assert sleeps == [1.0, 1.0, pytest.approx(0.5)]
        assert clock[0] == pytest.approx(2.5)

        # the cancel-event path caps the final wait identically
        import threading

        class _Ev(threading.Event):
            def __init__(self, log):
                super().__init__()
                self._log = log

            def wait(self, t=None):
                self._log.append(t)
                clock[0] += t
                return False

        waits: list[float] = []
        clock[0] = 0.0
        assert not retrylib.poll_until(
            lambda: False, grace_s=0.25, poll_s=1.0,
            clock=lambda: clock[0], cancel=_Ev(waits))
        assert waits == [pytest.approx(0.25)]

    def test_watchdog_finish_vs_fire_atomic(self):
        fired = []
        wd = retrylib.Watchdog(on_fire=lambda: fired.append(1))
        assert wd.finish() is True
        wd.fire()                       # finished first: must be a no-op
        assert fired == []
        wd2 = retrylib.Watchdog(on_fire=lambda: fired.append(1))
        wd2.fire()
        assert fired == [1]
        assert wd2.finish() is False    # the fire claimed completion:
        #                                 the caller must NOT also emit
        #                                 its result (one-output rule)
        wd2.fire()                      # and a second fire is a no-op
        assert fired == [1]
        # an on_fire that itself calls finish() must not deadlock
        wd3 = retrylib.Watchdog(on_fire=lambda: wd3.finish())
        wd3.fire()

    def test_retry_call_cancel_before_first_attempt(self):
        import threading
        ev = threading.Event()
        ev.set()
        calls = []
        with pytest.raises(retrylib.RetryCancelled):
            retrylib.retry_call(lambda: calls.append(1), cancel=ev)
        assert not calls                # never even tried

    def test_retry_call_cancel_interrupts_backoff_budget(self):
        """Cancellation lands DURING the backoff sleep: the in-flight
        budget ends immediately (event wait, not time.sleep) and the
        real failure surfaces — no further attempts (the serve
        deadline/shutdown teardown path)."""
        import threading
        import time as _time
        ev = threading.Event()
        calls = []

        def flaky():
            calls.append(1)
            raise RuntimeError("UNAVAILABLE")

        t0 = _time.monotonic()
        with pytest.raises(RuntimeError, match="UNAVAILABLE"):
            retrylib.retry_call(
                flaky,
                policy=retrylib.RetryPolicy(attempts=5, base_s=30.0,
                                            jitter=0.0),
                on_retry=lambda a, e: ev.set(),   # cancel mid-backoff
                cancel=ev)
        assert len(calls) == 1          # the 30 s backoff never ran out
        assert _time.monotonic() - t0 < 5.0

    def test_poll_until_cancel(self):
        import threading
        ev = threading.Event()
        ev.set()
        assert not retrylib.poll_until(lambda: True, grace_s=10.0,
                                       cancel=ev)   # pre-cancelled
        ev2 = threading.Event()
        threading.Timer(0.05, ev2.set).start()
        t0 = retrylib.time.monotonic()
        assert not retrylib.poll_until(lambda: False, grace_s=30.0,
                                       poll_s=0.01, cancel=ev2)
        assert retrylib.time.monotonic() - t0 < 5.0

    def test_watchdog_rearm_replaces_timer(self):
        """Re-arming cancels the prior timer (no stale fire) and a
        resolved watchdog refuses to re-arm — the serve layer arms per
        request from client threads."""
        import time as _time
        fired = []
        wd = retrylib.Watchdog(on_fire=lambda: fired.append(1))
        wd.arm(0.05)
        wd.arm(30.0)                    # replaces: the 0.05 s timer dies
        _time.sleep(0.2)
        assert fired == []
        assert wd.finish() is True
        wd.arm(0.01)                    # after resolution: a no-op
        _time.sleep(0.1)
        assert fired == []

    def test_watchdog_concurrent_finish_vs_fire_single_winner(self):
        """Hammer fire/finish from many threads: exactly ONE side ever
        wins (the one-output contract under real races)."""
        import threading
        for _ in range(20):
            fired = []
            wd = retrylib.Watchdog(on_fire=lambda: fired.append(1))
            wins = []
            threads = (
                [threading.Thread(target=wd.fire) for _ in range(4)]
                + [threading.Thread(
                    target=lambda: wins.append(wd.finish()))
                   for _ in range(4)])
            for t in threads:
                t.start()
            for t in threads:
                t.join()
            assert len(fired) + sum(wins) == 1

    def test_failure_record_matches_checker_schema(self):
        sys.path.insert(0, str(REPO / "benchmarks"))
        import check_results
        row = retrylib.ExecutionFailure(stage="s", error="e").to_row()
        assert set(row) <= check_results._FAILURE_ALLOWED
        assert check_results._FAILURE_REQUIRED <= set(row)


# -------------------------------------------------------- chunk executor

class TestChunkExecutor:
    def test_transient_retry_then_success(self):
        ex = ChunkExecutor(policy=retrylib.RetryPolicy(
            attempts=3, base_s=0.0, jitter=0.0))
        calls = []

        def flaky():
            calls.append(1)
            if len(calls) < 2:
                raise RuntimeError("DEADLINE exceeded through tunnel")
            return 42

        assert ex.run(flaky, stage="t") == 42
        assert ex.retries == 1 and not ex.degraded and not ex.failures
        assert ex.row_fields() == {"retries": 1}

    def test_nontransient_and_injected_crash_pass_through(self):
        ex = ChunkExecutor()
        with pytest.raises(ValueError):
            ex.run(lambda: (_ for _ in ()).throw(ValueError("bug")))
        with pytest.raises(InjectedCrash):
            ex.run(lambda: (_ for _ in ()).throw(InjectedCrash("kill")))
        assert not ex.retries and not ex.degraded

    def test_cpu_fallback_is_loud_and_recorded(self):
        pol = retrylib.RetryPolicy(attempts=2, base_s=0.0, jitter=0.0)
        ex = ChunkExecutor(policy=pol)
        calls = []

        def dies_on_device():
            calls.append(1)
            if len(calls) <= pol.attempts:
                raise RuntimeError("UNAVAILABLE: device wedged")
            return "cpu result"

        assert ex.run(dies_on_device, stage="chunk3") == "cpu result"
        assert ex.degraded and ex.retries == pol.attempts - 1
        [fail] = ex.failures
        assert fail.fallback == "cpu" and fail.stage == "chunk3"
        fields = ex.row_fields()
        assert fields["degraded"] is True
        assert fields["execution_failures"][0]["fallback"] == "cpu"

    def test_cancelled_stage_gets_no_cpu_fallback(self):
        """A torn-down request (deadline/shutdown) must surface its
        failure immediately: no remaining retries, no CPU fallback."""
        import threading
        ev = threading.Event()
        ex = ChunkExecutor(policy=retrylib.RetryPolicy(
            attempts=4, base_s=10.0, jitter=0.0))
        calls = []

        def dies():
            calls.append(1)
            ev.set()                    # teardown lands mid-flight
            raise RuntimeError("UNAVAILABLE: device wedged")

        with pytest.raises(RuntimeError, match="UNAVAILABLE"):
            ex.run(dies, stage="t", cancel=ev)
        assert len(calls) == 1 and not ex.degraded
        ev2 = threading.Event()
        ev2.set()
        with pytest.raises(retrylib.RetryCancelled):
            ex.run(lambda: 1, cancel=ev2)

    def test_deleted_buffer_not_retried(self):
        ex = ChunkExecutor()
        calls = []

        def donated():
            calls.append(1)
            raise RuntimeError("Array has been deleted with shape=f32[4]")

        with pytest.raises(RuntimeError, match="deleted"):
            ex.run(donated)
        assert len(calls) == 1


# --------------------------------------- engine-level resume equivalence

def _leaves(tree):
    import jax
    return [np.asarray(x) for x in jax.tree.leaves(tree)]


def _assert_trees_equal(a, b, what=""):
    la, lb = _leaves(a), _leaves(b)
    assert len(la) == len(lb), what
    for i, (x, y) in enumerate(zip(la, lb)):
        assert x.dtype == y.dtype, (what, i)
        np.testing.assert_array_equal(x, y, err_msg=f"{what} leaf {i}")


def _engine_problem(n=5, faults=False, checks=False, telemetry=False):
    import jax.numpy as jnp

    from aclswarm_tpu import sim
    from aclswarm_tpu.core.types import (ControlGains, SafetyParams,
                                         make_formation)
    from aclswarm_tpu.faults import sample_schedule
    rng = np.random.default_rng(0)
    ang = np.linspace(0, 2 * np.pi, n, endpoint=False)
    pts = np.stack([3 * np.cos(ang), 3 * np.sin(ang), np.full(n, 2.0)], 1)
    form = make_formation(
        jnp.asarray(pts), jnp.asarray(np.ones((n, n)) - np.eye(n)),
        jnp.asarray(np.eye(n)[:, :, None, None]
                    * np.eye(3)[None, None] * 0.01))
    sp = SafetyParams(bounds_min=jnp.asarray([-50.0, -50.0, 0.0]),
                      bounds_max=jnp.asarray([50.0, 50.0, 10.0]))
    sched = sample_schedule(7, n, dropout_frac=0.4, drop_tick=5,
                            rejoin_tick=25, link_loss=0.2,
                            dtype=jnp.asarray(pts).dtype) if faults \
        else None
    st = sim.init_state(rng.normal(size=(n, 3)) * 2.0 + [0, 0, 2.0],
                        faults=sched, checks=checks, telemetry=telemetry)
    if telemetry:
        # seed the driver-set leaves too: the resume proof must cover a
        # non-trivial float residual, not just zeroed counters
        st = st.replace(tel=st.tel.replace(
            admm_iters=jnp.asarray(7, jnp.int32),
            admm_residual=jnp.asarray(0.1231, st.swarm.q.dtype)))
    cfg = sim.SimConfig(assignment="auction", assign_every=10,
                        check_mode="on" if checks else "off",
                        telemetry="on" if telemetry else "off")
    return st, form, ControlGains(), sp, cfg


@pytest.mark.parametrize("faults,checks,telemetry",
                         [(False, False, False), (True, False, False),
                          (True, True, False), (True, False, True)])
def test_engine_chunked_resume_bit_identical(tmp_path, faults, checks,
                                             telemetry):
    """Serial rollout: save/load at a chunk boundary reproduces the
    remaining chunks' trajectories (q in StepMetrics), summaries,
    invariant codes, and swarmscope chunk counters (auction rounds,
    churn, ADMM iters/residual) bit-exactly — with and without a
    FaultSchedule."""
    import jax

    from aclswarm_tpu import sim
    st0, form, cg, sp, cfg = _engine_problem(faults=faults, checks=checks,
                                             telemetry=telemetry)
    chunk, cut, total = 10, 2, 4

    state = st0
    ref = []
    for k in range(total):
        state, m = sim.rollout(state, form, cg, sp, cfg, chunk)
        ref.append(jax.tree.map(np.asarray, m))
        if k == cut - 1:
            ckptlib.write_checkpoint(
                tmp_path, "eng", {"state": ckptlib.tree_arrays(state)},
                ckptlib.make_manifest("eng", "h", chunk=k + 1))
    final_ref = state

    payload, man = ckptlib.load_checkpoint(
        ckptlib.latest_checkpoint(tmp_path, "eng"),
        expected=ckptlib.expected_manifest("eng", "h"))
    state = ckptlib.restore_tree(st0, payload["state"], what="SimState")
    for k in range(int(man["chunk"]), total):
        state, m = sim.rollout(state, form, cg, sp, cfg, chunk)
        _assert_trees_equal(m, ref[k], f"metrics chunk {k}")
    _assert_trees_equal(state, final_ref, "final state")


@pytest.mark.parametrize("faults,telemetry", [(False, False),
                                              (True, False),
                                              (True, True)])
def test_batched_summary_resume_bit_identical(tmp_path, faults, telemetry):
    """Batched (B=2, per-trial fault scripts) fused rollout+summary:
    (state, carry) checkpoint round trip reproduces the remaining
    chunks' ChunkSummary — including the per-trial swarmscope counter
    snapshots — bit-exactly."""
    import jax
    import jax.numpy as jnp

    from aclswarm_tpu.faults import no_faults, sample_schedule
    from aclswarm_tpu.sim import summary as sumlib

    sts, forms = [], []
    for b in range(2):
        st, form, cg, sp, cfg = _engine_problem(telemetry=telemetry)
        if faults:
            dtype = st.swarm.q.dtype
            sched = sample_schedule(b + 1, 5, dropout_frac=0.4,
                                    drop_tick=3 + b, rejoin_tick=20,
                                    link_loss=0.1, dtype=dtype) \
                if b else no_faults(5, dtype)
            st = st.replace(faults=sched)
        sts.append(st)
        forms.append(form)
    bstate = jax.tree.map(lambda *xs: jnp.stack(xs), *sts)
    bform = jax.tree.map(lambda *xs: jnp.stack(xs), *forms)
    window = 5
    carry0 = sumlib.init_carry(5, window, dtype=bstate.swarm.q.dtype,
                               batch=2)
    alt = jnp.asarray(2.0, bstate.swarm.q.dtype)
    chunk, cut, total = 10, 1, 3

    state, carry = bstate, carry0
    ref = []
    for k in range(total):
        state, carry, summ = sumlib.batched_rollout_summary(
            state, carry, bform, cg, sp, cfg, chunk, None, 0,
            window=window, takeoff_alt=alt)
        ref.append(jax.tree.map(np.asarray, summ))
        if k == cut - 1:
            ckptlib.write_checkpoint(
                tmp_path, "bat",
                {"state": ckptlib.tree_arrays(state),
                 "carry": ckptlib.tree_arrays(carry)},
                ckptlib.make_manifest("bat", "h", chunk=k + 1))
    final_ref = state

    # donation consumed the originals: rebuild fresh templates
    sts2 = [s for s in sts]
    bstate2 = jax.tree.map(lambda *xs: jnp.stack(xs), *sts2)
    carry_t = sumlib.init_carry(5, window, dtype=bstate2.swarm.q.dtype,
                                batch=2)
    payload, man = ckptlib.load_checkpoint(
        ckptlib.latest_checkpoint(tmp_path, "bat"),
        expected=ckptlib.expected_manifest("bat", "h"))
    state = ckptlib.restore_tree(bstate2, payload["state"],
                                 batch_flex=True, what="SimState")
    carry = ckptlib.restore_tree(carry_t, payload["carry"],
                                 batch_flex=True, what="SummaryCarry")
    for k in range(int(man["chunk"]), total):
        state, carry, summ = sumlib.batched_rollout_summary(
            state, carry, bform, cg, sp, cfg, chunk, None, 0,
            window=window, takeoff_alt=alt)
        _assert_trees_equal(summ, ref[k], f"summary chunk {k}")
    _assert_trees_equal(state, final_ref, "final batched state")


# --------------------------------------- driver-level resume equivalence

def _fsm_signature(fsm, t):
    return (fsm.state, fsm.tick_count, fsm.times, fsm.time_avoidance,
            fsm.assignments, fsm.csv_row(t))


class TestTrialDriverResume:
    CFG = dict(formation="simform6", trials=1, seed=1, verbose=False,
               out="/dev/null")

    def test_serial_crash_resume_bit_identical(self, tmp_path):
        from aclswarm_tpu.harness import trials as triallib
        ref = triallib.run_trial(triallib.TrialConfig(**self.CFG), 0)

        cfg = triallib.TrialConfig(checkpoint_dir=str(tmp_path),
                                   checkpoint_every=1, **self.CFG)
        crashlib.arm(CrashPlan("trial", 2))
        with pytest.raises(InjectedCrash):
            triallib.run_trial(cfg, 0)
        assert ckptlib.latest_checkpoint(tmp_path, "trial00000")
        resumed = triallib.run_trial(cfg, 0)
        assert resumed.completed == ref.completed
        assert _fsm_signature(resumed, 0) == _fsm_signature(ref, 0)
        np.testing.assert_array_equal(resumed.dist, ref.dist)
        # finished: interim checkpoints pruned (bounded retention)
        assert ckptlib.latest_checkpoint(tmp_path, "trial00000") is None

    @pytest.mark.slow
    def test_batch_crash_resume_bit_identical(self, tmp_path):
        from aclswarm_tpu.harness import trials as triallib
        base = dict(self.CFG, trials=2, batch=2, chunk_ticks=120)
        refs = triallib.run_trial_batch(triallib.TrialConfig(**base),
                                        [0, 1])

        cfg = triallib.TrialConfig(checkpoint_dir=str(tmp_path),
                                   checkpoint_every=1, **base)
        crashlib.arm(CrashPlan("batch", 2))
        with pytest.raises(InjectedCrash):
            triallib.run_trial_batch(cfg, [0, 1])
        resumed = triallib.run_trial_batch(cfg, [0, 1])
        for t, (a, b) in enumerate(zip(resumed, refs)):
            assert a.completed == b.completed
            assert _fsm_signature(a, t) == _fsm_signature(b, t), t
            np.testing.assert_array_equal(a.dist, b.dist)

    def test_run_trials_resume_skips_done_and_dedupes_csv(self, tmp_path):
        from aclswarm_tpu.harness import trials as triallib
        out_ref = tmp_path / "ref.csv"
        cfg_ref = triallib.TrialConfig(
            **dict(self.CFG, trials=2, out=str(out_ref)))
        triallib.run_trials(cfg_ref)
        ref_rows = out_ref.read_text()

        out = tmp_path / "resumed.csv"
        cfg = triallib.TrialConfig(
            checkpoint_dir=str(tmp_path / "ck"), checkpoint_every=1,
            **dict(self.CFG, trials=2, out=str(out)))
        crashlib.arm(CrashPlan("trial", 2))    # dies inside trial 0
        with pytest.raises(InjectedCrash):
            triallib.run_trials(cfg)
        stats = triallib.run_trials(cfg)       # resumes + finishes
        assert stats["trials_completed"] == 2
        assert out.read_text() == ref_rows
        # a third run replays from done-markers without duplicating rows
        stats = triallib.run_trials(cfg)
        assert stats["trials_completed"] == 2
        assert out.read_text() == ref_rows

    def test_changed_config_rejected_loudly(self, tmp_path):
        from aclswarm_tpu.harness import trials as triallib
        cfg = triallib.TrialConfig(checkpoint_dir=str(tmp_path),
                                   checkpoint_every=1, **self.CFG)
        crashlib.arm(CrashPlan("trial", 1))
        with pytest.raises(InjectedCrash):
            triallib.run_trial(cfg, 0)
        # same checkpoint dir, different engine-visible knob: REJECT
        cfg2 = dataclasses.replace(cfg, tau=0.2)
        with pytest.raises(CheckpointMismatch) as ei:
            triallib.run_trial(cfg2, 0)
        assert [m[0] for m in ei.value.mismatches] == ["config_hash"]
        # output-path / verbosity changes do NOT invalidate a checkpoint
        cfg3 = dataclasses.replace(cfg, out="/dev/null", verbose=False)
        assert triallib.run_trial(cfg3, 0) is not None

    def test_record_dir_with_checkpoints_rejected(self, tmp_path):
        from aclswarm_tpu.harness import trials as triallib
        cfg = triallib.TrialConfig(checkpoint_dir=str(tmp_path),
                                   record_dir=str(tmp_path / "rec"),
                                   **self.CFG)
        with pytest.raises(ValueError, match="record_dir"):
            triallib.run_trial(cfg, 0)


# ------------------------------------------------- SIGKILL subprocess proof

@pytest.mark.slow
def test_sigkill_smoke_subprocess():
    """The scripts/check.sh smoke, exercised from tier-1: a child run is
    SIGKILL'd (env-armed crash plan) at chunk boundary 1, the parent
    resumes from its checkpoint and proves bit-parity."""
    r = subprocess.run(
        [sys.executable, "-m", "aclswarm_tpu.resilience.smoke"],
        capture_output=True, text=True, timeout=570, cwd=str(REPO),
        env={**__import__("os").environ, "JAX_PLATFORMS": "cpu"})
    assert r.returncode == 0, f"\n{r.stdout}\n{r.stderr}"
    assert "PASS" in r.stdout
    assert f"SIGKILL'd at chunk boundary {1}" in r.stdout


def test_crash_plan_env_roundtrip():
    plan = CrashPlan("suite", 3, "kill")
    assert CrashPlan.decode(plan.encode()) == plan
    assert CrashPlan.decode("trial:2") == CrashPlan("trial", 2, "raise")
    with pytest.raises(ValueError):
        CrashPlan.decode("bad")
    with pytest.raises(ValueError):
        CrashPlan("s", 0, kind="explode")
    # unmatched site/boundary: no-op
    crashlib.arm(CrashPlan("trial", 5))
    crashlib.maybe_crash("trial", 4)
    crashlib.maybe_crash("batch", 5)
    with pytest.raises(InjectedCrash):
        crashlib.maybe_crash("trial", 5)
    # one-shot: disarmed after firing
    crashlib.maybe_crash("trial", 5)
