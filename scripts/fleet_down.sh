#!/bin/bash
# Teardown for scripts/fleet_up.sh — the reference trial driver's
# defensive cleanup (`trial.sh:129-156`: kill the tmux sessions, pkill
# leftovers, and clear shared memory so the next run starts clean).
set -uo pipefail

NS=/asw
SESSION=aclswarm_tpu
while getopts "s:" opt; do
  case $opt in
    s) NS=$OPTARG ;;
    *) echo "usage: $0 [-s NS]"; exit 1 ;;
  esac
done

tmux kill-session -t $SESSION 2>/dev/null && echo "killed tmux $SESSION"
pkill -f "aclswarm_tpu.interop.bridge" 2>/dev/null || true
pkill -f "aclswarm_tpu.interop.operator" 2>/dev/null || true
# shm-ring cleanup (the reference clears /dev/shm leftovers the same way,
# trial.sh:150-156); ring names are the channel names minus the leading /
shopt -s nullglob
for f in /dev/shm/"${NS#/}"-*; do
  rm -f "$f" && echo "removed $f"
done
echo "fleet down"
