#!/bin/bash
# Multi-host bring-up — the `remote_start.sh` analogue for the JAX
# multi-controller model. The reference ssh-launches a vehicle stack per
# machine and lets ROS discover the processes
# (`aclswarm/scripts/remote_start.sh`, `start.sh:126-160`); here every
# host runs the SAME program (`aclswarm_tpu.parallel.launch`),
# `jax.distributed` performs the handshake, and the agent mesh spans all
# hosts' devices. The run ends with one JSON digest line per host; equal
# digests certify the multi-controller run agreed.
#
# Usage:
#   scripts/pod_up.sh --local-demo K [-n N] [--ticks T]
#       K local CPU processes on this machine (CI / laptop demo; the
#       exact path tests/test_multihost.py exercises)
#   scripts/pod_up.sh --hosts "host0 host1 ..." [-n N] [--ticks T]
#       ssh bring-up: process 0 on the first host is the coordinator
#       (port $PORT); remaining hosts join. Assumes the repo at the same
#       path everywhere (the reference's remote_start.sh makes the same
#       assumption about the catkin workspace).
#   On a TPU pod slice, skip this script: run
#       python -m aclswarm_tpu.parallel.launch
#   under the pod runtime on every worker — jax.distributed
#   auto-detects the topology.
set -euo pipefail

N=256
TICKS=20
PORT=9920
HOSTS=""
DEMO=0

while [[ $# -gt 0 ]]; do
  case "$1" in
    --local-demo) DEMO=$2; shift 2 ;;
    --hosts) HOSTS=$2; shift 2 ;;
    -n) N=$2; shift 2 ;;
    --ticks) TICKS=$2; shift 2 ;;
    --port) PORT=$2; shift 2 ;;
    *) echo "usage: $0 --local-demo K | --hosts \"h0 h1 ...\" [-n N] [--ticks T] [--port P]"; exit 1 ;;
  esac
done

cd "$(dirname "$0")/.."
REPO=$(pwd)

if [[ $DEMO -gt 0 ]]; then
  echo "local demo: $DEMO CPU processes, n=$N, coordinator 127.0.0.1:$PORT"
  pids=()
  for ((i = DEMO - 1; i >= 0; i--)); do
    python -m aclswarm_tpu.parallel.launch --cpu \
      --coordinator "127.0.0.1:$PORT" --num-processes "$DEMO" \
      --process-id "$i" --n "$N" --ticks "$TICKS" &
    pids+=($!)
  done
  rc=0
  for p in "${pids[@]}"; do wait "$p" || rc=1; done
  exit $rc
fi

[[ -n "$HOSTS" ]] || { echo "need --local-demo K or --hosts"; exit 1; }
read -r -a harr <<< "$HOSTS"
NPROC=${#harr[@]}
COORD="${harr[0]}:$PORT"
echo "pod bring-up: $NPROC hosts, coordinator $COORD, n=$N"
pids=()
for ((i = 0; i < NPROC; i++)); do
  ssh "${harr[$i]}" "cd $REPO && python -m aclswarm_tpu.parallel.launch \
    --coordinator $COORD --num-processes $NPROC --process-id $i \
    --n $N --ticks $TICKS" &
  pids+=($!)
done
rc=0
for p in "${pids[@]}"; do wait "$p" || rc=1; done
exit $rc
