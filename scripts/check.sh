#!/usr/bin/env bash
# One-shot pre-PR gate: every static/runtime guard the repo ships, in
# dependency order, failing fast. This is THE command to run before
# opening a PR (README "Quick start").
#
#   scripts/check.sh          # lint -> trace audit (+ zero-cost-off
#                             # proof) -> artifact schema -> analysis +
#                             # invariants + schema self-tests
#
# Pieces (each runnable standalone):
#   scripts/lint.sh                                        layer 1 lint
#   JAX_PLATFORMS=cpu python -m aclswarm_tpu.analysis.trace_audit
#                                         layer 2 audit + zero-cost-off
#   python benchmarks/check_results.py            committed artifacts
#   JAX_PLATFORMS=cpu python -m aclswarm_tpu.resilience.smoke
#                                         crash-resume smoke (SIGKILL)
#   JAX_PLATFORMS=cpu python -m aclswarm_tpu.serve.smoke
#                               serve smoke: SIGKILL the serving worker
#                               mid-batch, recover, zero losses
#   JAX_PLATFORMS=cpu python -m aclswarm_tpu.serve.smoke --multiworker
#                               worker-crash failover smoke: kill one of
#                               two workers mid-batch, zero loss +
#                               bit-identical migrated resume
#   JAX_PLATFORMS=cpu python -m aclswarm_tpu.serve.smoke --postmortem
#                               swarmtrace smoke: kill a worker, then
#                               reconstruct the migrated request's
#                               gap-free timeline from the journal alone
#   JAX_PLATFORMS=cpu python -m aclswarm_tpu.serve.smoke --procs
#                               swarmrouter smoke: SIGKILL one of two
#                               procworker OS processes mid-flight —
#                               the router's promise survives, zero
#                               journaled losses, fenced predecessor
#   python -m aclswarm_tpu.analysis.lint --protocol
#                               swarmproto conformance lint (JC201-204)
#   JAX_PLATFORMS=cpu python -m aclswarm_tpu.analysis.model --self-test
#                               explicit-state model checker: prove the
#                               five protocol properties AND that every
#                               deliberate mutation trips exactly its
#                               property
#   JAX_PLATFORMS=cpu python -m aclswarm_tpu.analysis.model --refine DIR
#                               refinement gate: the crash-drill
#                               journals the smokes above just produced
#                               must replay as accepted protocol traces
#   pytest tests/test_analysis.py tests/test_invariants.py \
#          tests/test_results_schema.py tests/test_resilience.py \
#          tests/test_serve.py ...                  guard self-tests
set -euo pipefail
cd "$(dirname "$0")/.."

echo "== jaxcheck layer 1: AST lint (JC001-JC006) =="
scripts/lint.sh

echo "== jaxcheck concurrency tier: lock discipline (JC101-JC103) =="
python -m aclswarm_tpu.analysis.lint --concurrency

echo "== swarmproto conformance lint: promise/journal/fencing =="
echo "== protocol (JC201-JC204) over serve/ + resilience/, with =="
echo "== event-vocabulary coverage (docs/STATIC_ANALYSIS.md) =="
python -m aclswarm_tpu.analysis.lint --protocol

echo "== swarmproto model checker: BFS the 2-request x 2-worker =="
echo "== crash/fence state space — prove no-lost-request, at-most- =="
echo "== once-or-bit-identical, terminal-once, fenced-no-ops, and =="
echo "== replay idempotence; then verify each deliberate protocol =="
echo "== mutation trips exactly its property (counterexample drill) =="
JAX_PLATFORMS=cpu python -m aclswarm_tpu.analysis.model --self-test

echo "== jaxcheck layer 2: trace audit + swarmcheck zero-cost-off proof =="
JAX_PLATFORMS=cpu python -m aclswarm_tpu.analysis.trace_audit

echo "== committed benchmark artifact schema =="
python benchmarks/check_results.py

# NOTE: the swarmscope telemetry zero-cost gate (telemetry-off lowered
# HLO == committed baseline) is enforced by the trace_audit step above —
# verify_zero_cost_off covers check_mode AND telemetry through the one
# shared baseline, so no second lowering sweep is run here.
echo "== swarmscope owed artifacts committed and on schema =="
echo "== (docs/OBSERVABILITY.md). Since PR 11 the schema IS the =="
echo "== acceptance bar: serve_throughput must show the >=3x =="
echo "== staged-round speedup, and serve_latency_breakdown must =="
echo "== keep host stages (pack+stack+unpack) under 50% of the =="
echo "== round — a stale pre-staging artifact fails here. =="
python - <<'EOF'
import sys

sys.path.insert(0, "benchmarks")
from check_results import RESULTS, check_file  # noqa: E402

for name in ("serve_throughput.json", "telemetry_overhead.json",
             "serve_multiworker_soak.json", "trace_soak.json",
             "serve_latency_breakdown.json", "scenario_suite.json",
             "serve_overload.json", "slo_detection.json",
             "pipeline_n1000.json", "router_fleet.json",
             "lock_overhead.json"):
    path = RESULTS / name
    if not path.exists():
        print(f"FAIL: missing owed artifact benchmarks/results/{name}")
        sys.exit(1)
    probs = check_file(path)
    if probs:
        print(f"FAIL: {name} schema drift: {probs}")
        sys.exit(1)
    print(f"{name}: committed and on schema")
EOF

echo "== swarmscenario fuzz smoke: random axis compositions (bounded =="
echo "== seeds) vs the swarmcheck invariant oracle — zero violations =="
echo "== (docs/SCENARIOS.md; the full >= 50-seed sweep is the slow =="
echo "== tier: python benchmarks/scenario_fuzz.py) =="
JAX_PLATFORMS=cpu python benchmarks/scenario_fuzz.py --seeds 8 -q

echo "== crash-resume smoke: SIGKILL at chunk 1 of an n=5 rollout, =="
echo "== resume from checkpoint, assert bit-parity (docs/RESILIENCE.md) =="
JAX_PLATFORMS=cpu python -m aclswarm_tpu.resilience.smoke

# keep the serve smokes' crash-drill journals: the swarmproto
# refinement gate below replays them through the protocol — real
# SIGKILL/failover/fence histories, zero extra smoke runtime
KEEP_JOURNALS=$(mktemp -d /tmp/aclswarm_smoke_journals.XXXXXX)
trap 'rm -rf "$KEEP_JOURNALS"' EXIT
export ACLSWARM_KEEP_JOURNALS="$KEEP_JOURNALS"

echo "== serve smoke: start the service, submit 3 mixed requests, =="
echo "== SIGKILL the worker mid-batch, recover the journal — zero =="
echo "== losses + bit-identical resume (docs/SERVICE.md) =="
JAX_PLATFORMS=cpu python -m aclswarm_tpu.serve.smoke

echo "== multi-worker crash-failover smoke: kill one of two workers =="
echo "== mid-batch — zero loss, bit-identical migrated resume, the =="
echo "== service keeps serving (docs/SERVICE.md §multi-worker). =="
echo "== Doubles as the swarmwatch smoke: the kill must fire a =="
echo "== worker_up alert on the live 'health' surface AND land as a =="
echo "== journaled alert record (docs/OBSERVABILITY.md §swarmwatch). =="
echo "== Runs with the swarmguard runtime detector ARMED: any rank =="
echo "== inversion or lock-order cycle on the OrderedLock tier raises =="
echo "== LockOrderViolation and fails the smoke =="
JAX_PLATFORMS=cpu ACLSWARM_LOCK_DEBUG=1 \
    python -m aclswarm_tpu.serve.smoke --multiworker

echo "== swarmtrace postmortem smoke: kill a worker mid-rollout, =="
echo "== reconstruct the migrated request's timeline from the journal =="
echo "== alone — complete, causally ordered, gap-free =="
echo "== (docs/OBSERVABILITY.md §swarmtrace) =="
JAX_PLATFORMS=cpu python -m aclswarm_tpu.serve.smoke --postmortem

echo "== swarmrouter process-mode smoke: router + two procworker OS =="
echo "== processes, SIGKILL one with a rollout mid-flight — the =="
echo "== router's promise survives (bit-identical migrated resume), =="
echo "== zero journaled losses, predecessor fenced, rolling restart =="
echo "== drains + re-admits (docs/SERVICE.md §process mode). Armed: =="
echo "== ACLSWARM_LOCK_DEBUG=1 inherits into the procworker children, =="
echo "== so lock-order discipline is enforced across every process =="
JAX_PLATFORMS=cpu ACLSWARM_LOCK_DEBUG=1 \
    python -m aclswarm_tpu.serve.smoke --procs

echo "== swarmproto refinement gate: every crash-drill journal the =="
echo "== four serve smokes just produced (SIGKILL, worker failover, =="
echo "== postmortem kill, process-fleet kill) must replay as an =="
echo "== accepted trace of the declarative protocol — the spec, the =="
echo "== model, and the running system agree on the same histories =="
JAX_PLATFORMS=cpu python -m aclswarm_tpu.analysis.model \
    --refine "$KEEP_JOURNALS"
# drop the kept journals now: the final exec replaces this shell, so
# the EXIT trap (which covers failure paths above) never fires
rm -rf "$KEEP_JOURNALS"
trap - EXIT
unset ACLSWARM_KEEP_JOURNALS

echo "== overload smoke: TCP clients at 10x measured capacity (the =="
echo "== adversarial open-loop fleet — slow-loris, corrupt frames, =="
echo "== reconnect storms) against a journaled service; assert ZERO =="
echo "== silent losses with every request postmortem-attributable =="
echo "== (docs/SERVICE.md §off-host serving) =="
JAX_PLATFORMS=cpu python benchmarks/serve_overload.py --smoke

echo "== bench trajectory (informational: benchmarks/bench_trend.py =="
echo "== exits nonzero standalone on a >10% regression) =="
python benchmarks/bench_trend.py --soft

# tier-1 duration guard: the verify command (ROADMAP.md) runs under a
# hard 1080 s timeout and tees its log to /tmp/_t1.log; fail loudly once
# the suite burns >80% of that budget (407 s at PR 4; re-planned 870 ->
# 1080 at PR 17 after the suite hit 848 s with +-10% host wall noise —
# 23 redundantly-covered heavy tests were ALSO re-marked slow, landing
# ~720-750 s) so the timeout is re-planned BEFORE it kills runs
# mid-suite.
echo "== tier-1 duration guard (last run must be < 80% of 1080 s) =="
T1_LOG=${T1_LOG:-/tmp/_t1.log}
if [ -f "$T1_LOG" ]; then
    secs=$(grep -aoE 'in [0-9]+\.[0-9]+s' "$T1_LOG" | tail -1 \
           | grep -oE '[0-9]+\.[0-9]+' || true)
    if [ -n "${secs:-}" ]; then
        python - "$secs" <<'EOF'
import sys
secs, budget = float(sys.argv[1]), 1080.0
frac = secs / budget
print(f"last tier-1 run: {secs:.0f}s = {100 * frac:.0f}% of the "
      f"{budget:.0f}s timeout budget (guard: 80%)")
if frac > 0.8:
    print("FAIL: tier-1 exceeds 80% of its timeout budget — trim or "
          "re-mark slow tests, or re-plan the budget")
    sys.exit(1)
EOF
    else
        echo "no pytest duration line in $T1_LOG — skipping (run tier-1 "
        echo "with the ROADMAP.md command to populate it)"
    fi
else
    echo "no tier-1 log at $T1_LOG — skipping (run tier-1 first)"
fi

echo "== guard self-tests (lint fixtures, audit grid, invariant contracts, resilience, serve, wire, router, traffic, telemetry, trace, watch, scenarios, protocol) =="
exec env JAX_PLATFORMS=cpu python -m pytest \
    tests/test_analysis.py tests/test_invariants.py \
    tests/test_results_schema.py tests/test_resilience.py \
    tests/test_serve.py tests/test_serve_wire.py \
    tests/test_router.py \
    tests/test_traffic.py \
    tests/test_telemetry.py tests/test_trace.py \
    tests/test_watch.py \
    tests/test_scenarios.py \
    tests/test_protocol.py \
    -q -m 'not slow' -p no:cacheprovider
