#!/usr/bin/env bash
# One-shot pre-PR gate: every static/runtime guard the repo ships, in
# dependency order, failing fast. This is THE command to run before
# opening a PR (README "Quick start").
#
#   scripts/check.sh          # lint -> trace audit (+ zero-cost-off
#                             # proof) -> artifact schema -> analysis +
#                             # invariants + schema self-tests
#
# Pieces (each runnable standalone):
#   scripts/lint.sh                                        layer 1 lint
#   JAX_PLATFORMS=cpu python -m aclswarm_tpu.analysis.trace_audit
#                                         layer 2 audit + zero-cost-off
#   python benchmarks/check_results.py            committed artifacts
#   pytest tests/test_analysis.py tests/test_invariants.py \
#          tests/test_results_schema.py             guard self-tests
set -euo pipefail
cd "$(dirname "$0")/.."

echo "== jaxcheck layer 1: AST lint (JC001-JC006) =="
scripts/lint.sh

echo "== jaxcheck layer 2: trace audit + swarmcheck zero-cost-off proof =="
JAX_PLATFORMS=cpu python -m aclswarm_tpu.analysis.trace_audit

echo "== committed benchmark artifact schema =="
python benchmarks/check_results.py

echo "== guard self-tests (lint fixtures, audit grid, invariant contracts) =="
exec env JAX_PLATFORMS=cpu python -m pytest \
    tests/test_analysis.py tests/test_invariants.py \
    tests/test_results_schema.py \
    -q -m 'not slow' -p no:cacheprovider
