#!/usr/bin/env bash
# jaxcheck layer 1 standalone: the JAX-specific AST lint (JC001-JC005).
#
#   scripts/lint.sh                 # lint aclswarm_tpu/ (the tier-1 bar)
#   scripts/lint.sh path/to/file.py # lint specific files/dirs
#
# Exit 1 on any violation. Layer 2 (the trace audit) needs a backend:
#   JAX_PLATFORMS=cpu python -m aclswarm_tpu.analysis.trace_audit
# Rule catalog + escape hatch syntax: docs/STATIC_ANALYSIS.md.
set -euo pipefail
cd "$(dirname "$0")/.."
exec env JAX_PLATFORMS=cpu python -m aclswarm_tpu.analysis.lint "$@"
