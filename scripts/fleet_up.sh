#!/bin/bash
# Fleet bring-up for the wire (shm-ring) deployment — the analogue of the
# reference's tmux orchestration (`aclswarm_sim/scripts/start.sh:126-160`
# launches n simulator + n vehicle-stack panes; `aclswarm/scripts/
# remote_start.sh` does the onboard equivalent over ssh). The TPU-native
# deployment needs exactly THREE processes instead of 3n+1:
#
#   pane 0  planner bridge daemon (the whole coordination layer, batched)
#   pane 1  operator             (takeoff broadcast + formation cycling)
#   pane 2  live wire plot       (the rqt_multiplot analogue)
#
# Usage: scripts/fleet_up.sh [-n N] [-g GROUP] [-s NS] [-d DISPATCH_S]
#        scripts/fleet_down.sh [-s NS]          # teardown (trial.sh:129-156)
#
# For real multi-host hardware the same shape applies per host (the shm
# rings are per-host; cross-host transport is the ROS adapter,
# `aclswarm_tpu/interop/ros_bridge.py`, or any socket pump of the framed
# codec).
set -euo pipefail

N=6
GROUP=swarm6_3d
NS=/asw
DISPATCH=10
SESSION=aclswarm_tpu

while getopts "n:g:s:d:" opt; do
  case $opt in
    n) N=$OPTARG ;;
    g) GROUP=$OPTARG ;;
    s) NS=$OPTARG ;;
    d) DISPATCH=$OPTARG ;;
    *) echo "usage: $0 [-n N] [-g GROUP] [-s NS] [-d DISPATCH_S]"; exit 1 ;;
  esac
done

cd "$(dirname "$0")/.."

if tmux has-session -t $SESSION 2>/dev/null; then
  echo "session '$SESSION' already running (scripts/fleet_down.sh first)"
  exit 1
fi

tmux new-session -d -s $SESSION -n fleet
tmux split-window -h -t $SESSION:0
tmux split-window -v -t $SESSION:0.1

# pane 0: the planner bridge (creates the rings; everything else opens them)
tmux send-keys -t $SESSION:0.0 \
  "python -m aclswarm_tpu.interop.bridge --n $N --ns $NS --verbose" Enter
sleep 2

# pane 1: operator — GO broadcast then formation cycling at the dispatch
# period (the reference operator's START semantics, operator.py:126-134)
tmux send-keys -t $SESSION:0.1 \
  "python -m aclswarm_tpu.interop.operator --group $GROUP \
--channel $NS-formation --mode-channel $NS-flightmode-veh --create \
--action start --dispatch $DISPATCH" Enter

# pane 2: live wire-signal plot (rqt_multiplot analogue)
tmux send-keys -t $SESSION:0.2 \
  "python -m aclswarm_tpu.harness.liveplot --ns $NS || \
echo 'liveplot unavailable (headless?)'" Enter

echo "fleet up: tmux attach -t $SESSION   (scripts/fleet_down.sh tears down)"
