"""swarmguard runtime tier: order-checked, instrumented locks
(docs/STATIC_ANALYSIS.md §host-side concurrency; docs/SERVICE.md
§locking protocol).

The host-side fleet (service, worker pool, router, wire dispatcher,
telemetry) grew an implicit locking protocol one review round at a
time — every PR since 8 caught at least one lock-discipline bug by
hand. `OrderedLock`/`OrderedRLock` make the protocol EXECUTABLE:

- **rank registry**: every lock belongs to a *family* (``"serve.
  service"``, ``"serve.pool"``, ...) with a numeric rank
  (`DEFAULT_RANKS`; `register_rank` for extensions). The protocol is
  "acquire in strictly increasing rank order"; a thread acquiring a
  lock whose rank is <= the highest rank it already holds is an
  inversion — the static analyzer (`analysis.concurrency`, JC102)
  proves the *program text* can't nest locks backwards, this layer
  proves the *running fleet* doesn't.
- **held-set tracking**: per-thread (thread-local) held stacks plus a
  cross-thread table of every thread's held families, so a violation
  report shows the would-be deadlock peer, not just the offender.
- **cycle detection**: for unranked families, a global first-seen
  nesting graph (family -> family edges); an acquire that closes a
  cycle in that graph is the two-thread deadlock pattern even when no
  rank was declared.
- **histograms**: construction with ``registry=`` feeds
  ``lock_wait_s{name=<family>}`` (time blocked acquiring) and
  ``lock_hold_s{name=<family>}`` (time held) into the existing
  `MetricsRegistry` — contention becomes a scrapeable surface next to
  the serve spans. The registry's own metric locks pass
  ``registry=None`` (a lock that observed its own hold time into a
  histogram guarded by itself would recurse).

Checking is gated by ``ACLSWARM_LOCK_DEBUG=1`` (env, read at import;
`arm()`/`disarm()` for tests) so the always-on fleet pays only the
instrumentation cost (< 2% of the serve round, enforced as schema by
``results/lock_overhead.json``); `scripts/check.sh` runs the
multiworker and ``--procs`` smokes with the detector armed, so every
check run is a live race drill.

Violations raise a structured `LockOrderViolation` naming the lock,
its rank, the full held set, and a snapshot of every other thread's
held families. Pure stdlib except the *optional* registry hook —
importing this module must never drag jax (or telemetry) in.
"""
from __future__ import annotations

import os
import threading
import time
from typing import Optional

__all__ = ["LockOrderViolation", "OrderedLock", "OrderedRLock",
           "DEFAULT_RANKS", "register_rank", "rank_of", "arm", "disarm",
           "debug_armed", "held_families"]

# ---------------------------------------------------------------------------
# rank registry
#
# One family per lock *role*; every instance of a family shares the
# rank (the per-metric locks are hundreds of instances of one family).
# The protocol: a thread may acquire a lock only while every lock it
# holds has a STRICTLY SMALLER rank. Ranks are spaced so new tiers can
# land between existing ones without renumbering the fleet.
# docs/SERVICE.md §locking protocol documents each row.

DEFAULT_RANKS: dict[str, int] = {
    "serve.router":     10,   # router front door (stateless tier)
    "serve.wire":       14,   # wire dispatcher connection table
    "serve.service":    20,   # THE service lock (jobs/stats/staging)
    "serve.admission":  30,   # admission queue condition
    "serve.pool":       40,   # worker-pool lifecycle lock
    "serve.traffic":    50,   # open-loop fleet ledgers
    "telemetry.lifecycle": 60,   # journal event appender
    "telemetry.watch":  70,   # timeseries store / SLO engine / sampler
    "telemetry.registry": 80,  # metric get-or-create table
    "telemetry.spans":  85,   # flight-recorder ring
    "telemetry.metric": 90,   # leaf per-metric locks (innermost)
}

_RANKS: dict[str, int] = dict(DEFAULT_RANKS)
_RANKS_GUARD = threading.Lock()


def register_rank(family: str, rank: int) -> None:
    """Register (or re-pin) a family's rank. Extensions slot between
    the defaults; re-registering an existing family to a DIFFERENT
    rank raises — two modules disagreeing about a family's rank is
    itself a protocol bug."""
    with _RANKS_GUARD:
        old = _RANKS.get(family)
        if old is not None and old != rank:
            raise ValueError(
                f"lock family {family!r} already ranked {old}; "
                f"re-registering as {rank} would fork the protocol")
        _RANKS[family] = rank


def rank_of(family: str) -> Optional[int]:
    return _RANKS.get(family)


# ---------------------------------------------------------------------------
# debug arming (ACLSWARM_LOCK_DEBUG=1)

def _env_armed() -> bool:
    return os.environ.get("ACLSWARM_LOCK_DEBUG", "") not in ("", "0")


_armed = _env_armed()


def arm() -> None:
    """Turn the order/cycle detector on (tests; env does it for real
    runs — the smokes in scripts/check.sh export ACLSWARM_LOCK_DEBUG=1
    so every check run is a live race drill)."""
    global _armed
    _armed = True


def disarm() -> None:
    global _armed
    _armed = False


def debug_armed() -> bool:
    return _armed


# ---------------------------------------------------------------------------
# held-set tracking
#
# Thread-local stack of currently-held OrderedLocks (the checker's
# input), mirrored into a cross-thread table keyed by thread id so a
# violation can report what every OTHER thread held at the instant of
# the inversion — the peer of the would-be deadlock. The mirror is
# guarded by a raw lock (never an OrderedLock: the tracker must not
# recurse into itself) and only maintained while armed.

_tls = threading.local()
_PEERS: dict[int, tuple[str, tuple[str, ...]]] = {}
_PEERS_GUARD = threading.Lock()

# first-seen nesting graph over families: edges[a] = set of families
# ever acquired while a was held. Used for cycle detection on
# unranked families (ranked ones are fully ordered already).
_EDGES: dict[str, set[str]] = {}
_EDGES_GUARD = threading.Lock()


def _held_stack() -> list:
    stack = getattr(_tls, "stack", None)
    if stack is None:
        stack = _tls.stack = []
    return stack


def held_families() -> tuple[str, ...]:
    """The calling thread's currently-held lock families, outermost
    first (diagnostics + tests)."""
    return tuple(lk.family for lk in _held_stack())


def _publish_held() -> None:
    t = threading.current_thread()
    with _PEERS_GUARD:
        fams = tuple(lk.family for lk in _held_stack())
        if fams:
            _PEERS[t.ident or 0] = (t.name, fams)
        else:
            _PEERS.pop(t.ident or 0, None)


def _peers_snapshot() -> dict[str, tuple[str, ...]]:
    me = threading.get_ident()
    with _PEERS_GUARD:
        return {f"{name}({tid})": fams
                for tid, (name, fams) in _PEERS.items() if tid != me}


def _reaches(src: str, dst: str) -> bool:
    """Is there a path src -> ... -> dst in the first-seen nesting
    graph? (Caller holds _EDGES_GUARD.)"""
    seen = set()
    stack = [src]
    while stack:
        f = stack.pop()
        if f == dst:
            return True
        if f in seen:
            continue
        seen.add(f)
        stack.extend(_EDGES.get(f, ()))
    return False


class LockOrderViolation(RuntimeError):
    """Structured lock-order violation: the acquire that would invert
    the protocol (or close a nesting cycle), with enough context to
    fix it without a debugger attached to a wedged fleet."""

    def __init__(self, kind: str, family: str, rank: Optional[int],
                 held: tuple[str, ...], peers: dict,
                 detail: str = ""):
        self.kind = kind            # "rank" | "cycle" | "self"
        self.family = family
        self.rank = rank
        self.held = held
        self.peers = peers
        msg = (f"lock-order violation ({kind}): acquiring "
               f"{family!r} (rank {rank}) while holding "
               f"{list(held)}")
        if detail:
            msg += f" — {detail}"
        if peers:
            msg += f"; other threads hold {peers}"
        super().__init__(msg)


class OrderedLock:
    """Drop-in `threading.Lock` with rank/cycle checking and hold/wait
    instrumentation. Non-reentrant: re-acquiring a held OrderedLock is
    reported as a self-deadlock when armed (and deadlocks for real
    when not, exactly like `threading.Lock`)."""

    _reentrant = False

    def __init__(self, family: str, *, rank: Optional[int] = None,
                 registry=None, name: Optional[str] = None):
        self.family = family
        self.name = name or family
        self.rank = rank if rank is not None else rank_of(family)
        self._inner = (threading.RLock() if self._reentrant
                       else threading.Lock())
        # cache the two histograms at construction: the acquire path
        # must not pay a registry get-or-create per lock op
        self._hold_hist = self._wait_hist = None
        if registry is not None:
            labels = {"name": family}
            self._wait_hist = registry.histogram("lock_wait_s",
                                                 labels=labels)
            self._hold_hist = registry.histogram("lock_hold_s",
                                                 labels=labels)
        self._t_acquired = 0.0
        self._depth = 0             # meaningful for the RLock subclass

    # -- checking ---------------------------------------------------------
    def _check(self) -> None:
        stack = _held_stack()
        if not stack:
            return
        if any(lk is self for lk in stack):
            if self._reentrant:
                return              # legal re-entry
            raise LockOrderViolation(
                "self", self.family, self.rank, held_families(),
                _peers_snapshot(),
                "re-acquiring a non-reentrant lock this thread already "
                "holds (guaranteed deadlock)")
        held_ranked = [lk for lk in stack if lk.rank is not None]
        if self.rank is not None and held_ranked:
            top = max(held_ranked, key=lambda lk: lk.rank)
            if self.rank < top.rank:
                raise LockOrderViolation(
                    "rank", self.family, self.rank, held_families(),
                    _peers_snapshot(),
                    f"rank {self.rank} is below held {top.family!r} "
                    f"(rank {top.rank}); the protocol is strictly "
                    "increasing rank (docs/SERVICE.md §locking "
                    "protocol)")
            if self.rank == top.rank and top.family == self.family:
                raise LockOrderViolation(
                    "rank", self.family, self.rank, held_families(),
                    _peers_snapshot(),
                    "two locks of one family nested — same-rank "
                    "sibling locks (e.g. two per-metric locks) have "
                    "no defined order, so nesting them can deadlock "
                    "against a thread nesting them the other way")
        # cycle detection over the first-seen nesting graph: catches
        # inversions BETWEEN unranked families (and ranked-vs-unranked)
        # that the rank test cannot see
        inner = self.family
        with _EDGES_GUARD:
            for lk in stack:
                if lk.family == inner:
                    continue
                if _reaches(inner, lk.family):
                    raise LockOrderViolation(
                        "cycle", self.family, self.rank,
                        held_families(), _peers_snapshot(),
                        f"the fleet has previously nested "
                        f"{inner!r} -> ... -> {lk.family!r}; acquiring "
                        f"{inner!r} under {lk.family!r} closes the "
                        "cycle (two threads doing both orders is a "
                        "deadlock)")
                _EDGES.setdefault(lk.family, set()).add(inner)

    # -- lock API ---------------------------------------------------------
    def acquire(self, blocking: bool = True,
                timeout: float = -1) -> bool:
        armed = _armed
        if armed:
            self._check()
        wh = self._wait_hist
        if wh is not None:
            t0 = time.perf_counter()
            ok = self._inner.acquire(blocking, timeout)
            if ok:
                wh.observe(time.perf_counter() - t0)
        else:
            ok = self._inner.acquire(blocking, timeout)
        if ok:
            self._depth += 1
            if self._depth == 1:
                self._t_acquired = time.perf_counter()
                if armed:
                    _held_stack().append(self)
                    _publish_held()
                elif getattr(_tls, "stack", None):
                    # disarmed mid-run with locks held: keep the stack
                    # coherent rather than leaking entries
                    _tls.stack = []
        return ok

    def release(self) -> None:
        self._depth -= 1
        if self._depth == 0:
            hh = self._hold_hist
            if hh is not None:
                hh.observe(time.perf_counter() - self._t_acquired)
            stack = getattr(_tls, "stack", None)
            if stack:
                for i in range(len(stack) - 1, -1, -1):
                    if stack[i] is self:
                        del stack[i]
                        break
                _publish_held()
        self._inner.release()

    def locked(self) -> bool:
        if self._reentrant:
            return self._depth > 0
        return self._inner.locked()

    def __enter__(self) -> "OrderedLock":
        self.acquire()
        return self

    def __exit__(self, *exc) -> None:
        self.release()

    def __repr__(self) -> str:    # pragma: no cover — diagnostics
        return (f"<{type(self).__name__} {self.family!r} "
                f"rank={self.rank}>")


class OrderedRLock(OrderedLock):
    """Reentrant variant: re-entry by the holding thread is legal (and
    not re-checked); everything else behaves like `OrderedLock`."""

    _reentrant = True
