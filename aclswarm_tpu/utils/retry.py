"""Unified retry / timeout / backoff policy (docs/RESILIENCE.md).

Before this module every layer that had to survive a transient failure
grew its own loop: the shm transport's backpressure poll
(`interop/transport.py send_reliable`), bench.py's watchdog timer +
subprocess device probe, and the trial FSM's timeout counters. This is
the single home for that machinery:

- `RetryPolicy` / `delay_for`: exponential backoff with DETERMINISTIC
  jitter (a pure hash of (seed, attempt) — retries must be reproducible
  in tests and in resumed runs, so `random` is banned here) and a hard
  wall-clock budget cap;
- `retry_call`: bounded retry of a callable under a policy, with a
  `retryable` predicate so non-transient errors surface immediately and
  a `cancel` event so a caller tearing a stage down (the serve layer's
  deadline/shutdown paths) stops an in-flight backoff budget instead of
  sleeping it out;
- `poll_until`: fixed-interval polling against a grace deadline (the
  transport backpressure shape: the resource drains on its own, backoff
  would only add latency), also cancellable;
- `Watchdog`: a one-shot timer with ATOMIC finish-vs-fire semantics
  (the bench.py boundary race: a measurement finishing exactly at the
  timeout must never let the timer claim the output line), safe to
  arm/finish/fire from concurrent threads;
- `subprocess_probe`: liveness probe in a throwaway subprocess with a
  hard timeout (a wedged device tunnel hangs the *calling* process
  inside `jax.devices()` uncancellably — probing must be sacrificial);
- `ExecutionFailure`: the structured record drivers attach to results
  JSON when a stage failed, retried, or degraded — evidence, not logs
  (`benchmarks/check_results.py` validates the schema).

Host-side only: nothing here is jit-reachable (jaxcheck JC004 bans
`time` in compiled paths; this module IS the host boundary).
"""
from __future__ import annotations

import dataclasses
import subprocess
import sys
import threading
import time
import zlib
from typing import Callable, Optional


@dataclasses.dataclass(frozen=True)
class RetryPolicy:
    """Exponential backoff with deterministic jitter and budget caps.

    Attempt k (0-based) sleeps ``min(base_s * factor**k, max_s)``
    scaled by ``1 + jitter * u(seed, k)`` with ``u`` a pure hash in
    [0, 1) — same policy + seed + attempt always yields the same delay
    (reproducible sweeps; no thundering-herd alignment across trials
    because each call site folds its own seed).
    """

    attempts: int = 4          # total tries (1 = no retry)
    base_s: float = 0.05       # first backoff delay
    factor: float = 2.0        # exponential growth per attempt
    max_s: float = 2.0         # per-delay ceiling
    budget_s: Optional[float] = None   # total wall-clock cap (None = off)
    jitter: float = 0.25       # fractional deterministic jitter
    seed: int = 0


def _unit_hash(seed: int, attempt: int) -> float:
    """Pure [0, 1) hash of (seed, attempt) — crc32, not `random`, so
    delays are identical across processes and resumed runs."""
    h = zlib.crc32(f"{seed}:{attempt}".encode())
    return (h & 0xFFFFFF) / float(1 << 24)


def delay_for(policy: RetryPolicy, attempt: int) -> float:
    """Backoff delay before retry number ``attempt`` (0-based)."""
    d = min(policy.base_s * (policy.factor ** attempt), policy.max_s)
    return d * (1.0 + policy.jitter * _unit_hash(policy.seed, attempt))


def jittered(delay_s: float, seed: int, attempt: int,
             frac: float = 0.25) -> float:
    """Deterministically jittered delay: the retry-after form of
    `delay_for`, for waits whose BASE the other side names (the serve
    admission ``retry_after_s`` hint). Same crc32 hash as the policy
    jitter — replays are identical, and a fleet of rejected clients
    folding distinct seeds de-aligns instead of thundering back in one
    herd."""
    return float(delay_s) * (1.0 + frac * _unit_hash(seed, attempt))


def retry_after_delay(hint_s: float, seed: int, attempt: int,
                      cap_s: float = 30.0) -> float:
    """THE serve retry-after backoff: the server's hint, floored away
    from zero, jittered deterministically, capped. One home for the
    contract every hint-honoring client shares (`serve.client`,
    `serve.wire.WireClient`, `serve.traffic`) — a tweak here changes
    all of them together."""
    return min(float(cap_s), jittered(max(0.01, float(hint_s)),
                                      seed, attempt))


@dataclasses.dataclass
class ExecutionFailure:
    """One stage's failure record, committed into results JSON so a
    degraded run is evidence instead of a dead artifact. ``fallback``
    names what absorbed the failure ('cpu', 'requeued', ...) or None
    when the stage ultimately failed."""

    stage: str
    error: str
    attempts: int = 1
    elapsed_s: float = 0.0
    fallback: Optional[str] = None

    def to_row(self) -> dict:
        """The exact key set `benchmarks/check_results.py` validates —
        add a field there before adding one here."""
        return {"stage": self.stage, "error": self.error,
                "attempts": self.attempts,
                "elapsed_s": round(self.elapsed_s, 3),
                "fallback": self.fallback}


class RetryCancelled(RuntimeError):
    """A `retry_call` was cancelled before its first attempt could run.
    Cancellation landing AFTER a failed attempt re-raises that attempt's
    exception instead — the caller sees the real failure, just without
    the remaining backoff budget."""


def retry_call(fn: Callable, *args,
               policy: RetryPolicy = RetryPolicy(),
               retryable: Callable[[BaseException], bool] = lambda e: True,
               on_retry: Optional[Callable[[int, BaseException], None]]
               = None,
               sleep: Callable[[float], None] = time.sleep,
               clock: Callable[[], float] = time.monotonic,
               cancel: Optional[threading.Event] = None):
    """Call ``fn(*args)``, retrying per ``policy`` while ``retryable(exc)``
    holds and the budget allows. Non-retryable exceptions and the final
    failure propagate unchanged (callers wrap them into
    `ExecutionFailure` records with their own stage context).

    ``cancel`` (optional) propagates an external teardown into the
    in-flight budget: a set event stops further attempts immediately and
    interrupts the backoff sleep mid-wait (the event IS the sleeper, so
    a 5 s backoff ends the moment the canceller fires). Cancellation
    before the first attempt raises `RetryCancelled`; after a failure it
    re-raises that failure. It never aborts ``fn`` itself mid-call —
    attempts are the cancellation boundaries, exactly like the serve
    layer's chunk boundaries."""
    t0 = clock()
    for attempt in range(policy.attempts):
        if cancel is not None and cancel.is_set():
            raise RetryCancelled(
                f"retry budget cancelled before attempt {attempt}")
        try:
            return fn(*args)
        except BaseException as e:            # noqa: BLE001 — re-raised
            last_try = attempt >= policy.attempts - 1
            if last_try or not retryable(e):
                raise
            d = delay_for(policy, attempt)
            if policy.budget_s is not None \
                    and clock() - t0 + d > policy.budget_s:
                raise
            if on_retry is not None:
                on_retry(attempt, e)
            if cancel is not None:
                if cancel.wait(d):        # interrupted backoff: surface
                    raise                 # the real failure, now
            else:
                sleep(d)
    raise AssertionError("unreachable")       # pragma: no cover


def poll_until(fn: Callable[[], bool], *, grace_s: float,
               poll_s: float = 0.001,
               sleep: Callable[[float], None] = time.sleep,
               clock: Callable[[], float] = time.monotonic,
               cancel: Optional[threading.Event] = None) -> bool:
    """Fixed-interval poll of ``fn`` until it returns truthy or the grace
    deadline passes. The backpressure shape (shm ring drain): the first
    call is immediate, and the deadline bounds TOTAL wait — False means
    the grace expired with ``fn`` still failing.

    The deadline is computed ONCE from the monotonic ``clock`` and every
    sleep is capped to the remaining budget: a poll interval larger than
    what is left can never overshoot the deadline (the old behavior
    slept the full ``poll_s`` past the boundary, so ``grace_s=0.01,
    poll_s=1.0`` waited ~1 s — a 100x overshoot the serve layer's
    lease arithmetic cannot absorb). After the final capped sleep ``fn``
    gets one last immediate check before False.

    ``cancel`` (optional) aborts the poll early with False; a set event
    also cuts the in-flight inter-poll sleep short (event-based wait),
    so a cancelled poller returns within one poll interval."""
    deadline = clock() + grace_s
    while True:
        if cancel is not None and cancel.is_set():
            return False
        if fn():
            return True
        remaining = deadline - clock()
        if remaining <= 0:
            return False
        step = min(poll_s, remaining)
        if cancel is not None:
            if cancel.wait(step):
                return False
        else:
            sleep(step)


class Watchdog:
    """One-shot watchdog with atomic finish-vs-fire semantics.

    The guarded code calls `finish()` when it completes; the timer calls
    `fire()` at the deadline. Exactly one of them wins: a lock makes the
    check-and-claim atomic, so a completion racing the timer boundary can
    never let both the result and the diagnostic escape (the bench.py
    one-JSON-line contract).

    Safe for concurrent use (the serve layer arms one per request from
    client threads while the worker finishes them): `arm` replaces and
    cancels any pending timer under the lock, a finished/fired watchdog
    refuses to re-arm, and every `fire`/`finish` combination — including
    two racing `fire`s from a stale and a fresh timer — resolves to
    exactly one winner."""

    def __init__(self, on_fire: Callable[[], None]):
        self.done = threading.Event()
        self._lock = threading.Lock()
        self._timer: Optional[threading.Timer] = None
        self._gen = 0           # armed-timer generation (stale-fire guard)
        self._on_fire = on_fire

    def arm(self, timeout_s: float) -> None:
        """Start (or restart) the countdown. Re-arming cancels the prior
        timer inside the lock AND bumps a generation counter: a stale
        timer whose wait already elapsed is past the point where
        `Timer.cancel` helps, so its callback re-checks the generation
        under the lock and yields — only the CURRENT deadline can ever
        claim. Arming after the watchdog already resolved is a no-op,
        not a resurrection."""
        with self._lock:
            if self.done.is_set():
                return
            if self._timer is not None:
                self._timer.cancel()
            self._gen += 1
            gen = self._gen
            t = threading.Timer(timeout_s,
                                lambda: self._timer_fire(gen))
            t.daemon = True
            self._timer = t
        t.start()

    def _timer_fire(self, gen: int) -> None:
        """Armed-timer callback: claim only if this timer is still the
        current generation (a re-arm in the cancel/expiry window
        otherwise lets the OLD deadline fire)."""
        with self._lock:
            if self.done.is_set() or gen != self._gen:
                return
            self.done.set()
        self._on_fire()

    def fire(self) -> None:
        """Manual fire: runs ``on_fire`` unless `finish` already won.
        Firing CLAIMS completion (sets ``done`` inside the lock), so a
        `finish` racing in right after returns False — exactly one side
        ever wins, even when ``on_fire`` does not exit the process. The
        callback itself runs outside the lock (an ``on_fire`` that calls
        `finish` must not deadlock)."""
        with self._lock:
            if self.done.is_set():
                return
            self.done.set()
        self._on_fire()

    def finish(self) -> bool:
        """Claim completion; True iff the watchdog had not fired (the
        caller may emit its result). Cancels a pending timer; idempotent
        — repeat calls return False without side effects."""
        with self._lock:
            won = not self.done.is_set()
            self.done.set()
            timer, self._timer = self._timer, None
        if timer is not None:
            timer.cancel()
        return won


def subprocess_output(code: str, timeout_s: float,
                      cwd: Optional[str] = None) -> Optional[str]:
    """Stdout of ``python -c code`` iff it exits 0 within the budget,
    else None. Sacrificial by design: a probe of a wedged resource must
    hang a throwaway process, never the caller. The single home for the
    throwaway-subprocess mechanics — `subprocess_probe` (boolean form)
    and `serve.client.probe_backend` (backend-name form) both layer on
    this."""
    try:
        r = subprocess.run([sys.executable, "-c", code],
                           capture_output=True, text=True,
                           timeout=timeout_s, cwd=cwd)
        return r.stdout if r.returncode == 0 else None
    except (subprocess.TimeoutExpired, OSError):
        return None


def subprocess_probe(code: str, timeout_s: float,
                     marker: str = "ok", cwd: Optional[str] = None) -> bool:
    """True iff ``python -c code`` exits 0 printing ``marker`` within the
    budget (the bench.py device-probe shape)."""
    out = subprocess_output(code, timeout_s, cwd=cwd)
    return out is not None and marker in out
