"""Unified retry / timeout / backoff policy (docs/RESILIENCE.md).

Before this module every layer that had to survive a transient failure
grew its own loop: the shm transport's backpressure poll
(`interop/transport.py send_reliable`), bench.py's watchdog timer +
subprocess device probe, and the trial FSM's timeout counters. This is
the single home for that machinery:

- `RetryPolicy` / `delay_for`: exponential backoff with DETERMINISTIC
  jitter (a pure hash of (seed, attempt) — retries must be reproducible
  in tests and in resumed runs, so `random` is banned here) and a hard
  wall-clock budget cap;
- `retry_call`: bounded retry of a callable under a policy, with a
  `retryable` predicate so non-transient errors surface immediately;
- `poll_until`: fixed-interval polling against a grace deadline (the
  transport backpressure shape: the resource drains on its own, backoff
  would only add latency);
- `Watchdog`: a one-shot timer with ATOMIC finish-vs-fire semantics
  (the bench.py boundary race: a measurement finishing exactly at the
  timeout must never let the timer claim the output line);
- `subprocess_probe`: liveness probe in a throwaway subprocess with a
  hard timeout (a wedged device tunnel hangs the *calling* process
  inside `jax.devices()` uncancellably — probing must be sacrificial);
- `ExecutionFailure`: the structured record drivers attach to results
  JSON when a stage failed, retried, or degraded — evidence, not logs
  (`benchmarks/check_results.py` validates the schema).

Host-side only: nothing here is jit-reachable (jaxcheck JC004 bans
`time` in compiled paths; this module IS the host boundary).
"""
from __future__ import annotations

import dataclasses
import subprocess
import sys
import threading
import time
import zlib
from typing import Callable, Optional


@dataclasses.dataclass(frozen=True)
class RetryPolicy:
    """Exponential backoff with deterministic jitter and budget caps.

    Attempt k (0-based) sleeps ``min(base_s * factor**k, max_s)``
    scaled by ``1 + jitter * u(seed, k)`` with ``u`` a pure hash in
    [0, 1) — same policy + seed + attempt always yields the same delay
    (reproducible sweeps; no thundering-herd alignment across trials
    because each call site folds its own seed).
    """

    attempts: int = 4          # total tries (1 = no retry)
    base_s: float = 0.05       # first backoff delay
    factor: float = 2.0        # exponential growth per attempt
    max_s: float = 2.0         # per-delay ceiling
    budget_s: Optional[float] = None   # total wall-clock cap (None = off)
    jitter: float = 0.25       # fractional deterministic jitter
    seed: int = 0


def _unit_hash(seed: int, attempt: int) -> float:
    """Pure [0, 1) hash of (seed, attempt) — crc32, not `random`, so
    delays are identical across processes and resumed runs."""
    h = zlib.crc32(f"{seed}:{attempt}".encode())
    return (h & 0xFFFFFF) / float(1 << 24)


def delay_for(policy: RetryPolicy, attempt: int) -> float:
    """Backoff delay before retry number ``attempt`` (0-based)."""
    d = min(policy.base_s * (policy.factor ** attempt), policy.max_s)
    return d * (1.0 + policy.jitter * _unit_hash(policy.seed, attempt))


@dataclasses.dataclass
class ExecutionFailure:
    """One stage's failure record, committed into results JSON so a
    degraded run is evidence instead of a dead artifact. ``fallback``
    names what absorbed the failure ('cpu', 'requeued', ...) or None
    when the stage ultimately failed."""

    stage: str
    error: str
    attempts: int = 1
    elapsed_s: float = 0.0
    fallback: Optional[str] = None

    def to_row(self) -> dict:
        """The exact key set `benchmarks/check_results.py` validates —
        add a field there before adding one here."""
        return {"stage": self.stage, "error": self.error,
                "attempts": self.attempts,
                "elapsed_s": round(self.elapsed_s, 3),
                "fallback": self.fallback}


def retry_call(fn: Callable, *args,
               policy: RetryPolicy = RetryPolicy(),
               retryable: Callable[[BaseException], bool] = lambda e: True,
               on_retry: Optional[Callable[[int, BaseException], None]]
               = None,
               sleep: Callable[[float], None] = time.sleep,
               clock: Callable[[], float] = time.monotonic):
    """Call ``fn(*args)``, retrying per ``policy`` while ``retryable(exc)``
    holds and the budget allows. Non-retryable exceptions and the final
    failure propagate unchanged (callers wrap them into
    `ExecutionFailure` records with their own stage context)."""
    t0 = clock()
    for attempt in range(policy.attempts):
        try:
            return fn(*args)
        except BaseException as e:            # noqa: BLE001 — re-raised
            last_try = attempt >= policy.attempts - 1
            if last_try or not retryable(e):
                raise
            d = delay_for(policy, attempt)
            if policy.budget_s is not None \
                    and clock() - t0 + d > policy.budget_s:
                raise
            if on_retry is not None:
                on_retry(attempt, e)
            sleep(d)
    raise AssertionError("unreachable")       # pragma: no cover


def poll_until(fn: Callable[[], bool], *, grace_s: float,
               poll_s: float = 0.001,
               sleep: Callable[[float], None] = time.sleep,
               clock: Callable[[], float] = time.monotonic) -> bool:
    """Fixed-interval poll of ``fn`` until it returns truthy or the grace
    deadline passes. The backpressure shape (shm ring drain): the first
    call is immediate, and the deadline bounds TOTAL wait — False means
    the grace expired with ``fn`` still failing."""
    deadline = clock() + grace_s
    while not fn():
        if clock() > deadline:
            return False
        sleep(poll_s)
    return True


class Watchdog:
    """One-shot watchdog with atomic finish-vs-fire semantics.

    The guarded code calls `finish()` when it completes; the timer calls
    `fire()` at the deadline. Exactly one of them wins: a lock makes the
    check-and-claim atomic, so a completion racing the timer boundary can
    never let both the result and the diagnostic escape (the bench.py
    one-JSON-line contract)."""

    def __init__(self, on_fire: Callable[[], None]):
        self.done = threading.Event()
        self._lock = threading.Lock()
        self._timer: Optional[threading.Timer] = None
        self._on_fire = on_fire

    def arm(self, timeout_s: float) -> None:
        self._timer = threading.Timer(timeout_s, self.fire)
        self._timer.daemon = True
        self._timer.start()

    def fire(self) -> None:
        """Timer callback: runs ``on_fire`` unless `finish` already won.
        Firing CLAIMS completion (sets ``done`` inside the lock), so a
        `finish` racing in right after returns False — exactly one side
        ever wins, even when ``on_fire`` does not exit the process. The
        callback itself runs outside the lock (an ``on_fire`` that calls
        `finish` must not deadlock)."""
        with self._lock:
            if self.done.is_set():
                return
            self.done.set()
        self._on_fire()

    def finish(self) -> bool:
        """Claim completion; True iff the watchdog had not fired (the
        caller may emit its result). Cancels a pending timer."""
        with self._lock:
            won = not self.done.is_set()
            self.done.set()
        if self._timer is not None:
            self._timer.cancel()
        return won


def subprocess_probe(code: str, timeout_s: float,
                     marker: str = "ok", cwd: Optional[str] = None) -> bool:
    """True iff ``python -c code`` exits 0 printing ``marker`` within the
    budget. Sacrificial by design: a probe of a wedged resource must hang
    a throwaway process, never the caller (bench.py device probe)."""
    try:
        r = subprocess.run([sys.executable, "-c", code],
                           capture_output=True, text=True,
                           timeout=timeout_s, cwd=cwd)
        return r.returncode == 0 and marker in r.stdout
    except (subprocess.TimeoutExpired, OSError):
        return False
