"""Logging (SURVEY.md §5.5): leveled, env-configurable, ANSI-highlighted.

The reference logs through ROS_INFO/WARN/ERROR with hand-colored
highlights (`coordination_ros.cpp:122-123`) and a `verbose` flag for the
auction trace (`auctioneer.cpp:111-116`). Equivalent here: stdlib logging
with a framework root logger, per-module children, an env knob
(``ACLSWARM_LOG=debug`` or ``ACLSWARM_LOG=aclswarm_tpu.interop=debug``),
and the same visual conventions on a tty. Every framework record is
additionally counted into the swarmscope registry
(``log_records_total{level=...}`` — docs/OBSERVABILITY.md), so log
volume by severity is a metric, not just a stream.

Usage::

    from aclswarm_tpu.utils.log import get_logger
    log = get_logger(__name__)
    log.info("committed formation %s", name)
    log.debug("auction trace ...")       # the reference's `verbose` flag
"""
from __future__ import annotations

import logging
import os
import sys

ROOT = "aclswarm_tpu"
_COLORS = {
    logging.WARNING: "\x1b[33m",
    logging.ERROR: "\x1b[31m",
    logging.CRITICAL: "\x1b[41m",
}
_RESET = "\x1b[0m"
_configured = False


class _TtyFormatter(logging.Formatter):
    def format(self, record):
        msg = super().format(record)
        color = _COLORS.get(record.levelno)
        if color and sys.stderr.isatty():
            return f"{color}{msg}{_RESET}"
        return msg


class _TelemetryHandler(logging.Handler):
    """Counts every framework log record into the swarmscope registry
    (``log_records_total{level=...}``, docs/OBSERVABILITY.md): warn/
    error rates become scrapeable metrics next to the counters they
    explain — a soak whose error counter climbs is visible without
    grepping its stderr. Always resolves the CURRENT default registry,
    so `telemetry.reset_registry` (test isolation) is honored."""

    def emit(self, record):
        try:
            from aclswarm_tpu.telemetry import get_registry
            get_registry().counter(
                "log_records_total",
                labels={"level": record.levelname.lower()}).inc()
        except Exception:       # noqa: BLE001 — logging must never raise
            pass


def _configure() -> None:
    global _configured
    if _configured:
        return
    _configured = True
    root = logging.getLogger(ROOT)
    if not root.handlers:
        handler = logging.StreamHandler()
        handler.setFormatter(_TtyFormatter(
            "[%(levelname).1s %(asctime)s %(name)s] %(message)s",
            datefmt="%H:%M:%S"))
        root.addHandler(handler)
    if not any(isinstance(h, _TelemetryHandler) for h in root.handlers):
        root.addHandler(_TelemetryHandler())
    root.setLevel(logging.INFO)
    # ACLSWARM_LOG=debug  or  ACLSWARM_LOG=<logger>=<level>,<logger>=...
    spec = os.environ.get("ACLSWARM_LOG", "")
    for part in filter(None, (s.strip() for s in spec.split(","))):
        if "=" in part:
            name, _, level = part.partition("=")
            logging.getLogger(name).setLevel(level.upper())
        else:
            root.setLevel(part.upper())


def get_logger(name: str = ROOT) -> logging.Logger:
    """A child of the framework root logger (configured on first use)."""
    _configure()
    if not name.startswith(ROOT):
        name = f"{ROOT}.{name}"
    return logging.getLogger(name)
