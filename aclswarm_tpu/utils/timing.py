"""Timing + profiling utilities (SURVEY.md §5.1).

The reference's tracing story is wall-clock log lines around the gain
solve (`coordination_ros.cpp:113-118`) and MATLAB tic/toc; the survey
calls JAX-profiler integration "a strict upgrade" — this module is that
upgrade, plus the benchmark timer with the two environment-specific
pitfalls baked in (see the project memory / bench.py methodology):

- `readback_sync`: the only reliable completion barrier through the
  remote-device tunnel (`jax.block_until_ready` may return at
  dispatch-acknowledge);
- `median_time`: chained-work timing with readback sync — the single
  home the benchmark suites import;
- `trace`: context manager around `jax.profiler` for per-kernel
  timelines viewable in TensorBoard/Perfetto;
- `Stopwatch`: the reference's log-line pattern (wall-clock of a named
  phase), for host-side code.
"""
from __future__ import annotations

import contextlib
import time

import numpy as np


def readback_sync(x) -> float:
    """Block until ``x`` is computed by fetching one scalar to the host.

    A device->host transfer cannot complete before the producing
    executable does, unlike `block_until_ready` on tunnel-attached
    devices (measured: early returns yielding ~1000x-off timings).
    """
    import jax
    return float(np.asarray(jax.tree.leaves(x)[0]).ravel()[0])


def timing_stats(fn, arg, per: int = 1, reps: int = 5,
                 name: str | None = None, registry=None) -> dict:
    """Wall-second statistics of ``fn(arg)`` divided by ``per``, after one
    warmup call; ``fn`` should return a small digest (see
    `readback_sync`). For device work, chain ``per`` distinct instances
    inside ``fn`` (one `lax.scan`) so fixed launch overhead amortizes.

    Returns median plus the rep spread (min/max) so artifacts carry a
    jitter column — a single median hides tunnel hiccups and thermal
    variance (the round-1 unexplained-variance lesson).

    ``name`` additionally records every rep into the swarmscope
    ``timing_<name>_s`` histogram (docs/OBSERVABILITY.md) — the default
    process registry unless ``registry`` overrides it — so benchmark
    timings and service latencies read out of ONE substrate. The
    returned dict's key set is unchanged (the committed artifacts'
    contract)."""
    readback_sync(fn(arg))
    times = []
    for _ in range(reps):
        t0 = time.perf_counter()
        readback_sync(fn(arg))
        times.append((time.perf_counter() - t0) / per)
    if name is not None:
        if registry is None:
            from aclswarm_tpu.telemetry import get_registry
            registry = get_registry()
        hist = registry.histogram(f"timing_{name}_s")
        for t in times:
            hist.observe(t)
    return {"median_s": float(np.median(times)),
            "min_s": float(np.min(times)), "max_s": float(np.max(times)),
            "reps": reps}


def median_time(fn, arg, per: int = 1, reps: int = 5) -> float:
    """Median-only convenience wrapper over `timing_stats`."""
    return timing_stats(fn, arg, per=per, reps=reps)["median_s"]


@contextlib.contextmanager
def trace(logdir: str):
    """JAX profiler trace around a block::

        with timing.trace("/tmp/prof"):
            rollout(...)  # then: tensorboard --logdir /tmp/prof

    Captures per-kernel device timelines (fusion boundaries, HBM stalls,
    collective overlap) — the debugging view the reference never had.
    """
    import jax
    jax.profiler.start_trace(logdir)
    try:
        yield
    finally:
        jax.profiler.stop_trace()


class Stopwatch:
    """Named wall-clock phases, the `coordination_ros.cpp:113-118` log
    pattern::

        sw = Stopwatch()
        with sw.phase("gains"):
            solve(...)
        sw.report(logger.info)
    """

    def __init__(self):
        self.phases: list[tuple[str, float]] = []

    @contextlib.contextmanager
    def phase(self, name: str):
        t0 = time.perf_counter()
        try:
            yield
        finally:
            self.phases.append((name, time.perf_counter() - t0))

    def report(self, sink=print) -> None:
        for name, secs in self.phases:
            sink(f"{name}: {secs * 1e3:.2f} ms")
