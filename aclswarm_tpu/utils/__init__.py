"""Shared utilities: timing/profiling (§5.1) and logging (§5.5)."""
from aclswarm_tpu.utils.log import get_logger
from aclswarm_tpu.utils.timing import (Stopwatch, median_time,
                                       readback_sync, trace)

__all__ = ["get_logger", "Stopwatch", "median_time", "readback_sync",
           "trace"]
