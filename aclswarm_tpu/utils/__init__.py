"""Shared utilities: timing/profiling (§5.1), logging (§5.5), and the
unified retry/timeout/backoff policy (docs/RESILIENCE.md)."""
from aclswarm_tpu.utils.log import get_logger
from aclswarm_tpu.utils.retry import (ExecutionFailure, RetryCancelled,
                                      RetryPolicy, Watchdog, delay_for,
                                      poll_until, retry_call,
                                      subprocess_output, subprocess_probe)
from aclswarm_tpu.utils.timing import (Stopwatch, median_time,
                                       readback_sync, trace)

__all__ = ["get_logger", "Stopwatch", "median_time", "readback_sync",
           "trace", "ExecutionFailure", "RetryCancelled", "RetryPolicy",
           "Watchdog", "delay_for", "poll_until", "retry_call",
           "subprocess_output", "subprocess_probe"]
