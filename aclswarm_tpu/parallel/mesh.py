"""Device mesh + sharding layout for the agent axis.

The reference scales by replicating the whole 3-node vehicle stack once per
vehicle as OS processes wired over TCPROS (SURVEY.md §2.5). The TPU-native
scaling axis is the same — agents — but realized as array sharding: every
per-agent quantity (rows of q/vel, goal state, assignment, per-agent gain
row-blocks) is sharded over a 1-D ``agents`` mesh axis, and every pairwise
interaction (control einsum, velocity-obstacle masks, auction bids) becomes
an XLA collective over ICI inserted by GSPMD. The "flooding" of position
estimates (`localization_ros.cpp:152-185`) is literally an all-gather of the
``q`` shards; bid max-consensus is a cross-shard max-reduce.

Multi-host: the same `Mesh` spans hosts under `jax.distributed` — the layout
below needs no change; DCN-vs-ICI placement is the runtime's concern.
"""
from __future__ import annotations

import jax
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from aclswarm_tpu import control, sim
from aclswarm_tpu.core.types import Formation, SwarmState

AGENT_AXIS = "agents"


def make_mesh(n_devices: int | None = None,
              n_agents: int | None = None) -> Mesh:
    """A 1-D mesh over the agent axis (all devices by default).

    XLA's jit sharding annotations require the sharded dimension to divide
    evenly across the mesh, so when ``n_agents`` is given the mesh takes the
    *largest* device count that divides it — whole agents per device, the
    sharded analogue of the reference placing whole vehicle stacks per
    process (`start.sh:141-160`).
    """
    devs = jax.devices()
    if n_devices is not None:
        devs = devs[:n_devices]
    if n_agents is not None:
        k = len(devs)
        while k > 1 and n_agents % k != 0:
            k -= 1
        devs = devs[:k]
    return Mesh(np.asarray(devs), axis_names=(AGENT_AXIS,))


def slice_devices(n_slices: int, devices: list | None = None
                  ) -> list[list]:
    """Partition the visible devices into ``n_slices`` worker slices
    (the multi-worker serving layout, docs/SERVICE.md: one serve worker
    per mesh slice). With at least one device per slice the split is
    contiguous — slice boundaries respect device order, which on TPU
    keeps each slice ICI-adjacent. With FEWER devices than slices (the
    CPU fallback host: one device, N worker threads) slices share
    devices round-robin: every slice still names a device, the workers
    just contend for the same stream — scheduling still scales, compute
    does not, and the caller can see that from the overlap."""
    devs = list(devices if devices is not None else jax.devices())
    n_slices = max(1, int(n_slices))
    if not devs:
        return [[] for _ in range(n_slices)]
    if len(devs) >= n_slices:
        # contiguous split, remainder spread over the leading slices
        base, extra = divmod(len(devs), n_slices)
        out, at = [], 0
        for i in range(n_slices):
            take = base + (1 if i < extra else 0)
            out.append(devs[at:at + take])
            at += take
        return out
    return [[devs[i % len(devs)]] for i in range(n_slices)]


def row_sharding(mesh: Mesh) -> NamedSharding:
    """Leading axis = agents, sharded."""
    return NamedSharding(mesh, P(AGENT_AXIS))


def replicated(mesh: Mesh) -> NamedSharding:
    return NamedSharding(mesh, P())


def sim_state_sharding(mesh: Mesh, localization: bool = False,
                       faults: bool = False,
                       checks: bool = False,
                       telemetry: bool = False,
                       scenario: bool = False,
                       cbaa_warm: bool = False) -> sim.SimState:
    """Sharding pytree for `sim.SimState`: per-agent leaves row-sharded.

    ``localization=True`` matches states built with
    ``init_state(..., localization=True)``: the (n, n, 3) estimate tables
    shard on the *owning-agent* axis (each shard holds its agents' whole
    belief vectors — the layout of the reference's per-vehicle tracker
    processes), so the flood's min-age merge gathers neighbor rows over
    ICI exactly like the bid consensus.

    ``faults=True`` matches states carrying a `FaultSchedule`: the
    per-vehicle timelines and the (n, n) link-loss matrix shard on the
    vehicle/receiver axis; the trial seed replicates (every shard draws
    the identical per-tick link lottery).

    ``checks=True`` matches states built with
    ``init_state(..., checks=True)``: the swarmcheck error carry is a
    pair of scalars, replicated (every shard records the identical
    first-violation code).

    ``telemetry=True`` matches states built with
    ``init_state(..., telemetry=True)``: the swarmscope counter carry
    (`telemetry.device.ChunkTelemetry`) is a handful of scalars,
    replicated exactly like the swarmcheck carry (every shard
    accumulates the identical counters).

    ``scenario=True`` matches states carrying a `Scenario`
    (`aclswarm_tpu.scenarios`): the per-vehicle byzantine mask shards
    on the vehicle axis like the fault timelines; everything else —
    obstacle tracks (K slots), disturbance scalars, sequence point
    tables (every agent's alignment consumes all points, exactly why
    `Formation.points` replicates), drift/cadence scalars, and the
    per-trial key — replicates.

    ``cbaa_warm=True`` matches states built with
    ``init_state(..., cbaa_warm=True)``: the carried (n, n) price/winner
    tables are per-agent local views, so they shard on the owning-agent
    axis exactly like the localization belief tables and the fault
    link-loss matrix."""
    from aclswarm_tpu.analysis.invariants import InvariantState
    from aclswarm_tpu.assignment.cbaa import CbaaTables
    from aclswarm_tpu.faults import FaultSchedule
    from aclswarm_tpu.scenarios.timeline import Scenario
    from aclswarm_tpu.telemetry.device import ChunkTelemetry

    row = row_sharding(mesh)
    rep = replicated(mesh)
    loc = sim.EstimateTable(est=row, age=row) if localization else None
    fsched = FaultSchedule(drop_tick=row, rejoin_tick=row,
                           link_loss=row, key=rep) if faults else None
    scen = Scenario(
        obs_center=rep, obs_vel=rep, obs_radius=rep, obs_appear=rep,
        obs_vanish=rep, wind_vel=rep, gust_std=rep, wind_tick=rep,
        noise_std=rep, noise_tick=rep, seq_points=rep, seq_tick=rep,
        byz_mask=row, byz_std=rep, byz_tick=rep, drift_vel=rep,
        drift_tick=rep, rematch_every=rep, key=rep) if scenario else None
    return sim.SimState(
        swarm=SwarmState(q=row, vel=row),
        goal=control.TrajGoal(pos=row, vel=row, yaw=row, dyaw=row),
        v2f=row, tick=rep,
        flight=sim.FlightState(mode=row, ticks_in_mode=row,
                               initial_alt=row, takeoff_alt=row),
        loc=loc, first_auction=rep, assign_enabled=rep, faults=fsched,
        scenario=scen,
        inv=InvariantState(code=rep, tick=rep) if checks else None,
        tel=ChunkTelemetry(auctions=rep, assign_rounds=rep, reassigns=rep,
                           ca_ticks=rep, flood_stale_max=rep,
                           admm_iters=rep, admm_residual=rep)
        if telemetry else None,
        cbaa_warm=CbaaTables(price=row, who=row) if cbaa_warm else None)


def formation_sharding(mesh: Mesh) -> Formation:
    """Sharding pytree for `Formation`: the O(n^2) tensors (gains, dstar,
    adjmat) shard on their first (formation-point) axis; points replicate
    (n x 3 is tiny and every agent's alignment needs all of it)."""
    row = row_sharding(mesh)
    rep = replicated(mesh)
    return Formation(points=rep, adjmat=row, gains=row,
                     dstar_xy=row, dstar_z=row)


def shard_problem(state: sim.SimState, formation, mesh: Mesh):
    """Place a sim state + formation onto the mesh with the standard layout."""
    st_sh = sim_state_sharding(mesh, localization=state.loc is not None,
                               faults=state.faults is not None,
                               checks=state.inv is not None,
                               telemetry=state.tel is not None,
                               scenario=state.scenario is not None)
    f_sh = formation_sharding(mesh)
    return (jax.device_put(state, st_sh), jax.device_put(formation, f_sh),
            st_sh, f_sh)
