"""Multi-host scale-out: `jax.distributed` + the same agent-axis layout.

The reference's multi-machine story is "run more ROS masters" (it never
does); the TPU framework's is the standard JAX multi-controller model:
every host runs the SAME program, `jax.distributed.initialize()` wires the
runtime together, and `jax.devices()` then spans all hosts — the 1-D agent
mesh (`aclswarm_tpu.parallel.mesh`) needs no change. GSPMD places the
collectives: intra-host reductions ride ICI, cross-host segments ride DCN.
Because every per-agent quantity shards by whole agents, the cross-host
traffic is exactly the reference's inter-vehicle traffic (position floods,
bid reductions) — small, and overlapped by XLA's latency hiding.

Practical notes (v5e pods / multi-host CPU alike):
- call `initialize()` before any other JAX API touches a backend;
- build arrays with `jax.make_array_from_process_local_data` (each host
  contributes its agents) or `jax.device_put` from host 0 for small
  replicated leaves;
- all hosts must execute the same jitted calls in the same order —
  the trial driver's chunked loop already satisfies this (host-side
  branching uses only replicated scalars).

This module only wraps the initialization handshake with the framework's
defaults; it is exercised degenerately (single-process) in CI — real
multi-host runs need a pod.
"""
from __future__ import annotations

import jax


def initialize(coordinator_address: str | None = None,
               num_processes: int | None = None,
               process_id: int | None = None) -> bool:
    """Initialize the multi-controller runtime (no-op when single-process).

    Mirrors `jax.distributed.initialize`'s auto-detection: on TPU pods all
    arguments come from the environment; elsewhere pass them explicitly.
    Returns True when a multi-process runtime is active.
    """
    if num_processes is None and coordinator_address is None:
        import os
        # multi-WORKER indicators only: single-host TPU attachments also
        # set TPU_WORKER_HOSTNAMES (e.g. 'localhost'), so that var counts
        # only when it lists several workers
        cluster_env = any(os.environ.get(v) for v in (
            "JAX_COORDINATOR_ADDRESS", "COORDINATOR_ADDRESS",
            "MEGASCALE_COORDINATOR_ADDRESS",
            "SLURM_JOB_ID", "OMPI_COMM_WORLD_SIZE")) \
            or "," in os.environ.get("TPU_WORKER_HOSTNAMES", "")
        try:
            jax.distributed.initialize()
        except (ValueError, RuntimeError):
            if cluster_env:
                # a cluster IS configured but the handshake failed —
                # silently degrading to single-process would run every
                # host at the wrong scale with no error
                raise
            # genuinely no cluster env: run locally
            return jax.process_count() > 1
    else:
        # explicit-cluster bring-up: the coordinator (process 0) may not
        # be listening yet when a follower starts — the classic bring-up
        # race `pod_up.sh` hits when hosts launch in parallel. Retry the
        # handshake under the unified policy (docs/RESILIENCE.md)
        # instead of requiring operators to sequence their ssh loops;
        # non-transient failures (bad address, version skew) surface on
        # the first attempt.
        from aclswarm_tpu.utils.retry import RetryPolicy, retry_call

        def _handshake_transient(e: BaseException) -> bool:
            s = str(e)
            return isinstance(e, (RuntimeError, ConnectionError)) and any(
                m in s for m in ("UNAVAILABLE", "DEADLINE", "connect",
                                 "refused", "unreachable"))

        def _attempt():
            try:
                jax.distributed.initialize(
                    coordinator_address=coordinator_address,
                    num_processes=num_processes, process_id=process_id)
            except BaseException:
                # jax assigns global_state.client/service BEFORE the
                # connect, and a second initialize() with them set
                # raises 'should only be called once' — so a failed
                # handshake must be torn down or the retry can never
                # succeed (it would just mask the real error)
                try:
                    jax.distributed.shutdown()
                except Exception:
                    pass
                raise

        retry_call(_attempt,
                   policy=RetryPolicy(attempts=5, base_s=0.5, max_s=4.0,
                                      budget_s=30.0),
                   retryable=_handshake_transient)
    return jax.process_count() > 1


def global_agent_mesh(n_agents: int):
    """The host-spanning agent mesh: same helper, all global devices."""
    from aclswarm_tpu.parallel import mesh as meshlib
    return meshlib.make_mesh(n_agents=n_agents)
