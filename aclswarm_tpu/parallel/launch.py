"""Multi-host launch program: one process per host, same program.

The reference brings a fleet up by ssh-ing `start.sh` onto every machine
and letting ROS wire the processes together
(`aclswarm_sim/scripts/start.sh:126-160`, `remote_start.sh`). The
TPU-native analogue is the JAX multi-controller model: every host runs
THIS program, `jax.distributed` performs the handshake
(`aclswarm_tpu.parallel.multihost.initialize`), and the agent-axis mesh
then spans all hosts' devices — intra-host collectives ride ICI,
cross-host segments ride DCN. `scripts/pod_up.sh` is the bring-up
wrapper (the `remote_start.sh` analogue).

What one run does: initialize the runtime, build the global agent mesh,
construct a seeded faithful-stack problem (flooded localization +
blocked CBAA — the same shape the driver's `dryrun_multichip` checks),
roll the sharded engine a few ticks, and print one JSON line per
process with a position digest. The digest is a pure function of the
global computation, so EQUAL DIGESTS ACROSS PROCESSES certify that the
multi-controller run agreed — the smoke every bring-up should end with.

Run (per host; pod_up.sh generates these):
    python -m aclswarm_tpu.parallel.launch \
        --coordinator <host0>:9920 --num-processes 4 --process-id $i \
        --n 256 --ticks 20
On a TPU pod slice, omit the coordinator flags — `jax.distributed`
auto-detects from the TPU environment.
"""
from __future__ import annotations

import argparse
import json
import sys

from aclswarm_tpu.utils import timing  # no backend touch at import time


def _put_global(tree, shardings):
    """Materialize a host-replicated pytree as global sharded arrays.

    Every process holds the same seeded numpy arrays; each contributes
    the shards it addresses (`jax.make_array_from_callback` slices the
    same global array identically on every host)."""
    import jax
    import numpy as np

    def put(x, sh):
        if x is None:        # matched absent leaves (e.g. loc=None)
            return None
        x = np.asarray(x)
        return jax.make_array_from_callback(
            x.shape, sh, lambda idx: x[idx])

    return jax.tree.map(put, tree, shardings,
                        is_leaf=lambda x: x is None)


def run(n: int, ticks: int, seed: int = 0) -> dict:
    """The post-handshake smoke: sharded faithful-stack rollout."""
    import jax
    import jax.numpy as jnp
    import numpy as np

    from aclswarm_tpu import sim
    from aclswarm_tpu.core.types import (ControlGains, SafetyParams,
                                         make_formation)
    from aclswarm_tpu.parallel import mesh as meshlib

    mesh = meshlib.make_mesh(n_agents=n)
    ndev = len(mesh.devices.ravel())
    rng = np.random.default_rng(seed)
    ang = np.linspace(0, 2 * np.pi, n, endpoint=False)
    r0 = 3.0 * np.sqrt(max(n, 8) / 8.0)
    points = np.stack([r0 * np.cos(ang), r0 * np.sin(ang),
                       np.zeros(n)], 1)
    adj = np.ones((n, n)) - np.eye(n)
    gains = rng.normal(size=(n, n, 3, 3)) * 0.01
    formation = make_formation(points, adj, gains)
    sparams = SafetyParams(
        bounds_min=jnp.asarray([-500.0, -500.0, 0.0], jnp.float32),
        bounds_max=jnp.asarray([500.0, 500.0, 10.0], jnp.float32))
    block = max(1, min(64, n // 2))
    cfg = sim.SimConfig(assignment="cbaa", assign_every=max(1, ticks // 2),
                        localization="flooded", flood_block=block,
                        cbaa_task_block=block, colavoid_neighbors=16,
                        flight_fsm=False)
    state = sim.init_state(rng.normal(size=(n, 3)) * 4.0 + [0, 0, 2.0],
                           localization=True)

    shardings = meshlib.sim_state_sharding(mesh, localization=True)
    rep = meshlib.replicated(mesh)
    with mesh:
        state = _put_global(state, shardings)
        step = jax.jit(
            lambda s: sim.step(s, formation, ControlGains(), sparams,
                               cfg)[0],
            in_shardings=(shardings,), out_shardings=shardings)
        for _ in range(ticks):
            state = step(state)
        digest = jax.jit(lambda s: s.swarm.q.sum(),
                         out_shardings=rep)(state)
        # completion barrier through the remote-device tunnel: one
        # documented idiom (`utils.timing.readback_sync`) — a bare
        # `block_until_ready` may return at dispatch-acknowledge there
        digest = timing.readback_sync(digest)
    return {"process": jax.process_index(),
            "processes": jax.process_count(),
            "global_devices": ndev,
            "n": n, "ticks": ticks,
            "digest": round(digest, 6)}


def main(argv=None):
    ap = argparse.ArgumentParser(description=__doc__)
    ap.add_argument("--n", type=int, default=256)
    ap.add_argument("--ticks", type=int, default=20)
    ap.add_argument("--seed", type=int, default=0)
    ap.add_argument("--coordinator", default=None,
                    help="host:port of process 0 (omit on TPU pods — "
                         "auto-detected)")
    ap.add_argument("--num-processes", type=int, default=None)
    ap.add_argument("--process-id", type=int, default=None)
    ap.add_argument("--cpu", action="store_true",
                    help="force the CPU backend (local demo / CI)")
    args = ap.parse_args(argv)

    if args.cpu:
        import jax
        jax.config.update("jax_platforms", "cpu")
    from aclswarm_tpu.parallel import multihost
    multi = multihost.initialize(coordinator_address=args.coordinator,
                                 num_processes=args.num_processes,
                                 process_id=args.process_id)
    report = run(args.n, args.ticks, args.seed)
    report["multiprocess"] = bool(multi)
    print(json.dumps(report), flush=True)
    return 0


if __name__ == "__main__":
    sys.exit(main())
