"""Distribution over device meshes (SURVEY.md §7 layer 6)."""
from aclswarm_tpu.parallel.mesh import (AGENT_AXIS, formation_sharding,
                                        make_mesh, replicated, row_sharding,
                                        shard_problem, sim_state_sharding)
from aclswarm_tpu.parallel import multihost
from aclswarm_tpu.parallel.rollout import (batched_formation_sharding,
                                           batched_rollout_fn,
                                           batched_sim_state_sharding,
                                           sharded_rollout_fn,
                                           sharded_step_fn)

__all__ = ["AGENT_AXIS", "make_mesh", "row_sharding", "replicated",
           "sim_state_sharding", "formation_sharding", "shard_problem",
           "sharded_step_fn", "sharded_rollout_fn", "batched_rollout_fn",
           "batched_sim_state_sharding", "batched_formation_sharding",
           "multihost"]
