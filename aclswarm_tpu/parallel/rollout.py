"""Sharded closed-loop rollout: the whole swarm step distributed over a mesh.

GSPMD propagates the agent-axis shardings declared in `mesh.py` through the
entire step — the control einsum contracts a row-sharded gain block against a
gathered q, the velocity-obstacle pair grid partitions by rows, the auction's
bid/accept rounds reduce across shards — so the program the reference runs as
n OS processes + TCPROS becomes one SPMD program with ICI collectives
(SURVEY.md §2.5, §5.8).
"""
from __future__ import annotations

from functools import partial

import jax
from jax.sharding import NamedSharding, PartitionSpec as P

from aclswarm_tpu import sim
from aclswarm_tpu.parallel import mesh as meshlib
from aclswarm_tpu.sim import engine as _engine


def _loc_in_sharding(cfg, localization):
    """The sharding spec's loc entry must match the *state's* pytree (an
    EstimateTable leaf when built with init_state(localization=True), None
    otherwise) — a mismatch fails at the jit boundary with an opaque
    pytree-structure error. Default: derived from cfg (the common case
    where state and cfg agree); pass ``localization`` explicitly for a
    truth-mode rollout of a state that still carries tables."""
    return (cfg.localization == "flooded") if localization is None \
        else localization


def _checks_in_sharding(cfg, checks):
    """Same contract as `_loc_in_sharding`, for the swarmcheck error
    carry: the sharding spec's `inv` entry must match the state's pytree
    (an `InvariantState` iff built with init_state(checks=True))."""
    return (cfg.check_mode == "on") if checks is None else checks


def sharded_step_fn(mesh, formation_sharded, gains, sparams, cfg,
                    localization: bool | None = None,
                    checks: bool | None = None):
    """Build a jitted, mesh-sharded single-tick function state -> state."""
    st_sh = meshlib.sim_state_sharding(
        mesh, localization=_loc_in_sharding(cfg, localization),
        checks=_checks_in_sharding(cfg, checks))

    @partial(jax.jit, in_shardings=(st_sh,),
             out_shardings=(st_sh, meshlib.replicated(mesh)))
    def step(state):
        return sim.step(state, formation_sharded, gains, sparams, cfg)

    return step


def sharded_rollout_fn(mesh, formation_sharded, gains, sparams, cfg,
                       n_ticks: int, localization: bool | None = None,
                       checks: bool | None = None):
    """Build a jitted, mesh-sharded rollout (lax.scan of the sharded step)."""
    st_sh = meshlib.sim_state_sharding(
        mesh, localization=_loc_in_sharding(cfg, localization),
        checks=_checks_in_sharding(cfg, checks))

    @partial(jax.jit, in_shardings=(st_sh,), static_argnums=())
    def roll(state):
        return sim.rollout(state, formation_sharded, gains, sparams, cfg,
                           n_ticks)

    return roll


def _prepend_batch_axis(sharding: NamedSharding) -> NamedSharding:
    """Lift a per-trial sharding to a trial-batched array: the new leading
    batch axis replicates, the agent axis keeps its mesh placement."""
    return NamedSharding(sharding.mesh, P(*((None,) + tuple(sharding.spec))))


def batched_sim_state_sharding(mesh, localization: bool = False,
                               checks: bool = False):
    """Sharding pytree for a trial-batched `SimState` (leaves (B, ...)):
    batch axis replicated, per-agent axes row-sharded as in
    `mesh.sim_state_sharding`."""
    return jax.tree.map(
        _prepend_batch_axis,
        meshlib.sim_state_sharding(mesh, localization=localization,
                                   checks=checks),
        is_leaf=lambda x: isinstance(x, NamedSharding))


def batched_formation_sharding(mesh):
    """Sharding pytree for a (B, ...)-stacked `Formation`."""
    return jax.tree.map(
        _prepend_batch_axis, meshlib.formation_sharding(mesh),
        is_leaf=lambda x: isinstance(x, NamedSharding))


def batched_rollout_fn(mesh, formation_batched, gains, sparams, cfg,
                       n_ticks: int, localization: bool | None = None,
                       checks: bool | None = None):
    """Build a jitted rollout combining BOTH scaling axes: vmap over the
    trial batch (outer, replicated — trials are independent) and GSPMD
    sharding over the agent axis (inner — the collectives of
    `sharded_step_fn` now carry a batch dimension). The returned callable
    maps a (B, ...)-batched state to (final state, time-major batched
    `StepMetrics`), one compiled program per chunk for B x n_ticks ticks.
    """
    st_sh = batched_sim_state_sharding(
        mesh, localization=_loc_in_sharding(cfg, localization),
        checks=_checks_in_sharding(cfg, checks))

    @partial(jax.jit, in_shardings=(st_sh,), donate_argnums=(0,))
    def roll(state):
        return _engine.batched_scan(state, formation_batched, gains,
                                    sparams, cfg, n_ticks)

    return roll
