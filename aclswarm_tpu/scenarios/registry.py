"""Named scenario families: declarative parameter spaces + generators.

A *family* is a named region of the scenario space — "pop-up obstacles
of radius 0.5-1.5 m appearing mid-trial", "a byzantine fifth of the
fleet" — with every free parameter carrying an explicit range. Sampling
is host-side numpy seeded like `faults.sample_schedule` (trial setup,
not device code), so a (family, seed, n) triple is fully reproducible:
the suites commit per-family artifacts keyed on exactly that triple,
the fuzzer sweeps random compositions of the underlying axes, and the
serve layer admits ``{"scenario": {"family": ..., "seed": ...}}``
request params validated against this registry at the door.

Parameter ranges are sized to the engine's safety envelope on purpose:
wind stays below the reference's 0.5 m/s velocity authority (a wind the
controller cannot out-fly would blow the fleet through the room-bounds
contract — that is a scenario DESIGN error, not a system bug, so the
registry refuses to script it), and event ticks land inside the horizon
so recovery is observable. The fuzzer relies on this: a sweep with
`swarmcheck` on must find zero violations on any in-space composition.
"""
from __future__ import annotations

import dataclasses
from typing import Callable

import numpy as np

from aclswarm_tpu.scenarios import timeline
from aclswarm_tpu.scenarios.timeline import (DEFAULT_MAX_OBSTACLES,
                                             DEFAULT_MAX_STAGES, NEVER,
                                             Scenario, no_scenario)

# default scripting horizon in control ticks: family event fractions
# scale to this (override per call for longer suites)
DEFAULT_HORIZON = 1200

# wind magnitudes cap well under the reference 0.5 m/s velocity
# saturation (`SafetyParams.max_vel_xy`): the controller must keep
# positive authority against the worst in-space wind + gusts
_WIND_MAX = 0.25
_GUST_MAX = 0.05


def _ring_points(n: int, radius: float, z: float = 2.0,
                 phase: float = 0.0) -> np.ndarray:
    ang = np.linspace(0, 2 * np.pi, n, endpoint=False) + phase
    return np.stack([radius * np.cos(ang), radius * np.sin(ang),
                     np.full(n, z)], 1)


def _split_points(n: int, radius: float, gap: float,
                  z: float = 2.0) -> np.ndarray:
    """Two half-fleet clusters ``gap`` apart — the split/merge stage."""
    h = n // 2
    a = _ring_points(h, radius, z)
    b = _ring_points(n - h, radius, z)
    a[:, 0] -= gap / 2.0
    b[:, 0] += gap / 2.0
    return np.concatenate([a, b], axis=0)


def formation_scale(n: int) -> float:
    """Ring radius keeping neighbor spacing ~2x the 1.2 m keep-out."""
    return max(3.0, 0.4 * n)


# ---------------------------------------------------------------------------
# axis samplers: each returns a field dict to `Scenario.replace` onto
# `no_scenario` — the composition algebra the fuzzer sweeps

def sample_obstacles(rng: np.random.Generator, n: int, horizon: int,
                     caps: tuple, dtype, *, count: int = 2,
                     radius: float = 1.0, speed: float = 0.0,
                     appear_frac: float = 0.25,
                     vanish_frac: float = 0.75) -> dict:
    K = caps[0]
    count = min(int(count), K)
    span = formation_scale(n)
    center = np.zeros((K, 3))
    vel = np.zeros((K, 3))
    rad = np.zeros((K,))
    appear = np.full((K,), NEVER, np.int32)
    vanish = np.full((K,), NEVER, np.int32)
    for k in range(count):
        ang = rng.uniform(0, 2 * np.pi)
        if speed > 0:
            # crossing track: start outside the cloud, transit through
            center[k] = [-1.5 * span * np.cos(ang),
                         -1.5 * span * np.sin(ang), 2.0]
            vel[k] = [speed * np.cos(ang), speed * np.sin(ang), 0.0]
        else:
            r = rng.uniform(0.3 * span, 0.9 * span)
            center[k] = [r * np.cos(ang), r * np.sin(ang), 2.0]
        rad[k] = radius * rng.uniform(0.7, 1.3)
        appear[k] = np.int32(int(appear_frac * horizon))
        vanish[k] = (np.int32(int(vanish_frac * horizon))
                     if vanish_frac < 1.0 else NEVER)
    return dict(obs_center=np.asarray(center, dtype),
                obs_vel=np.asarray(vel, dtype),
                obs_radius=np.asarray(rad, dtype),
                obs_appear=appear, obs_vanish=vanish)


def sample_wind(rng: np.random.Generator, n: int, horizon: int,
                caps: tuple, dtype, *, wind: float = 0.15,
                gust: float = 0.02, onset_frac: float = 0.3) -> dict:
    wind = min(float(wind), _WIND_MAX)
    ang = rng.uniform(0, 2 * np.pi)
    return dict(
        wind_vel=np.asarray([wind * np.cos(ang), wind * np.sin(ang),
                             0.0], dtype),
        gust_std=np.asarray(min(float(gust), _GUST_MAX), dtype),
        wind_tick=np.int32(int(onset_frac * horizon)))


def sample_noise(rng: np.random.Generator, n: int, horizon: int,
                 caps: tuple, dtype, *, sigma: float = 0.15,
                 onset_frac: float = 0.25) -> dict:
    return dict(noise_std=np.asarray(float(sigma), dtype),
                noise_tick=np.int32(int(onset_frac * horizon)))


def sample_sequence(rng: np.random.Generator, n: int, horizon: int,
                    caps: tuple, dtype, *, stages: int = 2,
                    split: bool = False) -> dict:
    S = caps[1]
    stages = min(int(stages), S)
    base_r = formation_scale(n)
    pts = np.zeros((S, n, 3))
    ticks = np.full((S,), NEVER, np.int32)
    fr = np.linspace(0.35, 0.7, max(stages, 1))
    for s in range(stages):
        if split and s == stages - 1:
            pts[s] = _split_points(n, 0.7 * base_r, 2.5 * base_r)
        else:
            scale = rng.uniform(0.6, 1.4)
            pts[s] = _ring_points(n, scale * base_r,
                                  phase=rng.uniform(0, 2 * np.pi))
        ticks[s] = np.int32(int(fr[s] * horizon))
    return dict(seq_points=np.asarray(pts, dtype), seq_tick=ticks)


def sample_byzantine(rng: np.random.Generator, n: int, horizon: int,
                     caps: tuple, dtype, *, frac: float = 0.2,
                     sigma: float = 1.5, onset_frac: float = 0.3) -> dict:
    k = max(1, int(round(float(frac) * n)))
    mask = np.zeros((n,), bool)
    mask[rng.choice(n, size=min(k, n), replace=False)] = True
    return dict(byz_mask=mask, byz_std=np.asarray(float(sigma), dtype),
                byz_tick=np.int32(int(onset_frac * horizon)))


def sample_drift(rng: np.random.Generator, n: int, horizon: int,
                 caps: tuple, dtype, *, speed: float = 0.05,
                 onset_frac: float = 0.25,
                 rematch_every: int = 0) -> dict:
    speed = min(float(speed), _WIND_MAX)  # same authority argument
    ang = rng.uniform(0, 2 * np.pi)
    return dict(
        drift_vel=np.asarray([speed * np.cos(ang), speed * np.sin(ang),
                              0.0], dtype),
        drift_tick=np.int32(int(onset_frac * horizon)),
        rematch_every=np.int32(int(rematch_every)))


AXES: dict[str, Callable] = {
    "obstacles": sample_obstacles,
    "wind": sample_wind,
    "noise": sample_noise,
    "sequence": sample_sequence,
    "byzantine": sample_byzantine,
    "drift": sample_drift,
}


def compose(n: int, seed: int, parts: dict, *, dtype=None,
            max_obstacles: int = DEFAULT_MAX_OBSTACLES,
            max_stages: int = DEFAULT_MAX_STAGES,
            horizon: int = DEFAULT_HORIZON) -> Scenario:
    """Build a Scenario by composing axis samplers: ``parts`` maps axis
    name (`AXES`) -> kwargs dict for its sampler. Axes are independent
    field groups, so composition is a plain merge onto `no_scenario`."""
    import jax.numpy as jnp

    dtype = jnp.result_type(float) if dtype is None else dtype
    rng = np.random.default_rng(seed)
    caps = (int(max_obstacles), int(max_stages))
    fields: dict = {}
    for axis in sorted(parts):       # order-stable rng consumption
        if axis not in AXES:
            raise ValueError(f"unknown scenario axis {axis!r} "
                             f"(registered: {sorted(AXES)})")
        fields.update(AXES[axis](rng, n, int(horizon), caps, dtype,
                                 **parts[axis]))
    scen = no_scenario(n, max_obstacles=caps[0], max_stages=caps[1],
                       dtype=dtype)
    fields = {k: jnp.asarray(v, getattr(scen, k).dtype)
              for k, v in fields.items()}
    return scen.replace(**fields, key=jnp.asarray(
        timeline.key_leaves(seed), jnp.uint32))


# ---------------------------------------------------------------------------
# named families: the committed scenario vocabulary

@dataclasses.dataclass(frozen=True)
class ScenarioFamily:
    """One named region of scenario space. ``space`` documents every
    overridable parameter as axis.param -> (lo, hi) range or choice
    tuple; ``localization`` names the information model the family's
    axes bite in (the suite runs it accordingly)."""

    name: str
    summary: str
    parts: dict                  # axis -> default sampler kwargs
    space: dict                  # "axis.param" -> (lo, hi) | choices
    localization: str = "truth"


FAMILIES: dict[str, ScenarioFamily] = {f.name: f for f in (
    ScenarioFamily(
        "popup_obstacles",
        "static cylinder obstacles pop up mid-trial and vanish",
        parts={"obstacles": dict(count=2, radius=1.0, speed=0.0)},
        space={"obstacles.count": (1, DEFAULT_MAX_OBSTACLES),
               "obstacles.radius": (0.5, 1.5)}),
    ScenarioFamily(
        "crossing_obstacle",
        "a moving obstacle transits straight through the formation",
        parts={"obstacles": dict(count=1, radius=1.2, speed=0.4,
                                 appear_frac=0.2, vanish_frac=1.0)},
        space={"obstacles.radius": (0.8, 1.5),
               "obstacles.speed": (0.2, 0.6)}),
    ScenarioFamily(
        "wind_gust",
        "steady wind + per-vehicle gusts switch on mid-trial",
        parts={"wind": dict(wind=0.15, gust=0.02)},
        space={"wind.wind": (0.05, _WIND_MAX),
               "wind.gust": (0.0, _GUST_MAX)}),
    ScenarioFamily(
        "sensor_noise",
        "flooded-localization estimate noise switches on mid-trial",
        parts={"noise": dict(sigma=0.15)},
        space={"noise.sigma": (0.05, 0.3)},
        localization="flooded"),
    ScenarioFamily(
        "formation_morph",
        "tick-scheduled formation sequence (morph, then split/merge)",
        parts={"sequence": dict(stages=2, split=True)},
        space={"sequence.stages": (1, DEFAULT_MAX_STAGES)}),
    ScenarioFamily(
        "byzantine_bidders",
        "a masked fraction of the fleet bids on corrupted positions",
        parts={"byzantine": dict(frac=0.2, sigma=1.5)},
        space={"byzantine.frac": (0.1, 0.3),
               "byzantine.sigma": (0.5, 3.0)}),
    ScenarioFamily(
        "goal_drift",
        "the formation drifts; re-matching is throttled to a cadence",
        parts={"drift": dict(speed=0.05, rematch_every=240)},
        space={"drift.speed": (0.02, 0.1),
               "drift.rematch_every": (0, 480)}),
    ScenarioFamily(
        "kitchen_sink",
        "obstacles + wind + morph + byzantine + drift composed",
        parts={"obstacles": dict(count=1, radius=0.8),
               "wind": dict(wind=0.08, gust=0.01),
               "sequence": dict(stages=1, split=False),
               "byzantine": dict(frac=0.15, sigma=1.0),
               "drift": dict(speed=0.03, rematch_every=240)},
        space={}),
)}


def validate(family: str, params: dict | None = None) -> ScenarioFamily:
    """Admission-time check (serve; ValueError = refuse at the door):
    the family exists and every override names a parameter in its
    space as ``"axis.param"`` AND holds a value inside the documented
    range — the safety-envelope claim above is only true for in-space
    scenarios, so an out-of-range override (a 1e6 m noise sigma, an
    arena-spanning obstacle) is a refused request, not a served one."""
    fam = FAMILIES.get(family)
    if fam is None:
        raise ValueError(f"unknown scenario family {family!r} "
                         f"(registered: {sorted(FAMILIES)})")
    for key, val in (params or {}).items():
        if key not in fam.space:
            raise ValueError(
                f"scenario family {family!r} has no parameter {key!r} "
                f"(space: {sorted(fam.space)})")
        lo, hi = fam.space[key]
        if isinstance(val, bool) or not isinstance(val, (int, float)) \
                or not lo <= val <= hi:
            raise ValueError(
                f"scenario override {key}={val!r} outside the "
                f"{family!r} space [{lo}, {hi}]")
    return fam


def sample(family: str, seed: int, n: int, *, dtype=None,
           max_obstacles: int = DEFAULT_MAX_OBSTACLES,
           max_stages: int = DEFAULT_MAX_STAGES,
           horizon: int = DEFAULT_HORIZON,
           params: dict | None = None) -> Scenario:
    """One seeded draw from a family: defaults from the family's
    ``parts``, overridden by ``params`` ("axis.param" keys, validated
    against the space). Deterministic from (family, seed, n, caps)."""
    fam = validate(family, params)
    parts = {axis: dict(kw) for axis, kw in fam.parts.items()}
    for key, val in (params or {}).items():
        axis, pname = key.split(".", 1)
        parts.setdefault(axis, {})[pname] = val
    return compose(n, seed, parts, dtype=dtype,
                   max_obstacles=max_obstacles, max_stages=max_stages,
                   horizon=horizon)
