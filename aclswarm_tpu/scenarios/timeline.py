"""Scenario timelines as data: the FaultSchedule pattern, generalized.

`aclswarm_tpu.faults` proved the design rule this package is built on: a
scripted world is a *pytree of arrays*, never Python control flow. Every
axis below is evaluated as a pure `where`-mask function of the per-trial
``state.tick``, so a `batched_rollout` batch in which every trial flies
a DIFFERENT scenario still compiles to one program and runs under `vmap`
with the shared-tick decimation intact — exactly how heterogeneous fault
scripts already ride the scan (`faults/schedule.py`).

The composable axes (each independent; compose by filling the fields):

- **(a) pop-up / moving obstacles** — time-parameterized cylinder
  tracks. An active obstacle casts a planar velocity-obstacle sector
  with its own keep-out radius, fed into the same avoidance kernel the
  vehicles use (`control.colavoid` grew per-column radii); tracks are
  ``center + vel * t`` with appear/vanish tick windows.
- **(b) wind + sensor noise** — a steady wind field plus per-tick,
  per-vehicle gusts displace the integrated positions (applied after
  the dynamics, BEFORE the fault freeze, so a dead vehicle stays
  frozen even in wind); sensor noise perturbs the flooded estimate
  tables AS CONSUMED (`localization.noised_view` — a measurement-noise
  model: the carried table stays clean, so every consumed estimate
  carries ~one draw of error regardless of trial length, and a
  never-refreshed stale entry cannot random-walk).
- **(c) formation sequences** — tick-indexed formation point tables
  (morph / split / merge as successive stages). While a stage is
  active the engine's *effective* formation replaces points and the
  derived desired-distance matrices; assignment and control both
  follow (the time-varying generalization of a formation dispatch).
- **(d) byzantine bidders** — masked agents lie about their position
  to every assignment solver (per-tick seeded offsets): the
  centralized auction/Sinkhorn see corrupted cost rows, CBAA agents
  bid on corrupted self-positions. Honest consensus extraction is
  preserved — the solvers still emit permutations, which `swarmcheck`'s
  ``assign_perm`` contract oracles.
- **(e) goal drift + re-matching cadence** — formation points translate
  at ``drift_vel`` from ``drift_tick`` (streaming assignment under
  drift, arXiv:1904.04318) while ``rematch_every`` throttles how often
  a scheduled auction's result is *accepted* — the drifting-goals
  re-matching cadence knob.

Zero-cost contracts (both pinned in tests/test_scenarios.py):

- ``scenario=None`` keeps the engine structurally unchanged — every
  scenario site in `sim.engine.step` is Python-gated on it, so the
  lowered HLO of the historical entry points is bit-identical
  (`analysis.trace_audit.verify_zero_cost_off` — the committed
  baseline's pre-scenario digests are unchanged; the `[scenario]`
  variants are additions).
- ``no_scenario(n)`` (all axes inert) is BIT-IDENTICAL to
  ``scenario=None`` in every output — serial, batched, and resumed
  from a checkpoint — because every axis application is a `where`
  against the baseline value. That is what lets scenario-free and
  scenario-ful serve requests share one compiled program
  (`serve.service`, the `no_faults` normalization extended).
"""
from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np
from flax import struct

# np scalar, not jnp: a jax array at import time would initialize the
# XLA backend (same rationale as `faults.schedule.NEVER`)
NEVER = np.int32(2**31 - 1)

# default axis capacities: the STATIC shape caps every Scenario of a
# given n shares (fixed caps keep the pytree structure uniform, so any
# two scenarios — or a scenario and `no_scenario` — stack into one
# batch and one serve bucket; unused slots are inert data)
DEFAULT_MAX_OBSTACLES = 4
DEFAULT_MAX_STAGES = 2

# per-tick key salts: each axis draws from its own fold of the
# per-trial key so composing axes never correlates their randomness
_SALT_BYZ = 1
_SALT_GUST = 2
_SALT_NOISE = 3


@struct.dataclass
class Scenario:
    """One trial's scenario script (all leaves are data; batch by
    stacking). Inert encodings: tick fields hold `NEVER`, masks are
    all-False, magnitudes are zero — see `no_scenario`."""

    # (a) obstacles: cylinder tracks pos(t) = center + vel * (t * dt)
    obs_center: jnp.ndarray   # (K, 3) track origin at tick 0
    obs_vel: jnp.ndarray      # (K, 3) track velocity (m/s)
    obs_radius: jnp.ndarray   # (K,) keep-out radius (m)
    obs_appear: jnp.ndarray   # (K,) int32 pop-up tick; NEVER = inert slot
    obs_vanish: jnp.ndarray   # (K,) int32 disappear tick; NEVER = stays
    # (b) disturbances
    wind_vel: jnp.ndarray     # (3,) steady wind (m/s)
    gust_std: jnp.ndarray     # () per-tick per-vehicle gust std (m/s)
    wind_tick: jnp.ndarray    # () int32 wind onset; NEVER = off
    noise_std: jnp.ndarray    # () flood-estimate noise std (m)
    noise_tick: jnp.ndarray   # () int32 noise onset; NEVER = off
    # (c) formation sequence: stage s becomes active at seq_tick[s]
    seq_points: jnp.ndarray   # (S, n, 3) stage formation point tables
    seq_tick: jnp.ndarray     # (S,) int32 ascending; NEVER = unused slot
    # (d) byzantine bidders
    byz_mask: jnp.ndarray     # (n,) bool dishonest agents
    byz_std: jnp.ndarray      # () reported-position corruption std (m)
    byz_tick: jnp.ndarray     # () int32 corruption onset; NEVER = off
    # (e) goal drift + re-matching cadence
    drift_vel: jnp.ndarray    # (3,) formation drift velocity (m/s)
    drift_tick: jnp.ndarray   # () int32 drift onset; NEVER = off
    rematch_every: jnp.ndarray  # () int32 accepted-auction cadence in
    #                             ticks (0 = every scheduled auction)
    key: jnp.ndarray          # (2,) uint32 per-trial seed (raw key data)

    @property
    def n(self) -> int:
        return self.byz_mask.shape[0]

    @property
    def max_obstacles(self) -> int:
        return self.obs_radius.shape[0]

    @property
    def max_stages(self) -> int:
        return self.seq_tick.shape[0]


def no_scenario(n: int, max_obstacles: int = DEFAULT_MAX_OBSTACLES,
                max_stages: int = DEFAULT_MAX_STAGES,
                dtype=jnp.float32) -> Scenario:
    """The identity scenario: every axis inert. Bit-identical to
    ``scenario=None`` through the whole engine (the parity contract)."""
    K, S = int(max_obstacles), int(max_stages)
    return Scenario(
        obs_center=jnp.zeros((K, 3), dtype),
        obs_vel=jnp.zeros((K, 3), dtype),
        obs_radius=jnp.zeros((K,), dtype),
        obs_appear=jnp.full((K,), NEVER, jnp.int32),
        obs_vanish=jnp.full((K,), NEVER, jnp.int32),
        wind_vel=jnp.zeros((3,), dtype),
        gust_std=jnp.zeros((), dtype),
        wind_tick=jnp.asarray(NEVER, jnp.int32),
        noise_std=jnp.zeros((), dtype),
        noise_tick=jnp.asarray(NEVER, jnp.int32),
        seq_points=jnp.zeros((S, n, 3), dtype),
        seq_tick=jnp.full((S,), NEVER, jnp.int32),
        byz_mask=jnp.zeros((n,), bool),
        byz_std=jnp.zeros((), dtype),
        byz_tick=jnp.asarray(NEVER, jnp.int32),
        drift_vel=jnp.zeros((3,), dtype),
        drift_tick=jnp.asarray(NEVER, jnp.int32),
        rematch_every=jnp.zeros((), jnp.int32),
        key=jnp.zeros((2,), jnp.uint32))


def key_leaves(seed: int) -> np.ndarray:
    """Raw threefry key data for ``seed`` — raw uint32 leaves keep the
    scenario a plain stackable pytree (the `faults.schedule` idiom)."""
    return np.array([(seed >> 32) & 0xFFFFFFFF, seed & 0xFFFFFFFF],
                    np.uint32)


def _folded(scen: Scenario, tick, salt: int):
    k = jax.random.fold_in(jax.random.wrap_key_data(scen.key),
                           jnp.asarray(tick, jnp.int32))
    return jax.random.fold_in(k, salt)


# ---------------------------------------------------------------------------
# per-tick evaluators (pure functions of data: vmap over batched
# scenarios AND batched per-trial ticks, like `faults.schedule.alive_at`)

def obstacles_at(scen: Scenario, tick, dt: float
                 ) -> tuple[jnp.ndarray, jnp.ndarray]:
    """((K, 3) obstacle positions, (K,) active mask) at ``tick``.
    Positions advance along the track regardless of activity (a crossing
    obstacle pops up mid-transit); inert slots are masked out."""
    t = jnp.asarray(tick, jnp.int32)
    active = (t >= scen.obs_appear) & (t < scen.obs_vanish)
    dtc = scen.obs_center.dtype
    pos = scen.obs_center + scen.obs_vel * (t.astype(dtc)
                                            * jnp.asarray(dt, dtc))
    return pos, active


def stage_at(scen: Scenario, tick) -> jnp.ndarray:
    """() int32 active formation-sequence stage at ``tick`` (-1 = the
    dispatched base formation; `NEVER` slots never activate)."""
    t = jnp.asarray(tick, jnp.int32)
    # jaxcheck: disable=JC006 — counts scheduled stages, not agents
    return jnp.sum((scen.seq_tick <= t).astype(jnp.int32)) - 1


def formation_points_at(scen: Scenario, base_points: jnp.ndarray, tick,
                        dt: float) -> tuple[jnp.ndarray, jnp.ndarray]:
    """((n, 3) effective formation points, () bool changed) at ``tick``:
    the active sequence stage's table (else the base points) translated
    by the goal drift. ``changed`` False passes ``base_points`` through
    bitwise — the parity rule."""
    t = jnp.asarray(tick, jnp.int32)
    dtc = base_points.dtype
    stage = stage_at(scen, t)
    staged = scen.seq_points[jnp.clip(stage, 0, scen.max_stages - 1)]
    pts = jnp.where(stage >= 0, staged.astype(dtc), base_points)
    drift_on = t >= scen.drift_tick
    # drift time measured from onset, clamped so pre-onset math is benign
    tf = jnp.maximum(t - scen.drift_tick, 0).astype(dtc) \
        * jnp.asarray(dt, dtc)
    pts = jnp.where(drift_on,
                    pts + scen.drift_vel.astype(dtc)[None, :] * tf, pts)
    return pts, (stage >= 0) | drift_on


def reported_positions(scen: Scenario, q: jnp.ndarray, tick
                       ) -> jnp.ndarray:
    """(n, 3) positions as REPORTED to the assignment layer: byzantine
    agents add a per-tick seeded lie of scale ``byz_std``; honest rows
    pass through bitwise (the masked bid corruption — every solver's
    bids derive from these positions)."""
    t = jnp.asarray(tick, jnp.int32)
    on = t >= scen.byz_tick
    lie = scen.byz_std.astype(q.dtype) * jax.random.normal(
        _folded(scen, t, _SALT_BYZ), q.shape, q.dtype)
    return jnp.where(on & scen.byz_mask[:, None], q + lie, q)


def wind_at(scen: Scenario, tick, dt: float, n: int, dtype
            ) -> tuple[jnp.ndarray, jnp.ndarray]:
    """((n, 3) per-tick position displacement, () bool active): steady
    wind plus per-vehicle gusts, integrated over one control tick."""
    t = jnp.asarray(tick, jnp.int32)
    on = t >= scen.wind_tick
    gust = scen.gust_std.astype(dtype) * jax.random.normal(
        _folded(scen, t, _SALT_GUST), (n, 3), dtype)
    dq = (scen.wind_vel.astype(dtype)[None, :] + gust) \
        * jnp.asarray(dt, dtype)
    return dq, on


def est_noise_at(scen: Scenario, tick, n: int, dtype
                 ) -> tuple[jnp.ndarray, jnp.ndarray]:
    """((n, n, 3) additive estimate noise, () bool active) for the
    flooded localization tables at ``tick``
    (`localization.noised_view`'s operand — applied to the consumed
    view, never the carry). Per-tick seeded: re-running a tick redraws
    the same noise, so checkpoint resume stays bit-identical."""
    t = jnp.asarray(tick, jnp.int32)
    on = (t >= scen.noise_tick) & (scen.noise_std > 0)
    draw = scen.noise_std.astype(dtype) * jax.random.normal(
        _folded(scen, t, _SALT_NOISE), (n, n, 3), dtype)
    return draw, on


def rematch_ok_at(scen: Scenario, tick) -> jnp.ndarray:
    """() bool: may a scheduled auction's result be ACCEPTED this tick?
    ``rematch_every <= 0`` keeps the engine's own cadence; otherwise
    acceptance is throttled to ticks on the scenario's re-matching
    period (the drifting-goals cadence knob — candidates off-cadence
    are discarded exactly like the engine's other gates)."""
    t = jnp.asarray(tick, jnp.int32)
    every = scen.rematch_every
    return (every <= 0) | (t % jnp.maximum(every, 1) == 0)


def scenario_event_at(scen: Scenario, tick) -> jnp.ndarray:
    """() bool: any scenario axis flips state at ``tick`` — an obstacle
    appears/vanishes, a sequence stage lands, or the wind / noise /
    byzantine / drift onset fires. The event that (re)starts the
    recovery clock in `sim.summary` (the scenario analogue of
    `faults.schedule.fault_event_at`)."""
    t = jnp.asarray(tick, jnp.int32)

    def obs_active(tt):
        return (tt >= scen.obs_appear) & (tt < scen.obs_vanish)

    ev = jnp.any(obs_active(t) != obs_active(t - 1))
    ev = ev | (stage_at(scen, t) != stage_at(scen, t - 1))
    for onset in (scen.wind_tick, scen.noise_tick, scen.byz_tick,
                  scen.drift_tick):
        ev = ev | (t == onset)
    return ev
