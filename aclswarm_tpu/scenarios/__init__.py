"""swarmscenario — composable scenario compiler (ROADMAP item 5).

FaultSchedule generalized: scripted worlds are pytree DATA riding in
`SimState`, heterogeneous per trial inside one compiled vmapped scan.
Five independent timeline axes (pop-up/moving obstacles, wind + sensor
noise, tick-scheduled formation sequences, byzantine bidders, goal
drift with a re-matching cadence) compose freely, normalize to
`no_scenario` (bit-identical to ``scenario=None``), and draw from a
declarative family registry that the fuzzer sweeps with `swarmcheck`
invariants as the oracle and the serve layer admits as a first-class
rollout axis. See docs/SCENARIOS.md.
"""
from aclswarm_tpu.scenarios.registry import (AXES, FAMILIES,
                                             ScenarioFamily, compose,
                                             sample, validate)
from aclswarm_tpu.scenarios.timeline import (DEFAULT_MAX_OBSTACLES,
                                             DEFAULT_MAX_STAGES, NEVER,
                                             Scenario, est_noise_at,
                                             formation_points_at,
                                             no_scenario, obstacles_at,
                                             rematch_ok_at,
                                             reported_positions,
                                             scenario_event_at, stage_at,
                                             wind_at)

__all__ = ["Scenario", "no_scenario", "NEVER", "DEFAULT_MAX_OBSTACLES",
           "DEFAULT_MAX_STAGES", "obstacles_at", "stage_at",
           "formation_points_at", "reported_positions", "wind_at",
           "est_noise_at", "rematch_ok_at", "scenario_event_at",
           "AXES", "FAMILIES", "ScenarioFamily", "compose", "sample",
           "validate"]
