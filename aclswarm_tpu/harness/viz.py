"""Rollout visualization: the `viz_commands.py` rviz pipeline, offline.

The reference's only debugging view is rviz markers published live by
`aclswarm/nodes/viz_commands.py`: blue `distcmd` arrows, red safe-command
arrows, black spheres for the centrally-aligned desired formation, quad
meshes (`viz_commands.py:36-50`, README.md:97-100). A TPU rollout is a
batched array, not a live topic stream, so the equivalent here is a
matplotlib renderer over recorded `StepMetrics`: swarm trajectories,
the aligned desired formation with its adjacency edges, per-vehicle
command arrows at a chosen tick, and the supervisor's observable
time-series (|distcmd|, collision-avoidance activity). Headless by
default (Agg backend) — every figure goes to a file, the analogue of
"look at rviz".

Usage:
    from aclswarm_tpu.harness import viz
    viz.plot_rollout(metrics, formation, out="rollout.png")
    viz.plot_timeseries(metrics, out="signals.png", dt=0.01)
"""
from __future__ import annotations

import numpy as np


def _mpl():
    import matplotlib
    matplotlib.use("Agg", force=False)
    import matplotlib.pyplot as plt
    return plt


def aligned_formation(q: np.ndarray, points: np.ndarray,
                      v2f: np.ndarray) -> np.ndarray:
    """Centrally-aligned desired formation (the black spheres of
    `viz_commands.py`, which reuse `assignment.py`'s global alignment):
    formation points mapped into the world by the d=2 Arun fit against the
    current swarm, ordered by vehicle."""
    from aclswarm_tpu.core import geometry
    from aclswarm_tpu.core import perm as permutil
    import jax.numpy as jnp

    q_form = permutil.veh_to_formation_order(jnp.asarray(q),
                                             jnp.asarray(v2f))
    aligned = np.asarray(geometry.align(jnp.asarray(points), q_form, d=2))
    return aligned[np.asarray(v2f)]        # vehicle order


def plot_rollout(metrics, formation, out: str, tick: int = -1,
                 trail: int = 400, elev: float = 35, azim: float = -60):
    """3D view at one tick: trajectories (trail), vehicles, the aligned
    desired formation + graph edges, and distcmd arrows."""
    plt = _mpl()
    q_all = np.asarray(metrics.q)              # (T, n, 3)
    T, n, _ = q_all.shape
    t = tick % T
    q = q_all[t]
    v2f = np.asarray(metrics.v2f[t])
    pts = np.asarray(formation.points)
    adj = np.asarray(formation.adjmat)
    goal = aligned_formation(q, pts, v2f)

    fig = plt.figure(figsize=(8, 7))
    ax = fig.add_subplot(projection="3d")
    t0 = max(0, t - trail)
    for v in range(n):
        ax.plot(*q_all[t0:t + 1, v].T, lw=0.8, alpha=0.5, color=f"C{v % 10}")
        ax.scatter(*q[v], s=40, color=f"C{v % 10}")
    # desired formation: black markers + graph edges (viz_commands.py:36-50)
    ax.scatter(*goal.T, s=60, facecolors="none", edgecolors="k",
               label="aligned formation")
    for i in range(n):
        for j in range(i + 1, n):
            if adj[int(v2f[i]), int(v2f[j])]:
                seg = np.stack([goal[i], goal[j]])
                ax.plot(*seg.T, color="k", lw=0.5, alpha=0.3)
    ax.view_init(elev=elev, azim=azim)
    ax.set_xlabel("x [m]")
    ax.set_ylabel("y [m]")
    ax.set_zlabel("z [m]")
    ax.set_title(f"tick {t} / {T}")
    ax.legend(loc="upper left", fontsize=8)
    fig.tight_layout()
    fig.savefig(out, dpi=120)
    plt.close(fig)
    return out


def plot_timeseries(metrics, out: str, dt: float = 0.01):
    """The supervisor's observables over time: per-vehicle |distcmd| (its
    convergence predicate input) and collision-avoidance activity (its
    gridlock predicate input), plus assignment-change events."""
    plt = _mpl()
    dn = np.asarray(metrics.distcmd_norm)      # (T, n)
    ca = np.asarray(metrics.ca_active)         # (T, n)
    re = np.asarray(metrics.reassigned)        # (T,)
    tt = np.arange(dn.shape[0]) * dt

    fig, axes = plt.subplots(2, 1, figsize=(9, 6), sharex=True)
    axes[0].plot(tt, dn, lw=0.6, alpha=0.6)
    axes[0].plot(tt, dn.mean(1), "k", lw=1.5, label="mean")
    axes[0].axhline(1.0, color="r", ls="--", lw=0.8,
                    label="convergence threshold")
    axes[0].set_ylabel("|distcmd| [m/s]")
    axes[0].legend(fontsize=8)
    axes[1].plot(tt, ca.mean(1), lw=1.0, label="CA-active fraction")
    for te in tt[re]:
        axes[1].axvline(te, color="g", lw=0.6, alpha=0.5)
    axes[1].set_ylabel("collision avoidance")
    axes[1].set_xlabel("t [s]")
    axes[1].set_ylim(-0.05, 1.05)
    axes[1].legend(fontsize=8)
    fig.tight_layout()
    fig.savefig(out, dpi=120)
    plt.close(fig)
    return out
