"""Random formation generation: the `simformN` input generator.

Spec: `aclswarm_sim/nodes/generate_random_formation.py` —

- agents are treated as infinite vertical *cylinders* (the collision
  avoidance strategy is planar), so formation points must keep pairwise
  **xy** distance >= ``min_dist``; points are rejection-sampled uniformly in
  an l x w x h box ([-l/2, l/2] x [-w/2, w/2] x [0, h])
  (`generate_random_formation.py:20-58`);
- the graph is complete, or K_n with m random edges removed, m uniform in
  [1, n-4] — at most n-4 removals so the graph stays generically globally
  rigid in 2D (`:61-73`); swarms with n < 5 are forced fully connected
  (`:118-120`);
- a *group* holds k formations over one shared adjacency, emitted in the
  formation-library dict format so the rest of the stack (loader, precalc,
  trials) treats generated groups exactly like shipped ones (`:90-95`).

Differences from the reference (deliberate): seeding uses
`np.random.default_rng` (stream-stable across NumPy versions, one generator
per call — Monte-Carlo trials pass disjoint seeds); the 5 s wall-clock
sampling timeout is replaced by a deterministic attempt budget so the same
seed always produces the same formation or the same failure; the requested
formation count ``k`` is honored (the reference hardcodes two, `:77-80`).
"""
from __future__ import annotations

import string

import numpy as np

from aclswarm_tpu.harness.formations import FormationSpec


def sample_cylinder_points(rng: np.random.Generator, n: int, l: float,
                           w: float, h: float, min_dist: float,
                           max_attempts: int = 100_000) -> np.ndarray:
    """Rejection-sample ``n`` points whose pairwise xy distance >= min_dist
    (`generate_random_formation.py:26-58`). Returns (n, 3); raises if the box
    can't fit n cylinders within the attempt budget."""
    pts = np.empty((0, 3))
    for _ in range(max_attempts):
        pt = np.array([rng.uniform(-l / 2.0, l / 2.0),
                       rng.uniform(-w / 2.0, w / 2.0),
                       rng.uniform(0.0, h)])
        if pts.shape[0] == 0 or np.all(
                np.linalg.norm(pts[:, :2] - pt[:2], axis=1) >= min_dist):
            pts = np.vstack([pts, pt])
            if pts.shape[0] == n:
                return pts
    raise RuntimeError(
        f"could not place {n} non-overlapping cylinders (min_dist="
        f"{min_dist}) in a {l}x{w}x{h} box within {max_attempts} attempts")


def random_adjmat(rng: np.random.Generator, n: int,
                  fc: bool = False) -> np.ndarray:
    """Complete graph, or K_n minus m random edges with m ~ U[1, n-4]
    (`generate_random_formation.py:61-73`; self-pairs and duplicate draws
    waste a removal, exactly as the reference's random row/col indexing
    does). n < 5 is always fully connected (`:118-120`)."""
    adjmat = np.ones((n, n), dtype=np.int64) - np.eye(n, dtype=np.int64)
    if fc or n < 5:
        return adjmat
    m = rng.integers(1, n - 4 + 1)
    rows = rng.integers(0, n, size=m)
    cols = rng.integers(0, n, size=m)
    for i, j in zip(rows, cols):
        adjmat[i, j] = adjmat[j, i] = 0
    np.fill_diagonal(adjmat, 0)
    return adjmat


def generate_group(n: int, seed: int | None = None, fc: bool = False,
                   l: float = 10.0, w: float = 10.0, h: float = 10.0,
                   min_dist: float = 2.0, k: int = 2) -> dict:
    """A random formation group in the library dict format
    ({agents, adjmat, formations:[{name, points}]}) — the `simformN`
    equivalent of a `formations.yaml` group entry."""
    if n < 3:
        raise ValueError("need at least 3 agents")
    rng = np.random.default_rng(seed)
    adjmat = random_adjmat(rng, n, fc)
    names = list(string.ascii_uppercase)
    formations = [
        {"name": names[i % 26] * (i // 26 + 1),
         "points": sample_cylinder_points(rng, n, l, w, h,
                                          min_dist).tolist()}
        for i in range(k)]
    return {"agents": n, "adjmat": adjmat.tolist(), "formations": formations}


def generate_specs(n: int, seed: int | None = None, **kw
                   ) -> list[FormationSpec]:
    """Same, as loaded `FormationSpec`s (gains left to the caller — trials
    design them on device via `aclswarm_tpu.gains.solve_gains`, the
    reference's solve-on-dispatch path `coordination_ros.cpp:112-119`)."""
    group = generate_group(n, seed, **kw)
    adjmat = np.asarray(group["adjmat"], dtype=np.float64)
    return [FormationSpec(name=f["name"],
                          points=np.asarray(f["points"], dtype=np.float64),
                          adjmat=adjmat, gains=None)
            for f in group["formations"]]


def rigidity_rank_2d(points: np.ndarray, adjmat: np.ndarray) -> int:
    """Rank of the 2D rigidity matrix of (xy of points, graph). A generically
    (infinitesimally) rigid 2D framework on n >= 2 vertices has rank 2n - 3;
    this is the check behind the reference's <= n-4 edge-removal rule (its
    comment `generate_random_formation.py:62` cites 2D global rigidity)."""
    p = np.asarray(points, dtype=np.float64)[:, :2]
    A = np.asarray(adjmat)
    n = p.shape[0]
    edges = [(i, j) for i in range(n) for j in range(i + 1, n) if A[i, j]]
    R = np.zeros((len(edges), 2 * n))
    for row, (i, j) in enumerate(edges):
        d = p[i] - p[j]
        R[row, 2 * i:2 * i + 2] = d
        R[row, 2 * j:2 * j + 2] = -d
    return int(np.linalg.matrix_rank(R))


def is_rigid_2d(points: np.ndarray, adjmat: np.ndarray) -> bool:
    n = np.asarray(points).shape[0]
    return rigidity_rank_2d(points, adjmat) == 2 * n - 3
