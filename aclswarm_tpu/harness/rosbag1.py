"""Pure-Python rosbag (v2.0) ingestion for the hardware-bag reviewer.

The reference reviews real flight recordings by playing a `.bag` through
`review_bag.py`'s metric FSM (`aclswarm/nodes/review_bag.py:80-100`
subscribes `/<veh>/world`, `/<veh>/safety/status`, `/<veh>/assignment`,
`/formation`; `launch/review.launch` wires `rosbag play`), and MATLAB
analysis reads bags directly (`aclswarm_sim/matlab/readACLBag.m:1-30`).
This module gives the TPU framework the same capability without ROS: a
self-contained rosbag1 format reader (records, connections, chunks with
none/bz2 compression) plus hand-rolled deserializers for the exact
message types the aclswarm topics carry, and `bag_to_recording()` which
resamples the topic streams onto the reviewer's 50 Hz tick grid
(`review_bag.py` `tick_rate = 50`) as a `harness.review` recording — so
`review()` / `--analyze` score a hardware bag with the same FSM oracle
that scores sim rollouts.

A minimal writer (single chunk, uncompressed) is included so CI can
fabricate fixture bags through the same serializers the reader decodes
— and so fieldwork can convert npz recordings back into bags for ROS
tooling.

Format reference: the rosbag v2.0 container is records of
``header_len(u32) header data_len(u32) data`` where the header is a
field list (``len(u32) name=value``); op=0x03 bag header, 0x05 chunk,
0x07 connection, 0x02 message data, 0x04/0x06 index (skipped — the
reader scans chunks linearly). All integers little-endian.
"""
from __future__ import annotations

import bz2
import struct
import warnings
from pathlib import Path
from typing import Iterator, NamedTuple, Optional

import numpy as np

MAGIC = b"#ROSBAG V2.0\n"

OP_MSG = 0x02
OP_BAG_HEADER = 0x03
OP_INDEX = 0x04
OP_CHUNK = 0x05
OP_CHUNK_INFO = 0x06
OP_CONNECTION = 0x07

_U32 = struct.Struct("<I")
_U64 = struct.Struct("<Q")


# ---------------------------------------------------------------------------
# low-level record plumbing
# ---------------------------------------------------------------------------

def _pack_header(fields: dict[str, bytes]) -> bytes:
    out = b""
    for name, value in fields.items():
        entry = name.encode() + b"=" + value
        out += _U32.pack(len(entry)) + entry
    return out


def _parse_header(buf: bytes) -> dict[str, bytes]:
    fields, off = {}, 0
    while off < len(buf):
        (ln,) = _U32.unpack_from(buf, off)
        off += 4
        entry = buf[off:off + ln]
        off += ln
        name, _, value = entry.partition(b"=")
        fields[name.decode()] = value
    return fields


def _read_record(buf: bytes, off: int) -> tuple[dict, bytes, int]:
    (hlen,) = _U32.unpack_from(buf, off)
    header = _parse_header(buf[off + 4:off + 4 + hlen])
    off += 4 + hlen
    (dlen,) = _U32.unpack_from(buf, off)
    data = buf[off + 4:off + 4 + dlen]
    return header, data, off + 4 + dlen


def _time_bytes(t: float) -> bytes:
    secs = int(t)
    nsecs = int(round((t - secs) * 1e9))
    return _U32.pack(secs) + _U32.pack(nsecs)


def _time_from(b: bytes) -> float:
    secs, nsecs = struct.unpack("<II", b)
    return secs + nsecs * 1e-9


# ---------------------------------------------------------------------------
# message (de)serializers — the aclswarm topic family
# ---------------------------------------------------------------------------
# ROS1 serialization: little-endian, strings = u32 len + bytes, Header =
# seq(u32) stamp(2xu32) frame_id(string), float64 fields packed raw.

def _ser_string(s: str) -> bytes:
    b = s.encode()
    return _U32.pack(len(b)) + b


def _des_string(buf: bytes, off: int) -> tuple[str, int]:
    (ln,) = _U32.unpack_from(buf, off)
    return buf[off + 4:off + 4 + ln].decode(), off + 4 + ln


def _ser_rosheader(stamp: float, frame_id: str = "", seq: int = 0) -> bytes:
    return _U32.pack(seq) + _time_bytes(stamp) + _ser_string(frame_id)


def _des_rosheader(buf: bytes, off: int) -> tuple[float, str, int]:
    stamp = _time_from(buf[off + 4:off + 12])
    frame_id, off2 = _des_string(buf, off + 12)
    return stamp, frame_id, off2


def ser_pose_stamped(stamp: float, pos, quat=(0.0, 0.0, 0.0, 1.0),
                     frame_id: str = "world") -> bytes:
    """geometry_msgs/PoseStamped (the `/<veh>/world` topic)."""
    return (_ser_rosheader(stamp, frame_id)
            + struct.pack("<3d", *[float(x) for x in pos])
            + struct.pack("<4d", *[float(x) for x in quat]))


def des_pose_stamped(buf: bytes) -> tuple[float, np.ndarray]:
    stamp, _, off = _des_rosheader(buf, 0)
    pos = np.frombuffer(buf, np.float64, 3, off)
    return stamp, pos


def ser_vector3_stamped(stamp: float, vec, frame_id: str = "") -> bytes:
    """geometry_msgs/Vector3Stamped (the `distcmd` topic)."""
    return (_ser_rosheader(stamp, frame_id)
            + struct.pack("<3d", *[float(x) for x in vec]))


def des_vector3_stamped(buf: bytes) -> tuple[float, np.ndarray]:
    stamp, _, off = _des_rosheader(buf, 0)
    return stamp, np.frombuffer(buf, np.float64, 3, off)


def ser_safety_status(stamp: float, ca_active: bool) -> bytes:
    """aclswarm_msgs/SafetyStatus (`SafetyStatus.msg:1-5`: Header +
    bool collision_avoidance_active)."""
    return _ser_rosheader(stamp) + bytes([1 if ca_active else 0])


def des_safety_status(buf: bytes) -> tuple[float, bool]:
    stamp, _, off = _des_rosheader(buf, 0)
    return stamp, bool(buf[off])


def ser_uint8_multiarray(data) -> bytes:
    """std_msgs/UInt8MultiArray as the coordination node publishes the
    `assignment` topic (`coordination_ros.cpp:293-297`): empty layout,
    bare data vector. Raises on values that would wrap (> 255) — use
    `ser_int32_multiarray` for wide assignments."""
    data = np.asarray(data)
    if data.size and (data.min() < 0 or data.max() > 255):
        raise ValueError("values do not fit uint8; use "
                         "ser_int32_multiarray for n > 255 assignments")
    arr = data.astype(np.uint8)
    return (_U32.pack(0)          # layout.dim: empty array
            + _U32.pack(0)        # layout.data_offset
            + _U32.pack(arr.size) + arr.tobytes())


def _des_multiarray(buf: bytes, dtype) -> np.ndarray:
    (ndims,) = _U32.unpack_from(buf, 0)
    off = 4
    for _ in range(ndims):        # label(string) size(u32) stride(u32)
        _, off = _des_string(buf, off)
        off += 8
    off += 4                      # data_offset
    (ln,) = _U32.unpack_from(buf, off)
    return np.frombuffer(buf, dtype, ln, off + 4).copy()


def des_uint8_multiarray(buf: bytes) -> np.ndarray:
    return _des_multiarray(buf, np.uint8)


def ser_int32_multiarray(data) -> bytes:
    """std_msgs/Int32MultiArray — the adapter's wide assignment wire for
    n > 255 (`ros_bridge.assignment_to_ros(wide=True)`); uint8 would wrap
    indices >= 256 into duplicate entries."""
    arr = np.asarray(data, np.int32)
    if np.any(arr != np.asarray(data)):
        raise ValueError("assignment indices do not fit int32")
    return (_U32.pack(0) + _U32.pack(0)
            + _U32.pack(arr.size) + arr.astype("<i4").tobytes())


def des_int32_multiarray(buf: bytes) -> np.ndarray:
    return _des_multiarray(buf, "<i4").astype(np.int32)


MSG_TYPES = {
    "geometry_msgs/PoseStamped": des_pose_stamped,
    "geometry_msgs/Vector3Stamped": des_vector3_stamped,
    "aclswarm_msgs/SafetyStatus": des_safety_status,
    "std_msgs/UInt8MultiArray": des_uint8_multiarray,
    "std_msgs/Int32MultiArray": des_int32_multiarray,
}


# ---------------------------------------------------------------------------
# reader
# ---------------------------------------------------------------------------

class BagMessage(NamedTuple):
    topic: str
    msgtype: str
    time: float          # record (receive) time
    raw: bytes           # serialized message body


def read_bag(path) -> Iterator[BagMessage]:
    """Iterate every message record in a rosbag v2.0 file, in file order.

    Scans chunks linearly (index records are skipped), decompressing
    `none` and `bz2` chunk encodings. Connections may appear before their
    messages in the same chunk or in the index section — both are
    handled."""
    buf = Path(path).read_bytes()
    if not buf.startswith(MAGIC):
        raise ValueError(f"{path}: not a rosbag v2.0 file")
    conns: dict[int, tuple[str, str]] = {}   # conn id -> (topic, type)

    def register_conn(header: dict, data: bytes) -> None:
        cid = _U32.unpack(header["conn"])[0]
        chdr = _parse_header(data)
        conns[cid] = (chdr["topic"].decode(), chdr["type"].decode())

    # pre-scan the top-level records: standard bags keep connection
    # records in the post-chunk index section, AFTER the messages that
    # reference them — register those up front (no chunk decompression)
    off = len(MAGIC)
    while off < len(buf):
        header, data, off = _read_record(buf, off)
        if header["op"][0] == OP_CONNECTION:
            register_conn(header, data)

    def walk(buf: bytes, off: int, end: int) -> Iterator[BagMessage]:
        while off < end:
            header, data, off = _read_record(buf, off)
            op = header["op"][0]
            if op == OP_CONNECTION:
                register_conn(header, data)
            elif op == OP_MSG:
                cid = _U32.unpack(header["conn"])[0]
                topic, mtype = conns[cid]
                yield BagMessage(topic, mtype, _time_from(header["time"]),
                                 data)
            elif op == OP_CHUNK:
                comp = header["compression"].decode()
                if comp == "none":
                    inner = data
                elif comp == "bz2":
                    inner = bz2.decompress(data)
                else:
                    raise ValueError(f"unsupported chunk compression "
                                     f"{comp!r} (none/bz2 handled)")
                yield from walk(inner, 0, len(inner))
            # OP_BAG_HEADER / OP_INDEX / OP_CHUNK_INFO: skip

    yield from walk(buf, len(MAGIC), len(buf))


# ---------------------------------------------------------------------------
# writer (single uncompressed chunk — fixture/export tool)
# ---------------------------------------------------------------------------

class BagWriter:
    """Minimal rosbag v2.0 writer: every message goes into one
    uncompressed chunk; connections are emitted inside the chunk and
    repeated in the index section, with the bag header's index_pos
    patched on close."""

    def __init__(self, path):
        self.path = Path(path)
        self._conns: dict[tuple[str, str], int] = {}
        self._chunk = bytearray()

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.close()

    def _conn_record(self, cid: int, topic: str, msgtype: str) -> bytes:
        chdr = _pack_header({
            "topic": topic.encode(),
            "type": msgtype.encode(),
            "md5sum": b"*",               # wildcard: reader does not check
            "message_definition": b"",
        })
        hdr = _pack_header({"op": bytes([OP_CONNECTION]),
                            "conn": _U32.pack(cid),
                            "topic": topic.encode()})
        return (_U32.pack(len(hdr)) + hdr
                + _U32.pack(len(chdr)) + chdr)

    def write(self, topic: str, msgtype: str, t: float, raw: bytes) -> None:
        key = (topic, msgtype)
        if key not in self._conns:
            cid = self._conns[key] = len(self._conns)
            self._chunk += self._conn_record(cid, topic, msgtype)
        hdr = _pack_header({"op": bytes([OP_MSG]),
                            "conn": _U32.pack(self._conns[key]),
                            "time": _time_bytes(t)})
        self._chunk += _U32.pack(len(hdr)) + hdr
        self._chunk += _U32.pack(len(raw)) + raw

    def close(self) -> None:
        chunk = bytes(self._chunk)
        chunk_hdr = _pack_header({"op": bytes([OP_CHUNK]),
                                  "compression": b"none",
                                  "size": _U32.pack(len(chunk))})
        chunk_rec = (_U32.pack(len(chunk_hdr)) + chunk_hdr
                     + _U32.pack(len(chunk)) + chunk)
        # bag header record is padded to 4096 bytes total with ASCII space
        index_pos = len(MAGIC) + 4096 + len(chunk_rec)
        bh = _pack_header({"op": bytes([OP_BAG_HEADER]),
                           "index_pos": _U64.pack(index_pos),
                           "conn_count": _U32.pack(len(self._conns)),
                           "chunk_count": _U32.pack(1)})
        pad = 4096 - 4 - len(bh) - 4
        bag_header = (_U32.pack(len(bh)) + bh + _U32.pack(pad)
                      + b" " * pad)
        index = b"".join(self._conn_record(cid, topic, mtype)
                         for (topic, mtype), cid in self._conns.items())
        self.path.write_bytes(MAGIC + bag_header + chunk_rec + index)


# ---------------------------------------------------------------------------
# bag -> review recording
# ---------------------------------------------------------------------------

def _veh_of(topic: str, suffix: str) -> Optional[str]:
    parts = topic.strip("/").split("/")
    return parts[0] if len(parts) >= 2 and "/".join(parts[1:]) == suffix \
        else None


# real-flight bags recorded by the reference's bag_record.sh throttle the
# high-rate streams: `review_bag.py:90` subscribes safety/status_throttle,
# and distcmd is recorded as distcmd_throttle — the reader accepts either
# name per vehicle (unthrottled first: it is the denser signal)
SAFETY_SUFFIXES = ("safety/status", "safety/status_throttle")
DISTCMD_SUFFIXES = ("distcmd", "distcmd_throttle")
# topic suffixes that mark a prefix as a real *vehicle* (anchor tags like
# /Tag01/world publish poses only): assignment + the FSM signal streams
_VEHICLE_EVIDENCE = SAFETY_SUFFIXES + DISTCMD_SUFFIXES + ("assignment",)


def bag_to_recording(bagpath, out_npz=None, dt: float = 0.02,
                     vehs: Optional[list[str]] = None) -> dict:
    """Resample a hardware bag's topic streams onto the reviewer's tick
    grid and (optionally) write a `harness.review` recording npz.

    Vehicle discovery starts from the `<veh>/world` topic prefixes
    (`review_bag.py:66-67` scrapes topics; `readACLBag.m:6-10` regexes
    them) but keeps only prefixes that also carry vehicle traffic
    (assignment/safety/distcmd, throttled or not): real bags recorded by
    `bag_record.sh` include the anchor-tag poses `/Tag01/world` /
    `/Tag02/world`, which would otherwise inflate ``n`` and break the
    ``perm.size == n`` assignment check. Pose-only bags (no vehicle
    traffic at all) fall back to every world prefix. Signals:

    - ``q`` from `/<veh>/world` PoseStamped, sample-and-hold;
    - ``ca_active`` from `/<veh>/safety/status` (or the real-flight
      recording's `status_throttle`) SafetyStatus;
    - ``distcmd_norm`` from `/<veh>/distcmd` (or `distcmd_throttle`)
      Vector3Stamped;
    - assignment events from the first vehicle's `/assignment`
      UInt8MultiArray — the reviewer subscribes exactly one
      (`review_bag.py:95-97`); every received message marks an auctioned+
      valid tick (hardware only ever publishes accepted assignments),
      `reassigned` when the permutation changed.

    A discovered vehicle with no safety or no distcmd stream triggers a
    `UserWarning` instead of a silent default — defaults (ca_active
    False, distcmd 0) make the review FSM blind to gridlock and
    instantly "converged" for that vehicle, which is a wrong verdict, not
    a neutral one.

    ``dt`` defaults to 0.02 s — the reviewer's 50 Hz FSM tick
    (`review_bag.py` `tick_rate = 50`).
    """
    streams: dict[str, list] = {}
    for msg in read_bag(bagpath):
        des = MSG_TYPES.get(msg.msgtype)
        if des is None:
            continue
        streams.setdefault(msg.topic, []).append((msg.time, des(msg.raw)))

    if vehs is None:
        worlds = {v for t in streams
                  if (v := _veh_of(t, "world")) is not None}
        evidence = {v for t in streams for sfx in _VEHICLE_EVIDENCE
                    if (v := _veh_of(t, sfx)) is not None}
        if worlds & evidence:
            vehs = sorted(worlds & evidence)
            dropped = sorted(worlds - evidence)
            if dropped:
                warnings.warn(
                    f"{bagpath}: ignoring pose-only topic prefixes "
                    f"{dropped} (anchor tags / non-vehicle frames — no "
                    "assignment/safety/distcmd traffic)")
        else:
            vehs = sorted(worlds)   # pose-only bag: nothing to intersect
    if not vehs:
        raise ValueError(f"{bagpath}: no /<veh>/world pose streams found")
    n = len(vehs)

    def _veh_stream(veh: str, suffixes: tuple[str, ...]) -> Optional[list]:
        for sfx in suffixes:
            series = streams.get(f"/{veh}/{sfx}")
            if series:
                return series
        return None

    t0 = min(t for series in streams.values() for t, _ in series)
    t1 = max(t for series in streams.values() for t, _ in series)
    ticks = max(2, int(np.ceil((t1 - t0) / dt)) + 1)
    grid = t0 + dt * np.arange(ticks)

    def hold(series, default, extract=lambda v: v):
        """Sample-and-hold a stamped series onto the tick grid (the value
        in force at each tick; ``default`` before the first message)."""
        default = np.asarray(default)
        out = np.broadcast_to(default,
                              (ticks,) + default.shape).copy()
        if not series:
            return out
        times = np.asarray([t for t, _ in series])
        # 1 us slack: stamps are ns-quantized on the wire, so a message
        # nominally ON a tick boundary must still belong to that tick
        idx = np.searchsorted(times, grid + 1e-6, side="right") - 1
        vals = [extract(v) for _, v in series]
        for k in range(ticks):
            if idx[k] >= 0:
                out[k] = vals[idx[k]]
        return out

    q = np.zeros((ticks, n, 3))
    ca = np.zeros((ticks, n), bool)
    dn = np.zeros((ticks, n))
    for i, veh in enumerate(vehs):
        poses = streams.get(f"/{veh}/world", [])
        if not poses:
            raise ValueError(f"{bagpath}: vehicle {veh} has no world poses")
        q[:, i, :] = hold(poses, np.zeros(3), extract=lambda v: v[1])
        safety = _veh_stream(veh, SAFETY_SUFFIXES)
        if safety is None:
            warnings.warn(
                f"{bagpath}: vehicle {veh} has no safety status stream "
                f"({' or '.join(SAFETY_SUFFIXES)}); ca_active defaults to "
                "False — the review FSM cannot detect gridlock for it")
        ca[:, i] = hold(safety or [], False, extract=lambda v: v[1])
        distcmd = _veh_stream(veh, DISTCMD_SUFFIXES)
        if distcmd is None:
            warnings.warn(
                f"{bagpath}: vehicle {veh} has no distcmd stream "
                f"({' or '.join(DISTCMD_SUFFIXES)}); |distcmd| defaults "
                "to 0 — the convergence predicate sees it as instantly "
                "converged")
        dn[:, i] = hold(distcmd or [], 0.0,
                        extract=lambda v: float(np.linalg.norm(v[1])))

    auctioned = np.zeros(ticks, bool)
    reassigned = np.zeros(ticks, bool)
    v2f = np.tile(np.arange(n, dtype=np.int32), (ticks, 1))
    asn_series = streams.get(f"/{vehs[0]}/assignment", [])
    prev = None
    size_warned = False
    for t, perm in asn_series:
        k = min(ticks - 1, max(0, int(round((t - t0) / dt))))
        auctioned[k] = True
        perm = np.asarray(perm, np.int32)
        if prev is None or not np.array_equal(perm, prev):
            reassigned[k] = True
        prev = perm
        if perm.size == n:
            v2f[k:] = perm[None, :]
        elif not size_warned:
            # cross-check on vehicle discovery: a real vehicle whose
            # signal topics were all lost is indistinguishable from an
            # anchor tag by topic shape, but the recorded assignment
            # permutations carry the true fleet size
            warnings.warn(
                f"{bagpath}: assignment permutations have size "
                f"{perm.size} but {n} vehicles were discovered — "
                "v2f is left at identity; if a real vehicle's "
                "safety/distcmd/assignment streams are missing from the "
                "bag, pass vehs=[...] explicitly")
            size_warned = True

    rec = {
        "q": q,
        "distcmd_norm": dn,
        "ca_active": ca,
        "reassigned": reassigned,
        "auctioned": auctioned,
        "assign_valid": auctioned.copy(),   # bags carry accepted ones only
        "mode": np.zeros((ticks, n), np.int32),
        "v2f": v2f,
        "dt": np.asarray(dt),
        "meta_source_bag": np.asarray(str(bagpath)),
    }
    if out_npz is not None:
        np.savez_compressed(out_npz, **rec)
    return rec


def recording_to_bag(npz_path, bag_path, vehs: Optional[list[str]] = None,
                     pose_every: int = 1) -> str:
    """Export a `harness.review` npz recording as a rosbag (the writer's
    field use-case: hand a TPU-framework rollout to ROS tooling —
    `rosbag play` + rviz, `readACLBag.m`)."""
    data = np.load(npz_path)
    q = data["q"]
    ticks, n = q.shape[0], q.shape[1]
    dt = float(data["dt"])
    if vehs is None:
        vehs = [f"SQ{i + 1:02d}s" for i in range(n)]
    ca = data["ca_active"]
    dn = data["distcmd_norm"]
    auctioned = data["auctioned"]
    valid = data["assign_valid"]
    v2f = data["v2f"]
    with BagWriter(bag_path) as bag:
        for k in range(0, ticks, pose_every):
            t = k * dt
            for i, veh in enumerate(vehs):
                bag.write(f"/{veh}/world", "geometry_msgs/PoseStamped", t,
                          ser_pose_stamped(t, q[k, i]))
                bag.write(f"/{veh}/safety/status",
                          "aclswarm_msgs/SafetyStatus", t,
                          ser_safety_status(t, bool(ca[k, i])))
                # the bag carries a synthesized unit-direction distcmd of
                # the recorded magnitude (the npz keeps only the norm)
                vec = np.array([dn[k, i], 0.0, 0.0])
                bag.write(f"/{veh}/distcmd",
                          "geometry_msgs/Vector3Stamped", t,
                          ser_vector3_stamped(t, vec))
        # assignment events are sparse and carry the trial's auction
        # history: export EVERY accepted one at its true tick, independent
        # of the pose decimation (with pose_every > 1, events on
        # non-exported ticks would otherwise vanish from the bag)
        for k in np.flatnonzero(np.asarray(auctioned, bool)
                                & np.asarray(valid, bool)):
            t = int(k) * dt
            if n > 255:   # uint8 would wrap indices into duplicates
                bag.write(f"/{vehs[0]}/assignment",
                          "std_msgs/Int32MultiArray", t,
                          ser_int32_multiarray(v2f[k]))
            else:
                bag.write(f"/{vehs[0]}/assignment",
                          "std_msgs/UInt8MultiArray", t,
                          ser_uint8_multiarray(v2f[k]))
    return str(bag_path)
