"""Formation-library loader: the reference's `formations.yaml` format.

Spec: `aclswarm/param/formations.yaml:1-8` (format comment) interpreted with
the operator's exact semantics (`aclswarm/nodes/operator.py:88-109,155-157`):

- a *group* holds ``agents``, an optional group ``adjmat``, and a list of
  formations, each with ``name``, ``points`` (n x 3), optional ``scale``,
  optional per-formation ``adjmat``, optional ``gains`` (3n x 3n);
- if the group supplies any ``adjmat`` key it overrides every formation's own
  (`operator.py:95-103`) — note ``adjmat: fc`` is a *string*, so a group-level
  ``fc`` forces every formation fully connected even when per-formation
  matrices exist (the reference's shipped swarm6_3d yaml has this quirk; this
  framework's library omits the group key there so the sparse per-formation
  graphs — the config its committed gains were designed for — actually fly);
- anything that is not a list at that point becomes fully connected
  (`operator.py:105-109`);
- ``scale`` multiplies the points only — never the gains (`operator.py:155-157`).
"""
from __future__ import annotations

import dataclasses
from pathlib import Path
from typing import Optional

import numpy as np
import yaml

from aclswarm_tpu.core.types import Formation, make_formation

# the framework's own formation library (same file format)
DEFAULT_LIBRARY = Path(__file__).resolve().parent.parent / "param" / "formations.yaml"


@dataclasses.dataclass
class FormationSpec:
    """One loaded formation, host-side (NumPy)."""

    name: str
    points: np.ndarray            # (n, 3), scale already applied
    adjmat: np.ndarray            # (n, n) {0,1}
    gains: Optional[np.ndarray]   # (3n, 3n) or None

    @property
    def n(self) -> int:
        return self.points.shape[0]

    def to_device(self, gains: Optional[np.ndarray] = None) -> Formation:
        """Build the device `Formation` pytree (precomputes dstar matrices)."""
        g = gains if gains is not None else self.gains
        return make_formation(self.points, self.adjmat, g)


def _resolve_adjmat(entry, n: int) -> np.ndarray:
    if isinstance(entry, list):
        return np.asarray(entry, dtype=np.float64)
    return np.ones((n, n)) - np.eye(n)  # 'fc', None, or anything non-list


def load_group(path: str | Path | None = None, group: str = "swarm6_3d"
               ) -> list[FormationSpec]:
    """Load every formation in a group, operator semantics applied."""
    path = Path(path) if path is not None else DEFAULT_LIBRARY
    with open(path) as f:
        lib = yaml.safe_load(f)
    if group not in lib:
        raise KeyError(f"formation group {group!r} not in {path} "
                       f"(available: {[k for k in lib if isinstance(lib[k], dict)]})")
    spec = lib[group]
    n = int(spec["agents"])
    has_global = "adjmat" in spec

    out = []
    for fm in spec["formations"]:
        adj_entry = spec["adjmat"] if has_global else fm.get("adjmat")
        adjmat = _resolve_adjmat(adj_entry, n)
        scale = float(fm.get("scale", 1.0))
        points = scale * np.asarray(fm["points"], dtype=np.float64)
        gains = None
        if "gains" in fm:
            gains = np.asarray(fm["gains"], dtype=np.float64)
            assert gains.shape == (3 * n, 3 * n), fm["name"]
        assert points.shape == (n, 3), fm["name"]
        out.append(FormationSpec(name=str(fm["name"]), points=points,
                                 adjmat=adjmat, gains=gains))
    return out


def min_planar_separation(points: np.ndarray) -> float:
    """Smallest pairwise **xy** distance between formation points.

    Collision avoidance treats vehicles as infinite vertical cylinders
    (planar sectors, `safety.cpp:427-441`), so a commanded formation is
    only *reachable* if every pair of points keeps planar distance above
    ``r_keep_out`` — two points sharing an xy column put their vehicles in
    permanent mutual avoidance regardless of altitude, a gridlock no
    reassignment can escape. Every reference demo formation satisfies
    min_xy >= d_avoid_thresh = 1.5; the simformN generator enforces the
    same invariant by construction (`generate_random_formation.py:26-58`,
    cylinder rejection sampling).
    """
    p = np.asarray(points, dtype=np.float64)
    if p.shape[0] < 2:
        return np.inf
    dxy = np.linalg.norm(p[:, None, :2] - p[None, :, :2], axis=-1)
    return float(dxy[~np.eye(p.shape[0], dtype=bool)].min())


def check_feasible(spec: "FormationSpec", r_keep_out: float = 1.2) -> None:
    """Raise if the formation is unreachable under planar avoidance."""
    sep = min_planar_separation(spec.points)
    if sep <= r_keep_out:
        raise ValueError(
            f"formation {spec.name!r} has min planar point separation "
            f"{sep:.3f} m <= r_keep_out {r_keep_out} m: vehicles on those "
            f"points sit in permanent mutual collision avoidance (planar "
            f"cylinder model), which gridlocks every trial")


def load_formation(name: str, path: str | Path | None = None,
                   group: str = "swarm6_3d") -> FormationSpec:
    """Load a single formation by name from a group."""
    for fm in load_group(path, group):
        if fm.name == name:
            return fm
    raise KeyError(f"formation {name!r} not in group {group!r}")
