"""Trial supervisor: the reference's experiment oracle, evaluated post-hoc.

Spec: `aclswarm_sim/nodes/supervisor.py` — a 50 Hz FSM sampling live topics
into 1 s ring buffers and applying windowed predicates (SURVEY.md §2.2 P7,
§4.4). Because the TPU sim records every control tick of the whole rollout
(`aclswarm_tpu.sim.engine.rollout` metrics), the same predicates are computed
here *after the fact* over the full time series — same thresholds, same
window, no FSM races:

- convergence: every vehicle's windowed-mean |distcmd| < 1.0 m/s
  (`supervisor.py:61,297-316`, ORIG_ZERO_VEL_THR over BUFFER_SECONDS=1);
- gridlock: any vehicle's windowed-mean collision-avoidance-active ratio
  > 0.95 (`supervisor.py:62,318-337`);
- metrics row: per-vehicle smoothed planar distance traveled (EWMA
  alpha=0.98, `supervisor.py:83,452-478`), convergence time, time in
  avoidance, assignment count (`supervisor.py:404-415` CSV schema).
"""
from __future__ import annotations

import dataclasses
from typing import Optional

import numpy as np

BUFFER_SECONDS = 1.0          # supervisor.py:47
ORIG_ZERO_VEL_THR = 1.00      # m/s, supervisor.py:61
AVG_ACTIVE_CA_THR = 0.95      # supervisor.py:62
EWMA_ALPHA = 0.98             # supervisor.py:83
ASSIGNMENT_TIMEOUT = 20.0     # s, supervisor.py:53
GRIDLOCK_TIMEOUT = 90.0       # s, supervisor.py:56
TRIAL_TIMEOUT = 600.0         # s, supervisor.py:57


def rolling_mean(x: np.ndarray, window: int) -> np.ndarray:
    """Rolling mean over the leading (time) axis; row t averages the window
    *ending* at t. Rows before a full window mirror the reference's "not
    enough data" answer by returning +inf-safe NaN."""
    x = np.asarray(x, dtype=np.float64)
    T = x.shape[0]
    out = np.full_like(x, np.nan, dtype=np.float64)
    if T < window:
        return out
    c = np.cumsum(x, axis=0)
    out[window - 1] = c[window - 1] / window
    out[window:] = (c[window:] - c[:-window]) / window
    return out


@dataclasses.dataclass
class TrialResult:
    """One formation's outcome — the CSV row of `supervisor.py:404-415`."""

    converged: bool
    convergence_time_s: Optional[float]   # first tick the predicate held
    gridlocked: bool                      # gridlock predicate ever held
    time_in_gridlock_s: float
    time_in_avoidance_s: np.ndarray       # (n,) per vehicle
    dist_traveled_m: np.ndarray           # (n,) EWMA-smoothed planar distance
    n_reassignments: int
    invalid_auctions: int

    def csv_row(self, trial: int) -> list:
        return ([trial] + self.dist_traveled_m.tolist()
                + [self.convergence_time_s if self.converged else np.nan]
                + [float(np.sum(self.time_in_avoidance_s))]
                + [self.n_reassignments])


def distance_traveled(q: np.ndarray, alpha: float = EWMA_ALPHA) -> np.ndarray:
    """Per-vehicle planar distance through an EWMA position filter
    (`supervisor.py:452-478`): smooth x/y, accumulate |delta| of the filtered
    signal — suppresses jitter so hover doesn't count as travel."""
    q = np.asarray(q)
    fx = q[0, :, 0].copy()
    fy = q[0, :, 1].copy()
    dist = np.zeros(q.shape[1])
    for t in range(1, q.shape[0]):
        nx = alpha * fx + (1 - alpha) * q[t, :, 0]
        ny = alpha * fy + (1 - alpha) * q[t, :, 1]
        dist += np.hypot(nx - fx, ny - fy)
        fx, fy = nx, ny
    return dist


def evaluate(distcmd_norm: np.ndarray, ca_active: np.ndarray,
             q: np.ndarray, reassigned: np.ndarray,
             assign_valid: np.ndarray, dt: float) -> TrialResult:
    """Apply the supervisor predicates to a recorded rollout.

    Args (time-major, from `rollout` metrics, moved to host):
      distcmd_norm: (T, n) per-tick |distcmd|.
      ca_active: (T, n) per-tick collision-avoidance-active flags.
      q: (T, n, 3) positions.
      reassigned / assign_valid: (T,) assignment events.
      dt: control tick period (s).
    """
    distcmd_norm = np.asarray(distcmd_norm)
    ca_active = np.asarray(ca_active, dtype=np.float64)
    window = max(1, int(round(BUFFER_SECONDS / dt)))

    # convergence: windowed per-vehicle mean speed all below threshold
    avg_mag = rolling_mean(distcmd_norm, window)          # (T, n)
    conv_t = np.all(avg_mag < ORIG_ZERO_VEL_THR, axis=1)  # NaN -> False
    converged = bool(conv_t.any())
    conv_time = float(np.argmax(conv_t) * dt) if converged else None

    # gridlock: windowed per-vehicle CA-active ratio, any above threshold
    avg_ca = rolling_mean(ca_active, window)
    grid_t = np.nan_to_num(avg_ca, nan=0.0) > AVG_ACTIVE_CA_THR
    grid_any = grid_t.any(axis=1)
    gridlocked = bool(grid_any.any())

    return TrialResult(
        converged=converged,
        convergence_time_s=conv_time,
        gridlocked=gridlocked,
        time_in_gridlock_s=float(np.sum(grid_any) * dt),
        time_in_avoidance_s=np.sum(ca_active, axis=0) * dt,
        dist_traveled_m=distance_traveled(q),
        n_reassignments=int(np.sum(np.asarray(reassigned))),
        invalid_auctions=int(np.sum(~np.asarray(assign_valid))),
    )
