"""Trial supervisor: the reference's experiment oracle, replayed post-hoc.

Spec: `aclswarm_sim/nodes/supervisor.py` — a 50 Hz FSM sampling live topics
into 1 s ring buffers (SURVEY.md §2.2 P7, §4.4). The TPU sim records every
control tick of the whole rollout (`aclswarm_tpu.sim.engine.rollout`), so the
same FSM is *emulated tick-by-tick over the recorded series* — same states,
same buffer-reset semantics, same thresholds and timeouts:

- convergence predicate: every vehicle's buffered-mean |distcmd| < 1.0 m/s
  (`supervisor.py:61,297-316`); buffers empty on state transitions
  (`supervisor.py:247-249`) except entering IN_FORMATION (reset=False,
  `supervisor.py:199`);
- gridlock predicate: any vehicle's buffered-mean CA-active ratio > 0.95
  (`supervisor.py:62,318-337`); a trial only *terminates* as gridlocked if
  the GRIDLOCK state persists GRIDLOCK_TIMEOUT=90 s (`supervisor.py:211-215`);
- the logged `time_avoidance` is the duration of the last GRIDLOCK episode
  (`supervisor.py:256-265`), NOT per-vehicle avoidance time (kept separately
  here as `time_in_avoidance_s`);
- convergence time runs from FLYING entry to leaving IN_FORMATION after
  CONVERGED_WAIT (`supervisor.py:203-206,397-403` start/stop_logging), so it
  includes the 1 s confirmation dwell, as the reference's CSV does.

This emulation covers the FLYING / IN_FORMATION / GRIDLOCK / COMPLETE /
TERMINATE portion of the FSM — the rollout starts with the swarm already
airborne and assigned (IDLE/TAKING_OFF/HOVERING/WAITING_ON_ASSIGNMENT are
trial-driver concerns, `aclswarm_tpu.harness.trials`).
"""
from __future__ import annotations

import dataclasses
from collections import deque
from typing import Optional

import numpy as np

BUFFER_SECONDS = 1.0          # supervisor.py:47
ZERO_POS_THR = 0.05           # m, supervisor.py:60
ORIG_ZERO_VEL_THR = 1.00      # m/s, supervisor.py:61
AVG_ACTIVE_CA_THR = 0.95      # supervisor.py:62
EWMA_ALPHA = 0.98             # supervisor.py:83
SIM_INIT_TIMEOUT = 10.0       # s, supervisor.py:50
TAKE_OFF_TIMEOUT = 10.0       # s, supervisor.py:51
HOVER_WAIT = 5.0              # s, supervisor.py:52
ASSIGNMENT_TIMEOUT = 20.0     # s, supervisor.py:53
FORMATION_RECEIVED_WAIT = 1.0  # s, supervisor.py:54
CONVERGED_WAIT = 1.0          # s, supervisor.py:55
GRIDLOCK_TIMEOUT = 90.0       # s, supervisor.py:56
TRIAL_TIMEOUT = 600.0         # s, supervisor.py:57


def rolling_mean(x: np.ndarray, window: int) -> np.ndarray:
    """Rolling mean over the leading (time) axis; row t averages the window
    ending at t. Rows before a full window are NaN (the reference's "not
    enough data" answer)."""
    x = np.asarray(x, dtype=np.float64)
    T = x.shape[0]
    out = np.full_like(x, np.nan, dtype=np.float64)
    if T < window:
        return out
    c = np.cumsum(x, axis=0)
    out[window - 1] = c[window - 1] / window
    out[window:] = (c[window:] - c[:-window]) / window
    return out


@dataclasses.dataclass
class TrialResult:
    """One formation's outcome, matching the reference CSV semantics
    (`supervisor.py:404-415`: trial, dist*, time, time_avoidance,
    assignments)."""

    converged: bool
    convergence_time_s: Optional[float]   # FLYING -> out of IN_FORMATION
    gridlocked: bool                      # ever entered the GRIDLOCK state
    gridlock_terminated: bool             # GRIDLOCK persisted >= 90 s
    timed_out: bool                       # trial watchdog (600 s) fired
    last_gridlock_episode_s: float        # the CSV's `time_avoidance` column
    time_in_avoidance_s: np.ndarray       # (n,) per vehicle (extra metric)
    dist_traveled_m: np.ndarray           # (n,) EWMA-smoothed planar distance
    n_reassignments: int
    invalid_auctions: int

    def csv_row(self, trial: int) -> list:
        return ([trial] + self.dist_traveled_m.tolist()
                + [self.convergence_time_s if self.converged else np.nan]
                + [self.last_gridlock_episode_s]
                + [1 + self.n_reassignments])  # counter starts at 1 on log


def distance_traveled(q: np.ndarray, alpha: float = EWMA_ALPHA) -> np.ndarray:
    """Per-vehicle planar distance through an EWMA position filter
    (`supervisor.py:452-478`): smooth x/y, accumulate |delta| of the filtered
    signal — suppresses jitter so hover doesn't count as travel."""
    q = np.asarray(q)
    fx = q[0, :, 0].copy()
    fy = q[0, :, 1].copy()
    dist = np.zeros(q.shape[1])
    for t in range(1, q.shape[0]):
        nx = alpha * fx + (1 - alpha) * q[t, :, 0]
        ny = alpha * fy + (1 - alpha) * q[t, :, 1]
        dist += np.hypot(nx - fx, ny - fy)
        fx, fy = nx, ny
    return dist


class _Buffer:
    """A predicate ring buffer: appended only when its predicate is invoked,
    cleared on (most) state transitions — `supervisor.py:297-346`."""

    def __init__(self, window: int):
        self.window = window
        self.buf: deque = deque(maxlen=window)

    def push(self, sample):
        self.buf.append(sample)

    @property
    def full(self) -> bool:
        return len(self.buf) == self.window

    def mean(self) -> np.ndarray:
        return np.mean(np.asarray(self.buf), axis=0)


# FSM states (subset relevant post-takeoff, supervisor.py:19-28)
FLYING, IN_FORMATION, GRIDLOCK, COMPLETE, TERMINATE = range(5)


def run_fsm(distcmd_norm: np.ndarray, ca_active: np.ndarray, dt: float,
            trial_timeout: float = TRIAL_TIMEOUT):
    """Emulate the supervisor FSM over a recorded rollout (single formation).

    Returns (converged, convergence_time_s, entered_gridlock,
    gridlock_terminated, timed_out, last_gridlock_episode_s, log_stop_tick).
    `log_stop_tick` is the tick where metric logging stops — COMPLETE/TERMINATE
    entry, else the end of the recording — matching the reference's
    start_logging-at-FLYING / stop_logging-at-exit window
    (`supervisor.py:397-403`), so distance metrics exclude post-trial ticks.
    """
    distcmd_norm = np.asarray(distcmd_norm)
    ca_active = np.asarray(ca_active, dtype=np.float64)
    T = distcmd_norm.shape[0]
    window = max(1, int(round(BUFFER_SECONDS / dt)))

    state = FLYING
    ticks_in_state = -1          # next_state resets to -1, ++ at tick top
    conv = _Buffer(window)
    grid = _Buffer(window)
    log_start_t = 0
    conv_time = None
    entered_gridlock = False
    grid_terminated = False
    timed_out = False
    grid_enter_t = None
    last_episode = 0.0
    log_stop_t = T - 1

    def elapsed(secs):
        return ticks_in_state * dt >= secs

    def has_converged(t):
        conv.push(distcmd_norm[t])
        return conv.full and bool(np.all(conv.mean() < ORIG_ZERO_VEL_THR))

    def has_gridlocked(t):
        grid.push(ca_active[t])
        return grid.full and bool(np.any(grid.mean() > AVG_ACTIVE_CA_THR))

    def next_state(new, t, reset=True):
        nonlocal state, ticks_in_state, conv, grid, grid_enter_t, \
            last_episode, entered_gridlock
        if new == GRIDLOCK:
            grid_enter_t = t
            entered_gridlock = True
        if state == GRIDLOCK and grid_enter_t is not None:
            last_episode = (t - grid_enter_t) * dt
            grid_enter_t = None
        state = new
        ticks_in_state = -1
        if reset:
            conv = _Buffer(window)
            grid = _Buffer(window)

    for t in range(T):
        ticks_in_state += 1
        if state == FLYING:
            if elapsed(FORMATION_RECEIVED_WAIT):
                if has_converged(t):
                    next_state(IN_FORMATION, t, reset=False)
                elif has_gridlocked(t):
                    next_state(GRIDLOCK, t)
        elif state == IN_FORMATION:
            if elapsed(CONVERGED_WAIT):
                conv_time = (t - log_start_t) * dt   # stop_logging
                log_stop_t = t
                next_state(COMPLETE, t)
                break
            elif not has_converged(t):
                next_state(FLYING, t)
        elif state == GRIDLOCK:
            # has_left_gridlock: full buffer and predicate false
            left = (not has_gridlocked(t)) and grid.full
            if left:
                next_state(FLYING, t)
            elif elapsed(GRIDLOCK_TIMEOUT):
                grid_terminated = True
                log_stop_t = t
                next_state(TERMINATE, t)
                break
        if t * dt > trial_timeout:                   # watchdog
            timed_out = True
            log_stop_t = t
            next_state(TERMINATE, t)
            break

    # recording ended mid-gridlock: close the open episode so the CSV's
    # time_avoidance column reflects it
    if state == GRIDLOCK and grid_enter_t is not None:
        last_episode = (T - 1 - grid_enter_t) * dt

    return (state == COMPLETE, conv_time, entered_gridlock,
            grid_terminated, timed_out, last_episode, log_stop_t)


def evaluate(distcmd_norm: np.ndarray, ca_active: np.ndarray,
             q: np.ndarray, reassigned: np.ndarray,
             assign_valid: np.ndarray, dt: float) -> TrialResult:
    """Apply the supervisor oracle to a recorded rollout.

    Args (time-major, from `rollout` metrics, moved to host):
      distcmd_norm: (T, n) per-tick |distcmd|.
      ca_active: (T, n) per-tick collision-avoidance-active flags.
      q: (T, n, 3) positions.
      reassigned / assign_valid: (T,) assignment events.
      dt: control tick period (s).
    """
    (converged, conv_time, entered, grid_term, timed_out, last_ep,
     log_stop) = run_fsm(distcmd_norm, ca_active, dt)
    ca = np.asarray(ca_active, dtype=np.float64)
    return TrialResult(
        converged=converged,
        convergence_time_s=conv_time,
        gridlocked=entered,
        gridlock_terminated=grid_term,
        timed_out=timed_out,
        last_gridlock_episode_s=last_ep,
        time_in_avoidance_s=np.sum(ca[:log_stop + 1], axis=0) * dt,
        dist_traveled_m=distance_traveled(np.asarray(q)[:log_stop + 1]),
        n_reassignments=int(np.sum(np.asarray(reassigned)[:log_stop + 1])),
        invalid_auctions=int(np.sum(~np.asarray(
            assign_valid)[:log_stop + 1])),
    )


# ---------------------------------------------------------------------------
# Full trial FSM (all nine reference states, `supervisor.py:19-28`)
# ---------------------------------------------------------------------------

class TrialState:
    """Reference state numbering (`aclswarm_sim/nodes/supervisor.py:19-28`)."""

    IDLE = 1
    TAKING_OFF = 2
    HOVERING = 3
    WAITING_ON_ASSIGNMENT = 4
    FLYING = 5
    IN_FORMATION = 6
    GRIDLOCK = 7
    COMPLETE = 8
    TERMINATE = 9


NAMES = {v: k for k, v in vars(TrialState).items() if not k.startswith("_")}


class TrialFSM:
    """The complete reference trial supervisor, stepped tick-by-tick.

    Unlike `run_fsm` (the post-takeoff single-formation oracle kept for
    rollback-free evaluation of bare rollouts), this class implements the
    whole experiment lifecycle of `aclswarm_sim/nodes/supervisor.py:160-236`:
    IDLE -> TAKING_OFF -> [HOVERING -> WAITING_ON_ASSIGNMENT -> FLYING ->
    IN_FORMATION]* -> COMPLETE, with GRIDLOCK/TERMINATE escapes, the
    SIM_INIT/TAKE_OFF/ASSIGNMENT timeouts, formation cycling through the
    group, and the reference's logging exactly: per-formation convergence
    time / last-gridlock-episode / accepted-assignment count, plus one
    cumulative EWMA-smoothed planar distance per vehicle accumulated only
    while logging (`supervisor.py:372-415,441-478`).

    The trial *driver* (`aclswarm_tpu.harness.trials`) owns the simulation;
    this FSM only observes per-tick signals and returns actions the driver
    must perform — mirroring the reference split where the supervisor calls
    the operator's `change_mode` service and the operator/vehicles do the
    work (`supervisor.py:355-372`).

    Deviations (documented, behavior-preserving in this stack):
    - `has_sim_initialized` is immediately true (the scan engine has no
      process bring-up races to wait out), so IDLE emits 'takeoff' on the
      first tick; the SIM_INIT timeout is retained for API parity.
    - assignment events are the engine's accepted-assignment ticks
      (`StepMetrics.reassigned`), the analogue of the reference's
      `assignment` messages which are published only when an auction result
      differs from the current assignment (`auctioneer.cpp:310-321`).
    """

    def __init__(self, n_vehicles: int, n_formations: int,
                 takeoff_alt: float, dt: float,
                 trial_timeout: float = TRIAL_TIMEOUT):
        self.n = n_vehicles
        self.n_formations = n_formations
        self.takeoff_alt = takeoff_alt
        self.dt = dt
        # the reference's 600 s watchdog (`supervisor.py:57`) was sized for
        # <=15 vehicles in a 15 m box; scale configs (simform1000) pass a
        # larger budget — a config knob, not a predicate change
        self.trial_timeout = trial_timeout
        self.window = max(1, int(round(BUFFER_SECONDS / dt)))

        self.state = TrialState.IDLE
        self.last_state = None
        self.timer_ticks = -1
        self.tick_count = -1
        self.curr_formation_idx = -1
        self.received_assignment = False
        self.is_logging = False
        self._conv = _Buffer(self.window)
        self._grid = _Buffer(self.window)

        # reference log structure (`supervisor.py:372-401,441-478`)
        self.dist = np.zeros(n_vehicles)
        self._fx = None
        self._fy = None
        self.times: list[float] = []
        self.time_avoidance: list[float] = []
        self.assignments: list[int] = []
        self._log_start_tick = 0
        self._grid_enter_tick = None

    # -- predicates (`supervisor.py:270-350`) --

    def _elapsed(self, secs: float) -> bool:
        return self.timer_ticks * self.dt >= secs

    def _has_taken_off(self, q) -> bool:
        return bool(np.all(np.abs(q[:, 2] - self.takeoff_alt)
                           < ZERO_POS_THR))

    def _has_converged(self, distcmd_norm) -> bool:
        self._conv.push(distcmd_norm)
        return self._conv.full and bool(
            np.all(self._conv.mean() < ORIG_ZERO_VEL_THR))

    def _has_gridlocked(self, ca_active) -> bool:
        self._grid.push(np.asarray(ca_active, dtype=np.float64))
        return self._grid.full and bool(
            np.any(self._grid.mean() > AVG_ACTIVE_CA_THR))

    # -- transitions --

    def _next_state(self, state: int, reset: bool = True) -> None:
        self.last_state = self.state
        self.state = state
        self.timer_ticks = -1
        if reset:
            self._conv = _Buffer(self.window)
            self._grid = _Buffer(self.window)
        # gridlock episode bookkeeping (`supervisor.py:256-265`)
        if self.state is TrialState.GRIDLOCK:
            self._grid_enter_tick = self.tick_count
        if self.last_state is TrialState.GRIDLOCK and self.time_avoidance:
            self.time_avoidance[-1] = (
                (self.tick_count - self._grid_enter_tick) * self.dt)
        # a TERMINATE mid-formation finalizes the open log entry so times[]
        # holds elapsed seconds, never a raw start tick (the reference never
        # reads the open entry because it only writes the CSV on COMPLETE)
        if self.state is TrialState.TERMINATE:
            self._stop_logging()

    def _start_logging(self) -> None:
        if self.is_logging:
            return
        self.assignments.append(1)
        self.times.append(self.tick_count)    # finalized in _stop_logging
        self.time_avoidance.append(0.0)
        self.is_logging = True
        self._log_start_tick = self.tick_count

    def _stop_logging(self) -> None:
        if not self.is_logging:
            return
        self.is_logging = False
        self.times[-1] = (self.tick_count - self.times[-1]) * self.dt

    def _log_signals(self, q) -> None:
        """EWMA position smoothing + planar distance (`supervisor.py:441-478`).
        """
        x, y = q[:, 0], q[:, 1]
        if self._fx is None:
            self._fx, self._fy = x.copy(), y.copy()
            return
        nx = EWMA_ALPHA * self._fx + (1 - EWMA_ALPHA) * x
        ny = EWMA_ALPHA * self._fy + (1 - EWMA_ALPHA) * y
        self.dist += np.hypot(nx - self._fx, ny - self._fy)
        self._fx, self._fy = nx, ny

    @property
    def done(self) -> bool:
        return self.state in (TrialState.COMPLETE, TrialState.TERMINATE)

    @property
    def completed(self) -> bool:
        return self.state is TrialState.COMPLETE

    def step(self, q, distcmd_norm, ca_active, assign_event,
             in_formation=None):
        """One supervisor tick (`supervisor.py:160-236`).

        Args are this tick's signals: q (n, 3) true positions, (n,) |distcmd|,
        (n,) collision-avoidance-active, and whether a new assignment was
        accepted this tick. Returns an action for the driver: 'takeoff'
        (send CMD_GO), 'dispatch' (commit the next formation in the group,
        index `curr_formation_idx`), or None.

        ``in_formation`` switches convergence to the human-in-the-loop
        review gate (`review_bag.py:29-60`): when not None, the human
        signal *replaces* the machine convergence predicate — True while
        FLYING declares the formation converged (the `/in_formation`
        service call), True while GRIDLOCK aborts the trial
        (`review_bag.py:168-174`), and IN_FORMATION completes immediately
        (`review_bag.py:214-217` stops logging without a dwell). Gridlock
        detection stays machine-derived, as in the reference reviewer.
        """
        if self.done:
            return None
        self.timer_ticks += 1
        self.tick_count += 1
        if assign_event:
            self.received_assignment = True
            if self.is_logging:
                self.assignments[-1] += 1
        action = None
        S = TrialState

        if self.state is S.IDLE:
            # has_sim_initialized is true by construction in the scan engine
            # (no process bring-up), so IDLE emits 'takeoff' immediately; the
            # reference's SIM_INIT_TIMEOUT escape has nothing to guard
            action = "takeoff"
            self._next_state(S.TAKING_OFF)

        elif self.state is S.TAKING_OFF:
            if self._has_taken_off(q):
                self._next_state(S.HOVERING)
            elif self._elapsed(TAKE_OFF_TIMEOUT):
                self._next_state(S.TERMINATE)

        elif self.state is S.HOVERING:
            if self._elapsed(HOVER_WAIT):
                if self.curr_formation_idx == self.n_formations - 1:
                    self._next_state(S.COMPLETE)
                else:
                    self.curr_formation_idx += 1
                    self.received_assignment = False
                    action = "dispatch"
                    self._next_state(S.WAITING_ON_ASSIGNMENT)

        elif self.state is S.WAITING_ON_ASSIGNMENT:
            if self.received_assignment:
                self._start_logging()
                self._next_state(S.FLYING)
            elif self._elapsed(ASSIGNMENT_TIMEOUT):
                self._next_state(S.TERMINATE)

        elif self.state is S.FLYING:
            if in_formation is not None:
                if in_formation:
                    self._next_state(S.IN_FORMATION, reset=False)
                elif self._has_gridlocked(ca_active):
                    self._next_state(S.GRIDLOCK)
            elif self._elapsed(FORMATION_RECEIVED_WAIT):
                if self._has_converged(distcmd_norm):
                    self._next_state(S.IN_FORMATION, reset=False)
                elif self._has_gridlocked(ca_active):
                    self._next_state(S.GRIDLOCK)

        elif self.state is S.IN_FORMATION:
            if in_formation is not None:
                # the human already confirmed; the reviewer stops logging
                # and moves on without a dwell (`review_bag.py:214-217`)
                self._stop_logging()
                self._next_state(S.HOVERING)
            elif self._elapsed(CONVERGED_WAIT):
                self._stop_logging()
                self._next_state(S.HOVERING)
            elif not self._has_converged(distcmd_norm):
                self._next_state(S.FLYING)

        elif self.state is S.GRIDLOCK:
            if in_formation is not None and in_formation:
                # `/in_formation` during gridlock aborts the trial
                # (`review_bag.py:168-171`)
                self._next_state(S.TERMINATE)
            else:
                left = ((not self._has_gridlocked(ca_active))
                        and self._grid.full)
                if left:
                    self._next_state(S.FLYING)
                elif self._elapsed(GRIDLOCK_TIMEOUT):
                    self._next_state(S.TERMINATE)

        if self.is_logging:
            self._log_signals(q)

        # trial watchdog (`supervisor.py:229-236`)
        if self.tick_count * self.dt > self.trial_timeout and not self.done:
            self._next_state(S.TERMINATE)

        return action

    def csv_row(self, trial: int) -> list:
        """The reference CSV schema (`supervisor.py:404-415`): [trial,
        dist x n, time x K, time_avoidance x K, assignments x K]."""
        return ([trial] + self.dist.tolist() + list(self.times)
                + list(self.time_avoidance) + list(self.assignments))
