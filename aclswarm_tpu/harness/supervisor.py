"""Trial supervisor: the reference's experiment oracle, replayed post-hoc.

Spec: `aclswarm_sim/nodes/supervisor.py` — a 50 Hz FSM sampling live topics
into 1 s ring buffers (SURVEY.md §2.2 P7, §4.4). The TPU sim records every
control tick of the whole rollout (`aclswarm_tpu.sim.engine.rollout`), so the
same FSM is *emulated tick-by-tick over the recorded series* — same states,
same buffer-reset semantics, same thresholds and timeouts:

- convergence predicate: every vehicle's buffered-mean |distcmd| < 1.0 m/s
  (`supervisor.py:61,297-316`); buffers empty on state transitions
  (`supervisor.py:247-249`) except entering IN_FORMATION (reset=False,
  `supervisor.py:199`);
- gridlock predicate: any vehicle's buffered-mean CA-active ratio > 0.95
  (`supervisor.py:62,318-337`); a trial only *terminates* as gridlocked if
  the GRIDLOCK state persists GRIDLOCK_TIMEOUT=90 s (`supervisor.py:211-215`);
- the logged `time_avoidance` is the duration of the last GRIDLOCK episode
  (`supervisor.py:256-265`), NOT per-vehicle avoidance time (kept separately
  here as `time_in_avoidance_s`);
- convergence time runs from FLYING entry to leaving IN_FORMATION after
  CONVERGED_WAIT (`supervisor.py:203-206,397-403` start/stop_logging), so it
  includes the 1 s confirmation dwell, as the reference's CSV does.

This emulation covers the FLYING / IN_FORMATION / GRIDLOCK / COMPLETE /
TERMINATE portion of the FSM — the rollout starts with the swarm already
airborne and assigned (IDLE/TAKING_OFF/HOVERING/WAITING_ON_ASSIGNMENT are
trial-driver concerns, `aclswarm_tpu.harness.trials`).
"""
from __future__ import annotations

import dataclasses
from collections import deque
from typing import Optional

import numpy as np

BUFFER_SECONDS = 1.0          # supervisor.py:47
ORIG_ZERO_VEL_THR = 1.00      # m/s, supervisor.py:61
AVG_ACTIVE_CA_THR = 0.95      # supervisor.py:62
EWMA_ALPHA = 0.98             # supervisor.py:83
FORMATION_RECEIVED_WAIT = 1.0  # s, supervisor.py:54
CONVERGED_WAIT = 1.0          # s, supervisor.py:55
GRIDLOCK_TIMEOUT = 90.0       # s, supervisor.py:56
TRIAL_TIMEOUT = 600.0         # s, supervisor.py:57


def rolling_mean(x: np.ndarray, window: int) -> np.ndarray:
    """Rolling mean over the leading (time) axis; row t averages the window
    ending at t. Rows before a full window are NaN (the reference's "not
    enough data" answer)."""
    x = np.asarray(x, dtype=np.float64)
    T = x.shape[0]
    out = np.full_like(x, np.nan, dtype=np.float64)
    if T < window:
        return out
    c = np.cumsum(x, axis=0)
    out[window - 1] = c[window - 1] / window
    out[window:] = (c[window:] - c[:-window]) / window
    return out


@dataclasses.dataclass
class TrialResult:
    """One formation's outcome, matching the reference CSV semantics
    (`supervisor.py:404-415`: trial, dist*, time, time_avoidance,
    assignments)."""

    converged: bool
    convergence_time_s: Optional[float]   # FLYING -> out of IN_FORMATION
    gridlocked: bool                      # ever entered the GRIDLOCK state
    gridlock_terminated: bool             # GRIDLOCK persisted >= 90 s
    timed_out: bool                       # trial watchdog (600 s) fired
    last_gridlock_episode_s: float        # the CSV's `time_avoidance` column
    time_in_avoidance_s: np.ndarray       # (n,) per vehicle (extra metric)
    dist_traveled_m: np.ndarray           # (n,) EWMA-smoothed planar distance
    n_reassignments: int
    invalid_auctions: int

    def csv_row(self, trial: int) -> list:
        return ([trial] + self.dist_traveled_m.tolist()
                + [self.convergence_time_s if self.converged else np.nan]
                + [self.last_gridlock_episode_s]
                + [1 + self.n_reassignments])  # counter starts at 1 on log


def distance_traveled(q: np.ndarray, alpha: float = EWMA_ALPHA) -> np.ndarray:
    """Per-vehicle planar distance through an EWMA position filter
    (`supervisor.py:452-478`): smooth x/y, accumulate |delta| of the filtered
    signal — suppresses jitter so hover doesn't count as travel."""
    q = np.asarray(q)
    fx = q[0, :, 0].copy()
    fy = q[0, :, 1].copy()
    dist = np.zeros(q.shape[1])
    for t in range(1, q.shape[0]):
        nx = alpha * fx + (1 - alpha) * q[t, :, 0]
        ny = alpha * fy + (1 - alpha) * q[t, :, 1]
        dist += np.hypot(nx - fx, ny - fy)
        fx, fy = nx, ny
    return dist


class _Buffer:
    """A predicate ring buffer: appended only when its predicate is invoked,
    cleared on (most) state transitions — `supervisor.py:297-346`."""

    def __init__(self, window: int):
        self.window = window
        self.buf: deque = deque(maxlen=window)

    def push(self, sample):
        self.buf.append(sample)

    @property
    def full(self) -> bool:
        return len(self.buf) == self.window

    def mean(self) -> np.ndarray:
        return np.mean(np.asarray(self.buf), axis=0)


# FSM states (subset relevant post-takeoff, supervisor.py:19-28)
FLYING, IN_FORMATION, GRIDLOCK, COMPLETE, TERMINATE = range(5)


def run_fsm(distcmd_norm: np.ndarray, ca_active: np.ndarray, dt: float):
    """Emulate the supervisor FSM over a recorded rollout (single formation).

    Returns (converged, convergence_time_s, entered_gridlock,
    gridlock_terminated, timed_out, last_gridlock_episode_s, log_stop_tick).
    `log_stop_tick` is the tick where metric logging stops — COMPLETE/TERMINATE
    entry, else the end of the recording — matching the reference's
    start_logging-at-FLYING / stop_logging-at-exit window
    (`supervisor.py:397-403`), so distance metrics exclude post-trial ticks.
    """
    distcmd_norm = np.asarray(distcmd_norm)
    ca_active = np.asarray(ca_active, dtype=np.float64)
    T = distcmd_norm.shape[0]
    window = max(1, int(round(BUFFER_SECONDS / dt)))

    state = FLYING
    ticks_in_state = -1          # next_state resets to -1, ++ at tick top
    conv = _Buffer(window)
    grid = _Buffer(window)
    log_start_t = 0
    conv_time = None
    entered_gridlock = False
    grid_terminated = False
    timed_out = False
    grid_enter_t = None
    last_episode = 0.0
    log_stop_t = T - 1

    def elapsed(secs):
        return ticks_in_state * dt >= secs

    def has_converged(t):
        conv.push(distcmd_norm[t])
        return conv.full and bool(np.all(conv.mean() < ORIG_ZERO_VEL_THR))

    def has_gridlocked(t):
        grid.push(ca_active[t])
        return grid.full and bool(np.any(grid.mean() > AVG_ACTIVE_CA_THR))

    def next_state(new, t, reset=True):
        nonlocal state, ticks_in_state, conv, grid, grid_enter_t, \
            last_episode, entered_gridlock
        if new == GRIDLOCK:
            grid_enter_t = t
            entered_gridlock = True
        if state == GRIDLOCK and grid_enter_t is not None:
            last_episode = (t - grid_enter_t) * dt
            grid_enter_t = None
        state = new
        ticks_in_state = -1
        if reset:
            conv = _Buffer(window)
            grid = _Buffer(window)

    for t in range(T):
        ticks_in_state += 1
        if state == FLYING:
            if elapsed(FORMATION_RECEIVED_WAIT):
                if has_converged(t):
                    next_state(IN_FORMATION, t, reset=False)
                elif has_gridlocked(t):
                    next_state(GRIDLOCK, t)
        elif state == IN_FORMATION:
            if elapsed(CONVERGED_WAIT):
                conv_time = (t - log_start_t) * dt   # stop_logging
                log_stop_t = t
                next_state(COMPLETE, t)
                break
            elif not has_converged(t):
                next_state(FLYING, t)
        elif state == GRIDLOCK:
            # has_left_gridlock: full buffer and predicate false
            left = (not has_gridlocked(t)) and grid.full
            if left:
                next_state(FLYING, t)
            elif elapsed(GRIDLOCK_TIMEOUT):
                grid_terminated = True
                log_stop_t = t
                next_state(TERMINATE, t)
                break
        if t * dt > TRIAL_TIMEOUT:                   # watchdog
            timed_out = True
            log_stop_t = t
            next_state(TERMINATE, t)
            break

    # recording ended mid-gridlock: close the open episode so the CSV's
    # time_avoidance column reflects it
    if state == GRIDLOCK and grid_enter_t is not None:
        last_episode = (T - 1 - grid_enter_t) * dt

    return (state == COMPLETE, conv_time, entered_gridlock,
            grid_terminated, timed_out, last_episode, log_stop_t)


def evaluate(distcmd_norm: np.ndarray, ca_active: np.ndarray,
             q: np.ndarray, reassigned: np.ndarray,
             assign_valid: np.ndarray, dt: float) -> TrialResult:
    """Apply the supervisor oracle to a recorded rollout.

    Args (time-major, from `rollout` metrics, moved to host):
      distcmd_norm: (T, n) per-tick |distcmd|.
      ca_active: (T, n) per-tick collision-avoidance-active flags.
      q: (T, n, 3) positions.
      reassigned / assign_valid: (T,) assignment events.
      dt: control tick period (s).
    """
    (converged, conv_time, entered, grid_term, timed_out, last_ep,
     log_stop) = run_fsm(distcmd_norm, ca_active, dt)
    ca = np.asarray(ca_active, dtype=np.float64)
    return TrialResult(
        converged=converged,
        convergence_time_s=conv_time,
        gridlocked=entered,
        gridlock_terminated=grid_term,
        timed_out=timed_out,
        last_gridlock_episode_s=last_ep,
        time_in_avoidance_s=np.sum(ca, axis=0) * dt,
        dist_traveled_m=distance_traveled(np.asarray(q)[:log_stop + 1]),
        n_reassignments=int(np.sum(np.asarray(reassigned))),
        invalid_auctions=int(np.sum(~np.asarray(assign_valid))),
    )
