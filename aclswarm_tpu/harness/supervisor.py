"""Trial supervisor: the reference's experiment oracle, replayed post-hoc.

Spec: `aclswarm_sim/nodes/supervisor.py` — a 50 Hz FSM sampling live topics
into 1 s ring buffers (SURVEY.md §2.2 P7, §4.4). The TPU sim records every
control tick of the whole rollout (`aclswarm_tpu.sim.engine.rollout`), so the
same FSM is *emulated tick-by-tick over the recorded series* — same states,
same buffer-reset semantics, same thresholds and timeouts:

- convergence predicate: every vehicle's buffered-mean |distcmd| < 1.0 m/s
  (`supervisor.py:61,297-316`); buffers empty on state transitions
  (`supervisor.py:247-249`) except entering IN_FORMATION (reset=False,
  `supervisor.py:199`);
- gridlock predicate: any vehicle's buffered-mean CA-active ratio > 0.95
  (`supervisor.py:62,318-337`); a trial only *terminates* as gridlocked if
  the GRIDLOCK state persists GRIDLOCK_TIMEOUT=90 s (`supervisor.py:211-215`);
- the logged `time_avoidance` is the duration of the last GRIDLOCK episode
  (`supervisor.py:256-265`), NOT per-vehicle avoidance time (kept separately
  here as `time_in_avoidance_s`);
- convergence time runs from FLYING entry to leaving IN_FORMATION after
  CONVERGED_WAIT (`supervisor.py:203-206,397-403` start/stop_logging), so it
  includes the 1 s confirmation dwell, as the reference's CSV does.

This emulation covers the FLYING / IN_FORMATION / GRIDLOCK / COMPLETE /
TERMINATE portion of the FSM — the rollout starts with the swarm already
airborne and assigned (IDLE/TAKING_OFF/HOVERING/WAITING_ON_ASSIGNMENT are
trial-driver concerns, `aclswarm_tpu.harness.trials`).
"""
from __future__ import annotations

import dataclasses
from collections import deque
from typing import Optional

import numpy as np

BUFFER_SECONDS = 1.0          # supervisor.py:47
ZERO_POS_THR = 0.05           # m, supervisor.py:60
ORIG_ZERO_VEL_THR = 1.00      # m/s, supervisor.py:61
AVG_ACTIVE_CA_THR = 0.95      # supervisor.py:62
EWMA_ALPHA = 0.98             # supervisor.py:83
SIM_INIT_TIMEOUT = 10.0       # s, supervisor.py:50
TAKE_OFF_TIMEOUT = 10.0       # s, supervisor.py:51
HOVER_WAIT = 5.0              # s, supervisor.py:52
ASSIGNMENT_TIMEOUT = 20.0     # s, supervisor.py:53
FORMATION_RECEIVED_WAIT = 1.0  # s, supervisor.py:54
CONVERGED_WAIT = 1.0          # s, supervisor.py:55
GRIDLOCK_TIMEOUT = 90.0       # s, supervisor.py:56
TRIAL_TIMEOUT = 600.0         # s, supervisor.py:57


def rolling_mean(x: np.ndarray, window: int) -> np.ndarray:
    """Rolling mean over the leading (time) axis; row t averages the window
    ending at t. Rows before a full window are NaN (the reference's "not
    enough data" answer)."""
    x = np.asarray(x, dtype=np.float64)
    T = x.shape[0]
    out = np.full_like(x, np.nan, dtype=np.float64)
    if T < window:
        return out
    c = np.cumsum(x, axis=0)
    out[window - 1] = c[window - 1] / window
    out[window:] = (c[window:] - c[:-window]) / window
    return out


@dataclasses.dataclass
class TrialResult:
    """One formation's outcome, matching the reference CSV semantics
    (`supervisor.py:404-415`: trial, dist*, time, time_avoidance,
    assignments)."""

    converged: bool
    convergence_time_s: Optional[float]   # FLYING -> out of IN_FORMATION
    gridlocked: bool                      # ever entered the GRIDLOCK state
    gridlock_terminated: bool             # GRIDLOCK persisted >= 90 s
    timed_out: bool                       # trial watchdog (600 s) fired
    last_gridlock_episode_s: float        # the CSV's `time_avoidance` column
    time_in_avoidance_s: np.ndarray       # (n,) per vehicle (extra metric)
    dist_traveled_m: np.ndarray           # (n,) EWMA-smoothed planar distance
    n_reassignments: int
    invalid_auctions: int

    def csv_row(self, trial: int) -> list:
        return ([trial] + self.dist_traveled_m.tolist()
                + [self.convergence_time_s if self.converged else np.nan]
                + [self.last_gridlock_episode_s]
                + [1 + self.n_reassignments])  # counter starts at 1 on log


def distance_traveled(q: np.ndarray, alpha: float = EWMA_ALPHA) -> np.ndarray:
    """Per-vehicle planar distance through an EWMA position filter
    (`supervisor.py:452-478`): smooth x/y, accumulate |delta| of the filtered
    signal — suppresses jitter so hover doesn't count as travel."""
    q = np.asarray(q)
    fx = q[0, :, 0].copy()
    fy = q[0, :, 1].copy()
    dist = np.zeros(q.shape[1])
    for t in range(1, q.shape[0]):
        nx = alpha * fx + (1 - alpha) * q[t, :, 0]
        ny = alpha * fy + (1 - alpha) * q[t, :, 1]
        dist += np.hypot(nx - fx, ny - fy)
        fx, fy = nx, ny
    return dist


class _Buffer:
    """A predicate ring buffer: appended only when its predicate is invoked,
    cleared on (most) state transitions — `supervisor.py:297-346`."""

    def __init__(self, window: int):
        self.window = window
        self.buf: deque = deque(maxlen=window)

    def push(self, sample):
        self.buf.append(sample)

    @property
    def full(self) -> bool:
        return len(self.buf) == self.window

    def mean(self) -> np.ndarray:
        return np.mean(np.asarray(self.buf), axis=0)

    def snapshot(self):
        """Stacked (k, ...) array of the buffered samples (None when
        empty) — the checkpointable form (docs/RESILIENCE.md)."""
        return np.asarray(list(self.buf)) if self.buf else None

    @classmethod
    def restore(cls, window: int, snap) -> "_Buffer":
        b = cls(window)
        if snap is not None:
            for row in np.asarray(snap):
                b.push(row)
        return b


# FSM states (subset relevant post-takeoff, supervisor.py:19-28)
FLYING, IN_FORMATION, GRIDLOCK, COMPLETE, TERMINATE = range(5)


def run_fsm(distcmd_norm: np.ndarray, ca_active: np.ndarray, dt: float,
            trial_timeout: float = TRIAL_TIMEOUT):
    """Emulate the supervisor FSM over a recorded rollout (single formation).

    Returns (converged, convergence_time_s, entered_gridlock,
    gridlock_terminated, timed_out, last_gridlock_episode_s, log_stop_tick).
    `log_stop_tick` is the tick where metric logging stops — COMPLETE/TERMINATE
    entry, else the end of the recording — matching the reference's
    start_logging-at-FLYING / stop_logging-at-exit window
    (`supervisor.py:397-403`), so distance metrics exclude post-trial ticks.
    """
    distcmd_norm = np.asarray(distcmd_norm)
    ca_active = np.asarray(ca_active, dtype=np.float64)
    T = distcmd_norm.shape[0]
    window = max(1, int(round(BUFFER_SECONDS / dt)))

    state = FLYING
    ticks_in_state = -1          # next_state resets to -1, ++ at tick top
    conv = _Buffer(window)
    grid = _Buffer(window)
    log_start_t = 0
    conv_time = None
    entered_gridlock = False
    grid_terminated = False
    timed_out = False
    grid_enter_t = None
    last_episode = 0.0
    log_stop_t = T - 1

    def elapsed(secs):
        return ticks_in_state * dt >= secs

    def has_converged(t):
        conv.push(distcmd_norm[t])
        return conv.full and bool(np.all(conv.mean() < ORIG_ZERO_VEL_THR))

    def has_gridlocked(t):
        grid.push(ca_active[t])
        return grid.full and bool(np.any(grid.mean() > AVG_ACTIVE_CA_THR))

    def next_state(new, t, reset=True):
        nonlocal state, ticks_in_state, conv, grid, grid_enter_t, \
            last_episode, entered_gridlock
        if new == GRIDLOCK:
            grid_enter_t = t
            entered_gridlock = True
        if state == GRIDLOCK and grid_enter_t is not None:
            last_episode = (t - grid_enter_t) * dt
            grid_enter_t = None
        state = new
        ticks_in_state = -1
        if reset:
            conv = _Buffer(window)
            grid = _Buffer(window)

    for t in range(T):
        ticks_in_state += 1
        if state == FLYING:
            if elapsed(FORMATION_RECEIVED_WAIT):
                if has_converged(t):
                    next_state(IN_FORMATION, t, reset=False)
                elif has_gridlocked(t):
                    next_state(GRIDLOCK, t)
        elif state == IN_FORMATION:
            if elapsed(CONVERGED_WAIT):
                conv_time = (t - log_start_t) * dt   # stop_logging
                log_stop_t = t
                next_state(COMPLETE, t)
                break
            elif not has_converged(t):
                next_state(FLYING, t)
        elif state == GRIDLOCK:
            # has_left_gridlock: full buffer and predicate false
            left = (not has_gridlocked(t)) and grid.full
            if left:
                next_state(FLYING, t)
            elif elapsed(GRIDLOCK_TIMEOUT):
                grid_terminated = True
                log_stop_t = t
                next_state(TERMINATE, t)
                break
        if t * dt > trial_timeout:                   # watchdog
            timed_out = True
            log_stop_t = t
            next_state(TERMINATE, t)
            break

    # recording ended mid-gridlock: close the open episode so the CSV's
    # time_avoidance column reflects it
    if state == GRIDLOCK and grid_enter_t is not None:
        last_episode = (T - 1 - grid_enter_t) * dt

    return (state == COMPLETE, conv_time, entered_gridlock,
            grid_terminated, timed_out, last_episode, log_stop_t)


def evaluate(distcmd_norm: np.ndarray, ca_active: np.ndarray,
             q: np.ndarray, reassigned: np.ndarray,
             assign_valid: np.ndarray, dt: float) -> TrialResult:
    """Apply the supervisor oracle to a recorded rollout.

    Args (time-major, from `rollout` metrics, moved to host):
      distcmd_norm: (T, n) per-tick |distcmd|.
      ca_active: (T, n) per-tick collision-avoidance-active flags.
      q: (T, n, 3) positions.
      reassigned / assign_valid: (T,) assignment events.
      dt: control tick period (s).
    """
    (converged, conv_time, entered, grid_term, timed_out, last_ep,
     log_stop) = run_fsm(distcmd_norm, ca_active, dt)
    ca = np.asarray(ca_active, dtype=np.float64)
    return TrialResult(
        converged=converged,
        convergence_time_s=conv_time,
        gridlocked=entered,
        gridlock_terminated=grid_term,
        timed_out=timed_out,
        last_gridlock_episode_s=last_ep,
        time_in_avoidance_s=np.sum(ca[:log_stop + 1], axis=0) * dt,
        dist_traveled_m=distance_traveled(np.asarray(q)[:log_stop + 1]),
        n_reassignments=int(np.sum(np.asarray(reassigned)[:log_stop + 1])),
        invalid_auctions=int(np.sum(~np.asarray(
            assign_valid)[:log_stop + 1])),
    )


# ---------------------------------------------------------------------------
# Full trial FSM (all nine reference states, `supervisor.py:19-28`)
# ---------------------------------------------------------------------------

class TrialState:
    """Reference state numbering (`aclswarm_sim/nodes/supervisor.py:19-28`)."""

    IDLE = 1
    TAKING_OFF = 2
    HOVERING = 3
    WAITING_ON_ASSIGNMENT = 4
    FLYING = 5
    IN_FORMATION = 6
    GRIDLOCK = 7
    COMPLETE = 8
    TERMINATE = 9


NAMES = {v: k for k, v in vars(TrialState).items() if not k.startswith("_")}


class TrialFSM:
    """The complete reference trial supervisor, stepped tick-by-tick.

    Unlike `run_fsm` (the post-takeoff single-formation oracle kept for
    rollback-free evaluation of bare rollouts), this class implements the
    whole experiment lifecycle of `aclswarm_sim/nodes/supervisor.py:160-236`:
    IDLE -> TAKING_OFF -> [HOVERING -> WAITING_ON_ASSIGNMENT -> FLYING ->
    IN_FORMATION]* -> COMPLETE, with GRIDLOCK/TERMINATE escapes, the
    SIM_INIT/TAKE_OFF/ASSIGNMENT timeouts, formation cycling through the
    group, and the reference's logging exactly: per-formation convergence
    time / last-gridlock-episode / accepted-assignment count, plus one
    cumulative EWMA-smoothed planar distance per vehicle accumulated only
    while logging (`supervisor.py:372-415,441-478`).

    The trial *driver* (`aclswarm_tpu.harness.trials`) owns the simulation;
    this FSM only observes per-tick signals and returns actions the driver
    must perform — mirroring the reference split where the supervisor calls
    the operator's `change_mode` service and the operator/vehicles do the
    work (`supervisor.py:355-372`).

    Deviations (documented, behavior-preserving in this stack):
    - `has_sim_initialized` is immediately true (the scan engine has no
      process bring-up races to wait out), so IDLE emits 'takeoff' on the
      first tick; the SIM_INIT timeout is retained for API parity.
    - assignment events are the engine's accepted-assignment ticks
      (`StepMetrics.reassigned`), the analogue of the reference's
      `assignment` messages which are published only when an auction result
      differs from the current assignment (`auctioneer.cpp:310-321`).
    """

    def __init__(self, n_vehicles: int, n_formations: int,
                 takeoff_alt: float, dt: float,
                 trial_timeout: float = TRIAL_TIMEOUT):
        self.n = n_vehicles
        self.n_formations = n_formations
        self.takeoff_alt = takeoff_alt
        self.dt = dt
        # the reference's 600 s watchdog (`supervisor.py:57`) was sized for
        # <=15 vehicles in a 15 m box; scale configs (simform1000) pass a
        # larger budget — a config knob, not a predicate change
        self.trial_timeout = trial_timeout
        self.window = max(1, int(round(BUFFER_SECONDS / dt)))

        self.state = TrialState.IDLE
        self.last_state = None
        self.timer_ticks = -1
        self.tick_count = -1
        self.curr_formation_idx = -1
        self.received_assignment = False
        self.is_logging = False
        self._conv = _Buffer(self.window)
        self._grid = _Buffer(self.window)

        # reference log structure (`supervisor.py:372-401,441-478`)
        self.dist = np.zeros(n_vehicles)
        self._fx = None
        self._fy = None
        self.times: list[float] = []
        self.time_avoidance: list[float] = []
        self.assignments: list[int] = []
        self._log_start_tick = 0
        self._grid_enter_tick = None

    # -- predicates (`supervisor.py:270-350`) --

    def _elapsed(self, secs: float) -> bool:
        return self.timer_ticks * self.dt >= secs

    def _has_taken_off(self, q) -> bool:
        return bool(np.all(np.abs(q[:, 2] - self.takeoff_alt)
                           < ZERO_POS_THR))

    def _has_converged(self, distcmd_norm) -> bool:
        self._conv.push(distcmd_norm)
        return self._conv.full and bool(
            np.all(self._conv.mean() < ORIG_ZERO_VEL_THR))

    def _has_gridlocked(self, ca_active) -> bool:
        self._grid.push(np.asarray(ca_active, dtype=np.float64))
        return self._grid.full and bool(
            np.any(self._grid.mean() > AVG_ACTIVE_CA_THR))

    # -- transitions --

    def _next_state(self, state: int, reset: bool = True) -> None:
        self.last_state = self.state
        self.state = state
        self.timer_ticks = -1
        if reset:
            self._conv = _Buffer(self.window)
            self._grid = _Buffer(self.window)
        # gridlock episode bookkeeping (`supervisor.py:256-265`)
        if self.state is TrialState.GRIDLOCK:
            self._grid_enter_tick = self.tick_count
        if self.last_state is TrialState.GRIDLOCK and self.time_avoidance:
            self.time_avoidance[-1] = (
                (self.tick_count - self._grid_enter_tick) * self.dt)
        # a TERMINATE mid-formation finalizes the open log entry so times[]
        # holds elapsed seconds, never a raw start tick (the reference never
        # reads the open entry because it only writes the CSV on COMPLETE)
        if self.state is TrialState.TERMINATE:
            self._stop_logging()

    def _start_logging(self) -> None:
        if self.is_logging:
            return
        self.assignments.append(1)
        self.times.append(self.tick_count)    # finalized in _stop_logging
        self.time_avoidance.append(0.0)
        self.is_logging = True
        self._log_start_tick = self.tick_count

    def _stop_logging(self) -> None:
        if not self.is_logging:
            return
        self.is_logging = False
        self.times[-1] = (self.tick_count - self.times[-1]) * self.dt

    def _log_signals(self, q) -> None:
        """EWMA position smoothing + planar distance (`supervisor.py:441-478`).
        """
        x, y = q[:, 0], q[:, 1]
        if self._fx is None:
            self._fx, self._fy = x.copy(), y.copy()
            return
        nx = EWMA_ALPHA * self._fx + (1 - EWMA_ALPHA) * x
        ny = EWMA_ALPHA * self._fy + (1 - EWMA_ALPHA) * y
        self.dist += np.hypot(nx - self._fx, ny - self._fy)
        self._fx, self._fy = nx, ny

    @property
    def done(self) -> bool:
        return self.state in (TrialState.COMPLETE, TrialState.TERMINATE)

    @property
    def completed(self) -> bool:
        return self.state is TrialState.COMPLETE

    def step(self, q, distcmd_norm, ca_active, assign_event,
             in_formation=None):
        """One supervisor tick (`supervisor.py:160-236`).

        Args are this tick's signals: q (n, 3) true positions, (n,) |distcmd|,
        (n,) collision-avoidance-active, and whether a new assignment was
        accepted this tick. Returns an action for the driver: 'takeoff'
        (send CMD_GO), 'dispatch' (commit the next formation in the group,
        index `curr_formation_idx`), or None.

        ``in_formation`` switches convergence to the human-in-the-loop
        review gate (`review_bag.py:29-60`): when not None, the human
        signal *replaces* the machine convergence predicate — True while
        FLYING declares the formation converged (the `/in_formation`
        service call), True while GRIDLOCK aborts the trial
        (`review_bag.py:168-174`), and IN_FORMATION completes immediately
        (`review_bag.py:214-217` stops logging without a dwell). Gridlock
        detection stays machine-derived, as in the reference reviewer.
        """
        if self.done:
            return None
        self.timer_ticks += 1
        self.tick_count += 1
        if assign_event:
            self.received_assignment = True
            if self.is_logging:
                self.assignments[-1] += 1
        action = None
        S = TrialState

        if self.state is S.IDLE:
            # has_sim_initialized is true by construction in the scan engine
            # (no process bring-up), so IDLE emits 'takeoff' immediately; the
            # reference's SIM_INIT_TIMEOUT escape has nothing to guard
            action = "takeoff"
            self._next_state(S.TAKING_OFF)

        elif self.state is S.TAKING_OFF:
            if self._has_taken_off(q):
                self._next_state(S.HOVERING)
            elif self._elapsed(TAKE_OFF_TIMEOUT):
                self._next_state(S.TERMINATE)

        elif self.state is S.HOVERING:
            if self._elapsed(HOVER_WAIT):
                if self.curr_formation_idx == self.n_formations - 1:
                    self._next_state(S.COMPLETE)
                else:
                    self.curr_formation_idx += 1
                    self.received_assignment = False
                    action = "dispatch"
                    self._next_state(S.WAITING_ON_ASSIGNMENT)

        elif self.state is S.WAITING_ON_ASSIGNMENT:
            if self.received_assignment:
                self._start_logging()
                self._next_state(S.FLYING)
            elif self._elapsed(ASSIGNMENT_TIMEOUT):
                self._next_state(S.TERMINATE)

        elif self.state is S.FLYING:
            if in_formation is not None:
                if in_formation:
                    self._next_state(S.IN_FORMATION, reset=False)
                elif self._has_gridlocked(ca_active):
                    self._next_state(S.GRIDLOCK)
            elif self._elapsed(FORMATION_RECEIVED_WAIT):
                if self._has_converged(distcmd_norm):
                    self._next_state(S.IN_FORMATION, reset=False)
                elif self._has_gridlocked(ca_active):
                    self._next_state(S.GRIDLOCK)

        elif self.state is S.IN_FORMATION:
            if in_formation is not None:
                # the human already confirmed; the reviewer stops logging
                # and moves on without a dwell (`review_bag.py:214-217`)
                self._stop_logging()
                self._next_state(S.HOVERING)
            elif self._elapsed(CONVERGED_WAIT):
                self._stop_logging()
                self._next_state(S.HOVERING)
            elif not self._has_converged(distcmd_norm):
                self._next_state(S.FLYING)

        elif self.state is S.GRIDLOCK:
            if in_formation is not None and in_formation:
                # `/in_formation` during gridlock aborts the trial
                # (`review_bag.py:168-171`)
                self._next_state(S.TERMINATE)
            else:
                left = ((not self._has_gridlocked(ca_active))
                        and self._grid.full)
                if left:
                    self._next_state(S.FLYING)
                elif self._elapsed(GRIDLOCK_TIMEOUT):
                    self._next_state(S.TERMINATE)

        if self.is_logging:
            self._log_signals(q)

        # trial watchdog (`supervisor.py:229-236`)
        if self.tick_count * self.dt > self.trial_timeout and not self.done:
            self._next_state(S.TERMINATE)

        return action

    def csv_row(self, trial: int) -> list:
        """The reference CSV schema (`supervisor.py:404-415`): [trial,
        dist x n, time x K, time_avoidance x K, assignments x K]."""
        return ([trial] + self.dist.tolist() + list(self.times)
                + list(self.time_avoidance) + list(self.assignments))

    # -- checkpointing (docs/RESILIENCE.md): the FSM's mutable state as a
    # plain dict of scalars/lists/arrays — constructor parameters are NOT
    # snapshotted (the resuming driver rebuilds them from its config, and
    # the checkpoint manifest's config hash guarantees they agree)

    _SNAP_FIELDS = ("state", "last_state", "timer_ticks", "tick_count",
                    "curr_formation_idx", "received_assignment",
                    "is_logging", "times", "time_avoidance", "assignments",
                    "_log_start_tick", "_grid_enter_tick")

    def snapshot(self) -> dict:
        snap = {k: getattr(self, k) for k in self._SNAP_FIELDS}
        snap["dist"] = self.dist.copy()
        snap["_fx"] = None if self._fx is None else self._fx.copy()
        snap["_fy"] = None if self._fy is None else self._fy.copy()
        snap["conv_buf"] = self._conv.snapshot()
        snap["grid_buf"] = self._grid.snapshot()
        return snap

    def restore(self, snap: dict) -> "TrialFSM":
        for k in self._SNAP_FIELDS:
            setattr(self, k, snap[k])
        # json round-trips lists, not the originals' copies
        self.times = list(snap["times"])
        self.time_avoidance = list(snap["time_avoidance"])
        self.assignments = list(snap["assignments"])
        self.dist = np.asarray(snap["dist"]).copy()
        self._fx = None if snap["_fx"] is None \
            else np.asarray(snap["_fx"]).copy()
        self._fy = None if snap["_fy"] is None \
            else np.asarray(snap["_fy"]).copy()
        self._conv = _Buffer.restore(self.window, snap["conv_buf"])
        self._grid = _Buffer.restore(self.window, snap["grid_buf"])
        return self


# ---------------------------------------------------------------------------
# Summary-driven trial FSM (batched trials: on-device metric reduction)
# ---------------------------------------------------------------------------

class SummaryTrialFSM:
    """`TrialFSM` semantics driven by per-chunk *device summaries* instead
    of per-tick per-vehicle arrays (`aclswarm_tpu.sim.summary`).

    Equivalence argument: the reference supervisor's ring buffers are
    pushed exactly once per tick a predicate is evaluated and evaluation
    ticks are consecutive within a state episode, so the buffer always
    holds the trailing ``min(pushes, W)`` ticks. A full-buffer mean
    therefore equals the trailing-W-tick mean the device computes
    (`ChunkSummary.conv_all`/`grid_any`), and "buffer full" is just
    ``pushes >= W`` — an integer this class counts. The per-tick Python
    loop of the serial driver collapses to vectorized NumPy over the
    chunk axis: inside one FSM state, the exit tick is the argmax of a
    boolean predicate array, so a chunk is processed in O(transitions)
    slice scans instead of O(ticks) steps.

    Metric deviations vs the tick-exact `TrialFSM` (both documented in
    docs/BATCHED_TRIALS.md; FSM *decisions* — states, times, assignment
    counts, gridlock episodes — are tick-exact):

    - ``dist`` differences the device's trial-cumulative EWMA distance at
      chunk boundaries, so each logging window is quantized to the chunk
      grid (both ends are hover dwell, where the EWMA filter suppresses
      accumulation) and the filter runs *through* inter-formation gaps
      instead of freezing (`supervisor.py:441-478` only smooths while
      logging).
    """

    def __init__(self, n_vehicles: int, n_formations: int,
                 takeoff_alt: float, dt: float,
                 trial_timeout: float = TRIAL_TIMEOUT):
        self.n = n_vehicles
        self.n_formations = n_formations
        self.takeoff_alt = takeoff_alt
        self.dt = dt
        self.trial_timeout = trial_timeout
        self.window = max(1, int(round(BUFFER_SECONDS / dt)))

        self.state = TrialState.IDLE
        self.timer_ticks = -1      # as of the last processed tick
        self.tick_count = -1
        self.curr_formation_idx = -1
        self.is_logging = False
        self._conv_pushes = 0
        self._grid_pushes = 0
        self._formation_just_received = False

        self.dist = np.zeros(n_vehicles)
        self.times: list[float] = []
        self.time_avoidance: list[float] = []
        self.assignments: list[int] = []
        self._log_start_tick = 0
        self._grid_enter_tick = None
        self._last_cumdist = None   # device cumdist at the last chunk end
        self._dist_mark = None      # cumdist at the logging-start boundary
        self._dist_pending = False  # stop seen, flush at next chunk end

    # -- exact float-threshold replication -------------------------------
    # The reference compares `ticks * dt >= secs` per tick; the smallest
    # qualifying integer is found by direct search around ceil() so the
    # vectorized FSM fires on exactly the tick the per-tick loop would
    # (0.01 is not exact in binary; an analytic ceil can be off by one).

    def _ticks_for(self, secs: float) -> int:
        k = max(0, int(np.ceil(secs / self.dt)) - 2)
        while k * self.dt < secs:
            k += 1
        return k

    def _ticks_strict(self, secs: float) -> int:
        k = max(0, int(np.ceil(secs / self.dt)) - 2)
        while not (k * self.dt > secs):
            k += 1
        return k

    @property
    def done(self) -> bool:
        return self.state in (TrialState.COMPLETE, TrialState.TERMINATE)

    @property
    def completed(self) -> bool:
        return self.state is TrialState.COMPLETE

    # -- driver hooks ----------------------------------------------------

    def formation_dispatched(self) -> None:
        """The driver applied this trial's pending formation commit: the
        next valid auction counts as an accepted assignment even if the
        permutation is unchanged (`auctioneer.cpp:310-316`)."""
        self._formation_just_received = True

    def observe_cumdist(self, cumdist: np.ndarray) -> None:
        """Record the device's trial-cumulative EWMA distance at this
        chunk's end; flushes a logging window closed earlier in the
        chunk."""
        self._last_cumdist = np.asarray(cumdist, np.float64).copy()
        if self._dist_pending:
            self._flush_dist()

    def _flush_dist(self) -> None:
        if self._last_cumdist is not None:
            mark = 0.0 if self._dist_mark is None else self._dist_mark
            self.dist += self._last_cumdist - mark
        self._dist_pending = False

    # -- logging (`supervisor.py:372-415`) -------------------------------

    def _start_logging(self) -> None:
        if self.is_logging:
            return
        self.assignments.append(1)
        self.times.append(self.tick_count)   # finalized in _stop_logging
        self.time_avoidance.append(0.0)
        self.is_logging = True
        self._log_start_tick = self.tick_count
        if self._dist_pending:   # stop earlier in this same chunk: flush
            self._flush_dist()   # with the best boundary available
        self._dist_mark = (None if self._last_cumdist is None
                           else self._last_cumdist.copy())

    def _stop_logging(self) -> None:
        if not self.is_logging:
            return
        self.is_logging = False
        self.times[-1] = (self.tick_count - self.times[-1]) * self.dt
        self._dist_pending = True

    # -- transitions -----------------------------------------------------

    def _to(self, state: int, reset: bool = True) -> None:
        last = self.state
        self.state = state
        self.timer_ticks = -1
        if reset:
            self._conv_pushes = 0
            self._grid_pushes = 0
        if state is TrialState.GRIDLOCK:
            self._grid_enter_tick = self.tick_count
        if last is TrialState.GRIDLOCK and self.time_avoidance:
            self.time_avoidance[-1] = (
                (self.tick_count - self._grid_enter_tick) * self.dt)
        if state is TrialState.TERMINATE:
            self._stop_logging()

    @staticmethod
    def _pick(*cands):
        """Earliest candidate tick; list order breaks ties (= the serial
        FSM's within-tick branch order)."""
        best = None
        for sp, tag in cands:
            if sp is not None and (best is None or sp < best[0]):
                best = (sp, tag)
        return best

    # -- the chunk processor ---------------------------------------------

    def process_chunk(self, conv_ok, grid_ok, taken_off, auction_ok,
                      reassigned) -> list[str]:
        """Advance the FSM over one chunk of per-tick device summaries.

        Args are (T,) bool arrays (`ChunkSummary` fields for one trial;
        ``auction_ok`` = auctioned & assign_valid). Returns the driver
        actions emitted this chunk, in order: 'takeoff' (send CMD_GO next
        chunk) and/or 'dispatch' (commit formation `curr_formation_idx`
        at the next chunk boundary; later events this chunk are
        suppressed, as in the serial driver)."""
        S = TrialState
        conv_ok = np.asarray(conv_ok, bool)
        grid_ok = np.asarray(grid_ok, bool)
        taken_off = np.asarray(taken_off, bool)
        ev = np.asarray(reassigned, bool).copy()
        T = ev.shape[0]
        if self._formation_just_received:
            hit = np.flatnonzero(np.asarray(auction_ok, bool))
            if hit.size:
                ev[int(hit[0])] = True
                self._formation_just_received = False
        actions: list[str] = []
        W = self.window
        s = 0
        while s < T and not self.done:
            t0 = self.timer_ticks
            base = self.tick_count

            def first(mask, frm):
                idx = np.flatnonzero(mask)
                return frm + int(idx[0]) if idx.size else None

            def at_elapsed(secs):
                return s + max(0, self._ticks_for(secs) - t0 - 1)

            s_w = s + max(0,
                          self._ticks_strict(self.trial_timeout) - base - 1)
            fly_gate = None

            if self.state is S.IDLE:
                cand = (s, "takeoff")
            elif self.state is S.TAKING_OFF:
                cand = self._pick(
                    (first(taken_off[s:], s), "hover"),
                    (at_elapsed(TAKE_OFF_TIMEOUT), "terminate"))
            elif self.state is S.HOVERING:
                cand = (at_elapsed(HOVER_WAIT), "hover_done")
            elif self.state is S.WAITING_ON_ASSIGNMENT:
                cand = self._pick(
                    (first(ev[s:], s), "fly"),
                    (at_elapsed(ASSIGNMENT_TIMEOUT), "terminate"))
            elif self.state is S.FLYING:
                fly_gate = at_elapsed(FORMATION_RECEIVED_WAIT)
                a = b = None
                if fly_gate <= T - 1:
                    k = np.arange(fly_gate, T) - fly_gate + 1
                    mc = conv_ok[fly_gate:] & (self._conv_pushes + k >= W)
                    mg = (grid_ok[fly_gate:]
                          & (self._grid_pushes + k >= W) & ~mc)
                    a = first(mc, fly_gate)
                    b = first(mg, fly_gate)
                cand = self._pick((a, "inform"), (b, "gridlock"))
            elif self.state is S.IN_FORMATION:
                k = np.arange(s, T) - s + 1
                notconv = ~(conv_ok[s:] & (self._conv_pushes + k >= W))
                cand = self._pick(
                    (at_elapsed(CONVERGED_WAIT), "complete"),
                    (first(notconv, s), "unconverged"))
            elif self.state is S.GRIDLOCK:
                k = np.arange(s, T) - s + 1
                left = (~grid_ok[s:]) & (self._grid_pushes + k >= W)
                cand = self._pick(
                    (first(left, s), "gridlock_left"),
                    (at_elapsed(GRIDLOCK_TIMEOUT), "gridlock_timeout"))
            else:                                 # pragma: no cover
                raise RuntimeError(f"bad state {self.state}")

            if cand is not None and cand[0] > T - 1:
                cand = None
            e = T - 1 if cand is None else cand[0]
            e = min(e, s_w)
            state_fire = cand is not None and cand[0] == e
            tag = cand[1] if state_fire else None
            ticks_run = e - s + 1
            self.tick_count = base + ticks_run
            self.timer_ticks = t0 + ticks_run

            # push counters + event accounting over the processed run
            if self.state is S.FLYING and fly_gate is not None \
                    and fly_gate <= e:
                ng = e - fly_gate + 1
                self._conv_pushes += ng
                # grid is only probed when conv said "not converged"
                self._grid_pushes += ng - (1 if tag == "inform" else 0)
            elif self.state is S.IN_FORMATION:
                self._conv_pushes += ticks_run \
                    - (1 if tag == "complete" else 0)
            elif self.state is S.GRIDLOCK:
                self._grid_pushes += ticks_run
            if self.is_logging and self.assignments:
                self.assignments[-1] += int(np.count_nonzero(ev[s:e + 1]))

            if tag == "takeoff":
                actions.append("takeoff")
                self._to(S.TAKING_OFF)
            elif tag == "hover":
                self._to(S.HOVERING)
            elif tag == "terminate":
                self._to(S.TERMINATE)
            elif tag == "hover_done":
                if self.curr_formation_idx == self.n_formations - 1:
                    self._to(S.COMPLETE)
                else:
                    self.curr_formation_idx += 1
                    actions.append("dispatch")
                    ev[e + 1:] = False    # stale events belong to the
                    self._to(S.WAITING_ON_ASSIGNMENT)  # outgoing formation
            elif tag == "fly":
                self._start_logging()
                self._to(S.FLYING)
            elif tag == "inform":
                self._to(S.IN_FORMATION, reset=False)
            elif tag == "gridlock":
                self._to(S.GRIDLOCK)
            elif tag == "complete":
                self._stop_logging()
                self._to(S.HOVERING)
            elif tag == "unconverged":
                self._to(S.FLYING)
            elif tag == "gridlock_left":
                self._to(S.FLYING)
            elif tag == "gridlock_timeout":
                self._to(S.TERMINATE)

            # trial watchdog (`supervisor.py:229-236`): end-of-tick, only
            # if the state logic did not already finish the trial
            if s_w == e and not self.done:
                self._to(S.TERMINATE)
            s = e + 1
        return actions

    def csv_row(self, trial: int) -> list:
        """Same schema as `TrialFSM.csv_row`."""
        return ([trial] + self.dist.tolist() + list(self.times)
                + list(self.time_avoidance) + list(self.assignments))

    # -- checkpointing (docs/RESILIENCE.md; same contract as
    # `TrialFSM.snapshot`: mutable state only, config re-derived)

    _SNAP_FIELDS = ("state", "timer_ticks", "tick_count",
                    "curr_formation_idx", "is_logging", "_conv_pushes",
                    "_grid_pushes", "_formation_just_received", "times",
                    "time_avoidance", "assignments", "_log_start_tick",
                    "_grid_enter_tick", "_dist_pending")

    def snapshot(self) -> dict:
        snap = {k: getattr(self, k) for k in self._SNAP_FIELDS}
        snap["dist"] = self.dist.copy()
        snap["_last_cumdist"] = (None if self._last_cumdist is None
                                 else self._last_cumdist.copy())
        snap["_dist_mark"] = (None if self._dist_mark is None
                              else self._dist_mark.copy())
        return snap

    def restore(self, snap: dict) -> "SummaryTrialFSM":
        for k in self._SNAP_FIELDS:
            setattr(self, k, snap[k])
        self.times = list(snap["times"])
        self.time_avoidance = list(snap["time_avoidance"])
        self.assignments = list(snap["assignments"])
        self.dist = np.asarray(snap["dist"]).copy()
        self._last_cumdist = (None if snap["_last_cumdist"] is None
                              else np.asarray(snap["_last_cumdist"]).copy())
        self._dist_mark = (None if snap["_dist_mark"] is None
                           else np.asarray(snap["_dist_mark"]).copy())
        return self
