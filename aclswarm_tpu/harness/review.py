"""Recorded-rollout reviewer: the `review_bag.py` pattern, bag-free.

The reference replays rosbagged hardware experiments through the same
metric FSM the sim supervisor uses (`aclswarm/nodes/review_bag.py:29-47`,
`launch/review.launch`), so hardware and sim runs are scored by one
oracle. Here the "bag" is a compressed npz of the rollout observables
(`StepMetrics` — the exact signals the supervisor consumes, plus
everything needed to re-derive them), written by `record()` during a
trial or rollout and replayed by `review()` through the `TrialFSM` with
fresh thresholds. Use cases match the reference's:

- re-score an old run after tuning supervisor thresholds (the reference's
  reason for replaying bags instead of re-flying);
- archive Monte-Carlo evidence next to the CSV so any row can be audited
  tick-by-tick;
- cross-check a live `TrialFSM` outcome against the batch `evaluate()`
  path on identical inputs.

Format: npz with ``q`` (T, n, 3), ``distcmd_norm`` (T, n), ``ca_active``
(T, n), ``reassigned`` (T,), ``auctioned`` (T,), ``assign_valid`` (T,),
``mode`` (T, n), ``v2f`` (T, n), scalar ``dt``, plus free-form metadata
under ``meta_*`` keys.
"""
from __future__ import annotations

from typing import Optional

import numpy as np

from aclswarm_tpu.harness.supervisor import NAMES, TrialFSM

_FIELDS = ("q", "distcmd_norm", "ca_active", "reassigned", "auctioned",
           "assign_valid", "mode", "v2f")


def record(path: str, metrics, dt: float = 0.01, **meta) -> str:
    """Write a rollout's `StepMetrics` stack (leading time axis) to a
    compressed npz "bag"."""
    arrays = {f: np.asarray(getattr(metrics, f)) for f in _FIELDS}
    arrays["dt"] = np.asarray(dt)
    for k, v in meta.items():
        arrays[f"meta_{k}"] = np.asarray(v)
    np.savez_compressed(path, **arrays)
    return path


class Recording:
    """A loaded bag; attribute access mirrors `StepMetrics`.

    Accepts a recording ``.npz`` (written by `record()`) or an actual
    rosbag ``.bag`` from a hardware flight — the latter is ingested by
    the pure-Python reader (`harness.rosbag1.bag_to_recording`,
    `readACLBag.m`/`review_bag.py` parity) and resampled onto the
    reviewer's 50 Hz grid."""

    def __init__(self, path: str):
        if str(path).endswith(".bag"):
            from aclswarm_tpu.harness import rosbag1
            data = rosbag1.bag_to_recording(path)
        else:
            data = np.load(path)
        for f in _FIELDS:
            setattr(self, f, data[f])
        self.dt = float(data["dt"])
        files = data.files if hasattr(data, "files") else data.keys()
        self.meta = {k[5:]: data[k] for k in files
                     if k.startswith("meta_")}

    @property
    def n_ticks(self) -> int:
        return self.q.shape[0]

    @property
    def n(self) -> int:
        return self.q.shape[1]


def review(path: str, n_formations: int = 1,
           takeoff_alt: Optional[float] = None,
           trial_timeout: Optional[float] = None,
           verbose: bool = False,
           in_formation_gate=None) -> TrialFSM:
    """Replay a recorded rollout through the trial supervisor FSM — the
    `review_bag.py` loop with the recording as the message stream. The
    recording must start on the ground for the takeoff phase to evaluate
    (recordings of airborne rollouts should instead use
    `supervisor.evaluate`, the post-takeoff batch oracle). Returns the
    finished (or exhausted) FSM.

    ``trial_timeout`` defaults to the recording's own ``meta_trial_timeout``
    (stamped by the trial driver on every recording), falling back to the
    reference's 600 s — so a replay judges a trial against the same
    watchdog budget it flew under.

    ``in_formation_gate`` enables the reference reviewer's
    human-in-the-loop mode (`rosservice call /in_formation`,
    `review_bag.py:29-60`): a callable ``gate(tick, fsm) -> bool`` polled
    every tick, returning True on the tick the human declares the
    formation converged. The human signal then *replaces* the machine
    convergence predicate (and aborts the trial if it fires during
    gridlock) — see `TrialFSM.step`. The CLI's ``--interactive`` flag
    builds a stdin gate.
    """
    rec = Recording(path)
    if takeoff_alt is None:
        from aclswarm_tpu.core.types import SafetyParams
        takeoff_alt = float(SafetyParams().takeoff_alt)
    if trial_timeout is None:
        from aclswarm_tpu.harness.supervisor import TRIAL_TIMEOUT
        trial_timeout = float(rec.meta.get("trial_timeout", TRIAL_TIMEOUT))
    fsm = TrialFSM(rec.n, n_formations, takeoff_alt=takeoff_alt, dt=rec.dt,
                   trial_timeout=trial_timeout)
    auction_ok = rec.auctioned & rec.assign_valid
    # the reference reviewer asks a human "/in_formation"; the recording
    # carries the machine signals, so events are re-derived exactly as the
    # trial driver derives them: after each formation dispatch, the first
    # valid auction counts as an accepted assignment even if unchanged
    # (`auctioneer.cpp:310-316` formation_just_received semantics)
    awaiting_first = False
    for t in range(rec.n_ticks):
        event = bool(rec.reassigned[t])
        if awaiting_first and bool(auction_ok[t]):
            event = True
            awaiting_first = False
        gate = (None if in_formation_gate is None
                else bool(in_formation_gate(t, fsm)))
        action = fsm.step(rec.q[t], rec.distcmd_norm[t], rec.ca_active[t],
                          event, in_formation=gate)
        if action == "dispatch":
            awaiting_first = True
        if fsm.done:
            break
    if verbose:
        print(f"review: {NAMES[fsm.state]} after {t + 1}/{rec.n_ticks} "
              f"ticks; conv times {[round(x, 2) for x in fsm.times]}")
    return fsm


def stdin_gate(dt: float, period_s: float = 1.0):
    """Interactive `/in_formation` gate: once per ``period_s`` of replay
    time while the FSM is in a gateable state, ask the operator whether
    the formation has converged (the CLI analogue of watching rviz and
    calling the service)."""
    from aclswarm_tpu.harness.supervisor import TrialState
    every = max(1, int(round(period_s / dt)))

    def gate(t: int, fsm) -> bool:
        if fsm.state not in (TrialState.FLYING, TrialState.GRIDLOCK):
            return False
        if t % every:
            return False
        name = NAMES[fsm.state]
        try:
            ans = input(f"t={t * dt:7.2f}s  state={name:9s} formation "
                        f"{fsm.curr_formation_idx}: in formation? [y/N] ")
        except EOFError:        # stdin exhausted: no confirmation
            return False
        return ans.strip().lower().startswith("y")

    return gate


def main(argv=None):
    import argparse

    ap = argparse.ArgumentParser(
        description="Replay a recorded rollout through the trial "
                    "supervisor FSM (the review_bag.py analogue).")
    ap.add_argument("path", help="recording .npz written by record(), or "
                                 "a hardware .bag (rosbag v2.0)")
    ap.add_argument("--formations", type=int, default=1)
    ap.add_argument("--trial-timeout", type=float, default=None)
    ap.add_argument("--interactive", action="store_true",
                    help="human-in-the-loop convergence gate "
                         "(`rosservice call /in_formation` analogue)")
    ap.add_argument("--gate-period", type=float, default=1.0,
                    help="seconds of replay time between interactive "
                         "prompts")
    args = ap.parse_args(argv)
    gate = None
    if args.interactive:
        if args.path.endswith(".bag"):
            dt = 0.02        # the bag resampler's reviewer-rate grid
        else:
            # read only the dt scalar — Recording materializes every
            # array, which review() is about to do anyway
            dt = float(np.load(args.path)["dt"])
        gate = stdin_gate(dt, args.gate_period)
    fsm = review(args.path, n_formations=args.formations,
                 trial_timeout=args.trial_timeout, verbose=True,
                 in_formation_gate=gate)
    return 0 if fsm.completed else 1


if __name__ == "__main__":
    raise SystemExit(main())
