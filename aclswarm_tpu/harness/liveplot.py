"""Live signal plots off the wire: the `rqt_multiplot` equivalent.

The reference's live observability is two rqt_multiplot configs
(`aclswarm/cfg/multiplot_xyvel.xml`: per-vehicle x/y velocity commands
vs time; `multiplot_vehicletracker_sq01s.xml`: tracker estimate
positions) attached to the running ROS graph. The TPU framework's
running system is the bridge process serving the wire API, so the
equivalent is a *wire-attached* consumer: this module opens the
`<ns>-distcmd` / `<ns>-safety` / `<ns>-estimates` channels (read-only
peer of the same rings the vehicles consume is not possible on SPSC
rings — so the bridge is pointed at a dedicated namespace, or this
plotter IS the consumer in an observation deployment), maintains rolling
time buffers, and re-renders the multiplot panels (per-vehicle vx/vy,
ca-active raster, xy estimate traces) to an atomically-rewritten PNG on
an interval — point any image viewer that auto-reloads at the file and
it behaves like the rqt window.

Run (observing a bridge at /asw, writing /tmp/live.png every 2 s):

    python -m aclswarm_tpu.harness.liveplot --ns /asw \
        --out /tmp/live.png --interval 2 --duration 60

Library use (the tests drive this):

    lp = LivePlot(n=6, window_s=10.0)
    lp.ingest_distcmd(msg); lp.ingest_safety(msg); lp.ingest_estimates(msg)
    lp.render("frame.png")
"""
from __future__ import annotations

import argparse
import collections
import time
from typing import Optional

import numpy as np

from aclswarm_tpu.interop import messages as m


class LivePlot:
    """Rolling-buffer multiplot state + renderer."""

    def __init__(self, n: int, window_s: float = 10.0,
                 expected_rate_hz: float = 100.0):
        self.n = n
        self.window_s = window_s
        cap = max(16, int(window_s * expected_rate_hz * 2))
        self._cmd = collections.deque(maxlen=cap)   # (stamp, (n, 3) vel)
        self._ca = collections.deque(maxlen=cap)    # (stamp, (n,) active)
        self._est = collections.deque(maxlen=cap)   # (stamp, (n, 3) pos)

    # -- ingestion (one call per decoded wire message) --------------------
    def ingest(self, msg) -> bool:
        """Route any supported wire message; returns False if unhandled."""
        if isinstance(msg, m.DistCmd):
            self.ingest_distcmd(msg)
        elif isinstance(msg, m.SafetyStatusArray):
            self.ingest_safety(msg)
        elif isinstance(msg, m.VehicleEstimates):
            self.ingest_estimates(msg)
        else:
            return False
        return True

    def ingest_distcmd(self, msg: m.DistCmd) -> None:
        self._cmd.append((msg.header.stamp, np.asarray(msg.vel)))

    def ingest_safety(self, msg: m.SafetyStatusArray) -> None:
        self._ca.append((msg.header.stamp, np.asarray(msg.active, bool)))

    def ingest_estimates(self, msg: m.VehicleEstimates) -> None:
        self._est.append((msg.header.stamp, np.asarray(msg.positions)))

    # -- window views -----------------------------------------------------
    def _window(self, buf):
        if not buf:
            return np.zeros((0,)), np.zeros((0, self.n, 0))
        t1 = buf[-1][0]
        ts, vals = zip(*[x for x in buf if x[0] >= t1 - self.window_s])
        return np.asarray(ts), np.stack(vals)

    # -- rendering --------------------------------------------------------
    def render(self, out: str) -> None:
        """One multiplot frame: per-vehicle vx/vy (`multiplot_xyvel.xml`),
        ca-active raster, and xy estimate traces
        (`multiplot_vehicletracker`)."""
        from aclswarm_tpu.harness.viz import _mpl
        plt = _mpl()

        fig, axes = plt.subplots(2, 2, figsize=(11, 7))
        (ax_vx, ax_vy), (ax_ca, ax_xy) = axes

        ts, vel = self._window(self._cmd)
        if ts.size:
            for v in range(self.n):
                ax_vx.plot(ts, vel[:, v, 0], lw=0.8)
                ax_vy.plot(ts, vel[:, v, 1], lw=0.8)
        ax_vx.set_title("distcmd vx (m/s)")
        ax_vy.set_title("distcmd vy (m/s)")
        for ax in (ax_vx, ax_vy):
            ax.set_xlabel("t (s)")
            ax.grid(True, alpha=0.3)

        tc, ca = self._window(self._ca)
        if tc.size:
            ax_ca.imshow(ca.T, aspect="auto", interpolation="nearest",
                         extent=[tc[0], tc[-1], -0.5, self.n - 0.5],
                         origin="lower", cmap="Reds", vmin=0, vmax=1)
        ax_ca.set_title("collision avoidance active (per vehicle)")
        ax_ca.set_xlabel("t (s)")
        ax_ca.set_ylabel("vehicle")

        te, est = self._window(self._est)
        if te.size:
            for v in range(self.n):
                ax_xy.plot(est[:, v, 0], est[:, v, 1], lw=0.8)
            ax_xy.plot(est[-1, :, 0], est[-1, :, 1], "k.", ms=6)
        ax_xy.set_title("estimate traces (xy)")
        ax_xy.set_aspect("equal", adjustable="datalim")
        ax_xy.grid(True, alpha=0.3)

        fig.tight_layout()
        # atomic rewrite so a viewer polling the file never sees a
        # half-written image
        import os
        tmp = out + ".tmp.png"
        fig.savefig(tmp, dpi=110)
        plt.close(fig)
        os.replace(tmp, out)


def observe(ns: str, n: int, out: str, interval_s: float = 2.0,
            duration_s: float = 0.0, poll_s: float = 0.002,
            channels: Optional[dict] = None) -> int:
    """Consume wire traffic and re-render ``out`` every ``interval_s``.

    ``channels`` (tests) injects already-open channel objects keyed by
    'distcmd'/'safety'/'estimates'; by default the shm rings ``<ns>-*``
    are opened (this process must be THE consumer of those rings — SPSC).
    Returns the number of frames rendered.
    """
    if channels is None:
        from aclswarm_tpu.interop.transport import Channel
        channels = {name: Channel(f"{ns}-{name}")
                    for name in ("distcmd", "safety", "estimates")}
    lp = LivePlot(n)
    frames = 0
    t_end = time.time() + duration_s if duration_s else None
    next_render = time.time() + interval_s
    while t_end is None or time.time() < t_end:
        progressed = False
        for ch in channels.values():
            msg = ch.recv()
            if msg is not None:
                lp.ingest(msg)
                progressed = True
        now = time.time()
        if now >= next_render:
            lp.render(out)
            frames += 1
            next_render = now + interval_s
        if not progressed:
            time.sleep(poll_s)
    lp.render(out)
    return frames + 1


def main(argv=None):
    ap = argparse.ArgumentParser(description=__doc__)
    ap.add_argument("--ns", default="/asw")
    ap.add_argument("--n", type=int, required=True)
    ap.add_argument("--out", default="live.png")
    ap.add_argument("--interval", type=float, default=2.0)
    ap.add_argument("--duration", type=float, default=0.0,
                    help="seconds to observe (0 = forever)")
    args = ap.parse_args(argv)
    frames = observe(args.ns, args.n, args.out, args.interval,
                     args.duration)
    print(f"rendered {frames} frames to {args.out}")
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
