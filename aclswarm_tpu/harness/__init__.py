"""Trial harness: formation library, random formation generator, supervisor
oracle, Monte-Carlo trial driver (SURVEY.md §7 layer 7)."""
from aclswarm_tpu.harness.formations import (FormationSpec, load_formation,
                                             load_group)
from aclswarm_tpu.harness.supervisor import (TrialFSM, TrialResult,
                                             TrialState, evaluate)

__all__ = ["FormationSpec", "load_formation", "load_group", "TrialResult",
           "TrialFSM", "TrialState", "evaluate"]
