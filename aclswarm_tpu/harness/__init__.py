"""Trial harness: formation library, supervisor oracle, trial driver
(SURVEY.md §7 layer 7)."""
from aclswarm_tpu.harness.formations import (FormationSpec, load_formation,
                                             load_group)
from aclswarm_tpu.harness.supervisor import TrialResult, evaluate

__all__ = ["FormationSpec", "load_formation", "load_group", "TrialResult",
           "evaluate"]
