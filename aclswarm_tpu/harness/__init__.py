"""Trial harness: formation library, random formation generator, supervisor
oracle, Monte-Carlo trial driver, recording review incl. rosbag ingestion
(SURVEY.md §7 layer 7)."""
from aclswarm_tpu.harness.formations import (FormationSpec, load_formation,
                                             load_group)
from aclswarm_tpu.harness.supervisor import (TrialFSM, TrialResult,
                                             TrialState, evaluate)

__all__ = ["FormationSpec", "load_formation", "load_group", "TrialResult",
           "TrialFSM", "TrialState", "evaluate", "review", "rosbag1"]


def __getattr__(name):
    # lazy submodule access for the heavier tools (review pulls the FSM
    # stack; rosbag1 is pure stdlib+numpy) without import-time cost
    if name in ("review", "rosbag1"):
        import importlib
        return importlib.import_module(f"aclswarm_tpu.harness.{name}")
    raise AttributeError(name)


def __dir__():
    return sorted(list(globals()) + ["review", "rosbag1"])
